"""Consensus stores: bounded decode caches over the persistent KV engine.

Mirrors the reference's store registry (consensus/src/model/stores/, 20
stores aggregated in ConsensusStorage, consensus/src/consensus/storage.rs)
and its memory discipline (database/src/access.rs CachedDbAccess: a BOUNDED
in-memory cache of decoded values over a persistent column, with read-through
misses, plus consensus/src/consensus/cache_policy_builder.rs sizing the
per-store budgets).  Two modes:

- **in-memory** (no DB attached): caches are unbounded plain dicts — the
  whole working set lives in RAM, nothing persists (simulation mode).
- **persistent** (DB attached): each store caches at most ``budget`` decoded
  entries (LRU), reads through to the native engine on miss, and stages
  mutations into the storage-wide pending buffer; ``ConsensusStorage.flush()``
  commits the buffer as ONE atomic CRC-framed batch (native/kvstore) at
  block-commit boundaries.  Entries with staged-but-unflushed writes are
  pinned (never evicted) so reads are always consistent; a crash between
  flushes loses at most the blocks since the last flush — the on-disk state
  is always a consistent prefix.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, fields
from kaspa_tpu.consensus.model import Header, Transaction
from kaspa_tpu.observability import trace
from kaspa_tpu.observability.core import REGISTRY

# key prefixes (database/src/registry.rs DatabaseStorePrefixes shape)
PREFIX_HEADERS = b"HD"
PREFIX_RELATIONS = b"RL"
PREFIX_CHILDREN = b"RC"
PREFIX_GHOSTDAG = b"GD"
PREFIX_STATUSES = b"ST"
PREFIX_BLOCK_TXS = b"BT"
PREFIX_UTXO_DIFFS = b"UD"
PREFIX_MULTISETS = b"MS"
PREFIX_ACCEPTANCE = b"AC"
PREFIX_DAA_EXCLUDED = b"DX"
PREFIX_UTXO_SET = b"US"
PREFIX_PRUNING_UTXO = b"PU"
PREFIX_DEPTH = b"MD"
PREFIX_PRUNING_SAMPLES = b"PS"
PREFIX_REACH_MERGESET = b"RM"
PREFIX_BLOCK_LEVELS = b"LV"
PREFIX_META = b"MT"
PREFIX_REACH_NODE = b"RN"  # per-node reachability records (crash-safe restart)

# prefix -> human store name for cache telemetry (ConsensusStorage.cache_stats)
_PREFIX_NAMES = {
    PREFIX_HEADERS: "headers",
    PREFIX_RELATIONS: "relations",
    PREFIX_CHILDREN: "children",
    PREFIX_GHOSTDAG: "ghostdag",
    PREFIX_STATUSES: "statuses",
    PREFIX_BLOCK_TXS: "block_txs",
    PREFIX_UTXO_DIFFS: "utxo_diffs",
    PREFIX_MULTISETS: "multisets",
    PREFIX_ACCEPTANCE: "acceptance",
    PREFIX_DAA_EXCLUDED: "daa_excluded",
    PREFIX_UTXO_SET: "utxo_set",
    PREFIX_PRUNING_UTXO: "pruning_utxo",
    PREFIX_DEPTH: "depth",
    PREFIX_PRUNING_SAMPLES: "pruning_samples",
    PREFIX_REACH_MERGESET: "reach_mergesets",
    PREFIX_BLOCK_LEVELS: "levels",
    PREFIX_REACH_NODE: "reach_nodes",
}


@dataclass
class CachePolicy:
    """Per-store decoded-entry budgets (cache_policy_builder.rs shape).

    Budgets are entry counts; ``scaled`` multiplies every budget by a
    ram-scale factor the way the reference's --ram-scale flag scales its
    cache policies (kaspad/src/args.rs).  ``None`` disables bounding for
    that store (used by the in-memory mode).
    """

    headers: int = 40_000
    relations: int = 80_000
    children: int = 80_000
    ghostdag: int = 40_000
    statuses: int = 200_000
    block_txs: int = 2_000
    utxo_diffs: int = 2_000
    multisets: int = 2_000
    acceptance: int = 2_000
    daa_excluded: int = 10_000
    reach_mergesets: int = 80_000
    depth: int = 40_000
    pruning_samples: int = 40_000
    utxo_set: int = 100_000
    pruning_utxo: int = 10_000
    levels: int = 40_000

    def scaled(self, ram_scale: float) -> "CachePolicy":
        kw = {f.name: max(16, int(getattr(self, f.name) * ram_scale)) for f in fields(self)}
        return CachePolicy(**kw)


class CachedDbAccess:
    """Bounded LRU decode cache over one DB prefix (database/src/access.rs).

    Mapping-style interface so consensus call sites read naturally.  With no
    DB the cache is authoritative and unbounded.  With a DB, mutations are
    cached AND staged into the storage pending buffer; dirty (staged but
    unflushed) entries are pinned until the next flush so read-your-writes
    holds across the whole batch window.
    """

    def __init__(self, storage: "ConsensusStorage", prefix: bytes, encode, decode, budget: int | None):
        self._storage = storage
        self._prefix = prefix
        self._encode = encode
        self._decode = decode
        self._budget = budget if storage.db is not None else None
        self._cache: OrderedDict = OrderedDict()
        self._dirty: set = set()        # staged writes not yet flushed (pinned)
        self._pending_del: set = set()  # staged deletes not yet flushed
        # plain-int cache telemetry (GIL-atomic; aggregated by
        # ConsensusStorage.cache_stats into the observability registry)
        self._hits = 0
        self._misses = 0
        self._evictions = 0
        if storage.db is not None:
            self._count = storage.db.engine.count_prefix(prefix)
        else:
            self._count = 0
        storage.register(self)

    # -- internal ------------------------------------------------------

    def _db_raw(self, key: bytes):
        if self._storage.db is None or key in self._pending_del:
            return None
        return self._storage.db.engine.get(self._prefix + key)

    def _evict(self) -> None:
        if self._budget is None:
            return
        while len(self._cache) > self._budget:
            for k in self._cache:
                if k not in self._dirty:
                    del self._cache[k]
                    self._evictions += 1
                    break
            else:
                return  # everything pinned; evict after next flush

    def on_flush(self) -> None:
        self._dirty.clear()
        self._pending_del.clear()
        self._evict()

    # -- reads ---------------------------------------------------------

    def try_get(self, key: bytes):
        obj = self._cache.get(key)
        if obj is not None:
            self._hits += 1
            # recency bookkeeping only matters under eviction pressure:
            # unbounded caches (no DB) and caches far below budget cannot
            # evict, so hit order cannot change any outcome — and this is
            # the hottest read path in header validation (the difficulty
            # windows issue tens of millions of hits per few thousand
            # blocks)
            if self._budget is not None and len(self._cache) * 2 >= self._budget:
                self._cache.move_to_end(key)
            return obj
        self._misses += 1
        raw = self._db_raw(key)
        if raw is None:
            return None
        obj = self._decode(raw)
        # `None` IS the miss sentinel of this cache: a decoder returning
        # None would alias a present row with a miss, silently re-reading
        # (and re-decoding) it forever — fail loudly instead
        assert obj is not None, f"decoder for store prefix {self._prefix!r} returned None for key {key!r}"
        self._cache[key] = obj
        self._evict()
        return obj

    def get(self, key: bytes, default=None):
        v = self.try_get(key)
        return default if v is None else v

    def __getitem__(self, key: bytes):
        v = self.try_get(key)
        if v is None:
            raise KeyError(key)
        return v

    def __contains__(self, key: bytes) -> bool:
        if key in self._cache:
            return True
        if self._storage.db is None or key in self._pending_del:
            return False
        return self._storage.db.engine.has(self._prefix + key)

    has = __contains__

    def __len__(self) -> int:
        return self._count if self._storage.db is not None else len(self._cache)

    def keys(self):
        """All live keys.  DB mode: engine prefix scan (ordered, no disk
        value reads) merged with unflushed staged writes."""
        if self._storage.db is None:
            return list(self._cache.keys())
        ks = self._storage.db.engine.keys_prefix(self._prefix)
        if self._pending_del:
            ks = [k for k in ks if k not in self._pending_del]
        if self._dirty:
            on_disk = set(ks)
            ks.extend(k for k in self._dirty if k not in on_disk)
        return ks

    def __iter__(self):
        return iter(self.keys())

    def items(self):
        """Decoded (key, value) pairs — a full scan; use sparingly."""
        if self._storage.db is None:
            return list(self._cache.items())
        return [(k, self[k]) for k in self.keys()]

    def values(self):
        return [v for _, v in self.items()]

    # -- writes --------------------------------------------------------

    def write(self, key: bytes, obj) -> None:
        # `None` is reserved as the miss sentinel (try_get/get return it
        # for absent keys); caching a literal None would make the entry
        # unreadable — every lookup would miss through to the DB forever
        assert obj is not None, "CachedDbAccess values must not be None (None is the miss sentinel)"
        if self._storage.db is not None:
            if key not in self:
                self._count += 1
            self._pending_del.discard(key)
            self._dirty.add(key)
            self._storage.stage(self._prefix + key, self._encode(obj))
        self._cache[key] = obj
        self._cache.move_to_end(key)
        self._evict()

    __setitem__ = write

    def delete(self, key: bytes) -> None:
        existed = key in self
        self._cache.pop(key, None)
        self._dirty.discard(key)
        if self._storage.db is not None and existed:
            self._count -= 1
            self._pending_del.add(key)
            self._storage.stage(self._prefix + key, None)

    def pop(self, key: bytes, default=None):
        v = self.try_get(key)
        if v is None:
            return default
        self.delete(key)
        return v

    def __delitem__(self, key: bytes) -> None:
        if key not in self:
            raise KeyError(key)
        self.delete(key)

    def update(self, mapping) -> None:
        items = mapping.items() if hasattr(mapping, "items") else mapping
        for k, v in items:
            self.write(k, v)

    def clear_cache(self) -> None:
        """Drop clean cached entries (dirty stay pinned)."""
        for k in list(self._cache):
            if k not in self._dirty:
                del self._cache[k]


@dataclass
class GhostdagData:
    """consensus/src/model/stores/ghostdag.rs GhostdagData."""

    blue_score: int
    blue_work: int
    selected_parent: bytes
    mergeset_blues: list[bytes]
    mergeset_reds: list[bytes]
    blues_anticone_sizes: dict[bytes, int]

    def mergeset_size(self) -> int:
        return len(self.mergeset_blues) + len(self.mergeset_reds)

    def unordered_mergeset(self):
        yield from self.mergeset_blues
        yield from self.mergeset_reds

    def unordered_mergeset_without_selected_parent(self):
        yield from self.mergeset_blues[1:]
        yield from self.mergeset_reds

    def ascending_mergeset_without_selected_parent(self, gd_store):
        """Mergeset (minus selected parent) ascending by (blue_work, hash)."""
        return sorted(
            self.unordered_mergeset_without_selected_parent(),
            key=lambda h: (gd_store.get(h).blue_work, h),
        )

    def consensus_ordered_mergeset(self, gd_store):
        return [self.selected_parent] + self.ascending_mergeset_without_selected_parent(gd_store)


def _enc_header(h):
    from kaspa_tpu.consensus import serde

    return serde.encode_header(h)


def _dec_header(b):
    from kaspa_tpu.consensus import serde

    return serde.decode_header(b)


class HeaderStore:
    def __init__(self, storage: "ConsensusStorage"):
        self._storage = storage
        self._access = CachedDbAccess(storage, PREFIX_HEADERS, _enc_header, _dec_header, storage.policy.headers)
        # pow-derived block levels: tiny persisted values, lazily computed
        self._levels = CachedDbAccess(
            storage, PREFIX_BLOCK_LEVELS, lambda v: bytes([v]), lambda b: b[0], storage.policy.levels
        )
        self.max_block_level = 225  # overwritten by Consensus from params

    def insert(self, header: Header) -> None:
        self._access.write(header.hash, header)

    def delete(self, block: bytes) -> None:
        self._access.delete(block)
        self._levels.delete(block)

    def get(self, block: bytes) -> Header:
        return self._access[block]

    def has(self, block: bytes) -> bool:
        return block in self._access

    def keys(self):
        return self._access.keys()

    def __len__(self) -> int:
        return len(self._access)

    def get_bits(self, block: bytes) -> int:
        return self._access[block].bits

    def get_timestamp(self, block: bytes) -> int:
        return self._access[block].timestamp

    def get_blue_score(self, block: bytes) -> int:
        return self._access[block].blue_score

    def get_daa_score(self, block: bytes) -> int:
        return self._access[block].daa_score

    def get_block_level(self, block: bytes) -> int:
        """Proof level from the PoW value (pow/src/lib.rs calc_block_level):
        max(0, max_block_level - pow_bits); genesis gets the max level.
        Lazily memoized and persisted — the heavy-hash is only paid once per
        block across restarts."""
        lvl = self._levels.try_get(block)
        if lvl is None:
            header = self._access[block]
            if not header.direct_parents():
                lvl = self.max_block_level  # genesis carries the max level
            else:
                from kaspa_tpu.crypto.powhash import calc_block_pow_hash

                pow_value = int.from_bytes(calc_block_pow_hash(header), "little")
                lvl = max(0, self.max_block_level - pow_value.bit_length())
            self._levels.write(block, lvl)
        return lvl


def _enc_hashes(hs):
    from kaspa_tpu.consensus import serde

    return serde.encode_hash_list(list(hs))


def _dec_hashes(b):
    from kaspa_tpu.consensus import serde

    return serde.decode_hash_list_bytes(b)


class RelationsStore:
    """Parent/child relations (level 0; higher levels added with pruning
    proofs).  Children lists are persisted under their own prefix (the
    reference's DbRelationsStore keeps a children column for the same
    reason: read-through must not require scanning all parents)."""

    def __init__(self, storage: "ConsensusStorage"):
        self._storage = storage
        self._parents = CachedDbAccess(storage, PREFIX_RELATIONS, _enc_hashes, _dec_hashes, storage.policy.relations)
        self._children = CachedDbAccess(storage, PREFIX_CHILDREN, _enc_hashes, _dec_hashes, storage.policy.children)

    def insert(self, block: bytes, parents: list[bytes]) -> None:
        self._parents.write(block, list(parents))
        if block not in self._children:
            self._children.write(block, [])
        for p in parents:
            ch = self._children.get(p, [])
            if block not in ch:
                self._children.write(p, ch + [block])

    def delete(self, block: bytes) -> None:
        """Remove the block AND scrub it from its children's parent lists —
        surviving blocks must never reference pruned history (the live
        ghostdag mergeset BFS walks these lists through reachability)."""
        parents = self._parents.pop(block, [])
        for p in parents:
            ch = self._children.get(p)
            if ch and block in ch:
                self._children.write(p, [c for c in ch if c != block])
        for c in self._children.pop(block, []):
            plist = self._parents.get(c)
            if plist and block in plist:
                self._parents.write(c, [x for x in plist if x != block])

    def get_parents(self, block: bytes) -> list[bytes]:
        return self._parents[block]

    def get_children(self, block: bytes) -> list[bytes]:
        return self._children.get(block, [])

    def has(self, block: bytes) -> bool:
        return block in self._parents

    def keys(self):
        return self._parents.keys()


def _enc_gd(gd):
    from kaspa_tpu.consensus import serde

    return serde.encode_ghostdag(gd)


def _dec_gd(b):
    from kaspa_tpu.consensus import serde

    return serde.decode_ghostdag(b)


class GhostdagStore:
    def __init__(self, storage: "ConsensusStorage"):
        self._access = CachedDbAccess(storage, PREFIX_GHOSTDAG, _enc_gd, _dec_gd, storage.policy.ghostdag)

    def insert(self, block: bytes, data: GhostdagData) -> None:
        self._access.write(block, data)

    def delete(self, block: bytes) -> None:
        self._access.delete(block)

    def get(self, block: bytes) -> GhostdagData:
        return self._access[block]

    def has(self, block: bytes) -> bool:
        return block in self._access

    def keys(self):
        return self._access.keys()

    def items(self):
        return self._access.items()

    def get_blue_work(self, block: bytes) -> int:
        return self._access[block].blue_work

    def get_blue_score(self, block: bytes) -> int:
        return self._access[block].blue_score

    def get_selected_parent(self, block: bytes) -> bytes:
        return self._access[block].selected_parent

    def get_blues_anticone_sizes(self, block: bytes) -> dict[bytes, int]:
        return self._access[block].blues_anticone_sizes


class StatusesStore:
    """Block statuses (consensus/src/model/stores/statuses.rs)."""

    STATUS_INVALID = "invalid"
    STATUS_UTXO_VALID = "utxo_valid"
    STATUS_UTXO_PENDING_VERIFICATION = "utxo_pending"
    STATUS_DISQUALIFIED = "disqualified"
    STATUS_HEADER_ONLY = "header_only"

    def __init__(self, storage: "ConsensusStorage"):
        self._access = CachedDbAccess(
            storage, PREFIX_STATUSES, lambda s: s.encode(), lambda b: b.decode(), storage.policy.statuses
        )

    def set(self, block: bytes, status: str) -> None:
        self._access.write(block, status)

    def delete(self, block: bytes) -> None:
        self._access.delete(block)

    def get(self, block: bytes) -> str | None:
        return self._access.try_get(block)

    def is_valid(self, block: bytes) -> bool:
        return self._access.try_get(block) in (
            self.STATUS_UTXO_VALID,
            self.STATUS_UTXO_PENDING_VERIFICATION,
            self.STATUS_HEADER_ONLY,
        )


def _enc_txs(txs):
    from kaspa_tpu.consensus import serde

    return serde.encode_txs(txs)


def _dec_txs(b):
    from kaspa_tpu.consensus import serde

    return serde.decode_txs(b)


class BlockTransactionsStore:
    def __init__(self, storage: "ConsensusStorage"):
        self._access = CachedDbAccess(storage, PREFIX_BLOCK_TXS, _enc_txs, _dec_txs, storage.policy.block_txs)

    def insert(self, block: bytes, txs: list[Transaction]) -> None:
        self._access.write(block, txs)

    def delete(self, block: bytes) -> None:
        self._access.delete(block)

    def get(self, block: bytes) -> list[Transaction]:
        return self._access[block]

    def has(self, block: bytes) -> bool:
        return block in self._access

    def keys(self):
        return self._access.keys()

    def __len__(self) -> int:
        return len(self._access)


class UtxoSetStore:
    """A UTXO collection over a DB prefix with outpoint-object keys.

    Bounded cache over the encoded column; point lookups miss through to
    the engine, full iteration streams from disk (model/stores/utxo_set.rs
    over CachedDbAccess with UtxoKey columns)."""

    def __init__(self, storage: "ConsensusStorage", prefix: bytes, budget: int | None):
        from kaspa_tpu.consensus import serde

        self._serde = serde
        self._access = CachedDbAccess(
            storage, prefix, serde.encode_utxo_entry, serde.decode_utxo_entry, budget
        )

    def _k(self, outpoint) -> bytes:
        return self._serde.encode_outpoint(outpoint)

    def get(self, outpoint, default=None):
        return self._access.get(self._k(outpoint), default)

    def __getitem__(self, outpoint):
        return self._access[self._k(outpoint)]

    def __setitem__(self, outpoint, entry) -> None:
        self._access.write(self._k(outpoint), entry)

    def __delitem__(self, outpoint) -> None:
        del self._access[self._k(outpoint)]

    def __contains__(self, outpoint) -> bool:
        return self._k(outpoint) in self._access

    def __len__(self) -> int:
        return len(self._access)

    def items(self):
        for k, v in self._access.items():
            yield self._serde.decode_outpoint(k), v

    def keys(self):
        return [self._serde.decode_outpoint(k) for k in self._access.keys()]

    def __iter__(self):
        return iter(self.keys())

    def replace_all(self, mapping) -> None:
        """Swap contents (pruning-point UTXO import).  Identical entries are
        left untouched, and the staged batch is flushed in chunks so a
        multi-million-entry import never pins the whole set in the pending
        buffer (import runs on a fresh/staging DB, so partial flushes are
        invisible until the final commit marks the state complete)."""
        new_keys = {self._k(op): entry for op, entry in mapping.items()}
        ops = 0
        for k in list(self._access.keys()):
            if k not in new_keys:
                self._access.delete(k)
                ops += 1
                if ops % 50_000 == 0:
                    self._access._storage.flush()
        for k, entry in new_keys.items():
            if self._access.try_get(k) != entry:
                self._access.write(k, entry)
                ops += 1
                if ops % 50_000 == 0:
                    self._access._storage.flush()


class ConsensusStorage:
    """Aggregation of all stores (consensus/src/consensus/storage.rs:38-83).

    With ``db`` attached (storage/kv.KvStore), mutations stage encoded ops
    into ``pending`` and ``flush()`` commits them as one atomic batch.  The
    mutation sites in the pipeline are exactly the reference's commit points,
    so any prefix of flushed batches is a consistent consensus state.
    ``policy`` bounds each store's decoded cache (CachePolicy); flush()
    unpins dirty entries and evicts over-budget ones.
    """

    def __init__(self, db=None, policy: CachePolicy | None = None):
        self.db = db
        self.policy = policy or CachePolicy()
        self.pending: list[tuple[bytes, bytes | None]] = []
        self._registered: list[CachedDbAccess] = []
        # callbacks run at the head of every flush so owners of derived
        # state (e.g. reachability dirty-node staging) join the same batch
        self.pre_flush_hooks: list = []
        self.headers = HeaderStore(self)
        self.relations = RelationsStore(self)
        self.ghostdag = GhostdagStore(self)
        self.statuses = StatusesStore(self)
        self.block_transactions = BlockTransactionsStore(self)
        # virtual-stage per-block columns (model/stores/{utxo_diffs,
        # utxo_multisets,acceptance_data,daa,depth,pruning_samples}.rs)
        from kaspa_tpu.consensus import serde

        self.utxo_diffs = CachedDbAccess(
            self, PREFIX_UTXO_DIFFS, serde.encode_utxo_diff, serde.decode_utxo_diff, self.policy.utxo_diffs
        )
        self.multisets = CachedDbAccess(
            self, PREFIX_MULTISETS, serde.encode_muhash, serde.decode_muhash, self.policy.multisets
        )
        self.acceptance = CachedDbAccess(
            self, PREFIX_ACCEPTANCE, _enc_hashes, _dec_hashes, self.policy.acceptance
        )
        self.daa_excluded = CachedDbAccess(
            self,
            PREFIX_DAA_EXCLUDED,
            lambda s: _enc_hashes(sorted(s)),
            lambda b: set(_dec_hashes(b)),
            self.policy.daa_excluded,
        )
        self.reach_mergesets = CachedDbAccess(
            self, PREFIX_REACH_MERGESET, _enc_hashes, _dec_hashes, self.policy.reach_mergesets
        )
        # depth store: (merge_depth_root, finality_point) packed as 64 bytes
        self.depth = CachedDbAccess(
            self, PREFIX_DEPTH, lambda t: t[0] + t[1], lambda b: (b[:32], b[32:64]), self.policy.depth
        )
        self.pruning_samples = CachedDbAccess(
            self, PREFIX_PRUNING_SAMPLES, lambda v: v, lambda b: b, self.policy.pruning_samples
        )
        self.utxo_set = UtxoSetStore(self, PREFIX_UTXO_SET, self.policy.utxo_set)
        self.pruning_utxo_set = UtxoSetStore(self, PREFIX_PRUNING_UTXO, self.policy.pruning_utxo)
        # bound method via WeakMethod inside the registry: per-test storages
        # don't leak, and multiple live storages merge by numeric sum
        REGISTRY.register_collector("store_cache", self.cache_stats)

    def register(self, access: CachedDbAccess) -> None:
        self._registered.append(access)

    def cache_stats(self) -> dict:
        """Per-store decode-cache telemetry: {store: {hits, misses,
        evictions, entries}}.  Consumed by the observability registry
        (which derives hit_rate); reading plain ints is torn-read safe."""
        out: dict[str, dict] = {}
        for access in self._registered:
            name = _PREFIX_NAMES.get(access._prefix, access._prefix.decode("ascii", "replace"))
            d = out.setdefault(name, {"hits": 0, "misses": 0, "evictions": 0, "entries": 0})
            d["hits"] += access._hits
            d["misses"] += access._misses
            d["evictions"] += access._evictions
            d["entries"] += len(access._cache)
        return out

    def stage(self, key: bytes, value: bytes | None) -> None:
        """Queue one write-through op (value None = delete)."""
        if self.db is not None:
            self.pending.append((key, value))

    def put_meta(self, name: bytes, value: bytes) -> None:
        self.stage(PREFIX_META + name, value)

    def get_meta(self, name: bytes) -> bytes | None:
        if self.db is None:
            return None
        # unflushed meta staged this batch wins over the engine copy
        for key, value in reversed(self.pending):
            if key == PREFIX_META + name:
                return value
        return self.db.engine.get(PREFIX_META + name)

    def flush(self) -> None:
        if self.db is None:
            return
        for hook in self.pre_flush_hooks:
            hook()
        if not self.pending:
            return
        with trace.span("store.flush", writes=len(self.pending)):
            with self.db.batch() as b:
                for key, value in self.pending:
                    if value is None:
                        b.delete(key)
                    else:
                        b.put(key, value)
            self.pending.clear()
            for access in self._registered:
                access.on_flush()

    def is_initialized(self) -> bool:
        return self.get_meta(b"init") == b"1"
