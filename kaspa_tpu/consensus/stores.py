"""Consensus stores: in-memory working set with optional KV write-through.

Mirrors the reference's store registry (consensus/src/model/stores/, 20
stores aggregated in ConsensusStorage, consensus/src/consensus/storage.rs)
and its persistence discipline (database/src/access.rs CachedDbAccess:
in-memory cache over a persistent column, mutations grouped into atomic
write batches).  Here every store keeps its full working set in a dict (the
cache) and, when a DB is attached, appends encoded write-through ops to the
storage-wide pending buffer; ``ConsensusStorage.flush()`` commits the buffer
as ONE atomic CRC-framed batch in the native engine (native/kvstore) at
block-commit boundaries.  A crash between flushes loses at most the blocks
since the last flush — the on-disk state is always a consistent prefix.
"""

from __future__ import annotations

from dataclasses import dataclass

from kaspa_tpu.consensus.model import Header, Transaction

# key prefixes (database/src/registry.rs DatabaseStorePrefixes shape)
PREFIX_HEADERS = b"HD"
PREFIX_RELATIONS = b"RL"
PREFIX_GHOSTDAG = b"GD"
PREFIX_STATUSES = b"ST"
PREFIX_BLOCK_TXS = b"BT"
PREFIX_UTXO_DIFFS = b"UD"
PREFIX_MULTISETS = b"MS"
PREFIX_ACCEPTANCE = b"AC"
PREFIX_DAA_EXCLUDED = b"DX"
PREFIX_UTXO_SET = b"US"
PREFIX_DEPTH = b"MD"
PREFIX_PRUNING_SAMPLES = b"PS"
PREFIX_REACH_MERGESET = b"RM"
PREFIX_META = b"MT"


@dataclass
class GhostdagData:
    """consensus/src/model/stores/ghostdag.rs GhostdagData."""

    blue_score: int
    blue_work: int
    selected_parent: bytes
    mergeset_blues: list[bytes]
    mergeset_reds: list[bytes]
    blues_anticone_sizes: dict[bytes, int]

    def mergeset_size(self) -> int:
        return len(self.mergeset_blues) + len(self.mergeset_reds)

    def unordered_mergeset(self):
        yield from self.mergeset_blues
        yield from self.mergeset_reds

    def unordered_mergeset_without_selected_parent(self):
        yield from self.mergeset_blues[1:]
        yield from self.mergeset_reds

    def ascending_mergeset_without_selected_parent(self, gd_store):
        """Mergeset (minus selected parent) ascending by (blue_work, hash)."""
        return sorted(
            self.unordered_mergeset_without_selected_parent(),
            key=lambda h: (gd_store.get(h).blue_work, h),
        )

    def consensus_ordered_mergeset(self, gd_store):
        return [self.selected_parent] + self.ascending_mergeset_without_selected_parent(gd_store)


class HeaderStore:
    def __init__(self, storage: "ConsensusStorage"):
        self._storage = storage
        self._headers: dict[bytes, Header] = {}
        self._levels: dict[bytes, int] = {}  # lazy pow-derived block levels
        self.max_block_level = 225  # overwritten by Consensus from params

    def insert(self, header: Header) -> None:
        self._headers[header.hash] = header
        if self._storage.db is not None:
            from kaspa_tpu.consensus import serde

            self._storage.stage(PREFIX_HEADERS + header.hash, serde.encode_header(header))

    def delete(self, block: bytes) -> None:
        self._headers.pop(block, None)
        self._levels.pop(block, None)
        self._storage.stage(PREFIX_HEADERS + block, None)

    def get(self, block: bytes) -> Header:
        return self._headers[block]

    def has(self, block: bytes) -> bool:
        return block in self._headers

    def get_bits(self, block: bytes) -> int:
        return self._headers[block].bits

    def get_timestamp(self, block: bytes) -> int:
        return self._headers[block].timestamp

    def get_blue_score(self, block: bytes) -> int:
        return self._headers[block].blue_score

    def get_daa_score(self, block: bytes) -> int:
        return self._headers[block].daa_score

    def get_block_level(self, block: bytes) -> int:
        """Proof level from the PoW value (pow/src/lib.rs calc_block_level):
        max(0, max_block_level - pow_bits); genesis gets the max level.
        Lazily memoized — the heavy-hash is only paid when levels are needed
        (parents building, proof building)."""
        lvl = self._levels.get(block)
        if lvl is None:
            header = self._headers[block]
            if not header.direct_parents():
                lvl = self.max_block_level  # genesis carries the max level
            else:
                from kaspa_tpu.crypto.powhash import calc_block_pow_hash

                pow_value = int.from_bytes(calc_block_pow_hash(header), "little")
                lvl = max(0, self.max_block_level - pow_value.bit_length())
            self._levels[block] = lvl
        return lvl


class RelationsStore:
    """Parent/child relations (level 0; higher levels added with pruning proofs)."""

    def __init__(self, storage: "ConsensusStorage"):
        self._storage = storage
        self._parents: dict[bytes, list[bytes]] = {}
        self._children: dict[bytes, list[bytes]] = {}

    def insert(self, block: bytes, parents: list[bytes]) -> None:
        self._parents[block] = list(parents)
        self._children.setdefault(block, [])
        for p in parents:
            self._children.setdefault(p, []).append(block)
        if self._storage.db is not None:
            from kaspa_tpu.consensus import serde

            self._storage.stage(PREFIX_RELATIONS + block, serde.encode_hash_list(parents))

    def delete(self, block: bytes) -> None:
        """Remove the block AND scrub it from its children's parent lists —
        surviving blocks must never reference pruned history (the live
        ghostdag mergeset BFS walks these lists through reachability)."""
        parents = self._parents.pop(block, [])
        for p in parents:
            ch = self._children.get(p)
            if ch and block in ch:
                ch.remove(block)
        for c in self._children.pop(block, []):
            plist = self._parents.get(c)
            if plist and block in plist:
                plist.remove(block)
                if self._storage.db is not None:
                    from kaspa_tpu.consensus import serde

                    self._storage.stage(PREFIX_RELATIONS + c, serde.encode_hash_list(plist))
        self._storage.stage(PREFIX_RELATIONS + block, None)

    def get_parents(self, block: bytes) -> list[bytes]:
        return self._parents[block]

    def get_children(self, block: bytes) -> list[bytes]:
        return self._children.get(block, [])

    def has(self, block: bytes) -> bool:
        return block in self._parents


class GhostdagStore:
    def __init__(self, storage: "ConsensusStorage"):
        self._storage = storage
        self._data: dict[bytes, GhostdagData] = {}

    def insert(self, block: bytes, data: GhostdagData) -> None:
        self._data[block] = data
        if self._storage.db is not None:
            from kaspa_tpu.consensus import serde

            self._storage.stage(PREFIX_GHOSTDAG + block, serde.encode_ghostdag(data))

    def delete(self, block: bytes) -> None:
        self._data.pop(block, None)
        self._storage.stage(PREFIX_GHOSTDAG + block, None)

    def get(self, block: bytes) -> GhostdagData:
        return self._data[block]

    def has(self, block: bytes) -> bool:
        return block in self._data

    def get_blue_work(self, block: bytes) -> int:
        return self._data[block].blue_work

    def get_blue_score(self, block: bytes) -> int:
        return self._data[block].blue_score

    def get_selected_parent(self, block: bytes) -> bytes:
        return self._data[block].selected_parent

    def get_blues_anticone_sizes(self, block: bytes) -> dict[bytes, int]:
        return self._data[block].blues_anticone_sizes


class StatusesStore:
    """Block statuses (consensus/src/model/stores/statuses.rs)."""

    STATUS_INVALID = "invalid"
    STATUS_UTXO_VALID = "utxo_valid"
    STATUS_UTXO_PENDING_VERIFICATION = "utxo_pending"
    STATUS_DISQUALIFIED = "disqualified"
    STATUS_HEADER_ONLY = "header_only"

    def __init__(self, storage: "ConsensusStorage"):
        self._storage = storage
        self._status: dict[bytes, str] = {}

    def set(self, block: bytes, status: str) -> None:
        self._status[block] = status
        self._storage.stage(PREFIX_STATUSES + block, status.encode())

    def delete(self, block: bytes) -> None:
        self._status.pop(block, None)
        self._storage.stage(PREFIX_STATUSES + block, None)

    def get(self, block: bytes) -> str | None:
        return self._status.get(block)

    def is_valid(self, block: bytes) -> bool:
        return self._status.get(block) in (self.STATUS_UTXO_VALID, self.STATUS_UTXO_PENDING_VERIFICATION, self.STATUS_HEADER_ONLY)


class BlockTransactionsStore:
    def __init__(self, storage: "ConsensusStorage"):
        self._storage = storage
        self._txs: dict[bytes, list[Transaction]] = {}

    def insert(self, block: bytes, txs: list[Transaction]) -> None:
        self._txs[block] = txs
        if self._storage.db is not None:
            from kaspa_tpu.consensus import serde

            self._storage.stage(PREFIX_BLOCK_TXS + block, serde.encode_txs(txs))

    def delete(self, block: bytes) -> None:
        self._txs.pop(block, None)
        self._storage.stage(PREFIX_BLOCK_TXS + block, None)

    def get(self, block: bytes) -> list[Transaction]:
        return self._txs[block]

    def has(self, block: bytes) -> bool:
        return block in self._txs


class ConsensusStorage:
    """Aggregation of all stores (consensus/src/consensus/storage.rs:38-83).

    With ``db`` attached (storage/kv.KvStore), mutations stage encoded ops
    into ``pending`` and ``flush()`` commits them as one atomic batch.  The
    mutation sites in the pipeline are exactly the reference's commit points,
    so any prefix of flushed batches is a consistent consensus state.
    """

    def __init__(self, db=None):
        self.db = db
        self.pending: list[tuple[bytes, bytes | None]] = []
        self.headers = HeaderStore(self)
        self.relations = RelationsStore(self)
        self.ghostdag = GhostdagStore(self)
        self.statuses = StatusesStore(self)
        self.block_transactions = BlockTransactionsStore(self)

    def stage(self, key: bytes, value: bytes | None) -> None:
        """Queue one write-through op (value None = delete)."""
        if self.db is not None:
            self.pending.append((key, value))

    def put_meta(self, name: bytes, value: bytes) -> None:
        self.stage(PREFIX_META + name, value)

    def get_meta(self, name: bytes) -> bytes | None:
        if self.db is None:
            return None
        return self.db.engine.get(PREFIX_META + name)

    def flush(self) -> None:
        if self.db is None or not self.pending:
            return
        with self.db.batch() as b:
            for key, value in self.pending:
                if value is None:
                    b.delete(key)
                else:
                    b.put(key, value)
        self.pending.clear()

    def is_initialized(self) -> bool:
        return self.get_meta(b"init") == b"1"

    def load_all(self) -> dict[bytes, dict[bytes, bytes]]:
        """Read the whole DB grouped by prefix: {prefix: {key: value}}."""
        assert self.db is not None
        grouped: dict[bytes, dict[bytes, bytes]] = {}
        for k, v in self.db.engine.items():
            grouped.setdefault(k[:2], {})[k[2:]] = v
        return grouped
