"""Consensus hashing: tx hash/id, header hash, sighash.

Bit-exact re-implementation of the reference's hashing layer:
- consensus/core/src/hashing/mod.rs (HasherExtensions encodings)
- consensus/core/src/hashing/tx.rs (tx hash / v0 & v1 txid)
- consensus/core/src/hashing/header.rs (block hash)
- consensus/core/src/hashing/sighash.rs (schnorr/ecdsa sighash with
  memoized per-tx component hashes killing the quadratic hashing problem)

Golden-tested against the vectors embedded in the reference's test modules.
"""

from __future__ import annotations

from dataclasses import dataclass

from kaspa_tpu.consensus.model.header import Header
from kaspa_tpu.consensus.model.tx import (
    ComputeCommit,
    ScriptPublicKey,
    Transaction,
    TransactionInput,
    TransactionOutput,
    subnetwork_is_native,
)
from kaspa_tpu.crypto import hashing as h

ZERO_HASH = h.ZERO_HASH

# --- encoding flags (hashing/tx.rs TxEncodingFlags) ---
FULL = 0
EXCLUDE_SIGNATURE_SCRIPT = 1 << 0
EXCLUDE_MASS_COMMIT = 1 << 1
EXCLUDE_PAYLOAD = 1 << 2


def _w_len(hasher, n: int):
    hasher.update(n.to_bytes(8, "little"))

def _w_u8(hasher, v: int):
    hasher.update(bytes([v]))

def _w_u16(hasher, v: int):
    hasher.update(v.to_bytes(2, "little"))

def _w_u32(hasher, v: int):
    hasher.update(v.to_bytes(4, "little"))

def _w_u64(hasher, v: int):
    hasher.update(v.to_bytes(8, "little"))

def _w_var_bytes(hasher, b: bytes):
    _w_len(hasher, len(b))
    hasher.update(b)

def _w_blue_work(hasher, work: int):
    """Big-endian bytes without leading zeros, as var-bytes (mod.rs:79-86)."""
    be = work.to_bytes(24, "big").lstrip(b"\x00")
    _w_var_bytes(hasher, be)


# --- transaction writing (hashing/tx.rs:52-130) ---

def _write_outpoint(hasher, outpoint):
    hasher.update(outpoint.transaction_id)
    _w_u32(hasher, outpoint.index)


def _write_input(hasher, inp: TransactionInput, version: int, flags: int):
    _write_outpoint(hasher, inp.previous_outpoint)
    if not (flags & EXCLUDE_SIGNATURE_SCRIPT):
        _w_var_bytes(hasher, inp.signature_script)
        if ComputeCommit.version_expects_sig_op_count_field(version):
            _w_u8(hasher, inp.compute_commit.sig_op_count() or 0)
    else:
        _w_var_bytes(hasher, b"")
    _w_u64(hasher, inp.sequence)
    if not (flags & EXCLUDE_MASS_COMMIT) and ComputeCommit.version_expects_compute_budget_field(version):
        _w_u16(hasher, inp.compute_commit.compute_budget() or 0)


def _write_output(hasher, out: TransactionOutput, version: int):
    _w_u64(hasher, out.value)
    _w_u16(hasher, out.script_public_key.version)
    _w_var_bytes(hasher, out.script_public_key.script)
    if version >= 1:
        _w_u8(hasher, 1 if out.covenant is not None else 0)
        if out.covenant is not None:
            _w_u16(hasher, out.covenant.authorizing_input)
            hasher.update(out.covenant.covenant_id)


def _write_transaction(hasher, tx: Transaction, flags: int):
    _w_u16(hasher, tx.version)
    _w_len(hasher, len(tx.inputs))
    for inp in tx.inputs:
        _write_input(hasher, inp, tx.version, flags)
    _w_len(hasher, len(tx.outputs))
    for out in tx.outputs:
        _write_output(hasher, out, tx.version)
    _w_u64(hasher, tx.lock_time)
    hasher.update(tx.subnetwork_id)
    _w_u64(hasher, tx.gas)
    if not (flags & EXCLUDE_PAYLOAD):
        _w_var_bytes(hasher, tx.payload)
    else:
        _w_var_bytes(hasher, b"")
    if not (flags & EXCLUDE_MASS_COMMIT):
        mass = tx.storage_mass
        if tx.version < 1:
            if mass > 0:
                _w_u64(hasher, mass)
        else:
            _w_u64(hasher, mass)


def tx_hash(tx: Transaction) -> bytes:
    hasher = h.TransactionHash()
    _write_transaction(hasher, tx, FULL)
    return hasher.digest()


def tx_hash_pre_crescendo(tx: Transaction) -> bytes:
    hasher = h.TransactionHash()
    _write_transaction(hasher, tx, EXCLUDE_MASS_COMMIT)
    return hasher.digest()


def tx_id(tx: Transaction) -> bytes:
    return tx_id_v0(tx) if tx.version == 0 else tx_id_v1(tx)


def tx_id_v0(tx: Transaction) -> bytes:
    hasher = h.TransactionID()
    _write_transaction(hasher, tx, EXCLUDE_SIGNATURE_SCRIPT | EXCLUDE_MASS_COMMIT)
    return hasher.digest()


# Blake3-keyed hashers for v1 ids (hashers.rs blake3_hasher) arrive with the
# KIP-21 SeqCommit layer; v1 txid needs PayloadDigest/TransactionRest/
# TransactionV1Id blake3 domains.
def tx_id_v1(tx: Transaction) -> bytes:
    from kaspa_tpu.crypto import blake3 as b3

    payload_digest = b3.PAYLOAD_ZERO_DIGEST if not tx.payload else b3.keyed_hash(b"PayloadDigest", tx.payload)
    rest = b3.Blake3Keyed(b"TransactionRest")
    _write_transaction(rest, tx, EXCLUDE_PAYLOAD | EXCLUDE_SIGNATURE_SCRIPT | EXCLUDE_MASS_COMMIT)
    hasher = b3.Blake3Keyed(b"TransactionV1Id")
    hasher.update(payload_digest)
    hasher.update(rest.digest())
    return hasher.digest()


# --- header hashing (hashing/header.rs) ---

def header_hash_override_nonce_time(header: Header, nonce: int, timestamp: int) -> bytes:
    hasher = h.BlockHash()
    _w_u16(hasher, header.version)
    _w_len(hasher, len(header.parents_by_level))
    for level in header.parents_by_level:
        _w_len(hasher, len(level))
        for parent in level:
            hasher.update(parent)
    hasher.update(header.hash_merkle_root)
    hasher.update(header.accepted_id_merkle_root)
    hasher.update(header.utxo_commitment)
    _w_u64(hasher, timestamp)
    _w_u32(hasher, header.bits)
    _w_u64(hasher, nonce)
    _w_u64(hasher, header.daa_score)
    _w_u64(hasher, header.blue_score)
    _w_blue_work(hasher, header.blue_work)
    hasher.update(header.pruning_point)
    return hasher.digest()


def header_hash(header: Header) -> bytes:
    return header_hash_override_nonce_time(header, header.nonce, header.timestamp)


# --- sighash (hashing/sighash.rs, sighash_type.rs) ---

SIG_HASH_ALL = 0b0000_0001
SIG_HASH_NONE = 0b0000_0010
SIG_HASH_SINGLE = 0b0000_0100
SIG_HASH_ANY_ONE_CAN_PAY = 0b1000_0000
SIG_HASH_MASK = 0b0000_0111

ALLOWED_SIG_HASH_TYPES = (
    SIG_HASH_ALL,
    SIG_HASH_NONE,
    SIG_HASH_SINGLE,
    SIG_HASH_ALL | SIG_HASH_ANY_ONE_CAN_PAY,
    SIG_HASH_NONE | SIG_HASH_ANY_ONE_CAN_PAY,
    SIG_HASH_SINGLE | SIG_HASH_ANY_ONE_CAN_PAY,
)


def sighash_is_all(t: int) -> bool:
    return t & SIG_HASH_MASK == SIG_HASH_ALL

def sighash_is_none(t: int) -> bool:
    return t & SIG_HASH_MASK == SIG_HASH_NONE

def sighash_is_single(t: int) -> bool:
    return t & SIG_HASH_MASK == SIG_HASH_SINGLE

def sighash_is_anyone_can_pay(t: int) -> bool:
    return t & SIG_HASH_ANY_ONE_CAN_PAY != 0


@dataclass
class SigHashReusedValues:
    """Memoizes the five per-tx component hashes (sighash.rs:14-49)."""

    previous_outputs_hash: bytes | None = None
    sequences_hash: bytes | None = None
    sig_op_counts_hash: bytes | None = None
    outputs_hash: bytes | None = None
    payload_hash: bytes | None = None


def _previous_outputs_hash(tx: Transaction, hash_type: int, reused: SigHashReusedValues) -> bytes:
    if sighash_is_anyone_can_pay(hash_type):
        return ZERO_HASH
    if reused.previous_outputs_hash is None:
        hasher = h.TransactionSigningHash()
        for inp in tx.inputs:
            hasher.update(inp.previous_outpoint.transaction_id)
            _w_u32(hasher, inp.previous_outpoint.index)
        reused.previous_outputs_hash = hasher.digest()
    return reused.previous_outputs_hash


def _sequences_hash(tx: Transaction, hash_type: int, reused: SigHashReusedValues) -> bytes:
    if sighash_is_single(hash_type) or sighash_is_anyone_can_pay(hash_type) or sighash_is_none(hash_type):
        return ZERO_HASH
    if reused.sequences_hash is None:
        hasher = h.TransactionSigningHash()
        for inp in tx.inputs:
            _w_u64(hasher, inp.sequence)
        reused.sequences_hash = hasher.digest()
    return reused.sequences_hash


def _sig_op_counts_hash(tx: Transaction, hash_type: int, reused: SigHashReusedValues) -> bytes:
    if sighash_is_anyone_can_pay(hash_type):
        return ZERO_HASH
    if reused.sig_op_counts_hash is None:
        hasher = h.TransactionSigningHash()
        for inp in tx.inputs:
            _w_u8(hasher, inp.compute_commit.sig_op_count() or 0)
        reused.sig_op_counts_hash = hasher.digest()
    return reused.sig_op_counts_hash


def _payload_hash(tx: Transaction, reused: SigHashReusedValues) -> bytes:
    if subnetwork_is_native(tx.subnetwork_id) and not tx.payload:
        return ZERO_HASH
    if reused.payload_hash is None:
        hasher = h.TransactionSigningHash()
        _w_var_bytes(hasher, tx.payload)
        reused.payload_hash = hasher.digest()
    return reused.payload_hash


def _hash_output(hasher, output: TransactionOutput, version: int):
    _w_u64(hasher, output.value)
    _hash_script_public_key(hasher, output.script_public_key)
    if version >= 1:
        _w_u8(hasher, 1 if output.covenant is not None else 0)
        if output.covenant is not None:
            _w_u16(hasher, output.covenant.authorizing_input)
            hasher.update(output.covenant.covenant_id)


def _hash_script_public_key(hasher, spk: ScriptPublicKey):
    _w_u16(hasher, spk.version)
    _w_var_bytes(hasher, spk.script)


def _outputs_hash(tx: Transaction, hash_type: int, reused: SigHashReusedValues, input_index: int) -> bytes:
    if sighash_is_none(hash_type):
        return ZERO_HASH
    if sighash_is_single(hash_type):
        if input_index >= len(tx.outputs):
            return ZERO_HASH
        hasher = h.TransactionSigningHash()
        _hash_output(hasher, tx.outputs[input_index], tx.version)
        return hasher.digest()
    if reused.outputs_hash is None:
        hasher = h.TransactionSigningHash()
        for out in tx.outputs:
            _hash_output(hasher, out, tx.version)
        reused.outputs_hash = hasher.digest()
    return reused.outputs_hash


def calc_schnorr_signature_hash(
    tx: Transaction,
    utxo_entries,  # list[UtxoEntry] aligned with tx.inputs
    input_index: int,
    hash_type: int,
    reused: SigHashReusedValues,
) -> bytes:
    inp = tx.inputs[input_index]
    utxo = utxo_entries[input_index]
    hasher = h.TransactionSigningHash()
    _w_u16(hasher, tx.version)
    hasher.update(_previous_outputs_hash(tx, hash_type, reused))
    hasher.update(_sequences_hash(tx, hash_type, reused))
    if tx.version < 1:
        hasher.update(_sig_op_counts_hash(tx, hash_type, reused))
    _write_outpoint(hasher, inp.previous_outpoint)
    _hash_script_public_key(hasher, utxo.script_public_key)
    _w_u64(hasher, utxo.amount)
    _w_u64(hasher, inp.sequence)
    if tx.version < 1:
        _w_u8(hasher, inp.compute_commit.sig_op_count() or 0)
    hasher.update(_outputs_hash(tx, hash_type, reused, input_index))
    _w_u64(hasher, tx.lock_time)
    hasher.update(tx.subnetwork_id)
    _w_u64(hasher, tx.gas)
    hasher.update(_payload_hash(tx, reused))
    _w_u8(hasher, hash_type)
    return hasher.digest()


def calc_ecdsa_signature_hash(tx, utxo_entries, input_index, hash_type, reused) -> bytes:
    inner = calc_schnorr_signature_hash(tx, utxo_entries, input_index, hash_type, reused)
    hasher = h.TransactionSigningHashECDSA()
    hasher.update(inner)
    return hasher.digest()
