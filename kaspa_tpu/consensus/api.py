"""ConsensusApi: the formal boundary between consensus and its consumers.

Reference: consensus/core/src/api/mod.rs (the ~87-method ConsensusApi
trait).  RPC, P2P flows, indexes and tools talk to consensus exclusively
through this facade — never by reaching into `Consensus` attributes — so
staging swaps, session locking and store reorganisations cannot silently
break consumers.  Method names and shapes mirror the trait; methods the
reference marks unimplemented-by-default raise ConsensusError the same
way the trait's default bodies panic.
"""

from __future__ import annotations


class ConsensusError(Exception):
    pass


class ConsensusApi:
    """Facade over one Consensus instance (api/mod.rs:114)."""

    def __init__(self, consensus):
        self._c = consensus

    # -- block intake (api/mod.rs:114-131) ------------------------------

    def build_block_template(self, miner_data, txs, timestamp=None):
        return self._c.build_block_template(miner_data, txs, timestamp)

    def validate_and_insert_block(self, block) -> str:
        return self._c.validate_and_insert_block(block)

    def validate_and_insert_header(self, header) -> str:
        return self._c.validate_and_insert_header(header)

    # -- mempool support (api/mod.rs:133-163) ----------------------------

    def validate_mempool_transaction(self, tx, entries, pov_daa_score, flags):
        """``entries``: resolved UtxoEntry list aligned with tx.inputs
        (transaction_validator.py validate_populated_transaction_and_get_fee)."""
        return self._c.transaction_validator.validate_populated_transaction_and_get_fee(
            tx, entries, pov_daa_score, flags
        )

    def validate_tx_in_isolation(self, tx) -> None:
        self._c.transaction_validator.validate_tx_in_isolation(tx)

    def calculate_transaction_non_contextual_masses(self, tx):
        return self._c.transaction_validator.mass_calculator.calc_non_contextual_masses(tx)

    # -- virtual state (api/mod.rs:166-230) ------------------------------

    def get_stats(self) -> dict:
        return {
            "block_count": len(self._c.storage.headers) - 1,
            "header_count": len(self._c.storage.headers),
            "tx_count": len(self._c.storage.block_transactions),
            "virtual_daa_score": self.get_virtual_daa_score(),
        }

    def get_virtual_daa_score(self) -> int:
        return self._c.get_virtual_daa_score()

    def get_virtual_bits(self) -> int:
        return self._c.virtual_state.bits

    def get_virtual_past_median_time(self) -> int:
        return self._c.virtual_state.past_median_time

    def get_virtual_merge_depth_root(self) -> bytes | None:
        from kaspa_tpu.consensus.reachability import ORIGIN

        sink = self.get_sink()
        root = self._c.depth_manager.merge_depth_root(sink)
        return root if root != ORIGIN else None

    def get_sink(self) -> bytes:
        return self._c.sink()

    def get_sink_timestamp(self) -> int:
        return self._c.storage.headers.get_timestamp(self.get_sink())

    def get_sink_blue_score(self) -> int:
        return self._c.storage.ghostdag.get_blue_score(self.get_sink())

    def get_sink_daa_score_timestamp(self) -> tuple[int, int]:
        sink = self.get_sink()
        h = self._c.storage.headers.get(sink)
        return h.daa_score, h.timestamp

    def get_retention_period_root(self) -> bytes:
        return self._c.pruning_processor.retention_period_root

    def estimate_block_count(self) -> dict:
        return {"block_count": len(self._c.storage.headers) - 1, "header_count": len(self._c.storage.headers)}

    def get_virtual_chain_from_block(self, low: bytes, added_limit: int | None = None) -> dict:
        chain = []
        cur = self.get_sink()
        while cur != low:
            chain.append(cur)
            if cur == self._c.params.genesis.hash:
                raise ConsensusError(f"block {low.hex()} is not a chain ancestor of the sink")
            cur = self._c.storage.ghostdag.get_selected_parent(cur)
        chain.reverse()
        if added_limit is not None:
            chain = chain[:added_limit]
        return {"added": chain, "removed": []}

    def get_virtual_parents(self) -> set[bytes]:
        return set(self._c.virtual_state.parents)

    def get_virtual_parents_ordered(self) -> list[bytes]:
        """Virtual parents in consensus order (selected parent first) —
        the RPC-visible ordering."""
        return list(self._c.virtual_state.parents)

    def get_virtual_parents_len(self) -> int:
        return len(self._c.virtual_state.parents)

    def get_virtual_utxo_view(self):
        """Read view over the virtual UTXO set (mempool/tx-resolution)."""
        return self._c.get_virtual_utxo_view()

    def get_virtual_utxos(self, from_outpoint=None, chunk_size: int = 1000):
        import heapq

        self._c.get_virtual_utxo_view()  # repositions utxo_set at the sink
        diff = self._c.virtual_utxo_diff
        after = (
            (from_outpoint.transaction_id, from_outpoint.index) if from_outpoint is not None else None
        )

        def qualifies(op):
            return (after is None or (op.transaction_id, op.index) > after) and op not in diff.remove

        # O(N + chunk log chunk): one filtered pass + partial selection,
        # never a full materialized sort of the whole UTXO set per page
        candidates = (
            (op, e) for op, e in self._c.utxo_set.items() if qualifies(op) and op not in diff.add
        )
        merged = list(
            heapq.nsmallest(chunk_size, candidates, key=lambda kv: (kv[0].transaction_id, kv[0].index))
        )
        extra = [
            (op, e)
            for op, e in diff.add.items()
            if after is None or (op.transaction_id, op.index) > after
        ]
        merged.extend(extra)
        merged.sort(key=lambda kv: (kv[0].transaction_id, kv[0].index))
        return merged[:chunk_size]

    def get_tips(self) -> list[bytes]:
        return sorted(self._c.tips)

    def get_tips_len(self) -> int:
        return len(self._c.tips)

    def calc_transaction_hash_merkle_root(self, txs) -> bytes:
        from kaspa_tpu.crypto import merkle

        return merkle.calc_hash_merkle_root(txs)

    # -- pruning / proof (api/mod.rs:303-370, 404-423, 495-567) ----------

    def validate_pruning_proof(self, proof, defender_proof=None):
        return self._c.pruning_proof_manager.validate_proof(proof, defender_proof)

    def apply_pruning_proof(self, proof, trusted, utxo_set, defender_proof=None) -> None:
        self._c.pruning_proof_manager.import_pruning_data(proof, trusted, utxo_set, defender_proof)

    def get_pruning_point_proof(self):
        return self._c.pruning_proof_manager.build_proof()

    def get_pruning_point_anticone_and_trusted_data(self):
        return self._c.pruning_proof_manager.get_trusted_data()

    def get_pruning_point_utxos(self):
        return self._c.pruning_processor.pruning_utxo_set

    def pruning_point(self) -> bytes:
        return self._c.pruning_processor.pruning_point

    def pruning_point_headers(self) -> list:
        return [self._c.storage.headers.get(h) for h in self._c.pruning_processor.past_pruning_points]

    def get_n_last_pruning_points(self, n: int) -> list[bytes]:
        return self._c.pruning_processor.past_pruning_points[-n:]

    def get_finality_conflicts(self) -> dict[bytes, str]:
        """Observed finality conflicts: violating tip -> active|resolved."""
        return dict(self._c._finality_conflicts)

    def acknowledge_finality_conflicts(self) -> list[bytes]:
        """Mark every active conflict resolved (operator action); returns
        the acknowledged tips.  The entries stay tracked so the virtual
        resolver does not re-notify them."""
        acked = [t for t, st in self._c._finality_conflicts.items() if st == "active"]
        for t in acked:
            self._c._finality_conflicts[t] = "resolved"
        return acked

    def finality_point(self) -> bytes:
        return self._c.depth_manager.finality_point(self.get_sink())

    def inactivity_shortcut_block_for_pov(self, pov_block: bytes) -> bytes:
        gd = self.get_ghostdag_data(pov_block)
        target = gd.blue_score - self._c.params.finality_depth - 1
        if target < 0:
            return self._c.params.genesis.hash
        try:
            return self._c._selected_chain_block_at(target)
        except Exception as e:  # retention violation => typed facade error
            raise ConsensusError(str(e)) from e

    # -- topology / reachability (api/mod.rs:376-401) --------------------

    def is_chain_ancestor_of(self, low: bytes, high: bytes) -> bool:
        return self._c.reachability.is_chain_ancestor_of(low, high)

    def is_chain_block(self, block: bytes) -> bool:
        return self._c.reachability.is_chain_ancestor_of(block, self.get_sink())

    def get_hashes_between(self, low: bytes, high: bytes, max_blocks: int | None = None):
        from kaspa_tpu.consensus.processes.sync import SyncManager

        return SyncManager(self._c).antipast_hashes_between(low, high, max_blocks)

    def get_anticone(self, block: bytes) -> list[bytes]:
        """BFS down from the tips, pruning at ancestors of ``block`` — the
        visit set is future(block) + anticone(block) + the pruned frontier,
        not the whole header store (traversal_manager anticone walk)."""
        reach = self._c.reachability
        relations = self._c.storage.relations
        out, seen = [], set()
        queue = [t for t in self._c.tips if reach.has(t)]
        seen.update(queue)
        while queue:
            h = queue.pop()
            if h == block or reach.is_dag_ancestor_of(h, block):
                continue  # h and its whole past are in past(block) or block
            if not reach.is_dag_ancestor_of(block, h):
                out.append(h)
            for p in relations.get_parents(h) if relations.has(h) else []:
                if p not in seen and reach.has(p):
                    seen.add(p)
                    queue.append(p)
        return out

    def create_block_locator_from_pruning_point(self, high: bytes, limit: int | None = None):
        from kaspa_tpu.consensus.processes.sync import SyncManager

        return SyncManager(self._c).create_block_locator_from_pruning_point(
            high, self.pruning_point(), limit
        )

    def create_virtual_selected_chain_block_locator(self, low=None, high=None):
        from kaspa_tpu.consensus.processes.sync import SyncManager

        return SyncManager(self._c).create_block_locator_from_pruning_point(
            high if high is not None else self.get_sink(),
            low if low is not None else self.pruning_point(),
        )

    # -- block data (api/mod.rs:384-470) ----------------------------------

    def get_header(self, block: bytes):
        if not self._c.storage.headers.has(block):
            raise ConsensusError(f"header {block.hex()} not found")
        return self._c.storage.headers.get(block)

    def get_headers_selected_tip(self) -> bytes:
        return self.get_sink()

    def get_block(self, block: bytes):
        from kaspa_tpu.consensus.model.block import Block

        if not self._c.storage.block_transactions.has(block):
            raise ConsensusError(f"block {block.hex()} has no body")
        return Block(self.get_header(block), self._c.storage.block_transactions.get(block))

    def get_block_even_if_header_only(self, block: bytes):
        from kaspa_tpu.consensus.model.block import Block

        txs = (
            self._c.storage.block_transactions.get(block)
            if self._c.storage.block_transactions.has(block)
            else []
        )
        return Block(self.get_header(block), txs)

    def get_block_body(self, block: bytes):
        if not self._c.storage.block_transactions.has(block):
            raise ConsensusError(f"block {block.hex()} has no body")
        return self._c.storage.block_transactions.get(block)

    def get_block_transactions(self, block: bytes, indices=None):
        txs = self.get_block_body(block)
        if indices is None:
            return txs
        return [txs[i] for i in indices]

    def get_ghostdag_data(self, block: bytes):
        if not self._c.storage.ghostdag.has(block):
            raise ConsensusError(f"no ghostdag data for {block.hex()}")
        return self._c.storage.ghostdag.get(block)

    def get_block_children(self, block: bytes) -> list[bytes] | None:
        if not self._c.storage.relations.has(block):
            return None
        return self._c.storage.relations.get_children(block)

    def get_block_parents(self, block: bytes) -> list[bytes] | None:
        if not self._c.storage.relations.has(block):
            return None
        return self._c.storage.relations.get_parents(block)

    def get_block_status(self, block: bytes) -> str | None:
        return self._c.storage.statuses.get(block)

    def get_block_acceptance_data(self, block: bytes):
        acc = self._c.acceptance_data.try_get(block)
        if acc is None:
            raise ConsensusError(f"no acceptance data for {block.hex()}")
        return acc

    def get_blocks_acceptance_data(self, blocks):
        return [self.get_block_acceptance_data(b) for b in blocks]

    def get_block_count(self) -> int:
        return len(self._c.storage.headers) - 1

    def block_exists(self, block: bytes) -> bool:
        return self._c.storage.headers.has(block)

    def has_block_body(self, block: bytes) -> bool:
        return self._c.storage.block_transactions.has(block)

    def iter_block_hashes(self):
        """All known block hashes (header store keys)."""
        return self._c.storage.headers.keys()

    def get_daa_score(self, block: bytes) -> int:
        return self._c.storage.headers.get_daa_score(block)

    def get_block_timestamp(self, block: bytes) -> int:
        return self._c.storage.headers.get_timestamp(block)

    def get_selected_parent(self, block: bytes) -> bytes:
        return self._c.storage.ghostdag.get_selected_parent(block)

    def is_dag_ancestor_of(self, low: bytes, high: bytes) -> bool:
        return self._c.reachability.is_dag_ancestor_of(low, high)

    def get_next_chain_ancestor(self, descendant: bytes, ancestor: bytes) -> bytes:
        """The selected-chain child of `ancestor` on the path to `descendant`."""
        return self._c.reachability.get_next_chain_ancestor(descendant, ancestor)

    def get_current_block_color(self, block: bytes) -> bool:
        """Blue/red of `block` from the virtual's perspective: the color
        assigned by the lowest selected-chain block merging it
        (consensus/mod.rs get_current_block_color)."""
        sink = self.get_sink()
        if block == sink or self.is_chain_ancestor_of(block, sink):
            return True
        if not self.is_dag_ancestor_of(block, sink):
            raise ConsensusError("block is not in the past of the virtual sink")
        merging = sink
        genesis = self._c.params.genesis.hash
        while merging != genesis:
            sp = self.get_selected_parent(merging)
            if not self.is_dag_ancestor_of(block, sp):
                break
            merging = sp
        return block in self.get_ghostdag_data(merging).mergeset_blues

    def iter_acceptance(self):
        """(accepting chain block, accepted txids) pairs over the retained
        acceptance column (tx-index source data)."""
        return self._c.acceptance_data.items()

    def get_accepted_transaction_ids(self, block: bytes) -> list:
        """Accepted txids of a chain block, or [] when not a chain block /
        outside retention (the virtual-chain RPC shape)."""
        acc = self._c.acceptance_data.try_get(block)
        return list(acc) if acc is not None else []

    def find_output_script(self, outpoint, max_daa: int | None = None):
        """Bounded body search for a funding output's script (the
        reference resolves this through its tx-index; here retained bodies
        below `max_daa` are scanned)."""
        store = self._c.storage.block_transactions
        for bh in list(store.keys()):
            if (
                max_daa
                and self.block_exists(bh)
                and self.get_daa_score(bh) > max_daa
            ):
                continue
            for tx in store.get(bh):
                if tx.id() == outpoint.transaction_id and outpoint.index < len(tx.outputs):
                    return tx.outputs[outpoint.index].script_public_key
        return None

    # -- misc (api/mod.rs:509-529) ----------------------------------------

    def estimate_network_hashes_per_second(self, start_hash=None, window_size: int = 1000) -> int:
        """Σ selected-chain work over `window_size` blocks / elapsed time
        (rpc.rs semantics; the oldest block bounds the span uncounted)."""
        from kaspa_tpu.consensus.difficulty import calc_work

        c = self._c
        cur = start_hash if start_hash is not None else self.get_sink()
        if not c.storage.headers.has(cur):
            raise ConsensusError("start hash not found")
        genesis = c.params.genesis.hash
        total_work = 0
        last = c.storage.headers.get_timestamp(cur)
        first = last
        for _ in range(window_size):
            if cur == genesis:
                break
            total_work += calc_work(c.storage.headers.get_bits(cur))
            cur = c.storage.ghostdag.get_selected_parent(cur)
            first = c.storage.headers.get_timestamp(cur)
        elapsed_ms = max(last - first, 1)
        return total_work * 1000 // elapsed_ms

    def get_missing_block_body_hashes(self, high: bytes) -> list[bytes]:
        c = self._c
        pp = self.pruning_point()
        if not c.reachability.is_chain_ancestor_of(pp, high):
            raise ConsensusError("pruning point not in the given chain")
        out = []
        for h in c.reachability.forward_chain_iterator(pp, high):
            if not c.storage.block_transactions.has(h):
                out.append(h)
        return out

    def creation_timestamp(self) -> int:
        return self._c.params.genesis.timestamp
