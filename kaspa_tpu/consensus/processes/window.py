"""Sampled difficulty / past-median-time windows (KIP-0004) + DAA.

Re-implementation of consensus/src/processes/{window,difficulty,
past_median_time}.rs (SampledWindowManager & friends): bounded max-work
heaps assembled by walking the selected chain, per-block window caches,
daa-score / mergeset-non-daa computation, difficulty retargeting over the
sampled window, and the 11-point median-time average.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field

from kaspa_tpu.consensus.difficulty import compact_to_target, target_to_compact
from kaspa_tpu.consensus.reachability import ORIGIN
from kaspa_tpu.consensus.stores import GhostdagData, GhostdagStore, HeaderStore


class RuleError(Exception):
    pass


class InsufficientDaaWindowSize(RuleError):
    pass


DIFFICULTY_WINDOW = "difficulty"
MEDIAN_TIME_WINDOW = "median_time"


class _LruWindowCache(dict):
    """Bounded LRU over per-block window lists (block_window_cache.rs is a
    CachePolicy-bounded store in the reference for the same reason: windows
    grow with history but only the recent tips are ever re-read)."""

    def __init__(self, bound: int = 8192):
        super().__init__()
        self._bound = bound

    def __setitem__(self, key, value):
        if key in self:
            del self[key]
        super().__setitem__(key, value)
        while len(self) > self._bound:
            del self[next(iter(self))]

    def __getitem__(self, key):
        # refresh recency (dict preserves insertion order)
        value = super().__getitem__(key)
        super().__delitem__(key)
        super().__setitem__(key, value)
        return value

    def get(self, key, default=None):
        try:
            return self[key]
        except KeyError:
            return default


class BoundedBlockHeap:
    """Keeps the `bound` blocks with highest (blue_work, hash).

    Mirror of window.rs BoundedSizeBlockHeap (reversed BinaryHeap); python
    heapq is a min-heap so the root is the eviction candidate directly.
    """

    def __init__(self, bound: int, items=None):
        self.bound = bound
        self.heap: list[tuple[int, bytes]] = list(items) if items else []
        heapq.heapify(self.heap)
        while len(self.heap) > bound:
            heapq.heappop(self.heap)

    def reached_size_bound(self) -> bool:
        return len(self.heap) == self.bound

    def can_push(self, hash_: bytes, blue_work: int) -> bool:
        if self.reached_size_bound():
            return self.heap[0] <= (blue_work, hash_)
        return True

    def try_push(self, hash_: bytes, blue_work: int) -> bool:
        item = (blue_work, hash_)
        if self.reached_size_bound():
            if self.heap[0] > item:
                return False
            heapq.heapreplace(self.heap, item)
            return True
        heapq.heappush(self.heap, item)
        return True

    def merge_ancestor_heap(self, ancestor_items) -> None:
        self.heap.extend(ancestor_items)
        heapq.heapify(self.heap)
        while len(self.heap) > self.bound:
            heapq.heappop(self.heap)


@dataclass
class DaaWindow:
    window: list[tuple[int, bytes]]  # (blue_work, hash)
    daa_score: int
    mergeset_non_daa: set[bytes]


class SampledWindowManager:
    def __init__(
        self,
        genesis_hash: bytes,
        genesis_bits: int,
        genesis_timestamp: int,
        ghostdag_store: GhostdagStore,
        headers_store: HeaderStore,
        max_difficulty_target: int,
        target_time_per_block: int,
        difficulty_window_size: int,
        min_difficulty_window_size: int,
        difficulty_sample_rate: int,
        past_median_time_window_size: int,
        past_median_time_sample_rate: int,
    ):
        assert min_difficulty_window_size <= difficulty_window_size
        self.genesis_hash = genesis_hash
        self.genesis_bits = genesis_bits
        self.genesis_timestamp = genesis_timestamp
        self.gd = ghostdag_store
        self.headers = headers_store
        self.max_difficulty_target = max_difficulty_target
        self.target_time_per_block = target_time_per_block
        self.difficulty_window_size = difficulty_window_size
        self.min_difficulty_window_size = min_difficulty_window_size
        self.difficulty_sample_rate = difficulty_sample_rate
        self.past_median_time_window_size = past_median_time_window_size
        self.past_median_time_sample_rate = past_median_time_sample_rate
        # block_window_cache stores (consensus/src/model/stores/block_window_cache.rs):
        # bounded LRU — windows are derivable from headers, so eviction only
        # costs a rebuild, never correctness
        self._difficulty_cache: dict[bytes, list] = _LruWindowCache()
        self._median_cache: dict[bytes, list] = _LruWindowCache()

    # --- sizes / rates ---

    def window_size(self, window_type: str) -> int:
        return self.difficulty_window_size if window_type == DIFFICULTY_WINDOW else self.past_median_time_window_size

    def sample_rate(self, window_type: str) -> int:
        return self.difficulty_sample_rate if window_type == DIFFICULTY_WINDOW else self.past_median_time_sample_rate

    def difficulty_full_window_size(self) -> int:
        return self.difficulty_window_size * self.difficulty_sample_rate

    def lowest_daa_blue_score(self, gd: GhostdagData) -> int:
        full = self.difficulty_full_window_size()
        return max(gd.blue_score, full) - full

    # --- window construction (window.rs build_block_window) ---

    def _sampled_mergeset_iter(self, sample_rate: int, gd: GhostdagData, sp_blue_work: int):
        """Yields ('sampled', (blue_work, hash)) / ('non_daa', hash) for the
        mergeset in descending (blue_work, hash) order, selected parent first."""
        sp_daa_score = self.headers.get_daa_score(gd.selected_parent)
        threshold = self.lowest_daa_blue_score(gd)
        rest = sorted(
            ((self.gd.get_blue_work(h), h) for h in gd.unordered_mergeset_without_selected_parent()),
            reverse=True,
        )
        index = 0
        for blue_work, h in [(sp_blue_work, gd.selected_parent)] + rest:
            if self.gd.get_blue_score(h) < threshold:
                yield ("non_daa", h)
            else:
                index += 1
                if (sp_daa_score + index) % sample_rate == 0:
                    yield ("sampled", (blue_work, h))

    def _push_mergeset(self, heap: BoundedBlockHeap, sample_rate: int, gd: GhostdagData, sp_blue_work: int, non_daa_out: set | None):
        if non_daa_out is not None:
            for kind, payload in self._sampled_mergeset_iter(sample_rate, gd, sp_blue_work):
                if kind == "sampled":
                    heap.try_push(payload[1], payload[0])
                else:
                    non_daa_out.add(payload)
        else:
            for kind, payload in self._sampled_mergeset_iter(sample_rate, gd, sp_blue_work):
                if kind == "sampled" and not heap.try_push(payload[1], payload[0]):
                    return

    def build_block_window(self, gd: GhostdagData, window_type: str, non_daa_out: set | None = None) -> list:
        window_size = self.window_size(window_type)
        sample_rate = self.sample_rate(window_type)
        if window_size == 0:
            return []
        if gd.selected_parent == self.genesis_hash:
            if non_daa_out is not None:
                non_daa_out.add(self.genesis_hash)
            return []
        if gd.selected_parent == ORIGIN:
            raise InsufficientDaaWindowSize(0)

        cache = self._difficulty_cache if window_type == DIFFICULTY_WINDOW else (
            self._median_cache if window_type == MEDIAN_TIME_WINDOW else None
        )
        sp_blue_work = self.gd.get_blue_work(gd.selected_parent)

        # init from selected parent's cached window
        if cache is not None and gd.selected_parent in cache:
            heap = BoundedBlockHeap(window_size, cache[gd.selected_parent])
            self._push_mergeset(heap, sample_rate, gd, sp_blue_work, non_daa_out)
            return sorted(heap.heap)

        heap = BoundedBlockHeap(window_size)
        self._push_mergeset(heap, sample_rate, gd, sp_blue_work, non_daa_out)

        current = self.gd.get(gd.selected_parent)
        while True:
            if current.selected_parent == ORIGIN:
                if heap.reached_size_bound():
                    break
                raise InsufficientDaaWindowSize(len(heap.heap))
            if current.selected_parent == self.genesis_hash:
                break
            parent_gd = self.gd.get(current.selected_parent)
            if not heap.can_push(current.selected_parent, parent_gd.blue_work):
                break
            self._push_mergeset(heap, sample_rate, current, parent_gd.blue_work, None)
            if cache is not None and current.selected_parent in cache:
                heap.merge_ancestor_heap(list(cache[current.selected_parent]))
                break
            current = parent_gd
        return sorted(heap.heap)

    def cache_block_window(self, block: bytes, window_type: str, window: list) -> None:
        (self._difficulty_cache if window_type == DIFFICULTY_WINDOW else self._median_cache)[block] = window

    # --- DAA (difficulty.rs) ---

    def calc_daa_score_and_non_daa(self, gd: GhostdagData) -> tuple[int, set[bytes]]:
        threshold = self.lowest_daa_blue_score(gd)
        non_daa = {h for h in gd.unordered_mergeset() if self.gd.get_blue_score(h) < threshold}
        sp_daa = self.headers.get_daa_score(gd.selected_parent)
        return sp_daa + gd.mergeset_size() - len(non_daa), non_daa

    def block_daa_window(self, gd: GhostdagData) -> DaaWindow:
        non_daa: set[bytes] = set()
        window = self.build_block_window(gd, DIFFICULTY_WINDOW, non_daa)
        sp_daa = self.headers.get_daa_score(gd.selected_parent) if gd.selected_parent != ORIGIN else 0
        daa_score = sp_daa + gd.mergeset_size() - len(non_daa)
        return DaaWindow(window, daa_score, non_daa)

    # --- difficulty retarget (difficulty.rs calculate_difficulty_bits) ---

    def calculate_difficulty_bits(self, gd: GhostdagData, daa_window: DaaWindow) -> int:
        window = daa_window.window
        if len(window) < self.min_difficulty_window_size:
            if gd.selected_parent == self.genesis_hash:
                return self.genesis_bits
            return self.headers.get_bits(gd.selected_parent)

        # DifficultyBlock ordering: (timestamp, blue_work, hash)
        blocks = [(self.headers.get_timestamp(h), bw, h) for bw, h in window]
        min_block = min(blocks)
        max_block = max(blocks)
        min_ts, max_ts = min_block[0], max_block[0]
        rest = list(blocks)
        rest.remove(min_block)  # swap_remove of the minimum
        n = len(rest)
        targets_sum = sum(compact_to_target(self.headers.get_bits(h)) for _, _, h in rest)
        average_target = targets_sum // n
        measured_duration = max(max_ts - min_ts, 1)
        expected_duration = self.target_time_per_block * self.difficulty_sample_rate * n
        new_target = average_target * measured_duration // expected_duration
        return target_to_compact(min(new_target, self.max_difficulty_target))

    # --- past median time (past_median_time.rs) ---

    def calc_past_median_time(self, gd: GhostdagData) -> tuple[int, list]:
        window = self.build_block_window(gd, MEDIAN_TIME_WINDOW)
        if not window:
            return self.headers.get_timestamp(gd.selected_parent), window
        timestamps = sorted(self.headers.get_timestamp(h) for _, h in window)
        frame = min(len(timestamps), 11)
        ending_index = (len(timestamps) + frame + 1) // 2
        frame_slice = timestamps[ending_index - frame : ending_index]
        return (sum(frame_slice) + frame // 2) // frame, window

    def estimate_network_hashes_per_second(self, window: list) -> int:
        if len(window) < 1000:
            raise RuleError(f"window size {len(window)} below minimum 1000")
        timestamps = [self.headers.get_timestamp(h) for _, h in window]
        min_ts, max_ts = min(timestamps), max(timestamps)
        if min_ts == max_ts:
            raise RuleError("empty timestamp range")
        duration_s = (max_ts - min_ts) // 1000
        if duration_s == 0:
            return 0
        works = [bw for bw, _ in window]
        return (max(works) - min(works)) // duration_s
