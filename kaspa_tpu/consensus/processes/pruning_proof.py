"""Pruning proof: build, validate, and trusted-state bootstrap.

Reference: consensus/src/processes/pruning_proof/{build,validate,apply}.rs
and the trusted-sync surface of consensus/core/src/api/mod.rs
(get_pruning_point_proof / get_pruning_point_anticone_and_trusted_data /
validate_and_insert_trusted_block / import_pruning_point_utxo_set).

A pruning proof is, per proof level L, the top slice (by blue work) of the
level-L header sub-DAG below the pruning point — headers whose PoW value
promotes them to level >= L.  Because expected density halves per level,
~2m headers per level commit to the chain's cumulative work all the way
down without shipping the full history.  A syncing node validates:

1. per-header PoW, and that each header's PoW value actually reaches the
   level it is presented at;
2. per-level parent closure + topological consistency;
3. per-level depth (>= m headers unless the level bottoms out at genesis);
4. that the proof's pruning point carries more blue work than the node's
   current sink (the adopt-or-reject decision).

The apply side here is a *trusted state snapshot*: exactly the data a
pruned donor node itself retains (pruning point + anticone with full data,
DAA/median windows and past pruning points with headers+ghostdag, the
pruning-point UTXO set) — the same shape consensus._load_state restores
after a local prune, so importing is loading a donor's post-prune state,
gated by the proof and the UTXO-set muhash commitment.

Deviations from the reference, by design: the donor serves proof levels
from its retained keep-set (the reference maintains a dedicated per-level
proof store); level ghostdag re-validation trusts header blue fields once
per-level PoW membership is proven (the reference re-runs ghostdag per
level).  Both tighten the trust boundary to headers whose PoW was checked,
which is the same boundary the reference's m-depth argument rests on.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field

from kaspa_tpu.consensus.stores import GhostdagData
from kaspa_tpu.consensus.utxo import UtxoCollection
from kaspa_tpu.crypto.muhash import MuHash


class ProofError(Exception):
    pass


@dataclass
class TrustedData:
    """Donor keep-set snapshot (PruningPointTrustedData + TrustedBlocks)."""

    pruning_point: bytes
    past_pruning_points: list[bytes]
    headers: list = field(default_factory=list)  # kept headers, any order
    ghostdag: dict = field(default_factory=dict)  # hash -> GhostdagData
    statuses: dict = field(default_factory=dict)  # hash -> status str
    reach_mergesets: dict = field(default_factory=dict)  # hash -> [hash]
    bodies: dict = field(default_factory=dict)  # hash -> [Transaction] (pp + anticone)
    daa_excluded: dict = field(default_factory=dict)  # hash -> set[hash]
    depth: dict = field(default_factory=dict)  # hash -> (merge_depth_root, finality_point)
    pruning_samples: dict = field(default_factory=dict)  # hash -> sample hash
    # pp's computed sampled windows (the reference's daa_window_blocks):
    # post-pp window builds chain off these caches instead of walking into
    # pruned history.  window_type -> list of (sort_key, hash) items
    pp_windows: dict = field(default_factory=dict)


class PruningProofManager:
    def __init__(self, consensus):
        self.c = consensus
        self.params = consensus.params

    # ------------------------------------------------------------------
    # build (donor)
    # ------------------------------------------------------------------

    def build_proof(self) -> list[list]:
        """Per-level header lists, blue-work ascending (build.rs:149)."""
        c = self.c
        pp = c.pruning_processor.pruning_point
        m = self.params.pruning_proof_m
        pm = c.parents_manager
        genesis = self.params.genesis.hash
        levels: list[list] = []
        for level in range(self.params.max_block_level + 1):
            # max-heap BFS by blue work through level-L parents, top 2m
            collected: dict[bytes, object] = {}
            heap: list = []
            seen: set[bytes] = set()

            def push(h):
                if h in seen or not c.storage.headers.has(h):
                    return
                seen.add(h)
                hdr = c.storage.headers.get(h)
                heapq.heappush(heap, (-hdr.blue_work, h, hdr))

            push(pp)
            while heap and len(collected) < 2 * m:
                _, h, hdr = heapq.heappop(heap)
                collected[h] = hdr
                for parent in pm.parents_at_level(hdr, level):
                    push(parent)
            level_headers = sorted(collected.values(), key=lambda x: (x.blue_work, x.hash))
            levels.append(level_headers)
            if {h.hash for h in level_headers} <= {pp, genesis}:
                break  # deeper levels are identical; validator extends
        return levels

    # ------------------------------------------------------------------
    # validate (importer)
    # ------------------------------------------------------------------

    def proof_level_works(self, proof: list[list]) -> list[int]:
        """Per-level Σ calc_work(bits) — work *derived* from the difficulty
        targets of (PoW-checked) headers, never from claimed blue_work."""
        from kaspa_tpu.consensus.difficulty import calc_work

        return [sum(calc_work(h.bits) for h in level) for level in proof]

    def validate_proof(self, proof: list[list], current_proof_works: list[int]):
        """Structural + PoW validation and the adopt decision.

        Adoption requires some level where the candidate proof's *derived*
        work (Σ calc_work(bits) of headers whose PoW was individually
        checked at that level) exceeds the node's own proof's derived work —
        the validate.rs per-level comparison.  Claimed blue_work fields are
        used for ordering only; they cannot buy adoption, so fabricating a
        winning proof costs real level-qualified PoW.
        Returns the proven pruning-point header or raises ProofError.
        """
        if not proof or not proof[0]:
            raise ProofError("empty proof")
        m = self.params.pruning_proof_m
        genesis = self.params.genesis.hash
        pp_header = max(proof[0], key=lambda h: (h.blue_work, h.hash))
        pm = self.c.parents_manager
        for level, headers in enumerate(proof):
            if not headers:
                raise ProofError(f"level {level} is empty")
            index = {h.hash: h for h in headers}
            in_level = set(index)
            reaches_genesis = genesis in in_level
            if not reaches_genesis and len(headers) < m:
                raise ProofError(
                    f"level {level} has {len(headers)} headers < m={m} and does not reach genesis"
                )
            prev_work = -1
            for h in headers:
                if h.blue_work < prev_work:
                    raise ProofError(f"level {level} not blue-work sorted")
                prev_work = h.blue_work
                if h.hash == genesis and not h.direct_parents():
                    continue
                if not self.params.skip_proof_of_work:
                    from kaspa_tpu.crypto.powhash import calc_block_pow_hash
                    from kaspa_tpu.consensus.difficulty import compact_to_target

                    pow_value = int.from_bytes(calc_block_pow_hash(h), "little")
                    if pow_value > compact_to_target(h.bits):
                        raise ProofError(f"level {level} header {h.hash.hex()} fails PoW")
                    hdr_level = max(0, self.params.max_block_level - pow_value.bit_length())
                    if hdr_level < level:
                        raise ProofError(
                            f"header {h.hash.hex()} presented at level {level} but PoW only reaches {hdr_level}"
                        )
                # parent closure: every in-proof level-parent must sort before us
                for parent in pm.parents_at_level(h, level):
                    ph = index.get(parent)
                    if ph is not None and (ph.blue_work, ph.hash) >= (h.blue_work, h.hash):
                        raise ProofError(f"level {level} parent ordering violated")
        candidate_works = self.proof_level_works(proof)
        if not any(
            cand > (current_proof_works[i] if i < len(current_proof_works) else 0)
            for i, cand in enumerate(candidate_works)
        ):
            raise ProofError("candidate proof does not exceed the current proof's derived work at any level")
        return pp_header

    # ------------------------------------------------------------------
    # trusted data (donor)
    # ------------------------------------------------------------------

    def get_trusted_data(self) -> TrustedData:
        """Snapshot the keep-set: everything outside strict future(pp)."""
        c = self.c
        pp = c.pruning_processor.pruning_point
        reach = c.reachability
        td = TrustedData(
            pruning_point=pp,
            past_pruning_points=list(c.pruning_processor.past_pruning_points),
        )
        kept: set[bytes] = set()
        for h in list(c.storage.headers.keys()):
            if h != pp and reach.has(h) and reach.is_dag_ancestor_of(pp, h):
                continue  # strict future of pp: synced via normal IBD
            kept.add(h)
        from kaspa_tpu.consensus.reachability import ORIGIN

        for h in kept:
            td.headers.append(c.storage.headers.get(h))
            if c.storage.ghostdag.has(h):
                gd = c.storage.ghostdag.get(h)
                sp = gd.selected_parent
                if sp != ORIGIN and sp not in kept:
                    sp = ORIGIN  # boundary block: parent beyond the snapshot
                td.ghostdag[h] = GhostdagData(
                    gd.blue_score,
                    gd.blue_work,
                    sp,
                    [b for b in gd.mergeset_blues if b in kept],
                    [b for b in gd.mergeset_reds if b in kept],
                    {k: v for k, v in gd.blues_anticone_sizes.items() if k in kept},
                )
            st = c.storage.statuses.get(h)
            if st is not None:
                td.statuses[h] = st
            rm = c.reach_mergesets.get(h)
            if rm is not None:
                td.reach_mergesets[h] = [x for x in rm if x in kept]
            if c.storage.block_transactions.has(h):
                td.bodies[h] = c.storage.block_transactions.get(h)
            if h in c.daa_excluded:
                td.daa_excluded[h] = c.daa_excluded[h]
            pair = c.storage.depth.try_get(h)
            if pair is not None:
                td.depth[h] = pair
            ps = c.storage.pruning_samples.try_get(h)
            if ps is not None:
                td.pruning_samples[h] = ps
        from kaspa_tpu.consensus.processes.window import DIFFICULTY_WINDOW, MEDIAN_TIME_WINDOW

        wm = c.window_manager
        prp = c.pruning_processor
        for wt, cache in ((DIFFICULTY_WINDOW, wm._difficulty_cache), (MEDIAN_TIME_WINDOW, wm._median_cache)):
            # priority: the prune-time snapshot (always coherent), then the
            # warm cache, then a cold rebuild (archival donors)
            win = prp.pp_windows.get(wt) if prp.pp_windows else None
            if win is None:
                win = cache.get(pp)
            if win is None:
                win = wm.build_block_window(c.storage.ghostdag.get(pp), wt)
            td.pp_windows[wt] = list(win)
        return td

    def get_pruning_utxo_set(self):
        return self.c.pruning_processor.pruning_utxo_set

    # ------------------------------------------------------------------
    # apply (importer)
    # ------------------------------------------------------------------

    def import_pruning_data(
        self, proof: list[list], trusted: TrustedData, utxo_set: UtxoCollection,
        current_proof_works: list[int] | None = None,
    ) -> None:
        """Bootstrap this (fresh) consensus from proof + trusted snapshot.

        `current_proof_works`: the derived per-level works of the proof the
        node currently holds (the ACTIVE consensus when importing into
        staging) — the candidate must beat them at some level.  Defaults to
        this consensus's own proof.

        Mirrors consensus._load_state's rebuild discipline: stores seeded
        from the snapshot, reachability re-derived in (blue_work, hash)
        topological order, virtual positioned at the pruning point over the
        commitment-checked UTXO set.  Raises ProofError without mutating
        state on any validation failure.
        """
        c = self.c
        pp = trusted.pruning_point
        if current_proof_works is None:
            current_proof_works = self.proof_level_works(self.build_proof())
        pp_header = self.validate_proof(proof, current_proof_works)
        if pp_header.hash != pp:
            raise ProofError("trusted data pruning point does not match the proven header")
        # UTXO commitment: muhash over the supplied set must equal the header's
        ms = MuHash()
        for op, entry in utxo_set.items():
            ms.add_utxo(op, entry)
        if ms.finalize() != pp_header.utxo_commitment:
            raise ProofError("pruning point UTXO set does not match the header commitment")

        by_hash = {h.hash: h for h in trusted.headers}
        if pp not in by_hash or pp not in trusted.ghostdag:
            raise ProofError("trusted data misses the pruning point itself")

        # --- seed stores ------------------------------------------------
        for hdr in trusted.headers:
            c.storage.headers.insert(hdr)
        # proof headers are retained (status header-only) so this node can
        # serve proofs onward; only trusted headers join relations
        for level in proof:
            for hdr in level:
                if hdr.hash not in by_hash and not c.storage.headers.has(hdr.hash):
                    c.storage.headers.insert(hdr)
                    c.storage.statuses.set(hdr.hash, c.storage.statuses.STATUS_HEADER_ONLY)
        for h, gd in trusted.ghostdag.items():
            c.storage.ghostdag.insert(h, gd)
        for h, st in trusted.statuses.items():
            c.storage.statuses.set(h, st)
        for h, txs in trusted.bodies.items():
            c.storage.block_transactions.insert(h, txs)
        for h, rm in trusted.reach_mergesets.items():
            c._set_reach_mergeset(h, rm)
        c.daa_excluded.update(trusted.daa_excluded)
        for h, (mdr, fp) in trusted.depth.items():
            c.depth_manager.store(h, mdr, fp)
        for h, s in trusted.pruning_samples.items():
            c.pruning_point_manager.store_pruning_sample(h, s)
        for wt, win in trusted.pp_windows.items():
            c.window_manager.cache_block_window(pp, wt, list(win))

        # --- relations + reachability (topological rebuild) -------------
        kept = set(by_hash)
        genesis = self.params.genesis.hash
        topo = sorted(
            (h for h in kept if h in trusted.ghostdag or h == genesis),
            key=lambda h: (trusted.ghostdag[h].blue_work if h in trusted.ghostdag else -1, h),
        )
        from kaspa_tpu.consensus.reachability import ORIGIN

        for blk in topo:
            parents = [p for p in by_hash[blk].direct_parents() if p in kept]
            c.storage.relations.insert(blk, parents)
            if blk == genesis:
                if not c.reachability.has(blk):
                    c.reachability.add_block(blk, ORIGIN, [], [ORIGIN])
                continue
            gd = trusted.ghostdag[blk]
            live_parents = parents or [gd.selected_parent]
            c.reachability.add_block(
                blk, gd.selected_parent, trusted.reach_mergesets.get(blk, []), live_parents
            )

        # --- pruning + virtual position ---------------------------------
        prp = c.pruning_processor
        prp.pruning_point = pp
        prp.past_pruning_points = list(trusted.past_pruning_points)
        prp.retention_period_root = pp
        prp.pruning_utxo_set.replace_all(utxo_set)
        prp.pruning_utxoset_position = pp
        prp._persist_meta()

        c.utxo_set.replace_all(utxo_set)
        c.utxo_position = pp
        c.multisets[pp] = ms
        # virtual parents are constrained to future(pp) (the reference's
        # pruning-point-on-virtual-chain invariant): anticone blocks stay
        # mergeable by incoming post-pp blocks but are never initial tips
        c.tips = {pp}
        c._resolve_virtual()
        c._persist_tips()
        c._persist_utxo_position()
        c.storage.flush()
