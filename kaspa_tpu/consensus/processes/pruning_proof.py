"""Pruning proof: build, validate, and trusted-state bootstrap.

Reference: consensus/src/processes/pruning_proof/{build,validate,apply}.rs
and the trusted-sync surface of consensus/core/src/api/mod.rs
(get_pruning_point_proof / get_pruning_point_anticone_and_trusted_data /
validate_and_insert_trusted_block / import_pruning_point_utxo_set).

A pruning proof is, per proof level L, the top slice (by blue work) of the
level-L header sub-DAG below the pruning point — headers whose PoW value
promotes them to level >= L.  Because expected density halves per level,
~2m headers per level commit to the chain's cumulative work all the way
down without shipping the full history.  A syncing node validates:

1. per-header PoW, and that each header's PoW value actually reaches the
   level it is presented at;
2. per-level parent closure + topological consistency;
3. per-level depth (>= m headers unless the level bottoms out at genesis);
4. that the proof's pruning point carries more blue work than the node's
   current sink (the adopt-or-reject decision).

The apply side here is a *trusted state snapshot*: exactly the data a
pruned donor node itself retains (pruning point + anticone with full data,
DAA/median windows and past pruning points with headers+ghostdag, the
pruning-point UTXO set) — the same shape consensus._load_state restores
after a local prune, so importing is loading a donor's post-prune state,
gated by the proof and the UTXO-set muhash commitment.

Validation re-runs GHOSTDAG per level: every level's sub-DAG is recolored
from scratch over scratch stores (validate.rs ProofContext::from_proof),
selected tips are derived from RECOMPUTED blue works, and the adopt
decision compares recomputed work beyond the challenger/defender common
ancestor (validate.rs compare_proofs_inner) — claimed header blue fields
only order the level lists and are cross-checked for monotonicity, so
forged blue fields cannot buy adoption.

Deviation from the reference, by design: the donor serves proof levels
from its retained keep-set (the reference maintains a dedicated per-level
proof store), and pruning-period relay work is not folded into the
compare (the in-flight relay block's blue work is verified after sync
here, so both sides contribute zero at compare time — ties keep favoring
the defender exactly as in compare_proofs_inner).
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field

from kaspa_tpu.consensus.stores import GhostdagData
from kaspa_tpu.consensus.utxo import UtxoCollection
from kaspa_tpu.crypto.muhash import MuHash


class _MapGd:
    """Scratch per-level ghostdag store (validate.rs temp DbGhostdagStore)."""

    def __init__(self):
        self.d: dict[bytes, GhostdagData] = {}

    def insert(self, h, gd):
        self.d[h] = gd

    def get(self, h):
        return self.d[h]

    def has(self, h):
        return h in self.d

    def get_blue_work(self, h):
        return self.d[h].blue_work

    def get_blue_score(self, h):
        return self.d[h].blue_score

    def get_selected_parent(self, h):
        return self.d[h].selected_parent

    def get_blues_anticone_sizes(self, h):
        return self.d[h].blues_anticone_sizes

    def block_at_depth(self, high: bytes, depth: int) -> bytes:
        """pruning_proof/mod.rs:438 GhostdagReaderExt::block_at_depth."""
        from kaspa_tpu.consensus.reachability import ORIGIN

        high_bs = self.get_blue_score(high)
        current = high
        while self.get_blue_score(current) + depth >= high_bs:
            sp = self.get_selected_parent(current)
            if sp == ORIGIN:
                break
            current = sp
        return current


class _MapRelations:
    def __init__(self):
        self.d: dict[bytes, list[bytes]] = {}

    def insert(self, h, parents):
        self.d[h] = list(parents)

    def get_parents(self, h):
        return self.d[h]

    def has(self, h):
        return h in self.d


class _MapHeaders:
    def __init__(self):
        self.d: dict[bytes, object] = {}

    def insert(self, hdr):
        self.d[hdr.hash] = hdr

    def get(self, h):
        return self.d[h]

    def get_bits(self, h):
        return self.d[h].bits


class ProofError(Exception):
    pass


@dataclass
class TrustedData:
    """Donor keep-set snapshot (PruningPointTrustedData + TrustedBlocks)."""

    pruning_point: bytes
    past_pruning_points: list[bytes]
    headers: list = field(default_factory=list)  # kept headers, any order
    ghostdag: dict = field(default_factory=dict)  # hash -> GhostdagData
    statuses: dict = field(default_factory=dict)  # hash -> status str
    reach_mergesets: dict = field(default_factory=dict)  # hash -> [hash]
    bodies: dict = field(default_factory=dict)  # hash -> [Transaction] (pp + anticone)
    daa_excluded: dict = field(default_factory=dict)  # hash -> set[hash]
    depth: dict = field(default_factory=dict)  # hash -> (merge_depth_root, finality_point)
    pruning_samples: dict = field(default_factory=dict)  # hash -> sample hash
    # pp's computed sampled windows (the reference's daa_window_blocks):
    # post-pp window builds chain off these caches instead of walking into
    # pruned history.  window_type -> list of (sort_key, hash) items
    pp_windows: dict = field(default_factory=dict)


@dataclass
class _ProofLevelContext:
    """validate.rs ProofLevelContext: one level's recomputed view."""

    gd: _MapGd
    selected_tip: bytes

    def blue_score(self) -> int:
        return self.gd.get_blue_score(self.selected_tip)

    def blue_work_diff(self, ancestor: bytes) -> int:
        return max(0, self.gd.get_blue_work(self.selected_tip) - self.gd.get_blue_work(ancestor))

    @staticmethod
    def find_common_ancestor(challenger: "_ProofLevelContext", defender: "_ProofLevelContext"):
        from kaspa_tpu.consensus.reachability import ORIGIN

        current = challenger.selected_tip
        while True:
            if defender.gd.has(current) and current != ORIGIN:
                return current
            current = challenger.gd.get_selected_parent(current)
            if current == ORIGIN:
                return None


@dataclass
class _ProofContext:
    """validate.rs ProofContext: recomputed per-level ghostdag + tips."""

    pp_header: object
    pp_level: int
    gd_by_level: dict = field(default_factory=dict)
    tip_by_level: dict = field(default_factory=dict)

    def level(self, level: int) -> _ProofLevelContext:
        return _ProofLevelContext(self.gd_by_level[level], self.tip_by_level[level])


class PruningProofManager:
    def __init__(self, consensus):
        self.c = consensus
        self.params = consensus.params
        # donor-side proof cache (the reference maintains persistent
        # per-level proof stores; serving must not re-run the level BFS +
        # recolor passes per request): keyed by the pruning point
        self._proof_cache: tuple[bytes, list[list]] | None = None

    # ------------------------------------------------------------------
    # build (donor)
    # ------------------------------------------------------------------

    def build_proof(self) -> list[list]:
        """Per-level header lists, blue-work ascending (build.rs:149).
        Cached per pruning point — the donor serves repeated proof
        requests (and the pruning executor's keep-set computation) without
        re-deriving the level sub-DAGs."""
        c = self.c
        pp = c.pruning_processor.pruning_point
        if self._proof_cache is not None and self._proof_cache[0] == pp:
            # per-level lists are copied out: a caller mutating its proof
            # must never corrupt the shared cache the pruning keep-set
            # computation depends on
            return [list(level) for level in self._proof_cache[1]]
        m = self.params.pruning_proof_m
        pm = c.parents_manager
        genesis = self.params.genesis.hash
        pp_header = c.storage.headers.get(pp)
        pp_level = c.storage.headers.get_block_level(pp)  # memoized + persisted
        levels: list[list] = []
        for level in range(self.params.max_block_level + 1):
            # max-heap BFS by blue work through level-L parents, top 2m
            collected: dict[bytes, object] = {}
            heap: list = []
            seen: set[bytes] = set()

            def push(h):
                if h in seen or not c.storage.headers.has(h):
                    return
                seen.add(h)
                hdr = c.storage.headers.get(h)
                heapq.heappush(heap, (-hdr.blue_work, h, hdr))

            # the pp belongs to levels up to its own PoW level; above that
            # the level sub-DAG hangs off its level parents (the validator
            # requires the level tip to BE pp at levels <= pp_level and to
            # be a level parent of pp above, validate.rs:266-276)
            if level <= pp_level:
                push(pp)
            else:
                for parent in pm.parents_at_level(pp_header, level):
                    push(parent)
            # collect until the RECOMPUTED level blue depth reaches 2m (or
            # the level bottoms out): build.rs:410 gates root candidacy on
            # current_level_score >= 2m, not on a raw header count — a
            # count-based slice can fall short when the level sub-DAG is
            # chain-like (score = count - 1) or carries reds
            target = 2 * m
            while heap:
                while heap and len(collected) < target:
                    _, h, hdr = heapq.heappop(heap)
                    collected[h] = hdr
                    for parent in pm.parents_at_level(hdr, level):
                        push(parent)
                level_sorted = sorted(collected.values(), key=lambda x: (x.blue_work, x.hash))
                if genesis in collected:
                    break
                _gd, tip = self._recolor_level(level_sorted, level)
                if tip is not None and _gd.get_blue_score(tip) >= 2 * m:
                    break
                target += m  # extend the slice and re-measure
            level_headers = sorted(collected.values(), key=lambda x: (x.blue_work, x.hash))
            levels.append(level_headers)
            if {h.hash for h in level_headers} <= {pp, genesis}:
                break  # deeper levels are identical; validator extends
        self._proof_cache = (pp, [list(level) for level in levels])
        return levels

    # ------------------------------------------------------------------
    # validate (importer)
    # ------------------------------------------------------------------

    def _recolor_level(self, headers_sorted: list, level: int):
        """Non-strict per-level GHOSTDAG recompute over a blue-work-ascending
        header list; returns (gd_store, recomputed_selected_tip).  Used by
        the builder to measure realized level blue depth (build.rs
        populate_level_proof_ghostdag_data) — the validator runs its own
        strict variant with full rejection semantics."""
        from kaspa_tpu.consensus.difficulty import level_work
        from kaspa_tpu.consensus.processes.ghostdag import GhostdagManager
        from kaspa_tpu.consensus.reachability import ORIGIN, ReachabilityService

        params = self.params
        pm = self.c.parents_manager
        gd_store = _MapGd()
        relations = _MapRelations()
        hstore = _MapHeaders()
        reach = ReachabilityService()
        manager = GhostdagManager(
            params.genesis.hash, params.ghostdag_k, gd_store, relations, hstore, reach,
            level_work=level_work(level, params.max_block_level),
        )
        gd_store.insert(ORIGIN, manager.genesis_ghostdag_data())
        relations.insert(ORIGIN, [])
        tip = None
        for h in headers_sorted:
            hstore.insert(h)
            parents = [p for p in pm.parents_at_level(h, level) if gd_store.has(p) and p != ORIGIN]
            parents = parents or [ORIGIN]
            relations.insert(h.hash, parents)
            gd = manager.ghostdag(parents)
            gd_store.insert(h.hash, gd)
            reach.add_block(
                h.hash,
                gd.selected_parent,
                [x for x in gd.unordered_mergeset_without_selected_parent() if reach.has(x)],
                parents,
            )
            tip = h.hash if tip is None else manager.find_selected_parent([tip, h.hash])
        return gd_store, tip

    def build_proof_context(self, proof: list[list]) -> "_ProofContext":
        """Re-run GHOSTDAG over every proof level (validate.rs from_proof).

        For each level, descending: scratch relations/ghostdag/reachability
        stores are populated header by header (blue-work-ascending), the
        coloring is recomputed from the level sub-DAG alone, and the level
        selected tip is derived from RECOMPUTED blue works.  Structural
        rejections mirror the reference error-for-error: wrong block level,
        PoW failure, unknown parents beyond the first root, claimed-blue-work
        inconsistency with parents, duplicate header at level, missing
        block-at-depth-m link from the next level, tip not anchored to the
        pruning point, tip not last in the level list, tip blue score below
        2m on a level that does not reach genesis.
        """
        from kaspa_tpu.consensus.difficulty import compact_to_target, level_work
        from kaspa_tpu.consensus.processes.ghostdag import GhostdagManager
        from kaspa_tpu.consensus.reachability import ORIGIN, ReachabilityService

        if not proof or not proof[0]:
            raise ProofError("empty proof")
        params = self.params
        m = params.pruning_proof_m
        genesis = params.genesis.hash
        pm = self.c.parents_manager
        max_level = params.max_block_level
        level_memo: dict[bytes, int] = {}

        # our build_proof truncates once a level bottoms out at {pp, genesis};
        # extend virtually: deeper levels reuse the last list filtered to
        # headers whose PoW actually reaches that level ("validator extends")
        def level_list(level: int) -> list:
            if level < len(proof):
                return proof[level]
            last = proof[-1]
            return [h for h in last if self._header_level(h, level_memo) >= level]

        pp_header = proof[0][-1]  # sortedness is enforced below
        pp_level = self._header_level(pp_header, level_memo)
        pp_level_parents = {
            level: set(pm.parents_at_level(pp_header, level)) for level in range(max_level + 1)
        }

        ctx = _ProofContext(pp_header=pp_header, pp_level=pp_level)
        selected_tip_by_level: dict[int, bytes] = {}
        for level in range(max_level, -1, -1):
            headers = level_list(level)
            if not headers:
                raise ProofError(f"level {level} is empty")
            gd_store = _MapGd()
            relations = _MapRelations()
            hstore = _MapHeaders()
            reach = ReachabilityService()
            manager = GhostdagManager(
                genesis, params.ghostdag_k, gd_store, relations, hstore, reach,
                level_work=level_work(level, max_level),
            )
            gd_store.insert(ORIGIN, manager.genesis_ghostdag_data())
            relations.insert(ORIGIN, [])

            selected_tip = headers[0].hash
            prev_work = (-1, b"")
            for i, h in enumerate(headers):
                if (h.blue_work, h.hash) < prev_work:
                    raise ProofError(f"level {level} not blue-work sorted")
                prev_work = (h.blue_work, h.hash)
                if not params.skip_proof_of_work and not (h.hash == genesis and not h.direct_parents()):
                    if h.hash not in level_memo:
                        from kaspa_tpu.crypto.powhash import calc_block_pow_hash

                        pow_value = int.from_bytes(calc_block_pow_hash(h), "little")
                        if pow_value > compact_to_target(h.bits):
                            raise ProofError(f"level {level} header {h.hash.hex()} fails PoW")
                        level_memo[h.hash] = max(0, max_level - pow_value.bit_length())
                    if level_memo[h.hash] < level:
                        raise ProofError(
                            f"header {h.hash.hex()} presented at level {level} but PoW does not reach it"
                        )
                if relations.has(h.hash):
                    raise ProofError(f"duplicate header {h.hash.hex()} at level {level}")
                hstore.insert(h)
                # parents filtered to those already processed at this level
                parents = [p for p in pm.parents_at_level(h, level) if gd_store.has(p) and p != ORIGIN]
                if not parents and i != 0:
                    raise ProofError(f"level {level} header {h.hash.hex()} has no known parents")
                for p in parents:
                    if hstore.get(p).blue_work >= h.blue_work:
                        raise ProofError(f"level {level} claimed blue work inconsistent at {h.hash.hex()}")
                parents = parents or [ORIGIN]
                relations.insert(h.hash, parents)
                gd = manager.ghostdag(parents)
                gd_store.insert(h.hash, gd)
                reach_mergeset = [
                    x for x in gd.unordered_mergeset_without_selected_parent() if reach.has(x)
                ]
                reach.add_block(h.hash, gd.selected_parent, reach_mergeset, parents)
                selected_tip = manager.find_selected_parent([selected_tip, h.hash])

            # cross-level link: block at depth m from the next level's tip
            # must appear in this level (validate.rs:256-263).  When the next
            # level bottoms out at its own root (tiny DAGs / keep-set-served
            # levels), the walk degenerates to that root, which legitimately
            # predates this level's 2m window — only a non-root anchor
            # missing from this level indicates detached levels.
            if level < max_level:
                next_headers = level_list(level + 1)
                anchor = ctx.gd_by_level[level + 1].block_at_depth(selected_tip_by_level[level + 1], m)
                if (
                    anchor != ORIGIN
                    and next_headers
                    and anchor != next_headers[0].hash
                    and not relations.has(anchor)
                ):
                    raise ProofError(f"level {level} misses block at depth m from level {level + 1}")
            # tip anchoring to the pruning point (validate.rs:266-276)
            if level <= pp_level:
                if selected_tip != pp_header.hash:
                    raise ProofError(f"level {level} selected tip is not the pruning point")
            elif selected_tip not in pp_level_parents[level]:
                raise ProofError(f"level {level} selected tip is not a level parent of the pruning point")
            if headers[-1].hash != selected_tip:
                raise ProofError(f"level {level} claimed tip is not the recomputed selected tip")
            tip_bs = gd_store.get_blue_score(selected_tip)
            if headers[0].hash != genesis and tip_bs < 2 * m:
                raise ProofError(f"level {level} tip blue score {tip_bs} < 2m")

            selected_tip_by_level[level] = selected_tip
            ctx.gd_by_level[level] = gd_store
            ctx.tip_by_level[level] = selected_tip
        return ctx

    def _header_level(self, h, memo: dict | None = None) -> int:
        """pow/src/lib.rs calc_block_level — real even under skip-PoW (only
        the difficulty-threshold check is waived, not the level geometry).
        ``memo`` caches by hash: the heavy-hash is milliseconds of pure
        python and proof validation touches each header at many levels."""
        if memo is not None and h.hash in memo:
            return memo[h.hash]
        if not h.direct_parents():
            lvl = self.params.max_block_level
        else:
            from kaspa_tpu.crypto.powhash import calc_block_pow_hash

            pow_value = int.from_bytes(calc_block_pow_hash(h), "little")
            lvl = max(0, self.params.max_block_level - pow_value.bit_length())
        if memo is not None:
            memo[h.hash] = lvl
        return lvl

    def validate_proof(self, proof: list[list], defender_proof: list[list] | None = None):
        """Full per-level GHOSTDAG validation + the adopt decision.

        Builds challenger and defender contexts with recomputed coloring and
        compares them level-by-level beyond their common ancestor
        (validate.rs compare_proofs_inner): the challenger wins only if, at
        some ≥2m level with a common ancestor, its recomputed blue-work gain
        beyond that ancestor strictly exceeds the defender's; with no shared
        blocks anywhere, only if it fills a ≥2m level the defender lacks (or
        the defender still sits at genesis).  Ties favor the defender.
        Returns the proven pruning-point header or raises ProofError.
        """
        challenger = self.build_proof_context(proof)
        if defender_proof is None:
            defender_proof = self.build_proof()
        m = self.params.pruning_proof_m
        genesis = self.params.genesis.hash
        defender_trivial = (
            len(defender_proof) == 1 and {h.hash for h in defender_proof[0]} <= {genesis}
        )
        if defender_trivial:
            return challenger.pp_header  # fresh node: any valid proof adopts
        defender = self.build_proof_context(defender_proof)

        for level in range(self.params.max_block_level + 1):
            ch = challenger.level(level)
            de = defender.level(level)
            if ch.blue_score() < 2 * m:
                continue
            ancestor = _ProofLevelContext.find_common_ancestor(ch, de)
            if ancestor is not None:
                if de.blue_work_diff(ancestor) >= ch.blue_work_diff(ancestor):
                    raise ProofError("candidate proof does not exceed the current proof's recomputed work")
                return challenger.pp_header

        if defender.pp_header.hash == genesis:
            return challenger.pp_header
        # no shared blocks at any level: the challenger must fill a >=2m
        # level the defender does not (validate.rs:409-419)
        for level in range(self.params.max_block_level, -1, -1):
            if challenger.level(level).blue_score() < 2 * m:
                continue
            if defender.level(level).blue_score() < 2 * m:
                return challenger.pp_header
        raise ProofError("candidate proof shares no blocks with ours and fills no level we lack")

    # ------------------------------------------------------------------
    # trusted data (donor)
    # ------------------------------------------------------------------

    def get_trusted_data(self) -> TrustedData:
        """Snapshot the keep-set: everything outside strict future(pp)."""
        c = self.c
        pp = c.pruning_processor.pruning_point
        reach = c.reachability
        td = TrustedData(
            pruning_point=pp,
            past_pruning_points=list(c.pruning_processor.past_pruning_points),
        )
        kept: set[bytes] = set()
        for h in list(c.storage.headers.keys()):
            if h != pp and reach.has(h) and reach.is_dag_ancestor_of(pp, h):
                continue  # strict future of pp: synced via normal IBD
            kept.add(h)
        from kaspa_tpu.consensus.reachability import ORIGIN

        for h in kept:
            td.headers.append(c.storage.headers.get(h))
            if c.storage.ghostdag.has(h):
                gd = c.storage.ghostdag.get(h)
                sp = gd.selected_parent
                if sp != ORIGIN and sp not in kept:
                    sp = ORIGIN  # boundary block: parent beyond the snapshot
                td.ghostdag[h] = GhostdagData(
                    gd.blue_score,
                    gd.blue_work,
                    sp,
                    [b for b in gd.mergeset_blues if b in kept],
                    [b for b in gd.mergeset_reds if b in kept],
                    {k: v for k, v in gd.blues_anticone_sizes.items() if k in kept},
                )
            st = c.storage.statuses.get(h)
            if st is not None:
                td.statuses[h] = st
            rm = c.reach_mergesets.get(h)
            if rm is not None:
                td.reach_mergesets[h] = [x for x in rm if x in kept]
            if c.storage.block_transactions.has(h):
                td.bodies[h] = c.storage.block_transactions.get(h)
            if h in c.daa_excluded:
                td.daa_excluded[h] = c.daa_excluded[h]
            pair = c.storage.depth.try_get(h)
            if pair is not None:
                td.depth[h] = pair
            ps = c.storage.pruning_samples.try_get(h)
            if ps is not None:
                td.pruning_samples[h] = ps
        from kaspa_tpu.consensus.processes.window import DIFFICULTY_WINDOW, MEDIAN_TIME_WINDOW

        wm = c.window_manager
        prp = c.pruning_processor
        for wt, cache in ((DIFFICULTY_WINDOW, wm._difficulty_cache), (MEDIAN_TIME_WINDOW, wm._median_cache)):
            # priority: the prune-time snapshot (always coherent), then the
            # warm cache, then a cold rebuild (archival donors)
            win = prp.pp_windows.get(wt) if prp.pp_windows else None
            if win is None:
                win = cache.get(pp)
            if win is None:
                win = wm.build_block_window(c.storage.ghostdag.get(pp), wt)
            td.pp_windows[wt] = list(win)
        return td

    def get_pruning_utxo_set(self):
        return self.c.pruning_processor.pruning_utxo_set

    # ------------------------------------------------------------------
    # apply (importer)
    # ------------------------------------------------------------------

    def import_pruning_data(
        self, proof: list[list], trusted: TrustedData, utxo_set: UtxoCollection,
        defender_proof: list[list] | None = None,
    ) -> None:
        """Bootstrap this (fresh) consensus from proof + trusted snapshot.

        `defender_proof`: the proof the node currently holds (the ACTIVE
        consensus when importing into staging) — the candidate must beat
        its recomputed work (see validate_proof).  Defaults to this
        consensus's own proof.

        Mirrors consensus._load_state's rebuild discipline: stores seeded
        from the snapshot, reachability re-derived in (blue_work, hash)
        topological order, virtual positioned at the pruning point over the
        commitment-checked UTXO set.  Raises ProofError without mutating
        state on any validation failure.
        """
        c = self.c
        pp = trusted.pruning_point
        pp_header = self.validate_proof(proof, defender_proof)
        if pp_header.hash != pp:
            raise ProofError("trusted data pruning point does not match the proven header")
        # UTXO commitment: muhash over the supplied set must equal the header's
        ms = MuHash()
        for op, entry in utxo_set.items():
            ms.add_utxo(op, entry)
        if ms.finalize() != pp_header.utxo_commitment:
            raise ProofError("pruning point UTXO set does not match the header commitment")

        by_hash = {h.hash: h for h in trusted.headers}
        if pp not in by_hash or pp not in trusted.ghostdag:
            raise ProofError("trusted data misses the pruning point itself")

        # --- seed stores ------------------------------------------------
        for hdr in trusted.headers:
            c.storage.headers.insert(hdr)
        # proof headers are retained (status header-only) so this node can
        # serve proofs onward; only trusted headers join relations
        for level in proof:
            for hdr in level:
                if hdr.hash not in by_hash and not c.storage.headers.has(hdr.hash):
                    c.storage.headers.insert(hdr)
                    c.storage.statuses.set(hdr.hash, c.storage.statuses.STATUS_HEADER_ONLY)
        for h, gd in trusted.ghostdag.items():
            c.storage.ghostdag.insert(h, gd)
        for h, st in trusted.statuses.items():
            c.storage.statuses.set(h, st)
        for h, txs in trusted.bodies.items():
            c.storage.block_transactions.insert(h, txs)
        for h, rm in trusted.reach_mergesets.items():
            c._set_reach_mergeset(h, rm)
        c.daa_excluded.update(trusted.daa_excluded)
        for h, (mdr, fp) in trusted.depth.items():
            c.depth_manager.store(h, mdr, fp)
        for h, s in trusted.pruning_samples.items():
            c.pruning_point_manager.store_pruning_sample(h, s)
        for wt, win in trusted.pp_windows.items():
            c.window_manager.cache_block_window(pp, wt, list(win))

        # --- relations + reachability (topological rebuild) -------------
        kept = set(by_hash)
        genesis = self.params.genesis.hash
        topo = sorted(
            (h for h in kept if h in trusted.ghostdag or h == genesis),
            key=lambda h: (trusted.ghostdag[h].blue_work if h in trusted.ghostdag else -1, h),
        )
        from kaspa_tpu.consensus.reachability import ORIGIN

        for blk in topo:
            parents = [p for p in by_hash[blk].direct_parents() if p in kept]
            c.storage.relations.insert(blk, parents)
            if blk == genesis:
                if not c.reachability.has(blk):
                    c.reachability.add_block(blk, ORIGIN, [], [ORIGIN])
                continue
            gd = trusted.ghostdag[blk]
            live_parents = parents or [gd.selected_parent]
            c.reachability.add_block(
                blk, gd.selected_parent, trusted.reach_mergesets.get(blk, []), live_parents
            )

        # --- pruning + virtual position ---------------------------------
        prp = c.pruning_processor
        prp.pruning_point = pp
        prp.past_pruning_points = list(trusted.past_pruning_points)
        prp.retention_period_root = pp
        prp.pruning_utxo_set.replace_all(utxo_set)
        prp.pruning_utxoset_position = pp
        prp._persist_meta()

        c.utxo_set.replace_all(utxo_set)
        c.utxo_position = pp
        # the selected-chain index must track the materialized position —
        # the fresh-consensus genesis entry is not on this chain (it gets
        # extended below the PP by the imported lane-state anchor segment)
        c.selected_chain = [(trusted.ghostdag[pp].blue_score, pp)]
        c.multisets[pp] = ms
        # virtual parents are constrained to future(pp) (the reference's
        # pruning-point-on-virtual-chain invariant): anticone blocks stay
        # mergeable by incoming post-pp blocks but are never initial tips
        c.tips = {pp}
        c._resolve_virtual()
        c._persist_tips()
        c._persist_utxo_position()
        c.storage.flush()
