"""Sync manager: block locators and antipast queries for IBD negotiation.

Reference: consensus/src/processes/sync/mod.rs (SyncManager):
``create_block_locator_from_pruning_point`` builds an exponentially-spaced
selected-chain locator (step doubling per hop, low appended last), and
``antipast_hashes_between`` yields the block hashes a donor must serve so a
peer holding ``low`` converges to ``high`` — the chain walk's mergesets,
excluding anything already in ``low``'s past.
"""

from __future__ import annotations


class SyncError(Exception):
    pass


class SyncManager:
    def __init__(self, consensus):
        self.c = consensus

    def create_block_locator_from_pruning_point(
        self, high: bytes, low: bytes, limit: int | None = None
    ) -> list[bytes]:
        """sync/mod.rs:201 — selected-chain hashes from ``high`` down to
        ``low`` with exponentially growing blue-score gaps (step doubling),
        ``low`` always last."""
        c = self.c
        if not c.reachability.is_chain_ancestor_of(low, high):
            raise SyncError("locator low hash is not in the high hash's chain")
        gd = c.storage.ghostdag
        low_bs = gd.get_blue_score(low)
        locator: list[bytes] = []
        current = high
        step = 1
        while gd.get_blue_score(current) > low_bs:
            locator.append(current)
            if limit is not None and len(locator) == limit:
                break
            target = max(gd.get_blue_score(current) - step, low_bs)
            while gd.get_blue_score(current) > target:
                current = gd.get_selected_parent(current)
            step *= 2
        locator.append(low)
        return locator

    def find_highest_common_chain_block(self, low: bytes, high: bytes) -> bytes:
        """sync/mod.rs find_highest_common_chain_block: walk down ``low``'s
        selected chain until a block on ``high``'s selected chain."""
        c = self.c
        current = low
        while not (
            c.reachability.has(current) and c.reachability.is_chain_ancestor_of(current, high)
        ):
            current = c.storage.ghostdag.get_selected_parent(current)
        return current

    def antipast_hashes_between(
        self, low: bytes, high: bytes, max_blocks: int | None = None
    ) -> tuple[list[bytes], bytes]:
        """sync/mod.rs:76 — hashes between low's antipast and high's
        antipast (excludes low, includes high), capped at ``max_blocks``.
        Returns (hashes ascending by (blue_work, hash), highest chain block
        reached) so callers can continue from ``highest_reached``."""
        c = self.c
        original_low = low
        low = self.find_highest_common_chain_block(low, high)
        gd = c.storage.ghostdag
        reach = c.reachability
        collected: set[bytes] = set()
        highest_reached = low
        for current in reach.forward_chain_iterator(low, high):
            if current == low:
                continue
            data = gd.get(current)
            mergeset = [current, *data.unordered_mergeset()]
            if (
                max_blocks is not None
                and len(collected) + len(mergeset) > max_blocks
                and highest_reached != low
            ):
                # stop at the cap — but only once at least one chain step
                # landed: a single mergeset larger than max_blocks must
                # still make progress or chunked IBD would stall/truncate
                break
            for m in mergeset:
                if m in collected or m == low:
                    continue
                if reach.has(m) and reach.is_dag_ancestor_of(m, original_low) and m != original_low:
                    continue  # the peer already has everything in low's past
                collected.add(m)
            highest_reached = current
        collected.discard(original_low)
        hashes = sorted(collected, key=lambda h: (gd.get_blue_work(h), h))
        return hashes, highest_reached
