"""Multi-level parents builder (consensus/src/processes/parents_builder.rs).

Every block carries, per proof level 0..=max_block_level, the antichain
frontier of that level's sub-DAG as seen from its direct parents.  These
level-parents are what make headers-proof pruning possible: level-L headers
form a sparse sub-DAG whose density halves per level, and the pruning proof
ships only the top of each.

Level of a block = max(0, max_block_level - pow_value_bits) (pow/src/lib.rs
calc_level_from_pow): a hash that undershoots the target by k extra bits of
zero promotes the block k levels.

Algorithm (calc_block_parents): for each level, candidates are direct
parents at that level plus the level-parents of direct parents below it;
the kept set is the maximal antichain, computed incrementally with
reachability queries exactly as the reference does (retain non-ancestors,
insert if in the future of a dropped candidate or in the anticone of all).
Candidates without reachability data (pruned history) participate with
empty reference sets — exact for archival/non-pruned operation; the
pruning-proof apply path supplies reachability for the proof sub-DAGs.
"""

from __future__ import annotations


class ParentsManager:
    def __init__(self, max_block_level: int, genesis_hash: bytes, headers_store, reachability, relations):
        self.max_block_level = max_block_level
        self.genesis_hash = genesis_hash
        self.headers = headers_store
        self.reachability = reachability
        self.relations = relations

    def parents_at_level(self, header, level: int) -> list[bytes]:
        if not header.parents_by_level:
            return []  # genesis
        if level < len(header.parents_by_level):
            return header.parents_by_level[level]
        return [self.genesis_hash]

    def calc_block_parents(self, pruning_point: bytes, direct_parents: list[bytes]) -> list[list[bytes]]:
        headers = [self.headers.get(p) for p in direct_parents]
        levels = [self.headers.get_block_level(p) for p in direct_parents]
        # rotate a parent in the future of the pruning point to the front so
        # pruned candidates are always in the past of the running candidates
        first = next(
            (
                i
                for i, p in enumerate(direct_parents)
                if self.reachability.has(p) and self.reachability.is_dag_ancestor_of(pruning_point, p)
            ),
            0,
        )
        headers[0], headers[first] = headers[first], headers[0]
        levels[0], levels[first] = levels[first], levels[0]

        parents: list[list[bytes]] = []
        for level in range(self.max_block_level + 1):
            # direct parents occupying this level are mutual-anticone by
            # validation; they are unconditional candidates
            candidates: dict[bytes, list[bytes]] = {
                h.hash: [h.hash] for h, lv in zip(headers, levels) if level <= lv
            }
            first_marker = 0
            if not candidates:
                # no direct parent reaches this level: the first parent's
                # level-parents take precedence (inserted unconditionally)
                grandparents: dict[bytes, None] = dict.fromkeys(self.parents_at_level(headers[0], level))
                first_marker = len(grandparents)
                for h in headers[1:]:
                    for g in self.parents_at_level(h, level):
                        grandparents.setdefault(g)
            else:
                grandparents = {}
                for h, lv in zip(headers, levels):
                    if level > lv:
                        for g in self.parents_at_level(h, level):
                            grandparents.setdefault(g)

            if not candidates and first_marker == len(grandparents):
                # all level-parents come from the single validated first
                # parent: already an antichain, no queries needed
                level_parents = list(grandparents)
            else:
                for i, parent in enumerate(grandparents):
                    has_reach = self.reachability.has(parent)
                    refs = [parent] if has_reach else []
                    if i < first_marker:
                        candidates[parent] = refs
                        continue
                    if not has_reach:
                        continue
                    before = len(candidates)
                    candidates = {
                        c: r
                        for c, r in candidates.items()
                        if not self.reachability.is_any_dag_ancestor_of(iter(r), parent)
                    }
                    displaced = len(candidates) < before
                    if displaced or not any(
                        self.reachability.is_dag_ancestor_of_any(parent, iter(r))
                        for r in candidates.values()
                    ):
                        candidates[parent] = refs
                level_parents = list(candidates)

            if level > 0 and level_parents == [self.genesis_hash]:
                break
            parents.append(level_parents)
        return parents
