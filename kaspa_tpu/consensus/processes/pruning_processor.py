"""History pruning executor: advance the pruning point, maintain its UTXO
set, and delete data below the retention root.

Reference: consensus/src/pipeline/pruning_processor/processor.rs (worker /
advance_pruning_point_if_possible / advance_pruning_utxoset / prune).  The
reference runs this on a dedicated thread gated by a session lock; here it
runs synchronously after virtual resolution (the single-writer engine makes
that safe) with the same phases:

1. `next_pruning_points` (pruning.rs) yields the samples to advance through;
   the past-pruning-points index and the retention root update first.
2. The pruning-point UTXO set advances chain-block-by-chain-block using the
   stored UTXO diffs, then (in tests) is asserted against the pruning point
   header's utxo_commitment.
3. `prune` computes the keep sets — the pruning point's anticone with full
   data, its DAA/median windows and the past-pruning-points chain with
   headers+ghostdag only — and deletes everything else outside
   future(pruning_point): bodies, diffs, multisets, acceptance data,
   reachability entries, relations, statuses.  GHOSTDAG data of surviving
   blocks is filtered so mergesets never dangle (processor.rs:336-355).

Archival nodes (`is_archival`) advance the pruning point but keep history.
"""

from __future__ import annotations

from kaspa_tpu.consensus.reachability import ORIGIN
from kaspa_tpu.consensus.stores import GhostdagData
from kaspa_tpu.consensus.utxo import apply_diff
from kaspa_tpu.crypto.muhash import MuHash


class PruningProcessor:
    def __init__(self, consensus, is_archival: bool = False):
        self.c = consensus
        self.is_archival = is_archival
        g = consensus.params.genesis.hash
        self.pruning_point: bytes = g
        self.past_pruning_points: list[bytes] = [g]
        self.retention_period_root: bytes = g
        # the pruning point UTXO set (pruning_meta utxo_set in the reference):
        # a bounded-cache store column (PREFIX_PRUNING_UTXO), disk-resident
        self.pruning_utxo_set = consensus.storage.pruning_utxo_set
        self.pruning_utxoset_position: bytes = g
        # pp's sampled windows, snapshotted while its past is still intact
        # (pruning deletes the blocks a cold rebuild would walk; trusted-data
        # export and post-restart window seeding read this snapshot)
        self.pp_windows: dict[str, list] = {}

    # ------------------------------------------------------------------
    # phase 1+2: pruning point movement and UTXO set advancement
    # ------------------------------------------------------------------

    def advance_if_possible(self, sink_gd: GhostdagData) -> bool:
        """processor.rs advance_pruning_point_if_possible; returns True if
        the pruning point moved."""
        new_points = self.c.pruning_point_manager.next_pruning_points(sink_gd, self.pruning_point)
        if not new_points:
            return False
        self.past_pruning_points.extend(new_points)
        old_pp = self.pruning_point
        self.pruning_point = new_points[-1]
        if not self.is_archival:
            self.retention_period_root = self.pruning_point
        self._snapshot_pp_windows()
        self._persist_meta()
        self._advance_pruning_utxoset(self.pruning_point)
        if not self.is_archival:
            self.prune(self.pruning_point, self.retention_period_root)
        return True

    def _advance_pruning_utxoset(self, new_pp: bytes) -> None:
        # the UtxoSetStore stages its own write-through ops per mutation
        for chain_block in self.c.reachability.forward_chain_iterator(self.pruning_utxoset_position, new_pp):
            apply_diff(self.pruning_utxo_set, self.c.utxo_diffs[chain_block])
            self.pruning_utxoset_position = chain_block
        self._persist_meta()

    def check_pruning_utxo_commitment(self) -> bool:
        """Sanity: the maintained PP UTXO set matches the header commitment
        (processor.rs assert_utxo_commitment)."""
        ms = MuHash()
        for op, entry in self.pruning_utxo_set.items():
            ms.add_utxo(op, entry)
        return ms.finalize() == self.c.storage.headers.get(self.pruning_point).utxo_commitment

    # ------------------------------------------------------------------
    # phase 3: history deletion
    # ------------------------------------------------------------------

    def _snapshot_pp_windows(self) -> None:
        """Capture the new pp's sampled windows before prune() deletes the
        history a cold rebuild would need; seed the window caches too."""
        from kaspa_tpu.consensus.processes.window import DIFFICULTY_WINDOW, MEDIAN_TIME_WINDOW

        wm = self.c.window_manager
        gd = self.c.storage.ghostdag.get(self.pruning_point)
        self.pp_windows = {}
        for wt, cache in ((DIFFICULTY_WINDOW, wm._difficulty_cache), (MEDIAN_TIME_WINDOW, wm._median_cache)):
            try:
                win = list(wm.build_block_window(gd, wt))
            except Exception:  # noqa: BLE001 - insufficient window near genesis
                win = list(cache.get(self.pruning_point, []))
            self.pp_windows[wt] = win
            wm.cache_block_window(self.pruning_point, wt, list(win))

    def _window_keep_set(self, pp: bytes) -> set[bytes]:
        """Blocks of the pruning point's DAA + median-time windows."""
        from kaspa_tpu.consensus.processes.window import DIFFICULTY_WINDOW, MEDIAN_TIME_WINDOW

        keep: set[bytes] = set()
        gd = self.c.storage.ghostdag.get(pp)
        for wt in (DIFFICULTY_WINDOW, MEDIAN_TIME_WINDOW):
            try:
                for item in self.c.window_manager.build_block_window(gd, wt):
                    keep.add(item[1])
            except Exception:  # noqa: BLE001 - insufficient window near genesis
                pass
        return keep

    def _anchor_keep_set(self, new_pp: bytes) -> set[bytes]:
        """Selected-chain blocks within the inactivity window below the new
        pruning point, kept header-only through pruning.  KIP-21 lane-state
        export (consensus.export_pp_lane_state) serves these headers to
        bootstrapping peers as hash-bound shortcut anchors, and local
        shortcut resolution reads them — the reference likewise resolves
        below-PP shortcuts from headers it retains
        (processor.rs:870-905 inactivity_shortcut_block_for_pov).
        Empty on networks that never activate Toccata."""
        from kaspa_tpu.consensus.params import NEVER_ACTIVATION

        c = self.c
        keep: set[bytes] = set()
        if c.params.toccata_activation == NEVER_ACTIVATION:
            return keep
        if not c.storage.ghostdag.has(new_pp):
            return keep
        pp_bs = c.storage.ghostdag.get_blue_score(new_pp)
        lo = max(pp_bs - c.params.finality_depth - 64, 0)
        chain = []  # (blue_score, hash) ascending once reversed
        cur = new_pp
        while True:
            keep.add(cur)
            bs = (
                c.storage.ghostdag.get_blue_score(cur)
                if c.storage.ghostdag.has(cur)
                else (c.storage.headers.get(cur).blue_score if c.storage.headers.has(cur) else 0)
            )
            chain.append((bs, cur))
            if cur == c.params.genesis.hash or bs <= lo:
                break
            nxt = c._chain_parent(cur)
            if nxt is None:
                break
            # record the chain linkage before ghostdag re-rooting can lose it
            c._segment_prev.setdefault(cur, nxt)
            cur = nxt
        # refresh the persisted anchor segment to the current window so a
        # restart never resurrects chain entries whose headers this prune
        # deletes (the stale-meta hazard)
        if c.storage.db is not None and chain:
            from kaspa_tpu.consensus.consensus import _encode_anchor_segment

            c.storage.put_meta(b"lane_anchor_segment", _encode_anchor_segment(chain[::-1]))
        return keep

    def prune(self, new_pp: bytes, retention_root: bytes) -> None:
        c = self.c
        reach = c.reachability
        # full-data keep: future(pp) (incl. pp itself) and pp's anticone
        # header+ghostdag keep: pp windows, the past pruning points chain,
        # and the pruning proof slices for the new pp (the reference keeps
        # dedicated per-level proof stores; we must stay able to serve and
        # rebuild proofs after history deletion)
        keep_headers = (
            self._window_keep_set(new_pp)
            | set(self.past_pruning_points)
            | self._anchor_keep_set(new_pp)
        )
        for level_headers in c.pruning_proof_manager.build_proof():
            keep_headers.update(h.hash for h in level_headers)
        # the pruning-sample chain from pp to genesis: expected-pruning-point
        # walks of post-pp headers read these samples and their blue scores
        cur = new_pp
        seen_samples = set()
        while cur not in seen_samples:
            seen_samples.add(cur)
            keep_headers.add(cur)
            nxt = c.storage.pruning_samples.try_get(cur)
            if nxt is None or cur == c.params.genesis.hash:
                break
            cur = nxt
        all_blocks = list(c.storage.headers.keys())
        full_delete: list[bytes] = []
        header_only: list[bytes] = []
        for h in all_blocks:
            if not reach.has(h) or reach.is_dag_ancestor_of(new_pp, h):
                continue  # in future(pp) (or already gone): keep fully
            if not reach.is_dag_ancestor_of(h, new_pp):
                continue  # pp anticone: keep fully (may still be merged)
            if h in keep_headers:
                header_only.append(h)
            else:
                full_delete.append(h)

        delete_set = set(full_delete)
        # drop bodies/diffs/etc. for header-only keeps too (their pruning
        # samples survive: expected-pruning-point walks still read them)
        for h in header_only:
            c.storage.block_transactions.delete(h)
            self._del_aux(h, keep_sample=True)
        for h in full_delete:
            c.storage.block_transactions.delete(h)
            self._del_aux(h)
        # delete all stores + reachability for fully-pruned blocks, oldest
        # first (reachability splices children into parents transparently)
        full_delete.sort(key=lambda h: (c.storage.ghostdag.get_blue_work(h), h))
        for h in full_delete:
            reach.delete_block(h)
            c.storage.headers.delete(h)
            c.storage.ghostdag.delete(h)
            c.storage.relations.delete(h)
            c.storage.statuses.delete(h)
            c.reach_mergesets.delete(h)
            c._segment_prev.pop(h, None)
        # prune tips that can never be merged by virtual (not in future(pp))
        pruned_tips = {t for t in c.tips if t in delete_set}
        if pruned_tips:
            c.tips -= pruned_tips
            c._persist_tips()
        # drop KIP-21 lane build records + selected-chain entries of pruned
        # blocks (their SMT effect is already folded into the live tip state)
        for h in header_only + full_delete:
            c.lane_tracker.prune(h)
        if delete_set:
            c.selected_chain = [e for e in c.selected_chain if e[1] not in delete_set]
        # filter ghostdag data of surviving blocks so mergesets never dangle
        for h in list(c.storage.ghostdag.keys()):
            gd = c.storage.ghostdag.get(h)
            if any(m in delete_set for m in gd.unordered_mergeset()) or gd.selected_parent in delete_set:
                filtered = GhostdagData(
                    gd.blue_score,
                    gd.blue_work,
                    ORIGIN if gd.selected_parent in delete_set else gd.selected_parent,
                    [b for b in gd.mergeset_blues if b not in delete_set],
                    [b for b in gd.mergeset_reds if b not in delete_set],
                    {k: v for k, v in gd.blues_anticone_sizes.items() if k not in delete_set},
                )
                c.storage.ghostdag.insert(h, filtered)
        # filter the persisted reachability mergesets the same way (the
        # load-time rebuild replays these verbatim)
        for h, rm in list(c.reach_mergesets.items()):
            if any(m in delete_set for m in rm):
                c._set_reach_mergeset(h, [m for m in rm if m not in delete_set])
        c.storage.flush()

    def _del_aux(self, h: bytes, keep_sample: bool = False) -> None:
        """Delete virtual-stage per-block data (diff/multiset/acceptance/...)."""
        c = self.c
        c.utxo_diffs.delete(h)
        c.multisets.delete(h)
        c.acceptance_data.delete(h)
        c.daa_excluded.delete(h)
        c.storage.depth.delete(h)
        if not keep_sample:
            c.storage.pruning_samples.delete(h)
        c.window_manager._difficulty_cache.pop(h, None)
        c.window_manager._median_cache.pop(h, None)

    # ------------------------------------------------------------------
    # persistence
    # ------------------------------------------------------------------

    def _persist_meta(self) -> None:
        from kaspa_tpu.consensus import serde

        if self.c.storage.db is None:
            return
        self.c.storage.put_meta(b"pruning_point", self.pruning_point)
        self.c.storage.put_meta(b"retention_root", self.retention_period_root)
        self.c.storage.put_meta(b"pruning_utxoset_position", self.pruning_utxoset_position)
        self.c.storage.put_meta(b"past_pruning_points", serde.encode_hash_list(self.past_pruning_points))
        import io

        w = io.BytesIO()
        serde.write_varint(w, len(self.pp_windows))
        for wt in sorted(self.pp_windows):
            serde.write_bytes(w, wt.encode())
            win = self.pp_windows[wt]
            serde.write_varint(w, len(win))
            for work, h in win:
                serde.write_varint(w, work)
                w.write(h)
        self.c.storage.put_meta(b"pp_windows", w.getvalue())

    def load(self) -> None:
        """Restore pruning state from the attached DB (consensus._load_state).
        The PP UTXO set column needs no loading — it is read-through."""
        from kaspa_tpu.consensus import serde

        meta = self.c.storage.get_meta
        pp = meta(b"pruning_point")
        if pp is None:
            return
        self.pruning_point = pp
        self.retention_period_root = meta(b"retention_root") or pp
        self.pruning_utxoset_position = meta(b"pruning_utxoset_position") or pp
        raw = meta(b"past_pruning_points")
        if raw:
            self.past_pruning_points = serde.decode_hash_list_bytes(raw)
        raw_win = meta(b"pp_windows")
        if raw_win:
            import io

            r = io.BytesIO(raw_win)
            self.pp_windows = {
                serde.read_bytes(r).decode(): [
                    (serde.read_varint(r), r.read(32)) for _ in range(serde.read_varint(r))
                ]
                for _ in range(serde.read_varint(r))
            }
            for wt, win in self.pp_windows.items():
                self.c.window_manager.cache_block_window(self.pruning_point, wt, list(win))
