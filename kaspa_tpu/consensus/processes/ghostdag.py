"""GHOSTDAG: k-cluster blue/red coloring of the block DAG.

Faithful re-implementation of the protocol in
consensus/src/processes/ghostdag/{protocol,mergeset,ordering}.rs — the
PHANTOM/GHOSTDAG greedy coloring (https://eprint.iacr.org/2018/104.pdf):

- selected parent = parent with max (blue_work, hash)
- mergeset = past(new) \\ past(selected_parent), ordered ascending by
  (blue_work, hash) (topological: ancestors have smaller blue work)
- a candidate is blue iff adding it keeps every blue block's blue-anticone
  <= k, tracked incrementally via blues_anticone_sizes maps

This is host-side pointer-chasing by design (SURVEY.md §7 "hard parts" #6):
the DAG walk is irregular and tiny compared to the tx-validation batches
the TPU consumes.
"""

from __future__ import annotations

from collections import deque

from kaspa_tpu.consensus.difficulty import calc_work
from kaspa_tpu.consensus.reachability import ORIGIN, ReachabilityService
from kaspa_tpu.consensus.stores import GhostdagData, GhostdagStore, HeaderStore, RelationsStore

_BLUE = "blue"
_RED = "red"
_PENDING = "pending"


class GhostdagManager:
    def __init__(
        self,
        genesis_hash: bytes,
        k: int,
        ghostdag_store: GhostdagStore,
        relations_store: RelationsStore,
        headers_store: HeaderStore,
        reachability: ReachabilityService,
        level_work: int = 0,
    ):
        self.genesis_hash = genesis_hash
        self.k = k
        self.ghostdag_store = ghostdag_store
        self.relations_store = relations_store
        self.headers_store = headers_store
        self.reachability = reachability
        self.level_work = level_work

    # --- construction helpers ---

    def genesis_ghostdag_data(self) -> GhostdagData:
        return GhostdagData(0, 0, ORIGIN, [], [], {})

    def _new_with_selected_parent(self, selected_parent: bytes) -> GhostdagData:
        return GhostdagData(0, 0, selected_parent, [selected_parent], [], {selected_parent: 0})

    def find_selected_parent(self, parents) -> bytes:
        return max(parents, key=lambda p: (self.ghostdag_store.get_blue_work(p), p))

    def sort_blocks(self, blocks) -> list[bytes]:
        return sorted(blocks, key=lambda h: (self.ghostdag_store.get_blue_work(h), h))

    # --- mergeset (mergeset.rs) ---

    def unordered_mergeset_without_selected_parent(self, selected_parent: bytes, parents) -> set[bytes]:
        queue = deque(p for p in parents if p != selected_parent)  # graftlint: allow(unbounded-queue) -- local BFS work-list, bounded by the block's anticone
        mergeset = set(queue)
        past: set[bytes] = set()
        while queue:
            current = queue.popleft()
            for parent in self.relations_store.get_parents(current):
                if parent in mergeset or parent in past:
                    continue
                if self.reachability.is_dag_ancestor_of(parent, selected_parent):
                    past.add(parent)
                    continue
                mergeset.add(parent)
                queue.append(parent)
        return mergeset

    def ordered_mergeset_without_selected_parent(self, selected_parent: bytes, parents) -> list[bytes]:
        return self.sort_blocks(self.unordered_mergeset_without_selected_parent(selected_parent, parents))

    # --- coloring (protocol.rs) ---

    def ghostdag(self, parents: list[bytes]) -> GhostdagData:
        assert parents, "genesis must be added via genesis_ghostdag_data"
        selected_parent = self.find_selected_parent(parents)
        if selected_parent == ORIGIN:
            return self._new_with_selected_parent(selected_parent)
        data = self._new_with_selected_parent(selected_parent)

        for candidate in self.ordered_mergeset_without_selected_parent(selected_parent, parents):
            coloring = self._check_blue_candidate(data, candidate)
            if coloring is not None:
                anticone_size, anticone_sizes = coloring
                self._add_blue(data, candidate, anticone_size, anticone_sizes)
            else:
                data.mergeset_reds.append(candidate)

        data.blue_score = self.ghostdag_store.get_blue_score(selected_parent) + len(data.mergeset_blues)
        added_work = sum(
            max(calc_work(self.headers_store.get_bits(b)), self.level_work) for b in data.mergeset_blues
        )
        data.blue_work = self.ghostdag_store.get_blue_work(selected_parent) + added_work
        return data

    def _add_blue(self, data: GhostdagData, block: bytes, blue_anticone_size: int, anticone_sizes: dict[bytes, int]):
        # protocol mirror of GhostdagData::add_blue (model/stores/ghostdag.rs):
        # register the new blue, bump anticone sizes of affected blues
        data.mergeset_blues.append(block)
        data.blues_anticone_sizes[block] = blue_anticone_size
        for peer in anticone_sizes:
            data.blues_anticone_sizes[peer] = anticone_sizes[peer] + 1

    def _blue_anticone_size(self, block: bytes, context: GhostdagData) -> int:
        """|anticone(block) ∩ blues(context)|; block must be blue in context."""
        current_sizes = context.blues_anticone_sizes
        current_selected_parent = context.selected_parent
        while True:
            if block in current_sizes:
                return current_sizes[block]
            if current_selected_parent in (self.genesis_hash, ORIGIN):
                raise AssertionError(f"block {block.hex()} is not in blue set of the given context")
            current_sizes = self.ghostdag_store.get_blues_anticone_sizes(current_selected_parent)
            current_selected_parent = self.ghostdag_store.get_selected_parent(current_selected_parent)

    def _check_blue_candidate(self, data: GhostdagData, candidate: bytes):
        """Returns (candidate_blue_anticone_size, affected_sizes) if blue, None if red."""
        k = self.k
        if len(data.mergeset_blues) == k + 1:
            return None
        candidate_sizes: dict[bytes, int] = {}
        candidate_anticone = 0

        chain_hash: bytes | None = None  # None == the new block
        chain_data = data
        while True:
            state, candidate_anticone = self._check_with_chain_block(
                data, chain_hash, chain_data, candidate, candidate_sizes, candidate_anticone
            )
            if state == _BLUE:
                return candidate_anticone, candidate_sizes
            if state == _RED:
                return None
            chain_hash = chain_data.selected_parent
            chain_data = self.ghostdag_store.get(chain_hash)

    def _check_with_chain_block(self, data, chain_hash, chain_data, candidate, candidate_sizes, candidate_anticone):
        # if candidate is in the future of chain_block, all remaining blues
        # are in its past: safe to color blue
        if chain_hash is not None and self.reachability.is_dag_ancestor_of(chain_hash, candidate):
            return _BLUE, candidate_anticone
        k = self.k
        for peer in chain_data.mergeset_blues:
            if self.reachability.is_dag_ancestor_of(peer, candidate):
                continue
            peer_size = self._blue_anticone_size(peer, data)
            candidate_sizes[peer] = peer_size
            candidate_anticone += 1
            if candidate_anticone > k:
                return _RED, candidate_anticone
            if peer_size == k:
                return _RED, candidate_anticone
            assert peer_size <= k, "found blue anticone larger than K"
        return _PENDING, candidate_anticone
