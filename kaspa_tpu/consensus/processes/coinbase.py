"""Coinbase manager: subsidy schedule, mergeset reward payout, payload codec.

Re-implementation of consensus/src/processes/coinbase.rs: the coinbase tx
pays each blue mergeset block's reward to the script it declared (fees +
subsidy), aggregates red/non-DAA rewards to the merging miner, and embeds
(blue_score, subsidy, miner script, extra data) in the payload.
"""

from __future__ import annotations

from dataclasses import dataclass

from kaspa_tpu.consensus.model import (
    SUBNETWORK_ID_COINBASE,
    ScriptPublicKey,
    Transaction,
    TransactionOutput,
)
from kaspa_tpu.consensus.processes.subsidy_table import SUBSIDY_BY_MONTH_TABLE
from kaspa_tpu.consensus.stores import GhostdagData

SECONDS_PER_MONTH = 2629800  # 30.4375 days
MIN_PAYLOAD_LENGTH = 8 + 8 + 2 + 1

TX_VERSION = 0


class CoinbaseError(Exception):
    pass


@dataclass
class MinerData:
    script_public_key: ScriptPublicKey
    extra_data: bytes = b""


@dataclass
class CoinbaseData:
    blue_score: int
    subsidy: int
    miner_data: MinerData


@dataclass
class BlockRewardData:
    subsidy: int
    total_fees: int
    script_public_key: ScriptPublicKey


class CoinbaseManager:
    def __init__(
        self,
        coinbase_payload_script_public_key_max_len: int = 150,
        max_coinbase_payload_len: int = 204,
        deflationary_phase_daa_score: int = 0,
        pre_deflationary_phase_base_subsidy: int = 50_000_000_000,
        bps: int = 1,
    ):
        self.coinbase_payload_script_public_key_max_len = coinbase_payload_script_public_key_max_len
        self.max_coinbase_payload_len = max_coinbase_payload_len
        self.deflationary_phase_daa_score = deflationary_phase_daa_score
        self.pre_deflationary_phase_base_subsidy = pre_deflationary_phase_base_subsidy
        self.bps = bps
        # reward per block = (reward per second) / bps, rounded up (bps.rs style)
        self._subsidy_table = tuple(-(-s // bps) for s in SUBSIDY_BY_MONTH_TABLE)

    def calc_block_subsidy(self, daa_score: int) -> int:
        if daa_score < self.deflationary_phase_daa_score:
            return self.pre_deflationary_phase_base_subsidy
        seconds = (daa_score - self.deflationary_phase_daa_score) // self.bps
        month = seconds // SECONDS_PER_MONTH
        return self._subsidy_table[min(month, len(self._subsidy_table) - 1)]

    def expected_coinbase_transaction(
        self,
        daa_score: int,
        miner_data: MinerData,
        ghostdag_data: GhostdagData,
        mergeset_rewards: dict[bytes, BlockRewardData],
        mergeset_non_daa: set[bytes],
    ) -> Transaction:
        outputs = []
        for blue in ghostdag_data.mergeset_blues:
            if blue in mergeset_non_daa:
                continue
            reward = mergeset_rewards[blue]
            if reward.subsidy + reward.total_fees > 0:
                outputs.append(TransactionOutput(reward.subsidy + reward.total_fees, reward.script_public_key))

        red_reward = 0
        for red in ghostdag_data.mergeset_reds:
            reward = mergeset_rewards[red]
            if red in mergeset_non_daa:
                red_reward += reward.total_fees
            else:
                red_reward += reward.subsidy + reward.total_fees
        if red_reward > 0:
            outputs.append(TransactionOutput(red_reward, miner_data.script_public_key))

        subsidy = self.calc_block_subsidy(daa_score)
        payload = self.serialize_coinbase_payload(CoinbaseData(ghostdag_data.blue_score, subsidy, miner_data))
        return Transaction(TX_VERSION, [], outputs, 0, SUBNETWORK_ID_COINBASE, 0, payload)

    def serialize_coinbase_payload(self, data: CoinbaseData) -> bytes:
        script = data.miner_data.script_public_key.script
        if len(script) > self.coinbase_payload_script_public_key_max_len:
            raise CoinbaseError("script public key length above max")
        return (
            data.blue_score.to_bytes(8, "little")
            + data.subsidy.to_bytes(8, "little")
            + data.miner_data.script_public_key.version.to_bytes(2, "little")
            + bytes([len(script)])
            + script
            + data.miner_data.extra_data
        )

    def deserialize_coinbase_payload(self, payload: bytes) -> CoinbaseData:
        if len(payload) < MIN_PAYLOAD_LENGTH:
            raise CoinbaseError(f"payload len {len(payload)} below min {MIN_PAYLOAD_LENGTH}")
        if len(payload) > self.max_coinbase_payload_len:
            raise CoinbaseError(f"payload len {len(payload)} above max {self.max_coinbase_payload_len}")
        blue_score = int.from_bytes(payload[0:8], "little")
        subsidy = int.from_bytes(payload[8:16], "little")
        version = int.from_bytes(payload[16:18], "little")
        script_len = payload[18]
        if script_len > self.coinbase_payload_script_public_key_max_len:
            raise CoinbaseError("script public key length above max")
        if len(payload) - 19 < script_len:
            raise CoinbaseError("payload can't contain script public key")
        script = payload[19 : 19 + script_len]
        extra = payload[19 + script_len :]
        return CoinbaseData(blue_score, subsidy, MinerData(ScriptPublicKey(version, script), extra))

    def validate_coinbase_payload_in_isolation_and_extract_coinbase_data(self, coinbase_tx: Transaction) -> CoinbaseData:
        return self.deserialize_coinbase_payload(coinbase_tx.payload)
