"""Transaction validation (reference: consensus/src/processes/transaction_validator/).

- in-isolation checks (tx_validation_in_isolation.rs): counts, duplicate
  outpoints, script length limits, value ranges
- KIP-9 mass commitment checks against the contextual mass calculator
  (consensus/mass.py)
- header-context checks (tx_validation_in_header_context.rs): lock time
- UTXO-context checks (tx_validation_in_utxo_context.rs): maturity, input
  amounts, fee, sequence locks, script checks

Script checks are *collected* into a BatchScriptChecker (TPU batch) rather
than executed per input — the deferred-dispatch twist on the reference's
rayon check_scripts_par_iter (the "TPU offload point", SURVEY.md §2.5).
"""

from __future__ import annotations

from kaspa_tpu.consensus.mass import MassCalculator
from kaspa_tpu.consensus.model import SUBNETWORK_ID_NATIVE, Transaction
from kaspa_tpu.consensus.params import Params
from kaspa_tpu.txscript.batch import BatchScriptChecker
from kaspa_tpu.txscript.caches import SigCache

MAX_SOMPI = 29_000_000_000 * 100_000_000  # constants.rs MAX_SOMPI
SEQUENCE_LOCK_TIME_MASK = 0x00000000FFFFFFFF
SEQUENCE_LOCK_TIME_DISABLED = 1 << 63
LOCK_TIME_THRESHOLD = 500_000_000_000  # tx_validation_in_header_context


class TxRuleError(Exception):
    pass


FLAG_FULL = "full"
FLAG_SKIP_SCRIPTS = "skip_scripts"
FLAG_SKIP_MASS = "skip_mass"


class TransactionValidator:
    def __init__(self, params: Params, sig_cache: SigCache | None = None, vm_fallback=None):
        self.params = params
        self.coinbase_maturity = params.coinbase_maturity
        self.sig_cache = sig_cache if sig_cache is not None else SigCache()
        self.mass_calculator = MassCalculator.from_params(params)
        if vm_fallback is None:
            # nonstandard scripts run through the host VM with the shared
            # cache; Toccata activation (by the block's DAA score) selects
            # the engine flags + metering regime
            # (tx_validation_in_utxo_context.rs:171-172)
            from kaspa_tpu.txscript import vm as _vm
            from kaspa_tpu.txscript.resource_meter import RuntimeScriptUnitMeter, RuntimeSigOpCounter

            def vm_fallback(tx, entries, idx, reused, pov_daa_score=None, seq_commit_accessor=None, _cache=self.sig_cache):
                active = pov_daa_score is not None and params.toccata_active(pov_daa_score)
                flags = _vm.EngineFlags(covenants_enabled=active)
                commit = tx.inputs[idx].compute_commit
                if active:
                    sigop_units = params.mass_per_sig_op * 100  # Gram -> script units
                    budget = commit.compute_budget() or 0
                    meter = RuntimeScriptUnitMeter(sigop_units, budget * 10_000)  # SCRIPT_UNITS_PER_COMPUTE_BUDGET_UNIT
                else:
                    # pre-Toccata regime (lib.rs:545): executed sig ops may
                    # not exceed the input's committed sig-op count
                    meter = RuntimeSigOpCounter(commit.sig_op_count() or 0)
                engine = _vm.TxScriptEngine(
                    tx, entries, idx, reused, _cache, flags=flags, meter=meter,
                    seq_commit_accessor=seq_commit_accessor if active else None,
                )
                engine.execute()

        self.vm_fallback = vm_fallback

    def new_checker(self, traffic_class: str | None = None) -> BatchScriptChecker:
        return BatchScriptChecker(self.sig_cache, self.vm_fallback, traffic_class=traffic_class)

    # --- in isolation (tx_validation_in_isolation.rs) ---

    def validate_tx_in_isolation(self, tx: Transaction) -> None:
        if not tx.is_coinbase():
            if len(tx.inputs) == 0:
                raise TxRuleError("transaction has no inputs")
            if len(tx.inputs) > self.params.max_tx_inputs:
                raise TxRuleError(f"too many inputs {len(tx.inputs)}")
            for inp in tx.inputs:
                if len(inp.signature_script) > self.params.max_signature_script_len:
                    raise TxRuleError("signature script too long")
        if len(tx.outputs) > self.params.max_tx_outputs:
            raise TxRuleError(f"too many outputs {len(tx.outputs)}")
        total = 0
        for out in tx.outputs:
            if out.value == 0:
                raise TxRuleError("zero output value")
            if out.value > MAX_SOMPI:
                raise TxRuleError("output value too high")
            total += out.value
            if total > MAX_SOMPI:
                raise TxRuleError("outputs total overflow")
            if len(out.script_public_key.script) > self.params.max_script_public_key_len:
                raise TxRuleError("script public key too long")
        seen = set()
        for inp in tx.inputs:
            if inp.previous_outpoint in seen:
                raise TxRuleError("duplicate outpoint")
            seen.add(inp.previous_outpoint)
        if tx.subnetwork_id == SUBNETWORK_ID_NATIVE and tx.gas > 0:
            raise TxRuleError("gas in native subnetwork")

    # --- header context (lock time) ---

    def validate_tx_in_header_context(self, tx: Transaction, ctx_daa_score: int, ctx_past_median_time: int) -> None:
        if tx.lock_time == 0:
            return
        if tx.lock_time < LOCK_TIME_THRESHOLD:
            block_or_time = ctx_daa_score  # interpreted as DAA score
        else:
            block_or_time = ctx_past_median_time
        # strict <: equality is NOT finalized (tx_validation_in_header_context.rs:79)
        if tx.lock_time < block_or_time:
            return
        # lock time hasn't occurred: every input must have max sequence
        if any(inp.sequence != (1 << 64) - 1 for inp in tx.inputs):
            raise TxRuleError("tx is not finalized")

    # --- utxo context (tx_validation_in_utxo_context.rs) ---

    def validate_populated_transaction_and_get_fee(
        self,
        tx: Transaction,
        entries: list,
        pov_daa_score: int,
        flags: str = FLAG_FULL,
        checker: BatchScriptChecker | None = None,
        token: int | None = None,
        seq_commit_accessor=None,
    ) -> int:
        self._check_coinbase_maturity(tx, entries, pov_daa_score)
        total_in = self._check_input_amounts(entries)
        total_out = self._check_output_values(tx, total_in)
        fee = total_in - total_out
        if flags != FLAG_SKIP_MASS:
            self._check_mass_commitment(tx, entries)
        self._check_sequence_lock(tx, entries, pov_daa_score)
        if flags in (FLAG_FULL, FLAG_SKIP_MASS):
            assert checker is not None and token is not None, "script checks need a batch checker"
            checker.collect_tx(token, tx, entries, pov_daa_score=pov_daa_score, seq_commit_accessor=seq_commit_accessor)
        return fee

    def _check_mass_commitment(self, tx, entries):
        """tx_validation_in_utxo_context.rs check_mass_commitment: the miner-
        committed storage mass must equal the KIP-9 contextual mass."""
        calculated = self.mass_calculator.calc_contextual_masses(tx, entries)
        if calculated is None:
            raise TxRuleError("mass incomputable")
        if tx.storage_mass != calculated:
            raise TxRuleError(f"wrong mass commitment: committed {tx.storage_mass}, calculated {calculated}")

    def _check_coinbase_maturity(self, tx, entries, pov_daa_score):
        for i, (inp, entry) in enumerate(zip(tx.inputs, entries)):
            if entry.is_coinbase and entry.block_daa_score + self.coinbase_maturity > pov_daa_score:
                raise TxRuleError(
                    f"immature coinbase spend at input {i}: utxo daa {entry.block_daa_score} pov {pov_daa_score}"
                )

    def _check_input_amounts(self, entries) -> int:
        total = 0
        for entry in entries:
            total += entry.amount
            if total > MAX_SOMPI:
                raise TxRuleError("input amount too high")
        return total

    def _check_output_values(self, tx, total_in) -> int:
        total_out = sum(out.value for out in tx.outputs)
        if total_in < total_out:
            raise TxRuleError(f"spend too high {total_out} > {total_in}")
        return total_out

    def _check_sequence_lock(self, tx, entries, pov_daa_score):
        pov = pov_daa_score
        for inp, entry in zip(tx.inputs, entries):
            if inp.sequence & SEQUENCE_LOCK_TIME_DISABLED == SEQUENCE_LOCK_TIME_DISABLED:
                continue
            relative_lock = inp.sequence & SEQUENCE_LOCK_TIME_MASK
            lock_daa_score = entry.block_daa_score + relative_lock - 1
            if lock_daa_score >= pov:
                raise TxRuleError("sequence lock conditions are not met")
