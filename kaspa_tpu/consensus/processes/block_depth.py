"""Block depth: merge-depth roots, finality points, bounded-merge checking.

Reference: consensus/src/processes/block_depth.rs (BlockDepthManager) and
pipeline/header_processor/post_pow_validation.rs check_bounded_merge_depth:
a block may not merge red blocks from beyond its merge-depth root unless a
blue block in its mergeset "kosherizes" the red (has it in its past and has
the merge-depth root on its selected chain) — the anti-deep-reorg rule.
"""

from __future__ import annotations

from kaspa_tpu.consensus.reachability import ORIGIN


class BlockDepthManager:
    def __init__(self, merge_depth: int, finality_depth: int, genesis_hash: bytes, ghostdag_store, reachability, depth_store):
        self.merge_depth = merge_depth
        self.finality_depth = finality_depth
        self.genesis_hash = genesis_hash
        self.gd = ghostdag_store
        self.reachability = reachability
        # per-block depth store (model/stores/depth.rs): bounded read-through
        # CachedDbAccess of (merge_depth_root, finality_point) pairs
        self.depth = depth_store

    def store(self, block: bytes, merge_depth_root: bytes, finality_point: bytes) -> None:
        self.depth[block] = (merge_depth_root, finality_point)

    def merge_depth_root(self, block: bytes) -> bytes:
        pair = self.depth.try_get(block)
        return pair[0] if pair else ORIGIN

    def finality_point(self, block: bytes) -> bytes:
        pair = self.depth.try_get(block)
        return pair[1] if pair else ORIGIN

    def calc_merge_depth_root(self, gd, pruning_point: bytes) -> bytes:
        return self._calc_block_at_depth(gd, self.merge_depth, pruning_point, 0)

    def calc_finality_point(self, gd, pruning_point: bytes) -> bytes:
        return self._calc_block_at_depth(gd, self.finality_depth, pruning_point, 1)

    def _calc_block_at_depth(self, gd, depth: int, pruning_point: bytes, pair_idx: int) -> bytes:
        if gd.selected_parent == ORIGIN:
            return ORIGIN
        if gd.blue_score < depth:
            return self.genesis_hash
        pp_bs = self.gd.get_blue_score(pruning_point)
        if gd.blue_score < pp_bs + depth:
            return ORIGIN
        if not self.reachability.is_chain_ancestor_of(pruning_point, gd.selected_parent):
            return ORIGIN
        pair = self.depth.try_get(gd.selected_parent)
        current = pair[pair_idx] if pair else ORIGIN
        if current == ORIGIN:
            current = pruning_point
        required_blue_score = gd.blue_score - depth
        # forward chain walk from `current` to selected parent (inclusive)
        path = []
        walker = gd.selected_parent
        while walker != current:
            path.append(walker)
            walker = self.gd.get_selected_parent(walker)
        for chain_block in reversed(path):
            if self.gd.get_blue_score(chain_block) >= required_blue_score:
                break
            current = chain_block
        return current

    def kosherizing_blues(self, gd, merge_depth_root: bytes) -> list[bytes]:
        return [b for b in gd.mergeset_blues if self.reachability.is_chain_ancestor_of(merge_depth_root, b)]

    def check_bounded_merge_depth(self, gd, pruning_point: bytes) -> tuple[bytes, bytes]:
        """Raises on violation; returns (merge_depth_root, finality_point)."""
        merge_depth_root = self.calc_merge_depth_root(gd, pruning_point)
        finality_point = self.calc_finality_point(gd, pruning_point)
        kosherizing = None
        for red in gd.mergeset_reds:
            if self.reachability.is_dag_ancestor_of(merge_depth_root, red):
                continue
            if kosherizing is None:
                kosherizing = self.kosherizing_blues(gd, merge_depth_root)
            if not any(self.reachability.is_dag_ancestor_of(red, k) for k in kosherizing):
                raise ViolatingBoundedMergeDepth(
                    f"red block {red.hex()[:16]} beyond merge depth root with no kosherizing blue"
                )
        return merge_depth_root, finality_point


class ViolatingBoundedMergeDepth(Exception):
    pass
