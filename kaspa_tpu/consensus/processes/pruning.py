"""Pruning-point management: samples, expected header pruning points.

Reference: consensus/src/processes/pruning.rs (PruningPointManager).  Chain
blocks sample the selected chain every finality-score epoch; a block's
expected pruning point is the most recent sample at pruning depth, clamped
by the sample-step bound and the selected parent's pruning point for
monotonicity.  Verified per chain block (verify_header_pruning_point in
the virtual processor's chain-qualification path).

The history-pruning executor (deleting pruned data, pruning-point UTXO set
maintenance) builds on this in the pruning-processor milestone.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class PruningPointReply:
    pruning_sample: bytes
    pruning_point: bytes


class PruningPointManager:
    def __init__(self, pruning_depth: int, finality_depth: int, genesis_hash: bytes, headers_store, samples_store):
        self.pruning_depth = pruning_depth
        self.finality_depth = finality_depth
        self.genesis_hash = genesis_hash
        self.headers = headers_store
        self.pruning_samples_steps = -(-pruning_depth // finality_depth)
        # pruning_sample_from_pov store (model/stores/pruning_samples.rs):
        # bounded read-through CachedDbAccess of 32-byte sample hashes
        self.samples = samples_store

    def store_pruning_sample(self, block: bytes, sample: bytes) -> None:
        self.samples[block] = sample

    def pruning_sample_from_pov(self, block: bytes) -> bytes:
        return self.samples[block]

    def finality_score(self, blue_score: int) -> int:
        return blue_score // self.finality_depth

    def is_pruning_sample(self, self_blue_score: int, epoch_chain_ancestor_blue_score: int) -> bool:
        """pruning.rs:165-172: own finality score exceeds the epoch ancestor's."""
        return self.finality_score(epoch_chain_ancestor_blue_score) < self.finality_score(self_blue_score)

    def expected_header_pruning_point(self, gd) -> PruningPointReply:
        """pruning.rs:105-158 — gd needs selected_parent and blue_score."""
        sp = gd.selected_parent
        sp_blue_score = self.headers.get_blue_score(sp)

        if sp == self.genesis_hash:
            pruning_sample = self.genesis_hash
        else:
            sp_sample = self.samples[sp]
            sp_sample_blue_score = self.headers.get_blue_score(sp_sample)
            if self.is_pruning_sample(sp_blue_score, sp_sample_blue_score):
                pruning_sample = sp  # the selected parent is the most recent sample
            else:
                pruning_sample = sp_sample

        is_self_sample = self.is_pruning_sample(gd.blue_score, sp_blue_score)
        sp_pruning_point = self.headers.get(sp).pruning_point
        steps = 1
        current = pruning_sample
        while True:
            if current == self.genesis_hash:
                break
            if self.headers.get_blue_score(current) + self.pruning_depth <= gd.blue_score:
                break  # most recent sample at pruning depth
            if is_self_sample and steps == self.pruning_samples_steps:
                break  # post-hardfork step clamp for samples
            if current == sp_pruning_point:
                break  # monotonicity clamp for non-samples
            current = self.samples[current]
            steps += 1

        return PruningPointReply(pruning_sample, current)

    def next_pruning_points(self, sink_gd, current_pruning_point: bytes) -> list[bytes]:
        """pruning.rs:174-203: samples between the current and expected PP."""
        cur_bs = self.headers.get_blue_score(current_pruning_point)
        if cur_bs + self.pruning_depth > sink_gd.blue_score:
            return []
        sink_pp = self.expected_header_pruning_point(sink_gd).pruning_point
        if self.headers.get_blue_score(sink_pp) <= cur_bs:
            return []
        out = []
        current = sink_pp
        while current != current_pruning_point:
            out.append(current)
            current = self.samples[current]
        out.reverse()
        return out
