"""Network parameters (reference: consensus/core/src/config/{params,bps,constants}.rs).

The Bps class mirrors the reference's const-generic `Bps<BPS>` generator
(config/bps.rs): every BPS-dependent constant is a function of the
blocks-per-second value.  `Params` carries the full per-network parameter
set; fork activation (ForkActivation gating on DAA score) is modeled with
plain integers ("always" == 0, "never" == 2**64-1).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

# --- constants.rs consensus module ---
NETWORK_DELAY_BOUND = 5
GHOSTDAG_TAIL_DELTA = 0.01
TIMESTAMP_DEVIATION_TOLERANCE = 132
PAST_MEDIAN_TIME_SAMPLE_INTERVAL = 10
MEDIAN_TIME_SAMPLED_WINDOW_SIZE = -(-(2 * TIMESTAMP_DEVIATION_TOLERANCE - 1) // PAST_MEDIAN_TIME_SAMPLE_INTERVAL)
MAX_DIFFICULTY_TARGET = (1 << 255) - 1
MIN_DIFFICULTY_WINDOW_SIZE = 150
DIFFICULTY_WINDOW_DURATION = 2641
DIFFICULTY_WINDOW_SAMPLE_INTERVAL = 4
DIFFICULTY_SAMPLED_WINDOW_SIZE = -(-DIFFICULTY_WINDOW_DURATION // DIFFICULTY_WINDOW_SAMPLE_INTERVAL)
FINALITY_DURATION = 43_200
PRUNING_DURATION = 108_000
MERGE_DEPTH_DURATION = 3600
PRUNING_PROOF_M = 1000
NEVER_ACTIVATION = (1 << 64) - 1  # ForkActivation::never()
COINBASE_MATURITY_SECONDS = 100
# Toccata lane limits (constants.rs:94-101): at 10 BPS, 50 lanes/block allows
# a worst-case rate of 500 SMT lane updates per second; the gas cap is set
# high for gas-cost granularity within lane/subnet applications.
DEFAULT_LANES_PER_BLOCK_LIMIT = 50
DEFAULT_GAS_PER_LANE_LIMIT = 1_000_000_000

FORK_ALWAYS = 0
FORK_NEVER = (1 << 64) - 1

_GHOSTDAG_K_TABLE = {
    1: 18, 2: 31, 3: 43, 4: 55, 5: 67, 6: 79, 7: 90, 8: 102, 9: 113, 10: 124,
    11: 135, 12: 146, 13: 157, 14: 168, 15: 179, 16: 190, 17: 201, 18: 212, 19: 223, 20: 234,
    21: 244, 22: 255, 23: 266, 24: 277, 25: 288, 26: 298, 27: 309, 28: 320, 29: 330, 30: 341,
    31: 352, 32: 362,
}


def calculate_ghostdag_k(x: float, delta: float) -> int:
    """Eq. 1, section 4.2 of the PHANTOM paper (config/bps.rs:9-21)."""
    assert x > 0 and 0 < delta < 1
    k_hat, sigma, fraction = 0, 0.0, 1.0
    exp = math.e ** (-x)
    while True:
        sigma += exp * fraction
        if 1.0 - sigma < delta:
            return k_hat
        k_hat += 1
        fraction *= x / k_hat


class Bps:
    """Constants generator for a given blocks-per-second value (config/bps.rs)."""

    def __init__(self, bps: int):
        assert 1000 % bps == 0, "BPS must divide 1000"
        self.bps = bps

    def ghostdag_k(self) -> int:
        return _GHOSTDAG_K_TABLE[self.bps]

    def target_time_per_block(self) -> int:
        return 1000 // self.bps

    def max_block_parents(self) -> int:
        return min(max(self.ghostdag_k() // 2, 10), 16)

    def mergeset_size_limit(self) -> int:
        return min(max(self.ghostdag_k() * 2, 180), 512)

    def merge_depth_bound(self) -> int:
        return self.bps * MERGE_DEPTH_DURATION

    def finality_depth(self) -> int:
        return self.bps * FINALITY_DURATION

    def pruning_depth(self) -> int:
        lower_bound = (
            self.finality_depth()
            + self.merge_depth_bound() * 2
            + 4 * self.mergeset_size_limit() * self.ghostdag_k()
            + 2 * self.ghostdag_k()
            + 2
        )
        return max(lower_bound, self.bps * PRUNING_DURATION)

    def past_median_time_sample_rate(self) -> int:
        return self.bps * PAST_MEDIAN_TIME_SAMPLE_INTERVAL

    def difficulty_adjustment_sample_rate(self) -> int:
        return self.bps * DIFFICULTY_WINDOW_SAMPLE_INTERVAL

    def coinbase_maturity(self) -> int:
        return self.bps * COINBASE_MATURITY_SECONDS


@dataclass
class GenesisBlock:
    hash: bytes
    bits: int
    timestamp: int
    version: int = 0
    daa_score: int = 0
    coinbase_payload: bytes = b""


@dataclass
class Params:
    """Consensus parameters for one network (config/params.rs Params)."""

    name: str
    bps: int
    genesis: GenesisBlock
    ghostdag_k: int
    target_time_per_block: int  # milliseconds
    max_block_parents: int
    mergeset_size_limit: int
    merge_depth: int
    finality_depth: int
    pruning_depth: int
    coinbase_maturity: int
    difficulty_window_size: int = DIFFICULTY_SAMPLED_WINDOW_SIZE
    min_difficulty_window_size: int = MIN_DIFFICULTY_WINDOW_SIZE
    difficulty_sample_rate: int = 4
    past_median_time_window_size: int = MEDIAN_TIME_SAMPLED_WINDOW_SIZE
    past_median_time_sample_rate: int = 10
    max_difficulty_target: int = MAX_DIFFICULTY_TARGET
    timestamp_deviation_tolerance: int = TIMESTAMP_DEVIATION_TOLERANCE
    max_block_mass: int = 500_000
    mass_per_tx_byte: int = 1
    mass_per_script_pub_key_byte: int = 10
    mass_per_sig_op: int = 1000
    storage_mass_parameter: int = 100_000_000 * 10_000  # STORAGE_MASS_PARAMETER
    max_tx_inputs: int = 1_000
    max_tx_outputs: int = 1_000
    max_signature_script_len: int = 1_000
    max_script_public_key_len: int = 10_000
    max_coinbase_payload_len: int = 204
    deflationary_phase_daa_score: int = 0
    pre_deflationary_phase_base_subsidy: int = 50_000_000_000
    skip_proof_of_work: bool = False
    max_block_level: int = 225
    pruning_proof_m: int = PRUNING_PROOF_M
    # KIP-21 block lane limits (params.rs:347 block_lane_limits). Enforced
    # unconditionally in body-in-isolation validation: pre-Toccata valid
    # blocks carry only native zero-gas non-coinbase txs, so the caps are
    # vacuous before activation (body_validation_in_isolation.rs:98-100).
    lanes_per_block: int = DEFAULT_LANES_PER_BLOCK_LIMIT
    gas_per_lane: int = DEFAULT_GAS_PER_LANE_LIMIT
    genesis_override: object = None  # full genesis Block (golden-DAG replay)
    # ForkActivation (config/params.rs:30): DAA score at which the Toccata
    # consensus surface (covenants, introspection breadth, ZK precompiles,
    # script-unit metering) activates; NEVER on all current networks.
    toccata_activation: int = NEVER_ACTIVATION

    def toccata_active(self, daa_score: int) -> bool:
        return daa_score >= self.toccata_activation

    def block_version(self, daa_score: int) -> int:
        """Forked block version (constants.rs BLOCK_VERSION=1 /
        TOCCATA_BLOCK_VERSION=2, params.rs:535).  Headers are checked
        against this in context (post_pow_validation.rs:105-111); genesis
        itself is exempt (inserted, never validated)."""
        return 2 if self.toccata_active(daa_score) else 1

    @staticmethod
    def from_bps(name: str, bps: int, genesis: GenesisBlock, **overrides) -> "Params":
        g = Bps(bps)
        p = Params(
            name=name,
            bps=bps,
            genesis=genesis,
            ghostdag_k=g.ghostdag_k(),
            target_time_per_block=g.target_time_per_block(),
            max_block_parents=g.max_block_parents(),
            mergeset_size_limit=g.mergeset_size_limit(),
            merge_depth=g.merge_depth_bound(),
            finality_depth=g.finality_depth(),
            pruning_depth=g.pruning_depth(),
            coinbase_maturity=g.coinbase_maturity(),
            difficulty_sample_rate=g.difficulty_adjustment_sample_rate(),
            past_median_time_sample_rate=g.past_median_time_sample_rate(),
        )
        for k, v in overrides.items():
            setattr(p, k, v)
        return p


def simnet_params(bps: int = 8, genesis_bits: int = 0x207FFFFF, genesis_timestamp: int = 0) -> Params:
    """Simulation params in the style of simpa's self-tuned config
    (simpa/src/main.rs:352-390): easy difficulty, skip-PoW, tuned to bps."""
    genesis = GenesisBlock(hash=b"\x01" + b"\x00" * 31, bits=genesis_bits, timestamp=genesis_timestamp)
    # short coinbase maturity so small simulations exercise real spends
    return Params.from_bps(f"simnet-{bps}bps", bps, genesis, skip_proof_of_work=True, coinbase_maturity=8)


MAINNET_BPS = 10
