"""Canonical binary codec for consensus data types.

One compact binary form shared by the persistence layer (store values in the
native KV engine) and the P2P/RPC wire framing — the role protobuf plays in
the reference (`protocol/p2p/proto/messages.proto`, plus bincode for store
values in `database/src/access.rs`).  Integers are unsigned LEB128 varints;
hashes are fixed 32 bytes; optional fields carry a 1-byte presence tag.

Round-trip exactness is what consensus persistence requires: `decode(encode
(x)) == x` for every stored type (tested in tests/test_serde.py).
"""

from __future__ import annotations

import io
import struct

from kaspa_tpu.consensus.model import (
    ComputeCommit,
    Covenant,
    Header,
    ScriptPublicKey,
    Transaction,
    TransactionInput,
    TransactionOutpoint,
    TransactionOutput,
    UtxoEntry,
)


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------

def _read_exact(r: io.BytesIO, n: int) -> bytes:
    data = r.read(n)
    if len(data) != n:
        raise EOFError(f"truncated read: wanted {n}, got {len(data)}")
    return data


def write_varint(w: io.BytesIO, v: int) -> None:
    if v < 0:
        raise ValueError("varint must be non-negative")
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            w.write(bytes([b | 0x80]))
        else:
            w.write(bytes([b]))
            return


def read_varint(r: io.BytesIO) -> int:
    shift = 0
    out = 0
    while True:
        c = r.read(1)
        if not c:
            raise EOFError("truncated varint")
        b = c[0]
        out |= (b & 0x7F) << shift
        if not (b & 0x80):
            return out
        shift += 7
        if shift > 448:
            raise ValueError("varint too long")


def write_bytes(w: io.BytesIO, data: bytes) -> None:
    write_varint(w, len(data))
    w.write(data)


def read_bytes(r: io.BytesIO) -> bytes:
    return _read_exact(r, read_varint(r))


def write_hash(w: io.BytesIO, h: bytes) -> None:
    assert len(h) == 32, len(h)
    w.write(h)


def read_hash(r: io.BytesIO) -> bytes:
    return _read_exact(r, 32)


def write_bigint(w: io.BytesIO, v: int) -> None:
    """Arbitrary-size non-negative int (blue_work is Uint192-range)."""
    raw = v.to_bytes((v.bit_length() + 7) // 8, "little") if v else b""
    write_bytes(w, raw)


def read_bigint(r: io.BytesIO) -> int:
    return int.from_bytes(read_bytes(r), "little")


def write_option(w: io.BytesIO, v, writer) -> None:
    if v is None:
        w.write(b"\x00")
    else:
        w.write(b"\x01")
        writer(w, v)


def read_option(r: io.BytesIO, reader):
    return reader(r) if _read_exact(r, 1)[0] else None


# ---------------------------------------------------------------------------
# consensus types
# ---------------------------------------------------------------------------

def write_outpoint(w: io.BytesIO, op: TransactionOutpoint) -> None:
    write_hash(w, op.transaction_id)
    write_varint(w, op.index)


def read_outpoint(r: io.BytesIO) -> TransactionOutpoint:
    return TransactionOutpoint(read_hash(r), read_varint(r))


def write_spk(w: io.BytesIO, spk: ScriptPublicKey) -> None:
    write_varint(w, spk.version)
    write_bytes(w, spk.script)


def read_spk(r: io.BytesIO) -> ScriptPublicKey:
    return ScriptPublicKey(read_varint(r), read_bytes(r))


def write_input(w: io.BytesIO, inp: TransactionInput) -> None:
    write_outpoint(w, inp.previous_outpoint)
    write_bytes(w, inp.signature_script)
    write_varint(w, inp.sequence)
    w.write(b"\x00" if inp.compute_commit.kind == "sigops" else b"\x01")
    write_varint(w, inp.compute_commit.value)


def read_input(r: io.BytesIO) -> TransactionInput:
    op = read_outpoint(r)
    script = read_bytes(r)
    seq = read_varint(r)
    kind = _read_exact(r, 1)[0]
    value = read_varint(r)
    cc = ComputeCommit.sigops(value) if kind == 0 else ComputeCommit.budget(value)
    return TransactionInput(op, script, seq, cc)


def write_covenant(w: io.BytesIO, cov: Covenant) -> None:
    write_varint(w, cov.authorizing_input)
    write_hash(w, cov.covenant_id)


def read_covenant(r: io.BytesIO) -> Covenant:
    return Covenant(read_varint(r), read_hash(r))


def write_output(w: io.BytesIO, out: TransactionOutput) -> None:
    write_varint(w, out.value)
    write_spk(w, out.script_public_key)
    write_option(w, out.covenant, write_covenant)


def read_output(r: io.BytesIO) -> TransactionOutput:
    return TransactionOutput(read_varint(r), read_spk(r), read_option(r, read_covenant))


def write_tx(w: io.BytesIO, tx: Transaction) -> None:
    assert len(tx.subnetwork_id) == 20, len(tx.subnetwork_id)
    write_varint(w, tx.version)
    write_varint(w, len(tx.inputs))
    for inp in tx.inputs:
        write_input(w, inp)
    write_varint(w, len(tx.outputs))
    for out in tx.outputs:
        write_output(w, out)
    write_varint(w, tx.lock_time)
    w.write(tx.subnetwork_id)
    write_varint(w, tx.gas)
    write_bytes(w, tx.payload)
    write_varint(w, tx.storage_mass)


def read_tx(r: io.BytesIO) -> Transaction:
    version = read_varint(r)
    inputs = [read_input(r) for _ in range(read_varint(r))]
    outputs = [read_output(r) for _ in range(read_varint(r))]
    lock_time = read_varint(r)
    subnetwork = _read_exact(r, 20)
    gas = read_varint(r)
    payload = read_bytes(r)
    storage_mass = read_varint(r)
    return Transaction(version, inputs, outputs, lock_time, subnetwork, gas, payload, storage_mass)


def write_header(w: io.BytesIO, h: Header) -> None:
    write_varint(w, h.version)
    write_varint(w, len(h.parents_by_level))
    for level in h.parents_by_level:
        write_varint(w, len(level))
        for p in level:
            write_hash(w, p)
    write_hash(w, h.hash_merkle_root)
    write_hash(w, h.accepted_id_merkle_root)
    write_hash(w, h.utxo_commitment)
    write_varint(w, h.timestamp)
    w.write(struct.pack("<I", h.bits))
    write_varint(w, h.nonce)
    write_varint(w, h.daa_score)
    write_bigint(w, h.blue_work)
    write_varint(w, h.blue_score)
    write_hash(w, h.pruning_point)
    # persisted headers carry their (validated) hash so loads skip rehashing
    write_option(w, h._hash_cache, write_hash)


def read_header(r: io.BytesIO) -> Header:
    version = read_varint(r)
    parents_by_level = []
    for _ in range(read_varint(r)):
        parents_by_level.append([read_hash(r) for _ in range(read_varint(r))])
    hash_merkle_root = read_hash(r)
    accepted_id_merkle_root = read_hash(r)
    utxo_commitment = read_hash(r)
    timestamp = read_varint(r)
    bits = struct.unpack("<I", _read_exact(r, 4))[0]
    nonce = read_varint(r)
    daa_score = read_varint(r)
    blue_work = read_bigint(r)
    blue_score = read_varint(r)
    pruning_point = read_hash(r)
    h = Header(
        version, parents_by_level, hash_merkle_root, accepted_id_merkle_root,
        utxo_commitment, timestamp, bits, nonce, daa_score, blue_work,
        blue_score, pruning_point,
    )
    h._hash_cache = read_option(r, read_hash)
    return h


def write_utxo_entry(w: io.BytesIO, e: UtxoEntry) -> None:
    write_varint(w, e.amount)
    write_spk(w, e.script_public_key)
    write_varint(w, e.block_daa_score)
    w.write(b"\x01" if e.is_coinbase else b"\x00")
    write_option(w, e.covenant_id, write_hash)


def read_utxo_entry(r: io.BytesIO) -> UtxoEntry:
    return UtxoEntry(
        read_varint(r), read_spk(r), read_varint(r), _read_exact(r, 1)[0] == 1,
        read_option(r, read_hash),
    )


def write_hash_list(w: io.BytesIO, hs) -> None:
    write_varint(w, len(hs))
    for h in hs:
        write_hash(w, h)


def read_hash_list(r: io.BytesIO) -> list[bytes]:
    return [read_hash(r) for _ in range(read_varint(r))]


# ---------------------------------------------------------------------------
# top-level helpers (bytes <-> object)
# ---------------------------------------------------------------------------

def _enc(writer, obj) -> bytes:
    w = io.BytesIO()
    writer(w, obj)
    return w.getvalue()


def _dec(reader, data: bytes):
    return reader(io.BytesIO(data))


def encode_header(h: Header) -> bytes:
    return _enc(write_header, h)


def decode_header(data: bytes) -> Header:
    return _dec(read_header, data)


def encode_tx(tx: Transaction) -> bytes:
    return _enc(write_tx, tx)


def decode_tx(data: bytes) -> Transaction:
    return _dec(read_tx, data)


def encode_txs(txs: list[Transaction]) -> bytes:
    w = io.BytesIO()
    write_varint(w, len(txs))
    for tx in txs:
        write_tx(w, tx)
    return w.getvalue()


def decode_txs(data: bytes) -> list[Transaction]:
    r = io.BytesIO(data)
    return [read_tx(r) for _ in range(read_varint(r))]


def encode_hash_list(hs) -> bytes:
    return _enc(write_hash_list, list(hs))


def decode_hash_list_bytes(data: bytes) -> list[bytes]:
    return _dec(read_hash_list, data)


def encode_utxo_entry(e: UtxoEntry) -> bytes:
    return _enc(write_utxo_entry, e)


def decode_utxo_entry(data: bytes) -> UtxoEntry:
    return _dec(read_utxo_entry, data)


def encode_ghostdag(gd) -> bytes:
    w = io.BytesIO()
    write_varint(w, gd.blue_score)
    write_bigint(w, gd.blue_work)
    write_hash(w, gd.selected_parent)
    write_hash_list(w, gd.mergeset_blues)
    write_hash_list(w, gd.mergeset_reds)
    write_varint(w, len(gd.blues_anticone_sizes))
    for h, size in gd.blues_anticone_sizes.items():
        write_hash(w, h)
        write_varint(w, size)
    return w.getvalue()


def decode_ghostdag(data: bytes):
    from kaspa_tpu.consensus.stores import GhostdagData

    r = io.BytesIO(data)
    blue_score = read_varint(r)
    blue_work = read_bigint(r)
    selected_parent = read_hash(r)
    blues = read_hash_list(r)
    reds = read_hash_list(r)
    anticone = {}
    for _ in range(read_varint(r)):
        h = read_hash(r)
        anticone[h] = read_varint(r)
    return GhostdagData(blue_score, blue_work, selected_parent, blues, reds, anticone)


def encode_utxo_diff(diff) -> bytes:
    """UtxoDiff (consensus/utxo.py): two outpoint->entry maps."""
    w = io.BytesIO()
    for side in (diff.add, diff.remove):
        write_varint(w, len(side))
        for op, entry in side.items():
            write_outpoint(w, op)
            write_utxo_entry(w, entry)
    return w.getvalue()


def decode_utxo_diff(data: bytes):
    from kaspa_tpu.consensus.utxo import UtxoDiff

    r = io.BytesIO(data)
    diff = UtxoDiff()
    for side in (diff.add, diff.remove):
        for _ in range(read_varint(r)):
            op = read_outpoint(r)
            side[op] = read_utxo_entry(r)
    return diff


def encode_outpoint(op: TransactionOutpoint) -> bytes:
    return op.transaction_id + struct.pack("<I", op.index)


def decode_outpoint(data: bytes) -> TransactionOutpoint:
    return TransactionOutpoint(data[:32], struct.unpack("<I", data[32:36])[0])


def encode_block(block) -> bytes:
    w = io.BytesIO()
    write_header(w, block.header)
    write_varint(w, len(block.transactions))
    for tx in block.transactions:
        write_tx(w, tx)
    return w.getvalue()


def decode_block(data: bytes):
    from kaspa_tpu.consensus.model.block import Block

    r = io.BytesIO(data)
    header = read_header(r)
    txs = [read_tx(r) for _ in range(read_varint(r))]
    return Block(header, txs)


def encode_muhash(mh) -> bytes:
    """Both accumulators (normalization is deferred in consensus use)."""
    return mh.numerator.to_bytes(384, "little") + mh.denominator.to_bytes(384, "little")


def decode_muhash(data: bytes):
    from kaspa_tpu.crypto.muhash import MuHash

    mh = MuHash(int.from_bytes(data[:384], "little"), int.from_bytes(data[384:768], "little"))
    return mh


# --- reachability snapshot (clean-shutdown fast-restart path) -------------


def encode_reach_node(reach, h: bytes) -> bytes:
    """One reachability node's persistent record (interval, tree links, FCS,
    height, DAG relations) — the per-flush incremental unit; the column of
    these records is the crash-safe source of truth (the reference's
    always-persistent reachability stores)."""
    w = io.BytesIO()
    lo, hi = reach._interval[h]
    write_varint(w, lo)
    write_varint(w, hi)
    write_option(w, reach._parent.get(h), write_hash)
    w.write(encode_hash_list(reach._children.get(h, [])))
    w.write(encode_hash_list(reach._fcs.get(h, [])))
    write_varint(w, reach._height.get(h, 0))
    w.write(encode_hash_list(reach._dag_parents.get(h, [])))
    w.write(encode_hash_list(reach._dag_children.get(h, [])))
    return w.getvalue()


def decode_reach_node(reach, h: bytes, raw: bytes) -> None:
    """Install one node record into a ReachabilityService being loaded."""
    r = io.BytesIO(raw)
    lo = read_varint(r)
    hi = read_varint(r)
    reach._interval[h] = (lo, hi)
    has_parent = _read_exact(r, 1) == b"\x01"
    reach._parent[h] = read_hash(r) if has_parent else None
    reach._children[h] = read_hash_list(r)
    reach._fcs[h] = read_hash_list(r)
    reach._height[h] = read_varint(r)
    reach._dag_parents[h] = read_hash_list(r)
    reach._dag_children[h] = read_hash_list(r)


def decode_reachability(raw: bytes, reach) -> None:
    """Restore a ReachabilityService from a legacy full-state snapshot blob
    (pre-RN-column DBs only; the matching encoder was retired with it)."""
    r = io.BytesIO(raw)
    n = read_varint(r)
    reach._interval = {}
    reach._parent = {}
    reach._children = {}
    reach._fcs = {}
    reach._height = {}
    reach._dag_parents = {}
    reach._dag_children = {}
    for _ in range(n):
        h = read_hash(r)
        lo = read_varint(r)
        hi = read_varint(r)
        reach._interval[h] = (lo, hi)
        has_parent = _read_exact(r, 1) == b"\x01"
        reach._parent[h] = read_hash(r) if has_parent else None
        reach._children[h] = read_hash_list(r)
        reach._fcs[h] = read_hash_list(r)
        reach._height[h] = read_varint(r)
        reach._dag_parents[h] = read_hash_list(r)
        reach._dag_children[h] = read_hash_list(r)
    reach._reindex_root = read_hash(r)
