"""The consensus engine: header/body/virtual processing over the block DAG.

Re-design of the reference's 4-stage pipeline (consensus/src/pipeline/) as
explicit processing stages sharing a ConsensusStorage.  This module is the
host-side control path; batchable crypto goes to the device through the
batch layers — signature/script checks via txscript.batch (every chain
block), muhash element products via MuHash.add_transactions_batch, which
tree-reduces on device above its element-count threshold.

Stage semantics follow the reference call stack (SURVEY.md §3.2):
- header stage: in-isolation checks -> parent relations -> GHOSTDAG ->
  difficulty/DAA window checks -> PoW -> median time, mergeset limit,
  blue score/work -> commit (header_processor/processor.rs:296-313)
- body stage: merkle root, coinbase form, tx in-isolation checks
  (body_processor/)
- virtual stage: sink search, chain-block UTXO verification with muhash
  commitments, virtual resolution (virtual_processor/processor.rs:261-384,
  utxo_validation.rs)
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kaspa_tpu.consensus import hashing as chash
from kaspa_tpu.consensus import serde
from kaspa_tpu.consensus.model import (
    SUBNETWORK_ID_COINBASE,
    Header,
    ScriptPublicKey,
    Transaction,
    TransactionOutpoint,
)
from kaspa_tpu.consensus.mass import BlockMassLimits
from kaspa_tpu.consensus.model.block import Block
from kaspa_tpu.consensus.params import Params
from kaspa_tpu.consensus.processes.coinbase import BlockRewardData, CoinbaseData, CoinbaseManager, MinerData
from kaspa_tpu.consensus.processes.block_depth import BlockDepthManager
from kaspa_tpu.consensus.processes.ghostdag import GhostdagManager
from kaspa_tpu.consensus.processes.pruning import PruningPointManager
from kaspa_tpu.consensus.processes.transaction_validator import (
    FLAG_FULL,
    FLAG_SKIP_SCRIPTS,
    TransactionValidator,
    TxRuleError,
)
from kaspa_tpu.consensus.processes.window import DIFFICULTY_WINDOW, MEDIAN_TIME_WINDOW, SampledWindowManager
from kaspa_tpu.consensus.reachability import ORIGIN, ReachabilityService
from kaspa_tpu.consensus.stores import (
    ConsensusStorage,
    GhostdagData,
    StatusesStore,
)
from kaspa_tpu.consensus.utxo import UtxoDiff, UtxoView, apply_diff, unapply_diff
from kaspa_tpu.crypto import merkle
from kaspa_tpu.crypto.muhash import MuHash
from kaspa_tpu.observability import flight, trace


class RuleError(Exception):
    pass


def _neg_bytes(b: bytes) -> bytes:
    """Lexicographic inversion so a min-heap orders hashes descending."""
    return bytes(255 - x for x in b)


def _encode_anchor_segment(segment: list) -> bytes:
    """[(blue_score, block_hash)] — the bootstrap shortcut-anchor chain
    persisted under the meta column (headers live in the header store)."""
    import struct as _struct

    out = [_struct.pack("<I", len(segment))]
    for bs, blk in segment:
        out.append(_struct.pack("<Q", bs) + blk)
    return b"".join(out)


def _decode_anchor_segment(raw: bytes) -> list:
    import struct as _struct

    (n,) = _struct.unpack_from("<I", raw, 0)
    off = 4
    out = []
    for _ in range(n):
        (bs,) = _struct.unpack_from("<Q", raw, off)
        out.append((bs, raw[off + 8 : off + 40]))
        off += 40
    return out


def _FinalityConflictNotification(tip: bytes, finality_point: bytes):
    from kaspa_tpu.notify.notifier import Notification

    return Notification(
        "finality-conflict",
        {"violating_tip": tip.hex(), "finality_point": finality_point.hex()},
    )


@dataclass
class VirtualState:
    """reference: consensus/src/model/stores/virtual_state.rs"""

    parents: list[bytes]
    ghostdag_data: GhostdagData
    daa_score: int
    bits: int
    past_median_time: int
    accepted_tx_ids: list[bytes]
    mergeset_rewards: dict
    mergeset_non_daa: set


class Consensus:
    def __init__(self, params: Params, db=None, cache_policy=None):
        """``db``: optional storage.kv.KvStore — attaches crash-safe
        persistence (bounded read-through caches + atomic batch flush per
        block).  A non-empty DB restores the consensus state (restart-resume)
        with O(tips + caches) work; an empty one is initialized with genesis.
        ``cache_policy``: stores.CachePolicy bounding per-store decode caches
        (defaults applied when a DB is attached)."""
        self.params = params
        self.storage = ConsensusStorage(db, cache_policy)
        self.reachability = ReachabilityService()
        # reachability rides every flush batch: dirty nodes are staged so a
        # kill -9 restart decodes the RN column instead of rebuilding
        self.storage.pre_flush_hooks.append(self._stage_reachability_dirty)
        self.ghostdag_manager = GhostdagManager(
            params.genesis.hash,
            params.ghostdag_k,
            self.storage.ghostdag,
            self.storage.relations,
            self.storage.headers,
            self.reachability,
        )
        self.window_manager = SampledWindowManager(
            params.genesis.hash,
            params.genesis.bits,
            params.genesis.timestamp,
            self.storage.ghostdag,
            self.storage.headers,
            params.max_difficulty_target,
            params.target_time_per_block,
            params.difficulty_window_size,
            params.min_difficulty_window_size,
            params.difficulty_sample_rate,
            params.past_median_time_window_size,
            params.past_median_time_sample_rate,
        )
        self.coinbase_manager = CoinbaseManager(
            max_coinbase_payload_len=params.max_coinbase_payload_len,
            deflationary_phase_daa_score=params.deflationary_phase_daa_score,
            pre_deflationary_phase_base_subsidy=params.pre_deflationary_phase_base_subsidy,
            bps=params.bps,
        )
        self.transaction_validator = TransactionValidator(params)
        self.depth_manager = BlockDepthManager(
            params.merge_depth, params.finality_depth, params.genesis.hash, self.storage.ghostdag,
            self.reachability, self.storage.depth,
        )
        self.pruning_point_manager = PruningPointManager(
            params.pruning_depth, params.finality_depth, params.genesis.hash, self.storage.headers,
            self.storage.pruning_samples,
        )
        from kaspa_tpu.consensus.processes.parents_builder import ParentsManager

        self.storage.headers.max_block_level = params.max_block_level
        self.parents_manager = ParentsManager(
            params.max_block_level,
            params.genesis.hash,
            self.storage.headers,
            self.reachability,
            self.storage.relations,
        )
        from kaspa_tpu.consensus.processes.pruning_processor import PruningProcessor

        self.pruning_processor = PruningProcessor(self, is_archival=getattr(params, "is_archival", False))
        from kaspa_tpu.consensus.processes.pruning_proof import PruningProofManager

        self.pruning_proof_manager = PruningProofManager(self)
        from kaspa_tpu.notify.notifier import ConsensusNotificationRoot

        self.notification_root = ConsensusNotificationRoot()
        from kaspa_tpu.consensus.counters import ProcessingCounters

        self.counters = ProcessingCounters()

        # speculative chain-state precompute (pipeline/speculative.py):
        # attached by ConsensusPipeline when enabled; None = synchronous
        # chain verification only (serial replay, tests, direct callers)
        self.speculative = None

        # virtual/UTXO state.  The per-block columns live in ConsensusStorage
        # as bounded read-through caches (CachedDbAccess); these attributes
        # alias them so processing code reads naturally.
        self.tips: set[bytes] = set()
        self.utxo_set = self.storage.utxo_set  # positioned at self.utxo_position
        self.utxo_position: bytes = params.genesis.hash
        self.utxo_diffs = self.storage.utxo_diffs  # chain-validated block -> diff vs selected parent position
        self.multisets = self.storage.multisets
        self.acceptance_data = self.storage.acceptance
        self.virtual_state: VirtualState | None = None
        self.daa_excluded = self.storage.daa_excluded
        # net UTXO delta accumulated between virtual resolutions (reorg-safe):
        # emitted as one UtxosChanged per resolve
        self._acc_added: dict = {}
        self._acc_removed: dict = {}
        self.reach_mergesets = self.storage.reach_mergesets

        # finality conflicts observed (tips heavier than the sink that
        # exclude the finality point): tip -> "active" | "resolved".
        # Entries are never dropped while the tip remains heavier, so an
        # acknowledged conflict is not re-notified every resolve cycle
        self._finality_conflicts: dict[bytes, str] = {}

        # KIP-21: materialized lane state + selected-chain index, both moved
        # in lock-step with utxo_position (smt-store / selected_chain_store)
        from kaspa_tpu.consensus.smt_processor import LaneTracker

        self.lane_tracker = LaneTracker(self.storage, params.finality_depth, params.genesis.hash)
        self.selected_chain: list[tuple[int, bytes]] = [(0, params.genesis.hash)]
        # chain linkage for below-pruning-point anchor-segment blocks whose
        # ghostdag records do not exist (proof bootstrap) or were re-rooted
        # by pruning: block -> selected parent.  Their headers live in the
        # ordinary header store.
        self._segment_prev: dict[bytes, bytes] = {}

        if self.storage.is_initialized():
            self._load_state()
        else:
            self._insert_genesis()

    # ------------------------------------------------------------------
    # genesis
    # ------------------------------------------------------------------

    def _insert_genesis(self):
        g = self.params.genesis
        override = self.params.genesis_override
        if override is not None:
            header = override.header
            genesis_txs = list(override.transactions)
        else:
            header = Header(
                version=g.version,
                parents_by_level=[[]],
                hash_merkle_root=b"\x00" * 32,
                accepted_id_merkle_root=b"\x00" * 32,
                utxo_commitment=MuHash().finalize(),
                timestamp=g.timestamp,
                bits=g.bits,
                nonce=0,
                daa_score=g.daa_score,
                blue_work=0,
                blue_score=0,
                pruning_point=g.hash,
            )
            header._hash_cache = g.hash
            genesis_txs = [
                Transaction(
                    0, [], [], 0, SUBNETWORK_ID_COINBASE, 0,
                    self.coinbase_manager.serialize_coinbase_payload(CoinbaseData(0, 0, MinerData(ScriptPublicKey(0, b"")))),
                )
            ]
        self.storage.headers.insert(header)
        self.storage.relations.insert(g.hash, [ORIGIN])
        self.storage.ghostdag.insert(g.hash, self.ghostdag_manager.genesis_ghostdag_data())
        self.reachability.add_block(g.hash, ORIGIN, [], [ORIGIN])
        self._set_reach_mergeset(g.hash, [])
        self.storage.block_transactions.insert(g.hash, genesis_txs)
        self.storage.statuses.set(g.hash, StatusesStore.STATUS_UTXO_VALID)
        self._set_multiset(g.hash, MuHash())
        self._set_utxo_diff(g.hash, UtxoDiff())
        self._set_daa_excluded(g.hash, set())
        self.tips = {g.hash}
        self._persist_tips()
        self.storage.put_meta(b"init", b"1")
        self._resolve_virtual()
        self.storage.flush()

    # ------------------------------------------------------------------
    # persistence (stage aux state alongside the write-through stores;
    # reference: consensus/src/consensus/storage.rs + database/src/access.rs)
    # ------------------------------------------------------------------

    def _set_multiset(self, block: bytes, ms: MuHash) -> None:
        self.multisets[block] = ms

    def _set_utxo_diff(self, block: bytes, diff: UtxoDiff) -> None:
        self.utxo_diffs[block] = diff

    def _set_acceptance(self, block: bytes, accepted_ids: list[bytes]) -> None:
        self.acceptance_data[block] = accepted_ids

    def _set_daa_excluded(self, block: bytes, excluded: set) -> None:
        self.daa_excluded[block] = excluded

    def _set_reach_mergeset(self, block: bytes, mergeset: list[bytes]) -> None:
        """Persist the exact mergeset registered with reachability, so the
        load-time rebuild replays identical FCS state even after pruning
        filtered the ghostdag data (the blues[0]==sp invariant no longer
        holds for blocks whose selected parent was pruned)."""
        self.reach_mergesets[block] = mergeset

    def _rebind_reachability(self) -> None:
        """Point every manager at a replacement ReachabilityService
        (snapshot-recovery path)."""
        self.ghostdag_manager.reachability = self.reachability
        self.depth_manager.reachability = self.reachability
        self.parents_manager.reachability = self.reachability

    def _stage_reachability_dirty(self) -> None:
        """Stage the reachability nodes mutated since the last flush into
        the RN column (pre-flush hook: the records join the same atomic
        batch as the block state that produced them).  This keeps the
        persistent reachability index the source of truth — crash restarts
        decode it instead of rebuilding, matching the reference's
        store-backed design (processes/reachability/)."""
        from kaspa_tpu.consensus.stores import PREFIX_REACH_NODE

        r = self.reachability
        if self.storage.db is None or (not r._dirty and not r._deleted):
            return
        for h in r._deleted:
            self.storage.stage(PREFIX_REACH_NODE + h, None)
        for h in r._dirty:
            self.storage.stage(PREFIX_REACH_NODE + h, serde.encode_reach_node(r, h))
        self.storage.put_meta(b"reach_reindex_root", r._reindex_root)
        r._dirty.clear()
        r._deleted.clear()

    def save_reachability_snapshot(self) -> None:
        """Orderly-shutdown persistence.  With the incremental RN column the
        crash and clean paths are identical — this just flushes any staged
        remainder (kept for API compatibility with earlier DB layouts)."""
        if self.storage.db is None:
            return
        self.storage.flush()

    def _persist_tips(self) -> None:
        if self.storage.db is not None:
            self.storage.put_meta(b"tips", serde.encode_hash_list(sorted(self.tips)))

    def _persist_utxo_position(self) -> None:
        if self.storage.db is not None:
            self.storage.put_meta(b"utxo_position", self.utxo_position)

    # ------------------------------------------------------------------
    # KIP-21 lane-state transfer (IBD / trusted bootstrap)
    # ------------------------------------------------------------------

    def _chain_parent(self, block: bytes) -> bytes | None:
        """Selected parent along the final (pruned-history) chain.

        The anchor archive takes precedence: pruning re-roots surviving
        ghostdag records whose parents were deleted to ORIGIN, while the
        archive records the true chain linkage before deletion (history
        below the pruning point is final, so archived links never go
        stale).  Above the archive, live ghostdag is authoritative."""
        sp = self._segment_prev.get(block)
        if sp is not None:
            return sp
        if self.storage.ghostdag.has(block):
            sp = self.storage.ghostdag.get_selected_parent(block)
            if sp != ORIGIN:
                return sp
        return None

    def export_pp_lane_state(self):
        """Lane state at the pruning point, for IBD serving — the donor side
        of flows/src/ibd/flow.rs:145-150 sync_new_smt_state.

        Returns None when the PP is pre-Toccata (the receiver starts empty,
        mirroring the reference's set_pruning_smt_stable fast path), else
        ``(meta, lanes, segment)``:

        - meta: {lanes_root, pcd, parent_seq_commit, shortcut_block,
          inactivity_shortcut} — the reference's 96-byte SmtMetadata plus
          the shortcut identity;
        - lanes: sorted [(lane_key, tip, blue_score)] at the PP;
        - segment: the selected-chain HEADERS from the PP's
          inactivity-shortcut block up to the PP itself — the receiver's
          shortcut anchors for the first finality-window of post-bootstrap
          chain blocks.  Whole headers, not bare value pairs: each is bound
          to the proof-validated PP by the parent-hash chain, so a peer
          cannot substitute anchor values without mining real alternative
          headers in the PP's past.  (The reference reads the same data
          from headers it retains below the PP.)
        """
        from kaspa_tpu.consensus.smt_processor import ZERO_HASH

        pp = self.pruning_processor.pruning_point
        if pp == self.params.genesis.hash:
            return None
        hdr = self.storage.headers.get(pp)
        if not self.params.toccata_active(hdr.daa_score):
            return None
        build = self.lane_tracker.builds.try_get(pp)
        if build is None:
            return None

        # rewind the materialized lane tips from the current UTXO position
        # back to the PP by applying per-chain-block undo records (the
        # in-RAM selected_chain index is trimmed, so walk storage)
        tips = dict(self.lane_tracker.lane_tips)
        cur = self.utxo_position
        while cur != pp:
            b = self.lane_tracker.builds.try_get(cur)
            if b is not None:
                for lk, prev in b.undo.items():
                    if prev is None:
                        tips.pop(lk, None)
                    else:
                        tips[lk] = prev
            cur = self._chain_parent(cur)
            if cur is None:
                return None  # chain walk left our materialized history

        if not self.storage.headers.has(build.shortcut_block):
            return None  # anchor headers not retained (pre-upgrade DB)
        sc_hdr = self.storage.headers.get(build.shortcut_block)
        inactivity = (
            sc_hdr.accepted_id_merkle_root
            if self.params.toccata_active(sc_hdr.daa_score)
            else ZERO_HASH
        )
        # the seq-commit chains from the GHOSTDAG selected parent (which the
        # post-Toccata chain rule also pins as direct_parents()[0])
        parent = (
            self.storage.ghostdag.get_selected_parent(pp)
            if self.storage.ghostdag.has(pp)
            else hdr.direct_parents()[0]
        )
        meta = {
            "lanes_root": build.lanes_root,
            "pcd": build.payload_ctx_digest,
            "parent_seq_commit": self.storage.headers.get(parent).accepted_id_merkle_root,
            "shortcut_block": build.shortcut_block,
            "inactivity_shortcut": inactivity,
        }

        # anchor segment: chain headers from shortcut(pp) to pp inclusive
        segment = []
        cur = pp
        while True:
            if not self.storage.headers.has(cur):
                return None
            segment.append(self.storage.headers.get(cur))
            if cur == build.shortcut_block or cur == self.params.genesis.hash:
                break
            cur = self._chain_parent(cur)
            if cur is None:
                return None
        segment.reverse()
        lanes = sorted((lk, tip, bs) for lk, (tip, bs) in tips.items())
        return meta, lanes, segment

    def import_pp_lane_state(self, meta: dict, lanes: list, segment: list) -> None:
        """Install a transferred pruning-point lane state into this (freshly
        proof-bootstrapped) consensus — the receiving side of
        sync_new_smt_state / import_pruning_point_smt.

        The lane set and metadata are verified against the proof-validated
        PP header's sequencing commitment (verify_lane_state), and the
        anchor-segment headers are verified as a parent-hash chain ending
        at the PP: header[i].hash must appear in header[i+1]'s direct
        parents and the last header must BE the proven PP header, so every
        anchor's (daa_score, accepted_id_merkle_root, blue_score) is bound
        through block hashes to the proof.
        """
        from kaspa_tpu.consensus.smt_processor import LaneStateError, ZERO_HASH, verify_lane_state

        pp = self.pruning_processor.pruning_point
        hdr = self.storage.headers.get(pp)
        # wire-decoded headers carry a cached hash restored from peer bytes;
        # recompute so every hash-binding check below is over real contents
        for h in segment:
            h.invalidate_cache()
        if not segment or segment[-1].hash != pp:
            raise LaneStateError("anchor segment must end at the pruning point")
        if segment[0].hash != meta["shortcut_block"]:
            raise LaneStateError("anchor segment must start at the shortcut block")
        for a, b in zip(segment, segment[1:]):
            # post-Toccata chain blocks pin the selected parent as the FIRST
            # direct parent (utxo_validation.rs:219-238), which rules out a
            # donor routing the segment through non-selected parents; for
            # pre-Toccata hops membership is the strongest header-level
            # check, and such anchors fold to ZERO regardless
            if self.params.toccata_active(b.daa_score):
                if b.direct_parents()[0] != a.hash:
                    raise LaneStateError("anchor segment hop is not the selected parent")
            elif a.hash not in b.direct_parents():
                raise LaneStateError("anchor segment headers do not form a parent chain")
            if b.blue_score <= a.blue_score:
                raise LaneStateError("anchor segment blue scores must strictly ascend")
        if len(segment) > 1 and self.storage.ghostdag.has(pp):
            if self.storage.ghostdag.get_selected_parent(pp) != segment[-2].hash:
                raise LaneStateError("anchor segment disagrees with the PP's selected parent")
        # the seq-commit chains from the GHOSTDAG selected parent
        # (smt_processor.compute); trusted ghostdag gives it for the PP
        par = (
            self.storage.ghostdag.get_selected_parent(pp)
            if self.storage.ghostdag.has(pp)
            else hdr.direct_parents()[0]
        )
        if self.storage.headers.has(par):
            if meta["parent_seq_commit"] != self.storage.headers.get(par).accepted_id_merkle_root:
                raise LaneStateError("metadata parent commitment contradicts the PP parent header")
        # the claimed folded shortcut value must equal what the (now hash-
        # bound) shortcut header itself folds to
        sc_hdr = segment[0]
        expected_fold = (
            sc_hdr.accepted_id_merkle_root
            if self.params.toccata_active(sc_hdr.daa_score)
            else ZERO_HASH
        )
        if meta["inactivity_shortcut"] != expected_fold:
            raise LaneStateError("metadata inactivity shortcut contradicts the shortcut header")
        verify_lane_state(hdr, meta, lanes)

        self.lane_tracker.import_state(pp, hdr, meta, lanes)
        pairs = []
        for i, h in enumerate(segment):
            if not self.storage.headers.has(h.hash):
                self.storage.headers.insert(h)
                self.storage.statuses.set(h.hash, StatusesStore.STATUS_HEADER_ONLY)
            if i > 0:
                self._segment_prev[h.hash] = segment[i - 1].hash
            pairs.append((h.blue_score, h.hash))
        self.selected_chain = pairs
        if self.storage.db is not None:
            self.storage.put_meta(b"lane_anchor_segment", _encode_anchor_segment(pairs))
        self.storage.flush()

    def _load_state(self) -> None:
        """Restore consensus state from the attached DB.

        Every store column is read-through (nothing is bulk-decoded at
        startup); the only O(retained-history) work is rebuilding the
        in-memory reachability index — a keys-only relations scan plus one
        transient ghostdag decode per block for the topological order.
        Ascending (blue_work, hash) is a total topological order of the DAG
        — every ancestor has strictly smaller blue work — and unlike a Kahn
        walk over relations it stays valid when pruning removed intermediate
        blocks (a kept block's mergeset members always sort before it)."""
        from kaspa_tpu.consensus.stores import PREFIX_GHOSTDAG, PREFIX_RELATIONS

        self.utxo_position = self.storage.get_meta(b"utxo_position") or self.params.genesis.hash
        self.tips = set(serde.decode_hash_list_bytes(self.storage.get_meta(b"tips")))
        self.pruning_processor.load()

        engine = self.storage.db.engine
        g = self.params.genesis.hash
        restored = False
        # primary path: the incrementally-persisted RN column — written at
        # every flush, so crash and clean restarts are both O(decode)
        from kaspa_tpu.consensus.stores import PREFIX_REACH_NODE

        try:
            n_nodes = 0
            for key, raw in engine.items_prefix(PREFIX_REACH_NODE):
                serde.decode_reach_node(self.reachability, key, raw)
                n_nodes += 1
            if n_nodes:
                root = self.storage.get_meta(b"reach_reindex_root")
                if root is not None:
                    self.reachability._reindex_root = root
                # the column IS the persisted state: nothing is dirty
                self.reachability._dirty.clear()
                restored = True
        except Exception:  # noqa: BLE001 - corrupt column must not brick startup
            self.reachability = ReachabilityService()
            self._rebind_reachability()
            # purge the corrupt column so the rebuild's rewrite converges
            # (stale orphan records would otherwise throw on every restart)
            for key in list(engine.keys_prefix(PREFIX_REACH_NODE)):
                self.storage.stage(PREFIX_REACH_NODE + key, None)
            restored = False
        if not restored:
            # legacy clean-shutdown blob (pre-RN-column DBs)
            snapshot = self.storage.get_meta(b"reach_snapshot")
            if snapshot is not None and self.storage.get_meta(b"reach_clean") == b"1":
                try:
                    serde.decode_reachability(snapshot, self.reachability)
                    # migrate: everything is dirty so the next flush writes
                    # the whole RN column; drop the legacy blob
                    self.reachability._dirty = set(self.reachability._interval.keys())
                    restored = True
                except Exception:  # noqa: BLE001 - corrupt/skewed snapshot
                    self.reachability = ReachabilityService()
                    self._rebind_reachability()
                from kaspa_tpu.consensus.stores import PREFIX_META

                self.storage.stage(PREFIX_META + b"reach_snapshot", None)
                self.storage.put_meta(b"reach_clean", b"0")
        if not restored:
            # transient (blue_work, hash, selected_parent) triples: one
            # ghostdag decode per block — the walk needs only selected_parent
            order = []
            for blk in engine.keys_prefix(PREFIX_RELATIONS):
                raw = engine.get(PREFIX_GHOSTDAG + blk)
                if raw:
                    gd = serde.decode_ghostdag(raw)
                    order.append((gd.blue_work, blk, gd.selected_parent))
                else:
                    order.append((0, blk, ORIGIN))
            order.sort()
            live = {blk for _, blk, _sp in order}
            for _, blk, sp in order:
                if blk == g:
                    self.reachability.add_block(blk, ORIGIN, [], [ORIGIN])
                else:
                    parents = self.storage.relations.get_parents(blk)
                    live_parents = [p for p in parents if p in live] or [sp]
                    self.reachability.add_block(
                        blk, sp, self.reach_mergesets.get(blk, []), live_parents
                    )
        # KIP-21 lane state resumes lazily from its persisted snapshot
        self.lane_tracker.load()
        # selected-chain index: only the finality window is ever queried
        # (inactivity-shortcut anchors reach back finality_depth+1 at most)
        chain = []
        cur = self.utxo_position
        limit = self.params.finality_depth + 1025
        while self.storage.ghostdag.has(cur) and len(chain) <= limit:
            chain.append((self.storage.ghostdag.get_blue_score(cur), cur))
            if cur == g:
                break
            cur = self.storage.ghostdag.get_selected_parent(cur)
        self.selected_chain = chain[::-1]
        # prepend the bootstrap anchor segment (below-PP shortcut anchors
        # whose headers were imported with the lane state) where it reaches
        # below the rebuilt chain's base
        raw_seg = self.storage.get_meta(b"lane_anchor_segment")
        if raw_seg:
            # defensively truncate a stale blob at the first missing header:
            # filtering interior holes would splice non-parents together in
            # _segment_prev and poison future exports
            decoded = _decode_anchor_segment(raw_seg)
            first_live = next(
                (i for i, (_, blk) in enumerate(decoded) if self.storage.headers.has(blk)),
                len(decoded),
            )
            entries = decoded[first_live:]
            if any(not self.storage.headers.has(blk) for _, blk in entries):
                entries = []  # interior hole: unusable without false links
            for i, (bs, blk) in enumerate(entries):
                if i > 0:
                    self._segment_prev[blk] = entries[i - 1][1]
            base_bs = self.selected_chain[0][0] if self.selected_chain else None
            prefix = [(bs, blk) for bs, blk in entries if base_bs is None or bs < base_bs]
            self.selected_chain = prefix + self.selected_chain

        self._resolve_virtual()
        # the load-time resolve may reposition the UTXO set; flush that
        self.storage.flush()

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    def validate_and_insert_block(self, block: Block) -> str:
        """Full pipeline for one block; returns the resulting block status.

        Synchronous path (serial replay, tests, direct callers); the
        concurrent pipeline inlines these stages in its own workers and
        never enters here, so both paths can own their block's flight
        trace without double-recording."""
        existing = self.storage.statuses.get(block.hash)
        if existing is not None and existing != StatusesStore.STATUS_HEADER_ONLY:
            return existing  # duplicate submission: no reprocessing, no events
        ctx = flight.begin(block.hash) if flight.enabled() else None
        try:
            with trace.span("consensus.validate", parent=ctx):
                self.counters.inc_blocks_submitted()
                if self._process_header(block.header):
                    self.counters.inc_headers()
                self._process_body(block)
                self.counters.inc_bodies()
                self.counters.inc_txs(len(block.transactions))
                self.notification_root.notify_block_added(block)
                self._update_tips(block.hash)
                self._resolve_virtual()
                status = self.storage.statuses.get(block.hash)
                self.storage.flush()
        except BaseException:
            if ctx is not None:
                flight.end(block.hash, "error")
            raise
        if ctx is not None:
            flight.end(block.hash, "ok")
        return status

    def validate_and_insert_header(self, header) -> str:
        """Headers-first intake (IBD): header validation + commit without a
        body; the block completes later via validate_and_insert_block."""
        existing = self.storage.statuses.get(header.hash)
        if existing is not None:
            return existing
        self._process_header(header)
        self.counters.inc_headers()
        self.storage.flush()
        return self.storage.statuses.get(header.hash)

    def sink(self) -> bytes:
        return self.virtual_state.ghostdag_data.selected_parent

    def get_virtual_daa_score(self) -> int:
        return self.virtual_state.daa_score

    # ------------------------------------------------------------------
    # header stage (pipeline/header_processor/)
    # ------------------------------------------------------------------

    def _process_header(self, header: Header) -> bool:
        """Returns True if the header was newly processed (False if known)."""
        block_hash = header.hash
        if self.storage.headers.has(block_hash) and self.storage.statuses.get(block_hash) is not None:
            return False  # known
        parents = header.direct_parents()

        # in isolation (pre_ghostdag_validation.rs)
        if not parents:
            raise RuleError("block has no parents")
        if len(parents) > self.params.max_block_parents:
            raise RuleError(f"too many parents {len(parents)}")
        if len(set(parents)) != len(parents):
            raise RuleError("duplicate parents")

        # parent relations
        for p in parents:
            if not self.storage.headers.has(p):
                raise RuleError(f"missing parent {p.hex()}")
            if self.storage.statuses.get(p) == StatusesStore.STATUS_INVALID:
                raise RuleError("invalid parent")

        # GHOSTDAG
        gd = self.ghostdag_manager.ghostdag(parents)

        # difficulty & DAA (pre_pow_validation.rs)
        daa_window = self.window_manager.block_daa_window(gd)
        expected_bits = self.window_manager.calculate_difficulty_bits(gd, daa_window)
        if header.bits != expected_bits:
            raise RuleError(f"unexpected difficulty bits {header.bits:#x} != {expected_bits:#x}")
        if header.daa_score != daa_window.daa_score:
            raise RuleError(f"unexpected daa score {header.daa_score} != {daa_window.daa_score}")
        # header version in context (post_pow_validation.rs:105-111 WrongBlockVersion):
        # the expected version is fork-activation-dependent, so it's checked against
        # the contextually-validated daa score rather than in isolation
        expected_version = self.params.block_version(header.daa_score)
        if header.version != expected_version:
            raise RuleError(f"wrong block version {header.version} != {expected_version}")

        # PoW (consensus/pow): gated by skip_proof_of_work (test/sim configs)
        if not self.params.skip_proof_of_work:
            from kaspa_tpu.crypto.powhash import check_pow

            if not check_pow(header):
                raise RuleError("invalid proof of work")

        # post-pow (post_pow_validation.rs)
        pmt, _w = self.window_manager.calc_past_median_time(gd)
        if header.timestamp <= pmt:
            raise RuleError(f"timestamp {header.timestamp} not later than past median time {pmt}")
        if gd.mergeset_size() > self.params.mergeset_size_limit:
            raise RuleError(f"mergeset size {gd.mergeset_size()} above limit")
        if header.blue_score != gd.blue_score:
            raise RuleError(f"blue score mismatch {header.blue_score} != {gd.blue_score}")
        if header.blue_work != gd.blue_work:
            raise RuleError(f"blue work mismatch {header.blue_work} != {gd.blue_work}")
        # bounded merge depth (post_pow_validation.rs check_bounded_merge_depth)
        try:
            mdr, fp = self.depth_manager.check_bounded_merge_depth(gd, self.pruning_processor.pruning_point)
        except Exception as e:
            raise RuleError(f"violating bounded merge depth: {e}") from e

        # commit (header_processor/processor.rs:361)
        self.storage.headers.insert(header)
        self.storage.relations.insert(block_hash, parents)
        self.storage.ghostdag.insert(block_hash, gd)
        reach_mergeset = list(gd.unordered_mergeset_without_selected_parent())
        self.reachability.add_block(block_hash, gd.selected_parent, reach_mergeset, parents)
        self._set_reach_mergeset(block_hash, reach_mergeset)
        self._set_daa_excluded(block_hash, daa_window.mergeset_non_daa)
        self.depth_manager.store(block_hash, mdr, fp)
        self.window_manager.cache_block_window(block_hash, DIFFICULTY_WINDOW, daa_window.window)
        # cache the median-time window too: children (and every virtual
        # resolve whose sink is this block) then extend it incrementally
        # instead of re-walking the selected chain from scratch
        self.window_manager.cache_block_window(block_hash, MEDIAN_TIME_WINDOW, _w)
        self.storage.statuses.set(block_hash, StatusesStore.STATUS_HEADER_ONLY)
        return True

    # ------------------------------------------------------------------
    # body stage (pipeline/body_processor/)
    # ------------------------------------------------------------------

    def _process_body(self, block: Block) -> None:
        txs = block.transactions
        if not txs:
            raise RuleError("block has no transactions (header-only unsupported in this path)")
        # merkle root (body_validation_in_isolation.rs)
        computed = merkle.calc_hash_merkle_root(txs)
        if computed != block.header.hash_merkle_root:
            raise RuleError("bad merkle root")
        if not txs[0].is_coinbase():
            raise RuleError("first tx is not coinbase")
        for tx in txs[1:]:
            if tx.is_coinbase():
                raise RuleError("second coinbase")
        coinbase_data = self.coinbase_manager.deserialize_coinbase_payload(txs[0].payload)
        gd = self.storage.ghostdag.get(block.hash)
        if coinbase_data.blue_score != gd.blue_score:
            raise RuleError("coinbase blue score mismatch")
        # per-dimension block mass limits (body_validation_in_isolation.rs
        # check_block_mass): compute/transient from the calculator, storage
        # from the miner commitments
        limits = BlockMassLimits.with_shared_limit(self.params.max_block_mass)
        total_compute = total_transient = total_storage = 0
        # KIP-21 lane limits (body_validation_in_isolation.rs:100-121): cap
        # occupied subnetwork lanes per block and summed gas per lane.
        # Applied unconditionally — pre-Toccata valid blocks contain only
        # native zero-gas non-coinbase txs, so the caps are vacuous there.
        lanes: dict[bytes, int] = {}  # lane (subnetwork id) -> summed gas
        for tx in txs:
            nc = self.transaction_validator.mass_calculator.calc_non_contextual_masses(tx)
            total_compute += nc.compute_mass
            total_transient += nc.transient_mass
            total_storage += tx.storage_mass
            if total_compute > limits.compute:
                raise RuleError(f"exceeds compute mass limit: {total_compute} > {limits.compute}")
            if total_transient > limits.transient:
                raise RuleError(f"exceeds transient mass limit: {total_transient} > {limits.transient}")
            if total_storage > limits.storage:
                raise RuleError(f"exceeds storage mass limit: {total_storage} > {limits.storage}")
            if not tx.is_coinbase():
                lane = tx.subnetwork_id
                if lane in lanes:
                    gas = lanes[lane] = min(lanes[lane] + tx.gas, (1 << 64) - 1)
                else:
                    if len(lanes) >= self.params.lanes_per_block:
                        raise RuleError(
                            f"exceeds lanes-per-block limit: {len(lanes) + 1} > {self.params.lanes_per_block}"
                        )
                    gas = lanes[lane] = tx.gas
                if gas > self.params.gas_per_lane:
                    raise RuleError(
                        f"exceeds gas-per-lane limit on lane {lane.hex()}: {gas} > {self.params.gas_per_lane}"
                    )
        seen_ids = set()
        seen_outpoints = set()
        created_outpoints = set()
        for tx in txs:
            self.transaction_validator.validate_tx_in_isolation(tx)
            txid = tx.id()
            if txid in seen_ids:
                raise RuleError("duplicate transactions")
            seen_ids.add(txid)
            for inp in tx.inputs:
                # body_validation_in_isolation.rs check_block_double_spends
                if inp.previous_outpoint in seen_outpoints:
                    raise RuleError(f"double spend in same block: {inp.previous_outpoint}")
                seen_outpoints.add(inp.previous_outpoint)
        # check_no_chained_transactions: a tx may not spend an output created
        # in the same block (keeps in-block txs independent -> parallelizable)
        for tx in txs:
            for i in range(len(tx.outputs)):
                created_outpoints.add(TransactionOutpoint(tx.id(), i))
        for op in seen_outpoints:
            if op in created_outpoints:
                raise RuleError(f"chained transaction spending in-block output {op}")
        # in-context: tx lock times vs this block's context
        pmt, _ = self.window_manager.calc_past_median_time(gd)
        hdr = block.header
        for tx in txs[1:]:
            self.transaction_validator.validate_tx_in_header_context(tx, hdr.daa_score, pmt)
        self.storage.block_transactions.insert(block.hash, txs)
        self.storage.statuses.set(block.hash, StatusesStore.STATUS_UTXO_PENDING_VERIFICATION)

    def _update_tips(self, new_block: bytes) -> None:
        parents = set(self.storage.relations.get_parents(new_block))
        self.tips = (self.tips - parents) | {new_block}
        self._persist_tips()

    # ------------------------------------------------------------------
    # virtual stage (pipeline/virtual_processor/)
    # ------------------------------------------------------------------

    def _resolve_virtual(self) -> None:
        # sink search: max blue-work candidate whose chain UTXO-verifies,
        # descending into parents of disqualified candidates
        # (virtual_processor/processor.rs sink_search_algorithm)
        import heapq as _hq

        heap = []  # max-heap via negated key
        seen = set()
        # blue-work sort keys fetched once per candidate: the finality
        # filter, the heap and the virtual-parent sort all reuse them
        blue_work: dict[bytes, int] = {}

        def bw(h):
            w = blue_work.get(h)
            if w is None:
                w = blue_work[h] = self.storage.ghostdag.get_blue_work(h)
            return w

        def push(h):
            if h not in seen:
                seen.add(h)
                _hq.heappush(heap, ((-bw(h), _neg_bytes(h)), h))

        with trace.span("virtual.sink_search"):
            # finality filter (processor.rs:296-316): only tips in the future
            # of the virtual finality point can become the sink; a heavier tip
            # on the wrong side is a FINALITY CONFLICT — surface it, never
            # adopt it
            finality_point = None
            if self.virtual_state is not None:
                pp = self.pruning_processor.pruning_point
                fp = self.depth_manager.calc_finality_point(self.virtual_state.ghostdag_data, pp)
                # virtual_finality_point (processor.rs:386-391): the finality
                # point only anchors when it sits on the pruning point's chain;
                # otherwise the pruning point itself is the anchor (e.g. right
                # after a trusted proof import, where the computed point falls
                # into pruned/disconnected history)
                if (
                    fp != ORIGIN
                    and self.reachability.has(fp)
                    and self.reachability.is_chain_ancestor_of(pp, fp)
                ):
                    finality_point = fp
                elif self.reachability.has(pp):
                    finality_point = pp
            allowed_tips = []
            for t in self.tips:
                if finality_point is not None and not self.reachability.is_dag_ancestor_of(finality_point, t):
                    if t not in self._finality_conflicts and bw(t) > bw(self.sink()):
                        # a chain heavier than ours that excludes our finality
                        # point: requires manual intervention (flow_context.rs
                        # on_finality_conflict -> FinalityConflict notification)
                        self._finality_conflicts[t] = "active"
                        self.notification_root.notify(
                            _FinalityConflictNotification(t, finality_point)
                        )
                    continue
                allowed_tips.append(t)
                push(t)
            sink = None
            while heap:
                _, cand = _hq.heappop(heap)
                st = self.storage.statuses.get(cand)
                if st == StatusesStore.STATUS_UTXO_VALID or (
                    st != StatusesStore.STATUS_DISQUALIFIED and self._ensure_chain_utxo_valid(cand)
                ):
                    sink = cand
                    break
                for p in self.storage.relations.get_parents(cand):
                    if p != ORIGIN:
                        push(p)
            assert sink is not None, "no valid sink found"
            prev_sink = (
                self.virtual_state.ghostdag_data.selected_parent if self.virtual_state is not None else None
            )
            # advance the reachability reindex root toward the agreed chain
            # (inquirer.rs hint_virtual_selected_parent)
            self.reachability.hint_virtual_selected_parent(sink)

            # virtual parents: bounded count of chain-qualified tips from the
            # finality-filtered set, sink first (pick_virtual_parents,
            # processor.rs:1013-1146) — virtual must never merge a tip that
            # excludes the finality point.  Tips already UTXO_VALID skip the
            # requalification walk entirely
            others = sorted(
                (
                    t
                    for t in allowed_tips
                    if t != sink
                    and (
                        self.storage.statuses.get(t) == StatusesStore.STATUS_UTXO_VALID
                        or self._ensure_chain_utxo_valid(t)
                    )
                ),
                key=lambda h: (bw(h), h),
                reverse=True,
            )
            virtual_parents = [sink] + others[: self.params.max_block_parents - 1]
            vgd = self.ghostdag_manager.ghostdag(virtual_parents)
            assert vgd.selected_parent == sink, "virtual selected parent must be the sink"

        with trace.span("virtual.window"):
            # virtual window state: both windows extend the sink's cached
            # windows (difficulty + median-time are cached at header commit),
            # so this is an incremental mergeset merge, not a chain walk
            daa_window = self.window_manager.block_daa_window(vgd)
            bits = self.window_manager.calculate_difficulty_bits(vgd, daa_window)
            pmt, _ = self.window_manager.calc_past_median_time(vgd)

        with trace.span("virtual.commit"):
            # virtual UTXO state: replay virtual mergeset over sink position.
            # The virtual multiset is never read (only chain blocks commit to
            # a utxo_commitment), so skip its device product outright
            self._move_utxo_position(sink)
            ctx = self._calculate_utxo_state(vgd, daa_window.daa_score, need_multiset=False)
            self.virtual_utxo_diff = ctx["mergeset_diff"]
            prev_state = self.virtual_state
            self.virtual_state = VirtualState(
                parents=virtual_parents,
                ghostdag_data=vgd,
                daa_score=daa_window.daa_score,
                bits=bits,
                past_median_time=pmt,
                accepted_tx_ids=ctx["accepted_tx_ids"],
                mergeset_rewards=ctx["mergeset_rewards"],
                mergeset_non_daa=daa_window.mergeset_non_daa,
            )
            # emit score notifications on every resolve; one net UtxosChanged
            # only when the chain state actually moved
            if prev_state is not None:
                self.notification_root.notify_virtual_change(
                    self.virtual_state, list(self._acc_added.items()), list(self._acc_removed.items())
                )
                if prev_sink is not None and prev_sink != sink:
                    self._notify_chain_changed(prev_sink, sink)
            self._acc_added = {}
            self._acc_removed = {}
            # pruning executor: advance the pruning point + delete stale
            # history (pipeline/pruning_processor/processor.rs worker)
            if prev_state is not None:
                self.pruning_processor.advance_if_possible(self.storage.ghostdag.get(sink))

    def _notify_chain_changed(self, prev_sink: bytes, sink: bytes) -> None:
        """VirtualChainChanged (notify/events.rs): the selected-chain path
        delta between resolves, with acceptance data for added blocks.
        The payload is only assembled when someone is subscribed — during
        IBD this would otherwise hex-encode the entire synced history."""
        from kaspa_tpu.notify.notifier import Notification

        if not self.notification_root.has_subscribers("virtual-chain-changed"):
            return
        # single walk down prev_sink's chain to the first block on sink's
        # chain collects `removed` and the common ancestor together
        removed = []
        cur = prev_sink
        while not (self.reachability.has(cur) and self.reachability.is_chain_ancestor_of(cur, sink)):
            removed.append(cur)
            cur = self.storage.ghostdag.get_selected_parent(cur)
        added = list(self.reachability.forward_chain_iterator(cur, sink))
        self.notification_root.notify(
            Notification(
                "virtual-chain-changed",
                {
                    "added_chain_block_hashes": [h.hex() for h in added],
                    "removed_chain_block_hashes": [h.hex() for h in removed],
                    "accepted_transaction_ids": {
                        h.hex(): [t.hex() for t in self.acceptance_data.get(h, [])] for h in added
                    },
                },
            )
        )

    def _ensure_chain_utxo_valid(self, block: bytes) -> bool:
        """Verify the selected chain up to `block` is UTXO valid; disqualify on failure."""
        # collect unverified chain ancestors
        chain = []
        cur = block
        while self.storage.statuses.get(cur) != StatusesStore.STATUS_UTXO_VALID:
            if self.storage.statuses.get(cur) == StatusesStore.STATUS_DISQUALIFIED:
                return False
            chain.append(cur)
            cur = self.storage.ghostdag.get_selected_parent(cur)
        if not chain:
            return True
        chain.reverse()
        with trace.span("virtual.chain_verify", blocks=len(chain)):
            # batch every cache-missing segment member's context into one
            # coalesced device dispatch before the serial verify loop —
            # k misses cost one script round-trip instead of k
            if self.speculative is not None and len(chain) > 1:
                self.speculative.precompute_chain(chain)
            for c in chain:
                if not self._verify_chain_block(c):
                    self.storage.statuses.set(c, StatusesStore.STATUS_DISQUALIFIED)
                    self.counters.inc_chain_disqualified()
                    return False
        return True

    def _verify_chain_block(self, block: bytes) -> bool:
        """verify_expected_utxo_state for one chain-candidate block.

        The expensive half — mergeset replay, script batch, muhash product
        (`_calculate_utxo_state`) — is served from the speculative
        precompute cache when a stage worker already ran it for this
        (block, selected_parent) position; the checks + commit half always
        runs here, so hit and miss paths write identical state."""
        gd = self.storage.ghostdag.get(block)
        header = self.storage.headers.get(block)
        self._move_utxo_position(gd.selected_parent)
        entry = None
        if self.speculative is not None:
            entry = self.speculative.take(block, gd.selected_parent)
        ctx = entry.ctx if entry is not None else self._calculate_utxo_state(gd, header.daa_score)
        return self._check_and_commit_chain_block(block, gd, header, ctx)

    def _check_and_commit_chain_block(self, block: bytes, gd: GhostdagData, header, ctx: dict) -> bool:
        """The five verify_expected_utxo_state checks + the chain commit,
        over an already-computed UTXO context (requires utxo_position ==
        gd.selected_parent).  Check order and side effects are identical
        whether ctx came from the synchronous path or the speculative
        cache."""
        # 1. utxo commitment
        multiset = ctx["multiset"]
        if multiset.finalize() != header.utxo_commitment:
            return False
        # 2. accepted id merkle root: KIP-15 two-level pre-Toccata, the
        # KIP-21 sequencing commitment after activation
        # (utxo_validation.rs:211-217)
        toccata = self.params.toccata_active(header.daa_score)
        build = None
        if toccata:
            # chain-qualification rule: first parent must be the selected
            # parent (utxo_validation.rs:219-238)
            if header.parents_by_level[0][0] != gd.selected_parent:
                return False
            build = self.lane_tracker.compute(
                gd,
                header.daa_score,
                ctx["mergeset_acceptance"],
                self.storage.headers,
                self.params.toccata_active,
                self._selected_chain_block_at,
            )
            expected_root = build.seq_commit
        else:
            sp_header = self.storage.headers.get(gd.selected_parent)
            expected_root = merkle.merkle_hash(
                sp_header.accepted_id_merkle_root, merkle.calc_merkle_root(ctx["accepted_tx_ids"])
            )
        if expected_root != header.accepted_id_merkle_root:
            return False
        # 3. header pruning point (verify_header_pruning_point: chain rule)
        reply = self.pruning_point_manager.expected_header_pruning_point(gd)
        if reply.pruning_point != header.pruning_point:
            return False
        self.pruning_point_manager.store_pruning_sample(block, reply.pruning_sample)
        # 4. coinbase
        txs = self.storage.block_transactions.get(block)
        if not self._verify_coinbase_transaction(txs[0], header.daa_score, gd, ctx["mergeset_rewards"], self.daa_excluded[block]):
            return False
        # 5. own txs valid in own utxo view
        own_view = UtxoView(self.utxo_set, ctx["mergeset_diff"])
        validated = self._validate_transactions(
            txs, own_view, header.daa_score, FLAG_FULL
        )
        if len(validated) < len(txs) - 1:
            return False

        # commit: store diff/multiset/acceptance, apply position
        self._set_multiset(block, multiset)
        self._set_utxo_diff(block, ctx["mergeset_diff"])
        self._set_acceptance(block, ctx["accepted_tx_ids"])
        self._apply_chain_diff(ctx["mergeset_diff"])
        if build is not None:
            self.lane_tracker.commit(block, build)
        self.selected_chain.append((gd.blue_score, block))
        # bound the in-RAM chain index to the queried window (finality+margin;
        # _selected_chain_block_at raises loudly if this ever proves too tight)
        limit = self.params.finality_depth + 1025
        if len(self.selected_chain) > limit + 256:
            del self.selected_chain[: len(self.selected_chain) - limit]
        self.utxo_position = block
        self._persist_utxo_position()
        self.storage.statuses.set(block, StatusesStore.STATUS_UTXO_VALID)
        self.counters.inc_chain_blocks()
        return True

    def _apply_chain_diff(self, diff: UtxoDiff) -> None:
        # the UtxoSetStore stages its own write-through ops per mutation
        apply_diff(self.utxo_set, diff)
        for op, entry in diff.remove.items():
            if op in self._acc_added:
                del self._acc_added[op]
            else:
                self._acc_removed[op] = entry
        for op, entry in diff.add.items():
            if op in self._acc_removed:
                del self._acc_removed[op]
            else:
                self._acc_added[op] = entry

    def _unapply_chain_diff(self, diff: UtxoDiff) -> None:
        unapply_diff(self.utxo_set, diff)
        for op, entry in diff.add.items():
            if op in self._acc_added:
                del self._acc_added[op]
            else:
                self._acc_removed[op] = entry
        for op, entry in diff.remove.items():
            if op in self._acc_removed:
                del self._acc_removed[op]
            else:
                self._acc_added[op] = entry

    def _selected_chain_block_at(self, target_bs: int) -> bytes:
        """Highest selected-chain block (<= utxo_position) with
        blue_score <= target_bs (processor.rs:790 shortcut anchor)."""
        import bisect

        i = bisect.bisect_right(self.selected_chain, (target_bs, b"\xff" * 32)) - 1
        if i < 0:
            # Target below our chain base.  If the base block is itself
            # pre-Toccata, it is a valid anchor: the reference's backward
            # walk stops at the first pre-Toccata ancestor and folds the
            # shortcut to ZERO (processor.rs:890-905) — any deeper true
            # anchor is also pre-Toccata and folds identically.  This is
            # the bootstrap-from-a-pre-Toccata-PP case, where no anchor
            # segment below the PP exists.
            base = self.selected_chain[0][1]
            base_hdr = self.storage.headers.get(base)
            if not self.params.toccata_active(base_hdr.daa_score):
                return base
            # otherwise selected_chain retention must reach
            # finality_depth+1 below the tip; a miss means pruning trimmed
            # too close — fail loudly rather than anchor the inactivity
            # shortcut wrongly
            raise RuleError(
                f"selected-chain retention violated: no entry with blue_score <= {target_bs}"
            )
        return self.selected_chain[i][1]

    def _verify_coinbase_transaction(self, coinbase, daa_score, gd, mergeset_rewards, non_daa) -> bool:
        miner_data = self.coinbase_manager.deserialize_coinbase_payload(coinbase.payload).miner_data
        expected = self.coinbase_manager.expected_coinbase_transaction(
            daa_score, miner_data, gd, mergeset_rewards, non_daa
        )
        return chash.tx_hash(coinbase) == chash.tx_hash(expected)

    def _calculate_utxo_state(
        self,
        gd: GhostdagData,
        pov_daa_score: int,
        need_multiset: bool = True,
        base=None,
        seed_multiset: MuHash | None = None,
        checker=None,
        token_ns=None,
    ) -> dict:
        """utxo_validation.rs calculate_utxo_state relative to current position
        (must equal gd.selected_parent).

        ``need_multiset=False`` skips the muhash device product entirely —
        the virtual resolve never reads it (only chain blocks commit to a
        utxo_commitment).

        Speculative mode (``checker`` given): UTXO reads go through ``base``
        (the caller's frozen view of the selected-parent position) instead of
        the live set, the multiset seeds from ``seed_multiset`` and its device
        batch is deferred (returned under ``multiset_items``), and script
        checks are staged *optimistically* on the shared checker — every
        staged tx is treated as accepted, with the staged tokens returned
        under ``staged_tokens`` so the caller can discard the whole context
        if any check fails after the async dispatch resolves."""
        speculative = checker is not None
        if not speculative:
            assert self.utxo_position == gd.selected_parent
        if base is None:
            base = self.utxo_set
        mergeset_diff = UtxoDiff()
        multiset = None
        if need_multiset:
            seed = seed_multiset if seed_multiset is not None else self.multisets[gd.selected_parent]
            multiset = seed.clone()
        accepted_tx_ids: list[bytes] = []
        mergeset_rewards: dict[bytes, BlockRewardData] = {}

        sp_txs = self.storage.block_transactions.get(gd.selected_parent)
        coinbase = sp_txs[0]
        coinbase_entries: list = []
        mergeset_diff.add_transaction(coinbase, coinbase_entries, pov_daa_score)
        accepted_tx_ids.append(coinbase.id())
        # multiset updates accumulate across the whole mergeset and reduce in
        # one batch below (the product is commutative) — this is what routes
        # the muhash work through the device tree-product kernel
        multiset_items: list = [(coinbase, coinbase_entries, pov_daa_score)]
        # per-merged-block acceptance (KIP-21 lane activity source):
        # (merged_block, coinbase payload, [accepted txs in block order])
        mergeset_acceptance: list = []
        staged_tokens: list = []

        ordered = [(gd.selected_parent, sp_txs)] + [
            (b, self.storage.block_transactions.get(b)) for b in gd.ascending_mergeset_without_selected_parent(self.storage.ghostdag)
        ]
        for i, (merged_block, txs) in enumerate(ordered):
            composed = UtxoView(base, mergeset_diff)
            is_selected_parent = i == 0
            flags = FLAG_SKIP_SCRIPTS if is_selected_parent else FLAG_FULL
            if speculative:
                # token_ns keeps tokens collision-free when several blocks
                # share one checker (the in-cycle chain precompute)
                staged = self._validate_transactions(
                    txs, composed, pov_daa_score, flags,
                    checker=checker,
                    token_tag=("ms", i) if token_ns is None else ("ms", token_ns, i),
                    position_anchor=gd.selected_parent,
                )
                staged_tokens.extend(t for t, _tx, _e, _f in staged)
                validated = [(tx, entries, fee) for _t, tx, entries, fee in staged]
            else:
                validated = self._validate_transactions(txs, composed, pov_daa_score, flags)
            block_fee = 0
            accepted_here = [coinbase] if is_selected_parent else []
            for tx, entries, fee in validated:
                mergeset_diff.add_transaction(tx, entries, pov_daa_score)
                multiset_items.append((tx, entries, pov_daa_score))
                accepted_tx_ids.append(tx.id())
                accepted_here.append(tx)
                block_fee += fee
            cb_data = self.coinbase_manager.deserialize_coinbase_payload(txs[0].payload)
            mergeset_rewards[merged_block] = BlockRewardData(cb_data.subsidy, block_fee, cb_data.miner_data.script_public_key)
            mergeset_acceptance.append((merged_block, txs[0].payload, accepted_here))
        if need_multiset and not speculative:
            multiset.add_transactions_batch(multiset_items)

        ctx = {
            "mergeset_diff": mergeset_diff,
            "multiset": multiset,
            "accepted_tx_ids": accepted_tx_ids,
            "mergeset_rewards": mergeset_rewards,
            "mergeset_acceptance": mergeset_acceptance,
        }
        if speculative:
            ctx["multiset_items"] = multiset_items
            ctx["staged_tokens"] = staged_tokens
        return ctx

    def _validate_transactions(
        self, txs, utxo_view, pov_daa_score, flags, checker=None, token_tag=None, position_anchor=None
    ):
        """validate_transactions_in_parallel: returns [(tx, entries, fee)] of
        valid non-coinbase txs; script checks batched on device.

        With a shared ``checker`` (speculative mode) nothing is dispatched
        here: the staged list [(token, tx, entries, fee)] is returned with
        tokens namespaced by ``token_tag``, and the caller joins the async
        handle and maps failures back.  ``position_anchor`` pins the
        seq-commit accessor to the position the synchronous path would have
        (it calls ``_move_utxo_position`` first; speculation does not)."""
        shared = checker is not None
        if not shared:
            checker = self.transaction_validator.new_checker()
        accessor = None
        if self.params.toccata_active(pov_daa_score):
            from kaspa_tpu.consensus.smt_processor import ConsensusSeqCommitAccessor

            accessor = ConsensusSeqCommitAccessor(
                position_anchor if position_anchor is not None else self.utxo_position,
                self.reachability,
                self.storage.headers,
                self.params.toccata_active,
                self.params.finality_depth,
            )
        staged = []
        for i, tx in enumerate(txs):
            if i == 0:
                continue  # coinbase
            entries = []
            missing = False
            for inp in tx.inputs:
                entry = utxo_view.get(inp.previous_outpoint)
                if entry is None:
                    missing = True
                    break
                entries.append(entry)
            if missing:
                continue
            token = (token_tag, i) if shared else i
            try:
                fee = self.transaction_validator.validate_populated_transaction_and_get_fee(
                    tx, entries, pov_daa_score, flags, checker=checker, token=token,
                    seq_commit_accessor=accessor,
                )
            except TxRuleError:
                continue
            staged.append((token, tx, entries, fee))
        if shared:
            return staged
        script_results = checker.dispatch()
        out = []
        for i, tx, entries, fee in staged:
            if script_results.get(i) is None:
                out.append((tx, entries, fee))
        return out

    # ------------------------------------------------------------------
    # block building (test_consensus.rs build_*_with_parents + the
    # template path of virtual_processor/processor.rs:1351-1510)
    # ------------------------------------------------------------------

    def build_block_with_parents(
        self,
        parents: list[bytes],
        miner_data: MinerData,
        txs: list[Transaction] | None = None,
        timestamp: int | None = None,
        tx_selector=None,
    ) -> Block:
        """Builds a fully valid block merging `parents` (any known tips).

        Computes GHOSTDAG, window state and the UTXO commitments exactly as a
        validator will, so the result passes validate_and_insert_block.
        ``tx_selector(utxo_view, pov_daa_score) -> [Transaction]`` selects
        transactions against the block's own UTXO context (the template
        path's validate_block_template_transactions discipline).
        """
        gd = self.ghostdag_manager.ghostdag(parents)
        if not self._ensure_chain_utxo_valid(gd.selected_parent):
            raise RuleError("selected parent chain is disqualified")
        daa_window = self.window_manager.block_daa_window(gd)
        if self.params.toccata_active(daa_window.daa_score):
            # KIP-21 chain rule: the selected parent leads the parent list
            parents = [gd.selected_parent] + [p for p in parents if p != gd.selected_parent]
        bits = self.window_manager.calculate_difficulty_bits(gd, daa_window)
        pmt, _ = self.window_manager.calc_past_median_time(gd)
        self._move_utxo_position(gd.selected_parent)
        ctx = self._calculate_utxo_state(gd, daa_window.daa_score)
        if tx_selector is not None:
            assert txs is None
            txs = tx_selector(UtxoView(self.utxo_set, ctx["mergeset_diff"]), daa_window.daa_score)
        txs = txs or []

        # mergeset rewards only cover merged blocks; txs of THIS block are
        # rewarded by the block that merges it
        coinbase = self.coinbase_manager.expected_coinbase_transaction(
            daa_window.daa_score, miner_data, gd, ctx["mergeset_rewards"], daa_window.mergeset_non_daa
        )
        all_txs = [coinbase] + list(txs)

        sp_header = self.storage.headers.get(gd.selected_parent)
        if self.params.toccata_active(daa_window.daa_score):
            accepted_root = self.lane_tracker.compute(
                gd,
                daa_window.daa_score,
                ctx["mergeset_acceptance"],
                self.storage.headers,
                self.params.toccata_active,
                self._selected_chain_block_at,
            ).seq_commit
        else:
            accepted_root = merkle.merkle_hash(
                sp_header.accepted_id_merkle_root, merkle.calc_merkle_root(ctx["accepted_tx_ids"])
            )
        header = Header(
            version=self.params.block_version(daa_window.daa_score),
            parents_by_level=self.parents_manager.calc_block_parents(
                self.pruning_processor.pruning_point, list(parents)
            ),
            hash_merkle_root=merkle.calc_hash_merkle_root(all_txs),
            accepted_id_merkle_root=accepted_root,
            utxo_commitment=ctx["multiset"].finalize(),
            timestamp=timestamp if timestamp is not None else pmt + 1,
            bits=bits,
            nonce=0,
            daa_score=daa_window.daa_score,
            blue_work=gd.blue_work,
            blue_score=gd.blue_score,
            pruning_point=self.pruning_point_manager.expected_header_pruning_point(gd).pruning_point,
        )
        if header.timestamp <= pmt:
            header.timestamp = pmt + 1
            header.invalidate_cache()
        return Block(header, all_txs)

    def build_block_template(self, miner_data: MinerData, txs: list[Transaction], timestamp: int | None = None) -> Block:
        """Template on top of the current virtual (mining path)."""
        return self.build_block_with_parents(self.virtual_state.parents, miner_data, txs, timestamp)

    def get_virtual_utxo_view(self) -> UtxoView:
        """UTXO view of the current virtual (for tx selection/mempool)."""
        self._move_utxo_position(self.sink())
        return UtxoView(self.utxo_set, self.virtual_utxo_diff)

    def _move_utxo_position(self, target: bytes) -> None:
        """Reposition the materialized UTXO set along the selected chain."""
        if self.utxo_position == target:
            return
        # walk current position down to a chain ancestor of target
        back_path = []
        cur = self.utxo_position
        while not self.reachability.is_chain_ancestor_of(cur, target):
            back_path.append(cur)
            cur = self.storage.ghostdag.get_selected_parent(cur)
        # walk target down to cur, collecting forward path
        fwd_path = []
        t = target
        while t != cur:
            fwd_path.append(t)
            t = self.storage.ghostdag.get_selected_parent(t)
        for b in back_path:
            self._unapply_chain_diff(self.utxo_diffs[b])
            self.lane_tracker.retreat(b)
            assert self.selected_chain[-1][1] == b
            self.selected_chain.pop()
        for b in reversed(fwd_path):
            self._apply_chain_diff(self.utxo_diffs[b])
            self.lane_tracker.advance(b)
            self.selected_chain.append((self.storage.ghostdag.get_blue_score(b), b))
        self.utxo_position = target
        self._persist_utxo_position()
