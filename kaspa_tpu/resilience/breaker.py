"""Device-dispatch circuit breaker with exponential re-probe backoff.

State machine (the classic closed/open/half-open breaker, applied to the
TPU batch-verify dispatch):

    CLOSED     every dispatch goes to the device; N consecutive failures
               (KASPA_TPU_BREAKER_THRESHOLD, default 3) trip to OPEN
    OPEN       dispatches are denied — the caller routes the batch to the
               host degraded lane — until the backoff window elapses
               (base * 2^k, capped; KASPA_TPU_BREAKER_BACKOFF_BASE /
               KASPA_TPU_BREAKER_BACKOFF_MAX, defaults 0.25s / 30s)
    HALF_OPEN  exactly one probe dispatch is allowed through; success
               re-arms (CLOSED, recovery latency recorded), failure
               re-opens with a doubled backoff

Determinism note: trips, probes and recoveries are driven by the
*attempt* sequence (each ``allow() == True``), which is workload-
determined; only the number of denied dispatches while OPEN depends on
wall clock.  Transition records therefore carry the attempt index (the
deterministic coordinate) and land in SUSTAIN.json's breaker section
alongside the wall-clock recovery latencies.
"""

from __future__ import annotations

import os
import threading
import time

from kaspa_tpu.observability.core import REGISTRY

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

_TRIPS = REGISTRY.counter_family("breaker_trips", "breaker", help="breaker transitions into OPEN")
_PROBES = REGISTRY.counter_family("breaker_probes", "breaker", help="half-open probe dispatches")
_RECOVERIES = REGISTRY.counter_family("breaker_recoveries", "breaker", help="breaker re-arms (probe succeeded)")
_RECOVERY_LATENCY = REGISTRY.histogram(
    "breaker_recovery_seconds", help="trip-to-recovery latency of the device breaker"
)

_MAX_TRANSITIONS = 256  # bounded transition log (oldest dropped)


class CircuitBreaker:
    def __init__(
        self,
        name: str,
        failure_threshold: int = 3,
        backoff_base: float = 0.25,
        backoff_max: float = 30.0,
        clock=time.monotonic,
    ):
        self.name = name
        self.failure_threshold = max(1, failure_threshold)
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self._clock = clock
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self.state = CLOSED
            self.consecutive_failures = 0
            self.attempts = 0  # allow() == True count: the deterministic coordinate
            self.denied = 0
            self.trips = 0
            self.probes = 0
            self.recoveries = 0
            self.recovery_latencies: list[float] = []
            self.transitions: list[dict] = []
            self._backoff_exp = 0
            self._reopen_at = 0.0
            self._tripped_at = 0.0

    # --- the dispatch gate ------------------------------------------------

    def allow(self) -> bool:
        """True = dispatch to the device (counts as an attempt); False =
        take the degraded lane."""
        with self._lock:
            if self.state == CLOSED:
                self.attempts += 1
                return True
            if self.state == OPEN and self._clock() >= self._reopen_at:
                self._transition(HALF_OPEN)
                self.probes += 1
                _PROBES.inc(self.name)
                self.attempts += 1
                return True
            # OPEN inside the backoff window, or a HALF_OPEN probe already
            # in flight on another thread
            self.denied += 1
            return False

    def record_success(self) -> None:
        with self._lock:
            self.consecutive_failures = 0
            if self.state != CLOSED:
                latency = self._clock() - self._tripped_at
                self.recovery_latencies.append(latency)
                _RECOVERY_LATENCY.observe(latency)
                self.recoveries += 1
                _RECOVERIES.inc(self.name)
                self._backoff_exp = 0
                self._transition(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self.consecutive_failures += 1
            if self.state == HALF_OPEN:
                # failed probe: back off harder before the next one
                self._backoff_exp += 1
                self._open()
            elif self.state == CLOSED and self.consecutive_failures >= self.failure_threshold:
                self.trips += 1
                _TRIPS.inc(self.name)
                self._tripped_at = self._clock()
                self._open()

    def _open(self) -> None:
        delay = min(self.backoff_base * (2.0**self._backoff_exp), self.backoff_max)
        self._reopen_at = self._clock() + delay
        self._transition(OPEN)

    def _transition(self, to: str) -> None:
        self.transitions.append({"attempt": self.attempts, "from": self.state, "to": to})
        del self.transitions[:-_MAX_TRANSITIONS]
        self.state = to
        if to == OPEN:
            # crash-style evidence: when the device lane trips, persist the
            # flight ring so the traces that led up to the trip survive
            # (no-op unless the recorder is on and a dump dir is set)
            from kaspa_tpu.observability import flight

            flight.on_breaker_open(self.name)

    # --- reporting --------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self.state,
                "consecutive_failures": self.consecutive_failures,
                "attempts": self.attempts,
                "denied": self.denied,
                "trips": self.trips,
                "probes": self.probes,
                "recoveries": self.recoveries,
                "recovery_latency_seconds": [round(x, 6) for x in self.recovery_latencies[-32:]],
                "transitions": list(self.transitions[-32:]),
            }


_device_breaker: CircuitBreaker | None = None
_device_lock = threading.Lock()


def device_breaker() -> CircuitBreaker:
    """The process-wide breaker guarding batched device signature verify
    (env knobs: KASPA_TPU_BREAKER_THRESHOLD / _BACKOFF_BASE / _BACKOFF_MAX)."""
    global _device_breaker
    if _device_breaker is None:
        with _device_lock:
            if _device_breaker is None:
                _device_breaker = CircuitBreaker(
                    "device_verify",
                    failure_threshold=int(os.environ.get("KASPA_TPU_BREAKER_THRESHOLD", "3")),
                    backoff_base=float(os.environ.get("KASPA_TPU_BREAKER_BACKOFF_BASE", "0.25")),
                    backoff_max=float(os.environ.get("KASPA_TPU_BREAKER_BACKOFF_MAX", "30")),
                )
                REGISTRY.register_collector("resilience", lambda: {"device_verify": _device_breaker.snapshot()})
    return _device_breaker
