"""Device-dispatch circuit breaker with exponential re-probe backoff.

State machine (the classic closed/open/half-open breaker, applied to the
TPU batch-verify dispatch):

    CLOSED     every dispatch goes to the device; N consecutive failures
               (KASPA_TPU_BREAKER_THRESHOLD, default 3) trip to OPEN
    OPEN       dispatches are denied — the caller routes the batch to the
               host degraded lane — until the backoff window elapses
               (base * 2^k, capped; KASPA_TPU_BREAKER_BACKOFF_BASE /
               KASPA_TPU_BREAKER_BACKOFF_MAX, defaults 0.25s / 30s)
    HALF_OPEN  exactly one probe dispatch is allowed through; success
               re-arms (CLOSED, recovery latency recorded), failure
               re-opens with a doubled backoff

Determinism note: trips, probes and recoveries are driven by the
*attempt* sequence (each ``allow() == True``), which is workload-
determined; only the number of denied dispatches while OPEN depends on
wall clock.  Transition records therefore carry the attempt index (the
deterministic coordinate) and land in SUSTAIN.json's breaker section
alongside the wall-clock recovery latencies.

Supervision (``resilience/supervisor.py``) adds two refinements:

* failures carry a *cause* — ``HUNG`` (a watchdog deadline, not an
  error) trips immediately from CLOSED, because one wedged dispatch
  already proves the device lane is stuck; waiting for two more hangs
  would cost two more full deadlines of stall.
* *managed* mode: live dispatches (``allow()``) while OPEN always take
  the degraded lane — only the canary prober's ``allow(probe=True)``
  transitions to HALF_OPEN, so a half-open probe is never a live
  super-batch racing a possibly-wedged device.
"""

from __future__ import annotations

import os
import threading

from kaspa_tpu.utils.sync import ranked_lock
import time

from kaspa_tpu.observability.core import REGISTRY

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

HUNG = "hung"  # failure cause: watchdog deadline, not a device error

_TRIPS = REGISTRY.counter_family("breaker_trips", "breaker", help="breaker transitions into OPEN")
_PROBES = REGISTRY.counter_family("breaker_probes", "breaker", help="half-open probe dispatches")
_RECOVERIES = REGISTRY.counter_family("breaker_recoveries", "breaker", help="breaker re-arms (probe succeeded)")
_RECOVERY_LATENCY = REGISTRY.histogram(
    "breaker_recovery_seconds", help="trip-to-recovery latency of the device breaker"
)

_MAX_TRANSITIONS = 256  # bounded transition log (oldest dropped)


class CircuitBreaker:
    def __init__(
        self,
        name: str,
        failure_threshold: int = 3,
        backoff_base: float = 0.25,
        backoff_max: float = 30.0,
        clock=time.monotonic,
    ):
        self.name = name
        self.failure_threshold = max(1, failure_threshold)
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self._clock = clock
        self._lock = threading.Lock()  # graftlint: allow(raw-lock) -- per-breaker state leaf, taken inside dispatch under arbitrary ranks
        # wiring that survives reset(): supervision attaches once per process
        self._managed = False
        self._trip_listeners: list = []
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self.state = CLOSED
            self.consecutive_failures = 0
            self.attempts = 0  # allow() == True count: the deterministic coordinate
            self.denied = 0
            self.trips = 0
            self.probes = 0
            self.recoveries = 0
            self.recovery_latencies: list[float] = []
            self.transitions: list[dict] = []
            self.last_trip_cause: str | None = None
            self._backoff_exp = 0
            self._reopen_at = 0.0
            self._tripped_at = 0.0

    # --- supervision wiring -----------------------------------------------

    def set_managed(self, flag: bool) -> None:
        """Managed = HALF_OPEN probes come only from ``allow(probe=True)``
        (the canary prober); live dispatches stay degraded while OPEN."""
        with self._lock:
            self._managed = bool(flag)

    def add_trip_listener(self, fn) -> None:
        """Call ``fn()`` (no args, must not block) on every OPEN transition."""
        with self._lock:
            if fn not in self._trip_listeners:
                self._trip_listeners.append(fn)

    def reopen_due(self) -> bool:
        """True when OPEN and the backoff window has elapsed."""
        with self._lock:
            return self.state == OPEN and self._clock() >= self._reopen_at

    # --- the dispatch gate ------------------------------------------------

    def allow(self, probe: bool = False) -> bool:
        """True = dispatch to the device (counts as an attempt); False =
        take the degraded lane.  ``probe=True`` marks the caller as the
        canary prober — in managed mode the only path to HALF_OPEN."""
        with self._lock:
            if self.state == CLOSED:
                if probe:
                    return False  # nothing to probe
                self.attempts += 1
                return True
            if (
                self.state == OPEN
                and self._clock() >= self._reopen_at
                and (probe or not self._managed)
            ):
                self._transition(HALF_OPEN)
                self.probes += 1
                _PROBES.inc(self.name)
                self.attempts += 1
                return True
            # OPEN inside the backoff window, OPEN-managed awaiting the
            # canary, or a HALF_OPEN probe already in flight elsewhere
            self.denied += 1
            return False

    def record_success(self) -> None:
        with self._lock:
            self.consecutive_failures = 0
            if self.state != CLOSED:
                latency = self._clock() - self._tripped_at
                self.recovery_latencies.append(latency)
                _RECOVERY_LATENCY.observe(latency)
                self.recoveries += 1
                _RECOVERIES.inc(self.name)
                self._backoff_exp = 0
                self._transition(CLOSED)

    def record_failure(self, cause: str | None = None) -> None:
        """``cause=HUNG`` (a watchdog deadline) trips immediately from
        CLOSED: one proven hang already cost a full deadline of stall."""
        with self._lock:
            self.consecutive_failures += 1
            if self.state == HALF_OPEN:
                # failed probe: back off harder before the next one
                self._backoff_exp += 1
                self._open(cause)
            elif self.state == CLOSED and (
                cause == HUNG or self.consecutive_failures >= self.failure_threshold
            ):
                self.trips += 1
                _TRIPS.inc(self.name)
                self._tripped_at = self._clock()
                self.last_trip_cause = cause or "error"
                self._open(cause)

    def _open(self, cause: str | None = None) -> None:
        delay = min(self.backoff_base * (2.0**self._backoff_exp), self.backoff_max)
        self._reopen_at = self._clock() + delay
        self._transition(OPEN, cause)

    def _transition(self, to: str, cause: str | None = None) -> None:
        rec = {"attempt": self.attempts, "from": self.state, "to": to}
        if cause is not None:
            rec["cause"] = cause
        self.transitions.append(rec)
        del self.transitions[:-_MAX_TRANSITIONS]
        self.state = to
        if to == OPEN:
            # crash-style evidence: when the device lane trips, persist the
            # flight ring so the traces that led up to the trip survive
            # (no-op unless the recorder is on and a dump dir is set)
            from kaspa_tpu.observability import flight

            flight.on_breaker_open(self.name)
            for fn in self._trip_listeners:
                fn()

    # --- reporting --------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self.state,
                "managed": self._managed,
                "last_trip_cause": self.last_trip_cause,
                "consecutive_failures": self.consecutive_failures,
                "attempts": self.attempts,
                "denied": self.denied,
                "trips": self.trips,
                "probes": self.probes,
                "recoveries": self.recoveries,
                "recovery_latency_seconds": [round(x, 6) for x in self.recovery_latencies[-32:]],
                "transitions": list(self.transitions[-32:]),
            }


_device_breaker: CircuitBreaker | None = None
_device_lock = ranked_lock("breaker.slot")


def device_breaker() -> CircuitBreaker:
    """The process-wide breaker guarding batched device signature verify
    (env knobs: KASPA_TPU_BREAKER_THRESHOLD / _BACKOFF_BASE / _BACKOFF_MAX)."""
    global _device_breaker
    if _device_breaker is None:
        with _device_lock:
            if _device_breaker is None:
                _device_breaker = CircuitBreaker(
                    "device_verify",
                    failure_threshold=int(os.environ.get("KASPA_TPU_BREAKER_THRESHOLD", "3")),
                    backoff_base=float(os.environ.get("KASPA_TPU_BREAKER_BACKOFF_BASE", "0.25")),
                    backoff_max=float(os.environ.get("KASPA_TPU_BREAKER_BACKOFF_MAX", "30")),
                )
                REGISTRY.register_collector("resilience", lambda: {"device_verify": _device_breaker.snapshot()})
    return _device_breaker
