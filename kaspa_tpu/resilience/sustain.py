"""Hostile-load sustain run: the chaos-engineering acceptance harness.

Builds a hostile workload (multisig/P2SH script mix that bypasses the
device fast path, plus an attacker side-DAG forking from genesis with
~1.5x the block count — a deep reorg when its heavier chain lands), then
replays it twice into fresh consensus instances:

  1. fault-free, in build order — the baseline fingerprints
  2. under a seeded fault schedule, delivered in shuffled windows through
     an orphan-tolerant queue (blocks held until their parents arrive)

and asserts the post-recovery end state (sink, utxo_commitment,
virtual_daa_score) is identical.  Every injected fault is transient
infrastructure noise — a device dispatch that errors into the breaker's
degraded lane, a VM fallback job that retries — so the faulted run must
converge to the byte-identical fault-free state; ``matches_fault_free``
in SUSTAIN.json is the acceptance bit.

The report splits deterministic data (fault event log, fingerprints)
from wall-clock data (throughput, breaker recovery latencies, lock-hold
traces): two runs of the same workload + schedule + seed produce
byte-identical ``deterministic`` sections.
"""

from __future__ import annotations

import json
import platform
import random
import sys
import time
from dataclasses import asdict, replace

import os

from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.observability.core import REGISTRY
from kaspa_tpu.resilience import supervisor
from kaspa_tpu.resilience.breaker import CLOSED, device_breaker
from kaspa_tpu.resilience.faults import FAULTS
from kaspa_tpu.sim.simulator import SimConfig, simulate
from kaspa_tpu.utils.sync import lock_trace_snapshot, set_lock_debug

# metric counters whose faulted-replay deltas land in SUSTAIN.json
_DELTA_COUNTERS = (
    "secp_degraded_dispatches",
    "secp_degraded_jobs",
    "txscript_vm_fault_retries",
    "kv_journal_repairs",
)


def default_schedule() -> dict:
    """The stock hostile schedule: four consecutive device-verify errors
    (trips the breaker, then fails its first probe — exercising trip,
    degraded lane, backoff doubling, and recovery) plus every-5th VM
    fallback job erroring (exercising the retry lane), capped at 8."""
    return {
        "device.verify": {"mode": "error", "hits": [2, 3, 4, 5]},
        "vm.fallback.exec": {"mode": "error", "every": 5, "max": 8},
    }


def build_workload(cfg: SimConfig) -> dict:
    """Hostile main DAG plus an attacker fork from the same genesis.

    The attacker sim runs with seed+1 (distinct miners/keys) and ~1.5x
    the blocks, so once its blocks are all delivered its chain carries
    more blue work and the virtual reorgs deep past the main DAG."""
    main = simulate(cfg)
    attacker = simulate(
        replace(cfg, num_blocks=max(cfg.num_blocks * 3 // 2, cfg.num_blocks + 1), seed=cfg.seed + 1)
    )
    return {"cfg": cfg, "main": main, "attacker": attacker, "blocks": main.blocks + attacker.blocks}


def _fingerprints(consensus: Consensus) -> dict:
    sink = consensus.sink()
    return {
        "sink": sink.hex(),
        "utxo_commitment": consensus.multisets[sink].finalize().hex(),
        "virtual_daa_score": consensus.get_virtual_daa_score(),
    }


def _insert(consensus: Consensus, block) -> None:
    status = consensus.validate_and_insert_block(block)
    assert status in ("utxo_valid", "utxo_pending"), f"sustain replay rejected block: {status}"


def _orphan_tolerant_replay(consensus: Consensus, blocks: list, seed: int, window: int = 8) -> None:
    """Deliver ``blocks`` in deterministically shuffled windows; a block
    whose parents have not arrived is parked and flushed once they do —
    the orphan-pool discipline a real node applies to out-of-order
    gossip, here driving the faulted run's out-of-order stress."""
    rng = random.Random(seed ^ 0x5EED)
    order: list = []
    for i in range(0, len(blocks), window):
        chunk = list(blocks[i : i + window])
        rng.shuffle(chunk)
        order.extend(chunk)

    def ready(b) -> bool:
        return all(consensus.storage.headers.has(p) for p in b.header.direct_parents())

    pending: dict[bytes, object] = {}
    for b in order:
        if not ready(b):
            pending[b.hash] = b
            continue
        _insert(consensus, b)
        progress = True
        while progress:
            progress = False
            for h, pb in list(pending.items()):
                if ready(pb):
                    del pending[h]
                    _insert(consensus, pb)
                    progress = True
    assert not pending, f"{len(pending)} orphans never became insertable"


def run_meta(wall: dict | None = None) -> dict:
    """Volatile per-run facts (timestamp, host, interpreter, wall-clock
    telemetry), quarantined under ONE artifact key so diffing two runs of
    the same workload+schedule+seed (``stable_view``) ignores them
    wholesale instead of chasing churn field by field."""
    return {
        "created_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "host": platform.node(),
        "python": sys.version.split()[0],
        "wall": wall or {},
    }


def stable_view(report: dict) -> dict:
    """The diffable surface of a SUSTAIN-family artifact: everything but
    ``run_meta``.  (``metrics`` stays — its throughput numbers are the
    run's headline, reviewed rather than diffed.)"""
    return {k: v for k, v in report.items() if k != "run_meta"}


def _split_breaker(snapshot: dict) -> tuple[dict, dict]:
    """(stable fields, volatile fields) of a breaker snapshot: recovery
    latencies and timestamped transition records differ every run and
    belong under ``run_meta.wall``."""
    snap = dict(snapshot)
    wall = {k: snap.pop(k) for k in ("recovery_latency_seconds", "transitions") if k in snap}
    return snap, wall


def _counter_value(counters: dict, name: str):
    v = counters.get(name, 0)
    return dict(v) if isinstance(v, dict) else v


def _delta(before: dict, after: dict, name: str):
    b, a = _counter_value(before, name), _counter_value(after, name)
    if isinstance(a, dict):
        b = b if isinstance(b, dict) else {}
        return {k: a[k] - b.get(k, 0) for k in sorted(a) if a[k] - b.get(k, 0)}
    return a - (b if isinstance(b, (int, float)) else 0)


def run_sustain(
    cfg: SimConfig,
    schedule: dict | None = None,
    seed: int = 0,
    out: str | None = None,
    workload: dict | None = None,
) -> dict:
    """Run the hostile sustain benchmark; returns (and optionally writes
    to ``out``) the SUSTAIN.json report dict."""
    schedule = default_schedule() if schedule is None else schedule
    wl = workload if workload is not None else build_workload(cfg)
    blocks = wl["blocks"]

    # fault-free baseline first, while nothing is armed
    FAULTS.clear()
    baseline = Consensus(wl["main"].params)
    for b in blocks:
        _insert(baseline, b)
    base_fp = _fingerprints(baseline)

    breaker = device_breaker()
    breaker.reset()
    set_lock_debug(True)
    before = REGISTRY.snapshot()["counters"]
    FAULTS.configure(schedule, seed)
    try:
        faulted = Consensus(wl["main"].params)
        t0 = time.perf_counter()
        _orphan_tolerant_replay(faulted, blocks, seed)
        elapsed = time.perf_counter() - t0
        events = FAULTS.events()
    finally:
        FAULTS.clear()
        set_lock_debug(False)
    after = REGISTRY.snapshot()["counters"]
    fp = _fingerprints(faulted)

    brk_stable, brk_wall = _split_breaker(breaker.snapshot())
    report = {
        "config": {**asdict(cfg), "fault_seed": seed, "schedule": schedule},
        "deterministic": {
            "blocks": len(blocks),
            "events": events,
            "fingerprints": fp,
            "fault_free_fingerprints": base_fp,
            "matches_fault_free": fp == base_fp,
        },
        "breaker": brk_stable,
        "metrics": {
            "replay_seconds": round(elapsed, 3),
            "blocks_per_sec": round(len(blocks) / elapsed, 2) if elapsed else None,
            "fault_injections": _delta(before, after, "fault_injections"),
            **{name: _delta(before, after, name) for name in _DELTA_COUNTERS},
        },
        "run_meta": run_meta(wall={"breaker": brk_wall, "lock_traces": lock_trace_snapshot()}),
    }
    if out:
        with open(out, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
    return report


# --- the wedge drill ------------------------------------------------------


def _await_recovery(breaker, timeout_s: float) -> bool:
    """Poll until the canary prober re-arms the breaker (CLOSED)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if breaker.state == CLOSED:
            return True
        time.sleep(0.02)
    return breaker.state == CLOSED


def _await_late_results(expected: int, before: int, timeout_s: float) -> int:
    """Wait for abandoned workers to finish and discard their results, so
    the accounting in the report is complete (best-effort: a wedged real
    device might never finish — the drill's fakes always do)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        seen = supervisor._POOL.snapshot()["late_results"] - before
        if seen >= expected:
            return seen
        time.sleep(0.05)
    return supervisor._POOL.snapshot()["late_results"] - before


def _compile_stall_drill(seed: int, stall_delay_s: float, compile_deadline_s: float) -> dict:
    """Micro-phase for the compile tier: wedge the first compile of a
    genuinely cold (schnorr, bucket) shape and assert the watchdog
    requeues it onto the host lane with the shape left cold.

    The injected wedge raises *before* the kernel call, so no real XLA
    compile runs — the phase costs ~compile_deadline_s, not minutes."""
    from kaspa_tpu.crypto import eclib, secp

    bucket = 8
    while ("schnorr_verify", bucket) in secp._seen_shapes:
        bucket <<= 1
    count = bucket // 2 + 1  # pads to exactly `bucket`
    seckey = (seed * 2 + 1) % eclib.N or 1
    pub = eclib.schnorr_pubkey(seckey)
    items = []
    for i in range(count):
        msg = bytes([i & 0xFF]) * 32
        items.append((pub, msg, eclib.schnorr_sign(msg, seckey)))

    prev_split = os.environ.get("KASPA_TPU_COLD_BUCKET_SPLIT")
    os.environ["KASPA_TPU_COLD_BUCKET_SPLIT"] = "0"  # hit the cold shape head-on
    FAULTS.configure({"device.jit_compile": {"mode": "wedge", "delay": stall_delay_s, "hits": [1]}}, seed)
    try:
        with supervisor.deadline_overrides(compile_s=compile_deadline_s):
            mask = secp.schnorr_verify_batch(items)
        events = FAULTS.events()
    finally:
        FAULTS.clear()
        if prev_split is None:
            os.environ.pop("KASPA_TPU_COLD_BUCKET_SPLIT", None)
        else:
            os.environ["KASPA_TPU_COLD_BUCKET_SPLIT"] = prev_split
    # the abandoned worker un-marks the shape when its wedge finally fires
    # (after stall_delay_s, well past our deadline) — wait for it so the
    # cold-shape assertion doesn't race the cleanup
    deadline = time.monotonic() + stall_delay_s + 5.0
    while ("schnorr_verify", bucket) in secp._seen_shapes and time.monotonic() < deadline:
        time.sleep(0.02)
    return {
        "bucket": bucket,
        "jobs": count,
        "injected": len(events),
        "events": events,
        "all_valid": bool(mask.all()) and len(mask) == count,
        "shape_left_cold": ("schnorr_verify", bucket) not in secp._seen_shapes,
    }


def run_wedge_drill(
    cfg: SimConfig,
    seed: int = 0,
    out: str | None = None,
    *,
    hang_delay_s: float = 8.0,
    dispatch_deadline_s: float = 5.0,
    stall_delay_s: float = 4.0,
    compile_deadline_s: float = 1.0,
    hang_hits: tuple = (2, 4, 6),
    recovery_timeout_s: float = 30.0,
) -> dict:
    """The supervision acceptance drill: wedge the device mid-replay and
    prove the node degrades instead of dying.

    Phase A replays the hostile workload fault-free (warming every device
    shape) and fingerprints the end state.  Phase B installs supervision
    (managed breaker + canary prober) and arms ``device.hang`` in mode
    "hang": the scheduled dispatches sleep past the watchdog deadline and
    then *complete* — the hardest case, because the late result must be
    discarded after the batch already resolved via the host lane.  Phase C
    replays out-of-order under those hangs.  Phase D is the compile-tier
    micro-drill (a wedged cold-bucket jit).  The report's gates: bitwise
    fingerprint identity, ``requeued == injected``, zero unresolved
    tickets, breaker recovered to CLOSED by the canary alone.
    """
    wl = build_workload(cfg)
    blocks = wl["blocks"]

    # A: fault-free baseline — also warms every (kernel, bucket) shape so
    # the hang phase exercises steady-state dispatch, not compiles
    FAULTS.clear()
    baseline = Consensus(wl["main"].params)
    for b in blocks:
        _insert(baseline, b)
    base_fp = _fingerprints(baseline)

    # B: supervision on.  Warm the canary's own (schnorr, bucket-8) shape
    # first — the hostile script mix may never dispatch that shape, and a
    # canary that compiles under a drill-shortened deadline would read as
    # a recovery failure that is really a cold jit
    from kaspa_tpu.crypto import secp

    breaker = device_breaker()
    breaker.reset()
    t_warm = time.perf_counter()
    canary_warm = secp.canary_probe()
    canary_warm_s = round(time.perf_counter() - t_warm, 3)
    before = REGISTRY.snapshot()["counters"]
    pool_before = supervisor._POOL.snapshot()
    supervisor.install(pretrace=False)
    schedule = {
        "device.hang": {
            "mode": "hang",
            "delay": hang_delay_s,
            "hits": list(hang_hits),
            "max": len(hang_hits),
        }
    }
    try:
        # C: out-of-order replay under dispatch hangs
        FAULTS.configure(schedule, seed)
        faulted = Consensus(wl["main"].params)
        t0 = time.perf_counter()
        with supervisor.deadline_overrides(
            dispatch_s=dispatch_deadline_s,
            compile_s=max(30.0, 6.0 * dispatch_deadline_s),
        ):
            _orphan_tolerant_replay(faulted, blocks, seed)
            hang_events = FAULTS.events()
            FAULTS.clear()
            recovered_after_hangs = _await_recovery(breaker, recovery_timeout_s)

            # D: compile-tier stall on a cold bucket
            compile_stall = _compile_stall_drill(seed, stall_delay_s, compile_deadline_s)
            recovered = _await_recovery(breaker, recovery_timeout_s)
        elapsed = time.perf_counter() - t0
        fp = _fingerprints(faulted)

        injected = len(hang_events) + compile_stall["injected"]
        late_seen = _await_late_results(
            injected, pool_before["late_results"], timeout_s=hang_delay_s + 10.0
        )
        # snapshot while supervision (managed) is live
        brk_stable, brk_wall = _split_breaker(breaker.snapshot())
    finally:
        FAULTS.clear()
        supervisor.shutdown()
    after = REGISTRY.snapshot()["counters"]
    pool_after = supervisor._POOL.snapshot()

    requeued = _delta(before, after, "secp_watchdog_requeued_total")
    from kaspa_tpu.ops import dispatch as coalesce

    eng = coalesce.active()
    tickets = {"coalescing": eng is not None}
    if eng is not None:
        tickets.update(eng.stats())
    unresolved = int(tickets.get("unresolved_chunks", 0))
    tickets["ok"] = unresolved == 0 and not tickets.get("abandoned", False)

    report = {
        "config": {
            **asdict(cfg),
            "fault_seed": seed,
            "schedule": schedule,
            "hang_delay_s": hang_delay_s,
            "dispatch_deadline_s": dispatch_deadline_s,
            "stall_delay_s": stall_delay_s,
            "compile_deadline_s": compile_deadline_s,
        },
        "deterministic": {
            "blocks": len(blocks),
            "events": hang_events,
            "fingerprints": fp,
            "fault_free_fingerprints": base_fp,
            "matches_fault_free": fp == base_fp,
        },
        "supervisor": {
            "injected_hangs": injected,
            "hang_phase_events": len(hang_events),
            "canary_warm": canary_warm,
            "canary_warm_seconds": canary_warm_s,
            "requeued_total": requeued,
            "requeue_matches_injected": requeued == injected,
            "requeued_jobs": _delta(before, after, "secp_watchdog_requeued_jobs"),
            "watchdog_timeouts": _delta(before, after, "secp_watchdog_timeouts"),
            "abandoned_threads": pool_after["abandoned_threads"] - pool_before["abandoned_threads"],
            "late_results": late_seen,
            "canary_probes": _delta(before, after, "secp_watchdog_canary_probes"),
            "recovered_after_hangs": recovered_after_hangs,
            "recovered": recovered,
            "verdict": supervisor.verdict(),
        },
        "compile_stall": compile_stall,
        "tickets": tickets,
        "breaker": brk_stable,
        "kernel_cache": supervisor.cache_report(),
        "metrics": {
            "replay_seconds": round(elapsed, 3),
            "blocks_per_sec": round(len(blocks) / elapsed, 2) if elapsed else None,
            "fault_injections": _delta(before, after, "fault_injections"),
            **{name: _delta(before, after, name) for name in _DELTA_COUNTERS},
        },
        "run_meta": run_meta(wall={"breaker": brk_wall}),
    }
    if out:
        with open(out, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
    return report
