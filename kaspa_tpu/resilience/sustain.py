"""Hostile-load sustain run: the chaos-engineering acceptance harness.

Builds a hostile workload (multisig/P2SH script mix that bypasses the
device fast path, plus an attacker side-DAG forking from genesis with
~1.5x the block count — a deep reorg when its heavier chain lands), then
replays it twice into fresh consensus instances:

  1. fault-free, in build order — the baseline fingerprints
  2. under a seeded fault schedule, delivered in shuffled windows through
     an orphan-tolerant queue (blocks held until their parents arrive)

and asserts the post-recovery end state (sink, utxo_commitment,
virtual_daa_score) is identical.  Every injected fault is transient
infrastructure noise — a device dispatch that errors into the breaker's
degraded lane, a VM fallback job that retries — so the faulted run must
converge to the byte-identical fault-free state; ``matches_fault_free``
in SUSTAIN.json is the acceptance bit.

The report splits deterministic data (fault event log, fingerprints)
from wall-clock data (throughput, breaker recovery latencies, lock-hold
traces): two runs of the same workload + schedule + seed produce
byte-identical ``deterministic`` sections.
"""

from __future__ import annotations

import json
import random
import time
from dataclasses import asdict, replace

from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.observability.core import REGISTRY
from kaspa_tpu.resilience.breaker import device_breaker
from kaspa_tpu.resilience.faults import FAULTS
from kaspa_tpu.sim.simulator import SimConfig, simulate
from kaspa_tpu.utils.sync import lock_trace_snapshot, set_lock_debug

# metric counters whose faulted-replay deltas land in SUSTAIN.json
_DELTA_COUNTERS = (
    "secp_degraded_dispatches",
    "secp_degraded_jobs",
    "txscript_vm_fault_retries",
    "kv_journal_repairs",
)


def default_schedule() -> dict:
    """The stock hostile schedule: four consecutive device-verify errors
    (trips the breaker, then fails its first probe — exercising trip,
    degraded lane, backoff doubling, and recovery) plus every-5th VM
    fallback job erroring (exercising the retry lane), capped at 8."""
    return {
        "device.verify": {"mode": "error", "hits": [2, 3, 4, 5]},
        "vm.fallback.exec": {"mode": "error", "every": 5, "max": 8},
    }


def build_workload(cfg: SimConfig) -> dict:
    """Hostile main DAG plus an attacker fork from the same genesis.

    The attacker sim runs with seed+1 (distinct miners/keys) and ~1.5x
    the blocks, so once its blocks are all delivered its chain carries
    more blue work and the virtual reorgs deep past the main DAG."""
    main = simulate(cfg)
    attacker = simulate(
        replace(cfg, num_blocks=max(cfg.num_blocks * 3 // 2, cfg.num_blocks + 1), seed=cfg.seed + 1)
    )
    return {"cfg": cfg, "main": main, "attacker": attacker, "blocks": main.blocks + attacker.blocks}


def _fingerprints(consensus: Consensus) -> dict:
    sink = consensus.sink()
    return {
        "sink": sink.hex(),
        "utxo_commitment": consensus.multisets[sink].finalize().hex(),
        "virtual_daa_score": consensus.get_virtual_daa_score(),
    }


def _insert(consensus: Consensus, block) -> None:
    status = consensus.validate_and_insert_block(block)
    assert status in ("utxo_valid", "utxo_pending"), f"sustain replay rejected block: {status}"


def _orphan_tolerant_replay(consensus: Consensus, blocks: list, seed: int, window: int = 8) -> None:
    """Deliver ``blocks`` in deterministically shuffled windows; a block
    whose parents have not arrived is parked and flushed once they do —
    the orphan-pool discipline a real node applies to out-of-order
    gossip, here driving the faulted run's out-of-order stress."""
    rng = random.Random(seed ^ 0x5EED)
    order: list = []
    for i in range(0, len(blocks), window):
        chunk = list(blocks[i : i + window])
        rng.shuffle(chunk)
        order.extend(chunk)

    def ready(b) -> bool:
        return all(consensus.storage.headers.has(p) for p in b.header.direct_parents())

    pending: dict[bytes, object] = {}
    for b in order:
        if not ready(b):
            pending[b.hash] = b
            continue
        _insert(consensus, b)
        progress = True
        while progress:
            progress = False
            for h, pb in list(pending.items()):
                if ready(pb):
                    del pending[h]
                    _insert(consensus, pb)
                    progress = True
    assert not pending, f"{len(pending)} orphans never became insertable"


def _counter_value(counters: dict, name: str):
    v = counters.get(name, 0)
    return dict(v) if isinstance(v, dict) else v


def _delta(before: dict, after: dict, name: str):
    b, a = _counter_value(before, name), _counter_value(after, name)
    if isinstance(a, dict):
        b = b if isinstance(b, dict) else {}
        return {k: a[k] - b.get(k, 0) for k in sorted(a) if a[k] - b.get(k, 0)}
    return a - (b if isinstance(b, (int, float)) else 0)


def run_sustain(
    cfg: SimConfig,
    schedule: dict | None = None,
    seed: int = 0,
    out: str | None = None,
    workload: dict | None = None,
) -> dict:
    """Run the hostile sustain benchmark; returns (and optionally writes
    to ``out``) the SUSTAIN.json report dict."""
    schedule = default_schedule() if schedule is None else schedule
    wl = workload if workload is not None else build_workload(cfg)
    blocks = wl["blocks"]

    # fault-free baseline first, while nothing is armed
    FAULTS.clear()
    baseline = Consensus(wl["main"].params)
    for b in blocks:
        _insert(baseline, b)
    base_fp = _fingerprints(baseline)

    breaker = device_breaker()
    breaker.reset()
    set_lock_debug(True)
    before = REGISTRY.snapshot()["counters"]
    FAULTS.configure(schedule, seed)
    try:
        faulted = Consensus(wl["main"].params)
        t0 = time.perf_counter()
        _orphan_tolerant_replay(faulted, blocks, seed)
        elapsed = time.perf_counter() - t0
        events = FAULTS.events()
    finally:
        FAULTS.clear()
        set_lock_debug(False)
    after = REGISTRY.snapshot()["counters"]
    fp = _fingerprints(faulted)

    report = {
        "config": {**asdict(cfg), "fault_seed": seed, "schedule": schedule},
        "deterministic": {
            "blocks": len(blocks),
            "events": events,
            "fingerprints": fp,
            "fault_free_fingerprints": base_fp,
            "matches_fault_free": fp == base_fp,
        },
        "breaker": breaker.snapshot(),
        "metrics": {
            "replay_seconds": round(elapsed, 3),
            "blocks_per_sec": round(len(blocks) / elapsed, 2) if elapsed else None,
            "fault_injections": _delta(before, after, "fault_injections"),
            **{name: _delta(before, after, name) for name in _DELTA_COUNTERS},
        },
        "lock_traces": lock_trace_snapshot(),
    }
    if out:
        with open(out, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
    return report
