"""Hostile transaction-flood harness: the ingest tier's chaos acceptance run.

Builds a DAG with the simulator, then replays it into a fresh consensus
at a *true* blocks-per-second cadence while a deterministic adversary
floods the ingest tier between block deliveries:

- **clean spends** — valid P2PK spends of mature miner UTXOs, paying out
  to a flood-owned key (so their ids can never collide with in-block
  txs); these are the fraction the sustained-acceptance gate measures;
- **double-spend chains** — bursts of conflicting spends of an outpoint
  a clean flood tx already spent, each id-distinct via a skewed output
  split; the pool must reject every one (tx-double-spend / tx-rbf-rejected);
- **RBF churn** — fee-escalating replacement chains on one outpoint;
  each link must evict its predecessor, thrashing the frontier and the
  template cache (the debounce knob is what bounds the rebuild cost);
- **orphan storms** — children of a withheld parent tx, parked in the
  orphan pool on the missing-input path without ever touching verify.

All flood traffic rides ``IngestTier.submit`` + ``pump`` (alternating
rpc/p2p source lanes), so waves batch onto the verify plane under the
``standalone_tx`` traffic class while the configured fault schedule
(device-verify errors, VM-fallback retries) fires underneath — sustained
admission through the breaker's degraded lane is the point.

Flood transactions are never mined, so consensus state is independent of
the flood by construction: the report's ``matches_fault_free`` compares
the chaos run's end-state fingerprints against a flood-free in-order
baseline, proving the admission tier perturbed nothing.  The new
``ingest`` block records the sustained acceptance rate on the clean
fraction, template-rebuild p50/p99 (from ``mempool_template_rebuild_ms``
scoped to this run), peak mempool/orphan occupancy, and the
lost-ticket count (must be 0: every submission resolves exactly once).
"""

from __future__ import annotations

import json
import random
import time
from collections import deque
from dataclasses import asdict, dataclass

from kaspa_tpu.consensus import hashing as chash
from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.consensus.model import Transaction, TransactionInput, TransactionOutput
from kaspa_tpu.consensus.model.tx import (
    SUBNETWORK_ID_NATIVE,
    ComputeCommit,
    TransactionOutpoint,
    UtxoEntry,
)
from kaspa_tpu.consensus.processes.coinbase import MinerData
from kaspa_tpu.crypto import eclib
from kaspa_tpu.ingest.queue import SOURCE_P2P, SOURCE_RPC
from kaspa_tpu.ingest.tier import ACCEPTED, ORPHANED, IngestTier
from kaspa_tpu.mempool.mining_manager import _TEMPLATE_REBUILD_MS, MiningManager
from kaspa_tpu.observability.core import REGISTRY
from kaspa_tpu.resilience.breaker import device_breaker
from kaspa_tpu.resilience.faults import FAULTS
from kaspa_tpu.resilience.sustain import (
    _DELTA_COUNTERS,
    _delta,
    _fingerprints,
    _insert,
    default_schedule,
)
from kaspa_tpu.sim.simulator import Miner, SimConfig, simulate
from kaspa_tpu.txscript import standard


@dataclass
class TxFloodConfig:
    """Per-block-slot flood rates (one slot per delivered block)."""

    clean_per_block: int = 6
    double_spend_per_block: int = 2  # targeted outpoints per slot
    double_spend_chain: int = 3  # conflicting spends per targeted outpoint
    orphans_per_block: int = 2  # children of the slot's withheld parent
    rbf_per_block: int = 1  # replacement chains opened per slot
    rbf_chain: int = 3  # links per chain (fee escalates each link)
    rbf_fee_step: int = 2_000  # sompi added per replacement link
    seed: int | None = None  # default: sim seed ^ 0xF100D


class FloodStream:
    """Deterministic adversarial tx generator bound to a live consensus.

    Re-derives the simulator's miner keys from the sim seed (the miner
    list is the first thing ``simulate`` draws from its rng), so it can
    sign real spends of any mature in-chain UTXO; pays out to its own
    key so flood txids are disjoint from every in-block txid.
    """

    _KINDS = ("clean", "double_spend", "rbf", "orphan")

    def __init__(self, consensus: Consensus, cfg: SimConfig, flood: TxFloodConfig, rng: random.Random):
        self.consensus = consensus
        self.flood = flood
        self.rng = rng
        mrng = random.Random(cfg.seed)
        self.miners = [Miner(i, mrng, hostile=cfg.hostile) for i in range(cfg.num_miners)]
        self.seckey = rng.randrange(1, eclib.N)
        self.spk = standard.pay_to_pub_key(eclib.schnorr_pubkey(self.seckey))
        self.miner_data = MinerData(self.spk, extra_data=b"txflood")
        self.mass_calc = consensus.transaction_validator.mass_calculator
        self.spent: set[TransactionOutpoint] = set()
        self._recent: deque = deque(maxlen=32)  # (outpoint, entry, seckey) of clean spends
        self.counters: dict[str, int] = {"submitted": 0, "evicted": 0, "other": 0}
        for k in self._KINDS:
            self.counters[f"{k}_submitted"] = 0
        for k in ("clean_accepted", "double_spend_rejected", "double_spend_landed",
                  "orphan_parked", "rbf_replaced", "rbf_opened", "rbf_rejected"):
            self.counters[k] = 0

    # -- candidate UTXOs -----------------------------------------------

    def _seckey_for(self, spk):
        for m in self.miners:
            if m.spk == spk:
                return m.seckey
        return None

    def _candidates(self, limit: int) -> list:
        """Mature miner-owned P2PK UTXOs the flood has not spent yet,
        walking the layered virtual view (simulator tx_selector idiom)."""
        view = self.consensus.get_virtual_utxo_view()
        pov = self.consensus.get_virtual_daa_score()
        maturity = self.consensus.params.coinbase_maturity
        items = list(view.diff.add.items())
        under = view.base
        while hasattr(under, "base"):
            items += list(under.diff.add.items())
            under = under.base
        items += list(under.items())
        removed = set(view.diff.remove.keys())
        out, seen = [], set()
        for outpoint, entry in items:
            if len(out) >= limit:
                break
            if outpoint in seen or outpoint in self.spent or outpoint in removed:
                continue
            seen.add(outpoint)
            if view.get(outpoint) is None:
                continue
            if entry.is_coinbase and entry.block_daa_score + maturity > pov:
                continue
            seckey = self._seckey_for(entry.script_public_key)
            if seckey is None:
                continue
            out.append((outpoint, entry, seckey))
        return out

    @staticmethod
    def _take(cands: list):
        return cands.pop(0) if cands else None

    # -- tx construction ------------------------------------------------

    def _spend(self, outpoint, entry, seckey, fee: int = 0, skew: int = 0) -> Transaction | None:
        """One-input two-output spend to the flood key.  ``fee`` shrinks
        the output sum (RBF feerate ladder); ``skew`` shifts the split so
        conflicting spends of one outpoint get distinct txids (txid
        excludes signature scripts)."""
        amount = entry.amount - fee
        half = amount // 2 - skew
        if half <= 0 or amount - half <= 0:
            return None
        outputs = [TransactionOutput(half, self.spk), TransactionOutput(amount - half, self.spk)]
        inp = TransactionInput(outpoint, b"", 0, ComputeCommit.sigops(1))
        tx = Transaction(0, [inp], outputs, 0, SUBNETWORK_ID_NATIVE, 0, b"")
        tx.storage_mass = self.mass_calc.calc_contextual_masses(tx, [entry])
        reused = chash.SigHashReusedValues()
        msg = chash.calc_schnorr_signature_hash(tx, [entry], 0, chash.SIG_HASH_ALL, reused)
        sig = eclib.schnorr_sign(msg, seckey, self.rng.randbytes(32))
        tx.inputs[0].signature_script = standard.schnorr_signature_script(sig, chash.SIG_HASH_ALL)
        tx._id_cache = None
        return tx

    def _build_slot(self) -> list[tuple[str, Transaction]]:
        f = self.flood
        cands = self._candidates(f.clean_per_block + f.rbf_per_block + 2)
        plan: list[tuple[str, Transaction]] = []
        # reserve rbf/orphan candidates from the tail so a thin UTXO set
        # (early run, post-reorg) doesn't let the clean loop starve them
        n_reserve = min(f.rbf_per_block + (1 if f.orphans_per_block else 0), max(len(cands) - 1, 0))
        reserve = [cands.pop() for _ in range(n_reserve)]
        # double-spend targets: clean spends from *previous* slots only —
        # the source-lane round-robin may reorder a same-slot conflict
        # ahead of its clean target inside the wave
        targets = list(self._recent)

        for _ in range(f.clean_per_block):
            got = self._take(cands)
            if got is None:
                break
            outpoint, entry, seckey = got
            tx = self._spend(outpoint, entry, seckey)
            if tx is None:
                continue
            self.spent.add(outpoint)
            self._recent.append(got)
            plan.append(("clean", tx))

        for _ in range(f.double_spend_per_block):
            if not targets:
                break
            outpoint, entry, seckey = targets[self.rng.randrange(len(targets))]
            for k in range(1, f.double_spend_chain + 1):
                tx = self._spend(outpoint, entry, seckey, skew=k)
                if tx is not None:
                    plan.append(("double_spend", tx))

        for _ in range(f.rbf_per_block):
            got = self._take(reserve) or self._take(cands)
            if got is None:
                break
            outpoint, entry, seckey = got
            self.spent.add(outpoint)
            for k in range(1, f.rbf_chain + 1):
                tx = self._spend(outpoint, entry, seckey, fee=k * f.rbf_fee_step)
                if tx is not None:
                    plan.append(("rbf", tx))

        if f.orphans_per_block:
            got = self._take(reserve) or self._take(cands)
            if got is not None:
                outpoint, entry, seckey = got
                self.spent.add(outpoint)
                parent = self._spend(outpoint, entry, seckey)  # built, never submitted
                if parent is not None:
                    pov = self.consensus.get_virtual_daa_score()
                    n_out = len(parent.outputs)
                    for k in range(f.orphans_per_block):
                        out = parent.outputs[k % n_out]
                        ghost = UtxoEntry(out.value, out.script_public_key, pov, False)
                        child = self._spend(
                            TransactionOutpoint(parent.id(), k % n_out),
                            ghost, self.seckey, skew=k // n_out,
                        )
                        if child is not None:
                            plan.append(("orphan", child))
        return plan

    # -- submission + outcome accounting --------------------------------

    def step(self, tier: IngestTier) -> int:
        """One block slot's worth of flood: submit everything, pump one
        batched wave, classify every resolved ticket."""
        plan = self._build_slot()
        tickets = []
        for i, (kind, tx) in enumerate(plan):
            source = SOURCE_RPC if i % 2 == 0 else SOURCE_P2P
            tickets.append((kind, tier.submit(tx, source)))
        tier.pump()
        for kind, ticket in tickets:
            self._classify(kind, ticket)
        return len(plan)

    def _classify(self, kind: str, t) -> None:
        c = self.counters
        c["submitted"] += 1
        c[f"{kind}_submitted"] += 1
        code = getattr(t.error, "code", None)
        if kind == "clean" and t.status == ACCEPTED:
            c["clean_accepted"] += 1
        elif kind == "double_spend":
            if code in ("tx-double-spend", "tx-rbf-rejected"):
                c["double_spend_rejected"] += 1
            elif t.status == ACCEPTED:
                # the conflicted pool tx was mined/conflicted away first —
                # this spend is now genuinely fresh, count it honestly
                c["double_spend_landed"] += 1
            else:
                c["other"] += 1
        elif kind == "orphan":
            if t.status == ORPHANED:
                c["orphan_parked"] += 1
            else:
                c["other"] += 1
        elif kind == "rbf":
            if t.status == ACCEPTED and t.evicted:
                c["rbf_replaced"] += 1
                c["evicted"] += len(t.evicted)
            elif t.status == ACCEPTED:
                c["rbf_opened"] += 1  # first link of the chain
            elif code == "tx-rbf-rejected":
                c["rbf_rejected"] += 1
            else:
                c["other"] += 1
        elif kind == "clean":
            c["other"] += 1


# --- the paced chaos replay -----------------------------------------------


def _flood_replay(
    consensus: Consensus,
    mining: MiningManager,
    tier: IngestTier,
    flood: FloodStream,
    blocks: list,
    seed: int,
    pace_s: float = 0.0,
    window: int = 8,
) -> dict:
    """Deliver ``blocks`` in shuffled orphan-tolerant windows (sustain.py
    discipline) with one flood slot + one template poll per block, paced
    to ``pace_s`` wall seconds per block when set."""
    rng = random.Random(seed ^ 0x5EED)
    order: list = []
    for i in range(0, len(blocks), window):
        chunk = list(blocks[i : i + window])
        rng.shuffle(chunk)
        order.extend(chunk)

    def ready(b) -> bool:
        return all(consensus.storage.headers.has(p) for p in b.header.direct_parents())

    def land(b) -> None:
        _insert(consensus, b)
        mining.handle_new_block_transactions(list(b.transactions), consensus.get_virtual_daa_score())

    peak_pool = peak_orphans = 0
    pending: dict[bytes, object] = {}
    t0 = time.perf_counter()
    t_next = time.monotonic() + pace_s
    for b in order:
        flood.step(tier)
        # poll the template every slot: with debounce on, a flood slot
        # costs one rebuild per debounce window, not one per tx
        mining.get_block_template(flood.miner_data)
        peak_pool = max(peak_pool, len(mining.mempool.pool))
        peak_orphans = max(peak_orphans, len(mining.mempool.orphans))
        if pace_s:
            now = time.monotonic()
            if t_next > now:
                time.sleep(t_next - now)
            t_next = max(t_next, now) + pace_s
        if not ready(b):
            pending[b.hash] = b
            continue
        land(b)
        progress = True
        while progress:
            progress = False
            for h, pb in list(pending.items()):
                if ready(pb):
                    del pending[h]
                    land(pb)
                    progress = True
    assert not pending, f"{len(pending)} blocks never became insertable"
    return {
        "peak_pool": peak_pool,
        "peak_orphans": peak_orphans,
        "delivery_seconds": time.perf_counter() - t0,
    }


def _rebuild_window(before_counts: list[int], before_count: int, before_sum: float) -> dict:
    """p50/p99 of the template-rebuild histogram scoped to this run
    (bucket-delta quantiles, same upper-edge semantics as Histogram)."""
    h = _TEMPLATE_REBUILD_MS
    counts = [a - b for a, b in zip(h.counts, before_counts)]
    count = h.count - before_count

    def q(qq: float) -> float:
        if count == 0:
            return 0.0
        rank, seen = qq * count, 0
        for i, c in enumerate(counts):
            seen += c
            if seen >= rank and c:
                return h.edges[i] if i < len(h.edges) else float("inf")
        return float("inf")

    return {
        "count": count,
        "sum_ms": round(h.sum - before_sum, 3),
        "p50_ms": q(0.50),
        "p99_ms": q(0.99),
    }


def run_txflood_sustain(
    cfg: SimConfig,
    flood_cfg: TxFloodConfig | None = None,
    schedule: dict | None = None,
    seed: int = 0,
    out: str | None = None,
    pace: bool = True,
    template_debounce: float = 0.25,
) -> dict:
    """The tx-flood sustain benchmark; returns (and optionally writes to
    ``out``) a SUSTAIN.json-shaped report with the extra ``ingest`` block."""
    schedule = default_schedule() if schedule is None else schedule
    flood_cfg = flood_cfg or TxFloodConfig()
    main = simulate(cfg)
    blocks = main.blocks

    # flood-free in-order baseline: the fingerprints the chaos run must hit
    FAULTS.clear()
    baseline = Consensus(main.params)
    for b in blocks:
        _insert(baseline, b)
    base_fp = _fingerprints(baseline)

    breaker = device_breaker()
    breaker.reset()
    before = REGISTRY.snapshot()["counters"]
    rb_counts, rb_count, rb_sum = (
        list(_TEMPLATE_REBUILD_MS.counts),
        _TEMPLATE_REBUILD_MS.count,
        _TEMPLATE_REBUILD_MS.sum,
    )
    FAULTS.configure(schedule, seed)
    try:
        faulted = Consensus(main.params)
        mining = MiningManager(faulted, seed=seed, template_debounce=template_debounce)
        tier = IngestTier(mining)
        frng = random.Random(flood_cfg.seed if flood_cfg.seed is not None else cfg.seed ^ 0xF100D)
        flood = FloodStream(faulted, cfg, flood_cfg, frng)
        t0 = time.perf_counter()
        replay_stats = _flood_replay(
            faulted, mining, tier, flood, blocks, seed,
            pace_s=(1.0 / cfg.bps) if pace and cfg.bps else 0.0,
        )
        elapsed = time.perf_counter() - t0
        events = FAULTS.events()
    finally:
        FAULTS.clear()
    after = REGISTRY.snapshot()["counters"]
    fp = _fingerprints(faulted)
    tier_stats = tier.stats()
    rebuild = _rebuild_window(rb_counts, rb_count, rb_sum)

    fl = flood.counters
    clean_rate = fl["clean_accepted"] / fl["clean_submitted"] if fl["clean_submitted"] else 0.0
    delivery_s = replay_stats["delivery_seconds"]
    report = {
        "config": {
            **asdict(cfg),
            "fault_seed": seed,
            "schedule": schedule,
            "flood": asdict(flood_cfg),
            "paced": bool(pace),
            "template_debounce_s": template_debounce,
        },
        "deterministic": {
            "blocks": len(blocks),
            "events": events,
            "fingerprints": fp,
            "fault_free_fingerprints": base_fp,
            "matches_fault_free": fp == base_fp,
        },
        "breaker": breaker.snapshot(),
        "ingest": {
            "tx_acceptance_rate": round(clean_rate, 4),
            "clean_submitted": fl["clean_submitted"],
            "clean_accepted": fl["clean_accepted"],
            "flood": dict(sorted(fl.items())),
            "template_rebuilds": rebuild["count"],
            "template_rebuild_p50_ms": rebuild["p50_ms"],
            "template_rebuild_p99_ms": rebuild["p99_ms"],
            "template_rebuild_sum_ms": rebuild["sum_ms"],
            "peak_mempool_occupancy": replay_stats["peak_pool"],
            "peak_orphan_occupancy": replay_stats["peak_orphans"],
            "end_mempool_occupancy": len(mining.mempool.pool),
            "lost_tickets": tier_stats["lost"],
            "waves": tier_stats["waves"],
            "tier": tier_stats,
            "bps_target": cfg.bps,
            "actual_bps": round(len(blocks) / delivery_s, 2) if delivery_s else None,
        },
        "metrics": {
            "replay_seconds": round(elapsed, 3),
            "blocks_per_sec": round(len(blocks) / elapsed, 2) if elapsed else None,
            "fault_injections": _delta(before, after, "fault_injections"),
            **{name: _delta(before, after, name) for name in _DELTA_COUNTERS},
        },
    }
    if out:
        with open(out, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
    return report
