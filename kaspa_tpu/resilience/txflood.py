"""Hostile transaction-flood harness: the ingest tier's chaos acceptance run.

Builds a DAG with the simulator, then replays it into a fresh consensus
at a *true* blocks-per-second cadence while a deterministic adversary
floods the ingest tier between block deliveries:

- **clean spends** — valid P2PK spends of mature miner UTXOs, paying out
  to a flood-owned key (so their ids can never collide with in-block
  txs); these are the fraction the sustained-acceptance gate measures;
- **double-spend chains** — bursts of conflicting spends of an outpoint
  a clean flood tx already spent, each id-distinct via a skewed output
  split; the pool must reject every one (tx-double-spend / tx-rbf-rejected);
- **RBF churn** — fee-escalating replacement chains on one outpoint;
  each link must evict its predecessor, thrashing the frontier and the
  template cache (the debounce knob is what bounds the rebuild cost);
- **orphan storms** — children of a withheld parent tx, parked in the
  orphan pool on the missing-input path without ever touching verify.

All flood traffic rides ``IngestTier.submit`` + ``pump`` (alternating
rpc/p2p source lanes), so waves batch onto the verify plane under the
``standalone_tx`` traffic class while the configured fault schedule
(device-verify errors, VM-fallback retries) fires underneath — sustained
admission through the breaker's degraded lane is the point.

Flood transactions are never mined, so consensus state is independent of
the flood by construction: the report's ``matches_fault_free`` compares
the chaos run's end-state fingerprints against a flood-free in-order
baseline, proving the admission tier perturbed nothing.  The new
``ingest`` block records the sustained acceptance rate on the clean
fraction, template-rebuild p50/p99 (from ``mempool_template_rebuild_ms``
scoped to this run), peak mempool/orphan occupancy, and the
lost-ticket count (must be 0: every submission resolves exactly once).
"""

from __future__ import annotations

import json
import queue
import random
import time
from collections import deque
from dataclasses import asdict, dataclass

from kaspa_tpu.consensus import hashing as chash
from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.consensus.model import Transaction, TransactionInput, TransactionOutput
from kaspa_tpu.consensus.model.tx import (
    SUBNETWORK_ID_NATIVE,
    ComputeCommit,
    TransactionOutpoint,
    UtxoEntry,
)
from kaspa_tpu.consensus.processes.coinbase import MinerData
from kaspa_tpu.crypto import eclib
from kaspa_tpu.ingest.queue import SOURCE_P2P, SOURCE_RPC
from kaspa_tpu.ingest.tier import ACCEPTED, ORPHANED, IngestTier
from kaspa_tpu.mempool.mempool import MempoolConfig
from kaspa_tpu.mempool.mining_manager import _TEMPLATE_REBUILD_MS, MiningManager
from kaspa_tpu.notify.notifier import Notification
from kaspa_tpu.observability.core import REGISTRY
from kaspa_tpu.observability.shed import SHED
from kaspa_tpu.resilience.breaker import device_breaker
from kaspa_tpu.resilience.faults import FAULTS
from kaspa_tpu.resilience.overload import LEVELS, NOMINAL, SATURATED, build_controller
from kaspa_tpu.resilience.sustain import (
    _DELTA_COUNTERS,
    _delta,
    _fingerprints,
    _insert,
    _split_breaker,
    default_schedule,
    run_meta,
)
from kaspa_tpu.serving.broadcaster import Subscriber
from kaspa_tpu.sim.simulator import Miner, SimConfig, simulate
from kaspa_tpu.txscript import standard


@dataclass
class TxFloodConfig:
    """Per-block-slot flood rates (one slot per delivered block)."""

    clean_per_block: int = 6
    double_spend_per_block: int = 2  # targeted outpoints per slot
    double_spend_chain: int = 3  # conflicting spends per targeted outpoint
    orphans_per_block: int = 2  # children of the slot's withheld parent
    rbf_per_block: int = 1  # replacement chains opened per slot
    rbf_chain: int = 3  # links per chain (fee escalates each link)
    rbf_fee_step: int = 2_000  # sompi added per replacement link
    seed: int | None = None  # default: sim seed ^ 0xF100D


class FloodStream:
    """Deterministic adversarial tx generator bound to a live consensus.

    Re-derives the simulator's miner keys from the sim seed (the miner
    list is the first thing ``simulate`` draws from its rng), so it can
    sign real spends of any mature in-chain UTXO; pays out to its own
    key so flood txids are disjoint from every in-block txid.
    """

    _KINDS = ("clean", "double_spend", "rbf", "orphan")

    def __init__(self, consensus: Consensus, cfg: SimConfig, flood: TxFloodConfig, rng: random.Random):
        self.consensus = consensus
        self.flood = flood
        self.rng = rng
        mrng = random.Random(cfg.seed)
        self.miners = [Miner(i, mrng, hostile=cfg.hostile) for i in range(cfg.num_miners)]
        self.seckey = rng.randrange(1, eclib.N)
        self.spk = standard.pay_to_pub_key(eclib.schnorr_pubkey(self.seckey))
        self.miner_data = MinerData(self.spk, extra_data=b"txflood")
        self.mass_calc = consensus.transaction_validator.mass_calculator
        self.spent: set[TransactionOutpoint] = set()
        self._recent: deque = deque(maxlen=32)  # (outpoint, entry, seckey) of clean spends
        self.last_build_s = 0.0  # adversary tx-construction cost of the last slot
        self.counters: dict[str, int] = {"submitted": 0, "evicted": 0, "other": 0}
        for k in self._KINDS:
            self.counters[f"{k}_submitted"] = 0
        for k in ("clean_accepted", "double_spend_rejected", "double_spend_landed",
                  "orphan_parked", "rbf_replaced", "rbf_opened", "rbf_rejected",
                  "overload_rejected"):
            self.counters[k] = 0

    # -- candidate UTXOs -----------------------------------------------

    def _seckey_for(self, spk):
        for m in self.miners:
            if m.spk == spk:
                return m.seckey
        return None

    def _candidates(self, limit: int) -> list:
        """Mature miner-owned P2PK UTXOs the flood has not spent yet,
        walking the layered virtual view (simulator tx_selector idiom)."""
        view = self.consensus.get_virtual_utxo_view()
        pov = self.consensus.get_virtual_daa_score()
        maturity = self.consensus.params.coinbase_maturity
        items = list(view.diff.add.items())
        under = view.base
        while hasattr(under, "base"):
            items += list(under.diff.add.items())
            under = under.base
        items += list(under.items())
        removed = set(view.diff.remove.keys())
        out, seen = [], set()
        for outpoint, entry in items:
            if len(out) >= limit:
                break
            if outpoint in seen or outpoint in self.spent or outpoint in removed:
                continue
            seen.add(outpoint)
            if view.get(outpoint) is None:
                continue
            if entry.is_coinbase and entry.block_daa_score + maturity > pov:
                continue
            seckey = self._seckey_for(entry.script_public_key)
            if seckey is None:
                continue
            out.append((outpoint, entry, seckey))
        return out

    @staticmethod
    def _take(cands: list):
        return cands.pop(0) if cands else None

    # -- tx construction ------------------------------------------------

    def _spend(self, outpoint, entry, seckey, fee: int = 0, skew: int = 0) -> Transaction | None:
        """One-input two-output spend to the flood key.  ``fee`` shrinks
        the output sum (RBF feerate ladder); ``skew`` shifts the split so
        conflicting spends of one outpoint get distinct txids (txid
        excludes signature scripts)."""
        amount = entry.amount - fee
        half = amount // 2 - skew
        if half <= 0 or amount - half <= 0:
            return None
        outputs = [TransactionOutput(half, self.spk), TransactionOutput(amount - half, self.spk)]
        inp = TransactionInput(outpoint, b"", 0, ComputeCommit.sigops(1))
        tx = Transaction(0, [inp], outputs, 0, SUBNETWORK_ID_NATIVE, 0, b"")
        tx.storage_mass = self.mass_calc.calc_contextual_masses(tx, [entry])
        reused = chash.SigHashReusedValues()
        msg = chash.calc_schnorr_signature_hash(tx, [entry], 0, chash.SIG_HASH_ALL, reused)
        sig = eclib.schnorr_sign(msg, seckey, self.rng.randbytes(32))
        tx.inputs[0].signature_script = standard.schnorr_signature_script(sig, chash.SIG_HASH_ALL)
        tx._id_cache = None
        return tx

    def _build_slot(self, scale: float = 1.0) -> list[tuple[str, Transaction]]:
        f = self.flood
        # overload-ramp hook: every per-slot rate scales together, so the
        # adversary's tx mix keeps its shape as the flood intensifies
        # (scale=1.0 reproduces the unscaled slot exactly)
        n_clean = int(round(f.clean_per_block * scale))
        n_ds = int(round(f.double_spend_per_block * scale))
        n_rbf = int(round(f.rbf_per_block * scale))
        n_orph = int(round(f.orphans_per_block * scale))
        cands = self._candidates(n_clean + n_rbf + 2)
        plan: list[tuple[str, Transaction]] = []
        # reserve rbf/orphan candidates from the tail so a thin UTXO set
        # (early run, post-reorg) doesn't let the clean loop starve them
        n_reserve = min(n_rbf + (1 if n_orph else 0), max(len(cands) - 1, 0))
        reserve = [cands.pop() for _ in range(n_reserve)]
        # double-spend targets: clean spends from *previous* slots only —
        # the source-lane round-robin may reorder a same-slot conflict
        # ahead of its clean target inside the wave
        targets = list(self._recent)

        for _ in range(n_clean):
            got = self._take(cands)
            if got is None:
                break
            outpoint, entry, seckey = got
            tx = self._spend(outpoint, entry, seckey)
            if tx is None:
                continue
            self.spent.add(outpoint)
            self._recent.append(got)
            plan.append(("clean", tx))

        for _ in range(n_ds):
            if not targets:
                break
            outpoint, entry, seckey = targets[self.rng.randrange(len(targets))]
            for k in range(1, f.double_spend_chain + 1):
                tx = self._spend(outpoint, entry, seckey, skew=k)
                if tx is not None:
                    plan.append(("double_spend", tx))

        for _ in range(n_rbf):
            got = self._take(reserve) or self._take(cands)
            if got is None:
                break
            outpoint, entry, seckey = got
            self.spent.add(outpoint)
            for k in range(1, f.rbf_chain + 1):
                tx = self._spend(outpoint, entry, seckey, fee=k * f.rbf_fee_step)
                if tx is not None:
                    plan.append(("rbf", tx))

        if n_orph:
            got = self._take(reserve) or self._take(cands)
            if got is not None:
                outpoint, entry, seckey = got
                self.spent.add(outpoint)
                parent = self._spend(outpoint, entry, seckey)  # built, never submitted
                if parent is not None:
                    pov = self.consensus.get_virtual_daa_score()
                    n_out = len(parent.outputs)
                    for k in range(n_orph):
                        out = parent.outputs[k % n_out]
                        ghost = UtxoEntry(out.value, out.script_public_key, pov, False)
                        child = self._spend(
                            TransactionOutpoint(parent.id(), k % n_out),
                            ghost, self.seckey, skew=k // n_out,
                        )
                        if child is not None:
                            plan.append(("orphan", child))
        return plan

    # -- submission + outcome accounting --------------------------------

    def step(self, tier: IngestTier, scale: float = 1.0) -> int:
        """One block slot's worth of flood: submit everything, pump one
        batched wave, classify every resolved ticket.  ``scale`` multiplies
        every per-slot rate (the overload ramp); tx-construction wall time
        lands in ``last_build_s`` so cadence measurement can exclude the
        adversary's own signing cost."""
        t_build = time.perf_counter()
        plan = self._build_slot(scale)
        self.last_build_s = time.perf_counter() - t_build
        tickets = []
        for i, (kind, tx) in enumerate(plan):
            source = SOURCE_RPC if i % 2 == 0 else SOURCE_P2P
            tickets.append((kind, tier.submit(tx, source)))
        tier.pump()
        for kind, ticket in tickets:
            self._classify(kind, ticket)
        return len(plan)

    def _classify(self, kind: str, t) -> None:
        c = self.counters
        c["submitted"] += 1
        c[f"{kind}_submitted"] += 1
        code = getattr(t.error, "code", None)
        if code == "node-overloaded":
            # brownout shed at admission: counted on its own, outside the
            # per-kind outcome buckets — the tx never reached the mempool
            c["overload_rejected"] += 1
            return
        if kind == "clean" and t.status == ACCEPTED:
            c["clean_accepted"] += 1
        elif kind == "double_spend":
            if code in ("tx-double-spend", "tx-rbf-rejected"):
                c["double_spend_rejected"] += 1
            elif t.status == ACCEPTED:
                # the conflicted pool tx was mined/conflicted away first —
                # this spend is now genuinely fresh, count it honestly
                c["double_spend_landed"] += 1
            else:
                c["other"] += 1
        elif kind == "orphan":
            if t.status == ORPHANED:
                c["orphan_parked"] += 1
            else:
                c["other"] += 1
        elif kind == "rbf":
            if t.status == ACCEPTED and t.evicted:
                c["rbf_replaced"] += 1
                c["evicted"] += len(t.evicted)
            elif t.status == ACCEPTED:
                c["rbf_opened"] += 1  # first link of the chain
            elif code == "tx-rbf-rejected":
                c["rbf_rejected"] += 1
            else:
                c["other"] += 1
        elif kind == "clean":
            c["other"] += 1


# --- the paced chaos replay -----------------------------------------------


def _flood_replay(
    consensus: Consensus,
    mining: MiningManager,
    tier: IngestTier,
    flood: FloodStream,
    blocks: list,
    seed: int,
    pace_s: float = 0.0,
    window: int = 8,
    scale_fn=None,
    on_slot=None,
) -> dict:
    """Deliver ``blocks`` in shuffled orphan-tolerant windows (sustain.py
    discipline) with one flood slot + one template poll per block, paced
    to ``pace_s`` wall seconds per block when set.

    The overload drill's hooks: ``scale_fn(slot) -> float`` sets the
    flood-rate multiplier per slot; ``on_slot(slot, scale) -> level``
    runs after the slot's node work (samples the controller, drives the
    drill's slow subscriber) and reports the overload level in force.
    Per-slot wall time — minus the adversary's tx-build cost and the
    pacing sleep — lands in ``slot_walls`` so the report can compare
    cadence at NOMINAL vs SATURATED."""
    rng = random.Random(seed ^ 0x5EED)
    order: list = []
    for i in range(0, len(blocks), window):
        chunk = list(blocks[i : i + window])
        rng.shuffle(chunk)
        order.extend(chunk)

    def ready(b) -> bool:
        return all(consensus.storage.headers.has(p) for p in b.header.direct_parents())

    def land(b) -> None:
        _insert(consensus, b)
        mining.handle_new_block_transactions(list(b.transactions), consensus.get_virtual_daa_score())

    peak_pool = peak_orphans = 0
    pending: dict[bytes, object] = {}
    slot_walls: list[float] = []
    slot_levels: list[int] = []
    slot_scales: list[float] = []
    slot_plans: list[int] = []
    t0 = time.perf_counter()
    t_next = time.monotonic() + pace_s
    for i, b in enumerate(order):
        scale = scale_fn(i) if scale_fn is not None else 1.0
        t_slot = time.perf_counter()
        slot_plans.append(flood.step(tier, scale))
        # poll the template every slot: with debounce on, a flood slot
        # costs one rebuild per debounce window, not one per tx
        mining.get_block_template(flood.miner_data)
        peak_pool = max(peak_pool, len(mining.mempool.pool))
        peak_orphans = max(peak_orphans, len(mining.mempool.orphans))
        level = on_slot(i, scale) if on_slot is not None else None
        slot_walls.append(time.perf_counter() - t_slot - flood.last_build_s)
        slot_scales.append(scale)
        if level is not None:
            slot_levels.append(level)
        if pace_s:
            now = time.monotonic()
            if t_next > now:
                time.sleep(t_next - now)
            t_next = max(t_next, now) + pace_s
        if not ready(b):
            pending[b.hash] = b
            continue
        land(b)
        progress = True
        while progress:
            progress = False
            for h, pb in list(pending.items()):
                if ready(pb):
                    del pending[h]
                    land(pb)
                    progress = True
    assert not pending, f"{len(pending)} blocks never became insertable"
    return {
        "peak_pool": peak_pool,
        "peak_orphans": peak_orphans,
        "delivery_seconds": time.perf_counter() - t0,
        "slot_walls": slot_walls,
        "slot_levels": slot_levels,
        "slot_scales": slot_scales,
        "slot_plans": slot_plans,
    }


def _rebuild_window(before_counts: list[int], before_count: int, before_sum: float) -> dict:
    """p50/p99 of the template-rebuild histogram scoped to this run
    (bucket-delta quantiles, same upper-edge semantics as Histogram)."""
    h = _TEMPLATE_REBUILD_MS
    counts = [a - b for a, b in zip(h.counts, before_counts)]
    count = h.count - before_count

    def q(qq: float) -> float:
        if count == 0:
            return 0.0
        rank, seen = qq * count, 0
        for i, c in enumerate(counts):
            seen += c
            if seen >= rank and c:
                return h.edges[i] if i < len(h.edges) else float("inf")
        return float("inf")

    return {
        "count": count,
        "sum_ms": round(h.sum - before_sum, 3),
        "p50_ms": q(0.50),
        "p99_ms": q(0.99),
    }


# --- the overload ramp drill ------------------------------------------------


@dataclass
class OverloadRampConfig:
    """Flood-rate ramp profile for the overload-control acceptance drill.

    Phases, as fractions of the block count: warm at scale 1.0 (the
    cadence baseline), linear ramp 1.0 -> ``peak_scale``, hold at peak
    (where the controller must reach SATURATED and shed), then cooldown
    at scale 0.0 — recovery back to NOMINAL is part of the run, not an
    epilogue."""

    peak_scale: float = 8.0
    warm_frac: float = 0.20
    ramp_frac: float = 0.25
    hold_frac: float = 0.30
    samples_per_slot: int = 2  # controller decisions per block slot
    rise_samples: int = 2
    fall_samples: int = 3
    # per-signal override atop the drill defaults: name -> (elev, sat, crit).
    # The drill re-tunes fanout_depth below DEFAULT_THRESHOLDS because its
    # single subscriber's queue is depth-pinned (~conflate floor 64) once
    # the fanout_conflation action engages at ELEVATED — the SATURATED
    # enter must sit under that pin or the brownout self-stabilizes one
    # level early and the drill never proves the saturated regime.
    thresholds: dict | None = None
    # fanout_lag_ms is parked out of reach: the drill's single consumer
    # parks 0.25 s per sink retry BY DESIGN, so its queue_wait mean is
    # seconds whenever the flood ramps — a real serving tier has a sender
    # crew, the drill has a deliberately wedged one.  The depth signal is
    # the one this drill's cadence was tuned against.
    DRILL_THRESHOLDS = {
        "fanout_depth": (24, 56, 2000),
        "fanout_lag_ms": (1e12, 1e12, 1e12),
    }
    expire_daa: int | None = None  # mempool expiry horizon; default max(6, blocks//6)
    fanout_per_slot: int = 4  # synthetic utxos-changed events per slot at scale 1.0

    def scale_for(self, slot: int, total: int) -> float:
        if total <= 0:
            return 1.0
        frac = slot / total
        if frac < self.warm_frac:
            return 1.0
        if frac < self.warm_frac + self.ramp_frac:
            t = (frac - self.warm_frac) / self.ramp_frac
            return 1.0 + t * (self.peak_scale - 1.0)
        if frac < self.warm_frac + self.ramp_frac + self.hold_frac:
            return self.peak_scale
        return 0.0


class _BlockedSink:
    """Subscriber sink that refuses payloads while ``blocked`` — the
    drill's slow consumer.  The drill blocks it while the flood runs
    above nominal rate (fanout depth builds, conflation engages) and
    unblocks it for cooldown so the fanout pressure signal can actually
    decay.  A blocked put honours ``timeout`` the way a full socket
    queue would — the subscriber's sender retry loop paces on it."""

    def __init__(self):
        self.blocked = False
        self.accepted = 0

    def put(self, item, timeout=None):
        if self.blocked:
            if timeout:
                time.sleep(min(float(timeout), 0.25))
            raise queue.Full
        self.accepted += 1


class _FanoutShim:
    """Adapts the drill's single Subscriber to the two broadcaster-facing
    seams the controller wires: the ``fanout_depth`` pressure signal and
    the ``fanout_conflation`` brownout action."""

    def __init__(self, sub: Subscriber):
        self.sub = sub

    def max_queue_depth(self) -> int:
        return self.sub.queue_depth()

    def set_conflation(self, floor) -> None:
        self.sub.conflate_floor = floor


class _RelayStub:
    """Records INV-damping engagement.  The drill has no live P2P mesh,
    so this proves the action fires (and releases) without synthesizing
    shed counts — real ``inv_damping`` sheds come from the daemon path
    and the unit tests."""

    def __init__(self):
        self.damped = False
        self.engagements = 0

    def set_relay_damping(self, active: bool) -> None:
        if active and not self.damped:
            self.engagements += 1
        self.damped = bool(active)


def run_txflood_sustain(
    cfg: SimConfig,
    flood_cfg: TxFloodConfig | None = None,
    schedule: dict | None = None,
    seed: int = 0,
    out: str | None = None,
    pace: bool = True,
    template_debounce: float = 0.25,
    overload: OverloadRampConfig | None = None,
) -> dict:
    """The tx-flood sustain benchmark; returns (and optionally writes to
    ``out``) a SUSTAIN.json-shaped report with the extra ``ingest`` block.

    With ``overload`` set, the flood ramps per ``OverloadRampConfig``
    while a live ``OverloadController`` (standard signals + brownout
    registry, wired to the run's mining/tier plus a drill fanout
    subscriber and relay stub) is sampled deterministically every slot;
    the report gains the ``overload`` block (level trace, dwell times,
    shed counters, NOMINAL-vs-SATURATED cadence, recovery)."""
    schedule = default_schedule() if schedule is None else schedule
    flood_cfg = flood_cfg or TxFloodConfig()
    main = simulate(cfg)
    blocks = main.blocks

    # flood-free in-order baseline: the fingerprints the chaos run must hit
    FAULTS.clear()
    baseline = Consensus(main.params)
    for b in blocks:
        _insert(baseline, b)
    base_fp = _fingerprints(baseline)

    breaker = device_breaker()
    breaker.reset()
    before = REGISTRY.snapshot()["counters"]
    rb_counts, rb_count, rb_sum = (
        list(_TEMPLATE_REBUILD_MS.counts),
        _TEMPLATE_REBUILD_MS.count,
        _TEMPLATE_REBUILD_MS.sum,
    )
    FAULTS.configure(schedule, seed)
    controller = sink = sub = relay = None
    scale_fn = on_slot = None
    shed_before: dict = {}
    try:
        faulted = Consensus(main.params)
        mp_cfg = None
        if overload is not None:
            # a scaled-down expiry horizon so pool occupancy admitted at
            # peak decays during cooldown block deliveries — controller
            # recovery is gated on the signals genuinely subsiding
            expire = overload.expire_daa
            if expire is None:
                expire = max(6, len(blocks) // 6)
            mp_cfg = MempoolConfig(transaction_expire_interval_daa_score=expire)
        mining = MiningManager(
            faulted, config=mp_cfg, seed=seed, template_debounce=template_debounce
        )
        tier = IngestTier(mining)
        frng = random.Random(flood_cfg.seed if flood_cfg.seed is not None else cfg.seed ^ 0xF100D)
        flood = FloodStream(faulted, cfg, flood_cfg, frng)
        if overload is not None:
            sink = _BlockedSink()
            sub = Subscriber("overload-drill", lambda n: b"x", sink, maxlen=1_000_000)
            relay = _RelayStub()
            drill_thr = dict(OverloadRampConfig.DRILL_THRESHOLDS)
            drill_thr.update(overload.thresholds or {})
            controller = build_controller(
                mining=mining,
                tier=tier,
                broadcaster=_FanoutShim(sub),
                node=relay,
                thresholds=drill_thr,
                rise_samples=overload.rise_samples,
                fall_samples=overload.fall_samples,
            )
            shed_before = dict(SHED.snapshot())
            n_total = len(blocks)

            def scale_fn(i: int) -> float:
                return overload.scale_for(i, n_total)

            def on_slot(i: int, scale: float) -> int:
                # drive the drill's slow consumer: keeps up at nominal
                # rate (clean cadence baseline), falls behind once the
                # flood ramps, catches up during cooldown
                sink.blocked = scale > 1.0
                if scale > 0:
                    for _ in range(max(1, int(round(overload.fanout_per_slot * scale)))):
                        sub.offer(
                            Notification("utxos-changed", {"added": [i], "removed": []}),
                            time.perf_counter_ns(),
                        )
                level = NOMINAL
                for _ in range(max(1, overload.samples_per_slot)):
                    level = controller.sample()
                return level

        t0 = time.perf_counter()
        replay_stats = _flood_replay(
            faulted, mining, tier, flood, blocks, seed,
            pace_s=(1.0 / cfg.bps) if pace and cfg.bps else 0.0,
            scale_fn=scale_fn, on_slot=on_slot,
        )
        elapsed = time.perf_counter() - t0
        events = FAULTS.events()
    finally:
        FAULTS.clear()
    overload_block = None
    if controller is not None:
        # post-run settle: the daemon's ticker keeps sampling after load
        # subsides — give the hysteresis fall path the same chance here,
        # bounded so a stuck signal fails the recovery gate instead of
        # hanging the run
        sink.blocked = False
        settle_samples = 0
        settle_budget = 4 * max(1, overload.fall_samples) * 3
        while controller.level() != NOMINAL and settle_samples < settle_budget:
            controller.sample()
            settle_samples += 1
            time.sleep(0.02)  # let the drill subscriber's sender drain
        ctrl = controller.stats()
        controller.shutdown()
        sub.stop()
        shed_after = SHED.snapshot()
        shed = {
            k: shed_after.get(k, 0) - shed_before.get(k, 0)
            for k in shed_after
            if shed_after.get(k, 0) - shed_before.get(k, 0)
        }
        walls = replay_stats.pop("slot_walls")
        levels = replay_stats.pop("slot_levels")
        scales = replay_stats.pop("slot_scales")
        plans = replay_stats.pop("slot_plans")
        # cadence baseline: nominal slots where the flood actually built
        # work — the early supply-starved slots (coinbase maturity) do
        # near-zero work and would deflate the denominator
        nom_w = [w for w, lv, n in zip(walls, levels, plans) if lv == NOMINAL and n > 0]
        if not nom_w:
            nom_w = [w for w, lv in zip(walls, levels) if lv == NOMINAL]
        sat_w = [w for w, lv in zip(walls, levels) if lv >= SATURATED]
        nom_s = sum(nom_w) / len(nom_w) if nom_w else None
        sat_s = sum(sat_w) / len(sat_w) if sat_w else None
        overload_block = {
            "enabled": True,
            "ramp": asdict(overload),
            "levels": {
                "max": LEVELS[max(levels)] if levels else LEVELS[NOMINAL],
                "final": ctrl["level_name"],
                "per_slot": [LEVELS[lv] for lv in levels],
            },
            "transitions": ctrl["transitions"],
            "dwell_seconds": ctrl["dwell_seconds"],
            "shed": shed,
            "recovered": ctrl["level"] == NOMINAL,
            "settle_samples": settle_samples,
            "cadence": {
                "nominal_slot_s": round(nom_s, 5) if nom_s is not None else None,
                "saturated_slot_s": round(sat_s, 5) if sat_s is not None else None,
                "saturated_over_nominal": (
                    round(sat_s / nom_s, 3) if nom_s and sat_s is not None else None
                ),
                "nominal_slots": len(nom_w),
                "saturated_slots": len(sat_w),
            },
            "signals_last": ctrl["signals"],
            "fanout": {
                "conflated": sub.conflated,
                "dropped": sub.dropped,
                "delivered": sink.accepted,
                "end_depth": sub.queue_depth(),
            },
            "relay_damping_engagements": relay.engagements,
            "overload_rejected": flood.counters["overload_rejected"],
            "peak_scale_slots": sum(1 for s in scales if s == overload.peak_scale),
        }
    else:
        for k in ("slot_walls", "slot_levels", "slot_scales", "slot_plans"):
            replay_stats.pop(k, None)

    after = REGISTRY.snapshot()["counters"]
    fp = _fingerprints(faulted)
    tier_stats = tier.stats()
    rebuild = _rebuild_window(rb_counts, rb_count, rb_sum)

    fl = flood.counters
    clean_rate = fl["clean_accepted"] / fl["clean_submitted"] if fl["clean_submitted"] else 0.0
    delivery_s = replay_stats["delivery_seconds"]
    brk_stable, brk_wall = _split_breaker(breaker.snapshot())
    report = {
        "config": {
            **asdict(cfg),
            "fault_seed": seed,
            "schedule": schedule,
            "flood": asdict(flood_cfg),
            "paced": bool(pace),
            "template_debounce_s": template_debounce,
        },
        "deterministic": {
            "blocks": len(blocks),
            "events": events,
            "fingerprints": fp,
            "fault_free_fingerprints": base_fp,
            "matches_fault_free": fp == base_fp,
        },
        "breaker": brk_stable,
        "ingest": {
            "tx_acceptance_rate": round(clean_rate, 4),
            "clean_submitted": fl["clean_submitted"],
            "clean_accepted": fl["clean_accepted"],
            "flood": dict(sorted(fl.items())),
            "template_rebuilds": rebuild["count"],
            "template_rebuild_p50_ms": rebuild["p50_ms"],
            "template_rebuild_p99_ms": rebuild["p99_ms"],
            "template_rebuild_sum_ms": rebuild["sum_ms"],
            "peak_mempool_occupancy": replay_stats["peak_pool"],
            "peak_orphan_occupancy": replay_stats["peak_orphans"],
            "end_mempool_occupancy": len(mining.mempool.pool),
            "lost_tickets": tier_stats["lost"],
            "waves": tier_stats["waves"],
            "tier": tier_stats,
            "bps_target": cfg.bps,
            "actual_bps": round(len(blocks) / delivery_s, 2) if delivery_s else None,
        },
        "metrics": {
            "replay_seconds": round(elapsed, 3),
            "blocks_per_sec": round(len(blocks) / elapsed, 2) if elapsed else None,
            "fault_injections": _delta(before, after, "fault_injections"),
            **{name: _delta(before, after, name) for name in _DELTA_COUNTERS},
        },
        "run_meta": run_meta(wall={"breaker": brk_wall}),
    }
    if overload_block is not None:
        report["overload"] = overload_block
    if out:
        with open(out, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
    return report
