"""Chaos-engineering layer: deterministic fault injection, the device
circuit breaker, and the hostile-load sustain harness.

- ``faults``: seedable fault-point registry wired into the hot layers
  (device dispatch, P2P transport, storage, VM fallback lane).
- ``breaker``: device-dispatch circuit breaker tripping to the host
  degraded lane, re-probing with exponential backoff.
- ``sustain``: ``sim --hostile`` workload + SUSTAIN.json report
  (ROADMAP item 5).
"""

from kaspa_tpu.resilience.faults import FAULTS, FaultInjected  # noqa: F401
