"""N-node deterministic swarm drills over the real P2P wire.

The many-node counterpart of the single-node sustain harnesses: N full
``p2p.node.Node`` instances live in one process, each with its own
consensus, ingest tier and ``P2PServer``, wired into a full mesh over
loopback sockets — the same machinery the two-daemon proto tests use
pairwise.  A seeded, declarative *scenario schedule* then drives the
fleet through the failure shapes a single node can never exercise:

- ``partition`` / ``heal`` — the LINKS fault plane (resilience/faults.py)
  black-holes frames by (src, dst) identity so each side extends its own
  DAG; heal triggers an explicit pairwise locator pull, because a severed
  link *poisons* relay state (broadcast marks ``peer.known_blocks`` even
  for frames that never left — exactly the lie a real partition tells);
- deep attacker reorgs — a minority side mines a heavier chain in
  isolation and must win fleet-wide at heal;
- ``join`` — a node IBDs into the fleet 100+ blocks late over the
  locator/antipast flow;
- relay-storm accounting — every mined block's INV fans across the mesh;
  per-node ``p2p_msgs_rx{block}`` (namespaced per node through
  ``Registry.scope``) is gated against an O(N x blocks) budget, the
  invariant the ``_block_requested`` in-flight ledger exists to hold.

Determinism: the scheduler is strictly sequential — one block is mined,
then the miner's connected component converges on it before the next —
so parent sets, timestamps (a virtual tick), coinbase payloads and thus
every block hash are functions of (n, seed, scenario) alone.  The
``deterministic`` section of SWARM.json (event log, per-node
fingerprints, fault-free comparison) is byte-identical across runs;
message counts and wall-clock facts are quarantined under ``fleet`` /
``metrics`` / ``run_meta`` per the SUSTAIN.json convention.

Acceptance gates (``sim --swarm N`` exits non-zero otherwise): all nodes
bit-identical in sink + utxo_commitment, the end state matching a
fault-free in-order replay of the same blocks, zero ingest tickets lost
fleet-wide, and block-relay amplification within budget.
"""

from __future__ import annotations

import json
import os
import random
import time

from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.consensus.params import simnet_params
from kaspa_tpu.observability.core import Registry
from kaspa_tpu.p2p.node import MSG_BLOCK, Node
from kaspa_tpu.p2p.transport import P2PServer, WireMetrics, connect_outbound
from kaspa_tpu.resilience.faults import LINKS
from kaspa_tpu.resilience.sustain import _fingerprints, _insert, run_meta
from kaspa_tpu.sim.simulator import Miner


class SwarmError(RuntimeError):
    """A drill invariant failed mid-run (a barrier timed out, a step was
    malformed).  Distinct from gate failures, which land in the report."""


def default_scenario(n: int, blocks: int = 24) -> list[dict]:
    """The stock drill: base chain -> tx gossip -> minority/majority
    partition -> heavier attacker chain -> heal + deep reorg -> a relay
    phase that merges every tip -> late join at depth.

    Node 0 is the attacker (minority side), the last node the late
    joiner (fleets of 3+; a 2-node fleet skips the join).  The relay
    phase runs BEFORE the join on purpose: antipast IBD serves the
    donor sink's *past* only, so a joiner syncing right after the heal
    would miss the losing side's blocks (they sit in the winning sink's
    anticone) — the first post-heal block merges all tips and closes
    that gap, which is exactly what a live network's next template does.
    """
    if n < 2:
        raise SwarmError("swarm needs at least 2 nodes")
    joiner = n - 1 if n >= 3 else None
    active = list(range(n - 1)) if joiner is not None else list(range(n))
    honest = active[1:] if len(active) > 1 else active
    h = max(4, blocks // 6)
    steps = [
        {"op": "mine", "nodes": active, "blocks": blocks},
        {"op": "txs", "node": active[-1], "count": 4},
        {"op": "partition", "groups": [[0], honest]},
        {"op": "mine", "nodes": honest, "blocks": h},
        {"op": "mine", "nodes": [0], "blocks": 2 * h + 2},
        {"op": "heal"},
        {"op": "converge"},
        {"op": "mine", "nodes": active, "blocks": max(6, blocks // 4)},
        {"op": "converge"},
    ]
    if joiner is not None:
        steps += [{"op": "join", "node": joiner}, {"op": "converge"}]
    return steps


def parse_scenario(spec) -> list[dict]:
    """Scenario from CLI input: a step list, ``{"steps": [...]}``, inline
    JSON text, or ``@/path/to/scenario.json``."""
    if isinstance(spec, str):
        if spec.startswith("@"):
            with open(spec[1:]) as f:
                spec = json.load(f)
        else:
            spec = json.loads(spec)
    if isinstance(spec, dict):
        spec = spec.get("steps", [])
    if not isinstance(spec, list) or not all(isinstance(s, dict) and "op" in s for s in spec):
        raise SwarmError("scenario must be a list of {'op': ...} steps (or {'steps': [...]})")
    return spec


class SwarmNode:
    """One fleet member: Node + consensus + listener + miner identity.

    The identity nonce is pinned to ``index + 1`` (the version handshake
    advertises it, the LINKS plane partitions on it) and the wire metrics
    are scoped to ``node<i>_`` inside the run's private registry, so N
    instances never collide on the process-global instrument names."""

    def __init__(self, index: int, params, seed: int, registry: Registry):
        self.index = index
        self.ident = index + 1
        self.node = Node(Consensus(params), name=f"swarm{index}", mempool_seed=seed, ident=self.ident)
        self.node.wire_metrics = WireMetrics(registry.scope(f"node{index}"))
        self.miner = Miner(index, random.Random((seed << 8) ^ index))
        self.server = P2PServer(self.node, port=0)
        self.joined = False

    def start(self) -> None:
        self.server.start()

    def stop(self) -> None:
        self.server.stop()
        self.node.shutdown()


class SwarmRun:
    """Scenario interpreter over a live fleet; produces the SWARM report."""

    def __init__(self, n: int, seed: int = 7, scenario: list[dict] | None = None,
                 blocks: int = 24, bps: int = 2):
        if n < 2:
            raise SwarmError("swarm needs at least 2 nodes")
        self.n = n
        self.seed = int(seed)
        self.params = simnet_params(bps=bps)
        self.scenario = scenario if scenario is not None else default_scenario(n, blocks)
        self.registry = Registry()  # private: two same-seed runs both start at zero
        self.converge_timeout = float(os.environ.get("KASPA_TPU_SWARM_CONVERGE_TIMEOUT", "60"))
        self.amp_budget = float(os.environ.get("KASPA_TPU_SWARM_AMP_BUDGET", "1.5"))
        self.nodes: list[SwarmNode] = []
        self.mined: list = []  # Block objects in global mined order
        self.events: list[dict] = []
        self.groups: list[list[int]] | None = None  # None = full connectivity
        self.tick = 0  # virtual clock: block timestamps are 10_000 + 600*tick
        self.converge_walls: list[float] = []

    # -- plumbing ----------------------------------------------------------

    def _joined(self) -> list[int]:
        return [sn.index for sn in self.nodes if sn.joined]

    def _component(self, idx: int) -> list[int]:
        """Joined nodes reachable from ``idx`` under the current partition
        (an index absent from every group keeps full mesh connectivity)."""
        joined = self._joined()
        if self.groups is None:
            return joined
        for g in self.groups:
            if idx in g:
                return [i for i in g if i in joined]
        return joined

    def _wait(self, predicate, what: str, timeout: float | None = None) -> float:
        timeout = self.converge_timeout if timeout is None else timeout
        t0 = time.monotonic()
        deadline = t0 + timeout
        while time.monotonic() < deadline:
            if predicate():
                return time.monotonic() - t0
            time.sleep(0.01)
        raise SwarmError(f"timed out after {timeout}s waiting for {what}")

    def _wait_valid(self, sn: SwarmNode, h: bytes) -> None:
        def have() -> bool:
            with sn.node.lock:
                return bool(sn.node.consensus.storage.statuses.is_valid(h))

        self._wait(have, f"node{sn.index} to validate block {h.hex()[:12]}")

    # -- lifecycle ---------------------------------------------------------

    def _start_fleet(self) -> None:
        late = {s["node"] for s in self.scenario if s.get("op") == "join"}
        for i in range(self.n):
            sn = SwarmNode(i, self.params, self.seed, self.registry)
            sn.joined = i not in late
            sn.start()
            self.nodes.append(sn)
        joined = self._joined()
        # full mesh among the initially-joined: one TCP connection per
        # unordered pair (relay is bidirectional over it), dialer = higher
        # index so the wiring order is reproducible
        for j in joined:
            for i in joined:
                if i < j:
                    connect_outbound(self.nodes[j].node, self.nodes[i].server.address)
        expected = len(joined) - 1
        for idx in joined:
            node = self.nodes[idx].node
            self._wait(
                lambda node=node: len(node.peers) >= expected
                and all(p.handshaken for p in list(node.peers)),
                f"node{idx} mesh handshakes",
            )
        self.events.append({"op": "start", "nodes": self.n, "joined": joined})

    def _teardown(self) -> None:
        LINKS.reset()
        for sn in self.nodes:
            try:
                sn.stop()
            except Exception:  # noqa: BLE001 - teardown is best-effort
                pass

    # -- scenario steps ----------------------------------------------------

    def _step_mine(self, step: dict) -> dict:
        nodes = list(step.get("nodes") or [step["node"]])
        count = int(step.get("blocks", 1))
        hashes = []
        for k in range(count):
            sn = self.nodes[nodes[k % len(nodes)]]
            ts = 10_000 + 600 * self.tick
            self.tick += 1
            with sn.node.lock:
                # graftlint: allow(blocking-under-lock) -- the node lock is the serialization point for consensus mutation (every p2p handler runs under it); template build legitimately waits on verify dispatch there
                block = sn.node.consensus.build_block_template(sn.miner.miner_data, [], timestamp=ts)
                # graftlint: allow(blocking-under-lock) -- same serialization point: submit inserts + unorphans synchronously, the sequential scheduler depends on it
                sn.node.submit_block(block)
            self.mined.append(block)
            hashes.append(block.hash)
            # component barrier: every reachable node validates this block
            # before the next template is built, so parent sets (and thus
            # hashes) are functions of the schedule alone
            for j in self._component(sn.index):
                if j != sn.index:
                    self._wait_valid(self.nodes[j], block.hash)
        return {"nodes": nodes, "blocks": [h.hex() for h in hashes]}

    def _step_partition(self, step: dict) -> dict:
        groups = [list(g) for g in step["groups"]]
        severed = LINKS.partition([[self.nodes[i].ident for i in g] for g in groups])
        self.groups = groups
        return {"groups": groups, "severed": severed}

    def _step_heal(self, _step: dict) -> dict:
        LINKS.heal()
        self.groups = None
        # explicit pairwise locator pull: the blackhole poisoned relay
        # state (broadcast_block marked known_blocks for dropped INVs), so
        # gossip alone never re-offers the missed blocks — each node asks
        # every peer to serve the antipast above their common chain block,
        # the same path a real IBD catch-up takes
        from kaspa_tpu.consensus.processes.sync import SyncManager
        from kaspa_tpu.p2p.node import MSG_IBD_BLOCK_LOCATOR

        for idx in self._joined():
            node = self.nodes[idx].node
            with node.lock:  # consensus read only; the sends happen unlocked
                sm = SyncManager(node.consensus)
                locator = sm.create_block_locator_from_pruning_point(
                    node.consensus.sink(), node.consensus.pruning_processor.pruning_point
                )
                peers = list(node.peers)
            for peer in peers:
                peer.send(MSG_IBD_BLOCK_LOCATOR, locator)
        return {}

    def _step_converge(self, step: dict) -> dict:
        joined = self._joined()

        def sinks() -> list[bytes]:
            out = []
            for i in joined:
                node = self.nodes[i].node
                with node.lock:
                    out.append(node.consensus.sink())
            return out

        wall = self._wait(
            lambda: len(set(sinks())) == 1,
            f"sink convergence across nodes {joined}",
            timeout=step.get("timeout"),
        )
        self.converge_walls.append(round(wall, 3))
        return {"sink": sinks()[0].hex(), "nodes": joined}

    def _step_join(self, step: dict) -> dict:
        idx = int(step["node"])
        sn = self.nodes[idx]
        if sn.joined:
            raise SwarmError(f"node{idx} is already joined")
        depth = len(self.mined)
        sn.joined = True
        for other in self._joined():
            if other == idx:
                continue
            peer = connect_outbound(sn.node, self.nodes[other].server.address)
            # ibd_from only sends the chain-info request (no consensus
            # access); the response flows run under the reader's node lock
            sn.node.ibd_from(peer)
        return {"node": idx, "depth": depth}

    def _step_txs(self, step: dict) -> dict:
        idx = int(step.get("node", 0))
        count = int(step.get("count", 4))
        sn = self.nodes[idx]
        txs = self._build_spends(sn, count)
        if not txs:
            raise SwarmError("no mature UTXOs for the txs step (mine past coinbase maturity first)")
        for tx in txs:
            sn.node.submit_transaction(tx)  # ingest front door; relays via INV
        txids = [tx.id() for tx in txs]
        comp = self._component(idx)
        for j in comp:
            node = self.nodes[j].node

            def pooled(node=node) -> bool:
                with node.lock:
                    pool = node.mining.mempool
                    return all(pool.has(t) or t in pool.accepted for t in txids)

            self._wait(pooled, f"node{j} mempool to hold the gossiped txs")
        return {"node": idx, "txids": [t.hex() for t in txids], "gossiped_to": comp}

    def _build_spends(self, sn: SwarmNode, count: int) -> list:
        """Deterministic clean P2PK spends of mature miner coinbase UTXOs,
        paying back to the submitting node's miner (txflood's spend idiom;
        txids are signature-independent, so the event log stays stable)."""
        from kaspa_tpu.consensus import hashing as chash
        from kaspa_tpu.consensus.model import Transaction, TransactionInput, TransactionOutput
        from kaspa_tpu.consensus.model.tx import SUBNETWORK_ID_NATIVE, ComputeCommit
        from kaspa_tpu.crypto import eclib
        from kaspa_tpu.txscript import standard

        rng = random.Random((self.seed << 16) ^ 0x7A5)
        key_by_spk = {n.miner.spk: n.miner.seckey for n in self.nodes}
        consensus = sn.node.consensus
        with sn.node.lock:
            view = consensus.get_virtual_utxo_view()
            pov = consensus.get_virtual_daa_score()
            maturity = consensus.params.coinbase_maturity
            items = list(view.diff.add.items())
            under = view.base
            while hasattr(under, "base"):
                items += list(under.diff.add.items())
                under = under.base
            items += list(under.items())
            removed = set(view.diff.remove.keys())
            cands, seen = [], set()
            for outpoint, entry in items:
                if outpoint in seen or outpoint in removed or view.get(outpoint) is None:
                    continue
                seen.add(outpoint)
                if entry.is_coinbase and entry.block_daa_score + maturity > pov:
                    continue
                seckey = key_by_spk.get(entry.script_public_key)
                if seckey is not None:
                    cands.append((outpoint, entry, seckey))
        cands.sort(key=lambda c: (c[0].transaction_id, c[0].index))
        mass_calc = consensus.transaction_validator.mass_calculator
        txs = []
        for outpoint, entry, seckey in cands[:count]:
            half = entry.amount // 2
            if half <= 0:
                continue
            outputs = [TransactionOutput(half, sn.miner.spk), TransactionOutput(entry.amount - half, sn.miner.spk)]
            inp = TransactionInput(outpoint, b"", 0, ComputeCommit.sigops(1))
            tx = Transaction(0, [inp], outputs, 0, SUBNETWORK_ID_NATIVE, 0, b"")
            tx.storage_mass = mass_calc.calc_contextual_masses(tx, [entry])
            reused = chash.SigHashReusedValues()
            msg = chash.calc_schnorr_signature_hash(tx, [entry], 0, chash.SIG_HASH_ALL, reused)
            sig = eclib.schnorr_sign(msg, seckey, rng.randbytes(32))
            tx.inputs[0].signature_script = standard.schnorr_signature_script(sig, chash.SIG_HASH_ALL)
            tx._id_cache = None
            txs.append(tx)
        return txs

    _STEPS = {
        "mine": _step_mine,
        "partition": _step_partition,
        "heal": _step_heal,
        "converge": _step_converge,
        "join": _step_join,
        "txs": _step_txs,
    }

    def _apply(self, i: int, step: dict) -> None:
        op = step.get("op")
        fn = self._STEPS.get(op)
        if fn is None:
            raise SwarmError(f"unknown scenario op {op!r} at step {i}")
        facts = fn(self, step)
        self.events.append({"step": i, "op": op, **facts})

    # -- the run -----------------------------------------------------------

    def run(self, out: str | None = None) -> dict:
        LINKS.reset()
        t_run = time.perf_counter()
        try:
            self._start_fleet()
            for i, step in enumerate(self.scenario):
                self._apply(i, step)
            report = self._report(time.perf_counter() - t_run)
        finally:
            self._teardown()
        if out:
            with open(out, "w") as f:
                json.dump(report, f, indent=2, sort_keys=True)
                f.write("\n")
        return report

    def _report(self, wall: float) -> dict:
        fps = {}
        for sn in self.nodes:
            with sn.node.lock:
                fps[f"node{sn.index}"] = _fingerprints(sn.node.consensus)
        converged = len({json.dumps(v, sort_keys=True) for v in fps.values()}) == 1

        # fault-free comparison: the same blocks, in mined order, into one
        # fresh consensus — partitions, reorg relays and IBD must have been
        # pure transport noise
        baseline = Consensus(self.params)
        for b in self.mined:
            _insert(baseline, b)
        base_fp = _fingerprints(baseline)
        matches = converged and all(v == base_fp for v in fps.values())

        tickets = {}
        for sn in self.nodes:
            s = sn.node.ingest.stats()
            tickets[f"node{sn.index}"] = {k: s[k] for k in ("submitted", "resolved", "lost")}
        lost = sum(t["lost"] for t in tickets.values())

        counters = self.registry.snapshot()["counters"]
        block_rx = {
            f"node{sn.index}": counters.get(f"node{sn.index}_p2p_msgs_rx", {}).get(MSG_BLOCK, 0)
            for sn in self.nodes
        }
        total_rx = sum(block_rx.values())
        budget = self.amp_budget * self.n * max(len(self.mined), 1)
        amp = total_rx / (self.n * max(len(self.mined), 1))

        report = {
            "config": {
                "n": self.n,
                "seed": self.seed,
                "params": self.params.name,
                "amp_budget": self.amp_budget,
                "scenario": self.scenario,
            },
            "deterministic": {
                "blocks": len(self.mined),
                "events": self.events,
                "fingerprints": fps,
                "converged": converged,
                "fault_free_fingerprints": base_fp,
                "matches_fault_free": matches,
            },
            "fleet": {
                "tickets": tickets,
                "lost_tickets": lost,
                "relay": {
                    "block_rx_by_node": block_rx,
                    "total_block_rx": total_rx,
                    "budget": budget,
                    "amplification": round(amp, 3),
                    "amp_ok": total_rx <= budget,
                },
                "links": LINKS.snapshot(),
            },
            "metrics": {
                "wall_seconds": round(wall, 3),
                "converge_seconds": self.converge_walls,
            },
            "run_meta": run_meta(),
        }
        return report


def run_swarm(n: int, seed: int = 7, scenario=None, blocks: int = 24, bps: int = 2,
              out: str | None = None) -> dict:
    """Build, run and (optionally) persist one swarm drill."""
    if scenario is not None:
        scenario = parse_scenario(scenario)
    return SwarmRun(n, seed=seed, scenario=scenario, blocks=blocks, bps=bps).run(out=out)


def gates(report: dict) -> dict:
    """The drill's acceptance bits, in one place for the CLI and tests."""
    det, fleet = report["deterministic"], report["fleet"]
    return {
        "converged": det["converged"],
        "matches_fault_free": det["matches_fault_free"],
        "lost_tickets_ok": fleet["lost_tickets"] == 0,
        "amp_ok": fleet["relay"]["amp_ok"],
    }
