"""Deterministic, seedable fault-injection registry.

Fault points are named strings compiled into the hot layers:

    device.verify        batch signature dispatch (ops/secp256k1/verify.py)
    device.hang          same site, for mode "hang"/"wedge" dispatch hangs
                         observed by the supervision watchdog
    device.jit_compile   first-compile of a (kernel, bucket) shape
                         (crypto/secp.py cold-bucket path)
    device.mesh.dispatch sharded shard_map dispatch (ops/mesh.py)
    vm.fallback.exec     one deferred VM fallback job (txscript/batch.py)
    p2p.send             outgoing frame (p2p/transport.py)
    p2p.recv             incoming frame read (p2p/transport.py)
    p2p.partition        frame black-holed across a severed link (send
                         path); the LINKS plane below is the programmatic
                         control surface the swarm scheduler drives
    p2p.link_drop        outbound dial (p2p/transport.py connect_outbound);
                         mode "error" fails the dial before the handshake —
                         the daemon's bounded connect retry absorbs it
    storage.commit       write-batch commit (storage/kv.py, both engines)
    storage.flush        python-engine log append (storage/kv.py)
    fabric.send          outgoing verify-fabric request (fabric/client.py);
                         cooperative modes sever/corrupt/drop the frame,
                         the balancer fails over to the next slice
    fabric.recv          incoming verify-fabric frame (fabric/client.py)
    fabric.slice_hang    verifyd slice worker pre-dispatch (fabric/
                         service.py): mode "slow"/"hang" stalls the slice
                         past the balancer's deadline so the per-slice
                         breaker trips with cause ``hung``

A *schedule* maps point name -> spec dict:

    {"device.verify":    {"mode": "error", "hits": [2, 3, 4]},
     "vm.fallback.exec": {"mode": "error", "every": 5, "max": 8},
     "p2p.send":         {"mode": "corrupt", "after": 3, "max": 3}}

Selection is by **hit index** (1-based count of times the point is
reached), never by wall clock or unseeded randomness: hit ``k`` fires iff
``k in hits``, or ``every and k % every == 0``, or ``after and k >=
after`` — bounded by ``max`` total firings per point.  Two runs of the
same workload under the same schedule therefore fire the same hits, and
the event log (sorted by ``(point, hit)`` since pool threads may reach a
point concurrently) is byte-identical.

Modes:

    error      raise FaultInjected at the point
    wedge      sleep ``delay`` (default 0.05s) then raise FaultWedged —
               a batch that hangs, then dies (a real hang would pin the
               test harness)
    slow       sleep ``delay`` (default 0.02s), then continue normally
    stall      alias of slow (peer-stall reads)
    hang       sleep ``delay`` (default 0.05s), then continue — a dispatch
               that completes *after* its supervisor already gave up on
               it: the late result must be discarded, the batch must have
               been requeued exactly once (the wedge-drill invariant)
    corrupt / truncate / drop / disconnect / partial
               cooperative: ``fire`` returns a FaultAction the call site
               applies (flip frame bytes, cut a frame short, drop it,
               sever the connection, tear a storage append)

Arming: ``FAULTS.configure(schedule, seed)`` in-process, or the
``KASPA_TPU_FAULTS`` env var (inline JSON, or ``@/path/to/schedule.json``)
plus ``KASPA_TPU_FAULT_SEED`` for subprocesses — read at import so a
freshly spawned node arms before any fault point is reached.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from contextlib import contextmanager

from kaspa_tpu.observability.core import REGISTRY
from kaspa_tpu.utils.sync import ranked_lock

# The single source of truth for compiled-in fault points.  graftlint's
# registry-hygiene checker cross-checks this catalog against every
# FAULTS.fire(...) literal in the tree, in both directions: firing an
# uncataloged point and cataloging a dead point are both lint errors.
FAULT_POINTS: dict[str, str] = {
    "device.verify": "batch signature dispatch (ops/secp256k1/verify.py, crypto/secp.py)",
    "device.hang": "same site, mode hang/wedge dispatch hangs seen by the watchdog",
    "device.jit_compile": "first-compile of a (kernel, bucket) shape (crypto/secp.py)",
    "device.mesh.dispatch": "sharded shard_map dispatch (ops/mesh.py)",
    "vm.fallback.exec": "one deferred VM fallback job (txscript/batch.py)",
    "p2p.send": "outgoing frame (p2p/transport.py)",
    "p2p.recv": "incoming frame read (p2p/transport.py)",
    "p2p.partition": "frame black-holed across a severed link (p2p/transport.py send, LINKS plane)",
    "p2p.link_drop": "outbound dial severed before the handshake (p2p/transport.py connect_outbound)",
    "storage.commit": "write-batch commit (storage/kv.py, both engines)",
    "storage.flush": "python-engine log append (storage/kv.py)",
    "fabric.send": "outgoing verify-fabric request (fabric/client.py)",
    "fabric.recv": "incoming verify-fabric frame (fabric/client.py)",
    "fabric.slice_hang": "verifyd slice worker pre-dispatch (fabric/service.py)",
}

_INJECTIONS = REGISTRY.counter_family("fault_injections", "point", help="fired fault injections by point")

_SLEEP_DEFAULTS = {"wedge": 0.05, "slow": 0.02, "stall": 0.02, "hang": 0.05}

_suppress_tls = threading.local()


@contextmanager
def suppress():
    """Disable fault injection on the current thread (canary probes and
    warm pretraces must not fire faults *or* advance hit counters — the
    drill's requeued==injected accounting depends on it)."""
    prev = getattr(_suppress_tls, "on", False)
    _suppress_tls.on = True
    try:
        yield
    finally:
        _suppress_tls.on = prev


def is_suppressed() -> bool:
    return getattr(_suppress_tls, "on", False)


class FaultInjected(Exception):
    """Raised at an armed fault point (modes error/wedge).

    Call sites treat it as a transient infrastructure failure — the VM
    fallback lane retries the job, the device breaker counts it toward a
    trip — so an injected fault can degrade throughput but never change a
    consensus decision.
    """

    def __init__(self, point: str, hit: int, mode: str = "error"):
        super().__init__(f"fault injected at {point} (hit {hit}, mode {mode})")
        self.point = point
        self.hit = hit
        self.mode = mode


class FaultWedged(FaultInjected):
    """A dispatch that hung for ``delay`` and then died."""


class FaultAction:
    """Cooperative fault handed back to the call site.

    ``rng`` is seeded from (registry seed, point, hit) so any random
    choice the call site makes (which byte to flip, where to cut) is
    reproducible.
    """

    __slots__ = ("point", "hit", "mode", "delay", "rng")

    def __init__(self, point: str, hit: int, mode: str, delay: float, seed: int):
        self.point = point
        self.hit = hit
        self.mode = mode
        self.delay = delay
        self.rng = random.Random((seed << 20) ^ hash(point) ^ (hit * 0x9E3779B9))


class FaultRegistry:
    """Process-wide registry; near-zero cost while disarmed (one attribute
    load and a branch per compiled-in fault point)."""

    def __init__(self):
        self._armed = False
        # leaf lock, fired while holding arbitrary subsystem ranks; it only
        # guards counter dicts and never acquires another lock
        self._lock = threading.Lock()  # graftlint: allow(raw-lock) -- leaf hit-counter guard, fired under arbitrary ranks
        self._schedule: dict[str, dict] = {}
        self._seed = 0
        self._hits: dict[str, int] = {}
        self._fired: dict[str, int] = {}
        self._events: list[tuple[str, int, str]] = []

    # --- configuration ----------------------------------------------------

    def configure(self, schedule: dict | None, seed: int = 0) -> None:
        """Arm ``schedule`` (point -> spec) with ``seed``; resets all hit
        counters and the event log.  ``None``/empty disarms."""
        with self._lock:
            self._schedule = dict(schedule or {})
            self._seed = int(seed)
            self._hits = {}
            self._fired = {}
            self._events = []
            self._armed = bool(self._schedule)

    def clear(self) -> None:
        self.configure(None)

    @property
    def armed(self) -> bool:
        return self._armed

    # --- the hot-path hook ------------------------------------------------

    def fire(self, point: str) -> FaultAction | None:
        """Count a hit at ``point``; raise/sleep/return per the schedule.

        Returns None when disarmed, unscheduled, or this hit does not
        match; raises FaultInjected/FaultWedged for error/wedge modes;
        sleeps and returns None for slow/stall; returns a FaultAction for
        cooperative modes.
        """
        if not self._armed or is_suppressed():
            return None
        with self._lock:
            spec = self._schedule.get(point)
            if spec is None:
                return None
            hit = self._hits.get(point, 0) + 1
            self._hits[point] = hit
            if not self._matches(spec, point, hit):
                return None
            self._fired[point] = self._fired.get(point, 0) + 1
            mode = spec.get("mode", "error")
            self._events.append((point, hit, mode))
        _INJECTIONS.inc(point)
        delay = float(spec.get("delay", _SLEEP_DEFAULTS.get(mode, 0.0)))
        if mode == "error":
            raise FaultInjected(point, hit, mode)
        if mode == "wedge":
            time.sleep(delay)
            raise FaultWedged(point, hit, mode)
        if mode in ("slow", "stall", "hang"):
            time.sleep(delay)
            return None
        return FaultAction(point, hit, mode, delay, self._seed)

    def _matches(self, spec: dict, point: str, hit: int) -> bool:
        limit = spec.get("max")
        if limit is not None and self._fired.get(point, 0) >= limit:
            return False
        hits = spec.get("hits")
        if hits is not None and hit in hits:
            return True
        every = spec.get("every")
        if every and hit % every == 0:
            return True
        after = spec.get("after")
        if after is not None and hit >= after and hits is None and not every:
            return True
        return False

    # --- reporting --------------------------------------------------------

    def events(self) -> list[dict]:
        """Fired injections as dicts, sorted by (point, hit) — the sort
        makes the log byte-identical even when pool threads interleave."""
        with self._lock:
            evs = sorted(self._events)
        return [{"point": p, "hit": h, "mode": m} for p, h, m in evs]

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "armed": self._armed,
                "seed": self._seed,
                "points": {
                    p: {"hits": self._hits.get(p, 0), "fired": self._fired.get(p, 0)}
                    for p in sorted(set(self._hits) | set(self._schedule))
                },
            }


FAULTS = FaultRegistry()
REGISTRY.register_collector("faults", FAULTS.snapshot)


class LinkPlane:
    """Link-level network partitions: black-hole frames by (src, dst) id.

    The swarm drill's fault plane.  ``partition(groups)`` severs every
    ordered pair of node ids that straddles a group boundary; a severed
    link silently drops frames at the sender (packet loss, not a TCP
    reset — the sender's relay state still believes the frame left, which
    is exactly the lie a real partition tells).  ``heal()`` restores
    every link but keeps the per-link drop ledger for the report.

    Near-zero cost while inactive (one attribute load and a branch per
    frame, the same discipline as the FAULTS registry); the ``drop``
    check itself is a frozenset lookup.  Endpoints are the nodes' version
    handshake identity nonces (``Node.id``) — the only peer identity both
    wire directions of a connection agree on.
    """

    def __init__(self):
        # leaf lock: guards the ledger only, taken under node(5) on sends
        self._lock = ranked_lock("p2p.links")
        self._severed: frozenset = frozenset()
        self._dropped: dict[tuple, int] = {}
        self.active = False

    def partition(self, groups) -> int:
        """Sever every (src, dst) pair across the group boundary; returns
        the number of severed ordered links.  Ids absent from ``groups``
        keep full connectivity."""
        severed = set()
        for i, ga in enumerate(groups):
            for gb in groups[i + 1 :]:
                for a in ga:
                    for b in gb:
                        severed.add((a, b))
                        severed.add((b, a))
        with self._lock:
            self._severed = frozenset(severed)
            self.active = bool(severed)
        return len(severed)

    def heal(self) -> None:
        with self._lock:
            self._severed = frozenset()
            self.active = False

    def reset(self) -> None:
        with self._lock:
            self._severed = frozenset()
            self._dropped = {}
            self.active = False

    def drop(self, src, dst) -> bool:
        """True (and one ledger tick) iff the ``src -> dst`` link is severed.
        Unlabeled endpoints (``None``) never match — a peer that has not
        completed its version handshake has no identity to partition on."""
        if src is None or dst is None or (src, dst) not in self._severed:
            return False
        with self._lock:
            self._dropped[(src, dst)] = self._dropped.get((src, dst), 0) + 1
        return True

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "active": self.active,
                "severed_links": len(self._severed),
                "dropped_frames": sum(self._dropped.values()),
                "dropped_by_link": {f"{s}->{d}": n for (s, d), n in sorted(self._dropped.items())},
            }


LINKS = LinkPlane()


def mangle_frame(frame: bytes, act: FaultAction) -> bytes | None:
    """Apply a cooperative frame fault; returns the mutated frame, or None
    for ``drop``.  Corruption targets the body region (offset >= 8) so the
    receiver sees a decode error, not a desynced length field."""
    if act.mode == "drop":
        return None
    if act.mode == "truncate":
        return frame[: max(1, len(frame) // 2)]
    if act.mode == "corrupt":
        i = 8 + act.rng.randrange(len(frame) - 8) if len(frame) > 8 else len(frame) - 1
        return frame[:i] + bytes([frame[i] ^ 0x5A]) + frame[i + 1 :]
    return frame


def _configure_from_env() -> None:
    raw = os.environ.get("KASPA_TPU_FAULTS")
    if not raw:
        return
    if raw.startswith("@"):
        with open(raw[1:]) as f:
            raw = f.read()
    FAULTS.configure(json.loads(raw), int(os.environ.get("KASPA_TPU_FAULT_SEED", "0")))


_configure_from_env()
