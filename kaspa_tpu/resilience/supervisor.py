"""Device-runtime supervision: the hang-proof verify plane.

The breaker (`resilience/breaker.py`) counts *errors*; it is blind to
*hangs* — and every bench round to date (BENCH_r01+) wedged exactly that
way: a jit compile or device call that never returns, pinning whichever
thread dispatched it (the CoalescingDispatcher thread in production).
This module closes that hole with three cooperating pieces:

**Watchdog** — ``run_supervised(fn, tier=...)`` executes the device call
on a disposable worker thread and waits with a hard deadline (env knobs
``KASPA_TPU_WATCHDOG_DISPATCH_S`` / ``_COMPILE_S``; compile gets a far
longer tier because a cold XLA trace legitimately takes minutes).  On
deadline the worker is *abandoned-and-replaced*: the caller gets
``DeviceHangError`` immediately (so the batch requeues onto the
bit-identical host degraded lane and the breaker trips with cause
``hung``), the wedged thread is left to die on its own, and any result it
produces later is discarded — a job-level lock makes timeout-vs-complete
atomic, so a batch is never lost and never double-resolved.

**Canary prober** — with the breaker in *managed* mode (``install()``),
live dispatches while OPEN always take the degraded lane; HALF_OPEN
probes are driven exclusively by a background thread dispatching a tiny
known-answer batch (fault-injection suppressed, so drills stay
deterministic).  Recovery is automatic and never stalls a live block.

**Warm-kernel manifest** — a JSON sidecar next to the persistent XLA
compilation cache recording every (kernel, bucket, mesh, backend,
jax_version) shape this machine has compiled.  ``pretrace_warm()``
re-traces those shapes in a background thread at daemon start, off the
commit lock, so a restart after a wedge comes back warm.  Honesty note,
measured on this repo's kernels: the XLA disk cache removes the *compile*
but not the *trace/lower* wall, and on the CPU backend executable
deserialization costs about as much as compiling — so ``auto`` pretraces
only on non-CPU backends, and the bench wedge dossier records measured
warm-start seconds rather than assuming the cache is free.
"""

from __future__ import annotations

import itertools
import json
import os
import queue
import threading

from kaspa_tpu.utils.sync import ranked_lock
import time
from contextlib import contextmanager

from kaspa_tpu.observability import trace
from kaspa_tpu.observability.core import REGISTRY
from kaspa_tpu.resilience import faults as faults_mod
from kaspa_tpu.resilience.breaker import CLOSED, device_breaker

_TIMEOUTS = REGISTRY.counter_family(
    "secp_watchdog_timeouts", "tier", help="supervised device calls that exceeded their watchdog deadline"
)
_REQUEUED = REGISTRY.counter(
    "secp_watchdog_requeued_total", help="hung device batches requeued onto the host degraded lane"
)
_REQUEUED_JOBS = REGISTRY.counter(
    "secp_watchdog_requeued_jobs", help="verify jobs inside watchdog-requeued batches"
)
_ABANDONED = REGISTRY.counter(
    "secp_watchdog_abandoned_threads", help="wedged device worker threads abandoned-and-replaced"
)
_LATE = REGISTRY.counter(
    "secp_watchdog_late_results", help="results from abandoned workers that arrived after requeue (discarded)"
)
_CANARY = REGISTRY.counter_family(
    "secp_watchdog_canary_probes", "result", help="background canary re-probe dispatches by outcome"
)

_DEADLINE_DEFAULTS = {"dispatch": 60.0, "compile": 900.0}
_overrides: dict[str, float] = {}


class DeviceHangError(RuntimeError):
    """A supervised device call blew its watchdog deadline.

    The call may still be running on the abandoned worker; the caller
    must treat the batch as *unresolved* and requeue it on the host lane
    (any late device result is discarded, never merged)."""

    def __init__(self, tier: str, deadline_s: float, kernel: str = "", jobs: int = 0):
        super().__init__(
            f"device {tier} exceeded the {deadline_s:g}s watchdog deadline "
            f"(kernel={kernel or '?'}, jobs={jobs}); batch requeued on the host lane"
        )
        self.tier = tier
        self.deadline_s = deadline_s
        self.kernel = kernel
        self.jobs = jobs


def watchdog_enabled() -> bool:
    return os.environ.get("KASPA_TPU_WATCHDOG", "1") not in ("0", "off", "false")


def deadline_s(tier: str) -> float:
    ov = _overrides.get(tier)
    if ov is not None:
        return ov
    env = os.environ.get(f"KASPA_TPU_WATCHDOG_{tier.upper()}_S")
    if env:
        return float(env)
    return _DEADLINE_DEFAULTS.get(tier, _DEADLINE_DEFAULTS["dispatch"])


@contextmanager
def deadline_overrides(dispatch_s: float | None = None, compile_s: float | None = None):
    """Scoped deadline overrides (process-global; drills and tests use
    this to make hangs observable in fractions of a second)."""
    prev = dict(_overrides)
    if dispatch_s is not None:
        _overrides["dispatch"] = float(dispatch_s)
    if compile_s is not None:
        _overrides["compile"] = float(compile_s)
    try:
        yield
    finally:
        _overrides.clear()
        _overrides.update(prev)


# --- the watchdogged worker pool ------------------------------------------


class _Job:
    __slots__ = ("fn", "event", "lock", "result", "error", "abandoned")

    def __init__(self, fn):
        self.fn = fn
        self.event = threading.Event()
        self.lock = ranked_lock("watchdog.task")
        self.result = None
        self.error: BaseException | None = None
        self.abandoned = False


class _Worker(threading.Thread):
    _ids = itertools.count(1)

    def __init__(self, pool: "WorkerPool"):
        super().__init__(name=f"secp-supervised-{next(self._ids)}", daemon=True)
        self._pool = pool
        self._q: queue.SimpleQueue = queue.SimpleQueue()  # graftlint: allow(unbounded-queue) -- one job in flight per supervised worker by construction (submit awaits the verdict)

    def submit(self, job: _Job) -> None:
        self._q.put(job)

    def retire(self) -> None:
        self._q.put(None)

    def run(self) -> None:
        while True:
            job = self._q.get()
            if job is None:
                return
            try:
                r, e = job.fn(), None
            except BaseException as ex:  # noqa: BLE001 - surfaced on the caller
                r, e = None, ex
            with job.lock:
                late = job.abandoned
                if not late:
                    job.result, job.error = r, e
                    job.event.set()
            if late:
                # the caller gave up on this job long ago: discard the
                # result and retire — a replacement worker already exists
                self._pool._note_late()
                return


class WorkerPool:
    """Disposable device-call workers with a small idle free-list.

    Concurrency is caller-driven (each ``run`` occupies one worker for
    its duration), so pipelined dispatch keeps overlapping exactly as it
    did without the watchdog."""

    def __init__(self, max_idle: int = 2):
        self._lock = ranked_lock("watchdog.pool")
        self._free: list[_Worker] = []
        self._max_idle = max_idle
        self.completed = 0
        self.timeouts: dict[str, int] = {}
        self.abandoned = 0
        self.late = 0

    def _get(self) -> _Worker:
        with self._lock:
            if self._free:
                return self._free.pop()
        w = _Worker(self)
        w.start()
        return w

    def _put(self, w: _Worker) -> None:
        with self._lock:
            if len(self._free) < self._max_idle:
                self._free.append(w)
                return
        w.retire()

    def _note_late(self) -> None:
        _LATE.inc()
        with self._lock:
            self.late += 1

    def run(self, fn, deadline: float, tier: str, kernel: str = "", jobs: int = 0):
        job = _Job(fn)
        w = self._get()
        w.submit(job)
        if not job.event.wait(deadline):
            with job.lock:
                if not job.event.is_set():
                    # timeout-vs-complete decided atomically: from here the
                    # worker's eventual result is late and gets discarded
                    job.abandoned = True
            if job.abandoned:
                _TIMEOUTS.inc(tier)
                _ABANDONED.inc()
                with self._lock:
                    self.timeouts[tier] = self.timeouts.get(tier, 0) + 1
                    self.abandoned += 1
                raise DeviceHangError(tier, deadline, kernel, jobs)
        self._put(w)
        with self._lock:
            self.completed += 1
        if job.error is not None:
            raise job.error
        return job.result

    def shutdown(self) -> None:
        with self._lock:
            free, self._free = self._free, []
        for w in free:
            w.retire()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "completed": self.completed,
                "timeouts": dict(self.timeouts),
                "abandoned_threads": self.abandoned,
                "late_results": self.late,
                "idle_workers": len(self._free),
            }


_POOL = WorkerPool()

_stats_lock = ranked_lock("watchdog.stats")
_REQUEUE_STATS = {"batches": 0, "jobs": 0}


def run_supervised(fn, *, tier: str = "dispatch", kernel: str = "", jobs: int = 0):
    """Run one device call under the watchdog; returns its result, raises
    its exception, or raises DeviceHangError on deadline.  With the
    watchdog disabled (KASPA_TPU_WATCHDOG=0) this is a plain call."""
    if not watchdog_enabled():
        return fn()
    d = deadline_s(tier)
    ctx = trace.context()

    def _on_worker():
        # umbrella span re-attaches the worker's device spans (host
        # marshal, jit compile, device dispatch) to the caller's trace
        with trace.span("supervisor.worker", parent=ctx, kernel=kernel, tier=tier, jobs=jobs):
            return fn()

    with trace.span("supervisor.dispatch", kernel=kernel, tier=tier, jobs=jobs, deadline_s=d):
        return _POOL.run(_on_worker, d, tier, kernel, jobs)


def note_requeue(jobs: int) -> None:
    """Record one hung batch requeued onto the host degraded lane."""
    _REQUEUED.inc()
    _REQUEUED_JOBS.inc(jobs)
    with _stats_lock:
        _REQUEUE_STATS["batches"] += 1
        _REQUEUE_STATS["jobs"] += jobs


def verdict() -> dict:
    """Compact supervision verdict attached to dispatch-timeout errors."""
    p = _POOL.snapshot()
    try:
        state = device_breaker().state
    except Exception:  # noqa: BLE001 - verdict is best-effort diagnostics
        state = "?"
    with _stats_lock:
        requeued = dict(_REQUEUE_STATS)
    return {
        "watchdog": "on" if watchdog_enabled() else "off",
        "installed": _install_count > 0,
        "breaker": state,
        "timeouts": p["timeouts"],
        "abandoned_threads": p["abandoned_threads"],
        "late_results": p["late_results"],
        "requeued": requeued,
    }


# --- warm-kernel manifest (persistent compiled-kernel cache index) --------

_manifest_lock = ranked_lock("supervisor.manifest")
_pretrace_report: list | None = None


def manifest_path() -> str:
    p = os.environ.get("KASPA_TPU_WARM_MANIFEST")
    if p:
        return p
    from kaspa_tpu.utils import jax_setup

    return os.path.join(jax_setup.cache_dir(), "warm_manifest.json")


def _env_key() -> dict:
    import jax

    from kaspa_tpu.ops import mesh

    return {"mesh": mesh.active_size(), "backend": jax.default_backend(), "jax_version": jax.__version__}


def _read_manifest(path: str) -> list[dict]:
    try:
        with open(path) as f:
            doc = json.load(f)
        entries = doc.get("entries")
        if not isinstance(entries, list):
            return []
        out = []
        for e in entries:
            if isinstance(e, dict):
                # schema upgrade: entries written before the aggregate lane
                # carry no kernel family — they are all ladder shapes
                e.setdefault("family", "ladder")
                out.append(e)
        return out
    except (OSError, ValueError):
        return []


def note_shape(kernel_name: str, bucket: int, family: str = "ladder") -> None:
    """Record a freshly compiled (kernel, bucket) shape in the manifest,
    keyed by the current mesh/backend/jax version plus the kernel family
    ("ladder" | "aggregate" — so a pretrace warms the right kernels and a
    wedge dossier names which family hung).  Write-through on new shapes
    only (rare); never allowed to fail a dispatch."""
    try:
        path = manifest_path()
        entry = {"kernel": str(kernel_name), "bucket": int(bucket), "family": str(family), **_env_key()}
        with _manifest_lock:
            entries = _read_manifest(path)
            if entry in entries:
                return
            entries.append(entry)
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"entries": entries}, f, indent=2, sort_keys=True)
            os.replace(tmp, path)
    except Exception:  # noqa: BLE001 - the manifest is an optimization
        pass


def load_warm_entries() -> list[dict]:
    """Manifest entries compiled under the *current* (mesh, backend,
    jax_version) — the only ones a pretrace can actually reuse."""
    try:
        key = _env_key()
        return [
            e
            for e in _read_manifest(manifest_path())
            if all(e.get(k) == v for k, v in key.items())
        ]
    except Exception:  # noqa: BLE001
        return []


def pretrace_warm(budget_s: float | None = None) -> list[dict]:
    """Pre-trace every matching manifest shape (smallest buckets first so
    a budget cut keeps the most common shapes warm).  Returns per-shape
    timing — the measured warm-start jit cost the wedge dossier records."""
    from kaspa_tpu.crypto import secp  # deferred: secp imports this module

    out: list[dict] = []
    t_all = time.monotonic()
    for e in sorted(load_warm_entries(), key=lambda e: (e.get("bucket", 0), e.get("kernel", ""))):
        row = {"kernel": e.get("kernel"), "bucket": e.get("bucket"), "family": e.get("family", "ladder")}
        if budget_s is not None and time.monotonic() - t_all > budget_s:
            row["status"] = "skipped:budget"
            out.append(row)
            continue
        t0 = time.monotonic()
        row["status"] = secp.pretrace_bucket(e.get("kernel", ""), int(e.get("bucket", 0)))
        row["seconds"] = round(time.monotonic() - t0, 3)
        out.append(row)
    global _pretrace_report
    _pretrace_report = out
    return out


def cache_report() -> dict:
    """Persistent-kernel-cache evidence for dossiers and drills."""
    report: dict = {"manifest_path": manifest_path()}
    try:
        from kaspa_tpu.utils import jax_setup

        report["xla_cache_dir"] = jax_setup.cache_dir()
    except Exception:  # noqa: BLE001
        pass
    try:
        report["env"] = _env_key()
        report["entries"] = load_warm_entries()
    except Exception:  # noqa: BLE001
        report["entries"] = []
    report["entries_total"] = len(_read_manifest(report["manifest_path"]))
    if _pretrace_report is not None:
        report["pretrace"] = _pretrace_report
    return report


# --- the canary prober ----------------------------------------------------


class CanaryProber(threading.Thread):
    """Drives breaker HALF_OPEN off the critical path.

    Woken by the breaker's trip listener; once the backoff window
    elapses it claims the probe slot (``allow(probe=True)`` — the only
    path that transitions a *managed* breaker to HALF_OPEN, so a live
    super-batch can never race it) and dispatches a tiny known-answer
    batch with fault injection suppressed."""

    def __init__(self, breaker, probe_fn=None, poll_s: float = 0.05):
        super().__init__(name="canary-prober", daemon=True)
        self._breaker = breaker
        self._probe_fn = probe_fn
        self._poll_s = poll_s
        self._wake = threading.Event()
        self._stop = threading.Event()
        self.probes = 0
        self.ok = 0
        self.failed = 0
        breaker.add_trip_listener(self._wake.set)

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()

    def snapshot(self) -> dict:
        return {"probes": self.probes, "ok": self.ok, "failed": self.failed, "alive": self.is_alive()}

    def run(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(0.5)
            if self._stop.is_set():
                return
            self._wake.clear()
            br = self._breaker
            while br.state != CLOSED and not self._stop.is_set():
                if not br.reopen_due() or not br.allow(probe=True):
                    self._stop.wait(self._poll_s)
                    continue
                self.probes += 1
                if self._run_probe():
                    self.ok += 1
                    _CANARY.inc("ok")
                    br.record_success()
                else:
                    self.failed += 1
                    _CANARY.inc("failed")
                    br.record_failure(cause="canary")

    def _run_probe(self) -> bool:
        fn = self._probe_fn
        if fn is None:
            from kaspa_tpu.crypto.secp import canary_probe as fn  # deferred: import cycle
        try:
            with faults_mod.suppress():
                with trace.span("supervisor.canary"):
                    return bool(fn())
        except Exception:  # noqa: BLE001 - a failed probe just re-opens
            return False


# --- install / shutdown ---------------------------------------------------

_install_lock = ranked_lock("supervisor.install")
_install_count = 0
_prober: CanaryProber | None = None


def _should_pretrace(flag: bool | None) -> bool:
    if flag is not None:
        return flag
    env = os.environ.get("KASPA_TPU_PRETRACE", "auto")
    if env in ("1", "on", "true"):
        return True
    if env in ("0", "off", "false"):
        return False
    # auto: on CPU the XLA cache's executable deserialization costs about
    # as much as compiling, so a background pretrace only burns cores; on
    # a real accelerator it is the restart-warmth mechanism
    try:
        import jax

        return jax.default_backend() != "cpu" and bool(load_warm_entries())
    except Exception:  # noqa: BLE001
        return False


def installed() -> bool:
    return _install_count > 0


def install(pretrace: bool | None = None, probe_fn=None) -> None:
    """Activate supervision: managed breaker + canary prober, and (backend
    permitting) a background warm-manifest pretrace off the commit lock.
    Refcounted — concurrent daemons in one process share one prober."""
    global _install_count, _prober
    with _install_lock:
        _install_count += 1
        if _install_count > 1:
            return
        br = device_breaker()
        br.set_managed(True)
        _prober = CanaryProber(br, probe_fn=probe_fn)
        _prober.start()
    if _should_pretrace(pretrace):
        budget = float(os.environ.get("KASPA_TPU_PRETRACE_BUDGET_S", "600"))
        threading.Thread(
            target=lambda: pretrace_warm(budget_s=budget), name="kernel-pretrace", daemon=True
        ).start()


def shutdown() -> None:
    """Release one install ref; the last one stops the prober and returns
    the breaker to legacy (unmanaged) probing."""
    global _install_count, _prober
    with _install_lock:
        if _install_count == 0:
            return
        _install_count -= 1
        if _install_count > 0:
            return
        prober, _prober = _prober, None
    if prober is not None:
        prober.stop()
    try:
        device_breaker().set_managed(False)
    except Exception:  # noqa: BLE001
        pass


def _state() -> dict:
    out = {
        "watchdog": watchdog_enabled(),
        "installed": _install_count > 0,
        "deadlines": {t: deadline_s(t) for t in ("dispatch", "compile")},
        "pool": _POOL.snapshot(),
    }
    with _stats_lock:
        out["requeued"] = dict(_REQUEUE_STATS)
    p = _prober
    if p is not None:
        out["canary"] = p.snapshot()
    return out


REGISTRY.register_collector("supervisor", _state)
