"""Adaptive overload control: node-wide pressure levels + brownout actions.

ROADMAP item 1 wants true 10 BPS *under load*; PR 12's evidence shows the
node simply falls behind when pushed (flood replay compute-bound, template
rebuild cost growing 10x with pool occupancy) because nothing decides what
to sacrifice when the verify plane saturates.  Fixed-throughput verify
engines make admission arbitration explicit — a pipeline sized to a hard
ceiling must shed above it.  This module is that arbiter for the whole
node: consensus-critical block verification holds cadence while mempool
admission, serving fanout, relay, and template rebuilds degrade
*deliberately* — shed early, shed cheap, recover cleanly.

Architecture:

- ``PressureSignal``: one scalar pressure source (queue depth, occupancy,
  windowed latency) with per-level *enter* thresholds and hysteresis
  *exit* thresholds (``enter * exit_ratio``) so a value oscillating around
  one boundary cannot flap the level.
- ``OverloadController``: samples every signal (lock-free — signal reads
  take only their own subsystem locks), takes the max requested level
  across signals, and moves the node level at most ONE step per decision:
  escalate after ``rise_samples`` consecutive over-threshold samples,
  de-escalate after ``fall_samples`` consecutive clear samples.  Level
  state lives under the rank-8 ``overload.state`` lock (below every
  subsystem lock its actions touch).
- ``BrownoutAction``: declarative (name, level, engage, release) entries.
  Actions are applied OUTSIDE the controller lock, after the level
  decision, by the single sampling thread — engage(level) fires for every
  action at or below the new level (re-fired on each level change so
  actions can tune per level), release() when the level drops below.

Levels: NOMINAL -> ELEVATED -> SATURATED -> CRITICAL.

Observability: ``overload_transitions{to}`` counter, the shared
``overload_shed{action}`` family (each subsystem increments its own
label), an ``overload`` REGISTRY collector (level gauge + signal values
-> getMetrics / kaspa_overload_level in Prometheus), and one flight trace
("overload") that accumulates a retroactive span per level dwell —
sealed into the ring at ``shutdown()``.

Every shed still resolves its AdmissionTicket: brownouts reject or defer,
they never lose work — ``stats()["lost"] == 0`` stays invariant.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from time import perf_counter_ns

from kaspa_tpu.observability import flight, trace
from kaspa_tpu.observability.core import REGISTRY
from kaspa_tpu.utils.sync import ranked_lock

NOMINAL, ELEVATED, SATURATED, CRITICAL = 0, 1, 2, 3
LEVELS = ("NOMINAL", "ELEVATED", "SATURATED", "CRITICAL")

_TRANSITIONS = REGISTRY.counter_family(
    "overload_transitions", "to", help="overload level transitions, by destination level"
)
from kaspa_tpu.observability.shed import SHED as _SHED

# retained transition records in stats() (ring-bounded; telemetry only)
_MAX_TRANSITIONS = 64


def level_name(level: int) -> str:
    return LEVELS[max(NOMINAL, min(CRITICAL, int(level)))]


@dataclass
class PressureSignal:
    """One scalar pressure source with hysteresis thresholds.

    ``enter`` is the (ELEVATED, SATURATED, CRITICAL) ascending threshold
    triple; a level's *exit* threshold is ``enter * exit_ratio`` — between
    exit and enter the signal votes to HOLD the level it already reached
    but never to enter it, which is what stops boundary noise flapping.
    """

    name: str
    read: object  # () -> float; exceptions read as 0.0 (signal absent)
    enter: tuple  # ascending thresholds for ELEVATED, SATURATED, CRITICAL
    exit_ratio: float = 0.7

    def classify(self, value: float) -> tuple[int, int]:
        """(enter_level, hold_level) this value votes for."""
        up = hold = NOMINAL
        for i, thr in enumerate(self.enter):
            lvl = i + 1
            if value >= thr:
                up = lvl
            if value >= thr * self.exit_ratio:
                hold = lvl
        return up, hold


@dataclass
class BrownoutAction:
    """One declarative brownout: engaged while node level >= ``level``.

    ``engage(level)`` is re-invoked on every level change at/above the
    action's level (actions tune themselves per level); ``release()``
    restores normal behavior.  Both run outside the controller lock on
    the sampling thread; exceptions are swallowed (a broken action must
    not wedge the controller).
    """

    name: str
    level: int
    engage: object  # (level: int) -> None
    release: object  # () -> None


class OverloadController:
    """Hysteresis-damped node pressure level + brownout-action driver.

    Deterministic by construction: given a fixed sequence of signal
    values (and an injectable ``clock``), the level trace is a pure
    function of the schedule — the unit tests and the sim drill both
    rely on this.  ``sample()`` is intended to be called from ONE place
    (the daemon ticker thread, or the drill loop); concurrent callers
    are safe for level state but would interleave action application.
    """

    def __init__(
        self,
        signals,
        actions=(),
        *,
        rise_samples: int = 2,
        fall_samples: int = 3,
        clock=time.monotonic,
    ):
        self.signals = list(signals)
        self.actions = sorted(actions, key=lambda a: (a.level, a.name))
        self.rise_samples = max(1, int(rise_samples))
        self.fall_samples = max(1, int(fall_samples))
        self._clock = clock
        self._lock = ranked_lock("overload.state", reentrant=False)
        self._level = NOMINAL
        self._up_streak = 0
        self._down_streak = 0
        self._samples = 0
        self._dwell = [0.0, 0.0, 0.0, 0.0]
        self._dwell_since = clock()
        self._dwell_since_ns = perf_counter_ns()
        self._transitions: list[dict] = []
        self._last_signals: dict[str, dict] = {}
        # level the actions currently reflect (sampling-thread-only state)
        self._engaged_level = NOMINAL
        self._shut = False
        self._ticker: threading.Thread | None = None
        self._stop = threading.Event()
        REGISTRY.register_collector("overload", self._collect)

    # -- sampling state machine ----------------------------------------

    def sample(self) -> int:
        """One controller decision: read signals, damp, move <=1 level,
        apply actions.  Returns the level now in force."""
        readings = []
        for s in self.signals:  # no lock held: reads take subsystem locks
            try:
                v = float(s.read())
            except Exception:  # noqa: BLE001 - an absent signal reads as no pressure
                v = 0.0
            readings.append((s, v))
        transition = None
        with self._lock:
            want = hold = NOMINAL
            last: dict[str, dict] = {}
            for s, v in readings:
                up, hd = s.classify(v)
                want = max(want, up)
                hold = max(hold, hd)
                last[s.name] = {"value": round(v, 3), "votes": LEVELS[up]}
            self._last_signals = last
            self._samples += 1
            if want > self._level:
                self._up_streak += 1
                self._down_streak = 0
                if self._up_streak >= self.rise_samples:
                    transition = self._set_level_locked(self._level + 1)
                    self._up_streak = 0
            elif hold < self._level:
                self._down_streak += 1
                self._up_streak = 0
                if self._down_streak >= self.fall_samples:
                    transition = self._set_level_locked(self._level - 1)
                    self._down_streak = 0
            else:
                self._up_streak = 0
                self._down_streak = 0
            level = self._level
        if transition is not None:
            self._record_transition_span(*transition)
        self._apply_actions(level)
        return level

    def _set_level_locked(self, new: int):
        now = self._clock()
        prev = self._level
        self._dwell[prev] += now - self._dwell_since
        t0_ns, t1_ns = self._dwell_since_ns, perf_counter_ns()
        self._dwell_since = now
        self._dwell_since_ns = t1_ns
        self._level = new
        _TRANSITIONS.inc(LEVELS[new])
        self._transitions.append(
            {"at": round(now, 3), "from": LEVELS[prev], "to": LEVELS[new]}
        )
        if len(self._transitions) > _MAX_TRANSITIONS:
            del self._transitions[: len(self._transitions) - _MAX_TRANSITIONS]
        return prev, new, t0_ns, t1_ns

    def _record_transition_span(self, prev: int, new: int, t0_ns: int, t1_ns: int) -> None:
        """Level-dwell span on the controller's own flight trace: one span
        per completed dwell, attributed to the level just left."""
        if not flight.enabled():
            return
        ctx = flight.begin("overload", "overload-controller")
        if ctx is not None:
            trace.record_span(
                f"overload.{LEVELS[prev]}", ctx, t0_ns, t1_ns, to=LEVELS[new]
            )

    def _apply_actions(self, level: int) -> None:
        """Engage/release brownout actions to match ``level``.  Runs with
        NO controller lock held: actions descend into subsystem locks
        (dispatch.queue, ingest.queue, serving.broadcaster ...) and the
        rank-8 controller lock sits below all of them."""
        prev = self._engaged_level
        if level == prev:
            return
        for a in self.actions:
            try:
                if level >= a.level:
                    a.engage(level)  # re-fired per level change: per-level tuning
                elif prev >= a.level:
                    a.release()
            except Exception:  # noqa: BLE001 - a broken action must not wedge control
                pass
        self._engaged_level = level

    # -- lifecycle ------------------------------------------------------

    def start(self, interval_s: float = 0.25) -> None:
        """Spawn the sampling ticker (daemon mode; the sim drill calls
        sample() itself for determinism)."""
        if self._ticker is not None:
            return
        self._stop.clear()

        def _run():
            while not self._stop.wait(interval_s):
                self.sample()

        self._ticker = threading.Thread(target=_run, name="overload-ticker", daemon=True)
        self._ticker.start()

    def shutdown(self, timeout: float = 2.0) -> None:
        """Stop sampling, release every engaged action, seal the flight
        trace into the ring."""
        self._stop.set()
        t = self._ticker
        if t is not None:
            t.join(timeout)
            self._ticker = None
        if self._shut:
            return
        self._shut = True
        prev = self._engaged_level
        for a in self.actions:
            if prev >= a.level:
                try:
                    a.release()
                except Exception:  # noqa: BLE001
                    pass
        self._engaged_level = NOMINAL
        if flight.enabled() and self._transitions:
            # seal the dwell-in-progress, then the trace (ring export)
            with self._lock:
                t0_ns, lvl = self._dwell_since_ns, self._level
            self._record_transition_span(lvl, lvl, t0_ns, perf_counter_ns())
            flight.end("overload", status="shutdown")

    # -- telemetry ------------------------------------------------------

    def level(self) -> int:
        with self._lock:
            return self._level

    def stats(self) -> dict:
        with self._lock:
            now = self._clock()
            dwell = list(self._dwell)
            dwell[self._level] += now - self._dwell_since
            return {
                "level": self._level,
                "level_name": LEVELS[self._level],
                "samples": self._samples,
                "up_streak": self._up_streak,
                "down_streak": self._down_streak,
                "dwell_seconds": {LEVELS[i]: round(d, 3) for i, d in enumerate(dwell)},
                "transitions": list(self._transitions),
                "signals": dict(self._last_signals),
                "actions": [
                    {
                        "name": a.name,
                        "level": LEVELS[a.level],
                        "engaged": self._engaged_level >= a.level,
                    }
                    for a in self.actions
                ],
                "shed": _SHED.snapshot(),
            }

    def _collect(self) -> dict:
        """REGISTRY collector: numeric leaves flatten into Prometheus
        gauges (kaspa_overload_level etc.) and the getMetrics snapshot's
        ``overload`` section."""
        with self._lock:
            return {
                "level": self._level,
                "level_name": LEVELS[self._level],
                "samples": self._samples,
                "signals": {k: v["value"] for k, v in self._last_signals.items()},
            }


# ---------------------------------------------------------------------------
# default wiring: the node's standard signal set + brownout registry
# ---------------------------------------------------------------------------

# (ELEVATED, SATURATED, CRITICAL) enter thresholds per signal.  Tuned on
# the 200-block --hostile --txflood --overload drill at 10 BPS (see
# SUSTAIN.json overload block): the flood ramp crosses SATURATED at peak
# and decays back below every exit threshold once the ramp subsides.
DEFAULT_THRESHOLDS: dict[str, tuple] = {
    "mempool": (40, 120, 400),              # pool occupancy (txs)
    "ingest_queue": (64, 256, 1024),        # queued admission tickets
    "template_lag_ms": (25.0, 100.0, 400.0),  # windowed rebuild mean
    "dispatch_tx_backlog": (256, 1024, 4096),  # standalone_tx verify jobs
    "fanout_depth": (64, 256, 768),         # deepest subscriber queue
    "fanout_lag_ms": (25.0, 100.0, 400.0),  # windowed serving queue_wait mean
    "commit_wait_ms": (50.0, 200.0, 800.0),  # windowed wait.* critical path
}


def _windowed_hist_mean(hist) -> object:
    """() -> mean of the histogram's observations since the last call
    (0.0 when none).  Survives REGISTRY.reset: a count regression just
    re-anchors the window."""
    state = {"count": 0, "sum": 0.0}

    def read() -> float:
        dc = hist.count - state["count"]
        ds = hist.sum - state["sum"]
        state["count"], state["sum"] = hist.count, hist.sum
        return (ds / dc) if dc > 0 else 0.0

    return read


def _windowed_wait_mean() -> object:
    """Windowed mean over the flight recorder's ``wait.*`` critical-path
    cells — commit-lock and queue-handoff pressure as the blocks actually
    experienced it."""
    state: dict[str, tuple] = {}

    def read() -> float:
        total_dc, total_ds = 0, 0.0
        for name, h in list(flight.CRIT_HIST._cells.items()):
            if not name.startswith("wait."):
                continue
            pc, ps = state.get(name, (0, 0.0))
            dc, ds = h.count - pc, h.sum - ps
            state[name] = (h.count, h.sum)
            if dc > 0:
                total_dc += dc
                total_ds += ds
        return (total_ds / total_dc) if total_dc else 0.0

    return read


def default_signals(
    *,
    mining=None,
    tier=None,
    broadcaster=None,
    fanout_depth_fn=None,
    thresholds: dict | None = None,
) -> list[PressureSignal]:
    """The node's standard pressure-signal set, built from whatever
    subsystems exist (absent ones contribute no signal).  ``thresholds``
    overrides DEFAULT_THRESHOLDS per signal name."""
    thr = dict(DEFAULT_THRESHOLDS)
    thr.update(thresholds or {})
    out: list[PressureSignal] = []

    if mining is not None:
        out.append(
            PressureSignal("mempool", lambda: len(mining.mempool.pool), thr["mempool"])
        )
        # the histogram is declared by mining_manager (one name, one series);
        # mining is only ever a MiningManager here, so the module is loaded
        from kaspa_tpu.mempool.mining_manager import _TEMPLATE_REBUILD_MS

        out.append(
            PressureSignal(
                "template_lag_ms",
                _windowed_hist_mean(_TEMPLATE_REBUILD_MS),
                thr["template_lag_ms"],
            )
        )
    if tier is not None:
        out.append(
            PressureSignal("ingest_queue", lambda: tier.queue.depth(), thr["ingest_queue"])
        )

    def _tx_backlog() -> float:
        from kaspa_tpu.ops import dispatch

        eng = dispatch.active()
        if eng is None:
            return 0.0
        return eng.pressure().get(dispatch.TX_CLASS, {}).get("jobs", 0)

    out.append(PressureSignal("dispatch_tx_backlog", _tx_backlog, thr["dispatch_tx_backlog"]))

    if fanout_depth_fn is not None:
        out.append(PressureSignal("fanout_depth", fanout_depth_fn, thr["fanout_depth"]))
    elif broadcaster is not None:
        out.append(
            PressureSignal("fanout_depth", broadcaster.max_queue_depth, thr["fanout_depth"])
        )
    if broadcaster is not None or fanout_depth_fn is not None:
        # time-domain twin of fanout_depth: the windowed mean of the
        # serving tier's queue_wait stage (serving_lag_ms) — depth says
        # how much is queued, this says how long events actually sat
        # there.  A few deep-but-fast queues stay quiet; shallow queues
        # on a stalled sender crew raise it immediately.  Reads 0 while
        # stage tracing is off (no new observations -> no pressure).
        # Sharded tier: MAX of per-shard windowed means — one wedged
        # shard must trip ELEVATED even when the other shards' fast
        # deliveries would dilute a global mean below threshold.
        if broadcaster is not None and hasattr(broadcaster, "shard_wait_cells"):
            readers = [_windowed_hist_mean(c) for c in broadcaster.shard_wait_cells()]

            def _shard_lag_max(_readers=readers) -> float:
                return max((r() for r in _readers), default=0.0)

            lag_fn = _shard_lag_max
        else:
            from kaspa_tpu.serving.broadcaster import _LAG_QUEUE_WAIT

            lag_fn = _windowed_hist_mean(_LAG_QUEUE_WAIT)
        out.append(
            PressureSignal("fanout_lag_ms", lag_fn, thr["fanout_lag_ms"])
        )

    out.append(PressureSignal("commit_wait_ms", _windowed_wait_mean(), thr["commit_wait_ms"]))
    return out


@dataclass
class BrownoutKnobs:
    """Per-level tuning for the default action registry."""

    # ingest lane capacity clamp per level (ELEVATED shrinks, deeper
    # levels shrink harder); None entries leave the configured capacity
    ingest_caps: dict = field(
        default_factory=lambda: {ELEVATED: 2048, SATURATED: 256, CRITICAL: 32}
    )
    # retry-after hint (ms) on node-overloaded rejections, per level
    retry_after_ms: dict = field(default_factory=lambda: {SATURATED: 500, CRITICAL: 2000})
    # standalone_tx starvation bound under class-yield, per level
    yield_starvation_s: dict = field(default_factory=lambda: {ELEVATED: 0.25, SATURATED: 0.5, CRITICAL: 1.0})
    # subscriber queue depth at which utxos-changed diffs conflate
    conflate_floor: dict = field(default_factory=lambda: {ELEVATED: 64, SATURATED: 16, CRITICAL: 4})
    # template staleness grace under CRITICAL (seconds past normal rebuild)
    template_grace_s: float = 2.0


def _per_level(table: dict, level: int):
    """Highest entry at or below ``level`` (actions engage at their own
    level and keep tightening as the node escalates)."""
    best = None
    for lvl in sorted(table):
        if lvl <= level:
            best = table[lvl]
    return best


def default_actions(
    *,
    tier=None,
    broadcaster=None,
    node=None,
    mining=None,
    knobs: BrownoutKnobs | None = None,
    thresholds: dict | None = None,
) -> list[BrownoutAction]:
    """The node's standard brownout registry, wired through existing
    seams.  Order of engagement as pressure rises:

      ELEVATED:  dispatch class-yield (txs yield to block verify),
                 ingest lane caps shrink, fanout diff-conflation arms
      SATURATED: ingest rejects with node-overloaded (+ retryAfterMs),
                 INV tx-relay damping
      CRITICAL:  template-rebuild deferral (bounded staleness grace)
    """
    k = knobs or BrownoutKnobs()
    out: list[BrownoutAction] = []

    def _yield_engage(level: int) -> None:
        from kaspa_tpu.ops import dispatch

        eng = dispatch.active()
        if eng is not None:
            eng.set_class_yield(
                {dispatch.TX_CLASS}, _per_level(k.yield_starvation_s, level) or 0.25
            )

    def _yield_release() -> None:
        from kaspa_tpu.ops import dispatch

        eng = dispatch.active()
        if eng is not None:
            eng.set_class_yield(())

    out.append(BrownoutAction("dispatch_yield", ELEVATED, _yield_engage, _yield_release))

    if tier is not None:
        out.append(
            BrownoutAction(
                "ingest_caps",
                ELEVATED,
                lambda level: tier.queue.set_capacity_limit(_per_level(k.ingest_caps, level)),
                lambda: tier.queue.set_capacity_limit(None),
            )
        )
        out.append(
            BrownoutAction(
                "ingest_shed",
                SATURATED,
                lambda level: tier.set_overload(
                    True, _per_level(k.retry_after_ms, level) or 500
                ),
                lambda: tier.set_overload(False),
            )
        )
    if broadcaster is not None:
        if hasattr(broadcaster, "shard_depths"):
            # sharded tier: conflation engages PER SHARD — only the
            # partitions actually under depth pressure start folding
            # diffs; subscribers on healthy shards keep full-resolution
            # streams.  (Re-engagement on each level change re-evaluates
            # which shards are pressured; release clears every shard.)
            depth_thr = (thresholds or {}).get(
                "fanout_depth", DEFAULT_THRESHOLDS["fanout_depth"]
            )

            def _conflate_engage(level: int) -> None:
                floor = _per_level(k.conflate_floor, level)
                trip = depth_thr[0]
                for idx, depth in enumerate(broadcaster.shard_depths()):
                    broadcaster.set_conflation(
                        floor if depth >= trip else None, shard=idx
                    )

            def _conflate_release() -> None:
                broadcaster.set_conflation(None)

            out.append(
                BrownoutAction(
                    "fanout_conflation", ELEVATED, _conflate_engage, _conflate_release
                )
            )
        else:
            out.append(
                BrownoutAction(
                    "fanout_conflation",
                    ELEVATED,
                    lambda level: broadcaster.set_conflation(_per_level(k.conflate_floor, level)),
                    lambda: broadcaster.set_conflation(None),
                )
            )
    if node is not None:
        out.append(
            BrownoutAction(
                "inv_damping",
                SATURATED,
                lambda level: node.set_relay_damping(True),
                lambda: node.set_relay_damping(False),
            )
        )
    if mining is not None:
        out.append(
            BrownoutAction(
                "template_deferral",
                CRITICAL,
                lambda level: mining.set_template_deferral(k.template_grace_s),
                lambda: mining.set_template_deferral(0.0),
            )
        )
    return out


def build_controller(
    *,
    mining=None,
    tier=None,
    broadcaster=None,
    node=None,
    fanout_depth_fn=None,
    thresholds: dict | None = None,
    knobs: BrownoutKnobs | None = None,
    rise_samples: int = 2,
    fall_samples: int = 3,
    clock=time.monotonic,
) -> OverloadController:
    """Standard node wiring: default signals + default actions over
    whatever subsystems exist."""
    return OverloadController(
        default_signals(
            mining=mining,
            tier=tier,
            broadcaster=broadcaster,
            fanout_depth_fn=fanout_depth_fn,
            thresholds=thresholds,
        ),
        default_actions(
            tier=tier, broadcaster=broadcaster, node=node, mining=mining,
            knobs=knobs, thresholds=thresholds,
        ),
        rise_samples=rise_samples,
        fall_samples=fall_samples,
        clock=clock,
    )
