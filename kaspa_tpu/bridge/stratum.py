"""Stratum bridge: miner-facing job server over block templates.

Reference: bridge/src/stratum_server.rs + client_handler.rs +
mining_state.rs + share_handler.rs (the rk-stratum bridge): accepts
stratum JSON-line connections from miners, serves jobs derived from node
block templates (pre-PoW hash + timestamp), tracks a bounded job ring,
validates submitted nonces against the per-worker share target and the
network target, forwards solved blocks to the node, runs the vardiff
loop (share_handler.rs vardiff_compute_next_diff, same tunables), and
exposes Prometheus-style metrics (prom.rs).

Protocol (line-delimited JSON, the kaspa-stratum dialect):
  -> {"id", "method": "mining.subscribe", "params": [agent]}
  <- result [subscription id, extranonce]
  -> {"id", "method": "mining.authorize", "params": [worker, _]}
  <- result true; then notifications:
  <- {"method": "set_extranonce"| "mining.set_difficulty", ...}
  <- {"method": "mining.notify", "params": [job_id, pre_pow_hash_hex, timestamp]}
  -> {"id", "method": "mining.submit", "params": [worker, job_id, nonce_hex]}
  <- result true | error (stale/low-difficulty/duplicate share)
"""

from __future__ import annotations

import json
import math
import secrets
import socketserver
import threading

from kaspa_tpu.utils.sync import ranked_lock
import time

from kaspa_tpu.consensus import hashing as chash
from kaspa_tpu.consensus.difficulty import compact_to_target
from kaspa_tpu.core.log import get_logger
from kaspa_tpu.crypto.powhash import pow_hash

log = get_logger("stratum")

MAX_JOBS = 256

# stratum difficulty 1 reference target (hasher.rs DIFF1 convention)
DIFF1_TARGET = (1 << 255) - 1

# VarDiff tunables (share_handler.rs:44-58, same values)
VARDIFF_MIN_ELAPSED_SECS = 30.0
VARDIFF_MAX_ELAPSED_SECS_NO_SHARES = 90.0
VARDIFF_MIN_SHARES = 3.0
VARDIFF_LOWER_RATIO = 0.75  # below this => decrease diff
VARDIFF_UPPER_RATIO = 1.25  # above this => increase diff
VARDIFF_MAX_STEP_UP = 2.0  # max 2x per adjustment tick
VARDIFF_MAX_STEP_DOWN = 0.5  # max -50% per adjustment tick


def vardiff_pow2_clamp_towards(current: float, next_: float) -> float:
    """share_handler.rs:46 — snap toward the nearest power of two."""
    if not math.isfinite(next_) or next_ <= 0.0:
        return 1.0
    exp = math.ceil(math.log2(next_)) if next_ >= current else math.floor(math.log2(next_))
    clamped = 2.0 ** int(exp)
    return clamped if clamped >= 1.0 else 1.0


def vardiff_compute_next_diff(
    current: float, shares: float, elapsed_secs: float, expected_spm: float, clamp_pow2: bool
) -> float | None:
    """Next difficulty, or None when no adjustment applies — same policy
    constants and semantics as share_handler.rs:56 vardiff_compute_next_diff
    (including the :100-102 10% hysteresis), since pools tune against the
    reference's observable adjustment behavior."""
    if not math.isfinite(current) or current <= 0.0:
        return None
    if not math.isfinite(elapsed_secs) or elapsed_secs <= 0.0:
        return None
    if shares == 0.0 and elapsed_secs >= VARDIFF_MAX_ELAPSED_SECS_NO_SHARES:
        next_ = max(current * VARDIFF_MAX_STEP_DOWN, 1.0)
        if clamp_pow2:
            next_ = vardiff_pow2_clamp_towards(current, next_)
        return None if next_ == current else next_
    if elapsed_secs < VARDIFF_MIN_ELAPSED_SECS or shares < VARDIFF_MIN_SHARES:
        return None
    observed_spm = (shares / elapsed_secs) * 60.0
    ratio = observed_spm / max(expected_spm, 1.0)
    if not math.isfinite(ratio) or ratio <= 0.0:
        return None
    if VARDIFF_LOWER_RATIO < ratio < VARDIFF_UPPER_RATIO:
        return None
    step = min(max(math.sqrt(ratio), VARDIFF_MAX_STEP_DOWN), VARDIFF_MAX_STEP_UP)
    next_ = max(current * step, 1.0)
    if clamp_pow2:
        next_ = vardiff_pow2_clamp_towards(current, next_)
    # 10% hysteresis (share_handler.rs:100-102): hold difficulty unless the
    # relative change is large enough — prevents oscillation when pow2
    # clamping is off and the observed rate hovers near a band edge
    rel_change = abs(next_ - current) / max(current, 1.0)
    if rel_change < 0.10:
        return None
    return None if next_ == current else next_


class StratumError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code


class WorkerStats:
    """Per-worker share window + difficulty (share_handler.rs WorkerStats)."""

    def __init__(self, difficulty: float, now: float):
        self.difficulty = difficulty
        self.window_shares = 0
        self.window_start = now
        self.total_accepted = 0
        self.total_stale = 0
        self.total_duplicate = 0
        self.total_low_diff = 0
        self.blocks_found = 0
        self.connected_at = now


class ShareHandler:
    """Share accounting + the vardiff loop (share_handler.rs).

    ``now`` is injectable for deterministic tests."""

    def __init__(
        self,
        expected_shares_per_min: float = 20.0,  # app_config.rs default
        initial_difficulty: float = 1.0,
        clamp_pow2: bool = True,
        now=time.monotonic,
    ):
        self.expected_spm = expected_shares_per_min
        self.initial_difficulty = initial_difficulty
        self.clamp_pow2 = clamp_pow2
        self.now = now
        self.workers: dict[str, WorkerStats] = {}
        self._mu = ranked_lock("stratum.stats")

    def worker(self, name: str) -> WorkerStats:
        with self._mu:
            ws = self.workers.get(name)
            if ws is None:
                ws = self.workers[name] = WorkerStats(self.initial_difficulty, self.now())
            return ws

    def share_target(self, name: str) -> int:
        d = max(self.worker(name).difficulty, 1.0)
        return int(DIFF1_TARGET / d)

    def record_share(self, name: str, outcome: str) -> None:
        ws = self.worker(name)
        with self._mu:
            if outcome == "accepted":
                ws.total_accepted += 1
                ws.window_shares += 1
            elif outcome == "stale":
                ws.total_stale += 1
            elif outcome == "duplicate":
                ws.total_duplicate += 1
            elif outcome == "low-diff":
                ws.total_low_diff += 1

    def maybe_adjust(self, name: str) -> float | None:
        """Run one vardiff evaluation for the worker; returns the NEW
        difficulty when it changed (callers push mining.set_difficulty)."""
        ws = self.worker(name)
        with self._mu:
            elapsed = self.now() - ws.window_start
            nxt = vardiff_compute_next_diff(
                ws.difficulty, float(ws.window_shares), elapsed, self.expected_spm, self.clamp_pow2
            )
            if nxt is None:
                return None
            ws.difficulty = nxt
            ws.window_shares = 0
            ws.window_start = self.now()
            return nxt


class MiningState:
    """Job ring + share bookkeeping (mining_state.rs)."""

    def __init__(self):
        self._jobs: dict[int, object] = {}
        self._next = 0
        self._seen_shares: set = set()
        self._mu = ranked_lock("stratum.shares")
        self.shares_accepted = 0
        self.shares_stale = 0
        self.shares_duplicate = 0
        self.shares_low_diff = 0
        self.blocks_found = 0

    def add_job(self, block) -> int:
        with self._mu:
            job_id = self._next
            self._next += 1
            self._jobs[job_id % MAX_JOBS] = (job_id, block)
            return job_id

    def get_job(self, job_id: int):
        with self._mu:
            slot = self._jobs.get(job_id % MAX_JOBS)
            if slot is None or slot[0] != job_id:
                return None
            return slot[1]

    def register_share(self, job_id: int, nonce: int) -> bool:
        """False if this (job, nonce) was already submitted (dup share)."""
        with self._mu:
            key = (job_id, nonce)
            if key in self._seen_shares:
                return False
            self._seen_shares.add(key)
            if len(self._seen_shares) > 1 << 16:
                self._seen_shares.clear()
            return True


class StratumBridge:
    """The bridge core, transport-independent for testability.

    ``template_source() -> Block`` and ``submit_block(block) -> status``
    bind it to a node (in-process or RPC)."""

    def __init__(
        self,
        template_source,
        submit_block,
        expected_shares_per_min: float = 20.0,
        initial_difficulty: float = 1.0,
        clamp_pow2: bool = True,
        now=time.monotonic,
    ):
        self.template_source = template_source
        self.submit_block = submit_block
        self.state = MiningState()
        self.share_handler = ShareHandler(
            expected_shares_per_min, initial_difficulty, clamp_pow2, now
        )

    # --- jobs ---

    def new_job(self):
        """Fetch a template and publish a job: (job_id, pre_pow_hash, ts)."""
        block = self.template_source()
        job_id = self.state.add_job(block)
        pre_pow = chash.header_hash_override_nonce_time(block.header, 0, 0)
        return job_id, pre_pow, block.header.timestamp

    def notify_params(self):
        job_id, pre_pow, ts = self.new_job()
        return [f"{job_id:08x}", pre_pow.hex(), ts]

    # --- shares ---

    def submit(self, worker: str, job_id: int, nonce: int) -> bool:
        """Returns True when the share also solved a block."""
        block = self.state.get_job(job_id)
        if block is None:
            self.state.shares_stale += 1
            self.share_handler.record_share(worker, "stale")
            raise StratumError(21, "Job not found")  # stale share
        if not self.state.register_share(job_id, nonce):
            self.state.shares_duplicate += 1
            self.share_handler.record_share(worker, "duplicate")
            raise StratumError(22, "Duplicate share")
        pre_pow = chash.header_hash_override_nonce_time(block.header, 0, 0)
        value = int.from_bytes(pow_hash(pre_pow, block.header.timestamp, nonce), "little")
        network_target = compact_to_target(block.header.bits)
        share_target = max(self.share_handler.share_target(worker), network_target)
        if value > share_target:
            self.state.shares_low_diff += 1
            self.share_handler.record_share(worker, "low-diff")
            raise StratumError(20, "Low difficulty share")
        self.state.shares_accepted += 1
        self.share_handler.record_share(worker, "accepted")
        if value <= network_target:
            # block found: graft the nonce and hand it to the node
            block.header.nonce = nonce
            block.header.invalidate_cache()
            self.submit_block(block)
            self.state.blocks_found += 1
            self.share_handler.worker(worker).blocks_found += 1
            return True
        return False

    # --- metrics (prom.rs exposition) ---

    def metrics_text(self) -> str:
        s = self.state
        lines = [
            "# TYPE stratum_shares_accepted_total counter",
            f"stratum_shares_accepted_total {s.shares_accepted}",
            "# TYPE stratum_shares_stale_total counter",
            f"stratum_shares_stale_total {s.shares_stale}",
            "# TYPE stratum_shares_duplicate_total counter",
            f"stratum_shares_duplicate_total {s.shares_duplicate}",
            "# TYPE stratum_shares_low_diff_total counter",
            f"stratum_shares_low_diff_total {s.shares_low_diff}",
            "# TYPE stratum_blocks_found_total counter",
            f"stratum_blocks_found_total {s.blocks_found}",
            "# TYPE stratum_worker_difficulty gauge",
        ]
        with self.share_handler._mu:
            workers = [(name, ws.difficulty) for name, ws in self.share_handler.workers.items()]
        for name, diff in workers:
            label = name.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
            lines.append(f'stratum_worker_difficulty{{worker="{label}"}} {diff}')
        return "\n".join(lines) + "\n"


class _StratumHandler(socketserver.StreamRequestHandler):
    # periodic wakeup so vardiff's zero-share decay path runs for idle
    # miners (share_handler.rs evaluates on a timer, not only per share)
    IDLE_TICK_SECS = 10.0

    def handle(self):
        import socket as _socket

        bridge: StratumBridge = self.server.bridge  # type: ignore[attr-defined]
        extranonce = secrets.token_hex(2)
        worker = None
        self.connection.settimeout(self.IDLE_TICK_SECS)
        # own line buffer: a timeout mid-line must keep the partial bytes
        # (BufferedReader.readline would discard them on the exception)
        buf = bytearray()
        while True:
            nl = buf.find(b"\n")
            if nl < 0:
                try:
                    chunk = self.connection.recv(65536)
                except (_socket.timeout, TimeoutError):
                    if worker is not None:
                        new_diff = bridge.share_handler.maybe_adjust(worker)
                        if new_diff is not None:
                            self._notify("mining.set_difficulty", [new_diff])
                    continue
                except OSError:
                    return
                if not chunk:
                    return
                buf += chunk
                continue
            line = bytes(buf[:nl])
            del buf[: nl + 1]
            line = line.strip()
            if not line:
                continue
            try:
                req = json.loads(line)
            except ValueError:
                break
            rid = req.get("id")
            method = req.get("method", "")
            params = req.get("params", [])
            try:
                if method == "mining.subscribe":
                    self._reply(rid, [["kaspa/1.0", extranonce], extranonce])
                elif method == "mining.authorize":
                    worker = str(params[0]) if params else f"worker-{extranonce}"
                    ws = bridge.share_handler.worker(worker)
                    self._reply(rid, True)
                    self._notify("set_extranonce", [extranonce])
                    self._notify("mining.set_difficulty", [ws.difficulty])
                    self._notify("mining.notify", bridge.notify_params())
                elif method == "mining.submit":
                    if worker is None:
                        raise StratumError(24, "Unauthorized")
                    _worker, job_hex, nonce_hex = params[:3]
                    solved = bridge.submit(worker, int(job_hex, 16), int(nonce_hex, 16))
                    self._reply(rid, True)
                    # vardiff tick rides the submit path (share_handler.rs
                    # evaluates per share against the worker's window)
                    new_diff = bridge.share_handler.maybe_adjust(worker)
                    if new_diff is not None:
                        self._notify("mining.set_difficulty", [new_diff])
                    if solved:
                        self._notify("mining.notify", bridge.notify_params())
                elif method == "mining.get_job":
                    # convenience poll for miners without notify support
                    self._reply(rid, bridge.notify_params())
                elif method == "mining.get_metrics":
                    self._reply(rid, bridge.metrics_text())
                else:
                    self._reply(rid, None, error=[20, f"unknown method {method}", None])
            except StratumError as e:
                self._reply(rid, None, error=[e.code, str(e), None])
            except Exception as e:  # noqa: BLE001 - wire boundary
                self._reply(rid, None, error=[20, str(e), None])

    def _reply(self, rid, result, error=None):
        self.wfile.write((json.dumps({"id": rid, "result": result, "error": error}) + "\n").encode())
        self.wfile.flush()

    def _notify(self, method: str, params) -> None:
        self.wfile.write((json.dumps({"id": None, "method": method, "params": params}) + "\n").encode())
        self.wfile.flush()


class StratumServer:
    """TCP front end (stratum_listener.rs)."""

    def __init__(self, bridge: StratumBridge, host: str = "127.0.0.1", port: int = 5555):
        self.bridge = bridge
        srv = socketserver.ThreadingTCPServer((host, port), _StratumHandler, bind_and_activate=False)
        srv.allow_reuse_address = True
        srv.daemon_threads = True
        srv.server_bind()
        srv.server_activate()
        srv.bridge = bridge  # type: ignore[attr-defined]
        self._srv = srv
        self.address = f"{host}:{srv.server_address[1]}"
        self._thread = threading.Thread(target=srv.serve_forever, daemon=True)

    def start(self) -> str:
        self._thread.start()
        log.info("stratum bridge listening on %s", self.address)
        return self.address

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
