"""Stratum bridge: miner-facing job server over block templates.

Reference: bridge/src/stratum_server.rs + client_handler.rs +
mining_state.rs (the rk-stratum bridge): accepts stratum JSON-line
connections from miners, serves jobs derived from node block templates
(pre-PoW hash + timestamp), tracks a bounded job ring, validates
submitted nonces against the share and network targets, and forwards
solved blocks to the node.

Protocol (line-delimited JSON, the kaspa-stratum dialect):
  -> {"id", "method": "mining.subscribe", "params": [agent]}
  <- result [subscription id, extranonce]
  -> {"id", "method": "mining.authorize", "params": [worker, _]}
  <- result true; then notifications:
  <- {"method": "set_extranonce"| "mining.set_difficulty", ...}
  <- {"method": "mining.notify", "params": [job_id, pre_pow_hash_hex, timestamp]}
  -> {"id", "method": "mining.submit", "params": [worker, job_id, nonce_hex]}
  <- result true | error (stale/low-difficulty/duplicate share)
"""

from __future__ import annotations

import json
import secrets
import socketserver
import threading

from kaspa_tpu.consensus import hashing as chash
from kaspa_tpu.consensus.difficulty import compact_to_target
from kaspa_tpu.core.log import get_logger
from kaspa_tpu.crypto.powhash import pow_hash

log = get_logger("stratum")

MAX_JOBS = 256


class StratumError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code


class MiningState:
    """Job ring + share bookkeeping (mining_state.rs)."""

    def __init__(self):
        self._jobs: dict[int, object] = {}
        self._next = 0
        self._seen_shares: set = set()
        self._mu = threading.Lock()
        self.shares_accepted = 0
        self.shares_stale = 0
        self.blocks_found = 0

    def add_job(self, block) -> int:
        with self._mu:
            job_id = self._next
            self._next += 1
            self._jobs[job_id % MAX_JOBS] = (job_id, block)
            return job_id

    def get_job(self, job_id: int):
        with self._mu:
            slot = self._jobs.get(job_id % MAX_JOBS)
            if slot is None or slot[0] != job_id:
                return None
            return slot[1]

    def register_share(self, job_id: int, nonce: int) -> bool:
        """False if this (job, nonce) was already submitted (dup share)."""
        with self._mu:
            key = (job_id, nonce)
            if key in self._seen_shares:
                return False
            self._seen_shares.add(key)
            if len(self._seen_shares) > 1 << 16:
                self._seen_shares.clear()
            return True


class StratumBridge:
    """The bridge core, transport-independent for testability.

    ``template_source() -> Block`` and ``submit_block(block) -> status``
    bind it to a node (in-process or RPC)."""

    def __init__(self, template_source, submit_block, share_difficulty_shift: int = 8):
        self.template_source = template_source
        self.submit_block = submit_block
        self.state = MiningState()
        # share target = network target << shift (easier shares for vardiff
        # accounting; the reference runs a full vardiff loop)
        self.share_difficulty_shift = share_difficulty_shift

    # --- jobs ---

    def new_job(self):
        """Fetch a template and publish a job: (job_id, pre_pow_hash, ts)."""
        block = self.template_source()
        job_id = self.state.add_job(block)
        pre_pow = chash.header_hash_override_nonce_time(block.header, 0, 0)
        return job_id, pre_pow, block.header.timestamp

    def notify_params(self):
        job_id, pre_pow, ts = self.new_job()
        return [f"{job_id:08x}", pre_pow.hex(), ts]

    # --- shares ---

    def submit(self, job_id: int, nonce: int) -> bool:
        """Returns True when the share also solved a block."""
        block = self.state.get_job(job_id)
        if block is None:
            self.state.shares_stale += 1
            raise StratumError(21, "Job not found")  # stale share
        if not self.state.register_share(job_id, nonce):
            raise StratumError(22, "Duplicate share")
        pre_pow = chash.header_hash_override_nonce_time(block.header, 0, 0)
        value = int.from_bytes(pow_hash(pre_pow, block.header.timestamp, nonce), "little")
        network_target = compact_to_target(block.header.bits)
        share_target = min(network_target << self.share_difficulty_shift, (1 << 256) - 1)
        if value > share_target:
            raise StratumError(20, "Low difficulty share")
        self.state.shares_accepted += 1
        if value <= network_target:
            # block found: graft the nonce and hand it to the node
            block.header.nonce = nonce
            block.header.invalidate_cache()
            self.submit_block(block)
            self.state.blocks_found += 1
            return True
        return False


class _StratumHandler(socketserver.StreamRequestHandler):
    def handle(self):
        bridge: StratumBridge = self.server.bridge  # type: ignore[attr-defined]
        extranonce = secrets.token_hex(2)
        authorized = False
        for line in self.rfile:
            line = line.strip()
            if not line:
                continue
            try:
                req = json.loads(line)
            except ValueError:
                break
            rid = req.get("id")
            method = req.get("method", "")
            params = req.get("params", [])
            try:
                if method == "mining.subscribe":
                    self._reply(rid, [["kaspa/1.0", extranonce], extranonce])
                elif method == "mining.authorize":
                    authorized = True
                    self._reply(rid, True)
                    self._notify("set_extranonce", [extranonce])
                    self._notify("mining.set_difficulty", [1.0])
                    self._notify("mining.notify", bridge.notify_params())
                elif method == "mining.submit":
                    if not authorized:
                        raise StratumError(24, "Unauthorized")
                    _worker, job_hex, nonce_hex = params[:3]
                    solved = bridge.submit(int(job_hex, 16), int(nonce_hex, 16))
                    self._reply(rid, True)
                    if solved:
                        self._notify("mining.notify", bridge.notify_params())
                elif method == "mining.get_job":
                    # convenience poll for miners without notify support
                    self._reply(rid, bridge.notify_params())
                else:
                    self._reply(rid, None, error=[20, f"unknown method {method}", None])
            except StratumError as e:
                self._reply(rid, None, error=[e.code, str(e), None])
            except Exception as e:  # noqa: BLE001 - wire boundary
                self._reply(rid, None, error=[20, str(e), None])

    def _reply(self, rid, result, error=None):
        self.wfile.write((json.dumps({"id": rid, "result": result, "error": error}) + "\n").encode())
        self.wfile.flush()

    def _notify(self, method: str, params) -> None:
        self.wfile.write((json.dumps({"id": None, "method": method, "params": params}) + "\n").encode())
        self.wfile.flush()


class StratumServer:
    """TCP front end (stratum_listener.rs)."""

    def __init__(self, bridge: StratumBridge, host: str = "127.0.0.1", port: int = 5555):
        self.bridge = bridge
        srv = socketserver.ThreadingTCPServer((host, port), _StratumHandler, bind_and_activate=False)
        srv.allow_reuse_address = True
        srv.daemon_threads = True
        srv.server_bind()
        srv.server_activate()
        srv.bridge = bridge  # type: ignore[attr-defined]
        self._srv = srv
        self.address = f"{host}:{srv.server_address[1]}"
        self._thread = threading.Thread(target=srv.serve_forever, daemon=True)

    def start(self) -> str:
        self._thread.start()
        log.info("stratum bridge listening on %s", self.address)
        return self.address

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
