"""UTXO index: script-pubkey -> UTXO inverted index.

Reference: indexes/utxoindex/src/{index.rs,update_container.rs,stores/} —
fed by UtxosChanged virtual diffs from the consensus notification root,
with full resync from the virtual UTXO set on reset.
"""

from __future__ import annotations

from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.notify.notifier import Notification


class UtxoIndex:
    def __init__(self, consensus: Consensus):
        self.consensus = consensus
        # spk script bytes -> {outpoint: UtxoEntry}
        self._by_script: dict[bytes, dict] = {}
        self._listener_id = consensus.notification_root.register(self._on_notification)
        consensus.notification_root.start_notify(self._listener_id, "utxos-changed")
        self.resync()

    def _on_notification(self, n: Notification) -> None:
        if n.event_type != "utxos-changed":
            return
        for outpoint, entry in n.data.get("removed", []):
            bucket = self._by_script.get(entry.script_public_key.script)
            if bucket is not None:
                bucket.pop(outpoint, None)
                if not bucket:
                    del self._by_script[entry.script_public_key.script]
        for outpoint, entry in n.data.get("added", []):
            self._by_script.setdefault(entry.script_public_key.script, {})[outpoint] = entry

    def resync(self) -> None:
        """Rebuild from the sink UTXO state (index.rs resync).

        Tracks the materialized selected-chain state; the unmerged virtual
        mergeset diff is intentionally excluded (it is replayed when those
        blocks become chain blocks)."""
        self._by_script.clear()
        self.consensus._move_utxo_position(self.consensus.sink())
        for outpoint, entry in self.consensus.utxo_set.items():
            self._by_script.setdefault(entry.script_public_key.script, {})[outpoint] = entry

    def get_utxos_by_script(self, script: bytes) -> dict:
        return dict(self._by_script.get(script, {}))

    def get_balance_by_script(self, script: bytes) -> int:
        return sum(e.amount for e in self._by_script.get(script, {}).values())

    def get_circulating_supply(self) -> int:
        return sum(e.amount for bucket in self._by_script.values() for e in bucket.values())
