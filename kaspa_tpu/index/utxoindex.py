"""UTXO index: script-pubkey -> UTXO inverted index, memory- or DB-backed.

Reference: indexes/utxoindex/src/{index.rs,update_container.rs,stores/} —
fed by UtxosChanged virtual diffs from the consensus notification root,
with full resync from the virtual UTXO set only on a version/network
mismatch (stores/indexed_utxos.rs + supply.rs + tips.rs columns).

Persistent mode rides the crash-safe journaled KV batches from storage/kv:
every UtxosChanged diff lands as ONE atomic write batch (utxo puts/deletes
+ supply + position + an undo-journal record), so a kill -9 can only ever
leave the index at a batch boundary.  Because consensus publishes the
notification BEFORE flushing its own stores (virtual resolve precedes
``storage.flush``), a crash can leave the index AHEAD of the reopened
consensus by one diff — the bounded undo journal rewinds exactly those
diffs on reopen, then the selected-chain walk over ``consensus.utxo_diffs``
replays forward to the live sink.  Full resync is the last resort, never
the restart path.

DB layout (single KvStore, own file — ``utxoindex.db`` beside the
consensus DB):

  ``M...``            meta: version, network, position(32B), supply(u64 LE),
                      dirty marker (present only mid-resync)
  ``U`` + len(script) as u16 BE + script + txid(32) + index(u32 BE)
                      -> serde-encoded UtxoEntry (the length prefix makes
                      the per-script prefix scan exact: no same-prefix
                      script can alias)
  ``J`` + seq(u64 BE) -> undo record: prev_pos | new_pos | added | removed
"""

from __future__ import annotations

import struct

from kaspa_tpu.consensus import serde
from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.consensus.model import TransactionOutpoint
from kaspa_tpu.core.log import get_logger
from kaspa_tpu.notify.notifier import Notification
from kaspa_tpu.observability import trace
from kaspa_tpu.observability.core import REGISTRY

log = get_logger("utxoindex")

INDEX_VERSION = 1

_META_VERSION = b"Mversion"
_META_NETWORK = b"Mnetwork"
_META_POSITION = b"Mposition"
_META_SUPPLY = b"Msupply"
_META_DIRTY = b"Mdirty"
_UTXO = b"U"
_JOURNAL = b"J"

_JOURNAL_KEEP = 16  # rewind depth >> the 1-diff crash window
_RESYNC_CHUNK = 4096

_OPENS = REGISTRY.counter_family(
    "utxoindex_opens", "mode", help="index open outcomes: memory/fresh/clean/catchup/resync"
)
_DIFFS = REGISTRY.counter("utxoindex_diffs_applied", help="UtxosChanged diffs applied atomically to the index DB")
_REWINDS = REGISTRY.counter("utxoindex_journal_rewinds", help="crash-window diffs undone from the journal on reopen")
_CATCHUP = REGISTRY.counter("utxoindex_catchup_blocks", help="chain diffs replayed to reach the live sink on reopen")
_RESYNCS = REGISTRY.counter("utxoindex_resyncs", help="full rebuilds from the virtual UTXO set")


class UtxoIndexError(Exception):
    pass


class _CatchUpError(UtxoIndexError):
    """Reopen state can't be reconciled incrementally — resync instead."""


def utxo_key(script: bytes, outpoint: TransactionOutpoint) -> bytes:
    if len(script) > 0xFFFF:
        raise UtxoIndexError(f"script of {len(script)} bytes exceeds the index key bound")
    return _UTXO + struct.pack(">H", len(script)) + script + outpoint.transaction_id + struct.pack(">I", outpoint.index)


def script_prefix(script: bytes) -> bytes:
    return _UTXO + struct.pack(">H", len(script)) + script


def _encode_journal(prev_pos: bytes, new_pos: bytes, added, removed) -> bytes:
    import io

    w = io.BytesIO()
    w.write(prev_pos)
    w.write(new_pos)
    for pairs in (added, removed):
        w.write(struct.pack("<I", len(pairs)))
        for outpoint, entry in pairs:
            serde.write_outpoint(w, outpoint)
            serde.write_utxo_entry(w, entry)
    return w.getvalue()


def _decode_journal(data: bytes):
    import io

    r = io.BytesIO(data)
    prev_pos = r.read(32)
    new_pos = r.read(32)
    out = []
    for _ in range(2):
        (n,) = struct.unpack("<I", r.read(4))
        out.append([(serde.read_outpoint(r), serde.read_utxo_entry(r)) for _ in range(n)])
    return prev_pos, new_pos, out[0], out[1]


class UtxoIndex:
    """``UtxoIndex(consensus)`` is the in-memory index (tests, --no-persist);
    ``UtxoIndex(consensus, db_path=...)`` is the persistent serving index."""

    VERSION = INDEX_VERSION

    def __init__(self, consensus: Consensus, db_path: str | None = None, db=None):
        self.consensus = consensus
        self.db = db
        self._owns_db = False
        if db is None and db_path is not None:
            from kaspa_tpu.storage.kv import KvStore

            self.db = KvStore(db_path)
            self._owns_db = True
        # in-memory mode only: spk script bytes -> {outpoint: UtxoEntry}
        self._by_script: dict[bytes, dict] | None = {} if self.db is None else None
        self._position: bytes = consensus.params.genesis.hash
        self._supply = 0
        self._journal_seq = 0
        self.open_mode: str | None = None
        self.journal_rewinds = 0
        self.catchup_blocks = 0
        self._listener_id = consensus.notification_root.register(self._on_notification)
        consensus.notification_root.start_notify(self._listener_id, "utxos-changed")
        try:
            if self.db is None:
                self.resync()
                self.open_mode = "memory"
            else:
                self._open_persistent()
        except BaseException:
            self.close()
            raise
        _OPENS.inc(self.open_mode)

    # ------------------------------------------------------------------
    # notification path
    # ------------------------------------------------------------------

    def _on_notification(self, n: Notification) -> None:
        if n.event_type != "utxos-changed":
            return
        added = n.data.get("added", [])
        removed = n.data.get("removed", [])
        with trace.span(
            "utxoindex.apply", parent=getattr(n, "ctx", None),
            added=len(added), removed=len(removed),
        ):
            if self.db is None:
                for outpoint, entry in removed:
                    bucket = self._by_script.get(entry.script_public_key.script)
                    if bucket is not None:
                        bucket.pop(outpoint, None)
                        if not bucket:
                            del self._by_script[entry.script_public_key.script]
                for outpoint, entry in added:
                    self._by_script.setdefault(entry.script_public_key.script, {})[outpoint] = entry
                return
            sink = n.data.get("sink", self._position)
            try:
                self._apply_diff(added, removed, sink)
                _DIFFS.inc()
            except Exception:  # noqa: BLE001 - a broken diff must not wedge consensus
                log.exception("diff application failed at %s; rebuilding index", sink.hex()[:16])
                self.resync()

    def _apply_diff(self, added, removed, new_pos: bytes, journal: bool = True) -> None:
        """ONE atomic batch: entry mutations + supply + position + journal."""
        eng = self.db.engine
        delta = 0
        prev_pos = self._position
        with self.db.batch() as b:
            for outpoint, entry in removed:
                key = utxo_key(entry.script_public_key.script, outpoint)
                if not eng.has(key):
                    raise UtxoIndexError(f"removed entry missing from index: {outpoint}")
                b.delete(key)
                delta -= entry.amount
            for outpoint, entry in added:
                b.put(utxo_key(entry.script_public_key.script, outpoint), serde.encode_utxo_entry(entry))
                delta += entry.amount
            if self._supply + delta < 0:
                raise UtxoIndexError("circulating supply went negative")
            b.put(_META_SUPPLY, struct.pack("<Q", self._supply + delta))
            b.put(_META_POSITION, new_pos)
            if journal and new_pos != prev_pos:
                b.put(_JOURNAL + struct.pack(">Q", self._journal_seq), _encode_journal(prev_pos, new_pos, added, removed))
                drop = self._journal_seq - _JOURNAL_KEEP
                if drop >= 0:
                    b.delete(_JOURNAL + struct.pack(">Q", drop))
        self._supply += delta
        self._position = new_pos
        if journal and new_pos != prev_pos:
            self._journal_seq += 1

    # ------------------------------------------------------------------
    # open / reconcile
    # ------------------------------------------------------------------

    def _open_persistent(self) -> None:
        eng = self.db.engine
        raw_ver = eng.get(_META_VERSION)
        net = self.consensus.params.name
        if raw_ver is None:
            self.resync()
            self.open_mode = "fresh"
            return
        stored_net = (eng.get(_META_NETWORK) or b"").decode()
        pos = eng.get(_META_POSITION)
        supply_raw = eng.get(_META_SUPPLY)
        if (
            int(raw_ver) != self.VERSION
            or stored_net != net
            or pos is None
            or supply_raw is None
            or eng.get(_META_DIRTY) is not None  # crashed mid-resync
        ):
            self.resync()
            self.open_mode = "resync"
            return
        self._position = pos
        self._supply = struct.unpack("<Q", supply_raw)[0]
        self._journal_seq = self._next_journal_seq()
        target = self.consensus.sink()
        if pos == target:
            self.open_mode = "clean"
            return
        try:
            self._catch_up(target)
            self.open_mode = "catchup"
        except (UtxoIndexError, KeyError, AssertionError) as e:
            log.warning("incremental catch-up failed (%s); full resync", e)
            self.resync()
            self.open_mode = "resync"

    def _next_journal_seq(self) -> int:
        keys = self.db.engine.keys_prefix(_JOURNAL)
        return struct.unpack(">Q", keys[-1])[0] + 1 if keys else 0

    def _known(self, block: bytes) -> bool:
        c = self.consensus
        return c.storage.statuses.get(block) is not None and c.reachability.has(block)

    def _catch_up(self, target: bytes) -> None:
        """Reconcile the stored position with the reopened consensus:
        (1) rewind journal records while the stored position is unknown to
        consensus (the notify-before-flush crash window), then (2) the
        selected-chain back/forward walk over ``utxo_diffs`` — the index's
        copy of ``Consensus._move_utxo_position``, applied to the DB."""
        c = self.consensus
        rewinds = 0
        while not self._known(self._position):
            if rewinds >= _JOURNAL_KEEP:
                raise _CatchUpError("position unknown to consensus beyond journal depth")
            self._rewind_one()
            rewinds += 1
        cur = self._position
        back = []
        while not c.reachability.is_chain_ancestor_of(cur, target):
            back.append(cur)
            cur = c.storage.ghostdag.get_selected_parent(cur)
        fwd = []
        t = target
        while t != cur:
            fwd.append(t)
            t = c.storage.ghostdag.get_selected_parent(t)
        for b in back:
            diff = c.utxo_diffs.get(b)
            if diff is None:
                raise _CatchUpError(f"no chain diff for {b.hex()[:16]}")
            # unapply: the inverse mutation, journaled like any other move
            self._apply_diff(list(diff.remove.items()), list(diff.add.items()),
                             c.storage.ghostdag.get_selected_parent(b))
        for b in reversed(fwd):
            diff = c.utxo_diffs.get(b)
            if diff is None:
                raise _CatchUpError(f"no chain diff for {b.hex()[:16]}")
            self._apply_diff(list(diff.add.items()), list(diff.remove.items()), b)
        moved = len(back) + len(fwd)
        self.catchup_blocks += moved
        _CATCHUP.inc(moved)

    def _rewind_one(self) -> None:
        """Undo the most recent journaled diff (one atomic batch)."""
        eng = self.db.engine
        keys = eng.keys_prefix(_JOURNAL)
        if not keys:
            raise _CatchUpError("undo journal is empty")
        last = keys[-1]
        prev_pos, new_pos, added, removed = _decode_journal(eng.get(_JOURNAL + last))
        if new_pos != self._position:
            raise _CatchUpError("journal head does not match the stored position")
        delta = 0
        with self.db.batch() as b:
            for outpoint, entry in added:
                key = utxo_key(entry.script_public_key.script, outpoint)
                if not eng.has(key):
                    raise _CatchUpError(f"journaled add missing from index: {outpoint}")
                b.delete(key)
                delta -= entry.amount
            for outpoint, entry in removed:
                b.put(utxo_key(entry.script_public_key.script, outpoint), serde.encode_utxo_entry(entry))
                delta += entry.amount
            b.put(_META_SUPPLY, struct.pack("<Q", self._supply + delta))
            b.put(_META_POSITION, prev_pos)
            b.delete(_JOURNAL + last)
        self._supply += delta
        self._position = prev_pos
        self._journal_seq = struct.unpack(">Q", last)[0]
        self.journal_rewinds += 1
        _REWINDS.inc()

    # ------------------------------------------------------------------
    # resync
    # ------------------------------------------------------------------

    def resync(self) -> None:
        """Rebuild from the sink UTXO state (index.rs resync).

        Tracks the materialized selected-chain state; the unmerged virtual
        mergeset diff is intentionally excluded (it is replayed when those
        blocks become chain blocks).  Persistent mode writes in chunked
        atomic batches under a dirty marker, so a crash mid-resync reopens
        as another resync, never as a silently-partial index."""
        c = self.consensus
        c._move_utxo_position(c.sink())
        if self.db is None:
            self._by_script.clear()
            for outpoint, entry in c.utxo_set.items():
                self._by_script.setdefault(entry.script_public_key.script, {})[outpoint] = entry
            return
        _RESYNCS.inc()
        eng = self.db.engine
        eng.put(_META_DIRTY, b"1")
        for prefix in (_UTXO, _JOURNAL):
            keys = eng.keys_prefix(prefix)
            for i in range(0, len(keys), _RESYNC_CHUNK):
                with self.db.batch() as b:
                    for k in keys[i : i + _RESYNC_CHUNK]:
                        b.delete(prefix + k)
        supply = 0
        chunk: list[tuple[bytes, bytes]] = []

        def flush_chunk():
            with self.db.batch() as b:
                for k, v in chunk:
                    b.put(k, v)
            chunk.clear()

        for outpoint, entry in c.utxo_set.items():
            chunk.append((utxo_key(entry.script_public_key.script, outpoint), serde.encode_utxo_entry(entry)))
            supply += entry.amount
            if len(chunk) >= _RESYNC_CHUNK:
                flush_chunk()
        flush_chunk()
        with self.db.batch() as b:
            # version/network/position land with the dirty-marker removal:
            # the index only ever looks committed when it IS committed
            b.put(_META_VERSION, str(self.VERSION).encode())
            b.put(_META_NETWORK, c.params.name.encode())
            b.put(_META_POSITION, c.sink())
            b.put(_META_SUPPLY, struct.pack("<Q", supply))
            b.delete(_META_DIRTY)
        self._position = c.sink()
        self._supply = supply
        self._journal_seq = 0

    # ------------------------------------------------------------------
    # queries
    # ------------------------------------------------------------------

    def get_utxos_by_script(self, script: bytes) -> dict:
        if self.db is None:
            return dict(self._by_script.get(script, {}))
        out = {}
        for suffix, value in self.db.engine.items_prefix(script_prefix(script)):
            outpoint = TransactionOutpoint(suffix[:32], struct.unpack(">I", suffix[32:36])[0])
            out[outpoint] = serde.decode_utxo_entry(value)
        return out

    def get_balance_by_script(self, script: bytes) -> int:
        if self.db is None:
            return sum(e.amount for e in self._by_script.get(script, {}).values())
        return sum(e.amount for e in self.get_utxos_by_script(script).values())

    def get_circulating_supply(self) -> int:
        if self.db is None:
            return sum(e.amount for bucket in self._by_script.values() for e in bucket.values())
        return self._supply

    def entry_count(self) -> int:
        if self.db is None:
            return sum(len(b) for b in self._by_script.values())
        return self.db.engine.count_prefix(_UTXO)

    @property
    def position(self) -> bytes:
        return self._position

    def content_snapshot(self):
        """(position, supply, ordered U-column pairs) — the identity the
        kill -9 acceptance compares against a fresh resync.  Journal and
        meta columns are excluded by construction (they encode HOW the
        state was reached, not the state)."""
        if self.db is None:
            raise UtxoIndexError("content_snapshot requires the persistent index")
        return (self._position, self._supply, self.db.engine.items_prefix(_UTXO))

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def close(self) -> None:
        """Unregister from the notification root (a torn-down index must
        stop receiving diffs) and close an owned DB.  Idempotent."""
        if self._listener_id is not None:
            self.consensus.notification_root.unregister(self._listener_id)
            self._listener_id = None
        if self._owns_db and self.db is not None:
            self.db.close()
            self.db = None
