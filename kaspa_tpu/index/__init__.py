from kaspa_tpu.index.utxoindex import UtxoIndex  # noqa: F401
