"""The shared ``overload_shed`` counter family: one declaration site.

Every brownout seam (ops/dispatch.py, ingest/tier.py, serving/broadcaster.py,
mempool/mining_manager.py, p2p/node.py, resilience/overload.py) increments
the same family under its own action label:

    dispatch_yield      standalone-tx chunk held back for block-verify work
    ingest_shed         tx rejected at admission with ``node-overloaded``
    fanout_conflation   utxos-changed diffs merged for a slow subscriber
    inv_damping         tx INV relay suppressed under SATURATED
    template_deferral   stale-but-mineable template served past rebuild point

The registry's get-or-create is idempotent, but the registry-hygiene rule
is one name, one declaration — so the family lives here (observability is
below every subsystem; no import cycles) and seams import SHED.
"""

from kaspa_tpu.observability.core import REGISTRY

SHED = REGISTRY.counter_family(
    "overload_shed", "action", help="work shed/deferred by brownout actions, per action"
)
