"""Observability core: counters, fixed-bucket histograms, global registry.

The telemetry substrate the validation pipeline and the TPU kernel path
report into (the role of the reference's perf-monitor + log counters, but
structured): plain-int counters and fixed-bucket histograms mutated without
locks — Python int += and list-slot += are atomic under the GIL — plus a
process-global ``REGISTRY`` whose ``snapshot()`` copies everything into
deterministic plain dicts (sorted keys, JSON-serializable) for
``RpcCoreService.get_metrics`` and the Prometheus exporter (prom.py).

Hot-path discipline: metric objects are created once at import/module
level and call sites hold direct references; ``observe``/``inc`` never
allocate beyond the bisect index.  Registration (rare) takes a lock;
mutation (hot) never does.
"""

from __future__ import annotations

import threading
import weakref

from kaspa_tpu.utils.sync import ranked_lock
from bisect import bisect_left

# log-spaced latency edges in SECONDS: 10 µs .. 10 s (spans, dispatch, IO)
DEFAULT_LATENCY_BUCKETS = (
    1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4,
    1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

# log-spaced latency edges in MILLISECONDS: 10 µs .. 10 s.  Shared by the
# flight recorder's critical-path attribution and the serving tier's
# block-accept -> wire lag families (serving_lag_ms{stage}) so the two
# views of the same interval are bucket-compatible.
MS_LATENCY_BUCKETS = (
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
    25.0, 50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0,
)

# power-of-two size edges (batch sizes, queue depths)
SIZE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192, 16384, 32768, 65536)

# occupancy percentage edges
PERCENT_BUCKETS = (10.0, 25.0, 50.0, 62.5, 75.0, 87.5, 95.0, 100.0)


class Counter:
    """Monotonic counter; also serves as a cell of a CounterFamily."""

    __slots__ = ("name", "help", "value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n

    def snapshot(self) -> int:
        return self.value

    def reset(self) -> None:
        self.value = 0


class CounterFamily:
    """Counter with one label dimension; cells created on first use.

    Hot call sites should hold ``cell(label)`` and call ``inc`` on it.
    """

    __slots__ = ("name", "help", "label", "_cells")

    def __init__(self, name: str, label: str, help: str = ""):
        self.name = name
        self.help = help
        self.label = label
        self._cells: dict[str, Counter] = {}

    def cell(self, labelval: str) -> Counter:
        c = self._cells.get(labelval)
        if c is None:
            # benign race under the GIL: last assignment wins, both cells
            # start at 0 and only one remains reachable
            c = self._cells.setdefault(labelval, Counter(self.name, self.help))
        return c

    def inc(self, labelval: str, n: int = 1) -> None:
        self.cell(labelval).value += n

    def snapshot(self) -> dict:
        return {k: c.value for k, c in sorted(self._cells.items())}

    def reset(self) -> None:
        for c in self._cells.values():
            c.value = 0


class Histogram:
    """Fixed-bucket histogram (Prometheus ``le`` semantics: a value lands
    in the first bucket whose upper edge is >= the value; values above the
    last edge land in the implicit +Inf bucket)."""

    __slots__ = ("name", "help", "edges", "counts", "count", "sum", "min", "max")

    def __init__(self, name: str, buckets=DEFAULT_LATENCY_BUCKETS, help: str = ""):
        self.name = name
        self.help = help
        self.edges = tuple(sorted(buckets))
        self.counts = [0] * (len(self.edges) + 1)  # +1 = +Inf overflow
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")

    def observe(self, v: float) -> None:
        self.counts[bisect_left(self.edges, v)] += 1
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    def quantile(self, q: float) -> float:
        """Bucket-resolution quantile estimate: upper edge of the bucket
        holding the q-th observation.  Edge cases are explicit instead of
        interpolated: an empty histogram reports 0.0, and a quantile that
        lands in the +Inf overflow bucket reports inf — the edges carry
        no upper bound there, so the observed max would understate the
        tail the caller asked about."""
        if self.count == 0:
            return 0.0
        rank = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= rank and c:
                return self.edges[i] if i < len(self.edges) else float("inf")
        return float("inf") if self.counts[-1] else self.max

    def snapshot(self) -> dict:
        out = {
            "count": self.count,
            "sum": self.sum,
            "buckets": [[le, c] for le, c in zip(self.edges, self.counts)] + [["+Inf", self.counts[-1]]],
        }
        if self.count:
            out["min"] = self.min
            out["max"] = self.max
            out["p50"] = self.quantile(0.50)
            out["p90"] = self.quantile(0.90)
            out["p99"] = self.quantile(0.99)
        return out

    def reset(self) -> None:
        self.counts = [0] * (len(self.edges) + 1)
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")


class HistogramFamily:
    """Histogram with one label dimension (e.g. per pipeline stage)."""

    __slots__ = ("name", "help", "label", "buckets", "_cells")

    def __init__(self, name: str, label: str, buckets=DEFAULT_LATENCY_BUCKETS, help: str = ""):
        self.name = name
        self.help = help
        self.label = label
        self.buckets = tuple(sorted(buckets))
        self._cells: dict[str, Histogram] = {}

    def cell(self, labelval: str) -> Histogram:
        h = self._cells.get(labelval)
        if h is None:
            h = self._cells.setdefault(labelval, Histogram(self.name, self.buckets, self.help))
        return h

    def observe(self, labelval: str, v: float) -> None:
        self.cell(labelval).observe(v)

    def snapshot(self) -> dict:
        return {k: h.snapshot() for k, h in sorted(self._cells.items())}

    def reset(self) -> None:
        for h in self._cells.values():
            h.reset()


def _merge_numeric(dst: dict, src: dict) -> dict:
    """Recursively sum numeric leaves (multiple collectors, same name —
    e.g. several live ConsensusStorage instances in one process)."""
    for k, v in src.items():
        if isinstance(v, dict):
            dst[k] = _merge_numeric(dst.get(k, {}), v)
        elif isinstance(v, (int, float)) and isinstance(dst.get(k), (int, float)):
            dst[k] = dst[k] + v
        else:
            dst[k] = v
    return dst


def _derive_rates(d: dict) -> None:
    """Where a dict carries hits+misses, attach the derived hit_rate."""
    if "hits" in d and "misses" in d:
        total = d["hits"] + d["misses"]
        d["hit_rate"] = (d["hits"] / total) if total else 0.0
    for v in d.values():
        if isinstance(v, dict):
            _derive_rates(v)


class Registry:
    """Process-global metric registry.

    Metric creation is idempotent (get-or-create by name) so modules can
    declare their instruments at import time; ``snapshot()`` walks
    everything without taking the registration lock — mutation is
    GIL-atomic and a torn read across metrics is acceptable for telemetry.
    """

    def __init__(self):
        self._mu = ranked_lock("observability.registry")
        self._counters: dict[str, Counter | CounterFamily] = {}
        self._histograms: dict[str, Histogram | HistogramFamily] = {}
        # name -> list of weakref-able callables contributing gauge trees
        self._collectors: dict[str, list] = {}

    # -- registration (rare; locked) -----------------------------------

    def counter(self, name: str, help: str = "") -> Counter:
        with self._mu:
            m = self._counters.get(name)
            if m is None:
                m = self._counters[name] = Counter(name, help)
            assert isinstance(m, Counter), f"{name} already registered with labels"
            return m

    def counter_family(self, name: str, label: str, help: str = "") -> CounterFamily:
        with self._mu:
            m = self._counters.get(name)
            if m is None:
                m = self._counters[name] = CounterFamily(name, label, help)
            assert isinstance(m, CounterFamily), f"{name} already registered without labels"
            return m

    def histogram(self, name: str, buckets=DEFAULT_LATENCY_BUCKETS, help: str = "") -> Histogram:
        with self._mu:
            m = self._histograms.get(name)
            if m is None:
                m = self._histograms[name] = Histogram(name, buckets, help)
            assert isinstance(m, Histogram), f"{name} already registered with labels"
            return m

    def histogram_family(
        self, name: str, label: str, buckets=DEFAULT_LATENCY_BUCKETS, help: str = ""
    ) -> HistogramFamily:
        with self._mu:
            m = self._histograms.get(name)
            if m is None:
                m = self._histograms[name] = HistogramFamily(name, label, buckets, help)
            assert isinstance(m, HistogramFamily), f"{name} already registered without labels"
            return m

    def register_collector(self, name: str, fn) -> None:
        """Attach a ``() -> dict`` gauge source under ``name``.  Bound
        methods are held via WeakMethod so short-lived owners (per-test
        Consensus instances) never leak; plain functions are held strong.
        Multiple collectors under one name are merged by numeric sum."""
        import inspect

        ref = weakref.WeakMethod(fn) if inspect.ismethod(fn) else (lambda fn=fn: fn)
        with self._mu:
            self._collectors.setdefault(name, []).append(ref)

    def unregister_collector(self, name: str, fn) -> None:
        """Detach one collector from ``name`` (close() symmetry).  The
        weak refs already prune collected owners, but an owner that is
        closed yet not garbage-collected would keep contributing to the
        merged snapshot — torn-down subsystems unregister explicitly."""
        with self._mu:
            refs = self._collectors.get(name)
            if not refs:
                return
            kept = [r for r in refs if r() is not None and r() != fn]
            if kept:
                self._collectors[name] = kept
            else:
                self._collectors.pop(name, None)

    # -- snapshot (hot-ish; lock-free) ---------------------------------

    def snapshot(self) -> dict:
        counters = {name: m.snapshot() for name, m in sorted(self._counters.items())}
        histograms = {name: m.snapshot() for name, m in sorted(self._histograms.items())}
        out = {"counters": counters, "histograms": histograms}
        for name, refs in sorted(self._collectors.items()):
            merged: dict = {}
            live = []
            for ref in refs:
                fn = ref()
                if fn is None:
                    continue  # owner collected; prune below
                live.append(ref)
                try:
                    contribution = fn()
                except Exception:  # noqa: BLE001 - telemetry must not throw
                    continue
                if isinstance(contribution, dict):
                    _merge_numeric(merged, contribution)
            if len(live) != len(refs):
                with self._mu:
                    self._collectors[name] = live
            _derive_rates(merged)
            out[name] = merged
        return out

    def reset(self) -> None:
        """Zero all metric values in place (keeps the object identities
        hot-path modules captured at import).  Test helper."""
        for m in self._counters.values():
            m.reset()
        for m in self._histograms.values():
            m.reset()

    def scope(self, namespace: str) -> "ScopedRegistry":
        """A namespaced view: every instrument created through it gets a
        ``<namespace>_`` name prefix inside THIS registry.  This is how N
        node instances in one process (the swarm drill) keep per-node
        p2p counters without colliding on the shared metric names — each
        node reports into its own namespace, one snapshot shows them all."""
        return ScopedRegistry(self, namespace)


class ScopedRegistry:
    """Registry facade that prefixes metric names with a namespace.

    Same creation surface as :class:`Registry` (counter/counter_family/
    histogram/histogram_family), delegating storage to the parent so the
    parent's ``snapshot()``/``reset()`` cover scoped instruments too.
    """

    __slots__ = ("_parent", "namespace")

    def __init__(self, parent: Registry, namespace: str):
        self._parent = parent
        self.namespace = namespace

    def _name(self, name: str) -> str:
        return f"{self.namespace}_{name}"

    def counter(self, name: str, help: str = "") -> Counter:
        return self._parent.counter(self._name(name), help)

    def counter_family(self, name: str, label: str, help: str = "") -> CounterFamily:
        return self._parent.counter_family(self._name(name), label, help)

    def histogram(self, name: str, buckets=DEFAULT_LATENCY_BUCKETS, help: str = "") -> Histogram:
        return self._parent.histogram(self._name(name), buckets, help)

    def histogram_family(
        self, name: str, label: str, buckets=DEFAULT_LATENCY_BUCKETS, help: str = ""
    ) -> HistogramFamily:
        return self._parent.histogram_family(self._name(name), label, buckets, help)


REGISTRY = Registry()
