"""Per-block flight recorder: bounded ring of completed block traces.

Dapper-style assembly point for the cross-thread span ids trace.py now
stamps: ``begin(block_hash)`` opens a trace (trace_id = block hash hex)
and returns the root ``TraceContext`` the pipeline hands across every
queue boundary; every completed span whose ``trace`` matches an open (or
recently completed — serving fanout lands after virtual resolution)
trace is collected; ``end(block_hash)`` seals the trace, synthesizes the
root "block" span over the begin..end interval, runs the critical-path
analyzer and pushes the result into a bounded ring buffer.

The ring is dumpable on demand (``dump()``), on breaker-open
(``on_breaker_open`` — auto-dump when a dump dir is configured, wired
from resilience/breaker.py), or on daemon crash; ``tools/trace_report.py
--perfetto`` converts a dump into Chrome trace-event JSON loadable in
ui.perfetto.dev (``chrome_trace`` below is the converter).

Critical path: a backward "last-finisher" walk over each block's span
DAG — from the root's end, repeatedly step to the child span that
finished last, attributing the gap between that child's end and the
cursor to the parent's self-time, then recurse into the child.  Queue
waits are first-class spans (``wait.*``, recorded retroactively at
pickup), so handoff latency is attributed by name instead of vanishing
into parent self-time.  Per-stage critical-path milliseconds feed the
``block_critical_path_ms{stage=...}`` histogram family.

Cost discipline: when disabled (default) the only overhead is a None
check in trace._sink — nothing here runs.  When enabled, collection is
one lock + list append per span.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from time import perf_counter_ns

from kaspa_tpu.observability import trace
from kaspa_tpu.observability.core import MS_LATENCY_BUCKETS, REGISTRY
from kaspa_tpu.observability.trace import TraceContext

# critical-path attribution in MILLISECONDS per stage (the edges are the
# registry-wide ms ladder, so serving_lag_ms and block_critical_path_ms
# quantiles compare bucket-for-bucket)
MS_BUCKETS = MS_LATENCY_BUCKETS

CRIT_HIST = REGISTRY.histogram_family(
    "block_critical_path_ms", "stage", MS_BUCKETS,
    help="per-block critical-path self-time attributed to each stage/queue-wait span",
)
TRACES_DONE = REGISTRY.counter(
    "flight_traces_completed", help="block traces sealed into the flight ring"
)
SPANS_DROPPED = REGISTRY.counter(
    "flight_spans_dropped", help="spans whose trace was not open or already evicted"
)

# spans kept per trace before we start dropping (runaway guard)
_MAX_SPANS_PER_TRACE = 4096


def _hex(trace_id) -> str:
    return trace_id.hex() if isinstance(trace_id, (bytes, bytearray)) else str(trace_id)


def critical_path(spans: list[dict], root_id: int) -> dict:
    """Attribute the root span's wall time to per-stage self-time.

    Backward last-finisher walk: starting at the root's end, pick the
    child whose (clipped) end is latest; the gap between that end and the
    cursor is the parent's self-time; recurse into the child over its
    clipped interval; continue left of the child's start.  Concurrent
    siblings therefore contribute only along the single critical chain.

    Returns {"stages": {name: ns}, "total_ns", "attributed_ns",
    "fraction"} where fraction counts everything except the root's own
    self-time (the unexplained remainder).
    """
    by_id = {s["span"]: s for s in spans if s.get("span")}
    root = by_id.get(root_id)
    if root is None:
        return {"stages": {}, "total_ns": 0, "attributed_ns": 0, "fraction": 0.0}
    children: dict[int, list] = {}
    for s in spans:
        p = s.get("parent") or 0
        if p and s.get("span") != root_id and p in by_id:
            children.setdefault(p, []).append(s)
    stages: dict[str, int] = {}
    _walk(root, root["start_ns"], root["end_ns"], children, stages)
    total = max(root["end_ns"] - root["start_ns"], 0)
    unattr = stages.get(root["name"], 0)
    attributed = max(total - unattr, 0)
    return {
        "stages": stages,
        "total_ns": total,
        "attributed_ns": attributed,
        "fraction": (attributed / total) if total else 0.0,
    }


def _walk(span: dict, lo: int, hi: int, children: dict, out: dict) -> None:
    cursor = hi
    kids = list(children.get(span["span"], ()))
    name = span["name"]
    while cursor > lo:
        best, best_end = None, lo
        for k in kids:
            ks = max(k["start_ns"], lo)
            ke = min(k["end_ns"], cursor)
            if ks < ke and ke > best_end:
                best, best_end = k, ke
        if best is None:
            out[name] = out.get(name, 0) + (cursor - lo)
            return
        if best_end < cursor:
            out[name] = out.get(name, 0) + (cursor - best_end)
        ks = max(best["start_ns"], lo)
        _walk(best, ks, best_end, children, out)
        cursor = ks
        kids.remove(best)


def chrome_trace(traces: list[dict]) -> dict:
    """Convert flight entries to Chrome trace-event JSON (Perfetto).

    One trace-event "process" per block (process_name metadata = the
    block label), one "thread" row per OS thread that touched the block,
    ph:"X" complete events per span, ph:"s"/"f" flow arrows for every
    cross-thread parent->child edge.
    """
    events: list[dict] = []
    flow_id = 0
    for pid, t in enumerate(traces, start=1):
        spans = t.get("spans", [])
        label = t.get("label") or t.get("trace", "?")[:8]
        events.append(
            {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
             "args": {"name": f"block {label}"}}
        )
        tids: dict[str, int] = {}
        for s in spans:
            th = s.get("thread", "?")
            if th not in tids:
                tids[th] = len(tids) + 1
                events.append(
                    {"ph": "M", "name": "thread_name", "pid": pid, "tid": tids[th],
                     "args": {"name": th}}
                )
        by_id = {s["span"]: s for s in spans if s.get("span")}
        for s in spans:
            args = dict(s.get("attrs") or {})
            args.update({"span": s.get("span"), "parent": s.get("parent"), "path": s.get("path")})
            events.append(
                {
                    "ph": "X",
                    "name": s["name"],
                    "cat": "block",
                    "pid": pid,
                    "tid": tids.get(s.get("thread", "?"), 0),
                    "ts": s["start_us"],
                    "dur": max(s.get("dur_us", 0.0), 0.001),
                    "args": args,
                }
            )
            parent = by_id.get(s.get("parent") or 0)
            if parent is not None and parent.get("thread") != s.get("thread"):
                flow_id += 1
                events.append(
                    {"ph": "s", "id": flow_id, "name": "handoff", "cat": "flow",
                     "pid": pid, "tid": tids.get(parent.get("thread", "?"), 0),
                     "ts": parent["start_us"]}
                )
                events.append(
                    {"ph": "f", "bp": "e", "id": flow_id, "name": "handoff", "cat": "flow",
                     "pid": pid, "tid": tids.get(s.get("thread", "?"), 0),
                     "ts": max(s["start_us"], parent["start_us"])}
                )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


class FlightRecorder:
    """Process-global recorder; use via the module-level singleton."""

    def __init__(self):
        self._mu = threading.Lock()  # graftlint: allow(raw-lock) -- flight-recorder ring leaf, taken inside every span under arbitrary ranks
        self._open: dict[str, dict] = {}
        self._done: dict[str, dict] = {}  # ring members, addressable for late spans
        self._ring: deque = deque()  # graftlint: allow(unbounded-queue) -- trimmed to _ring_max on every seal
        self._ring_max = 256
        self._enabled = False
        self.dump_dir: str | None = None

    # -- lifecycle ------------------------------------------------------

    def enable(self, ring: int = 256, dump_dir: str | None = None) -> None:
        with self._mu:
            self._ring_max = max(int(ring), 1)
            if dump_dir is not None:
                self.dump_dir = dump_dir
            self._enabled = True
        trace._flight_sink = self.record

    def disable(self) -> None:
        trace._flight_sink = None
        with self._mu:
            self._enabled = False

    def enabled(self) -> bool:
        return self._enabled

    def reset(self) -> None:
        with self._mu:
            self._open.clear()
            self._done.clear()
            self._ring.clear()

    # -- trace lifecycle ------------------------------------------------

    def begin(self, trace_id, label: str | None = None) -> TraceContext | None:
        """Open a block trace; idempotent — a duplicate begin returns the
        existing root context so unorphan/retry paths don't fork trees."""
        if not self._enabled:
            return None
        tid = _hex(trace_id)
        with self._mu:
            t = self._open.get(tid)
            if t is not None:
                return TraceContext(tid, t["root"], t["label"])
            lbl = label or ("block:" + tid[:8])
            t = {
                "trace": tid,
                "label": lbl,
                "root": trace._next_id(),
                "t0_ns": perf_counter_ns(),
                "wall_start": time.time(),
                "spans": [],
                "status": "open",
            }
            self._open[tid] = t
            return TraceContext(tid, t["root"], lbl)

    def record(self, rec: dict) -> None:
        """trace._flight_sink: collect a completed span into its trace."""
        tid = rec.get("trace")
        if tid is None:
            return
        with self._mu:
            t = self._open.get(tid) or self._done.get(tid)
            if t is None:
                SPANS_DROPPED.inc()
                return
            if len(t["spans"]) >= _MAX_SPANS_PER_TRACE:
                SPANS_DROPPED.inc()
                return
            t["spans"].append(rec)

    def end(self, trace_id, status: str = "ok") -> dict | None:
        """Seal a trace: synthesize the root span, attribute the critical
        path, and push to the ring.  Late spans (serving fanout) may keep
        arriving until ring eviction; they join the tree but not the
        already-computed attribution (they fall outside the root
        interval by construction)."""
        t1 = perf_counter_ns()
        tid = _hex(trace_id)
        with self._mu:
            t = self._open.pop(tid, None)
        if t is None:
            return None
        t["status"] = status
        t["end_ns"] = t1
        t["duration_ms"] = (t1 - t["t0_ns"]) / 1e6
        t["spans"].append(
            {
                "name": "block",
                "path": t["label"],
                "trace": tid,
                "span": t["root"],
                "parent": 0,
                "start_us": t["t0_ns"] // 1000,
                "dur_us": (t1 - t["t0_ns"]) / 1000.0,
                "start_ns": t["t0_ns"],
                "end_ns": t1,
                "thread": "block",
                "depth": 0,
                "attrs": {"status": status},
            }
        )
        cp = critical_path(t["spans"], t["root"])
        t["critical_path"] = {
            "fraction": round(cp["fraction"], 4),
            "total_ms": cp["total_ns"] / 1e6,
            "stages_ms": {
                k: v / 1e6 for k, v in sorted(cp["stages"].items(), key=lambda kv: -kv[1])
            },
        }
        for stage, ns in cp["stages"].items():
            if stage != "block":
                CRIT_HIST.observe(stage, ns / 1e6)
        TRACES_DONE.inc()
        with self._mu:
            self._ring.append(t)
            self._done[tid] = t
            while len(self._ring) > self._ring_max:
                old = self._ring.popleft()
                self._done.pop(old["trace"], None)
        return t

    # -- export ---------------------------------------------------------

    def traces(self, limit: int = 0) -> list[dict]:
        """Completed traces, oldest first (copies of the entry dicts)."""
        with self._mu:
            out = list(self._ring)
        if limit and len(out) > limit:
            out = out[-limit:]
        return [dict(t) for t in out]

    def summaries(self, limit: int = 32) -> list[dict]:
        """Small JSON-safe summaries for the getTraces RPC surface."""
        out = []
        for t in self.traces(limit):
            out.append(
                {
                    "trace": t["trace"],
                    "label": t["label"],
                    "status": t["status"],
                    "duration_ms": round(t.get("duration_ms", 0.0), 3),
                    "spans": len(t["spans"]),
                    "threads": len({s.get("thread") for s in t["spans"]}),
                    "critical_path": t.get("critical_path"),
                }
            )
        return out

    def dump(self, path: str | None = None, reason: str = "on-demand") -> str:
        """Write the ring as a flight dump (trace_report.py input)."""
        if path is None:
            base = self.dump_dir or "."
            path = os.path.join(base, f"flight_{os.getpid()}_{int(time.time())}.json")
        doc = {
            "format": "kaspa-flight",
            "version": 1,
            "created": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "reason": reason,
            "traces": self.traces(),
        }
        with open(path, "w") as f:
            json.dump(doc, f)
            f.write("\n")
        return path

    def on_breaker_open(self, breaker_name: str) -> str | None:
        """Crash-style dump hook (resilience/breaker.py calls on the
        CLOSED/HALF_OPEN -> OPEN transition).  Only writes when a dump
        dir was configured — tests trip breakers constantly."""
        if not self._enabled or self.dump_dir is None:
            return None
        try:
            return self.dump(reason=f"breaker-open:{breaker_name}")
        except OSError:
            return None

    def _state(self) -> dict:
        with self._mu:
            return {
                "enabled": int(self._enabled),
                "open_traces": len(self._open),
                "completed_ring": len(self._ring),
            }


RECORDER = FlightRecorder()
REGISTRY.register_collector("flight", RECORDER._state)

# module-level convenience (what instrumentation call sites import)
begin = RECORDER.begin
end = RECORDER.end
enable = RECORDER.enable
disable = RECORDER.disable
enabled = RECORDER.enabled
dump = RECORDER.dump
traces = RECORDER.traces
summaries = RECORDER.summaries
reset = RECORDER.reset
on_breaker_open = RECORDER.on_breaker_open
