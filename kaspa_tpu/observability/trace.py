"""Low-overhead span tracer: ``trace.span("stage", **attrs)``.

Spans time a code region on the monotonic clock (perf_counter_ns), nest
through a thread-local stack, and report their duration into a per-stage
latency histogram in the global registry.  Optionally a bounded in-memory
span log captures every completed span (name, path, start, duration,
thread, attrs) for offline replay by ``tools/trace_report.py``.

Cost model (the contract tests/test_observability.py asserts loosely):
- tracing disabled: ``span()`` returns a shared no-op object — well under
  a microsecond per use;
- tracing enabled: one small-object allocation, two clock reads, one
  histogram observe and a stack push/pop — single-digit microseconds.

Exception safety: ``__exit__`` always pops the stack and always records
the span (tagging ``error`` with the exception type); the exception
propagates unchanged.
"""

from __future__ import annotations

import threading
from collections import deque
from time import perf_counter_ns

from kaspa_tpu.observability.core import DEFAULT_LATENCY_BUCKETS, REGISTRY

# per-stage latency: the "per-stage latency histograms" surface of
# RpcCoreService.get_metrics()["observability"]["histograms"]
SPAN_HIST = REGISTRY.histogram_family(
    "span_duration_seconds", "stage", DEFAULT_LATENCY_BUCKETS,
    help="wall time of traced spans by stage name",
)

_tls = threading.local()
_enabled = True
_capture: deque | None = None  # bounded span log for trace_report replay


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


class _NoopSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class Span:
    __slots__ = ("name", "attrs", "path", "_t0")

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self.attrs = attrs
        self.path = name
        self._t0 = 0

    def __enter__(self):
        st = _stack()
        if st:
            self.path = st[-1].path + "/" + self.name
        st.append(self)
        self._t0 = perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur_ns = perf_counter_ns() - self._t0
        st = _stack()
        if st and st[-1] is self:
            st.pop()
        SPAN_HIST.observe(self.name, dur_ns * 1e-9)
        cap = _capture
        if cap is not None:
            if exc_type is not None:
                self.attrs["error"] = exc_type.__name__
            cap.append(
                {
                    "name": self.name,
                    "path": self.path,
                    "start_us": self._t0 // 1000,
                    "dur_us": dur_ns / 1000.0,
                    "thread": threading.current_thread().name,
                    "depth": len(st),
                    "attrs": self.attrs,
                }
            )
        return False  # never swallow the exception


def span(name: str, **attrs) -> Span | _NoopSpan:
    """Open a timed span; use as ``with trace.span("stage", key=val):``."""
    if not _enabled:
        return _NOOP
    return Span(name, attrs)


def enabled() -> bool:
    return _enabled


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def current_path() -> str:
    """Slash-joined path of the active span stack on this thread."""
    st = getattr(_tls, "stack", None)
    return st[-1].path if st else ""


def set_capture(maxlen: int = 65536) -> None:
    """Turn the bounded span log on (maxlen > 0) or off (maxlen == 0)."""
    global _capture
    _capture = deque(maxlen=maxlen) if maxlen > 0 else None


def drain() -> list[dict]:
    """Return and clear the captured span log (oldest first)."""
    cap = _capture
    if cap is None:
        return []
    out = []
    while cap:
        try:
            out.append(cap.popleft())
        except IndexError:  # racing producer threads; good enough
            break
    return out


def dump(path: str) -> int:
    """Write the captured span log as JSONL for tools/trace_report.py."""
    import json

    spans = drain()
    with open(path, "w") as f:
        for s in spans:
            f.write(json.dumps(s) + "\n")
    return len(spans)
