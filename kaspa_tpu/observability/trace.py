"""Low-overhead span tracer: ``trace.span("stage", **attrs)``.

Spans time a code region on the monotonic clock (perf_counter_ns), nest
through a thread-local stack, and report their duration into a per-stage
latency histogram in the global registry.  Optionally a bounded in-memory
span log captures every completed span (name, path, start, duration,
thread, attrs) for offline replay by ``tools/trace_report.py``.

Cross-thread causality (flight recorder substrate): every recorded span
carries Dapper-style identity — ``trace`` (the block hash that owns it),
``span`` (a process-unique id), ``parent`` (the enclosing span's id).
Within a thread the ids flow through the TLS stack as before; across a
queue boundary the producer captures ``trace.context()`` (a small
immutable ``TraceContext``) and the consumer reopens the tree with
``trace.span("stage", parent=ctx)`` or records an already-elapsed
interval with ``trace.record_span(...)`` (queue waits, fan-back device
spans).  ``kaspa_tpu.observability.flight`` installs ``_flight_sink`` to
collect per-trace span sets into the ring buffer.

Cost model (the contract tests/test_observability.py asserts loosely):
- tracing disabled: ``span()`` returns a shared no-op object — well under
  a microsecond per use;
- tracing enabled: one small-object allocation, two clock reads, one
  histogram observe and a stack push/pop — single-digit microseconds.

Exception safety: ``__exit__`` always pops the stack and always records
the span (tagging ``error`` with the exception type); the exception
propagates unchanged.
"""

from __future__ import annotations

import itertools
import threading
from collections import deque
from time import perf_counter_ns

from kaspa_tpu.observability.core import DEFAULT_LATENCY_BUCKETS, REGISTRY

# per-stage latency: the "per-stage latency histograms" surface of
# RpcCoreService.get_metrics()["observability"]["histograms"]
SPAN_HIST = REGISTRY.histogram_family(
    "span_duration_seconds", "stage", DEFAULT_LATENCY_BUCKETS,
    help="wall time of traced spans by stage name",
)

_tls = threading.local()
_enabled = True
_capture: deque | None = None  # bounded span log for trace_report replay
_flight_sink = None  # set by observability.flight when the recorder is on
_next_id = itertools.count(1).__next__  # process-unique span ids


class TraceContext:
    """Immutable handle passed across thread/queue boundaries.

    ``trace_id`` is the owning block hash (hex), ``span_id`` the producer
    span to parent on, ``path`` the slash-joined ancestry so flame paths
    stay connected in trace_report across threads.
    """

    __slots__ = ("trace_id", "span_id", "path")

    def __init__(self, trace_id: str | None, span_id: int, path: str):
        self.trace_id = trace_id
        self.span_id = span_id
        self.path = path

    def __repr__(self):  # debugging aid only
        return f"TraceContext({self.trace_id!r}, {self.span_id}, {self.path!r})"


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


class _NoopSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class Span:
    __slots__ = ("name", "attrs", "path", "_t0", "trace_id", "span_id", "parent_id", "_parent")

    def __init__(self, name: str, attrs: dict, parent: TraceContext | None = None):
        self.name = name
        self.attrs = attrs
        self.path = name
        self._t0 = 0
        self.trace_id = None
        self.span_id = 0
        self.parent_id = 0
        self._parent = parent

    def __enter__(self):
        st = _stack()
        if st:
            top = st[-1]
            self.path = top.path + "/" + self.name
            self.trace_id = top.trace_id
            self.parent_id = top.span_id
        elif self._parent is not None:
            p = self._parent
            self.path = p.path + "/" + self.name
            self.trace_id = p.trace_id
            self.parent_id = p.span_id
        self.span_id = _next_id()
        st.append(self)
        self._t0 = perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = perf_counter_ns()
        st = _stack()
        if st and st[-1] is self:
            st.pop()
        SPAN_HIST.observe(self.name, (t1 - self._t0) * 1e-9)
        if _capture is not None or _flight_sink is not None:
            if exc_type is not None:
                self.attrs["error"] = exc_type.__name__
            _sink(
                {
                    "name": self.name,
                    "path": self.path,
                    "trace": self.trace_id,
                    "span": self.span_id,
                    "parent": self.parent_id,
                    "start_us": self._t0 // 1000,
                    "dur_us": (t1 - self._t0) / 1000.0,
                    "start_ns": self._t0,
                    "end_ns": t1,
                    "thread": threading.current_thread().name,
                    "depth": len(st),
                    "attrs": self.attrs,
                }
            )
        return False  # never swallow the exception

    def context(self) -> TraceContext:
        """Handle for parenting work handed to another thread/queue."""
        return TraceContext(self.trace_id, self.span_id, self.path)


def _sink(rec: dict) -> None:
    cap = _capture
    if cap is not None:
        cap.append(rec)
    fs = _flight_sink
    if fs is not None:
        fs(rec)


def span(name: str, parent: TraceContext | None = None, **attrs) -> Span | _NoopSpan:
    """Open a timed span; use as ``with trace.span("stage", key=val):``.

    ``parent`` (a TraceContext) grafts this span onto a tree started on
    another thread; it only applies when this thread's span stack is
    empty — an enclosing local span always wins.
    """
    if not _enabled:
        return _NOOP
    return Span(name, attrs, parent)


def record_span(
    name: str,
    parent: TraceContext | None,
    t0_ns: int,
    t1_ns: int,
    **attrs,
) -> TraceContext | None:
    """Record an already-elapsed interval (queue wait, fan-back device
    span) retroactively: the producer stamped ``t0_ns`` (perf_counter_ns)
    when it enqueued, the consumer calls this at pickup.  Returns the new
    span's context so callers can parent further children on it."""
    if not _enabled:
        return None
    if t1_ns < t0_ns:
        t1_ns = t0_ns
    SPAN_HIST.observe(name, (t1_ns - t0_ns) * 1e-9)
    if _capture is None and _flight_sink is None:
        return None
    sid = _next_id()
    trace_id = parent.trace_id if parent is not None else None
    parent_id = parent.span_id if parent is not None else 0
    path = (parent.path + "/" + name) if parent is not None else name
    _sink(
        {
            "name": name,
            "path": path,
            "trace": trace_id,
            "span": sid,
            "parent": parent_id,
            "start_us": t0_ns // 1000,
            "dur_us": (t1_ns - t0_ns) / 1000.0,
            "start_ns": t0_ns,
            "end_ns": t1_ns,
            "thread": threading.current_thread().name,
            "depth": 0,
            "attrs": attrs,
        }
    )
    return TraceContext(trace_id, sid, path)


def context() -> TraceContext | None:
    """TraceContext of this thread's innermost open span (None outside)."""
    st = getattr(_tls, "stack", None)
    return st[-1].context() if st else None


def enabled() -> bool:
    return _enabled


def sinks_active() -> bool:
    """True when completed spans actually land somewhere (capture log or
    flight ring).  Ultra-hot paths use this to skip building retroactive
    spans nobody would collect."""
    return _capture is not None or _flight_sink is not None


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def current_path() -> str:
    """Slash-joined path of the active span stack on this thread."""
    st = getattr(_tls, "stack", None)
    return st[-1].path if st else ""


def set_capture(maxlen: int = 65536) -> None:
    """Turn the bounded span log on (maxlen > 0) or off (maxlen == 0)."""
    global _capture
    _capture = deque(maxlen=maxlen) if maxlen > 0 else None


def drain() -> list[dict]:
    """Return and clear the captured span log (oldest first)."""
    cap = _capture
    if cap is None:
        return []
    out = []
    while cap:
        try:
            out.append(cap.popleft())
        except IndexError:  # racing producer threads; good enough
            break
    return out


def dump(path: str) -> int:
    """Write the captured span log as JSONL for tools/trace_report.py."""
    import json

    spans = drain()
    with open(path, "w") as f:
        for s in spans:
            f.write(json.dumps(s) + "\n")
    return len(spans)
