"""Prometheus text exposition (format 0.0.4) over the global registry.

Renders the SAME registry ``RpcCoreService.get_metrics`` snapshots:
counters as ``<name>_total``, histograms with cumulative ``le`` buckets +
``_sum``/``_count``, and collector gauge trees flattened to
``kaspa_<collector>_<path>`` (one-level dicts become a ``key`` label).
The daemon re-renders on its metrics tick (node/daemon.py) and serves the
text via the ``getMetricsPrometheus`` RPC.
"""

from __future__ import annotations

import re

from kaspa_tpu.observability.core import (
    Counter,
    CounterFamily,
    Histogram,
    HistogramFamily,
    REGISTRY,
)

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
PREFIX = "kaspa_"


def _name(raw: str) -> str:
    n = _NAME_RE.sub("_", raw)
    if not n.startswith(PREFIX):
        n = PREFIX + n
    return n


def _esc(labelval: str) -> str:
    return str(labelval).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _esc_help(text: str) -> str:
    # exposition format 0.0.4: HELP text escapes backslash and newline
    # (quotes are NOT escaped in help text, unlike label values)
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _family_header(lines: list[str], seen: set, name: str, help_text: str, mtype: str) -> None:
    """Emit # HELP / # TYPE exactly once per family.  Distinct raw names
    can sanitize to the same exposition name (``_name`` folds illegal
    chars to ``_``); the first registrant wins the header and later ones
    only contribute samples — duplicate HELP/TYPE lines are a parse
    error for real Prometheus servers."""
    if name in seen:
        return
    seen.add(name)
    if help_text:
        lines.append(f"# HELP {name} {_esc_help(help_text)}")
    lines.append(f"# TYPE {name} {mtype}")


def _fmt(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, float):
        # non-finite gauges (a quantile in the +Inf overflow bucket, an
        # empty histogram's min) must use the exposition spellings —
        # repr() would emit 'inf'/'nan' which Go's ParseFloat accepts but
        # the text-format spec does not promise
        if v != v:
            return "NaN"
        if v == float("inf"):
            return "+Inf"
        if v == float("-inf"):
            return "-Inf"
        return repr(v)
    return str(v)


def _render_histogram(lines: list[str], name: str, label: str | None, cells) -> None:
    for labelval, hist in cells:
        base = f'{name}_bucket{{{label}="{_esc(labelval)}",le=' if label is not None else f"{name}_bucket{{le="
        cum = 0
        for le, c in zip(hist.edges, hist.counts):
            cum += c
            lines.append(f'{base}"{_fmt(float(le))}"}} {cum}')
        cum += hist.counts[-1]
        lines.append(f'{base}"+Inf"}} {cum}')
        suffix = f'{{{label}="{_esc(labelval)}"}}' if label is not None else ""
        lines.append(f"{name}_sum{suffix} {_fmt(hist.sum)}")
        lines.append(f"{name}_count{suffix} {hist.count}")


def _flatten_gauges(lines: list[str], name: str, tree: dict) -> None:
    # {store: {stat: num}} is the common collector shape: emit
    # kaspa_<name>_<stat>{key="store"}; anything deeper flattens by path.
    for key in sorted(tree):
        val = tree[key]
        if isinstance(val, dict):
            if all(isinstance(v, (int, float)) for v in val.values()):
                for stat in sorted(val):
                    lines.append(f'{name}_{_NAME_RE.sub("_", stat)}{{key="{_esc(key)}"}} {_fmt(val[stat])}')
            else:
                _flatten_gauges(lines, f'{name}_{_NAME_RE.sub("_", key)}', val)
        elif isinstance(val, (int, float)):
            lines.append(f'{name}_{_NAME_RE.sub("_", key)} {_fmt(val)}')


def render(registry=REGISTRY) -> str:
    """The full registry as Prometheus exposition text."""
    lines: list[str] = []
    seen: set[str] = set()
    for raw, m in sorted(registry._counters.items()):
        name = _name(raw)
        _family_header(lines, seen, name, m.help, "counter")
        if isinstance(m, CounterFamily):
            for labelval, cell in sorted(m._cells.items()):
                lines.append(f'{name}_total{{{m.label}="{_esc(labelval)}"}} {cell.value}')
        else:
            lines.append(f"{name}_total {m.value}")
    for raw, m in sorted(registry._histograms.items()):
        name = _name(raw)
        _family_header(lines, seen, name, m.help, "histogram")
        if isinstance(m, HistogramFamily):
            _render_histogram(lines, name, m.label, sorted(m._cells.items()))
        else:
            _render_histogram(lines, name, None, [(None, m)])
    snap = registry.snapshot()
    for cname in sorted(snap):
        if cname in ("counters", "histograms"):
            continue
        tree = snap[cname]
        if isinstance(tree, dict) and tree:
            # untyped samples: the flattened names vary per leaf, so a
            # single TYPE line cannot legally cover the family
            _flatten_gauges(lines, _name(cname), tree)
    return "\n".join(lines) + "\n"
