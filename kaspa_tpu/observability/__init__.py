"""Observability layer: span tracing, hot-path histograms, counters.

Usage::

    from kaspa_tpu.observability import trace
    with trace.span("pipeline.stage", block=h.hex()[:8]):
        ...

    from kaspa_tpu.observability.core import REGISTRY
    REGISTRY.counter("my_counter").inc()

``snapshot()`` returns the full registry as deterministic plain dicts
(what ``RpcCoreService.get_metrics`` embeds under ``observability``);
``kaspa_tpu.observability.prom.render()`` emits the same registry as
Prometheus exposition text.
"""

from kaspa_tpu.observability import trace  # noqa: F401
from kaspa_tpu.observability import flight  # noqa: F401
from kaspa_tpu.observability.core import (  # noqa: F401
    DEFAULT_LATENCY_BUCKETS,
    MS_LATENCY_BUCKETS,
    PERCENT_BUCKETS,
    REGISTRY,
    SIZE_BUCKETS,
    Counter,
    CounterFamily,
    Histogram,
    HistogramFamily,
    Registry,
)


def snapshot() -> dict:
    """Global registry snapshot (counters, histograms, collector gauges)."""
    return REGISTRY.snapshot()
