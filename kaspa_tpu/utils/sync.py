"""Concurrency primitives: channels, reader-writer lock, lock-order debug.

The runtime counterpart of the reference's kaspa-utils sync layer
(utils/src/channel.rs, utils/src/sync/rwlock.rs, utils/src/sync/
semaphore.rs).  Python-runtime notes baked into the design:

- Channels are closeable MPMC queues (async_channel semantics): `send`
  after close raises, receivers drain remaining items then see `Closed`.
- LockCtx is the race/deadlock *detection* strategy (SURVEY §5): with
  KASPA_TPU_LOCK_DEBUG=1 every guarded acquisition records a per-thread
  held-set and asserts a global partial order over lock ranks — a cycle
  (deadlock candidate) fails loudly in tests instead of hanging a node.
"""

from __future__ import annotations

import collections
import os
import threading


class Closed(Exception):
    """Channel closed and drained."""


class Channel:
    """Closeable MPMC FIFO channel (utils/src/channel.rs semantics)."""

    def __init__(self, maxsize: int = 0):
        self._q: collections.deque = collections.deque()
        self._maxsize = maxsize
        self._mu = threading.Lock()
        self._not_empty = threading.Condition(self._mu)
        self._not_full = threading.Condition(self._mu)
        self._closed = False

    def send(self, item) -> None:
        with self._mu:
            if self._closed:
                raise Closed("send on closed channel")
            while self._maxsize and len(self._q) >= self._maxsize:
                self._not_full.wait()
                if self._closed:
                    raise Closed("send on closed channel")
            self._q.append(item)
            self._not_empty.notify()

    def recv(self, timeout: float | None = None):
        with self._mu:
            while not self._q:
                if self._closed:
                    raise Closed
                if not self._not_empty.wait(timeout):
                    raise TimeoutError
            item = self._q.popleft()
            self._not_full.notify()
            return item

    def drain(self) -> list:
        """Atomically take everything currently queued."""
        with self._mu:
            items = list(self._q)
            self._q.clear()
            self._not_full.notify_all()
            return items

    def close(self) -> None:
        with self._mu:
            self._closed = True
            self._not_empty.notify_all()
            self._not_full.notify_all()

    def __iter__(self):
        while True:
            try:
                yield self.recv()
            except Closed:
                return

    def __len__(self) -> int:
        with self._mu:
            return len(self._q)


# ----------------------------------------------------------------------
# lock-order debugging (deadlock detection strategy)
# ----------------------------------------------------------------------

_LOCK_DEBUG = bool(os.environ.get("KASPA_TPU_LOCK_DEBUG"))
_held = threading.local()


class LockCtx:
    """Ranked lock wrapper: acquiring a lock with rank <= any currently
    held rank (on the same thread) is an ordering violation — the static
    discipline that makes the pipeline deadlock-free.  Zero overhead
    unless KASPA_TPU_LOCK_DEBUG is set."""

    def __init__(self, name: str, rank: int, lock=None):
        self.name = name
        self.rank = rank
        self._lock = lock if lock is not None else threading.RLock()

    def __enter__(self):
        if _LOCK_DEBUG:
            stack = getattr(_held, "stack", None)
            if stack is None:
                stack = _held.stack = []
            if stack and stack[-1][1] >= self.rank and stack[-1][0] is not self:
                raise AssertionError(
                    f"lock-order violation: acquiring {self.name}(rank {self.rank}) "
                    f"while holding {stack[-1][2]}(rank {stack[-1][1]})"
                )
            stack.append((self, self.rank, self.name))
        self._lock.acquire()
        return self

    def __exit__(self, *exc):
        self._lock.release()
        if _LOCK_DEBUG:
            _held.stack.pop()
        return False
