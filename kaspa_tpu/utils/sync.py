"""Concurrency primitives: channels, reader-writer lock, lock-order debug.

The runtime counterpart of the reference's kaspa-utils sync layer
(utils/src/channel.rs, utils/src/sync/rwlock.rs, utils/src/sync/
semaphore.rs).  Python-runtime notes baked into the design:

- Channels are closeable MPMC queues (async_channel semantics): `send`
  after close raises, receivers drain remaining items then see `Closed`.
- LockCtx is the race/deadlock *detection* strategy (SURVEY §5): with
  KASPA_TPU_LOCK_DEBUG=1 every guarded acquisition records a per-thread
  held-set and asserts a global partial order over lock ranks — a cycle
  (deadlock candidate) fails loudly in tests instead of hanging a node.
"""

from __future__ import annotations

import collections
import os
import threading
import time


class Closed(Exception):
    """Channel closed and drained."""


class Channel:
    """Closeable MPMC FIFO channel (utils/src/channel.rs semantics)."""

    def __init__(self, maxsize: int = 0):
        self._q: collections.deque = collections.deque()
        self._maxsize = maxsize
        self._mu = threading.Lock()
        self._not_empty = threading.Condition(self._mu)
        self._not_full = threading.Condition(self._mu)
        self._closed = False

    def send(self, item) -> None:
        with self._mu:
            if self._closed:
                raise Closed("send on closed channel")
            while self._maxsize and len(self._q) >= self._maxsize:
                self._not_full.wait()
                if self._closed:
                    raise Closed("send on closed channel")
            self._q.append(item)
            self._not_empty.notify()

    def recv(self, timeout: float | None = None):
        with self._mu:
            while not self._q:
                if self._closed:
                    raise Closed
                if not self._not_empty.wait(timeout):
                    raise TimeoutError
            item = self._q.popleft()
            self._not_full.notify()
            return item

    def drain(self, max_items: int | None = None) -> list:
        """Atomically take everything currently queued (up to ``max_items``)."""
        with self._mu:
            if max_items is None or max_items >= len(self._q):
                items = list(self._q)
                self._q.clear()
            elif max_items <= 0:
                return []
            else:
                items = [self._q.popleft() for _ in range(max_items)]
            self._not_full.notify_all()
            return items

    def close(self) -> None:
        with self._mu:
            self._closed = True
            self._not_empty.notify_all()
            self._not_full.notify_all()

    def __iter__(self):
        while True:
            try:
                yield self.recv()
            except Closed:
                return

    def __len__(self) -> int:
        with self._mu:
            return len(self._q)


# ----------------------------------------------------------------------
# lock-order debugging (deadlock detection strategy)
# ----------------------------------------------------------------------

_LOCK_DEBUG = bool(os.environ.get("KASPA_TPU_LOCK_DEBUG"))
_held = threading.local()
# per-lock contention/hold aggregates under debug: the runtime analog of
# the reference's semaphore trace feature (utils/src/sync/semaphore.rs
# trace-enabled acquisition accounting)
_trace_mu = threading.Lock()
_trace: dict[str, list] = {}  # name -> [acquisitions, total_hold_s, max_hold_s]


def set_lock_debug(on: bool) -> None:
    """Toggle lock-order checking + hold tracing (tests; env is read once)."""
    global _LOCK_DEBUG
    _LOCK_DEBUG = bool(on)


def lock_trace_snapshot() -> dict:
    """{lock name: {acquisitions, total_hold_s, max_hold_s}} accumulated
    while debug is on — contention hunting without a profiler attached."""
    with _trace_mu:
        return {
            name: {"acquisitions": c, "total_hold_s": round(t, 6), "max_hold_s": round(m, 6)}
            for name, (c, t, m) in _trace.items()
        }


def _trace_record(name: str, held_s: float) -> None:
    with _trace_mu:
        entry = _trace.setdefault(name, [0, 0.0, 0.0])
        entry[0] += 1
        entry[1] += held_s
        entry[2] = max(entry[2], held_s)


# The global lock rank table: locks may only be acquired in strictly
# ascending rank order on one thread (same-instance re-entry excepted).
# Every production LockCtx takes its rank from here via ranked_lock() so
# the whole-node partial order is reviewable in one place.  Rationale for
# the ordering (outer → inner as ranks ascend):
#
#   node / ingest.state sit at the outside: RPC and P2P entry points take
#   them first, then descend into consensus commit, then into the leaf
#   queues/stats.  Wire/service/stats locks are leaves — nothing else is
#   acquired while they are held — so they rank highest.  daemon.upnp is
#   a pure leaf around a blocking-free socket probe.
RANKS: dict[str, int] = {
    "service.shutdown": 3,     # core/service.py — held across service.stop() fan-out
    "node": 5,                 # p2p/node.py — outermost node state
    "ingest.state": 7,         # ingest/tier.py — mempool admission state
    "overload.state": 8,       # resilience/overload.py — controller level state
    "consensus-commit": 10,    # pipeline/pipeline.py — UTXO commit section
    "pipeline.deps": 20,       # pipeline/deps_manager.py — orphan/deps graph
    "fabric.config": 25,       # fabric/balancer.py — process-wide balancer slot
    "fabric.balancer": 30,     # fabric/balancer.py — slice table + breaker state
    "dispatch.config": 35,     # ops/dispatch.py — process-wide dispatcher slot
    "mesh.config": 38,         # ops/mesh.py — mesh/topology (re)configuration
    "dispatch.queue": 40,      # ops/dispatch.py — verify coalescing queue
    "ingest.queue": 45,        # ingest/queue.py — tx admission queue
    "serving.shards": 49,      # serving/shards.py — sharded-fanout facade (event refs)
    "serving.broadcaster": 50, # serving/broadcaster.py — subscriber table
    "serving.shard": 51,       # serving/shards.py — per-shard scope index + membership
    # (serving/pool.py's ready queue is a stdlib Queue — its internal lock
    # is a leaf taken between broadcaster(50)/shard(51) and subscriber(55)
    # acquisitions, never while either ranked lock is held)
    "serving.subscriber": 55,  # serving/broadcaster.py — per-subscriber buffer
    "pipeline.idle": 60,       # pipeline/pipeline.py — idle/backlog condvar
    "pipeline.speculative": 65,# pipeline/speculative.py — prefetch results
    "fabric.wire": 70,         # fabric/client.py — per-connection write lock
    "fabric.service": 75,      # fabric/service.py — verifyd slice state
    "ingest.stats": 80,        # ingest/tier.py — admission counters (leaf)
    "daemon.upnp": 85,         # node/daemon.py — UPnP probe guard (leaf)
    # leaves (nothing ranked is ever acquired while holding these)
    "p2p.addressbook": 86,     # p2p/address_manager.py — address-book state
    "p2p.connmgr": 87,         # p2p/address_manager.py — dial bookkeeping
    "p2p.links": 88,           # resilience/faults.py — LINKS drop ledger (frame send path)
    "breaker.slot": 89,        # resilience/breaker.py — device-breaker slot swap
    "supervisor.install": 90,  # resilience/supervisor.py — install/shutdown slot
    "supervisor.manifest": 91, # resilience/supervisor.py — warm-manifest file io
    "watchdog.pool": 92,       # resilience/supervisor.py — worker freelist
    "watchdog.task": 93,       # resilience/supervisor.py — per-job result latch
    "watchdog.stats": 94,      # resilience/supervisor.py — requeue counters
    "txscript.pool": 96,       # txscript/batch.py — VM fallback pool slot
    "txscript.inflight": 97,   # txscript/batch.py — drain accounting
    "txscript.cache": 98,      # txscript/caches.py — sighash/sig cache
    "mining.stats": 99,        # mining/rule_engine.py — sync-rate window
    "stratum.stats": 100,      # bridge/stratum.py — per-worker vardiff stats
    "stratum.shares": 101,     # bridge/stratum.py — job ring + share dedup
    "service.list": 102,       # core/service.py — bound-services list
    "wrpc.ids": 104,           # rpc/wrpc.py — client request-id counter
    "storage.build": 105,      # storage/kv.py — one-shot native build guard
    "chacha.build": 106,       # crypto/chacha.py — one-shot native build guard
    "observability.registry": 110,  # observability/core.py — metric registration (innermost)
}


def ranked_lock(name: str, reentrant: bool = True) -> "LockCtx":
    """A LockCtx whose rank comes from the RANKS table (KeyError on an
    undeclared name — adding a lock means declaring its place in the
    global order first)."""
    return LockCtx(name, RANKS[name], reentrant=reentrant)


class LockCtx:
    """Ranked lock wrapper: acquiring a lock with rank <= any currently
    held rank (on the same thread) is an ordering violation — the static
    discipline that makes the pipeline deadlock-free.  Zero overhead
    unless KASPA_TPU_LOCK_DEBUG is set.

    ``condition()`` builds a threading.Condition over the *underlying*
    lock, so condvar users keep the rank bookkeeping of ``with ctx:``
    while wait/notify release and reacquire the raw lock underneath.
    Note: under debug, a hold that spans ``cv.wait()`` is traced as one
    long hold (the stack entry stays while the raw lock is released —
    the parked thread cannot acquire anything, so order checking is
    unaffected, but hold-time aggregates include wait time).
    """

    def __init__(self, name: str, rank: int, lock=None, reentrant: bool = True):
        self.name = name
        self.rank = rank
        if lock is not None:
            self._lock = lock
        else:
            self._lock = threading.RLock() if reentrant else threading.Lock()

    def condition(self) -> threading.Condition:
        """A Condition bound to this lock; use inside ``with ctx:``."""
        return threading.Condition(self._lock)

    def __enter__(self):
        tracked = _LOCK_DEBUG
        if tracked:
            stack = getattr(_held, "stack", None)
            if stack is None:
                stack = _held.stack = []
            if stack and stack[-1][1] >= self.rank and stack[-1][0] is not self:
                raise AssertionError(
                    f"lock-order violation: acquiring {self.name}(rank {self.rank}) "
                    f"while holding {stack[-1][2]}(rank {stack[-1][1]})"
                )
        self._lock.acquire()
        if tracked:
            # timestamp AFTER acquire: the trace measures hold time, not
            # wait+hold (contention shows as many short holds, not one long)
            stack.append((self, self.rank, self.name, time.perf_counter()))
        return self

    def __exit__(self, *exc):
        self._lock.release()
        # pop-if-ours regardless of the current debug flag: a debug toggle
        # while locks are held must neither pop a foreign/missing entry nor
        # leave a stale one behind (set_lock_debug races are test-only, but
        # corruption here would surface as false ordering violations)
        stack = getattr(_held, "stack", None)
        if stack and stack[-1][0] is self:
            entry = stack.pop()
            _trace_record(self.name, time.perf_counter() - entry[3])
        return False
