"""Concurrency primitives: channels, reader-writer lock, lock-order debug.

The runtime counterpart of the reference's kaspa-utils sync layer
(utils/src/channel.rs, utils/src/sync/rwlock.rs, utils/src/sync/
semaphore.rs).  Python-runtime notes baked into the design:

- Channels are closeable MPMC queues (async_channel semantics): `send`
  after close raises, receivers drain remaining items then see `Closed`.
- LockCtx is the race/deadlock *detection* strategy (SURVEY §5): with
  KASPA_TPU_LOCK_DEBUG=1 every guarded acquisition records a per-thread
  held-set and asserts a global partial order over lock ranks — a cycle
  (deadlock candidate) fails loudly in tests instead of hanging a node.
"""

from __future__ import annotations

import collections
import os
import threading
import time


class Closed(Exception):
    """Channel closed and drained."""


class Channel:
    """Closeable MPMC FIFO channel (utils/src/channel.rs semantics)."""

    def __init__(self, maxsize: int = 0):
        self._q: collections.deque = collections.deque()
        self._maxsize = maxsize
        self._mu = threading.Lock()
        self._not_empty = threading.Condition(self._mu)
        self._not_full = threading.Condition(self._mu)
        self._closed = False

    def send(self, item) -> None:
        with self._mu:
            if self._closed:
                raise Closed("send on closed channel")
            while self._maxsize and len(self._q) >= self._maxsize:
                self._not_full.wait()
                if self._closed:
                    raise Closed("send on closed channel")
            self._q.append(item)
            self._not_empty.notify()

    def recv(self, timeout: float | None = None):
        with self._mu:
            while not self._q:
                if self._closed:
                    raise Closed
                if not self._not_empty.wait(timeout):
                    raise TimeoutError
            item = self._q.popleft()
            self._not_full.notify()
            return item

    def drain(self, max_items: int | None = None) -> list:
        """Atomically take everything currently queued (up to ``max_items``)."""
        with self._mu:
            if max_items is None or max_items >= len(self._q):
                items = list(self._q)
                self._q.clear()
            elif max_items <= 0:
                return []
            else:
                items = [self._q.popleft() for _ in range(max_items)]
            self._not_full.notify_all()
            return items

    def close(self) -> None:
        with self._mu:
            self._closed = True
            self._not_empty.notify_all()
            self._not_full.notify_all()

    def __iter__(self):
        while True:
            try:
                yield self.recv()
            except Closed:
                return

    def __len__(self) -> int:
        with self._mu:
            return len(self._q)


# ----------------------------------------------------------------------
# lock-order debugging (deadlock detection strategy)
# ----------------------------------------------------------------------

_LOCK_DEBUG = bool(os.environ.get("KASPA_TPU_LOCK_DEBUG"))
_held = threading.local()
# per-lock contention/hold aggregates under debug: the runtime analog of
# the reference's semaphore trace feature (utils/src/sync/semaphore.rs
# trace-enabled acquisition accounting)
_trace_mu = threading.Lock()
_trace: dict[str, list] = {}  # name -> [acquisitions, total_hold_s, max_hold_s]


def set_lock_debug(on: bool) -> None:
    """Toggle lock-order checking + hold tracing (tests; env is read once)."""
    global _LOCK_DEBUG
    _LOCK_DEBUG = bool(on)


def lock_trace_snapshot() -> dict:
    """{lock name: {acquisitions, total_hold_s, max_hold_s}} accumulated
    while debug is on — contention hunting without a profiler attached."""
    with _trace_mu:
        return {
            name: {"acquisitions": c, "total_hold_s": round(t, 6), "max_hold_s": round(m, 6)}
            for name, (c, t, m) in _trace.items()
        }


def _trace_record(name: str, held_s: float) -> None:
    with _trace_mu:
        entry = _trace.setdefault(name, [0, 0.0, 0.0])
        entry[0] += 1
        entry[1] += held_s
        entry[2] = max(entry[2], held_s)


class LockCtx:
    """Ranked lock wrapper: acquiring a lock with rank <= any currently
    held rank (on the same thread) is an ordering violation — the static
    discipline that makes the pipeline deadlock-free.  Zero overhead
    unless KASPA_TPU_LOCK_DEBUG is set."""

    def __init__(self, name: str, rank: int, lock=None):
        self.name = name
        self.rank = rank
        self._lock = lock if lock is not None else threading.RLock()

    def __enter__(self):
        tracked = _LOCK_DEBUG
        if tracked:
            stack = getattr(_held, "stack", None)
            if stack is None:
                stack = _held.stack = []
            if stack and stack[-1][1] >= self.rank and stack[-1][0] is not self:
                raise AssertionError(
                    f"lock-order violation: acquiring {self.name}(rank {self.rank}) "
                    f"while holding {stack[-1][2]}(rank {stack[-1][1]})"
                )
        self._lock.acquire()
        if tracked:
            # timestamp AFTER acquire: the trace measures hold time, not
            # wait+hold (contention shows as many short holds, not one long)
            stack.append((self, self.rank, self.name, time.perf_counter()))
        return self

    def __exit__(self, *exc):
        self._lock.release()
        # pop-if-ours regardless of the current debug flag: a debug toggle
        # while locks are held must neither pop a foreign/missing entry nor
        # leave a stale one behind (set_lock_debug races are test-only, but
        # corruption here would surface as false ordering violations)
        stack = getattr(_held, "stack", None)
        if stack and stack[-1][0] is self:
            entry = stack.pop()
            _trace_record(self.name, time.perf_counter() - entry[3])
        return False
