"""Process-wide JAX configuration for the framework.

Call ``setup()`` once from every entry point (tests, bench, node, tools).
Enables the persistent XLA compilation cache so the big crypto ladders
compile once per machine rather than once per process.
"""

from __future__ import annotations

import os

_DONE = False


def cache_dir() -> str:
    """The persistent compilation-cache directory (no jax import — the
    warm-kernel manifest lives next to the XLA cache entries)."""
    return os.environ.get("KASPA_TPU_JAX_CACHE", os.path.expanduser("~/.cache/kaspa_tpu_jax"))


def setup(cache_dir: str | None = None) -> None:
    global _DONE
    if _DONE:
        return
    _DONE = True
    import jax

    # KASPA_TPU_PLATFORM=cpu forces the CPU backend even where a platform
    # plugin self-registers at interpreter startup (the axon sitecustomize
    # hook ignores JAX_PLATFORMS) — needed for subprocess test daemons
    forced = os.environ.get("KASPA_TPU_PLATFORM")
    if forced:
        jax.config.update("jax_platforms", forced)

    cache_dir = cache_dir or os.environ.get(
        "KASPA_TPU_JAX_CACHE", os.path.expanduser("~/.cache/kaspa_tpu_jax")
    )
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
