"""Process-wide JAX configuration for the framework.

Call ``setup()`` once from every entry point (tests, bench, node, tools).
Enables the persistent XLA compilation cache so the big crypto ladders
compile once per machine rather than once per process.

``KASPA_TPU_HOST_DEVICES=N`` splits the host CPU backend into N XLA
devices (the ergonomic spelling of
``XLA_FLAGS=--xla_force_host_platform_device_count=N``): it lets
``--mesh auto`` / ``--mesh N`` / ``--mesh RxC`` find N devices on a
CPU-only box without the caller hand-assembling XLA_FLAGS.  It must be
seen before the first ``import jax`` in the process, so every entry
point calls ``setup()`` at module import time, ahead of any jax-touching
import.  An explicit device-count flag already present in XLA_FLAGS
wins — the knob never overrides a deliberate setting.
"""

from __future__ import annotations

import os

_DONE = False


def cache_dir() -> str:
    """The persistent compilation-cache directory (no jax import — the
    warm-kernel manifest lives next to the XLA cache entries)."""
    return os.environ.get("KASPA_TPU_JAX_CACHE", os.path.expanduser("~/.cache/kaspa_tpu_jax"))


def _apply_host_devices() -> None:
    """Fold KASPA_TPU_HOST_DEVICES=N into XLA_FLAGS (pre-`import jax`)."""
    knob = os.environ.get("KASPA_TPU_HOST_DEVICES", "").strip()
    if not knob:
        return
    try:
        n = int(knob)
    except ValueError:
        raise SystemExit(f"KASPA_TPU_HOST_DEVICES must be an integer, got {knob!r}")
    if n < 1:
        raise SystemExit(f"KASPA_TPU_HOST_DEVICES must be >= 1, got {n}")
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" in flags:
        return  # an explicit XLA_FLAGS setting wins over the knob
    os.environ["XLA_FLAGS"] = (flags + f" --xla_force_host_platform_device_count={n}").strip()


def setup(cache_dir: str | None = None) -> None:
    global _DONE
    if _DONE:
        return
    _DONE = True
    _apply_host_devices()
    import jax

    # KASPA_TPU_PLATFORM=cpu forces the CPU backend even where a platform
    # plugin self-registers at interpreter startup (the axon sitecustomize
    # hook ignores JAX_PLATFORMS) — needed for subprocess test daemons
    forced = os.environ.get("KASPA_TPU_PLATFORM")
    if forced:
        jax.config.update("jax_platforms", forced)

    cache_dir = cache_dir or os.environ.get(
        "KASPA_TPU_JAX_CACHE", os.path.expanduser("~/.cache/kaspa_tpu_jax")
    )
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
