"""File-descriptor budget preflight for socket-heavy harnesses.

The serving load harness opens a socketpair (2 fds) per wire-cohort
subscriber on top of whatever the process already holds.  Hitting
``RLIMIT_NOFILE`` mid-ramp surfaces as a cryptic ``EMFILE`` from deep
inside socket creation, after minutes of setup — so the harness preflights
the budget up front and fails with the remedy instead.

``preflight(required)`` answers "can this process open ``required`` MORE
fds right now?"; ``budget()`` reports the full accounting (recorded in
``SERVING_LOAD.json`` run_meta so an artifact read on another machine
carries the limit it ran under).
"""

from __future__ import annotations

import os

# fds we refuse to hand to the caller: stdio, log files, late-bound
# sockets, the JAX runtime's own handles all need room to breathe
HEADROOM = 128


class FdBudgetError(RuntimeError):
    """Raised when a requested fd budget cannot fit under RLIMIT_NOFILE."""


def fd_limit() -> int:
    """Soft RLIMIT_NOFILE (0 when the platform cannot say)."""
    try:
        import resource

        return resource.getrlimit(resource.RLIMIT_NOFILE)[0]
    except Exception:  # noqa: BLE001 - non-POSIX fallback
        return 0


def fds_in_use() -> int:
    """Open descriptors right now (0 when /proc is unavailable)."""
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return 0


def budget(headroom: int = HEADROOM) -> dict:
    """Current accounting: limit, in-use, headroom, what's left to spend."""
    limit = fd_limit()
    in_use = fds_in_use()
    return {
        "limit": limit,
        "in_use": in_use,
        "headroom": headroom,
        "available": max(0, limit - in_use - headroom) if limit else 0,
    }


def preflight(required: int, *, what: str = "file descriptors", headroom: int = HEADROOM) -> dict:
    """Assert ``required`` more fds fit under the soft limit; returns the
    ``budget()`` dict (for run_meta) on success, raises ``FdBudgetError``
    with the ``ulimit -n`` remedy on failure."""
    b = budget(headroom)
    if b["limit"] and required > b["available"]:
        need = required + b["in_use"] + headroom
        raise FdBudgetError(
            f"fd budget exceeded: {what} needs {required} fds but only "
            f"{b['available']} fit under RLIMIT_NOFILE={b['limit']} "
            f"({b['in_use']} already open + {headroom} headroom). "
            f"Raise the limit (`ulimit -n {need}` before launching, or "
            f"bump nofile in /etc/security/limits.conf) or shrink the "
            f"wire cohort (--wire)."
        )
    return b


def serving_preflight(
    *,
    shards: int,
    pool_workers: int,
    wire_cohort: int,
    what: str = "serving tier",
    headroom: int = HEADROOM,
) -> dict:
    """Sharded-serving-tier budget: ``max(1, shards)`` sender-pool crews of
    ``pool_workers`` each (a slot per worker thread — conservative: worker
    threads hold log/epoll handles on some runtimes) plus two descriptors
    per wire-cohort subscriber (a datagram socketpair).  Returns the
    ``budget()`` dict extended with the accounting breakdown (recorded in
    ``SERVING_LOAD.json`` run_meta); raises ``FdBudgetError`` on a miss."""
    crews = max(1, int(shards))
    worker_slots = crews * max(0, int(pool_workers))
    socket_fds = 2 * max(0, int(wire_cohort))
    required = worker_slots + socket_fds
    b = preflight(
        required,
        what=f"{what} ({crews} shard(s) x {pool_workers} pool workers "
        f"+ wire cohort of {wire_cohort} subscribers)",
        headroom=headroom,
    )
    b["required"] = required
    b["worker_slots"] = worker_slots
    b["socket_fds"] = socket_fds
    b["shards"] = crews
    return b
