"""System + build information (reference: utils/src/sysinfo.rs and the
kaspad build-info plumbing surfaced through GetSystemInfo RPC)."""

from __future__ import annotations

import functools
import os
import platform
import subprocess
import uuid

VERSION = "0.2.0"  # framework version (round 2)


@functools.lru_cache(maxsize=1)
def build_info() -> dict:
    """Version + git state baked at query time (the reference embeds these
    at compile time via vergen; we read the live repo once per process)."""
    commit = None
    try:
        commit = (
            subprocess.run(
                ["git", "rev-parse", "--short=12", "HEAD"],
                capture_output=True, text=True, timeout=5,
                cwd=os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
            ).stdout.strip()
            or None
        )
    except Exception:
        pass
    return {"version": VERSION, "git_hash": commit}


def _meminfo_kb(field: str) -> int | None:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith(field + ":"):
                    return int(line.split()[1])
    except OSError:
        return None
    return None


@functools.lru_cache(maxsize=1)
def system_id() -> str:
    """Stable anonymous node id (sysinfo.rs system_id: machine-derived)."""
    return uuid.uuid5(uuid.NAMESPACE_DNS, f"{platform.node()}-{os.getuid()}").hex


def system_info() -> dict:
    total_kb = _meminfo_kb("MemTotal")
    fd_count = None
    try:
        fd_count = len(os.listdir("/proc/self/fd"))
    except OSError:
        pass
    info = {
        "system_id": system_id(),
        "cpu_physical_cores": os.cpu_count() or 0,
        "total_memory": (total_kb or 0) * 1024,
        "fd_limit": _fd_limit(),
        "fd_count": fd_count,
        "proxy_socket_limit_per_cpu_core": None,
        **build_info(),
    }
    return info


def _fd_limit() -> int:
    try:
        import resource

        return resource.getrlimit(resource.RLIMIT_NOFILE)[0]
    except Exception:
        return 0
