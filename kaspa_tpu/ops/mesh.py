"""Mesh execution layer: shard_map dispatch for the production batch kernels.

The 8-device dryrun (`__graft_entry__.dryrun_multichip`) proved the verify
kernels and the muhash tree product shard bit-identically over a 1-D device
mesh; this module makes that the *production* path.  `configure("--mesh N")`
selects a mesh size once per process (``auto`` = every visible device), and
the batch front-ends (`ops/secp256k1/verify.py`, `ops/muhash_ops.py`) route
through here whenever the active size is > 1:

- inputs are padded to a shard multiple (invalid lanes for verify, the
  monoid identity for muhash) and results unpadded, so callers keep their
  exact single-device shapes and semantics;
- one jit entry is cached per (kernel, mesh size) — the shard_map trace
  sees the per-shard local shape, so the compiled artifact set stays as
  small as the single-device bucket scheme;
- per-shard observability (occupancy, padding waste, local batch sizes,
  dispatch counts) lands in the global registry next to the secp batch
  telemetry, surfacing through ``get_metrics`` and the Prometheus text.

CPU-mesh testing recipe (no TPU needed, what the test suite does):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python -m kaspa_tpu.sim --blocks 32 --mesh 8

Sharding layout: pure batch-dim data parallelism for the verify kernels
(no collectives — each shard verifies its slice and returns its mask
slice); the muhash tree product reduces each shard's slice to one U3072
partial product on device and combines the <= mesh-size partials on host
(one cheap 3072-bit multiply each), which keeps the result bit-identical
to any other association order of the commutative monoid product.
"""

from __future__ import annotations

import functools
import os
import threading

import numpy as np

from kaspa_tpu.observability.core import PERCENT_BUCKETS, REGISTRY, SIZE_BUCKETS

# --- per-shard observability ----------------------------------------------
# occupancy is per SHARD (not per batch): contiguous padding concentrates
# waste in the tail shards, and a starved tail shard is pure bubble on that
# device — the first thing to look at when mesh throughput disappoints
_SHARD_OCCUPANCY = REGISTRY.histogram(
    "mesh_shard_occupancy_pct", PERCENT_BUCKETS,
    help="useful (non-pad) lanes per shard / shard width * 100, one observation per shard per dispatch",
)
_SHARD_BATCH = REGISTRY.histogram(
    "mesh_shard_batch_size", SIZE_BUCKETS, help="per-shard local batch width of mesh dispatches"
)
_PAD_WASTE = REGISTRY.histogram(
    "mesh_padding_waste_pct", PERCENT_BUCKETS,
    help="pad lanes added by the mesh layer / padded total * 100, per dispatch",
)
_PADDED_LANES = REGISTRY.counter("mesh_padded_lanes", help="device lanes wasted on pad-to-shard-multiple")
_DISPATCHES = REGISTRY.counter_family(
    "mesh_dispatches", "kernel", help="sharded dispatches by kernel (schnorr/ecdsa/muhash)"
)

_lock = threading.Lock()
_configured: str | int | None = None  # raw spec, resolved lazily
_active: int | None = None  # resolved mesh size (clamped to visible devices)


def _mesh_state() -> dict:
    return {
        "configured": str(_configured) if _configured is not None else "",
        "size": active_size(),
    }


REGISTRY.register_collector("mesh", _mesh_state)


def configure(spec: int | str | None) -> int:
    """Select the process-wide mesh size; returns the resolved size.

    ``spec``: an int, a decimal string, ``"auto"`` (every visible device),
    or None (fall back to the KASPA_TPU_MESH env var, default 1).  Sizes
    above the visible device count clamp; <= 1 disables mesh dispatch.
    """
    global _configured, _active
    with _lock:
        _configured = spec if spec is not None else os.environ.get("KASPA_TPU_MESH", 1)
        _active = None  # re-resolve on next use
    return active_size()


def active_size() -> int:
    """Resolved mesh size (1 = mesh dispatch disabled)."""
    global _configured, _active
    if _active is None:
        with _lock:
            if _active is None:
                spec = _configured if _configured is not None else os.environ.get("KASPA_TPU_MESH", 1)
                _configured = spec
                _active = _resolve(spec)
    return _active


def _resolve(spec: int | str) -> int:
    import jax

    if isinstance(spec, str):
        spec = spec.strip().lower()
        if spec in ("auto", "all"):
            n = len(jax.devices())
        else:
            n = int(spec or 1)
    else:
        n = int(spec)
    if n <= 1:
        return 1
    return min(n, len(jax.devices()))


@functools.lru_cache(maxsize=None)
def _mesh(n: int):
    import jax
    from jax.sharding import Mesh

    devices = np.array(jax.devices()[:n])
    assert len(devices) == n, f"mesh size {n} exceeds visible devices {len(jax.devices())}"
    return Mesh(devices, axis_names=("shard",))


def _pad_rows(arr: np.ndarray, m: int) -> np.ndarray:
    """Zero-pad the leading (batch) axis of `arr` to m rows."""
    arr = np.asarray(arr)
    if arr.shape[0] == m:
        return arr
    out = np.zeros((m,) + arr.shape[1:], dtype=arr.dtype)
    out[: arr.shape[0]] = arr
    return out


def _observe(kernel: str, logical: int, padded: int, n: int) -> None:
    _DISPATCHES.inc(kernel)
    _PADDED_LANES.inc(padded - logical)
    _PAD_WASTE.observe(100.0 * (padded - logical) / padded)
    width = padded // n
    for shard in range(n):
        useful = min(max(logical - shard * width, 0), width)
        _SHARD_OCCUPANCY.observe(100.0 * useful / width)
        _SHARD_BATCH.observe(width)


# --- batched signature verification ---------------------------------------


@functools.lru_cache(maxsize=None)
def _verify_entry(kind: str, n: int):
    """Cached shard_map-jitted verify kernel for one (kind, mesh size)."""
    import jax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from kaspa_tpu.ops.secp256k1 import verify as v

    kernel = (v.schnorr_verify_kernel if kind == "schnorr" else v.ecdsa_verify_kernel).__wrapped__
    lane = P("shard", None)
    flat = P("shard")
    fn = shard_map(kernel, mesh=_mesh(n), in_specs=(lane,) * 5 + (flat,), out_specs=flat)
    return jax.jit(fn)


def dispatch_verify(kind: str, px, py, rc, d1_digits, d2_digits, valid_in) -> np.ndarray:
    """Batch-dim sharded verify: pads to a shard multiple, dispatches the
    cached shard_map entry, unpads the mask.  Pad lanes carry zeroed limbs
    and ``valid_in=False`` so they can never contribute a True.
    """
    from kaspa_tpu.resilience.faults import FAULTS

    # mesh-specific fault point (a single wedged shard kills the whole
    # shard_map dispatch); propagates into the device breaker like any
    # other dispatch failure
    FAULTS.fire("device.mesh.dispatch")
    n = active_size()
    px = np.asarray(px)
    b = px.shape[0]
    if b == 0:
        return np.zeros(0, dtype=bool)
    m = -(-b // n) * n  # ceil to shard multiple
    args = (
        _pad_rows(px, m),
        _pad_rows(py, m),
        _pad_rows(rc, m),
        _pad_rows(d1_digits, m),
        _pad_rows(d2_digits, m),
        _pad_rows(np.asarray(valid_in, dtype=bool), m),
    )
    mask = np.asarray(_verify_entry(kind, n)(*args))
    _observe(kind, b, m, n)
    return mask[:b]


# --- muhash tree product ---------------------------------------------------


@functools.lru_cache(maxsize=None)
def _tree_entry(n: int, levels: int):
    """Cached shard_map-jitted local tree product: each shard reduces its
    [bucket, 192] slice to one canonical U3072 element ([1, 192])."""
    import jax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from kaspa_tpu.ops import bigint as bi

    F = bi.F3072

    def local_tree(x):
        for _ in range(levels):
            half = x.shape[0] // 2
            x = bi.mul(F, x[:half], x[half:])
        return bi.canon(F, x[0])[None, :]

    fn = shard_map(local_tree, mesh=_mesh(n), in_specs=P("shard", None), out_specs=P("shard", None))
    return jax.jit(fn)


def dispatch_tree_product(elements: np.ndarray) -> int:
    """Sharded U3072 product: [N, 192] int32 limbs -> python int mod the
    muhash prime.  Mirrors `muhash_ops.batch_product_device`'s bucket
    policy per shard (one compiled shape per (mesh, bucket)); each shard's
    partial product combines on host with one 3072-bit multiply.
    """
    from kaspa_tpu.ops import bigint as bi
    from kaspa_tpu.ops.muhash_ops import BUCKETS

    F = bi.F3072
    n = active_size()
    elements = np.asarray(elements)
    total = elements.shape[0]
    if total == 0:
        return 1
    result = 1
    pos = 0
    while pos < total:
        remaining = total - pos
        per_shard = -(-remaining // n)
        # largest bucket that fits the per-shard remainder, else the
        # smallest bucket (identity-padded) — same shape discipline as the
        # single-device path, scaled by the mesh
        fitting = [bk for bk in BUCKETS if bk <= per_shard]
        bucket = fitting[-1] if fitting else BUCKETS[0]
        take = min(bucket * n, remaining)
        chunk = elements[pos : pos + take]
        padded = np.tile(np.asarray(F.one, dtype=np.int32), (bucket * n, 1))
        padded[: chunk.shape[0]] = chunk
        partials = np.asarray(_tree_entry(n, bucket.bit_length() - 1)(padded))
        for row in partials:
            result = result * bi.limbs_to_int(row) % F.modulus
        _observe("muhash", take, bucket * n, n)
        pos += take
    return result
