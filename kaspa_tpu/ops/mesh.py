"""Mesh execution layer: shard_map dispatch for the production batch kernels.

The 8-device dryrun (`__graft_entry__.dryrun_multichip`) proved the verify
kernels and the muhash tree product shard bit-identically over a 1-D device
mesh; this module makes that the *production* path.  `configure("--mesh N")`
selects a mesh size once per process (``auto`` = every visible device), and
the batch front-ends (`ops/secp256k1/verify.py`, `ops/muhash_ops.py`) route
through here whenever the active size is > 1:

- inputs are padded to a shard multiple (invalid lanes for verify, the
  monoid identity for muhash) and results unpadded, so callers keep their
  exact single-device shapes and semantics;
- one jit entry is cached per (kernel, mesh size) — the shard_map trace
  sees the per-shard local shape, so the compiled artifact set stays as
  small as the single-device bucket scheme;
- per-shard observability (occupancy, padding waste, local batch sizes,
  dispatch counts) lands in the global registry next to the secp batch
  telemetry, surfacing through ``get_metrics`` and the Prometheus text.

CPU-mesh testing recipe (no TPU needed, what the test suite does):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python -m kaspa_tpu.sim --blocks 32 --mesh 8

Sharding layout: pure batch-dim data parallelism for the verify kernels
(no collectives — each shard verifies its slice and returns its mask
slice); the muhash tree product reduces each shard's slice to one U3072
partial product on device and combines the <= mesh-size partials on host
(one cheap 3072-bit multiply each), which keeps the result bit-identical
to any other association order of the commutative monoid product.

2-D hybrid mesh (the verify-fabric substrate): ``configure("RxC")``
arranges the devices as R slices of C devices each — on a multi-host
deployment via ``create_hybrid_device_mesh`` (slices map to hosts, the
fast intra-host links carry the "shard" axis), on a single host by
reshaping the local devices (the CPU test topology).  A fabric slice
worker pins itself with ``slice_lane(i)`` so its dispatches run on slice
i's devices only; unpinned dispatches shard over the whole grid.  All
in/out specs derive from the regex partition-rule registry, and every
path — 1-D, full grid, single slice — is batch-dim data parallelism over
the same kernels, so masks stay bit-identical across layouts.
"""

from __future__ import annotations

import contextlib
import functools
import os
import re
import threading

from kaspa_tpu.utils.sync import ranked_lock

import numpy as np

from kaspa_tpu.observability.core import PERCENT_BUCKETS, REGISTRY, SIZE_BUCKETS

# --- per-shard observability ----------------------------------------------
# occupancy is per SHARD (not per batch): contiguous padding concentrates
# waste in the tail shards, and a starved tail shard is pure bubble on that
# device — the first thing to look at when mesh throughput disappoints
_SHARD_OCCUPANCY = REGISTRY.histogram(
    "mesh_shard_occupancy_pct", PERCENT_BUCKETS,
    help="useful (non-pad) lanes per shard / shard width * 100, one observation per shard per dispatch",
)
_SHARD_BATCH = REGISTRY.histogram(
    "mesh_shard_batch_size", SIZE_BUCKETS, help="per-shard local batch width of mesh dispatches"
)
_PAD_WASTE = REGISTRY.histogram(
    "mesh_padding_waste_pct", PERCENT_BUCKETS,
    help="pad lanes added by the mesh layer / padded total * 100, per dispatch",
)
_PADDED_LANES = REGISTRY.counter("mesh_padded_lanes", help="device lanes wasted on pad-to-shard-multiple")
_DISPATCHES = REGISTRY.counter_family(
    "mesh_dispatches", "kernel", help="sharded dispatches by kernel (schnorr/ecdsa/muhash)"
)

_SLICE_DISPATCHES = REGISTRY.counter_family(
    "mesh_slice_dispatches", "slice", help="slice-pinned verify dispatches by mesh slice"
)
_SLICE_JOBS = REGISTRY.counter_family(
    "mesh_slice_jobs", "slice", help="verify jobs dispatched per mesh slice (pre-padding)"
)

_lock = ranked_lock("mesh.config")
_configured: str | int | None = None  # raw spec, resolved lazily
_active: int | None = None  # resolved mesh size (clamped to visible devices)
_grid: tuple[int, int] | None = None  # (slices, shards-per-slice) for "RxC" specs
_slice_tls = threading.local()  # slice_lane() pin: route dispatches to one slice


def _mesh_state() -> dict:
    n = active_size()
    return {
        "configured": str(_configured) if _configured is not None else "",
        "size": n,
        "grid": "x".join(map(str, _grid)) if _grid else "",
        "slices": slice_count(),
    }


REGISTRY.register_collector("mesh", _mesh_state)


def configure(spec: int | str | None) -> int:
    """Select the process-wide mesh size; returns the resolved size.

    ``spec``: an int, a decimal string, ``"auto"`` (every visible device),
    an ``"RxC"`` grid (R slices of C devices — the 2-D hybrid mesh), or
    None (fall back to the KASPA_TPU_MESH env var, default 1).  Sizes
    above the visible device count clamp; <= 1 disables mesh dispatch.
    """
    global _configured, _active, _grid
    with _lock:
        _configured = spec if spec is not None else os.environ.get("KASPA_TPU_MESH", 1)
        _active = None  # re-resolve on next use
        _grid = None
    return active_size()


def active_size() -> int:
    """Resolved mesh size (1 = mesh dispatch disabled)."""
    global _configured, _active, _grid
    if _active is None:
        with _lock:
            if _active is None:
                spec = _configured if _configured is not None else os.environ.get("KASPA_TPU_MESH", 1)
                _configured = spec
                _active, _grid = _resolve(spec)
    return _active


def grid() -> tuple[int, int] | None:
    """The resolved (slices, shards-per-slice) grid, or None in 1-D mode."""
    active_size()
    return _grid


def slice_count() -> int:
    """Mesh slices of the active grid (1 in 1-D / disabled mode)."""
    g = grid()
    return g[0] if g else 1


def slice_width() -> int:
    """Devices per slice of the active grid (= mesh size in 1-D mode)."""
    g = grid()
    return g[1] if g else active_size()


def _resolve(spec: int | str) -> tuple[int, int | None]:
    import jax

    ndev = len(jax.devices())
    if isinstance(spec, str):
        spec = spec.strip().lower()
        if "x" in spec:
            r_s, _, c_s = spec.partition("x")
            r, c = int(r_s or 1), int(c_s or 1)
            # clamp the grid to the visible devices, preferring to keep the
            # slice count (the fabric's unit of failover) over slice width
            r = max(1, min(r, ndev))
            c = max(1, min(c, ndev // r))
            if r <= 1:
                return (c if c > 1 else 1), None
            return r * c, (r, c)
        if spec in ("auto", "all"):
            n = ndev
        else:
            n = int(spec or 1)
    else:
        n = int(spec)
    if n <= 1:
        return 1, None
    return min(n, ndev), None


@contextlib.contextmanager
def slice_lane(idx: int | None):
    """Pin this thread's verify dispatches to mesh slice ``idx`` (no-op
    when no 2-D grid is configured or ``idx`` is None) — the fabric slice
    workers wrap their device calls in this so concurrent slices run on
    disjoint devices."""
    if idx is None or _grid is None:
        yield
        return
    prev = getattr(_slice_tls, "idx", None)
    _slice_tls.idx = idx % _grid[0]
    try:
        yield
    finally:
        _slice_tls.idx = prev


@functools.lru_cache(maxsize=None)
def _mesh(n: int):
    import jax
    from jax.sharding import Mesh

    devices = np.array(jax.devices()[:n])
    assert len(devices) == n, f"mesh size {n} exceeds visible devices {len(jax.devices())}"
    return Mesh(devices, axis_names=("shard",))


@functools.lru_cache(maxsize=None)
def _device_grid(r: int, c: int) -> np.ndarray:
    """[r, c] device array for the hybrid mesh: `create_hybrid_device_mesh`
    when the process set actually spans hosts (slices ride the slow DCN
    axis, shards the fast ICI axis), plain local reshape otherwise (the
    single-host / CPU-test topology, where the hybrid helper has no slice
    metadata to work with)."""
    import jax

    if jax.process_count() > 1:
        try:
            from jax.experimental.mesh_utils import create_hybrid_device_mesh

            return np.asarray(
                create_hybrid_device_mesh((1, c), (r, 1), devices=jax.devices())
            ).reshape(r, c)
        except Exception:  # noqa: BLE001 - topology metadata absent: fall back
            pass
    return np.array(jax.devices()[: r * c]).reshape(r, c)


@functools.lru_cache(maxsize=None)
def _mesh2d(r: int, c: int):
    from jax.sharding import Mesh

    return Mesh(_device_grid(r, c), axis_names=("slice", "shard"))


@functools.lru_cache(maxsize=None)
def _slice_mesh(r: int, c: int, idx: int):
    """1-D mesh over slice ``idx``'s row of the grid — slice-pinned
    dispatches reuse the plain ("shard",) kernel entries on it."""
    from jax.sharding import Mesh

    return Mesh(_device_grid(r, c)[idx], axis_names=("shard",))


# --- partition-rule registry ------------------------------------------------
# regex -> PartitionSpec axes, first match wins (the t5x/EasyLM registry
# idiom): verify/muhash operands are pure batch-dim data parallelism, so
# the batch axis shards over every mesh axis and everything else
# replicates.  register_partition_rule() lets a new kernel claim a layout
# without touching the dispatch plumbing.
DEFAULT_PARTITION_RULES: tuple = (
    (r"(px|py|rc|pxn|pyn|rxn|ryn|.*digits|elements)$", (("slice", "shard"), None)),
    (r"(valid_in|mask)$", (("slice", "shard"),)),
    # aggregate window partials: each shard emits a [1, 64, W] stack —
    # batch-sharded on the leading axis, windows/limbs replicated
    (r"partials$", (("slice", "shard"), None, None)),
    (r".*", ()),  # replicate
)

_partition_rules: list = list(DEFAULT_PARTITION_RULES)


def register_partition_rule(pattern: str, axes: tuple) -> None:
    """Prepend one (regex, PartitionSpec axes) rule (first match wins)."""
    _partition_rules.insert(0, (pattern, axes))


def _axes_for_1d(axes: tuple) -> tuple:
    """Project a 2-D rule onto a 1-D ("shard",) mesh: the composite
    ("slice", "shard") batch axis collapses to "shard"."""
    return tuple("shard" if isinstance(a, tuple) else a for a in axes)


def partition_spec_for(name: str, *, flat: bool = False):
    """PartitionSpec for a named operand per the registry; ``flat=True``
    projects onto the 1-D mesh axis."""
    from jax.sharding import PartitionSpec as P

    for pattern, axes in _partition_rules:
        if re.fullmatch(pattern, name):
            return P(*(_axes_for_1d(axes) if flat else axes))
    return P()


def match_partition_rules(rules, tree: dict) -> dict:
    """Map a (possibly nested) dict of named arrays to PartitionSpecs by
    first-matching regex on the '/'-joined path — the SNIPPETS registry
    shape, usable for any future parameter pytree."""
    from jax.sharding import PartitionSpec as P

    def walk(prefix: str, node):
        if isinstance(node, dict):
            return {k: walk(f"{prefix}/{k}" if prefix else k, v) for k, v in node.items()}
        for pattern, axes in rules:
            if re.search(pattern, prefix):
                return P(*axes)
        return P()

    return walk("", tree)


def constrain(x, name: str):
    """`with_sharding_constraint` under the registry's spec for ``name`` —
    a no-op on CPU or when no 2-D grid is configured (the SNIPPETS [3]
    CPU-fallback contract), so call sites never need backend guards."""
    g = grid()
    if g is None:
        return x
    import jax

    if jax.default_backend() == "cpu":
        return x
    try:
        from jax.sharding import NamedSharding

        return jax.lax.with_sharding_constraint(
            x, NamedSharding(_mesh2d(*g), partition_spec_for(name))
        )
    except Exception:  # noqa: BLE001 - outside jit / mesh ctx: identity
        return x


def _pad_rows(arr: np.ndarray, m: int) -> np.ndarray:
    """Zero-pad the leading (batch) axis of `arr` to m rows."""
    arr = np.asarray(arr)
    if arr.shape[0] == m:
        return arr
    out = np.zeros((m,) + arr.shape[1:], dtype=arr.dtype)
    out[: arr.shape[0]] = arr
    return out


def _observe(kernel: str, logical: int, padded: int, n: int) -> None:
    _DISPATCHES.inc(kernel)
    _PADDED_LANES.inc(padded - logical)
    _PAD_WASTE.observe(100.0 * (padded - logical) / padded)
    width = padded // n
    for shard in range(n):
        useful = min(max(logical - shard * width, 0), width)
        _SHARD_OCCUPANCY.observe(100.0 * useful / width)
        _SHARD_BATCH.observe(width)


# --- batched signature verification ---------------------------------------


_VERIFY_ARG_NAMES = ("px", "py", "rc", "d1_digits", "d2_digits", "valid_in")


def _verify_kernel(kind: str):
    from kaspa_tpu.ops.secp256k1 import verify as v

    return (v.schnorr_verify_kernel if kind == "schnorr" else v.ecdsa_verify_kernel).__wrapped__


@functools.lru_cache(maxsize=None)
def _verify_entry(kind: str, n: int):
    """Cached shard_map-jitted verify kernel for one (kind, mesh size);
    in/out specs come from the partition-rule registry projected onto the
    1-D ("shard",) axis."""
    import jax
    from jax.experimental.shard_map import shard_map

    in_specs = tuple(partition_spec_for(nm, flat=True) for nm in _VERIFY_ARG_NAMES)
    out_specs = partition_spec_for("mask", flat=True)
    fn = shard_map(_verify_kernel(kind), mesh=_mesh(n), in_specs=in_specs, out_specs=out_specs)
    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _verify_entry_2d(kind: str, r: int, c: int):
    """Full-grid entry: batch axis sharded over ("slice", "shard") — the
    same per-device local shapes (and thus the same trace cost and
    bit-identical masks) as the 1-D entry of size r*c."""
    import jax
    from jax.experimental.shard_map import shard_map

    in_specs = tuple(partition_spec_for(nm) for nm in _VERIFY_ARG_NAMES)
    out_specs = partition_spec_for("mask")

    kernel = _verify_kernel(kind)

    def wrapped(*args):
        return constrain(kernel(*args), "mask")

    fn = shard_map(wrapped, mesh=_mesh2d(r, c), in_specs=in_specs, out_specs=out_specs)
    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _verify_entry_slice(kind: str, r: int, c: int, idx: int):
    """Slice-pinned entry: the 1-D kernel over slice ``idx``'s devices, so
    concurrent fabric slice workers occupy disjoint hardware."""
    import jax
    from jax.experimental.shard_map import shard_map

    in_specs = tuple(partition_spec_for(nm, flat=True) for nm in _VERIFY_ARG_NAMES)
    out_specs = partition_spec_for("mask", flat=True)
    fn = shard_map(
        _verify_kernel(kind), mesh=_slice_mesh(r, c, idx), in_specs=in_specs, out_specs=out_specs
    )
    return jax.jit(fn)


def dispatch_verify(kind: str, px, py, rc, d1_digits, d2_digits, valid_in) -> np.ndarray:
    """Batch-dim sharded verify: pads to a shard multiple, dispatches the
    cached shard_map entry, unpads the mask.  Pad lanes carry zeroed limbs
    and ``valid_in=False`` so they can never contribute a True.

    With a 2-D grid configured, a thread inside ``slice_lane(i)`` runs on
    slice i's devices only; unpinned threads shard over the full grid.
    """
    from kaspa_tpu.resilience.faults import FAULTS

    # mesh-specific fault point (a single wedged shard kills the whole
    # shard_map dispatch); propagates into the device breaker like any
    # other dispatch failure
    FAULTS.fire("device.mesh.dispatch")
    total = active_size()
    g = _grid
    pin = getattr(_slice_tls, "idx", None) if g else None
    if g is None:
        n, entry = total, _verify_entry(kind, total)
    elif pin is not None:
        n, entry = g[1], _verify_entry_slice(kind, g[0], g[1], pin)
    else:
        n, entry = total, _verify_entry_2d(kind, g[0], g[1])
    px = np.asarray(px)
    b = px.shape[0]
    if b == 0:
        return np.zeros(0, dtype=bool)
    m = -(-b // n) * n  # ceil to shard multiple
    args = (
        _pad_rows(px, m),
        _pad_rows(py, m),
        _pad_rows(rc, m),
        _pad_rows(d1_digits, m),
        _pad_rows(d2_digits, m),
        _pad_rows(np.asarray(valid_in, dtype=bool), m),
    )
    mask = np.asarray(entry(*args))
    _observe(kind, b, m, n)
    if pin is not None:
        _SLICE_DISPATCHES.inc(str(pin))
        _SLICE_JOBS.inc(str(pin), b)
    return mask[:b]


# --- aggregate RLC window partials -----------------------------------------
#
# The aggregate multi-scalar kernel (ops/secp256k1/aggregate.py) shards the
# same way as verify — pure batch-dim data parallelism — but each shard
# returns its lanes' [64] window-sum points instead of a mask slice; the
# [n, 64] stack combines in the (tiny, unsharded) reduce/finish kernel, the
# muhash partial-product pattern applied to the EC group.

_AGG_ARG_NAMES = ("pxn", "pyn", "rxn", "ryn", "c_digits", "a_digits")


def _agg_local_kernel():
    from kaspa_tpu.ops.secp256k1 import aggregate as agg

    raw = agg.aggregate_partials_kernel.__wrapped__

    def local(*args):
        sx, sy, sz = raw(*args)
        return sx[None], sy[None], sz[None]  # leading shard axis for out spec

    return local


@functools.lru_cache(maxsize=None)
def _agg_entry(n: int):
    import jax
    from jax.experimental.shard_map import shard_map

    in_specs = tuple(partition_spec_for(nm, flat=True) for nm in _AGG_ARG_NAMES)
    out_spec = partition_spec_for("partials", flat=True)
    fn = shard_map(
        _agg_local_kernel(), mesh=_mesh(n), in_specs=in_specs,
        out_specs=(out_spec, out_spec, out_spec),
    )
    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _agg_entry_2d(r: int, c: int):
    import jax
    from jax.experimental.shard_map import shard_map

    in_specs = tuple(partition_spec_for(nm) for nm in _AGG_ARG_NAMES)
    out_spec = partition_spec_for("partials")
    fn = shard_map(
        _agg_local_kernel(), mesh=_mesh2d(r, c), in_specs=in_specs,
        out_specs=(out_spec, out_spec, out_spec),
    )
    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _agg_entry_slice(r: int, c: int, idx: int):
    import jax
    from jax.experimental.shard_map import shard_map

    in_specs = tuple(partition_spec_for(nm, flat=True) for nm in _AGG_ARG_NAMES)
    out_spec = partition_spec_for("partials", flat=True)
    fn = shard_map(
        _agg_local_kernel(), mesh=_slice_mesh(r, c, idx), in_specs=in_specs,
        out_specs=(out_spec, out_spec, out_spec),
    )
    return jax.jit(fn)


def dispatch_aggregate_partials(pxn, pyn, rxn, ryn, c_digits, a_digits):
    """Batch-dim sharded aggregate partials: pads lanes to a shard
    multiple (all-zero rows select only identity table entries, so pads
    contribute nothing), returns (Sx, Sy, Sz) each [n, 64, W] — one
    window-sum stack per shard, combined by the reduce/finish kernel.

    Slice pinning works exactly as dispatch_verify: a thread inside
    ``slice_lane(i)`` runs on slice i's devices only.
    """
    from kaspa_tpu.resilience.faults import FAULTS

    FAULTS.fire("device.mesh.dispatch")
    total = active_size()
    g = _grid
    pin = getattr(_slice_tls, "idx", None) if g else None
    if g is None:
        n, entry = total, _agg_entry(total)
    elif pin is not None:
        n, entry = g[1], _agg_entry_slice(g[0], g[1], pin)
    else:
        n, entry = total, _agg_entry_2d(g[0], g[1])
    pxn = np.asarray(pxn)
    b = pxn.shape[0]
    m = -(-b // n) * n  # ceil to shard multiple
    args = (
        _pad_rows(pxn, m),
        _pad_rows(pyn, m),
        _pad_rows(rxn, m),
        _pad_rows(ryn, m),
        _pad_rows(c_digits, m),
        _pad_rows(a_digits, m),
    )
    sx, sy, sz = entry(*args)
    _observe("schnorr_aggregate", b, m, n)
    if pin is not None:
        _SLICE_DISPATCHES.inc(str(pin))
        _SLICE_JOBS.inc(str(pin), b)
    return sx, sy, sz


# --- muhash tree product ---------------------------------------------------


@functools.lru_cache(maxsize=None)
def _tree_entry(n: int, levels: int):
    """Cached shard_map-jitted local tree product: each shard reduces its
    [bucket, 192] slice to one canonical U3072 element ([1, 192])."""
    import jax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from kaspa_tpu.ops import bigint as bi

    F = bi.F3072

    def local_tree(x):
        for _ in range(levels):
            half = x.shape[0] // 2
            x = bi.mul(F, x[:half], x[half:])
        return bi.canon(F, x[0])[None, :]

    fn = shard_map(local_tree, mesh=_mesh(n), in_specs=P("shard", None), out_specs=P("shard", None))
    return jax.jit(fn)


def dispatch_tree_product(elements: np.ndarray) -> int:
    """Sharded U3072 product: [N, 192] int32 limbs -> python int mod the
    muhash prime.  Mirrors `muhash_ops.batch_product_device`'s bucket
    policy per shard (one compiled shape per (mesh, bucket)); each shard's
    partial product combines on host with one 3072-bit multiply.
    """
    from kaspa_tpu.ops import bigint as bi
    from kaspa_tpu.ops.muhash_ops import BUCKETS

    F = bi.F3072
    n = active_size()
    elements = np.asarray(elements)
    total = elements.shape[0]
    if total == 0:
        return 1
    result = 1
    pos = 0
    while pos < total:
        remaining = total - pos
        per_shard = -(-remaining // n)
        # largest bucket that fits the per-shard remainder, else the
        # smallest bucket (identity-padded) — same shape discipline as the
        # single-device path, scaled by the mesh
        fitting = [bk for bk in BUCKETS if bk <= per_shard]
        bucket = fitting[-1] if fitting else BUCKETS[0]
        take = min(bucket * n, remaining)
        chunk = elements[pos : pos + take]
        padded = np.tile(np.asarray(F.one, dtype=np.int32), (bucket * n, 1))
        padded[: chunk.shape[0]] = chunk
        partials = np.asarray(_tree_entry(n, bucket.bit_length() - 1)(padded))
        for row in partials:
            result = result * bi.limbs_to_int(row) % F.modulus
        _observe("muhash", take, bucket * n, n)
        pos += take
    return result
