"""Batched secp256k1 on TPU: field/point arithmetic, Schnorr+ECDSA verify.

TPU-native replacement for the reference's libsecp256k1 (C) usage in
`crypto/txscript/src/lib.rs:885-935` (check_schnorr_signature /
check_ecdsa_signature).  The batch dimension is the leading axis; everything
is jit/vmap/shard_map-safe with static shapes.
"""

from kaspa_tpu.ops.secp256k1.points import (  # noqa: F401
    G_AFFINE,
    dual_scalar_mul_base,
    point_add,
    point_double,
)
from kaspa_tpu.ops.secp256k1.verify import (  # noqa: F401
    ecdsa_verify_kernel,
    schnorr_verify_kernel,
)
