"""Batched complete projective point arithmetic on secp256k1 (a=0, b=7).

Points are pytrees ``(X, Y, Z)`` of int32 lazy limbs [..., 16] (see
ops/bigint.py) in homogeneous projective coordinates; the identity is
(0 : 1 : 0) and needs no flag.  Formulas are the *complete* addition laws of
Renes–Costello–Batina 2016 (algorithms 7/8/9 for a=0), valid for ALL input
pairs on a prime-order curve — including P == Q, P == -Q and the identity.
Completeness matters doubly here: consensus demands exactness under
adversarial inputs (a wrong validity bit is a chain split), and branch-free
total functions are exactly what XLA wants.

Replaces the EC internals of libsecp256k1 used by the reference's signature
checks (crypto/txscript/src/lib.rs:885-935).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from kaspa_tpu.ops import bigint as bi

FP = bi.FP
B3 = 21  # 3*b for y^2 = x^3 + 7

GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8
G_AFFINE = (GX, GY)

WINDOW = 4
N_WINDOWS = 256 // WINDOW  # 64 windows of 4 bits, MSB-first


def point_identity(shape_prefix):
    zero = jnp.zeros((*shape_prefix, FP.W), dtype=jnp.int32)
    one = jnp.broadcast_to(jnp.asarray(FP.one), zero.shape).astype(jnp.int32)
    return (zero, one, zero)


def point_double(p):
    """RCB alg. 9 (a=0): 3M + 2S + 1*b3; complete."""
    x, y, z = p
    t0 = bi.sqr(FP, y)
    z3 = bi.mul_small(FP, t0, 8)
    t1 = bi.mul(FP, y, z)
    t2 = bi.mul_small(FP, bi.sqr(FP, z), B3)
    x3 = bi.mul(FP, t2, z3)
    y3 = bi.add(FP, t0, t2)
    z3 = bi.mul(FP, t1, z3)
    t0 = bi.sub(FP, t0, bi.mul_small(FP, t2, 3))
    y3 = bi.add(FP, x3, bi.mul(FP, t0, y3))
    x3 = bi.mul_small(FP, bi.mul(FP, t0, bi.mul(FP, x, y)), 2)
    return (x3, y3, z3)


def point_add(p, q):
    """RCB alg. 7 (a=0): 12M + 2*b3; complete for all inputs."""
    x1, y1, z1 = p
    x2, y2, z2 = q
    t0 = bi.mul(FP, x1, x2)
    t1 = bi.mul(FP, y1, y2)
    t2 = bi.mul(FP, z1, z2)
    t3 = bi.mul(FP, bi.add(FP, x1, y1), bi.add(FP, x2, y2))
    t3 = bi.sub(FP, t3, bi.add(FP, t0, t1))
    t4 = bi.mul(FP, bi.add(FP, y1, z1), bi.add(FP, y2, z2))
    t4 = bi.sub(FP, t4, bi.add(FP, t1, t2))
    x3 = bi.mul(FP, bi.add(FP, x1, z1), bi.add(FP, x2, z2))
    y3 = bi.sub(FP, x3, bi.add(FP, t0, t2))
    t0 = bi.mul_small(FP, t0, 3)
    t2 = bi.mul_small(FP, t2, B3)
    z3 = bi.add(FP, t1, t2)
    t1 = bi.sub(FP, t1, t2)
    y3 = bi.mul_small(FP, y3, B3)
    x3_out = bi.sub(FP, bi.mul(FP, t3, t1), bi.mul(FP, t4, y3))
    y3_out = bi.add(FP, bi.mul(FP, t1, z3), bi.mul(FP, y3, t0))
    z3_out = bi.add(FP, bi.mul(FP, z3, t4), bi.mul(FP, t0, t3))
    return (x3_out, y3_out, z3_out)


def point_add_mixed(p, q_affine):
    """RCB alg. 8 (a=0, Z2=1): 11M + 2*b3; complete except Q == identity
    (unrepresentable in affine — callers select around digit==0)."""
    x1, y1, z1 = p
    x2, y2 = q_affine
    t0 = bi.mul(FP, x1, x2)
    t1 = bi.mul(FP, y1, y2)
    t3 = bi.mul(FP, bi.add(FP, x2, y2), bi.add(FP, x1, y1))
    t3 = bi.sub(FP, t3, bi.add(FP, t0, t1))
    t4 = bi.add(FP, bi.mul(FP, y2, z1), y1)
    y3 = bi.add(FP, bi.mul(FP, x2, z1), x1)
    t0 = bi.mul_small(FP, t0, 3)
    t2 = bi.mul_small(FP, z1, B3)
    z3 = bi.add(FP, t1, t2)
    t1 = bi.sub(FP, t1, t2)
    y3 = bi.mul_small(FP, y3, B3)
    x3_out = bi.sub(FP, bi.mul(FP, t3, t1), bi.mul(FP, t4, y3))
    y3_out = bi.add(FP, bi.mul(FP, t1, z3), bi.mul(FP, y3, t0))
    z3_out = bi.add(FP, bi.mul(FP, z3, t4), bi.mul(FP, t0, t3))
    return (x3_out, y3_out, z3_out)


def _g_multiples_table():
    """Host-precomputed affine multiples 1..15 of G (python ints).

    Entry 0 is a placeholder (G) — the ladder selects around digit == 0.
    """
    from kaspa_tpu.crypto import eclib

    pts = []
    acc = None
    for _ in range(15):
        acc = eclib.point_add(acc, (GX, GY))
        pts.append(acc)
    pts = [pts[0]] + pts  # index 0 placeholder
    xs = bi.ints_to_limbs([q[0] for q in pts], FP.W)
    ys = bi.ints_to_limbs([q[1] for q in pts], FP.W)
    return xs, ys


_GTAB_X, _GTAB_Y = _g_multiples_table()


def _build_p_table(px, py):
    """Per-batch projective multiples 0..15 of P. Returns [B, 16, W] arrays.

    Entry 0 is the true identity (0:1:0) — complete addition handles it.
    Entries 2..15 come from one lax.scan'd point_add rather than a fully
    unrolled chain: 14 adds in the jaxpr made XLA:CPU compile time grow
    superlinearly with the op count (tens of seconds per bucket), while
    the rolled form traces one add and compiles flat.  Identical math,
    identical limbs out."""
    one = jnp.broadcast_to(jnp.asarray(FP.one), px.shape).astype(jnp.int32)
    p1 = (px, py, one)
    ident = point_identity(px.shape[:-1])

    def step(acc, _):
        nxt = point_add(acc, p1)
        return nxt, nxt

    _, rest = jax.lax.scan(step, p1, None, length=14)  # 2P..15P, [14, B, W]
    cols = []
    for i in range(3):
        head = jnp.stack([ident[i], p1[i]], axis=-2)  # [B, 2, W]
        tail = jnp.moveaxis(rest[i], 0, -2)  # [B, 14, W]
        cols.append(jnp.concatenate([head, tail], axis=-2))  # [B, 16, W]
    return tuple(cols)


def _gather_tab(tab, digit):
    """Select table entry per batch element. digit: [B] int32 in [0,16)."""
    idx = digit[..., None, None]
    return tuple(jnp.take_along_axis(a, idx, axis=-2)[..., 0, :] for a in tab)


def dual_scalar_mul_base(px, py, g_digits, p_digits):
    """R = a*G + b*P with 4-bit MSB-first window digits of a and b.

    px, py: [B, W] limbs of P (affine, on-curve — host-validated);
    g_digits, p_digits: [B, 64] int32.  Shamir's trick: one shared doubling
    chain, two table additions per window (G mixed-affine, P projective).
    Returns projective (X, Y, Z); identity <=> Z == 0 (mod p).
    """
    ptab = _build_p_table(px, py)
    gtx = jnp.asarray(_GTAB_X)
    gty = jnp.asarray(_GTAB_Y)

    r0 = point_identity(px.shape[:-1])

    def body(w, r):
        for _ in range(WINDOW):
            r = point_double(r)
        gd = jax.lax.dynamic_slice_in_dim(g_digits, w, 1, axis=-1)[..., 0]
        pd = jax.lax.dynamic_slice_in_dim(p_digits, w, 1, axis=-1)[..., 0]
        ra = point_add_mixed(r, (gtx[gd], gty[gd]))
        sel = (gd == 0)[..., None]
        r = tuple(jnp.where(sel, a, b) for a, b in zip(r, ra))
        r = point_add(r, _gather_tab(ptab, pd))
        return r

    return jax.lax.fori_loop(0, N_WINDOWS, body, r0)


def to_affine(p):
    """Projective -> canonical affine limbs (x, y, is_identity)."""
    x, y, z = p
    zi = bi.inv(FP, z)
    xa = bi.canon(FP, bi.mul(FP, x, zi))
    ya = bi.canon(FP, bi.mul(FP, y, zi))
    inf = bi.is_zero(FP, z)
    return xa, ya, inf


def to_affine_batch(p):
    """Projective -> canonical affine limbs for a batch (leading axis).

    Same contract as ``to_affine``, but all Z inversions share one
    batch-affine Montgomery inversion (bigint.inv_batch): ~3(B-1) modular
    multiplies plus a single Fermat ladder instead of B ladders — the
    dominant per-element saving in the portable XLA verify lane.  Identity
    lanes (Z ≡ 0) keep zi == 0, matching ``inv``'s inv(0) == 0, so the
    returned (x, y) are (0, 0) there exactly as in the per-lane path.
    """
    x, y, z = p
    inf = bi.is_zero(FP, z)
    zi = bi.inv_batch(FP, z, zero_mask=inf)
    xa = bi.canon(FP, bi.mul(FP, x, zi))
    ya = bi.canon(FP, bi.mul(FP, y, zi))
    return xa, ya, inf


def scalar_digits_msb(k: int) -> np.ndarray:
    """Host: scalar -> 64 MSB-first 4-bit window digits."""
    return np.array([(k >> (256 - WINDOW * (i + 1))) & 0xF for i in range(N_WINDOWS)], dtype=np.int32)
