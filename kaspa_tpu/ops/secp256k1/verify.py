"""Jitted batched Schnorr/ECDSA verification kernels (device side).

The host (kaspa_tpu/crypto/secp.py) parses/validates encodings, lifts
pubkeys to affine coordinates, computes challenge scalars, and extracts
4-bit window digits; the device does the heavy dual-scalar ladder and the
final affine checks, returning a validity bitmask — the layout prescribed
by the north star (BASELINE.json): triples in, bitmask out.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from kaspa_tpu.observability import trace
from kaspa_tpu.observability.core import REGISTRY
from kaspa_tpu.ops import bigint as bi
from kaspa_tpu.ops.secp256k1 import points as pt
from kaspa_tpu.resilience.faults import FAULTS

FP = bi.FP
FN = bi.FN


def _jit_compile_counts() -> dict:
    """Actual jit cache sizes of the verify kernels — one entry per
    (shape, backend) compilation.  When the round-5 style "0.0
    verifies/sec" failure recurs, this says whether the device ever
    finished a compile at all."""
    out = {}
    pairs = [("schnorr", schnorr_verify_kernel), ("ecdsa", ecdsa_verify_kernel)]
    try:  # the aggregate lane's two kernels, when the module has loaded
        from kaspa_tpu.ops.secp256k1 import aggregate as _agg

        pairs.append(("aggregate_partials", _agg.aggregate_partials_kernel))
        pairs.append(("aggregate_finish", _agg.aggregate_reduce_finish_kernel))
    except Exception:  # noqa: BLE001
        pass
    for name, fn in pairs:
        try:
            out[name] = int(fn._cache_size())
        except Exception:  # noqa: BLE001 - jax internals may shift
            pass
    return {"jit_compiles": out}


REGISTRY.register_collector("secp", _jit_compile_counts)


def _use_pallas() -> bool:
    """The fused Mosaic ladder runs on real TPU backends; the XLA
    formulation remains the portable path (CPU mesh tests, fallback)."""
    if os.environ.get("KASPA_TPU_NO_PALLAS"):
        return False
    # "axon" is the tunneled-TPU plugin's platform name; any other backend
    # (cpu/gpu/...) takes the portable XLA formulation
    return jax.default_backend() in ("tpu", "axon")


def _scalars_to_digits(ks, b: int) -> np.ndarray:
    """Host: scalars -> [b, 64] MSB-first 4-bit window digits (padded).

    Elements are python ints or already-canonical 32-byte big-endian
    strings (the schnorr s column ships ``sig[32:]`` straight through,
    skipping the int round trip entirely); everything downstream of the
    single join is np.frombuffer bulk work.  Measured at B=8..16384
    against a log-depth shift-or bigint tree and a uint64-decompose numpy
    path: the one-join form is ~2-3x faster than either (CPython's
    to_bytes C path wins), and dropping the old loop's per-item ``int()``
    coercion is another 1.4-1.7x.  Shared by the ladder lane (s/e, u1/u2)
    and the aggregate lane's weight/combined-challenge digits.
    """
    out = np.zeros((b, 64), np.int32)
    if ks:
        raw = b"".join([k if type(k) is bytes else k.to_bytes(32, "big") for k in ks])
        arr = np.frombuffer(raw, dtype=np.uint8).reshape(len(ks), 32)
        dig = np.empty((len(ks), 64), np.uint8)
        dig[:, 0::2] = arr >> 4
        dig[:, 1::2] = arr & 0x0F
        out[: len(ks)] = dig
    return out


def schnorr_verify(px, py, r_canon, s_scalars, e_scalars, valid_in) -> np.ndarray:
    """Backend-dispatching batched Schnorr verify.

    px/py/r_canon: [B, 16] limb arrays; s_scalars/e_scalars: python-int
    scalar sequences (already reduced mod n); valid_in: [B] bool.
    """
    # raise/wedge/slow the whole batch here — above every backend path, so
    # the breaker in crypto/secp.py sees the failure whichever way it routes
    FAULTS.fire("device.verify")
    # separate point for supervised-hang drills: mode "hang" sleeps past the
    # watchdog deadline then completes, "wedge" sleeps then dies — either
    # way the batch must already have been requeued on the host lane
    FAULTS.fire("device.hang")
    from kaspa_tpu.ops import mesh

    n_mesh = mesh.active_size()
    if n_mesh == 1 and _use_pallas():
        from kaspa_tpu.ops.secp256k1.ladder_pallas import verify_batch_pallas

        with trace.span("secp.device_dispatch", kernel="schnorr_pallas"):
            return verify_batch_pallas(px, py, r_canon, s_scalars, e_scalars, valid_in, ecdsa=False)
    b = np.asarray(px).shape[0]
    # host marshal vs device dispatch split: when throughput collapses,
    # this localizes the stall to python packing or the XLA round trip
    with trace.span("secp.host_marshal", kernel="schnorr", batch=b):
        sd = _scalars_to_digits(s_scalars, b)
        ed = _scalars_to_digits(e_scalars, b)
    if n_mesh > 1:
        # mesh > 1 rides the portable XLA formulation sharded over the
        # device mesh (the fused Mosaic ladder stays the single-chip path)
        with trace.span("secp.device_dispatch", kernel="schnorr_mesh", batch=b, mesh=n_mesh):
            return mesh.dispatch_verify("schnorr", px, py, r_canon, sd, ed, valid_in)
    with trace.span("secp.device_dispatch", kernel="schnorr", batch=b):
        return np.asarray(schnorr_verify_kernel(px, py, r_canon, sd, ed, valid_in))


def ecdsa_verify(px, py, r_n_canon, u1_scalars, u2_scalars, valid_in) -> np.ndarray:
    """Backend-dispatching batched ECDSA verify (see schnorr_verify)."""
    FAULTS.fire("device.verify")
    FAULTS.fire("device.hang")
    from kaspa_tpu.ops import mesh

    n_mesh = mesh.active_size()
    if n_mesh == 1 and _use_pallas():
        from kaspa_tpu.ops.secp256k1.ladder_pallas import verify_batch_pallas

        with trace.span("secp.device_dispatch", kernel="ecdsa_pallas"):
            return verify_batch_pallas(px, py, r_n_canon, u1_scalars, u2_scalars, valid_in, ecdsa=True)
    b = np.asarray(px).shape[0]
    with trace.span("secp.host_marshal", kernel="ecdsa", batch=b):
        u1 = _scalars_to_digits(u1_scalars, b)
        u2 = _scalars_to_digits(u2_scalars, b)
    if n_mesh > 1:
        with trace.span("secp.device_dispatch", kernel="ecdsa_mesh", batch=b, mesh=n_mesh):
            return mesh.dispatch_verify("ecdsa", px, py, r_n_canon, u1, u2, valid_in)
    with trace.span("secp.device_dispatch", kernel="ecdsa", batch=b):
        return np.asarray(ecdsa_verify_kernel(px, py, r_n_canon, u1, u2, valid_in))


@jax.jit
def schnorr_verify_kernel(px, py, r_canon, s_digits, e_digits, valid_in):
    """BIP340: R = s*G + e*(-P); valid iff R finite, even-y, x(R) == r.

    px/py: [B, 16] limbs of lifted pubkey (even y);  r_canon: [B, 16]
    canonical limbs of sig r;  s_digits/e_digits: [B, 64] int32 4-bit MSB
    windows;  valid_in: [B] bool (host-side encoding checks).
    """
    py_neg = bi.neg(FP, py)
    r = pt.dual_scalar_mul_base(px, py_neg, s_digits, e_digits)
    # batch-affine Montgomery inversion: one Fermat ladder per batch
    # instead of one per lane (see points.to_affine_batch)
    xa, ya, inf = pt.to_affine_batch(r)
    ok = ~inf
    ok &= jnp.all(xa == r_canon, axis=-1)
    ok &= (ya[..., 0] & 1) == 0
    return ok & valid_in


@jax.jit
def ecdsa_verify_kernel(px, py, r_n_canon, u1_digits, u2_digits, valid_in):
    """ECDSA: R = u1*G + u2*P; valid iff R finite and x(R) mod n == r.

    u1 = z*s^-1 mod n, u2 = r*s^-1 mod n are computed host-side (cheap,
    n-field inversions are per-signature scalars).
    """
    r = pt.dual_scalar_mul_base(px, py, u1_digits, u2_digits)
    xa, _ya, inf = pt.to_affine_batch(r)
    x_mod_n = bi.canon(FN, xa)  # x < p < 2**256: reinterpret limbs mod n
    ok = ~inf
    ok &= jnp.all(x_mod_n == r_n_canon, axis=-1)
    return ok & valid_in
