"""Aggregated random-linear-combination Schnorr verification kernel.

Instead of B independent dual-scalar ladders (points.dual_scalar_mul_base,
~2 scalar muls x 64 windows per signature), the aggregate lane checks ONE
combined equation over the whole batch.  With per-signature random weights
a_i (host-derived, ChaCha-seeded from the batch transcript — see
crypto/secp.py), each BIP340 equation R_i = s_i*G - e_i*P_i folds into

    T  =  u*G  +  sum_i c_i*(-P_i)  +  sum_i a_i*(-R_i)          (== O)

where u = sum_i a_i*s_i mod n is a single host-side scalar, c_i = a_i*e_i
mod n, and R_i = lift_x(r_i) (even y).  All B signatures are valid iff T
is the identity; random 128-bit weights bound the probability that a set
of invalid signatures conspires to cancel at 2^-128 (the FPGA
ECDSA-engine batching trick, mapped onto this repo's windowed ladder).

Multi-scalar shape (Strauss with a shared doubling chain): every lane
gathers its window summand from its own 16-entry table
(points._build_p_table — entry 0 is the true identity, so a zero digit
contributes nothing), the per-window summands tree-reduce across the
batch axis with the *complete* addition law, and one final 64-window
Horner pass (4 doublings + one add per window, plus the mixed-affine u*G
add) collapses the window sums.  Field-mul count per lane: 2 tables
(~336M) + the a/c gathers' adds (~1.5 adds/window amortized) versus the
ladder's ~43M/window — the doubling chain, previously paid per lane, is
paid once per *batch*.

The weights are 128-bit, so their 4-bit MSB-first digit columns 0..31 are
statically zero: the R-term gathers and adds run only for windows 32..63
(`A_WINDOWS`), saving half the R-side work.

Sharding: `aggregate_partials_kernel` maps cleanly onto the mesh — each
shard reduces its lanes to one [64] window-sum vector, and the [n, 64]
stack reduces + Horner-finishes in `aggregate_reduce_finish_kernel`
(tiny, runs unsharded).  `ops/mesh.py:dispatch_aggregate_partials` owns
the shard_map plumbing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from kaspa_tpu.ops import bigint as bi
from kaspa_tpu.ops.secp256k1 import points as pt

FP = bi.FP

# weight scalars are 128-bit -> only the low 32 of the 64 MSB-first 4-bit
# windows can be non-zero; the host ships a_digits already sliced to these
A_WINDOWS = pt.N_WINDOWS // 2  # windows 32..63


def _gather_window_points(tab, digits):
    """Per-lane per-window table select.

    tab: (xs, ys, zs) each [B, 16, W];  digits: [B, K] int32 in [0, 16).
    Returns (X, Y, Z) each [B, K, W] — lane b's window w summand.  Digit 0
    selects the table's true-identity entry, so zero-weight (pad/invalid)
    lanes contribute nothing anywhere.
    """
    idx = digits[..., None, None]  # [B, K, 1, 1] -> broadcasts over W
    return tuple(
        jnp.take_along_axis(a[:, None, :, :], idx, axis=-2)[..., 0, :] for a in tab
    )


def _tree_reduce_lanes(p):
    """Sum a [B, K, W] point batch over the lane axis with the complete
    addition law: log2(B) levels of halved point_adds -> [K, W].  Odd
    levels pad with the identity (complete addition absorbs it).  The
    graph holds one point_add per level, so keep B small here (shard
    stacks, scan-group remainders) — big lane axes go through
    _scan_reduce_lanes."""
    x, y, z = p
    while x.shape[0] > 1:
        if x.shape[0] % 2:
            ident = pt.point_identity(x.shape[1:-1])
            x = jnp.concatenate([x, ident[0][None]], axis=0)
            y = jnp.concatenate([y, ident[1][None]], axis=0)
            z = jnp.concatenate([z, ident[2][None]], axis=0)
        h = x.shape[0] // 2
        x, y, z = pt.point_add((x[:h], y[:h], z[:h]), (x[h:], y[h:], z[h:]))
    return x[0], y[0], z[0]


# lane-fold accumulator width: wide enough to keep the per-step point_add
# vectorized (8 lanes x 64 windows = 512 parallel adds), short enough that
# the trailing unrolled tree is 3 levels
_SCAN_GROUP = 8


def _scan_reduce_lanes(p):
    """Sum a [B, K, W] point batch over the lane axis -> [K, W], with a
    graph whose size does NOT grow with B.

    A fully unrolled binary tree puts log2(B) distinct point_adds in the
    jaxpr and XLA:CPU compile time blows up superlinearly in the bucket
    (measured: ~45s at B=8 -> ~4m50s at B=16).  Instead the lanes fold
    into _SCAN_GROUP parallel accumulators through ONE lax.scan'd
    complete point_add, then a 3-level tree collapses the group.  Runtime
    work is B + G - 2 lane-adds vs the tree's B - 1 — noise — and the
    compile cost is flat across buckets.
    """
    x, y, z = p
    b = x.shape[0]
    g = min(b, _SCAN_GROUP)
    if b % g:  # pad to a whole number of scan steps; identity lanes absorb
        pad = g - b % g
        ident = pt.point_identity((pad,) + x.shape[1:-1])
        x = jnp.concatenate([x, ident[0]], axis=0)
        y = jnp.concatenate([y, ident[1]], axis=0)
        z = jnp.concatenate([z, ident[2]], axis=0)
    xs = tuple(a.reshape(-1, g, *a.shape[1:]) for a in (x, y, z))
    acc = pt.point_identity((g,) + x.shape[1:-1])

    def step(acc, lanes):
        return pt.point_add(acc, lanes), None

    acc, _ = jax.lax.scan(step, acc, xs)
    return _tree_reduce_lanes(acc)


@jax.jit
def aggregate_partials_kernel(pxn, pyn, rxn, ryn, c_digits, a_digits):
    """Per-window multi-scalar partial sums for one (shard's) lane slice.

    pxn/pyn: [B, W] limbs of -P_i (negated lifted pubkey);
    rxn/ryn: [B, W] limbs of -R_i (negated lift_x(r_i));
    c_digits: [B, 64] digits of c_i = a_i*e_i mod n;
    a_digits: [B, 32] digits of a_i (windows 32..63 only — see A_WINDOWS).
    Invalid/pad lanes carry zero digits (their garbage tables are never
    selected).  Returns (Sx, Sy, Sz) each [64, W]: window w's summand sum.
    """
    ptab = pt._build_p_table(pxn, pyn)
    rtab = pt._build_p_table(rxn, ryn)
    cx, cy, cz = _gather_window_points(ptab, c_digits)  # [B, 64, W]
    ar = _gather_window_points(rtab, a_digits)  # [B, 32, W]
    lo = (cx[:, :A_WINDOWS], cy[:, :A_WINDOWS], cz[:, :A_WINDOWS])
    hi = pt.point_add((cx[:, A_WINDOWS:], cy[:, A_WINDOWS:], cz[:, A_WINDOWS:]), ar)
    per_lane = tuple(jnp.concatenate([a, b], axis=1) for a, b in zip(lo, hi))
    return _scan_reduce_lanes(per_lane)


@jax.jit
def aggregate_reduce_finish_kernel(sx, sy, sz, u_digits):
    """Combine shard partials and run the shared Horner chain.

    sx/sy/sz: [n, 64, W] stacked per-shard window sums (n == 1 off-mesh);
    u_digits: [64] int32 digits of u = sum a_i*s_i mod n.  Returns a
    scalar bool: True iff  u*G + sum_w 16^(63-w) * S_w  is the identity —
    i.e. every aggregated signature equation holds.
    """
    s = _tree_reduce_lanes((sx, sy, sz))  # [64, W] triple
    sxw, syw, szw = s
    gtx = jnp.asarray(pt._GTAB_X)
    gty = jnp.asarray(pt._GTAB_Y)
    r0 = pt.point_identity(())

    def body(w, r):
        for _ in range(pt.WINDOW):
            r = pt.point_double(r)
        gd = jax.lax.dynamic_slice_in_dim(u_digits, w, 1, axis=-1)[..., 0]
        ra = pt.point_add_mixed(r, (gtx[gd], gty[gd]))
        sel = (gd == 0)[..., None]
        r = tuple(jnp.where(sel, a, b) for a, b in zip(r, ra))
        sw = tuple(
            jax.lax.dynamic_slice_in_dim(a, w, 1, axis=0)[0] for a in (sxw, syw, szw)
        )
        return pt.point_add(r, sw)

    t = jax.lax.fori_loop(0, pt.N_WINDOWS, body, r0)
    # identity <=> Z == 0 mod p; no affine lift needed for the yes/no check
    return bi.is_zero(FP, t[2])


def aggregate_check(pxn, pyn, rxn, ryn, c_digits, a_digits, u_digits) -> bool:
    """Single-dispatch aggregate check for one device batch (mesh-aware).

    The mesh path ships the partials kernel through shard_map (each shard
    reduces its lane slice) and finishes on the [n, 64] stack; off-mesh the
    same two kernels run back to back with n == 1, so masks and compile
    shapes stay uniform across layouts.
    """
    from kaspa_tpu.ops import mesh

    if mesh.active_size() > 1:
        sx, sy, sz = mesh.dispatch_aggregate_partials(
            pxn, pyn, rxn, ryn, c_digits, a_digits
        )
    else:
        sx, sy, sz = aggregate_partials_kernel(pxn, pyn, rxn, ryn, c_digits, a_digits)
        sx, sy, sz = sx[None], sy[None], sz[None]
    return bool(aggregate_reduce_finish_kernel(sx, sy, sz, u_digits))
