"""Fused Pallas TPU kernel for batched dual-scalar EC verification.

The XLA formulation in ops/secp256k1/{points,verify}.py emits ~3.5k HLO ops
per ladder window and materialises every intermediate in HBM — measured
~0.44 ms per field mul at B=16k, entirely HBM-bound.  This kernel runs the
WHOLE verification — P-table build, 64-window Shamir ladder, Fermat
inversion, canonicalisation and the final affine checks — inside one
`pallas_call`, so all limb state stays VMEM-resident across the windows
(the round-1 handoff's top perf lever).

Layout choices, dictated by TPU tiling:

- Transposed limbs: device arrays are ``[limbs, batch]`` — the batch rides
  the 128-wide lane dimension (every op vectorises across lanes), limbs sit
  on sublanes where carry shifts are cheap static slices.
- Radix 2**8, 32 limbs per 256-bit element (int32 carriers).  The smaller
  radix removes the 8-bit split/recombine steps that the 2**16-radix XLA
  path needs around every multiply: schoolbook columns bound by
  64 * (2**9)**2 < 2**25 stay comfortably inside int32, and carry rounds
  are plain shift/mask ops.
- Complete Renes-Costello-Batina point formulas (same as points.py) — no
  data-dependent branches, which is exactly what Mosaic wants.

Replaces the hot loop of libsecp256k1 batch verification used by the
reference's parallel script checks
(consensus/src/processes/transaction_validator/tx_validation_in_utxo_context.rs:206-223,
crypto/txscript/src/lib.rs:885-935) with a TPU-resident dataflow.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kaspa_tpu.ops import bigint as bi

W8 = 32  # 8-bit limbs per 256-bit element
BLK = 256  # batch lanes per grid step
N_WIN = 33  # 4-bit windows per GLV half-scalar (|k1|,|k2| < 2**132)

SECP_P = bi.SECP_P
SECP_N = bi.SECP_N
_C_P = (1 << 256) - SECP_P  # 2**32 + 977
_C_N = (1 << 256) - SECP_N

B3 = 21  # 3*b for y^2 = x^3 + 7


def _c_digits(c: int) -> tuple[int, ...]:
    out = []
    while c:
        out.append(c & 0xFF)
        c >>= 8
    return tuple(out)


_C8_P = _c_digits(_C_P)
_C8_N = _c_digits(_C_N)


def int_to_limbs8(v: int) -> np.ndarray:
    out = np.zeros(W8, dtype=np.int32)
    for i in range(W8):
        out[i] = v & 0xFF
        v >>= 8
    assert v == 0
    return out


def _m_limbs8(m: int) -> np.ndarray:
    return int_to_limbs8(m).reshape(W8, 1)


_MP8 = _m_limbs8(SECP_P)
_MN8 = _m_limbs8(SECP_N)

# --- GLV endomorphism -------------------------------------------------------
# secp256k1 has an order-3 automorphism phi(x, y) = (beta*x, y) acting as
# scalar multiplication by lambda; splitting each 256-bit scalar into two
# signed ~128-bit halves over the reduced lattice below halves the shared
# doubling chain (64 -> 33 windows).  The constants are validated here, not
# trusted: lambda**3 == 1 (mod n), beta**3 == 1 (mod p), phi(G) == lambda*G.

GLV_LAMBDA = 0x5363AD4CC05C30E0A5261C028812645A122E22EA20816678DF02967C1B23BD72
GLV_BETA = 0x7AE96A2B657C07106E64479EAC3434E99CF0497512F58995C1396C28719501EE
# Lagrange-Gauss reduced basis of {(x, y) : x + y*lambda == 0 (mod n)}
_GLV_U = (64502973549206556628585045361533709077, -303414439467246543595250775667605759171)
_GLV_V = (367917413016453100223835821029139468248, 64502973549206556628585045361533709077)
_GLV_DET = _GLV_U[0] * _GLV_V[1] - _GLV_V[0] * _GLV_U[1]

assert pow(GLV_LAMBDA, 3, SECP_N) == 1 and GLV_LAMBDA != 1
assert pow(GLV_BETA, 3, SECP_P) == 1 and GLV_BETA != 1
assert (_GLV_U[0] + _GLV_U[1] * GLV_LAMBDA) % SECP_N == 0
assert (_GLV_V[0] + _GLV_V[1] * GLV_LAMBDA) % SECP_N == 0


def _rdiv(a: int, b: int) -> int:
    """Exact round(a/b) for ints (b > 0 after normalisation)."""
    if b < 0:
        a, b = -a, -b
    return (2 * a + b) // (2 * b)


def glv_split(k: int) -> tuple[int, int]:
    """k -> (k1, k2), k1 + k2*lambda == k (mod n), |k1|,|k2| <~ 2**128."""
    c1 = _rdiv(k * _GLV_V[1], _GLV_DET)
    c2 = _rdiv(-k * _GLV_U[1], _GLV_DET)
    k1 = k - c1 * _GLV_U[0] - c2 * _GLV_V[0]
    k2 = -(c1 * _GLV_U[1] + c2 * _GLV_V[1])
    return k1, k2


# G / phi(G) multiples tables (1..15, entry 0 placeholder), transposed [W8, 16]
def _gtab8():
    from kaspa_tpu.crypto import eclib

    assert eclib.point_mul(eclib.G, GLV_LAMBDA) == ((GLV_BETA * eclib.GX) % SECP_P, eclib.GY)
    pts = []
    acc = None
    for _ in range(15):
        acc = eclib.point_add(acc, (eclib.GX, eclib.GY))
        pts.append(acc)
    pts = [pts[0]] + pts
    gx = np.stack([int_to_limbs8(q[0]) for q in pts], axis=1)  # [W8, 16]
    gxb = np.stack([int_to_limbs8(q[0] * GLV_BETA % SECP_P) for q in pts], axis=1)
    gy = np.stack([int_to_limbs8(q[1]) for q in pts], axis=1)
    return gx, gxb, gy


_GTAB8_X, _GTAB8_XB, _GTAB8_Y = _gtab8()
_BETA8 = int_to_limbs8(GLV_BETA).reshape(W8, 1)



# ---------------------------------------------------------------------------
# transposed radix-2**8 field arithmetic on [..limbs.., lanes] int32 values
# ---------------------------------------------------------------------------


def _zrows(n, like):
    return jnp.zeros((n, like.shape[-1]), dtype=jnp.int32)


def _shift_rows(x, lo: int, hi: int):
    """Pad x with `lo` zero rows before and `hi` after (pure concat: Mosaic
    has no scatter, so shifted adds are built from concatenation)."""
    parts = []
    if lo:
        parts.append(_zrows(lo, x))
    parts.append(x)
    if hi:
        parts.append(_zrows(hi, x))
    return jnp.concatenate(parts, axis=0) if len(parts) > 1 else x


def _carry_round(x):
    """One carry round; widens by one limb.  [K, L] -> [K+1, L]."""
    limb = x & 0xFF
    carry = x >> 8  # arithmetic shift: signed-safe
    return _shift_rows(limb, 0, 1) + _shift_rows(carry, 1, 0)


def _carry2(x):
    return _carry_round(_carry_round(x))


def _conv(a, b):
    """Schoolbook product columns: [Ka, L] x [Kb, L] -> [Ka+Kb-1, L].

    Unrolled shifted multiply-accumulate; all operands VMEM/register
    resident inside the kernel, so the unroll is pure VPU work.
    """
    ka, kb = a.shape[0], b.shape[0]
    out = jnp.zeros((ka + kb - 1, a.shape[1]), dtype=jnp.int32)
    for i in range(ka):
        out = out + _shift_rows(a[i : i + 1] * b, i, ka - 1 - i)
    return out


def _conv_sqr(a):
    """Squaring columns via symmetry: a_i*a_j pairs (i<j) counted once and
    doubled, so ~half the MACs of `_conv(a, a)`.  [K, L] -> [2K-1, L].

    Row i contributes a_i * [a_i, 2a_{i+1}, .., 2a_{K-1}] at offset 2i;
    bound: 255 * 510 * K < 2**23 per lazy column — far inside int32.
    """
    ka = a.shape[0]
    a2 = a * 2
    out = jnp.zeros((2 * ka - 1, a.shape[1]), dtype=jnp.int32)
    for i in range(ka):
        v = a[i : i + 1] if i + 1 == ka else jnp.concatenate([a[i : i + 1], a2[i + 1 :]], axis=0)
        out = out + _shift_rows(a[i : i + 1] * v, 2 * i, ka - 1 - i)
    return out


def _mul_c(c8: tuple, x):
    """x * c for the special-form modulus complement c (few 8-bit digits)."""
    k = x.shape[0]
    nc = len(c8)
    out = jnp.zeros((k + nc - 1, x.shape[1]), dtype=jnp.int32)
    for j, d in enumerate(c8):
        if d:
            out = out + _shift_rows(x * d, j, nc - 1 - j)
    return _carry2(out)


def _fold(c8: tuple, x):
    """Reduce any width to W8 limbs preserving value mod m."""
    while x.shape[0] > W8:
        lo, hi = x[:W8], x[W8:]
        prod = _mul_c(c8, hi)
        if prod.shape[0] <= W8:
            x = lo + _shift_rows(prod, 0, W8 - prod.shape[0])
        else:
            x = jnp.concatenate([prod[:W8] + lo, prod[W8:]], axis=0)
    return x


def _tighten(c8: tuple, x):
    return _fold(c8, _carry2(x))


def _mul(a, b, c8=_C8_P):
    x = _fold(c8, _carry2(_conv(a, b)))
    return _fold(c8, _carry2(x))


def _sqr(a, c8=_C8_P):
    x = _fold(c8, _carry2(_conv_sqr(a)))
    return _fold(c8, _carry2(x))


def _add(a, b, c8=_C8_P):
    return _tighten(c8, a + b)


def _sub(a, b, c8=_C8_P):
    return _tighten(c8, a - b)


def _mul_small(a, k: int, c8=_C8_P):
    return _tighten(c8, a * k)


def _neg(a, c8=_C8_P):
    return _tighten(c8, -a)


def _scan_carry(x):
    """Exact carry: [W, L] lazy -> ([W, L] limbs in [0,256), [1, L] top)."""
    carry = jnp.zeros_like(x[:1])
    outs = []
    for i in range(x.shape[0]):
        v = x[i : i + 1] + carry
        outs.append(v & 0xFF)
        carry = v >> 8
    return jnp.concatenate(outs, axis=0), carry


def _cond_sub_m(m8, x):
    d, top = _scan_carry(x - m8)
    return jnp.where(top >= 0, d, x)


def _canon(x, m8, c8=_C8_P):
    """Full canonicalisation into [0, m); mirrors bigint.canon's rounds."""
    base, t = _scan_carry(x)
    nc = len(c8)
    for _ in range(3):
        corr = jnp.concatenate(
            [t * d for d in c8] + [_zrows(W8 - nc, t)], axis=0
        )
        base, t = _scan_carry(base + corr)
    out = _cond_sub_m(m8, base)
    return _cond_sub_m(m8, out)


# Fermat inversion addition chain: (steps of (squarings, multiplicand)).
# 255 squarings + 15 multiplies instead of square-and-multiply's ~495 ops
# (p-2 is mostly 1-bits).  Same chain shape libsecp256k1 uses for its
# field inverse; verified symbolically below by replaying the chain on
# exponents and checking the result equals p-2 exactly.
_INV_CHAIN = (
    (1, "x"),      # x2  = x^3
    (1, "x"),      # x3  = x^7
    (3, "x3"),     # x6
    (3, "x3"),     # x9
    (2, "x2"),     # x11
    (11, "x11"),   # x22
    (22, "x22"),   # x44
    (44, "x44"),   # x88
    (88, "x88"),   # x176
    (44, "x44"),   # x220
    (3, "x3"),     # x223
    (23, "x22"),
    (5, "x"),
    (3, "x2"),
    (2, "x"),
)
_INV_NAMES = ("x2", "x3", "x6", "x9", "x11", "x22", "x44", "x88", "x176", "x220", "x223")


def _chain_exponent() -> int:
    exps = {"x": 1}
    e = 1
    for step, (n, name) in enumerate(_INV_CHAIN):
        e = (e << n) + exps[name]
        if step < len(_INV_NAMES):
            exps[_INV_NAMES[step]] = e
    return e


assert _chain_exponent() == SECP_P - 2


def _inv(x):
    """x**(p-2) via the fixed addition chain (255 S + 15 M)."""

    def pw(v, n):
        if n <= 4:
            for _ in range(n):
                v = _sqr(v)
            return v
        return jax.lax.fori_loop(0, n, lambda _i, a: _sqr(a), v)

    vals = {"x": x}
    acc = x
    for step, (n, name) in enumerate(_INV_CHAIN):
        acc = _mul(pw(acc, n), vals[name])
        if step < len(_INV_NAMES):
            vals[_INV_NAMES[step]] = acc
    return acc


# ---------------------------------------------------------------------------
# complete projective point ops (Renes-Costello-Batina, a=0, b=7)
# ---------------------------------------------------------------------------


def _pt_identity(lanes):
    zero = jnp.zeros((W8, lanes), dtype=jnp.int32)
    one = jnp.concatenate([jnp.ones((1, lanes), jnp.int32), zero[1:]], axis=0)
    return (zero, one, zero)


def _pt_double(p):
    x, y, z = p
    t0 = _sqr(y)
    z3 = _mul_small(t0, 8)
    t1 = _mul(y, z)
    t2 = _mul_small(_sqr(z), B3)
    x3 = _mul(t2, z3)
    y3 = _add(t0, t2)
    z3 = _mul(t1, z3)
    t0 = _sub(t0, _mul_small(t2, 3))
    y3 = _add(x3, _mul(t0, y3))
    x3 = _mul_small(_mul(t0, _mul(x, y)), 2)
    return (x3, y3, z3)


def _pt_add(p, q):
    x1, y1, z1 = p
    x2, y2, z2 = q
    t0 = _mul(x1, x2)
    t1 = _mul(y1, y2)
    t2 = _mul(z1, z2)
    t3 = _mul(_add(x1, y1), _add(x2, y2))
    t3 = _sub(t3, _add(t0, t1))
    t4 = _mul(_add(y1, z1), _add(y2, z2))
    t4 = _sub(t4, _add(t1, t2))
    x3 = _mul(_add(x1, z1), _add(x2, z2))
    y3 = _sub(x3, _add(t0, t2))
    t0 = _mul_small(t0, 3)
    t2 = _mul_small(t2, B3)
    z3 = _add(t1, t2)
    t1 = _sub(t1, t2)
    y3 = _mul_small(y3, B3)
    x3_out = _sub(_mul(t3, t1), _mul(t4, y3))
    y3_out = _add(_mul(t1, z3), _mul(y3, t0))
    z3_out = _add(_mul(z3, t4), _mul(t0, t3))
    return (x3_out, y3_out, z3_out)


def _pt_add_mixed(p, q_affine):
    x1, y1, z1 = p
    x2, y2 = q_affine
    t0 = _mul(x1, x2)
    t1 = _mul(y1, y2)
    t3 = _mul(_add(x2, y2), _add(x1, y1))
    t3 = _sub(t3, _add(t0, t1))
    t4 = _add(_mul(y2, z1), y1)
    y3 = _add(_mul(x2, z1), x1)
    t0 = _mul_small(t0, 3)
    t2 = _mul_small(z1, B3)
    z3 = _add(t1, t2)
    t1 = _sub(t1, t2)
    y3 = _mul_small(y3, B3)
    x3_out = _sub(_mul(t3, t1), _mul(t4, y3))
    y3_out = _add(_mul(t1, z3), _mul(y3, t0))
    z3_out = _add(_mul(z3, t4), _mul(t0, t3))
    return (x3_out, y3_out, z3_out)


# ---------------------------------------------------------------------------
# the kernel
# ---------------------------------------------------------------------------


def _select_ptab(tabx, taby, tabz, digit):
    """One-hot gather of per-lane table entries. digit: [1, L] int32."""
    lanes = digit.shape[1]
    gx = jnp.zeros((W8, lanes), dtype=jnp.int32)
    gy = jnp.zeros((W8, lanes), dtype=jnp.int32)
    gz = jnp.zeros((W8, lanes), dtype=jnp.int32)
    for e in range(16):
        m = (digit == e).astype(jnp.int32)
        gx = gx + tabx[e].reshape(W8, lanes) * m
        gy = gy + taby[e].reshape(W8, lanes) * m
        gz = gz + tabz[e].reshape(W8, lanes) * m
    return gx, gy, gz


def _select_gtab(gtx, gty, digit):
    lanes = digit.shape[1]
    gx = jnp.zeros((W8, lanes), dtype=jnp.int32)
    gy = jnp.zeros((W8, lanes), dtype=jnp.int32)
    for e in range(16):
        m = (digit == e).astype(jnp.int32)  # [1, L]; sublane-broadcasts below
        gx = gx + jnp.broadcast_to(gtx[:, e : e + 1], (W8, lanes)) * m
        gy = gy + jnp.broadcast_to(gty[:, e : e + 1], (W8, lanes)) * m
    return gx, gy


def _cond_negate(y, sign_mask):
    """y -> -y mod p where sign_mask (int32 [1, L]) is 1."""
    yn = _neg(y)
    return yn * sign_mask + y * (1 - sign_mask)


def _verify_kernel(
    ecdsa: bool, gtx_ref, gtxb_ref, gty_ref, mp_ref, mn_ref, beta_ref,
    px_ref, py_ref, rc_ref, g1_ref, g2_ref, p1_ref, p2_ref, sgn_ref, vin_ref,
    out_ref, tabx, tabxb, taby, tabz,
):
    """GLV quad-scalar ladder: R = (g1 + lam*g2)*G + (p1 + lam*p2)*P.

    Four signed ~128-bit digit streams share one 33-window doubling chain:
    G and phi(G) add mixed-affine from constant tables; P and phi(P) add
    projective from the per-lane scratch tables (phi only rescales X by
    beta, so the phi tables share Y/Z).  sgn_ref row 0 packs the four
    half-scalar sign bits.
    """
    lanes = px_ref.shape[1]
    px = px_ref[:]
    py = py_ref[:]
    if not ecdsa:
        py = _neg(py)  # BIP340: R = s*G + e*(-P)

    # P multiples table 0..15 (entry 0 = identity; complete adds handle it)
    zero = jnp.zeros((W8, lanes), dtype=jnp.int32)
    one = jnp.concatenate([jnp.ones((1, lanes), jnp.int32), zero[1:]], axis=0)
    beta = jnp.broadcast_to(beta_ref[:], (W8, lanes))
    tabx[0] = zero
    tabxb[0] = zero
    taby[0] = one
    tabz[0] = zero
    tabx[1] = px
    tabxb[1] = _mul(px, beta)
    taby[1] = py
    tabz[1] = one

    def build(e, _):
        prev = (
            tabx[pl.ds(e - 1, 1)].reshape(W8, lanes),
            taby[pl.ds(e - 1, 1)].reshape(W8, lanes),
            tabz[pl.ds(e - 1, 1)].reshape(W8, lanes),
        )
        nx, ny, nz = _pt_add(prev, (px, py, one))
        tabx[pl.ds(e, 1)] = nx.reshape(1, W8, lanes)
        tabxb[pl.ds(e, 1)] = _mul(nx, beta).reshape(1, W8, lanes)
        taby[pl.ds(e, 1)] = ny.reshape(1, W8, lanes)
        tabz[pl.ds(e, 1)] = nz.reshape(1, W8, lanes)
        return 0

    jax.lax.fori_loop(2, 16, build, 0)

    gtx = gtx_ref[:]
    gtxb = gtxb_ref[:]
    gty = gty_ref[:]
    sgn = sgn_ref[0:1, :]

    def window(w, r):
        for _ in range(4):
            r = _pt_double(r)
        # fixed-base streams: G (digits g1) and phi(G) (digits g2)
        for dig_ref, xtab, bit in ((g1_ref, gtx, 0), (g2_ref, gtxb, 1)):
            gd = dig_ref[pl.ds(w, 1), :]
            gx, gy = _select_gtab(xtab, gty, gd)
            gy = _cond_negate(gy, (sgn >> bit) & 1)
            ra = _pt_add_mixed(r, (gx, gy))
            keep = (gd == 0).astype(jnp.int32)
            r = tuple(a * keep + b * (1 - keep) for a, b in zip(r, ra))
        # per-lane streams: P (digits p1) and phi(P) (digits p2)
        for dig_ref, xtab, bit in ((p1_ref, tabx, 2), (p2_ref, tabxb, 3)):
            pd = dig_ref[pl.ds(w, 1), :]
            qx, qy, qz = _select_ptab(xtab, taby, tabz, pd)
            qy = _cond_negate(qy, (sgn >> bit) & 1)
            r = _pt_add(r, (qx, qy, qz))
        return r

    x, y, z = jax.lax.fori_loop(0, N_WIN, window, _pt_identity(lanes))

    mp = mp_ref[:]
    zc = _canon(z, mp)
    inf = jnp.all(zc == 0, axis=0, keepdims=True)
    zi = _inv(z)
    xa = _canon(_mul(x, zi), mp)
    if ecdsa:
        # x mod n: x < p < 2n, so a single conditional subtract suffices
        xn = _cond_sub_m(mn_ref[:], xa)
        ok = jnp.all(xn == rc_ref[:], axis=0, keepdims=True)
    else:
        ok = jnp.all(xa == rc_ref[:], axis=0, keepdims=True)
        ya = _canon(_mul(y, zi), mp)
        ok = ok & ((ya[0:1] & 1) == 0)
    ok = ok & ~inf & (vin_ref[0:1] > 0)
    out_ref[:] = jnp.broadcast_to(ok.astype(jnp.int32), (8, lanes))


def _verify_kernel_plain(
    ecdsa: bool, gtx_ref, gty_ref, mp_ref, mn_ref,
    px_ref, py_ref, rc_ref, sd_ref, ed_ref, vin_ref, out_ref, tabx, taby, tabz,
):
    """Non-GLV dual-scalar ladder (64 unsigned 4-bit windows).

    The proven production path: the GLV quad-stream kernel above is ~25%
    lighter arithmetically but its Mosaic compile has not yet been validated
    on the tunneled device, so it stays opt-in (KASPA_TPU_GLV=1)."""
    lanes = px_ref.shape[1]
    px = px_ref[:]
    py = py_ref[:]
    if not ecdsa:
        py = _neg(py)  # BIP340: R = s*G + e*(-P)

    zero = jnp.zeros((W8, lanes), dtype=jnp.int32)
    one = jnp.concatenate([jnp.ones((1, lanes), jnp.int32), zero[1:]], axis=0)
    tabx[0] = zero
    taby[0] = one
    tabz[0] = zero
    tabx[1] = px
    taby[1] = py
    tabz[1] = one

    def build(e, _):
        prev = (
            tabx[pl.ds(e - 1, 1)].reshape(W8, lanes),
            taby[pl.ds(e - 1, 1)].reshape(W8, lanes),
            tabz[pl.ds(e - 1, 1)].reshape(W8, lanes),
        )
        nx, ny, nz = _pt_add(prev, (px, py, one))
        tabx[pl.ds(e, 1)] = nx.reshape(1, W8, lanes)
        taby[pl.ds(e, 1)] = ny.reshape(1, W8, lanes)
        tabz[pl.ds(e, 1)] = nz.reshape(1, W8, lanes)
        return 0

    jax.lax.fori_loop(2, 16, build, 0)

    gtx = gtx_ref[:]
    gty = gty_ref[:]

    def window(w, r):
        for _ in range(4):
            r = _pt_double(r)
        gd = sd_ref[pl.ds(w, 1), :]
        gx, gy = _select_gtab(gtx, gty, gd)
        ra = _pt_add_mixed(r, (gx, gy))
        keep = (gd == 0).astype(jnp.int32)
        r = tuple(a * keep + b * (1 - keep) for a, b in zip(r, ra))
        pd = ed_ref[pl.ds(w, 1), :]
        q = _select_ptab(tabx, taby, tabz, pd)
        return _pt_add(r, q)

    x, y, z = jax.lax.fori_loop(0, 64, window, _pt_identity(lanes))

    mp = mp_ref[:]
    zc = _canon(z, mp)
    inf = jnp.all(zc == 0, axis=0, keepdims=True)
    zi = _inv(z)
    xa = _canon(_mul(x, zi), mp)
    if ecdsa:
        xn = _cond_sub_m(mn_ref[:], xa)
        ok = jnp.all(xn == rc_ref[:], axis=0, keepdims=True)
    else:
        ok = jnp.all(xa == rc_ref[:], axis=0, keepdims=True)
        ya = _canon(_mul(y, zi), mp)
        ok = ok & ((ya[0:1] & 1) == 0)
    ok = ok & ~inf & (vin_ref[0:1] > 0)
    out_ref[:] = jnp.broadcast_to(ok.astype(jnp.int32), (8, lanes))


@functools.lru_cache(maxsize=None)
def _build_call_plain(n_padded: int, ecdsa: bool, interpret: bool):
    grid = n_padded // BLK

    def const_spec(shape):
        return pl.BlockSpec(shape, lambda i: (0, 0), memory_space=pltpu.VMEM)

    limb_spec = pl.BlockSpec((W8, BLK), lambda i: (0, i), memory_space=pltpu.VMEM)
    dig_spec = pl.BlockSpec((64, BLK), lambda i: (0, i), memory_space=pltpu.VMEM)
    v_spec = pl.BlockSpec((8, BLK), lambda i: (0, i), memory_space=pltpu.VMEM)
    call = pl.pallas_call(
        functools.partial(_verify_kernel_plain, ecdsa),
        out_shape=jax.ShapeDtypeStruct((8, n_padded), jnp.int32),
        grid=(grid,),
        in_specs=[
            const_spec((W8, 16)),
            const_spec((W8, 16)),
            const_spec((W8, 1)),
            const_spec((W8, 1)),
            limb_spec,
            limb_spec,
            limb_spec,
            dig_spec,
            dig_spec,
            v_spec,
        ],
        out_specs=v_spec,
        scratch_shapes=[
            pltpu.VMEM((16, W8, BLK), jnp.int32),
            pltpu.VMEM((16, W8, BLK), jnp.int32),
            pltpu.VMEM((16, W8, BLK), jnp.int32),
        ],
        interpret=interpret,
    )
    jitted = jax.jit(call)

    def run(px8, py8, rc8, sd, ed, vin):
        return jitted(
            jnp.asarray(_GTAB8_X), jnp.asarray(_GTAB8_Y), jnp.asarray(_MP8),
            jnp.asarray(_MN8), px8, py8, rc8, sd, ed, vin,
        )

    return run


def _full_digits(scalars) -> np.ndarray:
    """Host: scalars (ints, or canonical 32-byte BE strings — the schnorr
    s column's wire form) -> [64, B] MSB-first 4-bit digits (transposed)."""
    b = len(scalars)
    raw = b"".join([k if type(k) is bytes else int(k).to_bytes(32, "big") for k in scalars])
    arr = np.frombuffer(raw, dtype=np.uint8).reshape(b, 32)
    dig = np.empty((b, 64), np.uint8)
    dig[:, 0::2] = arr >> 4
    dig[:, 1::2] = arr & 0x0F
    return dig.astype(np.int32).T.copy()


@functools.lru_cache(maxsize=None)
def _build_call(n_padded: int, ecdsa: bool, interpret: bool):
    grid = n_padded // BLK

    def const_spec(shape):
        return pl.BlockSpec(shape, lambda i: (0, 0), memory_space=pltpu.VMEM)

    limb_spec = pl.BlockSpec((W8, BLK), lambda i: (0, i), memory_space=pltpu.VMEM)
    dig_spec = pl.BlockSpec((N_WIN, BLK), lambda i: (0, i), memory_space=pltpu.VMEM)
    v_spec = pl.BlockSpec((8, BLK), lambda i: (0, i), memory_space=pltpu.VMEM)
    call = pl.pallas_call(
        functools.partial(_verify_kernel, ecdsa),
        out_shape=jax.ShapeDtypeStruct((8, n_padded), jnp.int32),
        grid=(grid,),
        in_specs=[
            const_spec((W8, 16)),   # gtx
            const_spec((W8, 16)),   # gtxb (beta-scaled)
            const_spec((W8, 16)),   # gty
            const_spec((W8, 1)),    # modulus p
            const_spec((W8, 1)),    # modulus n
            const_spec((W8, 1)),    # beta
            limb_spec,              # px
            limb_spec,              # py
            limb_spec,              # rc
            dig_spec,               # g1 digits
            dig_spec,               # g2 digits
            dig_spec,               # p1 digits
            dig_spec,               # p2 digits
            v_spec,                 # sign bits
            v_spec,                 # valid_in
        ],
        out_specs=v_spec,
        scratch_shapes=[
            pltpu.VMEM((16, W8, BLK), jnp.int32),  # tabx
            pltpu.VMEM((16, W8, BLK), jnp.int32),  # tabxb
            pltpu.VMEM((16, W8, BLK), jnp.int32),  # taby
            pltpu.VMEM((16, W8, BLK), jnp.int32),  # tabz
        ],
        interpret=interpret,
    )
    jitted = jax.jit(call)

    def run(px8, py8, rc8, g1, g2, p1, p2, sgn, vin):
        return jitted(
            jnp.asarray(_GTAB8_X), jnp.asarray(_GTAB8_XB), jnp.asarray(_GTAB8_Y),
            jnp.asarray(_MP8), jnp.asarray(_MN8), jnp.asarray(_BETA8),
            px8, py8, rc8, g1, g2, p1, p2, sgn, vin,
        )

    return run


def _to_radix8_T(limbs16: np.ndarray) -> np.ndarray:
    """Host: [B, 16] canonical 2**16-radix limbs -> [32, B] radix-2**8."""
    a = np.asarray(limbs16, dtype=np.int32)
    out = np.empty((W8, a.shape[0]), dtype=np.int32)
    out[0::2] = (a & 0xFF).T
    out[1::2] = (a >> 8).T
    return out


def _pad_lanes(x: np.ndarray, n: int) -> np.ndarray:
    if x.shape[-1] == n:
        return x
    pad = np.zeros((*x.shape[:-1], n - x.shape[-1]), dtype=x.dtype)
    return np.concatenate([x, pad], axis=-1)


def _glv_digits(scalars) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host: scalars (ints mod n) -> (d1, d2 [N_WIN, B] MSB-first 4-bit
    digit arrays of |k1|, |k2|, sign bits [B] as (s1 | s2 << 1))."""
    b = len(scalars)
    halves = [
        glv_split((int.from_bytes(k, "big") if type(k) is bytes else k) % SECP_N)
        for k in scalars
    ]
    signs = np.fromiter(
        ((k1 < 0) | ((k2 < 0) << 1) for k1, k2 in halves), dtype=np.int32, count=b
    )
    raw = b"".join(
        abs(k1).to_bytes(17, "big") + abs(k2).to_bytes(17, "big") for k1, k2 in halves
    )
    arr = np.frombuffer(raw, dtype=np.uint8).reshape(b, 2, 17)
    nib = np.empty((b, 2, 34), np.uint8)
    nib[..., 0::2] = arr >> 4
    nib[..., 1::2] = arr & 0x0F
    digs = nib[..., 34 - N_WIN :].astype(np.int32)  # |k| < 2**(4*N_WIN)
    return digs[:, 0].T.copy(), digs[:, 1].T.copy(), signs


def verify_batch_pallas(px, py, r_canon, s_scalars, e_scalars, valid_in, *, ecdsa: bool, interpret: bool = False, glv: bool | None = None):
    """Fused-Pallas batched verification.

    px/py/r_canon: [B, 16] canonical 2**16-radix limb arrays (same host
    marshalling as the XLA kernels); s_scalars/e_scalars: python-int scalars
    (s/e for Schnorr, u1/u2 for ECDSA); valid_in: [B] bool.  -> [B] bool.

    Two kernels: the proven 64-window dual-scalar ladder (default) and the
    GLV quad-stream 33-window ladder (opt-in via KASPA_TPU_GLV=1 or glv=True
    until its Mosaic compile is validated on the tunneled device).
    """
    import os

    if glv is None:
        glv = bool(os.environ.get("KASPA_TPU_GLV"))
    b = np.asarray(px).shape[0]
    n = -(-b // BLK) * BLK
    px8 = _pad_lanes(_to_radix8_T(px), n)
    py8 = _pad_lanes(_to_radix8_T(py), n)
    rc8 = _pad_lanes(_to_radix8_T(r_canon), n)
    vin = _pad_lanes(np.broadcast_to(np.asarray(valid_in, dtype=np.int32), (8, b)).copy(), n)
    if glv:
        g1, g2, gs = _glv_digits(s_scalars)
        p1, p2, ps = _glv_digits(e_scalars)
        sgn = np.broadcast_to((gs | (ps << 2)).astype(np.int32), (8, b)).copy()
        out = np.asarray(
            _build_call(n, ecdsa, interpret)(
                px8, py8, rc8,
                _pad_lanes(g1, n), _pad_lanes(g2, n),
                _pad_lanes(p1, n), _pad_lanes(p2, n),
                _pad_lanes(sgn, n), vin,
            )
        )
    else:
        sd = _pad_lanes(_full_digits(s_scalars), n)
        ed = _pad_lanes(_full_digits(e_scalars), n)
        out = np.asarray(_build_call_plain(n, ecdsa, interpret)(px8, py8, rc8, sd, ed, vin))
    return out[0, :b].astype(bool)
