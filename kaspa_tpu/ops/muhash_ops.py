"""Batched U3072 product on device: the muhash bulk-diff kernel.

The reference reduces muhash over txs with a rayon map-reduce
(consensus/src/pipeline/virtual_processor/utxo_validation.rs:334-363,
crypto/muhash/src/lib.rs:87-90 `combine`).  Here the monoid product of a
batch of 3072-bit field elements is a jax.lax tree reduction (log2(N)
levels of pairwise modular multiplies) — the multiplies vectorise over the
shrinking batch, keeping the VPU busy at every level.

Elements enter as [N, 192] int32 limb arrays (see ops/bigint.int_to_limbs);
N is padded to a power of two with ones (the monoid identity).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from kaspa_tpu.ops import bigint as bi

F = bi.F3072


# Fixed batch buckets: one jit compile per bucket size (the 3072-bit mul
# body is large, so unbounded shape-polymorphism would hammer compile time).
BUCKETS = (64, 1024)


@functools.partial(jax.jit, static_argnames=("levels",))
def _tree_product(x, levels: int):
    for _ in range(levels):
        half = x.shape[0] // 2
        x = bi.mul(F, x[:half], x[half:])
    return bi.canon(F, x[0])


def batch_product_device(elements: np.ndarray) -> int:
    """[N, 192] int32 limbs -> product mod 2**3072 - 1103717 (python int).

    Batches larger than the biggest bucket are reduced bucket-by-bucket with
    the partial products combined on host (cheap: one 3072-bit mul each).
    With a configured device mesh (> 1) the whole reduction shards over the
    mesh instead — same result (the monoid product is association-free),
    one compiled shape per (mesh, bucket).
    """
    n = elements.shape[0]
    if n == 0:
        return 1
    from kaspa_tpu.ops import mesh

    if mesh.active_size() > 1:
        return mesh.dispatch_tree_product(elements)
    result = 1
    pos = 0
    while pos < n:
        remaining = n - pos
        # largest bucket that fits the remainder, else the smallest bucket
        # (padded with identity) — keeps the set of compiled shapes tiny
        fitting = [b for b in BUCKETS if b <= remaining]
        bucket = fitting[-1] if fitting else BUCKETS[0]
        chunk = elements[pos : pos + min(bucket, remaining)]
        levels = bucket.bit_length() - 1
        padded = np.tile(np.asarray(F.one, dtype=np.int32), (bucket, 1))
        padded[: chunk.shape[0]] = chunk
        out = _tree_product(jnp.asarray(padded), levels)
        _note_bucket(bucket)
        result = result * bi.limbs_to_int(np.asarray(out)) % F.modulus
        pos += chunk.shape[0]
    return result


# warm-manifest integration: first dispatch of each bucket this process
# records the shape so a restart can pretrace it (once per bucket — the
# manifest write is file io, not something to pay per reduction)
_noted_buckets: set[int] = set()


def _note_bucket(bucket: int) -> None:
    if bucket in _noted_buckets:
        return
    _noted_buckets.add(bucket)
    try:
        from kaspa_tpu.resilience import supervisor

        supervisor.note_shape("muhash_tree", bucket, family="muhash")
    except Exception:  # noqa: BLE001 - the manifest is an optimization
        pass


def pretrace_bucket(bucket: int) -> str:
    """Compile the tree-product kernel at one bucket shape ahead of
    traffic (warm-manifest restart path): an all-identity batch, so the
    product is 1 and the compile is the only work."""
    if bucket not in BUCKETS:
        return f"error:unknown muhash_tree/{bucket}"
    if bucket in _noted_buckets:
        return "warm"
    padded = np.tile(np.asarray(F.one, dtype=np.int32), (bucket, 1))
    jax.block_until_ready(_tree_product(jnp.asarray(padded), bucket.bit_length() - 1))
    _noted_buckets.add(bucket)
    return "traced"


def ints_to_elements(vals: list[int]) -> np.ndarray:
    return bi.ints_to_limbs(vals, F.W).astype(np.int32)


def batch_product_ints(vals: list[int]) -> int:
    return batch_product_device(ints_to_elements(vals))
