"""Width-generic fixed-size big-integer modular arithmetic on int32 limbs.

This is the TPU-native replacement for the reference's CPU big-int stacks:
`math/src/uint.rs` (Uint256/Uint3072 limbed ints) and the field arithmetic
inside libsecp256k1 (C) / `crypto/muhash/src/u3072.rs`.  Design notes:

- Values are arrays of shape ``[..., W]`` (int32), little-endian limbs in a
  2**16 radix.  Limb values are *lazy*: any int32 in ``(-2**18, 2**18)`` is
  legal between operations; the represented integer is ``sum(l[i] << 16*i)``.
  Signed lazy limbs make subtraction carry-free and avoid sequential borrow
  ripple on the VPU (there is no widening 32x32 multiply on TPU, so the radix
  is chosen such that all partial products and column sums stay inside int32).
- Multiplication splits limbs into 8-bit half-limbs so that schoolbook
  partial products (<= 2**20) summed over a column (<= 2*W terms) stay below
  2**31 for every width used here (W=16 for secp256k1, W=192 for muhash).
- All moduli are of the special form ``m = 2**(16*W) - c`` with small-ish
  ``c`` (secp256k1 p and n, muhash's 2**3072 - 1103717), so reduction is a
  fold: ``hi * c + lo``, iterated until the value fits W limbs.
- Everything is branch-free / fixed-shape: jit- and vmap-safe, identical
  semantics on CPU and TPU.

Canonicalisation (exact carry propagation + range reduction into [0, m)) is
only needed at equality tests and outputs; it uses short unrolled scans.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

RADIX_BITS = 16
RADIX = 1 << RADIX_BITS
RADIX_MASK = RADIX - 1


def int_to_limbs(v: int, w: int) -> np.ndarray:
    """Host: python int -> W int32 limbs (little-endian, 16-bit radix)."""
    if v < 0:
        raise ValueError("int_to_limbs expects non-negative")
    out = np.zeros(w, dtype=np.int32)
    for i in range(w):
        out[i] = v & RADIX_MASK
        v >>= RADIX_BITS
    if v:
        raise ValueError("value does not fit in width")
    return out

def ints_to_limbs(vs, w: int) -> np.ndarray:
    """Host: iterable of python ints -> [N, W] int32 limb array."""
    rows = [int_to_limbs(v, w) for v in vs]
    if not rows:
        return np.zeros((0, w), dtype=np.int32)
    return np.stack(rows)

def limbs_to_int(arr) -> int:
    """Host: limb array (possibly lazy/signed) -> python int."""
    arr = np.asarray(arr)
    v = 0
    for i in range(arr.shape[-1]):
        v += int(arr[..., i]) << (RADIX_BITS * i)
    return v

def limbs_to_ints(arr):
    """Host: [N, W] limb array -> list of python ints."""
    arr = np.asarray(arr)
    return [limbs_to_int(arr[i]) for i in range(arr.shape[0])]


class FieldCtx:
    """Static context for a special-form prime field m = 2**(16W) - c."""

    def __init__(self, name: str, bits: int, modulus: int):
        assert bits % RADIX_BITS == 0
        self.name = name
        self.bits = bits
        self.W = bits // RADIX_BITS
        self.modulus = modulus
        self.c = (1 << bits) - modulus
        assert 0 < self.c < (1 << (bits - RADIX_BITS)), "modulus not special-form"
        # 8-bit digits of c (little-endian), python ints
        c8 = []
        c = self.c
        while c:
            c8.append(c & 0xFF)
            c >>= 8
        self.c8 = tuple(c8)
        self.c_limbs16 = int_to_limbs(self.c, (len(c8) + 1) // 2)
        self.m_limbs = int_to_limbs(modulus, self.W)
        self.zero = np.zeros(self.W, dtype=np.int32)
        self.one = int_to_limbs(1, self.W)

    # like _const below: a first access inside shard_map's check_rep rewrite
    # trace yields a RewriteTracer, which must not be cached on the ctx

    @property
    def m_limbs_dev(self):
        if not hasattr(self, "_m_limbs_dev"):
            with jax.ensure_compile_time_eval():
                out = jnp.asarray(self.m_limbs)
            if isinstance(out, jax.core.Tracer):
                return out
            self._m_limbs_dev = out
        return self._m_limbs_dev

    @property
    def c_limbs16_dev(self):
        if not hasattr(self, "_c_limbs16_dev"):
            with jax.ensure_compile_time_eval():
                out = jnp.asarray(self.c_limbs16)
            if isinstance(out, jax.core.Tracer):
                return out
            self._c_limbs16_dev = out
        return self._c_limbs16_dev

    def __repr__(self):
        return f"FieldCtx({self.name}, {self.bits}b)"


# ---------------------------------------------------------------------------
# lazy-limb primitives
# ---------------------------------------------------------------------------

def _split8(x):
    """[..., K] limbs -> [..., 2K] 8-bit half-limbs (even in [0,256), odd signed)."""
    lo = x & 0xFF
    hi = x >> 8  # arithmetic shift: value-preserving for signed lazy limbs
    return jnp.stack([lo, hi], axis=-1).reshape(*x.shape[:-1], 2 * x.shape[-1])


def _carry_round(cols):
    """One vectorised carry round in the 2**16 radix; widens by one limb."""
    limb = cols & RADIX_MASK
    carry = cols >> RADIX_BITS
    out = jnp.concatenate([limb, jnp.zeros_like(limb[..., :1])], axis=-1)
    return out.at[..., 1:].add(carry)


def _carry_rounds(cols, n=2):
    for _ in range(n):
        cols = _carry_round(cols)
    return cols


@functools.lru_cache(maxsize=None)
def _conv_matrix_np(k: int):
    """[k*k, 2k] one-hot anti-diagonal collector: (i,j) -> column i+j."""
    m = np.zeros((k * k, 2 * k), np.int32)
    for i in range(k):
        for j in range(k):
            m[i * k + j, i + j] = 1
    return m


_CONST_CACHE: dict = {}


def _const(arr_factory_key):
    """Memoized device constants: avoids re-running numpy->jax conversion for
    the large one-hot matrices on every traced multiply (a dominant share of
    trace/lowering time for fresh batch shapes).

    ensure_compile_time_eval makes the conversion concrete when the first
    call happens inside a plain jit trace, but inside shard_map's check_rep
    rewrite interpreter it still yields a RewriteTracer — memoizing that
    poisons every later trace in the process, so tracers are returned
    uncached and only concrete arrays enter the cache."""
    hit = _CONST_CACHE.get(arr_factory_key)
    if hit is not None:
        return hit
    kind, arg = arr_factory_key
    with jax.ensure_compile_time_eval():
        if kind == "conv":
            out = jnp.asarray(_conv_matrix_np(arg))
        elif kind == "collect":
            out = jnp.asarray(_block_collect_np(arg))
        elif kind == "cmat":
            c8, k = arg
            out = jnp.asarray(_c_matrix_np(c8, k))
        else:
            raise KeyError(kind)
    if not isinstance(out, jax.core.Tracer):
        _CONST_CACHE[arr_factory_key] = out
    return out


@functools.lru_cache(maxsize=None)
def _block_collect_np(nb: int):
    """[nb*nb, 2nb-1] one-hot: block pair (i,j) -> result block i+j."""
    m = np.zeros((nb * nb, 2 * nb - 1), np.int32)
    for i in range(nb):
        for j in range(nb):
            m[i * nb + j, i + j] = 1
    return m


_BLK = 32  # 8-bit limbs per block in the blocked schoolbook


def _poly_mul8(a8, b8):
    """Schoolbook column products of two 8-bit-split operands.

    [..., K] x [..., K] -> [..., 2K (+pad)] columns in the 2**8 radix.
    Column magnitudes < 2K * 2**20 < 2**31 for K <= 512.

    Small widths contract the outer-product against a one-hot matrix in a
    single dot (XLA fuses the product into the matmul operand, minimising
    HBM round-trips and HLO size).  Large widths (muhash U3072) use a
    blocked schoolbook: all nb*nb block pairs go through the same 32-wide
    contraction in one shot, then a second one-hot dot collects block pairs
    into result blocks — two fat ops instead of K dynamic-slice updates.
    """
    k = a8.shape[-1]
    if k <= 64:
        m = _const(("conv", k))
        p = (a8[..., :, None] * b8[..., None, :]).reshape(*a8.shape[:-1], k * k)
        return jax.lax.dot_general(
            p, m, (((p.ndim - 1,), (0,)), ((), ())), preferred_element_type=jnp.int32
        )
    assert k % _BLK == 0, "large operands must be a multiple of the block size"
    nb = k // _BLK
    lead = a8.shape[:-1]
    ab = a8.reshape(*lead, nb, _BLK)
    bb = b8.reshape(*lead, nb, _BLK)
    # all block-pair products through one 32-wide contraction
    m = _const(("conv", _BLK))  # [blk*blk, 2blk]
    p = (ab[..., :, None, :, None] * bb[..., None, :, None, :]).reshape(*lead, nb * nb, _BLK * _BLK)
    c = jax.lax.dot_general(p, m, (((p.ndim - 1,), (0,)), ((), ())), preferred_element_type=jnp.int32)
    # collect pair results into blocks k = i + j  (sums of <= nb products:
    # per-column bound nb * blk * 2**20 <= 2**31 for nb <= 16, blk = 32
    # ... tighter: blk*2**20 per pair, nb pairs -> nb*2**25; nb<=12 ok)
    coll = _const(("collect", nb))
    d = jax.lax.dot_general(
        c, coll, (((c.ndim - 2,), (0,)), ((), ())), preferred_element_type=jnp.int32
    )  # [..., 2blk, 2nb-1]
    d = jnp.moveaxis(d, -1, -2)  # [..., 2nb-1, 2blk]
    # overlap-add the two halves of each block result (phase offset blk)
    out = jnp.zeros((*lead, 2 * nb + 1, _BLK), dtype=jnp.int32)
    out = out.at[..., : 2 * nb - 1, :].add(d[..., :_BLK])
    out = out.at[..., 1 : 2 * nb, :].add(d[..., _BLK:])
    return out.reshape(*lead, (2 * nb + 1) * _BLK)


def _pair_columns(cols8):
    """Columns in 2**8 radix [..., 2K] -> columns in 2**16 radix [..., K+1]."""
    if cols8.shape[-1] % 2:
        cols8 = jnp.concatenate([cols8, jnp.zeros_like(cols8[..., :1])], axis=-1)
    even = cols8[..., 0::2]
    odd = cols8[..., 1::2]
    out = even + ((odd & 0xFF) << 8)
    hi = odd >> 8
    out = out.at[..., 1:].add(hi[..., :-1])
    return jnp.concatenate([out, hi[..., -1:]], axis=-1)


@functools.lru_cache(maxsize=None)
def _c_matrix_np(c8: tuple, k: int):
    """[k, k + len(c8)] banded matrix: multiply an 8-bit-split value by c."""
    m = np.zeros((k, k + len(c8)), np.int32)
    for j, d in enumerate(c8):
        for i in range(k):
            m[i, i + j] = d
    return m


def _mul_by_c(ctx: FieldCtx, x):
    """x * c where c = 2**(16W) - m, via 8-bit digits of c. Input any width."""
    x8 = _split8(x)
    k = x8.shape[-1]
    m = _const(("cmat", (ctx.c8, k)))
    out = jax.lax.dot_general(
        x8, m, (((x8.ndim - 1,), (0,)), ((), ())), preferred_element_type=jnp.int32
    )
    return _carry_rounds(_pair_columns(out), 2)


def _fold(ctx: FieldCtx, x):
    """Reduce an arbitrary-width lazy value into width W (value mod m preserved)."""
    w = ctx.W
    while x.shape[-1] > w:
        lo, hi = x[..., :w], x[..., w:]
        prod = _mul_by_c(ctx, hi)  # hi * c  == hi * 2**(16W) (mod m)
        if prod.shape[-1] <= w:
            x = lo.at[..., : prod.shape[-1]].add(prod) if prod.shape[-1] < w else lo + prod
        else:
            x = prod.at[..., :w].add(lo)
    return x


def tighten(ctx: FieldCtx, x):
    """Re-establish the lazy-limb bound (|limb| < ~2**17) after adds."""
    return _fold(ctx, _carry_rounds(x, 2))


# ---------------------------------------------------------------------------
# public modular ops (all shapes [..., W] int32, lazy limbs)
# ---------------------------------------------------------------------------

def mul(ctx: FieldCtx, a, b):
    cols = _poly_mul8(_split8(a), _split8(b))
    x = _carry_rounds(_pair_columns(cols), 2)
    x = _fold(ctx, x)
    return _fold(ctx, _carry_rounds(x, 2))

def sqr(ctx: FieldCtx, a):
    return mul(ctx, a, a)

def add(ctx: FieldCtx, a, b):
    return tighten(ctx, a + b)

def sub(ctx: FieldCtx, a, b):
    return tighten(ctx, a - b)

def mul_small(ctx: FieldCtx, a, k: int):
    assert -(1 << 12) < k < (1 << 12)
    return tighten(ctx, a * k)

def neg(ctx: FieldCtx, a):
    return tighten(ctx, -a)


def _scan_carry(x):
    """Exact sequential carry: [..., W] lazy -> ([..., W] canonical limbs, top).

    value == sum(base[i] << 16i) + top << 16W, with base limbs in [0, 2**16).
    Unrolled: W is small (16) or used rarely (192, finalize-only).
    """
    carry = jnp.zeros(x.shape[:-1], dtype=jnp.int32)
    outs = []
    for i in range(x.shape[-1]):
        v = x[..., i] + carry
        outs.append(v & RADIX_MASK)
        carry = v >> RADIX_BITS
    return jnp.stack(outs, axis=-1), carry


def _cond_sub_m(ctx: FieldCtx, x):
    """x in [0, 2**16W) canonical -> subtract m once if x >= m."""
    m = ctx.m_limbs_dev
    d, top = _scan_carry(x - m)
    take = top >= 0  # no borrow => x >= m
    return jnp.where(take[..., None], d, x)


def canon(ctx: FieldCtx, x):
    """Full canonicalisation into [0, m) with limbs in [0, 2**16).

    Repeatedly substitutes the top carry t (value == base + t*2**16W) with
    t*c, which preserves the value mod m since 2**16W == c (mod m).  After
    three substitutions the top carry is provably zero; a final conditional
    subtract brings the value into [0, m).
    """
    c16 = ctx.c_limbs16_dev
    nc = ctx.c_limbs16.shape[0]
    base, t = _scan_carry(x)  # |t| <= 4 given lazy-limb bounds
    for _ in range(3):
        y = base.at[..., :nc].add(t[..., None] * c16)  # |t*c16| < 2**19: ok
        base, t = _scan_carry(y)
    # By range analysis: after the second substitution the value lies in
    # (-c, 2**16W + c), so the third lands in [0, 2**16W) with t == 0.
    out = _cond_sub_m(ctx, base)
    return _cond_sub_m(ctx, out)


def is_zero(ctx: FieldCtx, x):
    """Canonical zero test (x ≡ 0 mod m)."""
    return jnp.all(canon(ctx, x) == 0, axis=-1)

def eq(ctx: FieldCtx, a, b):
    return jnp.all(canon(ctx, a) == canon(ctx, b), axis=-1)

def eq_canonical(ctx: FieldCtx, a, b_canon):
    """Compare against an already-canonical value."""
    return jnp.all(canon(ctx, a) == b_canon, axis=-1)

def is_odd(ctx: FieldCtx, x):
    return (canon(ctx, x)[..., 0] & 1) == 1


def exp_const(ctx: FieldCtx, x, e: int):
    """x**e mod m for a *static* python-int exponent (square-and-multiply).

    Uses lax.fori_loop over the fixed bit string to keep the HLO small.
    """
    nbits = e.bit_length()
    bits = np.array([(e >> (nbits - 1 - i)) & 1 for i in range(nbits)], dtype=np.int32)
    bits_d = jnp.asarray(bits)
    one = jnp.broadcast_to(jnp.asarray(ctx.one), x.shape).astype(jnp.int32)

    def body(i, acc):
        acc = sqr(ctx, acc)
        withx = mul(ctx, acc, x)
        return jnp.where(bits_d[i][..., None], withx, acc)

    return jax.lax.fori_loop(0, nbits, body, one)


def inv(ctx: FieldCtx, x):
    """Modular inverse via Fermat (m prime). inv(0) == 0."""
    return exp_const(ctx, x, ctx.modulus - 2)


def inv_batch(ctx: FieldCtx, x, zero_mask=None):
    """Batch-affine Montgomery inversion along the leading axis.

    Replaces B independent Fermat ladders with ~3(B-1) modular multiplies
    plus ONE Fermat inversion of the running product:

        inv(x_i) == prefix_{i-1} * suffix_{i+1} * inv(prod_j x_j)

    Prefix/suffix products are two O(log B)-depth associative scans —
    modular multiplication is associative, so the scan's reassociation is
    exact (lazy-limb representations may differ; values mod m cannot).
    Zeros would poison the shared product, so zero lanes are substituted
    with 1 through the chain and masked back to 0 on output, preserving
    ``inv``'s inv(0) == 0 convention.

    x: [B, ..., W] lazy limbs; zero_mask: optional [B, ...] bool marking
    canonical zeros (computed here when absent). Returns lazy limbs.
    """
    b = x.shape[0]
    if b == 0:
        return x
    if zero_mask is None:
        zero_mask = is_zero(ctx, x)
    one = jnp.broadcast_to(jnp.asarray(ctx.one), x.shape).astype(jnp.int32)
    u = jnp.where(zero_mask[..., None], one, x)
    if b == 1:
        return jnp.where(zero_mask[..., None], jnp.zeros_like(x), inv(ctx, u))

    def mulfn(p, q):
        return mul(ctx, p, q)

    pre = jax.lax.associative_scan(mulfn, u, axis=0)  # pre[i] = u_0 .. u_i
    suf = jax.lax.associative_scan(mulfn, u, axis=0, reverse=True)
    total_inv = inv(ctx, pre[-1])  # the single Fermat ladder
    left = jnp.concatenate([one[:1], pre[:-1]], axis=0)  # prod of lanes < i
    right = jnp.concatenate([suf[1:], one[:1]], axis=0)  # prod of lanes > i
    out = mul(ctx, mul(ctx, left, right), jnp.broadcast_to(total_inv, x.shape))
    return jnp.where(zero_mask[..., None], jnp.zeros_like(x), out)


# ---------------------------------------------------------------------------
# field contexts used by the framework
# ---------------------------------------------------------------------------

SECP_P = 2**256 - 2**32 - 977
SECP_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
MUHASH_M = 2**3072 - 1103717  # crypto/muhash/src/u3072.rs:22 (PRIME_DIFF)

FP = FieldCtx("secp256k1_p", 256, SECP_P)
FN = FieldCtx("secp256k1_n", 256, SECP_N)
F3072 = FieldCtx("muhash_u3072", 3072, MUHASH_M)
