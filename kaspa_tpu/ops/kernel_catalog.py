"""Static kernel catalog: every family × bucket × mesh signature, audited.

The runtime's deterministic-execution discipline hangs on a closed world
of compiled shapes: ``crypto/secp._bucket`` pads batches to powers of two,
``ops/mesh`` shards the batch axis, and the warm manifest replays exactly
those (kernel, bucket) pairs on restart.  Nothing checked that the world
actually closes — that every reachable signature traces cleanly, keeps
its dtype contract, and is covered by a pretrace rule.  This module is
that check's data half:

- ``FAMILIES``: each kernel family's manifest kernel name, reachable
  bucket ladder, and shardable mesh sizes (the static mirror of
  ``secp._dispatch_tier`` + ``ops/mesh.dispatch_*``).
- ``enumerate_signatures()``: the closed world, one row per reachable
  (family, bucket, mesh) with the per-shard batch.
- ``audit_signature(row)``: ``jax.eval_shape`` on the real jitted kernel
  at that signature — no compile, no device — failing on shape/dtype
  drift.
- ``WARM_COVERAGE``: committed pretrace-coverage rules reconciled by the
  ``kernel-shape`` lint checker (``analysis/shapes.py``): every reachable
  shape must match a rule, every rule must match a reachable shape.

Heavy imports (jax, the kernels) stay inside functions: importing the
catalog is free, so lint tooling can read the static tables without
touching a backend.
"""

from __future__ import annotations

from dataclasses import dataclass

LIMBS = 16  # 256-bit field elements: 16 x 16-bit limbs (ops/secp256k1)
DIGITS = 64  # 4-bit MSB window digits per 256-bit scalar
A_WINDOWS = 32  # aggregate weights are 128-bit: only the low window half ships
MUHASH_LIMBS = 192  # 3072-bit muhash elements: 192 x 16-bit limbs

# secp._bucket pads to powers of two, min 8; the dispatch tiers cap
# coalesced batches at 1024 (BENCH_SWEEP targets stay inside this ladder)
VERIFY_BUCKETS = (8, 16, 32, 64, 128, 256, 512, 1024)
MUHASH_BUCKETS = (64, 1024)  # mirrors ops.muhash_ops.BUCKETS
MESH_SIZES = (1, 2, 4, 8)  # KASPA_TPU_MESH values the partition rules serve


@dataclass(frozen=True)
class Family:
    name: str  # warm-manifest "family"
    kernel: str  # warm-manifest "kernel"
    buckets: tuple
    mesh_sizes: tuple


FAMILIES: dict[str, Family] = {
    "ladder": Family("ladder", "schnorr_verify", VERIFY_BUCKETS, MESH_SIZES),
    "ecdsa": Family("ecdsa", "ecdsa_verify", VERIFY_BUCKETS, MESH_SIZES),
    "aggregate": Family("aggregate", "schnorr_aggregate", VERIFY_BUCKETS, MESH_SIZES),
    # the 3072-bit tree product shards whole buckets, not lanes: audit the
    # fixed buckets at mesh 1 (mesh dispatch reuses the same bucket shapes)
    "muhash": Family("muhash", "muhash_tree", MUHASH_BUCKETS, (1,)),
}

# Pretrace coverage rules: (family, min_bucket, max_bucket) — a reachable
# (family, bucket) is covered iff some rule brackets it.  The lint gate
# fails on uncovered reachable shapes AND on dead rules, so this table
# can't silently rot when a bucket ladder or family changes.
WARM_COVERAGE: tuple[tuple[str, int, int], ...] = (
    ("ladder", 8, 1024),
    ("ecdsa", 8, 1024),
    ("aggregate", 8, 1024),
    ("muhash", 64, 1024),
)


def covered(family: str, bucket: int) -> bool:
    return any(f == family and lo <= bucket <= hi for f, lo, hi in WARM_COVERAGE)


def enumerate_signatures() -> list[dict]:
    """One row per reachable (family, bucket, mesh): mesh must divide the
    bucket and leave at least the minimum (8-lane) per-shard batch."""
    rows = []
    for fam in FAMILIES.values():
        for b in fam.buckets:
            for m in fam.mesh_sizes:
                if b % m != 0 or b // m < 8:
                    continue
                rows.append(
                    {
                        "family": fam.name,
                        "kernel": fam.kernel,
                        "bucket": b,
                        "mesh": m,
                        "shard": b // m,
                    }
                )
    return rows


def _i32(*shape):
    import jax
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct(shape, jnp.int32)


def _b(*shape):
    import jax
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct(shape, jnp.bool_)


def audit_signature(row: dict) -> str | None:
    """eval_shape the row's kernel(s); an error string on drift, else
    None.  Runs entirely abstractly — no compile, no device memory."""
    import jax
    import jax.numpy as jnp

    fam, shard, mesh = row["family"], row["shard"], row["mesh"]
    try:
        if fam in ("ladder", "ecdsa"):
            from kaspa_tpu.ops.secp256k1 import verify

            kern = verify.schnorr_verify_kernel if fam == "ladder" else verify.ecdsa_verify_kernel
            out = jax.eval_shape(
                kern,
                _i32(shard, LIMBS), _i32(shard, LIMBS), _i32(shard, LIMBS),
                _i32(shard, DIGITS), _i32(shard, DIGITS), _b(shard),
            )
            if out.shape != (shard,) or out.dtype != jnp.bool_:
                return f"verify mask drifted: got {out.shape}/{out.dtype}, want ({shard},)/bool"
        elif fam == "aggregate":
            from kaspa_tpu.ops.secp256k1 import aggregate as agg

            parts = jax.eval_shape(
                agg.aggregate_partials_kernel,
                _i32(shard, LIMBS), _i32(shard, LIMBS),
                _i32(shard, LIMBS), _i32(shard, LIMBS),
                _i32(shard, DIGITS), _i32(shard, DIGITS - agg.A_WINDOWS),
            )
            if len(parts) != 3 or any(
                p.shape != (DIGITS, LIMBS) or p.dtype != jnp.int32 for p in parts
            ):
                got = [(p.shape, str(p.dtype)) for p in parts]
                return f"aggregate partials drifted: got {got}, want 3x(({DIGITS}, {LIMBS})/int32)"
            fin = jax.eval_shape(
                agg.aggregate_reduce_finish_kernel,
                _i32(mesh, DIGITS, LIMBS), _i32(mesh, DIGITS, LIMBS),
                _i32(mesh, DIGITS, LIMBS), _i32(DIGITS),
            )
            if fin.shape != () or fin.dtype != jnp.bool_:
                return f"aggregate finish drifted: got {fin.shape}/{fin.dtype}, want ()/bool"
        elif fam == "muhash":
            from kaspa_tpu.ops import muhash_ops

            levels = shard.bit_length() - 1  # shard is a power of two
            out = jax.eval_shape(
                lambda x: muhash_ops._tree_product(x, levels), _i32(shard, MUHASH_LIMBS)
            )
            if out.shape != (MUHASH_LIMBS,) or out.dtype != jnp.int32:
                return f"muhash product drifted: got {out.shape}/{out.dtype}, want ({MUHASH_LIMBS},)/int32"
        else:
            return f"unknown family {fam!r}"
    except Exception as e:  # noqa: BLE001 - the audit reports, never crashes lint
        return f"eval_shape failed: {type(e).__name__}: {e}"
    return None


def _audit_agg_finish(mesh: int) -> str | None:
    """eval_shape only the aggregate finish kernel at one mesh width."""
    import jax
    import jax.numpy as jnp

    from kaspa_tpu.ops.secp256k1 import aggregate as agg

    try:
        fin = jax.eval_shape(
            agg.aggregate_reduce_finish_kernel,
            _i32(mesh, DIGITS, LIMBS), _i32(mesh, DIGITS, LIMBS),
            _i32(mesh, DIGITS, LIMBS), _i32(DIGITS),
        )
        if fin.shape != () or fin.dtype != jnp.bool_:
            return f"aggregate finish drifted: got {fin.shape}/{fin.dtype}, want ()/bool"
    except Exception as e:  # noqa: BLE001
        return f"eval_shape failed: {type(e).__name__}: {e}"
    return None


def audit_all(rows: list[dict]) -> tuple[list[tuple[dict, str]], int]:
    """Audit every row with a minimal set of eval_shape traces:
    ``([(representative_row, error)...], traces_performed)``.

    Tracing a verify kernel costs seconds (the window ladders unroll at
    trace time) and its graph — so any dtype drift in it — is identical
    across batch widths: the kernels take no static arguments, only the
    batch axis changes.  One representative trace per kernel therefore
    validates the whole bucket ladder.  The exceptions re-trace: the
    aggregate *finish* kernel's shard axis is the mesh width (one trace
    per distinct mesh), and ``_tree_product``'s ``levels`` static
    argument changes the graph per muhash bucket (one trace per bucket).
    """
    errors: list[tuple[dict, str]] = []
    traces = 0
    for fam in ("ladder", "ecdsa", "aggregate"):
        frows = [r for r in rows if r["family"] == fam]
        if not frows:
            continue
        rep = min(frows, key=lambda r: (r["shard"], r["mesh"]))
        traces += 1
        err = audit_signature(rep)
        if err is not None:
            errors.append((rep, err))
        if fam == "aggregate":
            for mesh in sorted({r["mesh"] for r in frows} - {rep["mesh"]}):
                traces += 1
                err = _audit_agg_finish(mesh)
                if err is not None:
                    frep = min(
                        (r for r in frows if r["mesh"] == mesh),
                        key=lambda r: r["shard"],
                    )
                    errors.append((frep, err))
    for row in (r for r in rows if r["family"] == "muhash"):
        traces += 1
        err = audit_signature(row)
        if err is not None:
            errors.append((row, err))
    return errors, traces
