"""Asynchronous verify-dispatch engine: cross-block batch coalescing.

`BatchScriptChecker.dispatch()` historically blocked per block: every
block's handful of signature jobs paid full jit-dispatch latency at low
device occupancy.  This module owns a process-wide coalescing queue in
front of the batched verify kernels (`crypto/secp.py`): signature jobs
from *concurrent* callers — pipeline stage workers, mempool checks, RPC
validators — accumulate into device-sized super-batches and are flushed
by a dedicated dispatcher thread:

- **size**: a kind's pending jobs reach the adaptive target (seeded from
  ``BENCH_SWEEP.json``'s best batch for the active mesh, fallback 1024);
- **age**: the oldest queued chunk exceeds the flush age
  (``KASPA_TPU_COALESCE_AGE_MS``, default 2 ms);
- **nudge**: a caller blocks on its ticket — the queue flushes as soon
  as the dispatcher is idle, so a serial caller sees near-zero added
  latency and *bit-identical* results (verify masks are per-lane
  functions of each triple; batch composition cannot change them);
- **drain/barrier**: shutdown or an explicit `drain()` flushes
  everything and blocks until every callback has resolved.

Double buffering: the staging buffer is swapped out wholesale under the
lock (the host keeps collecting/sighashing block N+1 into the fresh
buffer) while the dispatcher marshals the taken chunks and runs the
device kernel — the taken arrays are *donated* to the dispatch in the
sense that no host reference mutates them afterwards, so XLA is free to
alias them.  The mesh path (`ops/mesh.py`) pads once per super-batch
instead of once per block.

Consensus note: `_calculate_utxo_state` consumes each merged block's
script results before building the next block's UTXO view, so the
production consensus path keeps its synchronous `dispatch()` semantics
(submit + nudge).  Coalescing wins come from jobs that arrive while the
device is busy — concurrent pipeline stages, the mempool lane — and from
callers that use `dispatch_async()` to overlap their own host work.

Traffic classes: a kind may be class-qualified (``"standalone_tx:schnorr"``)
to give a workload its own batch-size dynamics without a second queue.
Standalone-transaction admission (the ingest tier) arrives in small
concurrent bursts rather than block-sized slabs, so the ``standalone_tx``
class carries its own coalesce target (``KASPA_TPU_TX_COALESCE``, default
256) and flush age (``KASPA_TPU_TX_COALESCE_AGE_MS``, default 5 ms);
flush triggers, chunk packing, and span/counter attribution all key on
the full qualified kind, while the device call maps back to the base
kind — so the aggregate/auto verify-mode crossover, the fabric balancer,
breaker degradation, and host fallback are inherited unchanged.
"""

from __future__ import annotations

import itertools
import json
import os
import threading

from kaspa_tpu.utils.sync import ranked_lock
import time
from dataclasses import dataclass, field
from time import perf_counter_ns

import numpy as np

from kaspa_tpu.observability import trace
from kaspa_tpu.observability.core import REGISTRY, SIZE_BUCKETS

# shared id stamped on every fan-back span of one device dispatch, so a
# trace viewer can correlate the N per-ticket child spans that rode the
# same super-batch
_super_ids = itertools.count(1)

DEFAULT_TARGET = 1024
_TARGET_MIN, _TARGET_MAX = 8, 16384
_WAIT_CAP_S = 600.0  # ticket.wait() hard cap: covers a cold ladder compile

# standalone-transaction admission traffic class (the ingest tier's lane)
TX_CLASS = "standalone_tx"
DEFAULT_TX_TARGET = 256


def base_kind(kind: str) -> str:
    """Strip a traffic-class qualifier: "standalone_tx:schnorr" -> "schnorr"."""
    return kind.split(":", 1)[1] if ":" in kind else kind


def traffic_class(kind: str) -> str:
    """The traffic class of a (possibly qualified) kind; "block" default."""
    return kind.split(":", 1)[0] if ":" in kind else "block"

_COALESCE_DEPTH = REGISTRY.histogram(
    "dispatch_coalesce_depth", SIZE_BUCKETS,
    help="caller chunks merged into one super-batch, per dispatch",
)
_SUPER_BATCH = REGISTRY.histogram(
    "dispatch_super_batch_size", SIZE_BUCKETS,
    help="verify jobs per coalesced super-batch dispatch",
)
_QUEUE_AGE = REGISTRY.histogram(
    "dispatch_queue_age_seconds",
    (0.0005, 0.001, 0.002, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 1.0, 5.0),
    help="oldest chunk's queue residency at flush time",
)
_FLUSHES = REGISTRY.counter_family(
    "dispatch_flushes", "reason", help="super-batch flushes by trigger (size/age/nudge/drain)"
)
_COALESCED_JOBS = REGISTRY.counter_family(
    "dispatch_coalesced_jobs", "kind", help="verify jobs routed through the coalescing queue"
)
from kaspa_tpu.observability.shed import SHED as _SHED


class DispatchTimeout(TimeoutError):
    """A ticket wait expired.  Carries the chunk's identity (kind, job
    count, super_id once assigned) and the supervision verdict, so the
    error names the wedged super-batch instead of an opaque timeout."""

    def __init__(self, kind: str, jobs: int, super_id: int | None, waited_s: float, verdict: dict):
        sup = f"super_id={super_id}" if super_id is not None else "not yet super-batched"
        super().__init__(
            f"verify dispatch ticket timed out after {waited_s:g}s "
            f"(kind={kind}, jobs={jobs}, {sup}; supervisor: {verdict})"
        )
        self.kind = kind
        self.jobs = jobs
        self.super_id = super_id
        self.waited_s = waited_s
        self.verdict = verdict


class DispatchAbandoned(RuntimeError):
    """The dispatcher was abandoned (hung device thread at shutdown)
    before this chunk resolved; the caller must treat it as unverified."""


class Ticket:
    """Per-chunk completion handle: resolves to the [n] bool validity mask
    for exactly the items submitted (super-batch slicing is internal)."""

    __slots__ = ("_engine", "_event", "_mask", "_error", "kind", "jobs", "super_id")

    def __init__(self, engine: "CoalescingDispatcher | None", kind: str = "", jobs: int = 0):
        self._engine = engine
        self._event = threading.Event()
        self._mask: np.ndarray | None = None
        self._error: Exception | None = None
        self.kind = kind
        self.jobs = jobs
        self.super_id: int | None = None  # stamped when the super-batch forms

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: float | None = None) -> np.ndarray:
        """Block for this chunk's mask; nudges the queue so a lone waiter
        never sits out the full flush age."""
        if not self._event.is_set():
            if self._engine is not None:
                self._engine.nudge()
            waited = timeout if timeout is not None else _WAIT_CAP_S
            if not self._event.wait(waited):
                from kaspa_tpu.resilience import supervisor  # deferred: import DAG

                raise DispatchTimeout(self.kind, self.jobs, self.super_id, waited, supervisor.verdict())
        if self._error is not None:
            raise self._error
        return self._mask

    def _resolve(self, mask: np.ndarray | None, error: Exception | None) -> None:
        if self._event.is_set():
            return  # first resolution wins (late results from an abandoned
            # dispatcher thread are discarded, never merged)
        self._mask = mask
        self._error = error
        self._event.set()


@dataclass
class _Chunk:
    kind: str  # "schnorr" | "ecdsa", optionally class-qualified ("standalone_tx:schnorr")
    items: list  # [(pubkey, msg, sig), ...] — ownership donated on submit
    ticket: Ticket
    enqueued_at: float = field(default_factory=time.monotonic)
    # producer's TraceContext + enqueue stamp: the dispatcher thread fans
    # the one device span back into each submitting block's trace
    ctx: object = None
    enqueued_ns: int = 0
    resolved: bool = False  # guarded by the engine lock: first finish wins
    deferred: bool = False  # held back at least once by class-yield scheduling


class CoalescingDispatcher:
    """Cross-caller coalescing queue in front of secp's batched kernels."""

    def __init__(self, target: int, max_age_s: float, class_specs: dict | None = None):
        self.target = max(_TARGET_MIN, min(_TARGET_MAX, int(target)))
        self.max_age_s = max_age_s
        # traffic class -> (target, max_age_s): per-class batch dynamics for
        # class-qualified kinds; unqualified kinds use the defaults above
        self.class_specs = {
            cls: (max(_TARGET_MIN, min(_TARGET_MAX, int(t))), float(age))
            for cls, (t, age) in (class_specs or {}).items()
        }
        self._lock = ranked_lock("dispatch.queue", reentrant=False)
        self._wake = self._lock.condition()
        self._idle = self._lock.condition()
        # class-yield brownout seam: traffic classes in this set are held
        # back from flushes while non-yield work is pending, each chunk for
        # at most _starvation_s (the starvation bound) — the overload
        # controller points this at TX_CLASS under pressure so block-verify
        # super-batches keep the device to themselves
        self._yield_classes: frozenset[str] = frozenset()
        self._starvation_s = 0.25
        self._pending: list[_Chunk] = []  # staging buffer (swapped at flush)
        self._inflight: list[_Chunk] = []  # swapped out, not yet resolved
        self._urgent = False
        self._unresolved = 0  # chunks submitted but not yet resolved
        self._closed = False
        self._abandoned = False
        self._thread: threading.Thread | None = None

    # -- producer side ------------------------------------------------------

    def submit(self, kind: str, items: list) -> Ticket:
        """Queue one chunk of (pubkey, msg, sig) triples; the caller must
        not mutate `items` afterwards (donated to the dispatcher)."""
        ticket = Ticket(self, kind, len(items))
        if not items:
            ticket._resolve(np.zeros(0, dtype=bool), None)
            return ticket
        _COALESCED_JOBS.inc(kind, len(items))
        with self._lock:
            if self._closed:
                raise RuntimeError("verify dispatcher is shut down")
            self._pending.append(
                _Chunk(kind, items, ticket, ctx=trace.context(), enqueued_ns=perf_counter_ns())
            )
            self._unresolved += 1
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="verify-dispatch", daemon=True
                )
                self._thread.start()
            self._wake.notify()
        return ticket

    def nudge(self) -> None:
        """Request an immediate flush (a caller is blocked on a ticket)."""
        with self._lock:
            self._urgent = True
            self._wake.notify()

    def drain(self, timeout: float = 10.0) -> bool:
        """Flush everything and block until every submitted chunk has
        resolved (True) or the timeout expires (False)."""
        deadline = time.monotonic() + timeout
        with self._lock:
            self._urgent = True
            self._wake.notify()
            while self._unresolved > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._idle.wait(remaining)
        return True

    def close(self, timeout: float = 10.0, abandon: bool = True) -> bool:
        """Drain, then stop accepting work and retire the thread.

        When the drain times out — the dispatcher thread is wedged inside
        a device call — ``abandon=True`` (the default) bounds shutdown:
        every unresolved ticket is failed with DispatchAbandoned and the
        hung thread is left behind as a daemon, so daemon exit never
        blocks on a dead device."""
        drained = self.drain(timeout)
        if not drained and abandon:
            self.abandon("close timeout: device thread hung")
            return False
        with self._lock:
            self._closed = True
            self._wake.notify()
        return drained

    def abandon(self, reason: str) -> int:
        """Fail every unresolved chunk (queued or in flight) with
        DispatchAbandoned and stop accepting work; returns the number of
        chunks abandoned.  The wedged dispatcher thread is not joined —
        any result it later produces hits resolved chunks and is
        discarded."""
        err = DispatchAbandoned(f"verify dispatcher abandoned: {reason}")
        with self._lock:
            self._closed = True
            self._abandoned = True
            victims = [c for c in self._pending + self._inflight if not c.resolved]
            self._pending = []
            self._wake.notify_all()
        for c in victims:
            self._finish(c, None, err)
        return len(victims)

    def set_class_yield(self, classes, starvation_s: float = 0.25) -> None:
        """Make the given traffic classes yield to other pending work.
        A yielded chunk is excluded from flush decisions while non-yield
        chunks are pending, but never for longer than ``starvation_s``
        (the starvation bound) — block floods cannot starve txs forever.
        Empty/None restores plain FIFO coalescing."""
        with self._lock:
            self._yield_classes = frozenset(classes or ())
            self._starvation_s = max(0.0, float(starvation_s))
            self._wake.notify()

    def pressure(self) -> dict:
        """Per-traffic-class backlog snapshot for the overload controller:
        pending+inflight job counts and the oldest pending chunk age."""
        with self._lock:
            now = time.monotonic()
            per: dict[str, dict] = {}
            for c in self._pending:
                d = per.setdefault(traffic_class(c.kind), {"jobs": 0, "oldest_age_s": 0.0})
                d["jobs"] += len(c.items)
                d["oldest_age_s"] = max(d["oldest_age_s"], now - c.enqueued_at)
            for c in self._inflight:
                d = per.setdefault(traffic_class(c.kind), {"jobs": 0, "oldest_age_s": 0.0})
                d["jobs"] += len(c.items)
            return per

    def stats(self) -> dict:
        with self._lock:
            return {
                "target": self.target,
                "max_age_ms": round(self.max_age_s * 1000, 3),
                "classes": {
                    cls: {"target": t, "max_age_ms": round(age * 1000, 3)}
                    for cls, (t, age) in self.class_specs.items()
                },
                "pending_chunks": len(self._pending),
                "inflight_chunks": len(self._inflight),
                "unresolved_chunks": self._unresolved,
                "abandoned": self._abandoned,
                "yield_classes": sorted(self._yield_classes),
                "starvation_ms": round(self._starvation_s * 1000, 3),
            }

    # -- dispatcher thread ---------------------------------------------------

    def _target_for(self, kind: str) -> int:
        spec = self.class_specs.get(traffic_class(kind))
        return spec[0] if spec is not None else self.target

    def _age_for(self, kind: str) -> float:
        spec = self.class_specs.get(traffic_class(kind))
        return spec[1] if spec is not None else self.max_age_s

    def _eligible_locked(self, now: float) -> tuple[list[_Chunk], list[_Chunk]]:
        """Split staged chunks into (eligible, held) under class-yield.
        A chunk is held only while (a) its traffic class yields, (b) some
        non-yield chunk is pending (otherwise there is nothing to yield
        to), and (c) it is younger than the starvation bound.  Drain
        bypasses yielding entirely — shutdown flushes everything."""
        if not self._yield_classes or self._closed:
            return self._pending, []
        if not any(traffic_class(c.kind) not in self._yield_classes for c in self._pending):
            return self._pending, []
        eligible: list[_Chunk] = []
        held: list[_Chunk] = []
        for c in self._pending:
            if (
                traffic_class(c.kind) in self._yield_classes
                and now - c.enqueued_at < self._starvation_s
            ):
                held.append(c)
            else:
                eligible.append(c)
        return eligible, held

    def _flush_reason_locked(self, now: float, eligible: list[_Chunk]) -> str | None:
        if not eligible:
            return None
        if self._closed:
            return "drain"
        if self._urgent:
            return "nudge"
        per_kind: dict[str, int] = {}
        for c in eligible:
            per_kind[c.kind] = per_kind.get(c.kind, 0) + len(c.items)
        if any(n >= self._target_for(k) for k, n in per_kind.items()):
            return "size"
        if any(now - c.enqueued_at >= self._age_for(c.kind) for c in eligible):
            return "age"
        return None

    def _next_age_deadline_locked(self, now: float, held: list[_Chunk]) -> float:
        """Seconds until the earliest chunk becomes actionable (the sleep
        bound).  A held chunk's deadline is its starvation bound, not its
        flush age — otherwise an expired flush age on a held chunk makes
        this 0 and the loop busy-spins until the starvation bound."""
        held_ids = {id(c) for c in held}  # _Chunk is unhashable (dataclass eq)
        deadlines = [
            (self._starvation_s if id(c) in held_ids else self._age_for(c.kind))
            - (now - c.enqueued_at)
            for c in self._pending
        ]
        return max(0.0, min(deadlines))

    def _run(self) -> None:
        while True:
            with self._lock:
                while True:
                    if self._abandoned:
                        return
                    now = time.monotonic()
                    if not self._pending:
                        # a stale nudge with nothing queued must not force
                        # the next lone chunk into a depth-1 flush
                        self._urgent = False
                    eligible, held = self._eligible_locked(now)
                    reason = self._flush_reason_locked(now, eligible)
                    if reason is not None:
                        break
                    if self._closed and not self._pending:
                        return
                    if self._pending:
                        # sleep only until the earliest chunk is actionable
                        self._wake.wait(self._next_age_deadline_locked(now, held))
                    else:
                        self._wake.wait()
                # double-buffer swap: donate the eligible chunks to this
                # flush cycle (held chunks stay staged for a later flush);
                # producers refill a fresh buffer while XLA runs below
                for c in held:
                    if not c.deferred:
                        c.deferred = True
                        _SHED.inc("dispatch_yield")
                taken = eligible
                self._pending = held
                self._inflight.extend(taken)
                self._urgent = False
            self._dispatch(taken, reason)

    def _dispatch(self, chunks: list[_Chunk], reason: str) -> None:
        _FLUSHES.inc(reason)
        now = time.monotonic()
        by_kind: dict[str, list[_Chunk]] = {}
        for c in chunks:
            by_kind.setdefault(c.kind, []).append(c)
        for kind, group in by_kind.items():
            # greedy whole-chunk packing into <= target super-batches (a
            # single chunk larger than the target still goes out in one)
            target = self._target_for(kind)
            i = 0
            while i < len(group):
                batch, jobs = [], 0
                while i < len(group) and (not batch or jobs + len(group[i].items) <= target):
                    batch.append(group[i])
                    jobs += len(group[i].items)
                    i += 1
                self._run_super_batch(kind, batch, jobs, now)

    def _run_super_batch(self, kind: str, batch: list[_Chunk], jobs: int, now: float) -> None:
        from kaspa_tpu.crypto import secp  # deferred: keeps import DAG acyclic

        _COALESCE_DEPTH.observe(len(batch))
        _SUPER_BATCH.observe(jobs)
        _QUEUE_AGE.observe(now - min(c.enqueued_at for c in batch))
        sid = next(_super_ids)
        for c in batch:
            c.ticket.super_id = sid  # a timeout now names the super-batch
        items = [it for c in batch for it in c.items]
        try:
            t0 = perf_counter_ns()
            # verify_batch resolves the process-wide verify mode, so a
            # coalesced schnorr super-batch takes the aggregate RLC lane
            # exactly when a direct caller's batch of the same size would;
            # class-qualified kinds map to their base kernel here, keeping
            # the crossover/fabric/breaker behavior identical per class
            with trace.span("dispatch.super_batch", kind=kind, jobs=jobs, chunks=len(batch)):
                mask = np.asarray(secp.verify_batch(base_kind(kind), items))
            t1 = perf_counter_ns()
        except Exception as e:  # noqa: BLE001 - surfaced on every waiting ticket
            t1 = perf_counter_ns()
            self._fan_back(kind, batch, jobs, sid, t1, t1, error=type(e).__name__)
            for c in batch:
                self._finish(c, None, e)
            return
        self._fan_back(kind, batch, jobs, sid, t0, t1)
        pos = 0
        for c in batch:
            self._finish(c, mask[pos : pos + len(c.items)], None)
            pos += len(c.items)

    def _fan_back(self, kind: str, batch: list[_Chunk], jobs: int, sid: int, t0: int, t1: int, **extra) -> None:
        """Fan the single device dispatch back into each submitting block's
        trace: a retroactive ``wait.dispatch`` (enqueue -> kernel start)
        plus a ``dispatch.device`` child covering the device interval,
        stamped with a shared super_id so Perfetto can correlate them."""
        for c in batch:
            if c.ctx is None:
                continue
            trace.record_span("wait.dispatch", c.ctx, c.enqueued_ns, t0)
            trace.record_span(
                "dispatch.device", c.ctx, t0, t1,
                kind=kind, jobs=len(c.items), super_jobs=jobs,
                chunks=len(batch), super_id=sid, **extra,
            )

    def _finish(self, chunk: _Chunk, mask, error) -> bool:
        """Resolve one chunk exactly once; False = already resolved (a
        late result from an abandoned dispatcher thread, discarded)."""
        with self._lock:
            if chunk.resolved:
                return False
            chunk.resolved = True
            try:
                self._inflight.remove(chunk)
            except ValueError:
                pass  # abandoned straight from the staging buffer
            self._unresolved -= 1
            if self._unresolved == 0:
                self._idle.notify_all()
        chunk.ticket._resolve(mask, error)
        return True


# --- process-wide configuration (mirrors ops/mesh.py) -----------------------

_cfg_lock = ranked_lock("dispatch.config")
_configured: str | int | None = None
_engine: CoalescingDispatcher | None = None

# --- verify-mode selection (ladder | aggregate | auto) ----------------------
# The dispatch module owns which schnorr lane runs: the per-signature dual
# ladder, or the aggregate RLC multi-scalar lane (ops/secp256k1/aggregate).
# "auto" consults the bench sweep's measured crossover batch size — below
# it the per-batch doubling chain + bisection risk outweigh the saved
# ladders.  secp.verify_batch calls resolve_verify_mode() on every batch,
# so the legacy synchronous txscript lane, the coalescing dispatcher, and
# the fabric slice workers all honor one process-wide knob.

VERIFY_MODES = ("ladder", "aggregate", "auto")
_DEFAULT_AGG_CROSSOVER = 64  # conservative floor when no sweep artifact exists
_verify_mode: str | None = None  # None -> consult KASPA_TPU_VERIFY_MODE


def set_verify_mode(mode: str | None) -> str:
    """Pin the process-wide schnorr verify mode; None re-reads the
    KASPA_TPU_VERIFY_MODE env var (default "ladder").  Returns the raw
    mode now in force."""
    global _verify_mode
    if mode is not None and mode not in VERIFY_MODES:
        raise ValueError(f"verify mode {mode!r} not in {VERIFY_MODES}")
    with _cfg_lock:
        _verify_mode = mode
    return verify_mode()


def verify_mode() -> str:
    """The raw configured mode ("ladder" | "aggregate" | "auto")."""
    m = _verify_mode
    if m is None:
        m = os.environ.get("KASPA_TPU_VERIFY_MODE", "ladder")
    return m if m in VERIFY_MODES else "ladder"


def _aggregate_crossover() -> int:
    """Batch size where the aggregate lane starts winning, from the bench
    sweep artifact's ``aggregate.crossover_batch`` (bench.py --sweep), with
    a conservative default when no measurement exists."""
    path = os.environ.get(
        "KASPA_TPU_BENCH_SWEEP_PATH",
        os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "BENCH_SWEEP.json"),
    )
    try:
        with open(path) as f:
            agg = json.load(f).get("aggregate", {})
        x = int(agg.get("crossover_batch", 0))
        return x if x > 0 else _DEFAULT_AGG_CROSSOVER
    except (OSError, ValueError, TypeError):
        return _DEFAULT_AGG_CROSSOVER


def resolve_verify_mode(kind: str, jobs: int) -> str:
    """The lane one concrete batch should take: "ladder" or "aggregate"."""
    if base_kind(kind) != "schnorr" or jobs <= 0:
        return "ladder"
    m = verify_mode()
    if m == "auto":
        return "aggregate" if jobs >= _aggregate_crossover() else "ladder"
    return m


def _flush_age_s() -> float:
    return float(os.environ.get("KASPA_TPU_COALESCE_AGE_MS", "2")) / 1000.0


def _tx_class_spec(block_target: int) -> tuple[int, float]:
    """(target, age) for the standalone_tx class.  Admission batches are
    built from concurrent submitters, not block-sized slabs: the default
    target is smaller than the block-replay target and the flush age a bit
    longer, so a burst of independent submitters coalesces while a lone
    submitter still resolves within single-digit milliseconds."""
    raw = os.environ.get("KASPA_TPU_TX_COALESCE", "")
    target = int(raw) if raw else min(block_target, DEFAULT_TX_TARGET)
    age = float(os.environ.get("KASPA_TPU_TX_COALESCE_AGE_MS", "5")) / 1000.0
    return max(_TARGET_MIN, min(_TARGET_MAX, target)), age


def _sweep_target() -> int:
    """Adaptive super-batch target: the best-throughput batch recorded by
    `bench.py --sweep` for the active mesh size, else DEFAULT_TARGET."""
    path = os.environ.get(
        "KASPA_TPU_BENCH_SWEEP_PATH",
        os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "BENCH_SWEEP.json"),
    )
    try:
        with open(path) as f:
            best = json.load(f).get("best", {})
    except (OSError, ValueError):
        return DEFAULT_TARGET
    from kaspa_tpu.ops import mesh

    n = mesh.active_size()
    for key in (f"schnorr/mesh{n}", "schnorr/mesh1"):
        entry = best.get(key)
        if entry and entry.get("batch"):
            return int(entry["batch"])
    return DEFAULT_TARGET


def configure(spec: int | str | None) -> int:
    """Select the process-wide coalescing mode; returns the resolved
    super-batch target (0 = disabled, the default).

    spec: None/0/"off" disable; "auto" seeds the target from
    BENCH_SWEEP.json; an integer pins the target.  With no explicit spec
    the KASPA_TPU_COALESCE env var is consulted the same way.
    """
    global _configured, _engine
    with _cfg_lock:
        raw = spec if spec is not None else os.environ.get("KASPA_TPU_COALESCE", "0")
        _configured = raw
        old, _engine = _engine, None
    if old is not None:
        old.close(timeout=10.0)
    if raw in (0, "0", "", "off", None):
        return 0
    target = _sweep_target() if raw == "auto" else int(raw)
    target = max(_TARGET_MIN, min(_TARGET_MAX, target))
    with _cfg_lock:
        _engine = CoalescingDispatcher(
            target, _flush_age_s(), class_specs={TX_CLASS: _tx_class_spec(target)}
        )
    return target


def install(engine) -> None:
    """Install a custom dispatch engine as the process-wide verify engine
    (the fabric balancer uses this to become what `active()` returns, so
    BatchScriptChecker / the pipeline / daemon shutdown pick it up
    unchanged).  Any engine exposing the CoalescingDispatcher surface —
    submit/nudge/drain/close/abandon/stats — qualifies; a previously live
    engine is retired first."""
    global _configured, _engine
    with _cfg_lock:
        old, _engine = _engine, engine
        _configured = getattr(engine, "label", type(engine).__name__)
    if old is not None and old is not engine:
        old.close(timeout=10.0)


def active() -> CoalescingDispatcher | None:
    """The live engine, or None when coalescing is disabled."""
    return _engine


def drain(timeout: float = 10.0) -> bool:
    """Flush + resolve everything in flight (daemon-shutdown barrier).
    No-op True when coalescing is disabled."""
    eng = _engine
    return eng.drain(timeout) if eng is not None else True


def shutdown(timeout: float = 10.0) -> bool:
    """Daemon-stop barrier: drain and retire the engine, abandoning it if
    the device thread is hung so process exit stays bounded.  True = clean
    drain; False = tickets were failed with DispatchAbandoned."""
    global _engine
    with _cfg_lock:
        eng, _engine = _engine, None
    return eng.close(timeout, abandon=True) if eng is not None else True


def _dispatch_state() -> dict:
    eng = _engine
    if eng is None:
        return {
            "enabled": False,
            "configured": str(_configured) if _configured is not None else "",
            "verify_mode": verify_mode(),
        }
    out = {"enabled": True, "configured": str(_configured), "verify_mode": verify_mode()}
    out.update(eng.stats())
    return out


REGISTRY.register_collector("dispatch", _dispatch_state)
