from kaspa_tpu.wallet.bip32 import ExtendedKey  # noqa: F401
from kaspa_tpu.wallet.account import Account  # noqa: F401
