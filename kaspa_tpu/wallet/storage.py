"""Encrypted persistent wallet storage.

Reference: wallet/core/src/storage/local — a versioned, password-encrypted
wallet document holding key data, accounts, address derivation state and
metadata.  Scheme here: scrypt KDF (per-save random salt) -> 64 bytes
split into a ChaCha20 stream key and an HMAC-SHA256 key;
encrypt-then-MAC over the JSON payload.  Tampering (any byte of salt,
ciphertext or tag) and wrong passwords fail closed before parsing.

File layout (all raw bytes, little-endian lengths):
    magic "KTWL" | version u16 | salt(16) | nonce-counter u64 |
    ciphertext len u32 | ciphertext | hmac-sha256(32)
"""

from __future__ import annotations

import hashlib
import hmac
import json
import os
import secrets
import struct

import numpy as np

from kaspa_tpu.crypto import chacha

MAGIC = b"KTWL"
VERSION = 1
_SCRYPT_N, _SCRYPT_R, _SCRYPT_P = 1 << 14, 8, 1


class WalletStorageError(Exception):
    pass


def _derive_keys(password: str, salt: bytes) -> tuple[bytes, bytes]:
    material = hashlib.scrypt(
        password.encode(), salt=salt, n=_SCRYPT_N, r=_SCRYPT_R, p=_SCRYPT_P, maxmem=64 * 1024 * 1024, dklen=64
    )
    return material[:32], material[32:]


def _keystream(key32: bytes, n: int) -> bytes:
    ks = chacha.keystream(np.frombuffer(key32, dtype=np.uint8).reshape(1, 32), n)
    return ks.tobytes()[:n]


def encrypt_payload(password: str, payload: bytes) -> bytes:
    salt = secrets.token_bytes(16)
    enc_key, mac_key = _derive_keys(password, salt)
    ct = bytes(a ^ b for a, b in zip(payload, _keystream(enc_key, len(payload))))
    head = MAGIC + struct.pack("<H", VERSION) + salt + struct.pack("<QI", 0, len(ct))
    tag = hmac.new(mac_key, head + ct, hashlib.sha256).digest()
    return head + ct + tag


def decrypt_payload(password: str, blob: bytes) -> bytes:
    if len(blob) < 4 + 2 + 16 + 12 + 32 or blob[:4] != MAGIC:
        raise WalletStorageError("not a wallet file")
    (version,) = struct.unpack_from("<H", blob, 4)
    if version != VERSION:
        raise WalletStorageError(f"unsupported wallet version {version}")
    salt = blob[6:22]
    (_, ct_len) = struct.unpack_from("<QI", blob, 22)
    ct_start = 34
    ct = blob[ct_start : ct_start + ct_len]
    tag = blob[ct_start + ct_len : ct_start + ct_len + 32]
    if len(ct) != ct_len or len(tag) != 32:
        raise WalletStorageError("truncated wallet file")
    enc_key, mac_key = _derive_keys(password, salt)
    expect = hmac.new(mac_key, blob[:ct_start] + ct, hashlib.sha256).digest()
    if not hmac.compare_digest(tag, expect):
        raise WalletStorageError("wrong password or corrupted wallet file")
    return bytes(a ^ b for a, b in zip(ct, _keystream(enc_key, len(ct))))


class WalletStorage:
    """The wallet document: key data + accounts + derivation state.

    ``document`` shape (storage/local/wallet.rs equivalent):
      {"keydata": [{"id", "seed_hex"}],
       "accounts": [{"keydata_id", "account_index", "prefix",
                     "receive_index", "change_index", "name"}],
       "metadata": {...}}
    """

    def __init__(self, path: str):
        self.path = path
        self.document: dict = {"keydata": [], "accounts": [], "metadata": {}}

    # --- lifecycle ---

    @classmethod
    def create(cls, path: str, password: str, seed: bytes, account_name: str = "default", prefix: str = "kaspasim") -> "WalletStorage":
        if os.path.exists(path):
            raise WalletStorageError(f"wallet file already exists: {path}")
        ws = cls(path)
        kd_id = hashlib.sha256(seed).hexdigest()[:16]
        ws.document["keydata"].append({"id": kd_id, "seed_hex": seed.hex()})
        ws.document["accounts"].append(
            {
                "keydata_id": kd_id,
                "account_index": 0,
                "prefix": prefix,
                "receive_index": 1,
                "change_index": 0,
                "name": account_name,
            }
        )
        ws.save(password)
        return ws

    @classmethod
    def open(cls, path: str, password: str) -> "WalletStorage":
        ws = cls(path)
        with open(path, "rb") as f:
            blob = f.read()
        ws.document = json.loads(decrypt_payload(password, blob))
        return ws

    def save(self, password: str) -> None:
        blob = encrypt_payload(password, json.dumps(self.document).encode())
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    # --- account access ---

    def seed_for(self, account: dict) -> bytes:
        for kd in self.document["keydata"]:
            if kd["id"] == account["keydata_id"]:
                return bytes.fromhex(kd["seed_hex"])
        raise WalletStorageError(f"keydata {account['keydata_id']} missing")

    def accounts(self) -> list[dict]:
        return self.document["accounts"]

    def load_account(self, index: int = 0):
        """Materialize an Account, restoring its derivation watermark."""
        from kaspa_tpu.wallet.account import Account

        meta = self.document["accounts"][index]
        acct = Account.from_seed(self.seed_for(meta), meta["account_index"], meta["prefix"])
        while len(acct.receive_keys) < meta["receive_index"]:
            acct.derive_receive_address()
        return acct

    def bump_receive_index(self, index: int, password: str) -> None:
        self.document["accounts"][index]["receive_index"] += 1
        self.save(password)
