"""Wallet-side mass/fee estimation (wallet/core/src/tx/mass.rs).

The wallet prices transactions BEFORE signing: serialized sizes are
estimated deterministically, unsigned inputs are charged the standard
Schnorr signature size per required signature, and the overall mass is
max(compute, storage) exactly as consensus will compute it.  Every formula
below is a line-for-line numeric port of the cited mass.rs items — wallets
tune change/dust decisions against these exact numbers.
"""

from __future__ import annotations

from kaspa_tpu.consensus.mass import MassCalculator as ConsensusMassCalculator

HASH_SIZE = 32
SUBNETWORK_ID_SIZE = 20
# 1 byte OP_DATA_65 + 64-byte signature + 1 byte sighash type (mass.rs:16)
SIGNATURE_SIZE = 66
# sompi per 1000 grams of mass (mass.rs:21)
MINIMUM_RELAY_TRANSACTION_FEE = 100_000
# standardness ceiling (mass.rs:25)
MAXIMUM_STANDARD_TRANSACTION_MASS = 100_000
MAX_SOMPI = 29_000_000_000 * 100_000_000  # consensus/core constants
# max standard script-public-key vector size used for standard outputs
SCRIPT_VECTOR_SIZE = 36


def calc_minimum_required_transaction_relay_fee(mass: int) -> int:
    """mass.rs:29-45: scale the base fee by mass; floor at the base fee."""
    minimum_fee = (mass * MINIMUM_RELAY_TRANSACTION_FEE) // 1000
    if minimum_fee == 0:
        minimum_fee = MINIMUM_RELAY_TRANSACTION_FEE
    return min(minimum_fee, MAX_SOMPI)


def outpoint_serialized_size() -> int:
    """mass.rs:182-187: txid hash + u32 index."""
    return HASH_SIZE + 4


def transaction_input_serialized_byte_size(inp) -> int:
    """mass.rs:173-181."""
    return outpoint_serialized_size() + 8 + len(inp.signature_script) + 8


def transaction_output_serialized_byte_size(out) -> int:
    """mass.rs:190-196."""
    return 8 + 2 + 8 + len(out.script_public_key.script)


def transaction_standard_output_serialized_byte_size() -> int:
    """mass.rs:198-205 (standard output priced at the max script vector)."""
    return 8 + 2 + 8 + SCRIPT_VECTOR_SIZE


STANDARD_OUTPUT_SIZE_PLUS_INPUT_SIZE = transaction_standard_output_serialized_byte_size() + 148
STANDARD_OUTPUT_SIZE_PLUS_INPUT_SIZE_3X = STANDARD_OUTPUT_SIZE_PLUS_INPUT_SIZE * 3


def blank_transaction_serialized_byte_size() -> int:
    """mass.rs:154-171: fixed fields of an input/output-less tx."""
    return 2 + 8 + 8 + 8 + SUBNETWORK_ID_SIZE + 8 + HASH_SIZE + 8


def transaction_serialized_byte_size(tx) -> int:
    """mass.rs:131-153."""
    return (
        blank_transaction_serialized_byte_size()
        + sum(transaction_input_serialized_byte_size(i) for i in tx.inputs)
        + sum(transaction_output_serialized_byte_size(o) for o in tx.outputs)
        + len(tx.payload)
    )


class WalletMassCalculator:
    """wallet/core/src/tx/mass.rs MassCalculator."""

    def __init__(self, params):
        self.mass_per_tx_byte = params.mass_per_tx_byte
        self.mass_per_script_pub_key_byte = params.mass_per_script_pub_key_byte
        self.mass_per_sig_op = params.mass_per_sig_op
        self.storage_mass_parameter = params.storage_mass_parameter
        self._consensus_mc = ConsensusMassCalculator(
            mass_per_tx_byte=params.mass_per_tx_byte,
            mass_per_script_pub_key_byte=params.mass_per_script_pub_key_byte,
            mass_per_sig_op=params.mass_per_sig_op,
            storage_mass_parameter=params.storage_mass_parameter,
        )

    # -- dust (mass.rs:227-233) ------------------------------------------

    def is_dust(self, value: int) -> bool:
        return (value * 1000) // STANDARD_OUTPUT_SIZE_PLUS_INPUT_SIZE_3X < MINIMUM_RELAY_TRANSACTION_FEE

    # -- compute mass (mass.rs:236-291) ----------------------------------

    def blank_transaction_compute_mass(self) -> int:
        return blank_transaction_serialized_byte_size() * self.mass_per_tx_byte

    def calc_compute_mass_for_payload(self, payload_byte_size: int) -> int:
        # the payload byte term is hardened against the normalized transient
        # byte factor (mass.rs:245-258)
        normalized_transient_byte_factor = 2
        return payload_byte_size * max(self.mass_per_tx_byte, normalized_transient_byte_factor)

    def calc_compute_mass_for_output(self, out) -> int:
        return (
            self.mass_per_script_pub_key_byte * (2 + len(out.script_public_key.script))
            + transaction_output_serialized_byte_size(out) * self.mass_per_tx_byte
        )

    def calc_compute_mass_for_input(self, inp, tx_version: int = 0) -> int:
        """Per-input grams.  The reference leaves budget commits as a TODO
        ("Add support for v1 transactions", mass.rs:272); here they are
        charged exactly like consensus does (consensus/mass.py:162-165,
        GRAMS_PER_COMPUTE_BUDGET_UNIT) so the wallet never under-prices a
        v1 spend."""
        from kaspa_tpu.consensus.mass import GRAMS_PER_COMPUTE_BUDGET_UNIT

        if tx_version >= 1:
            script_mass = GRAMS_PER_COMPUTE_BUDGET_UNIT * (inp.compute_commit.compute_budget() or 0)
        else:
            script_mass = (inp.compute_commit.sig_op_count() or 0) * self.mass_per_sig_op
        return script_mass + transaction_input_serialized_byte_size(inp) * self.mass_per_tx_byte

    def calc_signature_compute_mass_for_inputs(self, number_of_inputs: int, minimum_signatures: int = 1) -> int:
        return SIGNATURE_SIZE * self.mass_per_tx_byte * max(minimum_signatures, 1) * number_of_inputs

    def calc_compute_mass_for_signed_transaction(self, tx) -> int:
        return (
            self.blank_transaction_compute_mass()
            + self.calc_compute_mass_for_payload(len(tx.payload))
            + sum(self.calc_compute_mass_for_output(o) for o in tx.outputs)
            + sum(self.calc_compute_mass_for_input(i, tx.version) for i in tx.inputs)
        )

    def estimate_standard_compute_mass(
        self, n_inputs: int, n_outputs: int, sig_op_count: int = 1, minimum_signatures: int = 1
    ) -> int:
        """Pre-selection estimate for a standard shape: unsigned inputs
        (fixed fields only) + standard-size outputs + signature mass —
        the generator's UTXO-selection steering surface."""
        input_size = outpoint_serialized_size() + 8 + 8  # empty script
        size = (
            blank_transaction_serialized_byte_size()
            + n_inputs * input_size
            + n_outputs * transaction_standard_output_serialized_byte_size()
        )
        return (
            size * self.mass_per_tx_byte
            + self.calc_signature_compute_mass_for_inputs(n_inputs, minimum_signatures)
            + n_inputs * sig_op_count * self.mass_per_sig_op
            + n_outputs * self.mass_per_script_pub_key_byte * (2 + SCRIPT_VECTOR_SIZE)
        )

    def calc_compute_mass_for_unsigned_transaction(self, tx, minimum_signatures: int = 1) -> int:
        return self.calc_compute_mass_for_signed_transaction(tx) + self.calc_signature_compute_mass_for_inputs(
            len(tx.inputs), minimum_signatures
        )

    # -- storage + overall (mass.rs:298-330) -----------------------------

    def calc_storage_mass(self, tx, entries) -> int:
        sm = self._consensus_mc.calc_contextual_masses(tx, entries)
        if sm is None:
            # the reference surfaces this as MassCalculationError
            raise ValueError("storage mass incomputable for this transaction shape")
        return sm

    def combine_mass(self, compute_mass: int, storage_mass: int) -> int:
        return max(compute_mass, storage_mass)

    def calc_overall_mass_for_unsigned_transaction(self, tx, entries, minimum_signatures: int = 1) -> int:
        return self.combine_mass(
            self.calc_compute_mass_for_unsigned_transaction(tx, minimum_signatures),
            self.calc_storage_mass(tx, entries),
        )

    def calc_minimum_transaction_fee_from_mass(self, mass: int) -> int:
        return calc_minimum_required_transaction_relay_fee(mass)
