"""Wallet account: address derivation, UTXO tracking, spend building/signing.

Reference: wallet/core (accounts over bip32 derivations, the UTXO
processor/context tracking virtual UtxosChanged, and the tx generator).
This round covers the single-signer P2PK account: derive receive addresses,
track spendable UTXOs through the utxoindex, build + schnorr-sign spends,
and submit via the mining manager / RPC service.
"""

from __future__ import annotations

from dataclasses import dataclass

from kaspa_tpu.consensus import hashing as chash
from kaspa_tpu.consensus.model import Transaction, TransactionInput, TransactionOutput
from kaspa_tpu.consensus.model.tx import SUBNETWORK_ID_NATIVE, ComputeCommit
from kaspa_tpu.crypto import eclib
from kaspa_tpu.crypto.addresses import Address, VERSION_PUBKEY
from kaspa_tpu.txscript import standard
from kaspa_tpu.wallet.bip32 import ExtendedKey, kaspa_account_path


class WalletError(Exception):
    pass


@dataclass
class DerivedAddress:
    index: int
    key: ExtendedKey
    address: Address

    @property
    def spk(self):
        return standard.pay_to_pub_key(self.key.x_only_public_key())


class Account:
    def __init__(self, master: ExtendedKey, account_index: int = 0, prefix: str = "kaspasim"):
        self.prefix = prefix
        self.account_key = master.derive_path(kaspa_account_path(account_index))
        self._external_chain = self.account_key.derive_child(0)  # receive chain node
        self.receive_keys: list[DerivedAddress] = []
        self.derive_receive_address()  # index 0

    @staticmethod
    def from_seed(seed: bytes, account_index: int = 0, prefix: str = "kaspasim") -> "Account":
        return Account(ExtendedKey.from_seed(seed), account_index, prefix)

    def derive_receive_address(self) -> DerivedAddress:
        i = len(self.receive_keys)
        key = self._external_chain.derive_child(i)
        addr = Address(self.prefix, VERSION_PUBKEY, key.x_only_public_key())
        derived = DerivedAddress(i, key, addr)
        self.receive_keys.append(derived)
        return derived

    def addresses(self) -> list[str]:
        return [d.address.to_string() for d in self.receive_keys]

    # --- utxo scanning (wallet/core utxo processor, via the utxoindex) ---

    def spendable_utxos(self, utxoindex, virtual_daa_score: int, coinbase_maturity: int):
        out = []
        for d in self.receive_keys:
            for outpoint, entry in utxoindex.get_utxos_by_script(d.spk.script).items():
                if entry.is_coinbase and entry.block_daa_score + coinbase_maturity > virtual_daa_score:
                    continue
                out.append((outpoint, entry, d))
        return out

    def balance(self, utxoindex) -> int:
        return sum(utxoindex.get_balance_by_script(d.spk.script) for d in self.receive_keys)

    # --- spend building + signing (wallet/core tx generator + sign.rs) ---

    def build_send(self, utxoindex, to_address: str, amount: int, fee: int, virtual_daa_score: int, coinbase_maturity: int, aux=b"\x00" * 32, mass_calculator=None) -> Transaction:
        spendables = self.spendable_utxos(utxoindex, virtual_daa_score, coinbase_maturity)
        spendables.sort(key=lambda t: -t[1].amount)
        selected = []
        total = 0
        for outpoint, entry, d in spendables:
            selected.append((outpoint, entry, d))
            total += entry.amount
            if total >= amount + fee:
                break
        if total < amount + fee:
            raise WalletError(f"insufficient funds: have {total}, need {amount + fee}")

        from kaspa_tpu.crypto.addresses import pay_to_address_script

        outputs = [TransactionOutput(amount, pay_to_address_script(Address.from_string(to_address)))]
        change = total - amount - fee
        if change > 0:
            outputs.append(TransactionOutput(change, self.receive_keys[0].spk))
        inputs = [TransactionInput(op, b"", 0, ComputeCommit.sigops(1)) for op, _, _ in selected]
        tx = Transaction(0, inputs, outputs, 0, SUBNETWORK_ID_NATIVE, 0, b"")

        entries = [e for _, e, _ in selected]
        if mass_calculator is None:
            from kaspa_tpu.consensus.mass import MassCalculator

            mass_calculator = MassCalculator()
        tx.storage_mass = mass_calculator.calc_contextual_masses(tx, entries)
        reused = chash.SigHashReusedValues()
        for i, (_, entry, derived) in enumerate(selected):
            msg = chash.calc_schnorr_signature_hash(tx, entries, i, chash.SIG_HASH_ALL, reused)
            sig = eclib.schnorr_sign(msg, derived.key.key, aux)
            tx.inputs[i].signature_script = standard.schnorr_signature_script(sig, chash.SIG_HASH_ALL)
        return tx
