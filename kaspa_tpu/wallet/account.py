"""Wallet account: address derivation, UTXO tracking, spend building/signing.

Reference: wallet/core (accounts over bip32 derivations, the UTXO
processor/context tracking virtual UtxosChanged, and the tx generator).
This round covers the single-signer P2PK account: derive receive addresses,
track spendable UTXOs through the utxoindex, build + schnorr-sign spends,
and submit via the mining manager / RPC service.
"""

from __future__ import annotations

from dataclasses import dataclass

from kaspa_tpu.consensus import hashing as chash
from kaspa_tpu.consensus.model import Transaction, TransactionInput, TransactionOutput
from kaspa_tpu.consensus.model.tx import SUBNETWORK_ID_NATIVE, ComputeCommit
from kaspa_tpu.crypto import eclib
from kaspa_tpu.crypto.addresses import Address, VERSION_PUBKEY
from kaspa_tpu.txscript import standard
from kaspa_tpu.wallet.bip32 import ExtendedKey, kaspa_account_path


class WalletError(Exception):
    pass


@dataclass
class DerivedAddress:
    index: int
    key: ExtendedKey
    address: Address

    @property
    def spk(self):
        return standard.pay_to_pub_key(self.key.x_only_public_key())


@dataclass
class MultisigAddress:
    index: int
    redeem_script: bytes
    address: Address

    @property
    def spk(self):
        return standard.pay_to_script_hash_script(self.redeem_script)


class MultisigAccount:
    """m-of-n schnorr multisig account (wallet/core account/variants/
    multisig.rs): every cosigner derives the same chain; addresses are
    P2SH over the ordered-keys redeem script, spends carry m signatures in
    key order plus the redeem script push."""

    def __init__(self, masters: list[ExtendedKey], required: int, account_index: int = 0, prefix: str = "kaspasim"):
        if not masters:
            raise WalletError("multisig needs at least one cosigner key")
        if not (1 <= required <= len(masters)):
            raise WalletError(f"invalid m-of-n: {required} of {len(masters)}")
        self.prefix = prefix
        self.required = required
        self._chains = [
            m.derive_path(kaspa_account_path(account_index)).derive_child(0) for m in masters
        ]
        self.receive_keys: list[MultisigAddress] = []
        self.derive_receive_address()

    @staticmethod
    def from_seeds(seeds: list[bytes], required: int, account_index: int = 0, prefix: str = "kaspasim") -> "MultisigAccount":
        return MultisigAccount([ExtendedKey.from_seed(s) for s in seeds], required, account_index, prefix)

    def _keys_at(self, index: int) -> list[ExtendedKey]:
        return [chain.derive_child(index) for chain in self._chains]

    def derive_receive_address(self) -> MultisigAddress:
        i = len(self.receive_keys)
        keys = self._keys_at(i)
        redeem = standard.multisig_redeem_script(
            [k.x_only_public_key() for k in keys], self.required
        )
        spk = standard.pay_to_script_hash_script(redeem)
        from kaspa_tpu.crypto.addresses import extract_script_pub_key_address

        derived = MultisigAddress(i, redeem, extract_script_pub_key_address(spk, self.prefix))
        self.receive_keys.append(derived)
        return derived

    def addresses(self) -> list[str]:
        return [d.address.to_string() for d in self.receive_keys]

    def spendable_utxos(self, utxoindex, virtual_daa_score: int, coinbase_maturity: int):
        out = []
        for d in self.receive_keys:
            for outpoint, entry in utxoindex.get_utxos_by_script(d.spk.script).items():
                if entry.is_coinbase and entry.block_daa_score + coinbase_maturity > virtual_daa_score:
                    continue
                out.append((outpoint, entry, d))
        return out

    def balance(self, utxoindex) -> int:
        return sum(utxoindex.get_balance_by_script(d.spk.script) for d in self.receive_keys)

    def build_send(
        self, utxoindex, to_address: str, amount: int, fee: int, virtual_daa_score: int,
        coinbase_maturity: int, signer_indices: list[int] | None = None, aux=b"\x00" * 32,
        mass_calculator=None,
    ) -> Transaction:
        """Build + sign an m-of-n spend.  ``signer_indices`` picks which
        cosigners sign (defaults to the first m); signatures are emitted in
        key order as OpCheckMultiSig verifies them positionally."""
        if signer_indices is None:
            signer_indices = list(range(self.required))
        signer_indices = sorted(set(signer_indices))
        if len(signer_indices) != self.required:
            raise WalletError(
                f"need exactly {self.required} distinct signers, got {len(signer_indices)}"
            )
        if any(i < 0 or i >= len(self._chains) for i in signer_indices):
            raise WalletError(f"signer index out of range (0..{len(self._chains) - 1})")
        spendables = self.spendable_utxos(utxoindex, virtual_daa_score, coinbase_maturity)
        spendables.sort(key=lambda t: -t[1].amount)
        selected, total = [], 0
        for outpoint, entry, d in spendables:
            selected.append((outpoint, entry, d))
            total += entry.amount
            if total >= amount + fee:
                break
        if total < amount + fee:
            raise WalletError(f"insufficient funds: have {total}, need {amount + fee}")

        from kaspa_tpu.crypto.addresses import pay_to_address_script
        from kaspa_tpu.txscript.script_builder import ScriptBuilder

        outputs = [TransactionOutput(amount, pay_to_address_script(Address.from_string(to_address)))]
        change = total - amount - fee
        if change > 0:
            outputs.append(TransactionOutput(change, self.receive_keys[0].spk))
        # the sig-op commit covers the KEY count, not the signature count:
        # OpCheckMultiSig may attempt a verify per key while matching
        # signatures positionally (vm._op_checkmultisig_impl; the
        # reference's static counter charges n for CheckMultiSig too)
        n_keys = len(self._chains)
        inputs = [TransactionInput(op, b"", 0, ComputeCommit.sigops(n_keys)) for op, _, _ in selected]
        tx = Transaction(0, inputs, outputs, 0, SUBNETWORK_ID_NATIVE, 0, b"")

        entries = [e for _, e, _ in selected]
        if mass_calculator is None:
            from kaspa_tpu.consensus.mass import MassCalculator

            mass_calculator = MassCalculator()
        tx.storage_mass = mass_calculator.calc_contextual_masses(tx, entries)
        reused = chash.SigHashReusedValues()
        for i, (_, entry, derived) in enumerate(selected):
            msg = chash.calc_schnorr_signature_hash(tx, entries, i, chash.SIG_HASH_ALL, reused)
            keys = self._keys_at(derived.index)
            b = ScriptBuilder()
            for s_idx in signer_indices:
                sig = eclib.schnorr_sign(msg, keys[s_idx].key, aux)
                b.add_data(sig + bytes([chash.SIG_HASH_ALL]))
            b.add_data(derived.redeem_script)
            tx.inputs[i].signature_script = b.drain()
        return tx


class Account:
    def __init__(self, master: ExtendedKey, account_index: int = 0, prefix: str = "kaspasim"):
        self.prefix = prefix
        self.account_key = master.derive_path(kaspa_account_path(account_index))
        self._external_chain = self.account_key.derive_child(0)  # receive chain node
        self.receive_keys: list[DerivedAddress] = []
        self.derive_receive_address()  # index 0

    @staticmethod
    def from_seed(seed: bytes, account_index: int = 0, prefix: str = "kaspasim") -> "Account":
        return Account(ExtendedKey.from_seed(seed), account_index, prefix)

    def derive_receive_address(self) -> DerivedAddress:
        i = len(self.receive_keys)
        key = self._external_chain.derive_child(i)
        addr = Address(self.prefix, VERSION_PUBKEY, key.x_only_public_key())
        derived = DerivedAddress(i, key, addr)
        self.receive_keys.append(derived)
        return derived

    def addresses(self) -> list[str]:
        return [d.address.to_string() for d in self.receive_keys]

    # --- utxo scanning (wallet/core utxo processor, via the utxoindex) ---

    def spendable_utxos(self, utxoindex, virtual_daa_score: int, coinbase_maturity: int):
        out = []
        for d in self.receive_keys:
            for outpoint, entry in utxoindex.get_utxos_by_script(d.spk.script).items():
                if entry.is_coinbase and entry.block_daa_score + coinbase_maturity > virtual_daa_score:
                    continue
                out.append((outpoint, entry, d))
        return out

    def balance(self, utxoindex) -> int:
        return sum(utxoindex.get_balance_by_script(d.spk.script) for d in self.receive_keys)

    # --- spend building + signing (wallet/core tx generator + sign.rs) ---

    def build_send(self, utxoindex, to_address: str, amount: int, fee: int, virtual_daa_score: int, coinbase_maturity: int, aux=b"\x00" * 32, mass_calculator=None) -> Transaction:
        spendables = self.spendable_utxos(utxoindex, virtual_daa_score, coinbase_maturity)
        spendables.sort(key=lambda t: -t[1].amount)
        selected = []
        total = 0
        for outpoint, entry, d in spendables:
            selected.append((outpoint, entry, d))
            total += entry.amount
            if total >= amount + fee:
                break
        if total < amount + fee:
            raise WalletError(f"insufficient funds: have {total}, need {amount + fee}")

        from kaspa_tpu.crypto.addresses import pay_to_address_script

        outputs = [TransactionOutput(amount, pay_to_address_script(Address.from_string(to_address)))]
        change = total - amount - fee
        if change > 0:
            outputs.append(TransactionOutput(change, self.receive_keys[0].spk))
        inputs = [TransactionInput(op, b"", 0, ComputeCommit.sigops(1)) for op, _, _ in selected]
        tx = Transaction(0, inputs, outputs, 0, SUBNETWORK_ID_NATIVE, 0, b"")

        entries = [e for _, e, _ in selected]
        if mass_calculator is None:
            from kaspa_tpu.consensus.mass import MassCalculator

            mass_calculator = MassCalculator()
        tx.storage_mass = mass_calculator.calc_contextual_masses(tx, entries)
        reused = chash.SigHashReusedValues()
        for i, (_, entry, derived) in enumerate(selected):
            msg = chash.calc_schnorr_signature_hash(tx, entries, i, chash.SIG_HASH_ALL, reused)
            sig = eclib.schnorr_sign(msg, derived.key.key, aux)
            tx.inputs[i].signature_script = standard.schnorr_signature_script(sig, chash.SIG_HASH_ALL)
        return tx
