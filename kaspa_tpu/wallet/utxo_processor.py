"""Wallet UTXO processor: notification-fed balance tracking + events.

Reference: wallet/core/src/utxo/processor.rs + context.rs — the wallet
side of the notify pipeline.  Subscribes to utxos-changed for the
account's addresses, maintains mature/pending partitions (coinbase
maturity by DAA score), and emits typed events (balance / pending /
maturity / discovery) to registered listeners — the reference's
multiplexer stream, as plain callbacks.
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum


def _wire_utxo_pair(u: dict):
    """Decode one wire utxo record into (TransactionOutpoint, UtxoEntry)."""
    from kaspa_tpu.consensus.model import ScriptPublicKey, TransactionOutpoint, UtxoEntry

    op = TransactionOutpoint(bytes.fromhex(u["outpoint"]["transaction_id"]), u["outpoint"]["index"])
    e = u["utxo_entry"]
    spk = e.get("script_public_key", {})
    entry = UtxoEntry(
        amount=e["amount"],
        script_public_key=ScriptPublicKey(spk.get("version", 0), bytes.fromhex(spk.get("script", ""))),
        block_daa_score=e["block_daa_score"],
        is_coinbase=e["is_coinbase"],
    )
    return op, entry


class WalletEventType(Enum):
    BALANCE = "balance"
    PENDING = "pending"
    MATURITY = "maturity"
    DISCOVERY = "discovery"


@dataclass
class WalletEvent:
    type: WalletEventType
    data: dict


@dataclass
class Balance:
    mature: int = 0
    pending: int = 0  # immature coinbase value

    @property
    def total(self) -> int:
        return self.mature + self.pending


class UtxoProcessor:
    def __init__(self, account, coinbase_maturity: int):
        self.account = account
        self.coinbase_maturity = coinbase_maturity
        self._scripts = {d.spk.script for d in account.receive_keys}
        self._mature: dict = {}  # outpoint -> entry
        self._pending: dict = {}  # immature coinbase
        self._listeners: list = []
        self._virtual_daa = 0

    # --- wiring ---

    def add_listener(self, cb) -> None:
        self._listeners.append(cb)

    def _emit(self, etype: WalletEventType, **data) -> None:
        ev = WalletEvent(etype, data)
        for cb in self._listeners:
            cb(ev)

    def track_new_address(self, derived) -> None:
        self._scripts.add(derived.spk.script)

    # --- feed (notify/notifier.py listener signature) ---

    def on_utxos_changed(self, added, removed, virtual_daa_score: int) -> None:
        """added/removed: [(outpoint, entry)]; the UtxosChanged payload."""
        self._virtual_daa = virtual_daa_score
        changed = False
        for op, entry in removed:
            if self._mature.pop(op, None) is not None or self._pending.pop(op, None) is not None:
                changed = True
        for op, entry in added:
            if entry.script_public_key.script not in self._scripts:
                continue
            changed = True
            if entry.is_coinbase and entry.block_daa_score + self.coinbase_maturity > virtual_daa_score:
                self._pending[op] = entry
                self._emit(WalletEventType.PENDING, outpoint=op, amount=entry.amount)
            else:
                self._mature[op] = entry
                self._emit(WalletEventType.DISCOVERY, outpoint=op, amount=entry.amount)
        self._revalidate_maturity()
        if changed:
            self._emit(WalletEventType.BALANCE, balance=self.balance())

    def on_virtual_daa_score_changed(self, virtual_daa_score: int) -> None:
        self._virtual_daa = virtual_daa_score
        if self._revalidate_maturity():
            self._emit(WalletEventType.BALANCE, balance=self.balance())

    def _revalidate_maturity(self) -> bool:
        matured = [
            op
            for op, e in self._pending.items()
            if e.block_daa_score + self.coinbase_maturity <= self._virtual_daa
        ]
        for op in matured:
            entry = self._pending.pop(op)
            self._mature[op] = entry
            self._emit(WalletEventType.MATURITY, outpoint=op, amount=entry.amount)
        return bool(matured)

    # --- remote feed (the RPC-wire subscriber path) ---

    def feed_wire_notification(self, event: str, data: dict) -> None:
        """Consume a streamed (event, data) pair from a NotificationClient
        subscription — the wallet-over-the-wire path (processor.rs consuming
        the gRPC notification stream)."""
        if event == "utxos-changed":
            added = [_wire_utxo_pair(u) for u in data.get("added", [])]
            removed = [_wire_utxo_pair(u) for u in data.get("removed", [])]
            daa = data.get("virtual_daa_score", self._virtual_daa)
            self.on_utxos_changed(added, removed, daa)
        elif event == "virtual-daa-score-changed":
            self.on_virtual_daa_score_changed(data["daa_score"])

    # --- queries ---

    def balance(self) -> Balance:
        return Balance(
            mature=sum(e.amount for e in self._mature.values()),
            pending=sum(e.amount for e in self._pending.values()),
        )

    def mature_utxos(self) -> dict:
        return dict(self._mature)
