"""PSKT: partially-signed kaspa transactions (multisig signing flows).

Reference: wallet/pskt (the kaspa-wallet-pskt crate) — a transaction
passes through roles: Creator -> Constructor (add inputs/outputs) ->
Updater (attach UTXO entries + redeem scripts) -> Signer (each party adds
partial signatures) -> Combiner (merge partial sigs) -> Finalizer (build
the final signature scripts) -> Extractor (a consensus-ready Transaction).

This round covers the multisig-schnorr P2SH flow over OpCheckMultiSig
(ordered-key matching, as the engine enforces).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from kaspa_tpu.consensus import hashing as chash
from kaspa_tpu.consensus.model import (
    Transaction,
    TransactionInput,
    TransactionOutpoint,
    TransactionOutput,
    UtxoEntry,
)
from kaspa_tpu.consensus.model.tx import SUBNETWORK_ID_NATIVE, ComputeCommit
from kaspa_tpu.crypto import eclib
from kaspa_tpu.txscript.script_builder import ScriptBuilder

OP_CHECKMULTISIG = 0xAE


class PsktError(Exception):
    pass


def multisig_redeem_script(m: int, pubkeys: list[bytes]) -> bytes:
    """<m> <pk1>..<pkn> <n> OP_CHECKMULTISIG (standard/multisig.rs)."""
    assert 1 <= m <= len(pubkeys) <= 20
    b = ScriptBuilder().add_i64(m)
    for pk in pubkeys:
        b.add_data(pk)
    return b.add_i64(len(pubkeys)).add_op(OP_CHECKMULTISIG).script()


def parse_multisig_redeem_script(redeem: bytes) -> tuple[int, list[bytes]]:
    """Inverse of multisig_redeem_script: (m, pubkeys in script order)."""
    from kaspa_tpu.txscript.vm import parse_script

    ops = list(parse_script(redeem))
    if len(ops) < 4 or ops[-1][0] != OP_CHECKMULTISIG:
        raise PsktError("not a multisig redeem script")
    def _small_int(op, data):
        if 0x51 <= op <= 0x60:
            return op - 0x50
        if data is not None and len(data) == 1:
            return data[0]
        raise PsktError("not a multisig redeem script")
    m = _small_int(*ops[0])
    n = _small_int(*ops[-2])
    keys = [data for op, data in ops[1:-2] if data is not None]
    if len(keys) != n or not 1 <= m <= n:
        raise PsktError("malformed multisig redeem script")
    return m, keys


@dataclass
class PsktInput:
    outpoint: TransactionOutpoint
    utxo_entry: UtxoEntry
    redeem_script: bytes
    sig_op_count: int
    sequence: int = 0
    partial_sigs: dict[bytes, bytes] = field(default_factory=dict)  # pubkey -> sig65


@dataclass
class Pskt:
    """Role-based partially-signed transaction (wallet/pskt/src/pskt.rs)."""

    version: int = 0
    inputs: list[PsktInput] = field(default_factory=list)
    outputs: list[TransactionOutput] = field(default_factory=list)
    lock_time: int = 0

    # --- constructor / updater roles ---

    def add_input(self, outpoint, utxo_entry, redeem_script: bytes, sig_op_count: int) -> "Pskt":
        self.inputs.append(PsktInput(outpoint, utxo_entry, redeem_script, sig_op_count))
        return self

    def add_output(self, output: TransactionOutput) -> "Pskt":
        self.outputs.append(output)
        return self

    # --- common ---

    def unsigned_tx(self, mass_calculator=None) -> Transaction:
        tx = Transaction(
            self.version,
            [TransactionInput(i.outpoint, b"", i.sequence, ComputeCommit.sigops(i.sig_op_count)) for i in self.inputs],
            list(self.outputs),
            self.lock_time,
            SUBNETWORK_ID_NATIVE,
            0,
            b"",
        )
        if mass_calculator is None:
            from kaspa_tpu.consensus.mass import MassCalculator

            mass_calculator = MassCalculator()
        mass = mass_calculator.calc_contextual_masses(tx, [i.utxo_entry for i in self.inputs])
        if mass is None:
            raise PsktError("storage mass incomputable for this input/output set")
        tx.storage_mass = mass
        return tx

    # --- signer role ---

    def sign(self, seckey: int, aux: bytes = b"\x00" * 32, mass_calculator=None) -> "Pskt":
        """Adds a partial signature on every input whose redeem script
        includes this key (exact push-parsed membership)."""
        pub = eclib.schnorr_pubkey(seckey)
        tx = self.unsigned_tx(mass_calculator)
        entries = [i.utxo_entry for i in self.inputs]
        reused = chash.SigHashReusedValues()
        for idx, inp in enumerate(self.inputs):
            _m, keys = parse_multisig_redeem_script(inp.redeem_script)
            if pub not in keys:
                continue
            msg = chash.calc_schnorr_signature_hash(tx, entries, idx, chash.SIG_HASH_ALL, reused)
            sig = eclib.schnorr_sign(msg, seckey, aux) + bytes([chash.SIG_HASH_ALL])
            inp.partial_sigs[pub] = sig
        return self

    # --- combiner role ---

    def combine(self, other: "Pskt") -> "Pskt":
        """Merges partial sigs; every sighash-relevant field must match, or
        the merged sigs would cover different messages."""
        if (
            len(other.inputs) != len(self.inputs)
            or other.version != self.version
            or other.lock_time != self.lock_time
            or [(o.value, o.script_public_key) for o in other.outputs]
            != [(o.value, o.script_public_key) for o in self.outputs]
        ):
            raise PsktError("combining incompatible PSKTs")
        for mine, theirs in zip(self.inputs, other.inputs):
            if (
                mine.outpoint != theirs.outpoint
                or mine.sequence != theirs.sequence
                or mine.redeem_script != theirs.redeem_script
                or mine.utxo_entry != theirs.utxo_entry
            ):
                raise PsktError("combining PSKTs with different inputs")
            mine.partial_sigs.update(theirs.partial_sigs)
        return self

    # --- finalizer / extractor roles ---

    def extract_tx(self, mass_calculator=None) -> Transaction:
        """Builds signature scripts (sigs in per-input redeem-script key
        order) and returns the consensus-ready transaction."""
        tx = self.unsigned_tx(mass_calculator)
        for idx, inp in enumerate(self.inputs):
            m, keys = parse_multisig_redeem_script(inp.redeem_script)
            ordered = [inp.partial_sigs[pk] for pk in keys if pk in inp.partial_sigs]
            if len(ordered) < m:
                raise PsktError(f"input {idx} has {len(ordered)} of {m} required signatures")
            b = ScriptBuilder()
            for sig in ordered[:m]:
                b.add_data(sig)
            b.add_data(inp.redeem_script)
            tx.inputs[idx].signature_script = b.script()
        return tx

    # --- serialization (wallet/pskt serde role-passing) ---

    def to_json(self) -> str:
        def spk(s):
            return {"version": s.version, "script": s.script.hex()}

        return json.dumps(
            {
                "version": self.version,
                "lock_time": self.lock_time,
                "inputs": [
                    {
                        "outpoint": {"txid": i.outpoint.transaction_id.hex(), "index": i.outpoint.index},
                        "utxo": {
                            "amount": i.utxo_entry.amount,
                            "spk": spk(i.utxo_entry.script_public_key),
                            "daa": i.utxo_entry.block_daa_score,
                            "coinbase": i.utxo_entry.is_coinbase,
                        },
                        "redeem": i.redeem_script.hex(),
                        "sig_ops": i.sig_op_count,
                        "sequence": i.sequence,
                        "sigs": {k.hex(): v.hex() for k, v in i.partial_sigs.items()},
                    }
                    for i in self.inputs
                ],
                "outputs": [{"value": o.value, "spk": spk(o.script_public_key)} for o in self.outputs],
            }
        )

    @staticmethod
    def from_json(data: str) -> "Pskt":
        from kaspa_tpu.consensus.model import ScriptPublicKey

        d = json.loads(data)
        pskt = Pskt(version=d["version"], lock_time=d["lock_time"])
        for i in d["inputs"]:
            entry = UtxoEntry(
                i["utxo"]["amount"],
                ScriptPublicKey(i["utxo"]["spk"]["version"], bytes.fromhex(i["utxo"]["spk"]["script"])),
                i["utxo"]["daa"],
                i["utxo"]["coinbase"],
            )
            pin = PsktInput(
                TransactionOutpoint(bytes.fromhex(i["outpoint"]["txid"]), i["outpoint"]["index"]),
                entry,
                bytes.fromhex(i["redeem"]),
                i["sig_ops"],
                i["sequence"],
                {bytes.fromhex(k): bytes.fromhex(v) for k, v in i["sigs"].items()},
            )
            pskt.inputs.append(pin)
        for o in d["outputs"]:
            pskt.outputs.append(
                TransactionOutput(o["value"], ScriptPublicKey(o["spk"]["version"], bytes.fromhex(o["spk"]["script"])))
            )
        return pskt
