"""BIP32 hierarchical deterministic keys over secp256k1.

Reference: wallet/bip32 (the kaspa-bip32 crate).  Standard BIP32: master
key from HMAC-SHA512("Bitcoin seed", seed), hardened/normal child key
derivation, fingerprints.  Kaspa's derivation path is m/44'/111111'/a'/c/i
(coin type 111111, wallet/core derivation defaults).
"""

from __future__ import annotations

import hashlib
import hmac
from dataclasses import dataclass

from kaspa_tpu.crypto import eclib

HARDENED = 0x80000000
KASPA_COIN_TYPE = 111111


def _ser256(i: int) -> bytes:
    return i.to_bytes(32, "big")


def _point_bytes(k: int) -> bytes:
    x, y = eclib.point_mul(eclib.G, k)
    return bytes([2 + (y & 1)]) + x.to_bytes(32, "big")


@dataclass(frozen=True)
class ExtendedKey:
    key: int  # private scalar
    chain_code: bytes
    depth: int = 0
    child_number: int = 0

    @staticmethod
    def from_seed(seed: bytes) -> "ExtendedKey":
        digest = hmac.new(b"Bitcoin seed", seed, hashlib.sha512).digest()
        key = int.from_bytes(digest[:32], "big")
        if not (1 <= key < eclib.N):
            raise ValueError("invalid master seed")
        return ExtendedKey(key, digest[32:])

    def public_key(self) -> bytes:
        """Compressed public key (33 bytes)."""
        return _point_bytes(self.key)

    def x_only_public_key(self) -> bytes:
        return eclib.schnorr_pubkey(self.key)

    def fingerprint(self) -> bytes:
        h = hashlib.new("ripemd160", hashlib.sha256(self.public_key()).digest()).digest()
        return h[:4]

    def derive_child(self, index: int) -> "ExtendedKey":
        if index >= HARDENED:
            data = b"\x00" + _ser256(self.key) + index.to_bytes(4, "big")
        else:
            data = self.public_key() + index.to_bytes(4, "big")
        digest = hmac.new(self.chain_code, data, hashlib.sha512).digest()
        tweak = int.from_bytes(digest[:32], "big")
        child = (tweak + self.key) % eclib.N
        if tweak >= eclib.N or child == 0:
            # per BIP32: skip to the next index (probability ~2^-127)
            return self.derive_child(index + 1)
        return ExtendedKey(child, digest[32:], self.depth + 1, index)

    def derive_path(self, path: str) -> "ExtendedKey":
        """e.g. "m/44'/111111'/0'/0/5" """
        node = self
        for part in path.split("/"):
            if part in ("m", ""):
                continue
            hardened = part.endswith("'") or part.endswith("h")
            idx = int(part.rstrip("'h"))
            node = node.derive_child(idx + (HARDENED if hardened else 0))
        return node


def kaspa_account_path(account: int = 0) -> str:
    return f"m/44'/{KASPA_COIN_TYPE}'/{account}'"
