"""Wallet CLI (reference: cli/ — the wallet terminal).

Talks to a running node over the JSON-RPC wire.  One-shot subcommands:

    python -m kaspa_tpu.wallet --rpc 127.0.0.1:16110 address --seed-file s.txt
    python -m kaspa_tpu.wallet --rpc 127.0.0.1:16110 balance --seed-file s.txt
    python -m kaspa_tpu.wallet --rpc 127.0.0.1:16110 send --seed-file s.txt \
        --to kaspasim:... --amount 100000000 --fee 2000

or the interactive terminal (the reference cli/ shell):

    python -m kaspa_tpu.wallet --rpc 127.0.0.1:16110 repl --seed-file s.txt
    kaspa-tpu> help | address | new-address | balance | node |
               send <to> <amount> [fee] | monitor <seconds> | exit
"""

from __future__ import annotations

import argparse
import sys

from kaspa_tpu.node.daemon import rpc_call
from kaspa_tpu.wallet import Account


def _account(args) -> Account:
    with open(args.seed_file, "rb") as f:
        seed = f.read().strip()
    acct = Account.from_seed(seed, prefix=args.prefix)
    for _ in range(args.addresses - 1):
        acct.derive_receive_address()
    return acct


class _RemoteIndex:
    """utxoindex facade backed by the node's RPC (one-shot + repl send)."""

    def __init__(self, rpc_addr: str, prefix: str):
        self.rpc_addr = rpc_addr
        self.prefix = prefix

    def get_utxos_by_script(self, script: bytes):
        from kaspa_tpu.consensus.model import ScriptPublicKey, TransactionOutpoint, UtxoEntry
        from kaspa_tpu.crypto.addresses import extract_script_pub_key_address

        addr = extract_script_pub_key_address(ScriptPublicKey(0, script), self.prefix).to_string()
        out = {}
        for u in rpc_call(self.rpc_addr, "getUtxosByAddresses", {"addresses": [addr]}):
            op = TransactionOutpoint(bytes.fromhex(u["outpoint"]["transaction_id"]), u["outpoint"]["index"])
            out[op] = UtxoEntry(
                u["utxo_entry"]["amount"], ScriptPublicKey(0, script),
                u["utxo_entry"]["block_daa_score"], u["utxo_entry"]["is_coinbase"],
            )
        return out

    def get_balance_by_script(self, script: bytes) -> int:
        return sum(e.amount for e in self.get_utxos_by_script(script).values())


def _send(acct, rpc_addr: str, prefix: str, to: str, amount: int, fee: int) -> str:
    info = rpc_call(rpc_addr, "getServerInfo")
    tx = acct.build_send(
        _RemoteIndex(rpc_addr, prefix), to, amount, fee, info["virtual_daa_score"],
        coinbase_maturity=info.get("coinbase_maturity", 200),
    )
    # first-use signature-kernel load in the node can take minutes
    return rpc_call(rpc_addr, "submitTransaction", {"tx": tx_to_wire(tx)}, timeout=600.0)


REPL_HELP = """commands:
  address              list receive addresses
  new-address          derive the next receive address
  balance              total balance over derived addresses
  utxos                list spendable UTXOs (outpoint, amount, maturity)
  node                 node server info
  dag                  DAG tip state (block count, sink, pruning point)
  estimate <to> <amount>     price a spend without sending (mass, fees)
  fee-rates            node feerate estimator buckets
  send <to> <amount> [fee]   build, sign and submit a spend (sompi)
  sweep [fee]          consolidate every spendable UTXO to a fresh address
  monitor <seconds>    stream live wallet events (UtxosChanged/daa)
  help                 this text
  exit | quit          leave the terminal"""


def _spendables(acct, args):
    """(spendable utxos, server info): the snapshot every balance-touching
    terminal command starts from."""
    info = rpc_call(args.rpc, "getServerInfo")
    index = _RemoteIndex(args.rpc, args.prefix)
    utxos = acct.spendable_utxos(
        index, info["virtual_daa_score"], info.get("coinbase_maturity", 200)
    )
    return utxos, info


def _estimate(acct, args, to: str, amount: int, out) -> None:
    """Dry-run pricing via the wallet mass surface (cli estimate verb /
    WalletApi estimate): never signs, never submits."""
    from kaspa_tpu.consensus.mass import MassCalculator
    from kaspa_tpu.crypto.addresses import Address
    from kaspa_tpu.wallet.mass import (
        WalletMassCalculator,
        calc_minimum_required_transaction_relay_fee,
    )

    Address.from_string(to)  # a quote for an unparseable destination is noise
    utxos, _info = _spendables(acct, args)
    utxos.sort(key=lambda t: -t[1].amount)
    # gram costs from the same calculator build_send prices with
    wmc = WalletMassCalculator(MassCalculator())
    # fee depends on input count which depends on fee: iterate the greedy
    # largest-first selection (build_send's order) to a fixed point
    fee = calc_minimum_required_transaction_relay_fee(
        wmc.estimate_standard_compute_mass(1, 2)
    )
    for _ in range(4):
        selected, acc = [], 0
        for item in utxos:
            selected.append(item)
            acc += item[1].amount
            if acc >= amount + fee:
                break
        if acc < amount + fee:
            out(f"insufficient funds: spendable {acc} < {amount + fee} (incl. fee)")
            return
        mass = wmc.estimate_standard_compute_mass(len(selected), 2)
        new_fee = calc_minimum_required_transaction_relay_fee(mass)
        if new_fee == fee:
            break
        fee = new_fee
    out(
        f"inputs {len(selected)}  outputs 2  est. compute mass {mass} grams\n"
        f"relay fee floor {fee} sompi  change {acc - amount - fee} (after floor fee)"
    )


def _sweep(acct, args, fee: int, out) -> None:
    """Consolidate every spendable UTXO into one output on a fresh address
    (cli sweep verb).  Reports what the built transaction actually
    consumed, not a pre-selection snapshot."""
    utxos, info = _spendables(acct, args)
    total = sum(e.amount for _, e, _ in utxos)
    if total <= fee:
        out(f"nothing to sweep (spendable {total} <= fee {fee})")
        return
    dest = acct.derive_receive_address().address.to_string()
    tx = acct.build_send(
        _RemoteIndex(args.rpc, args.prefix), dest, total - fee, fee,
        info["virtual_daa_score"], coinbase_maturity=info.get("coinbase_maturity", 200),
    )
    txid = rpc_call(args.rpc, "submitTransaction", {"tx": tx_to_wire(tx)}, timeout=600.0)
    swept = sum(o.value for o in tx.outputs) + fee
    out(f"swept {len(tx.inputs)} utxos ({swept} sompi) -> {dest}\nsubmitted {txid}")


def repl(acct, args, stdin=None, stdout=None) -> int:
    """The interactive wallet terminal (reference cli/ shell)."""
    import sys as _sys

    stdin = stdin or _sys.stdin
    stdout = stdout or _sys.stdout

    def out(msg: str) -> None:
        print(msg, file=stdout, flush=True)

    out(f"kaspa-tpu wallet terminal — node {args.rpc} — 'help' for commands")
    while True:
        try:
            stdout.write("kaspa-tpu> ")
            stdout.flush()
            line = stdin.readline()
        except (KeyboardInterrupt, EOFError):
            return 0
        if not line:
            return 0
        parts = line.split()
        if not parts:
            continue
        cmd, *rest = parts
        try:
            if cmd in ("exit", "quit"):
                return 0
            elif cmd == "help":
                out(REPL_HELP)
            elif cmd == "address":
                for a in acct.addresses():
                    out(a)
            elif cmd == "new-address":
                out(acct.derive_receive_address().address.to_string())
            elif cmd == "balance":
                total = sum(
                    rpc_call(args.rpc, "getBalanceByAddress", {"address": a}) for a in acct.addresses()
                )
                out(f"{total} sompi ({total / 1e8:.8f} KAS)")
            elif cmd == "node":
                info = rpc_call(args.rpc, "getServerInfo")
                out(f"network {info['network_id']} daa {info['virtual_daa_score']} version {info['server_version']}")
            elif cmd == "send":
                if len(rest) < 2:
                    out("usage: send <to> <amount> [fee]")
                    continue
                to, amount = rest[0], int(rest[1])
                fee = int(rest[2]) if len(rest) > 2 else 2000
                out(f"submitted {_send(acct, args.rpc, args.prefix, to, amount, fee)}")
            elif cmd == "utxos":
                rows, _info = _spendables(acct, args)
                for op, entry, _d in sorted(rows, key=lambda t: -t[1].amount):
                    kind = "coinbase" if entry.is_coinbase else "standard"
                    out(f"{op.transaction_id.hex()}:{op.index}  {entry.amount} sompi  {kind}  daa {entry.block_daa_score}")
                out(f"{len(rows)} spendable utxos")
            elif cmd == "dag":
                d = rpc_call(args.rpc, "getBlockDagInfo")
                out(
                    f"blocks {d['block_count']}  daa {d['virtual_daa_score']}  "
                    f"sink {d['sink'][:16]}  pruning-point {d['pruning_point'][:16]}  "
                    f"tips {len(d['tip_hashes'])}"
                )
            elif cmd == "estimate":
                if len(rest) < 2:
                    out("usage: estimate <to> <amount>")
                    continue
                _estimate(acct, args, rest[0], int(rest[1]), out)
            elif cmd == "fee-rates":
                est = rpc_call(args.rpc, "getFeeEstimate")
                pb = est["priority_bucket"]
                out(f"priority: {pb['feerate']:.2f} sompi/g (~{pb['estimated_seconds']:.1f}s)")
                for b in est.get("normal_buckets", []):
                    out(f"normal:   {b['feerate']:.2f} sompi/g (~{b['estimated_seconds']:.1f}s)")
                for b in est.get("low_buckets", []):
                    out(f"low:      {b['feerate']:.2f} sompi/g (~{b['estimated_seconds']:.1f}s)")
            elif cmd == "sweep":
                fee = int(rest[0]) if rest else 2000
                _sweep(acct, args, fee, out)
            elif cmd == "monitor":
                seconds = float(rest[0]) if rest else 10.0
                _monitor(acct, args, seconds, out)
            else:
                out(f"unknown command {cmd!r} — 'help' for commands")
        except Exception as e:  # noqa: BLE001 - terminal loop must survive
            out(f"error: {e}")


def _monitor(acct, args, seconds: float, out) -> None:
    """Stream wallet events over a notification subscription (the
    reference terminal's live event feed)."""
    import queue as _queue
    import time as _time

    from kaspa_tpu.node.daemon import NotificationClient
    from kaspa_tpu.wallet.utxo_processor import UtxoProcessor, WalletEventType

    client = NotificationClient(args.rpc)
    maturity = rpc_call(args.rpc, "getServerInfo").get("coinbase_maturity", 200)
    uproc = UtxoProcessor(acct, coinbase_maturity=maturity)
    uproc.add_listener(
        lambda ev: out(f"[{ev.type.value}] {ev.data.get('balance') or ev.data}")
    )
    try:
        client.subscribe("utxos-changed", acct.addresses())
        client.subscribe("virtual-daa-score-changed")
        deadline = _time.monotonic() + seconds
        out(f"monitoring for {seconds:.0f}s ...")
        while _time.monotonic() < deadline:
            try:
                event, data = client.next_notification(timeout=max(0.2, deadline - _time.monotonic()))
            except _queue.Empty:
                break
            uproc.feed_wire_notification(event, data)
    finally:
        client.close()
    b = uproc.balance()
    out(f"monitor done: observed balance mature={b.mature} pending={b.pending}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kaspa-tpu-wallet")
    p.add_argument("--rpc", default="127.0.0.1:16110", help="node RPC address")
    p.add_argument("--seed-file", required=True, help="file containing the wallet seed bytes")
    p.add_argument("--prefix", default="kaspasim", help="address prefix")
    p.add_argument("--addresses", type=int, default=1, help="number of receive addresses to derive")
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("address", help="print receive addresses")
    sub.add_parser("balance", help="total balance over derived addresses")
    sp = sub.add_parser("send", help="build, sign and submit a spend")
    sp.add_argument("--to", required=True)
    sp.add_argument("--amount", type=int, required=True, help="sompi")
    sp.add_argument("--fee", type=int, default=2000)
    sub.add_parser("repl", help="interactive wallet terminal")
    args = p.parse_args(argv)

    acct = _account(args)
    if args.cmd == "address":
        for a in acct.addresses():
            print(a)
        return 0

    if args.cmd == "balance":
        total = 0
        for a in acct.addresses():
            total += rpc_call(args.rpc, "getBalanceByAddress", {"address": a})
        print(f"{total} sompi ({total / 1e8:.8f} KAS)")
        return 0

    if args.cmd == "send":
        txid = _send(acct, args.rpc, args.prefix, args.to, args.amount, args.fee)
        print(f"submitted {txid}")
        return 0

    if args.cmd == "repl":
        return repl(acct, args)
    return 1


def tx_to_wire(tx) -> dict:
    return {
        "version": tx.version,
        "inputs": [
            {
                "previousOutpoint": {"transactionId": i.previous_outpoint.transaction_id.hex(), "index": i.previous_outpoint.index},
                "signatureScript": i.signature_script.hex(),
                "sequence": i.sequence,
                "sigOpCount": i.compute_commit.sig_op_count() or 0,
            }
            for i in tx.inputs
        ],
        "outputs": [
            {"value": o.value, "scriptPublicKey": o.script_public_key.version.to_bytes(2, "little").hex() + o.script_public_key.script.hex()}
            for o in tx.outputs
        ],
        "lockTime": tx.lock_time,
        "subnetworkId": tx.subnetwork_id.hex(),
        "gas": tx.gas,
        "payload": tx.payload.hex(),
        "mass": tx.storage_mass,
    }


def wire_to_tx(d: dict):
    from kaspa_tpu.consensus.model import (
        ComputeCommit,
        ScriptPublicKey,
        Transaction,
        TransactionInput,
        TransactionOutpoint,
        TransactionOutput,
    )

    inputs = [
        TransactionInput(
            TransactionOutpoint(bytes.fromhex(i["previousOutpoint"]["transactionId"]), i["previousOutpoint"]["index"]),
            bytes.fromhex(i["signatureScript"]),
            i["sequence"],
            ComputeCommit.sigops(i.get("sigOpCount", 0)),
        )
        for i in d["inputs"]
    ]
    outputs = []
    for o in d["outputs"]:
        raw = bytes.fromhex(o["scriptPublicKey"])
        outputs.append(TransactionOutput(o["value"], ScriptPublicKey(int.from_bytes(raw[:2], "little"), raw[2:])))
    return Transaction(
        d["version"], inputs, outputs, d["lockTime"], bytes.fromhex(d["subnetworkId"]), d["gas"],
        bytes.fromhex(d["payload"]), storage_mass=d.get("mass", 0),
    )


if __name__ == "__main__":
    sys.exit(main())
