"""Wallet CLI (reference: cli/ — the wallet terminal's core commands).

Talks to a running node over the JSON-RPC wire:

    python -m kaspa_tpu.wallet --rpc 127.0.0.1:16110 address --seed-file s.txt
    python -m kaspa_tpu.wallet --rpc 127.0.0.1:16110 balance --seed-file s.txt
    python -m kaspa_tpu.wallet --rpc 127.0.0.1:16110 send --seed-file s.txt \
        --to kaspasim:... --amount 100000000 --fee 2000
"""

from __future__ import annotations

import argparse
import sys

from kaspa_tpu.node.daemon import rpc_call
from kaspa_tpu.wallet import Account


def _account(args) -> Account:
    with open(args.seed_file, "rb") as f:
        seed = f.read().strip()
    acct = Account.from_seed(seed, prefix=args.prefix)
    for _ in range(args.addresses - 1):
        acct.derive_receive_address()
    return acct


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kaspa-tpu-wallet")
    p.add_argument("--rpc", default="127.0.0.1:16110", help="node RPC address")
    p.add_argument("--seed-file", required=True, help="file containing the wallet seed bytes")
    p.add_argument("--prefix", default="kaspasim", help="address prefix")
    p.add_argument("--addresses", type=int, default=1, help="number of receive addresses to derive")
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("address", help="print receive addresses")
    sub.add_parser("balance", help="total balance over derived addresses")
    sp = sub.add_parser("send", help="build, sign and submit a spend")
    sp.add_argument("--to", required=True)
    sp.add_argument("--amount", type=int, required=True, help="sompi")
    sp.add_argument("--fee", type=int, default=2000)
    args = p.parse_args(argv)

    acct = _account(args)
    if args.cmd == "address":
        for a in acct.addresses():
            print(a)
        return 0

    if args.cmd == "balance":
        total = 0
        for a in acct.addresses():
            total += rpc_call(args.rpc, "getBalanceByAddress", {"address": a})
        print(f"{total} sompi ({total / 1e8:.8f} KAS)")
        return 0

    if args.cmd == "send":
        # fetch spendable utxos via the node's index, then build/sign locally
        info = rpc_call(args.rpc, "getServerInfo")
        daa = info["virtual_daa_score"]

        class _RemoteIndex:
            """utxoindex facade backed by the node's RPC."""

            def get_utxos_by_script(self, script: bytes):
                from kaspa_tpu.consensus.model import ScriptPublicKey, TransactionOutpoint, UtxoEntry
                from kaspa_tpu.crypto.addresses import extract_script_pub_key_address

                addr = extract_script_pub_key_address(ScriptPublicKey(0, script), args.prefix).to_string()
                out = {}
                for u in rpc_call(args.rpc, "getUtxosByAddresses", {"addresses": [addr]}):
                    op = TransactionOutpoint(bytes.fromhex(u["outpoint"]["transaction_id"]), u["outpoint"]["index"])
                    out[op] = UtxoEntry(
                        u["utxo_entry"]["amount"], ScriptPublicKey(0, script),
                        u["utxo_entry"]["block_daa_score"], u["utxo_entry"]["is_coinbase"],
                    )
                return out

            def get_balance_by_script(self, script: bytes) -> int:
                return sum(e.amount for e in self.get_utxos_by_script(script).values())

        tx = acct.build_send(_RemoteIndex(), args.to, args.amount, args.fee, daa, coinbase_maturity=rpc_call(args.rpc, "getServerInfo").get("coinbase_maturity", 200))
        # first-use signature-kernel load in the node can take minutes
        txid = rpc_call(args.rpc, "submitTransaction", {"tx": tx_to_wire(tx)}, timeout=600.0)
        print(f"submitted {txid}")
        return 0
    return 1


def tx_to_wire(tx) -> dict:
    return {
        "version": tx.version,
        "inputs": [
            {
                "previousOutpoint": {"transactionId": i.previous_outpoint.transaction_id.hex(), "index": i.previous_outpoint.index},
                "signatureScript": i.signature_script.hex(),
                "sequence": i.sequence,
                "sigOpCount": i.compute_commit.sig_op_count() or 0,
            }
            for i in tx.inputs
        ],
        "outputs": [
            {"value": o.value, "scriptPublicKey": o.script_public_key.version.to_bytes(2, "little").hex() + o.script_public_key.script.hex()}
            for o in tx.outputs
        ],
        "lockTime": tx.lock_time,
        "subnetworkId": tx.subnetwork_id.hex(),
        "gas": tx.gas,
        "payload": tx.payload.hex(),
        "mass": tx.storage_mass,
    }


def wire_to_tx(d: dict):
    from kaspa_tpu.consensus.model import (
        ComputeCommit,
        ScriptPublicKey,
        Transaction,
        TransactionInput,
        TransactionOutpoint,
        TransactionOutput,
    )

    inputs = [
        TransactionInput(
            TransactionOutpoint(bytes.fromhex(i["previousOutpoint"]["transactionId"]), i["previousOutpoint"]["index"]),
            bytes.fromhex(i["signatureScript"]),
            i["sequence"],
            ComputeCommit.sigops(i.get("sigOpCount", 0)),
        )
        for i in d["inputs"]
    ]
    outputs = []
    for o in d["outputs"]:
        raw = bytes.fromhex(o["scriptPublicKey"])
        outputs.append(TransactionOutput(o["value"], ScriptPublicKey(int.from_bytes(raw[:2], "little"), raw[2:])))
    return Transaction(
        d["version"], inputs, outputs, d["lockTime"], bytes.fromhex(d["subnetworkId"]), d["gas"],
        bytes.fromhex(d["payload"]), storage_mass=d.get("mass", 0),
    )


if __name__ == "__main__":
    sys.exit(main())
