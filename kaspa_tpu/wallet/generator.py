"""Transaction generator: mass-aware UTXO aggregation with chaining.

Reference: wallet/core/src/tx/generator/ (generator.rs:1-1256) — the
wallet's tx factory.  Key behavior reproduced:

- selects UTXOs (largest-first) until the payment + fees are covered;
- when a single transaction would exceed the per-tx mass limit, emits
  intermediate *batch* transactions that sweep the selected inputs into
  the change address, then chains their outputs into the final tx (the
  reference's multi-stage generator pipeline);
- fees = feerate x compute-mass-equivalent (mass sourced from the
  consensus MassCalculator so wallet and validator always agree);
- produces PendingTransaction objects that sign against the account and
  a GeneratorSummary aggregating fees/mass/tx count.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kaspa_tpu.consensus import hashing as chash
from kaspa_tpu.consensus.mass import MassCalculator
from kaspa_tpu.consensus.model import (
    SUBNETWORK_ID_NATIVE,
    ComputeCommit,
    Transaction,
    TransactionInput,
    TransactionOutpoint,
    TransactionOutput,
    UtxoEntry,
)
from kaspa_tpu.crypto import eclib
from kaspa_tpu.txscript import standard


class GeneratorError(Exception):
    pass


@dataclass
class PendingTransaction:
    """generator/pending.rs: one unsigned stage tx + its signing context."""

    tx: Transaction
    entries: list
    derivations: list  # DerivedAddress per input (None => foreign)
    is_final: bool
    fees: int
    aggregate_mass: int

    def sign(self, aux: bytes = b"\x00" * 32) -> Transaction:
        reused = chash.SigHashReusedValues()
        for i, derived in enumerate(self.derivations):
            if derived is None:
                continue
            msg = chash.calc_schnorr_signature_hash(self.tx, self.entries, i, chash.SIG_HASH_ALL, reused)
            sig = eclib.schnorr_sign(msg, derived.key.key, aux)
            self.tx.inputs[i].signature_script = standard.schnorr_signature_script(sig, chash.SIG_HASH_ALL)
        self.tx._id_cache = None
        return self.tx


@dataclass
class GeneratorSummary:
    """generator/summary.rs: network totals for UI/consumers."""

    number_of_generated_transactions: int = 0
    aggregated_fees: int = 0
    aggregated_mass: int = 0
    aggregated_utxos: int = 0
    final_transaction_amount: int = 0
    final_transaction_id: bytes | None = None


class Generator:
    """One payment -> a stream of chained transactions.

    ``utxo_iterator`` yields (outpoint, entry, derivation) spendables —
    the shape produced by Account.spendable_utxos."""

    # keep staged txs comfortably under consensus limits
    MAX_INPUTS_PER_STAGE = 84

    def __init__(
        self,
        utxo_iterator,
        change_spk,
        outputs: list[tuple],  # (ScriptPublicKey, amount)
        feerate: float = 1.0,
        mass_calculator: MassCalculator | None = None,
        sig_op_count: int = 1,
    ):
        self.utxos = list(utxo_iterator)
        self.utxos.sort(key=lambda t: -t[1].amount)
        self.change_spk = change_spk
        self.outputs = outputs
        self.feerate = feerate
        self.mc = mass_calculator if mass_calculator is not None else MassCalculator()
        self.sig_op_count = sig_op_count
        self.summary = GeneratorSummary()
        self._wallet_mc = None  # built lazily from self.mc's gram costs

    # --- mass/fee helpers ---

    def _tx_fees(self, tx: Transaction, entries) -> tuple[int, int]:
        """(mass, fee): compute-equivalent mass priced at the feerate
        (mass.rs calc_overall_mass + fees.rs)."""
        nc = self.mc.calc_non_contextual_masses(tx)
        storage = self.mc.calc_contextual_masses(tx, entries)
        if storage is None:
            raise GeneratorError("transaction mass incomputable")
        mass = max(nc.compute_mass, nc.transient_mass, storage)
        return mass, max(int(mass * self.feerate), 1)

    def _build_stage(self, selected, outputs, final: bool) -> PendingTransaction:
        inputs = [
            TransactionInput(op, b"", 0, ComputeCommit.sigops(self.sig_op_count)) for op, _, _ in selected
        ]
        entries = [e for _, e, _ in selected]
        tx = Transaction(0, inputs, list(outputs), 0, SUBNETWORK_ID_NATIVE, 0, b"")
        # settle the committed storage mass + fee by fixed-point: the change
        # output depends on the fee which depends on the mass
        mass, fee = self._tx_fees(tx, entries)
        tx.storage_mass = self.mc.calc_contextual_masses(tx, entries) or 0
        return PendingTransaction(
            tx=tx,
            entries=entries,
            derivations=[d for _, _, d in selected],
            is_final=final,
            fees=fee,
            aggregate_mass=mass,
        )

    def generate(self):
        """Yield PendingTransactions; the last one is the final payment."""
        payment_total = sum(amount for _, amount in self.outputs)
        selected: list = []
        chained: list = []  # (outpoint, entry, None) from batch stages
        total_in = 0
        utxo_iter = iter(self.utxos)
        stage_index = 0

        while True:
            # pull until covered (estimate fees on current shape as we go)
            while total_in < payment_total + self._estimate_fee(len(selected) + len(chained), len(self.outputs) + 1):
                nxt = next(utxo_iter, None)
                if nxt is None:
                    break
                selected.append(nxt)
                total_in += nxt[1].amount
                if len(selected) + len(chained) >= self.MAX_INPUTS_PER_STAGE:
                    # sweep into a batch stage toward change, chain its output
                    batch = self._emit_batch(chained + selected, stage_index)
                    stage_index += 1
                    yield batch
                    out_amount = batch.tx.outputs[0].value
                    chained = [
                        (
                            TransactionOutpoint(batch.tx.id(), 0),
                            UtxoEntry(out_amount, self.change_spk, 0, False),
                            None,  # signed by the daa-score owner... change key
                        )
                    ]
                    # change outputs are ours: sign with the change derivation
                    chained[0] = (chained[0][0], chained[0][1], batch.derivations[0])
                    selected = []
                    total_in = out_amount

            fee_needed = self._estimate_fee(len(selected) + len(chained), len(self.outputs) + 1)
            if total_in < payment_total + fee_needed:
                raise GeneratorError(
                    f"insufficient funds: have {total_in}, need {payment_total + fee_needed}"
                )
            break

        all_inputs = chained + selected
        outs = [TransactionOutput(amount, spk) for spk, amount in self.outputs]
        # fee/change fixed point: KIP-9 storage mass depends on the change
        # value itself (tiny outputs are harmonically penalized), so probe
        # with the real change candidate and iterate to settlement
        fee = 0
        final = None
        for _ in range(6):
            change = total_in - payment_total - fee
            if change < 0:
                raise GeneratorError("insufficient funds after final fee")
            probe_outs = list(outs) + ([TransactionOutput(change, self.change_spk)] if change > 0 else [])
            final = self._build_stage(all_inputs, probe_outs, final=True)
            if final.fees == fee:
                break
            fee = final.fees
        self._account(final, payment_total)
        yield final

    def _emit_batch(self, selected, stage_index: int) -> PendingTransaction:
        total = sum(e.amount for _, e, _ in selected)
        fee = 0
        batch = None
        for _ in range(6):  # same fee/value fixed point as the final stage
            swept = total - fee
            if swept <= 0:
                raise GeneratorError("batch stage cannot cover its own fee")
            batch = self._build_stage(selected, [TransactionOutput(swept, self.change_spk)], final=False)
            if batch.fees == fee:
                break
            fee = batch.fees
        self._account(batch, 0)
        return batch

    def _account(self, pending: PendingTransaction, payment: int) -> None:
        s = self.summary
        s.number_of_generated_transactions += 1
        s.aggregated_fees += pending.fees
        s.aggregated_mass += pending.aggregate_mass
        s.aggregated_utxos += len(pending.tx.inputs)
        if pending.is_final:
            s.final_transaction_amount = payment
            s.final_transaction_id = pending.tx.id()

    def _estimate_fee(self, n_inputs: int, n_outputs: int) -> int:
        """Upfront estimate priced with the wallet mass surface
        (wallet/core/src/tx/mass.rs).  The generator still settles exact
        masses per stage; this only steers UTXO selection."""
        from kaspa_tpu.wallet.mass import WalletMassCalculator

        wmc = self._wallet_mc
        if wmc is None:
            from types import SimpleNamespace

            # gram costs come from the generator's consensus calculator
            wmc = self._wallet_mc = WalletMassCalculator(SimpleNamespace(
                mass_per_tx_byte=self.mc.mass_per_tx_byte,
                mass_per_script_pub_key_byte=self.mc.mass_per_script_pub_key_byte,
                mass_per_sig_op=self.mc.mass_per_sig_op,
                storage_mass_parameter=self.mc.storage_mass_parameter,
            ))
        mass = wmc.estimate_standard_compute_mass(n_inputs, n_outputs, self.sig_op_count)
        return max(int(mass * self.feerate), 1)


def estimate(utxo_iterator, change_spk, outputs, feerate: float = 1.0, mass_calculator=None) -> GeneratorSummary:
    """Dry-run the generator for fee/mass estimation without signing
    (the reference's WalletApi estimate call backed by generator
    iteration)."""
    gen = Generator(utxo_iterator, change_spk, outputs, feerate, mass_calculator)
    for _ in gen.generate():
        pass
    return gen.summary
