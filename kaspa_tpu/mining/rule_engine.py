"""Mining rule engine: sync-state gating for block-template serving.

Reference: protocol/mining/src/rule_engine.rs + rules/sync_rate_rule.rs.
``should_mine`` allows template serving only when the node has peer
connectivity (mainnet/testnet; isolated networks are exempt) AND is
nearly synced (sink timestamp within a quarter of the difficulty-window
duration of now) — OR the sync-rate rule fired: the node stopped
receiving blocks (rate below 50% of expected) while its finality point
is recent, meaning the network itself stalled and mining should resume
to revive it.
"""

from __future__ import annotations

import threading

from kaspa_tpu.utils.sync import ranked_lock
import time
from collections import deque

SNAPSHOT_INTERVAL = 10  # seconds between sync-rate samples (rule_engine.rs:27)
SYNC_RATE_THRESHOLD = 0.50  # sync_rate_rule.rs:18
SYNC_RATE_WINDOW_MAX_SIZE = 5 * 60 // SNAPSHOT_INTERVAL
SYNC_RATE_WINDOW_MIN_THRESHOLD = 60 // SNAPSHOT_INTERVAL


class SyncRateRule:
    """sync_rate_rule.rs: sliding window of (received, expected) blocks."""

    def __init__(self):
        self.use_sync_rate_rule = False
        self._samples: deque[tuple[int, float]] = deque()  # graftlint: allow(unbounded-queue) -- trimmed to the sliding window by check_rule on every sample
        self._total_received = 0
        self._total_expected = 0.0
        self._mu = ranked_lock("mining.stats")

    def check_rule(self, received_blocks: int, expected_blocks: float, finality_recent: bool) -> None:
        with self._mu:
            self._samples.append((received_blocks, expected_blocks))
            self._total_received += received_blocks
            self._total_expected += expected_blocks
            while len(self._samples) > SYNC_RATE_WINDOW_MAX_SIZE:
                old_r, old_e = self._samples.popleft()
                self._total_received -= old_r
                self._total_expected -= old_e
            if len(self._samples) < SYNC_RATE_WINDOW_MIN_THRESHOLD:
                return
            rate = self._total_received / self._total_expected if self._total_expected > 0 else 1.0
            # low receive rate + recent finality point => the network (not
            # this node) stalled: permit mining to revive it
            self.use_sync_rate_rule = rate < SYNC_RATE_THRESHOLD and finality_recent


class MiningRuleEngine:
    """rule_engine.rs MiningRuleEngine over the python service runtime."""

    def __init__(
        self,
        consensus_provider,
        params,
        has_peers,
        require_peers: bool | None = None,
        allow_unsynced: bool = False,
        now_ms=lambda: int(time.time() * 1000),
    ):
        """``consensus_provider() -> Consensus``; ``has_peers() -> bool``.
        ``require_peers`` defaults by network name: mainnet/testnet require
        connectivity, isolated networks (simnet/devnet) do not
        (rule_engine.rs has_sufficient_peer_connectivity)."""
        self._consensus = consensus_provider
        self.params = params
        self._has_peers = has_peers
        if require_peers is None:
            require_peers = any(t in params.name for t in ("mainnet", "testnet"))
        self.require_peers = require_peers
        # args.rs enable_unsynced_mining: bypass the sync gate entirely
        # (simnet/devnet single-node operation mines from genesis)
        self.allow_unsynced = allow_unsynced
        self.now_ms = now_ms
        self.sync_rate_rule = SyncRateRule()
        self._last_blocks = None

    # --- predicates (rule_engine.rs:106-143) ---

    def has_sufficient_peer_connectivity(self) -> bool:
        return not self.require_peers or self._has_peers()

    def synced_threshold_ms(self) -> int:
        """A quarter of the expected difficulty-window duration (~10 min)."""
        window_ms = (
            self.params.target_time_per_block
            * self.params.difficulty_window_size
            * self.params.difficulty_sample_rate
        )
        return window_ms // 4

    def is_nearly_synced(self, sink_timestamp_ms: int) -> bool:
        return self.now_ms() < sink_timestamp_ms + self.synced_threshold_ms()

    def should_mine(self, sink_timestamp_ms: int) -> bool:
        if self.allow_unsynced:
            return True
        if not self.has_sufficient_peer_connectivity():
            return False
        return self.is_nearly_synced(sink_timestamp_ms) or self.sync_rate_rule.use_sync_rate_rule

    def is_sink_recent_and_connected(self, sink_timestamp_ms: int) -> bool:
        return self.has_sufficient_peer_connectivity() and self.is_nearly_synced(sink_timestamp_ms)

    # --- sampling worker body (rule_engine.rs worker; call every tick) ---

    def sample(self, elapsed_secs: float | None = None) -> None:
        """One sync-monitor tick: delta of processed bodies vs expected
        block count for the elapsed period, fed into the sync-rate rule."""
        c = self._consensus()
        blocks = c.counters.snapshot().body_counts
        if self._last_blocks is None:
            self._last_blocks = blocks
            return
        delta = max(0, blocks - self._last_blocks)
        self._last_blocks = blocks
        elapsed = elapsed_secs if elapsed_secs is not None else float(SNAPSHOT_INTERVAL)
        expected = elapsed * 1000.0 / self.params.target_time_per_block
        fp = c.depth_manager.finality_point(c.sink())
        try:
            fp_ts = c.storage.headers.get_timestamp(fp)
        except KeyError:
            fp_ts = c.storage.headers.get_timestamp(c.params.genesis.hash)
        finality_recent = self.now_ms() < fp_ts + self.params.finality_depth * self.params.target_time_per_block
        self.sync_rate_rule.check_rule(delta, expected, finality_recent)
