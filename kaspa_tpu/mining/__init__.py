from kaspa_tpu.mining.rule_engine import MiningRuleEngine, SyncRateRule

__all__ = ["MiningRuleEngine", "SyncRateRule"]
