"""Cross-host verify balancer: the fabric's dispatch engine.

Implements the CoalescingDispatcher surface (submit/nudge/drain/close/
abandon/stats) over remote verifyd slices, so `ops/dispatch.install()`
makes it the process-wide verify engine and every existing caller —
BatchScriptChecker, the pipeline stage workers, daemon shutdown — routes
over the fabric unchanged.

Routing and resilience, per super-batch (one `submit()` chunk):

- the chunk goes to the **least-loaded** live slice (lowest outstanding
  jobs among slices whose per-slice CircuitBreaker admits traffic);
- every request carries a deadline (`KASPA_TPU_FABRIC_DEADLINE_S`,
  default: the PR 9 dispatch-watchdog deadline).  A deadline expiry is a
  *hang*: the slice's breaker trips immediately with cause ``hung`` —
  the supervisor semantics, applied per slice;
- a failed/hung/disconnected slice is retried on the **next** slice; when
  every slice is dead or already tried, the chunk lands on the
  **bit-identical host degraded lane** (`secp.host_verify_batch` — same
  prechecks, eclib oracle) so a ticket always resolves, exactly once;
- breakers are *managed* (PR 9): while OPEN, live chunks never probe a
  possibly-hung slice — the monitor's cheap STATUS canary does, and its
  answer re-arms the slice;
- remote work lands in the block's flight trace: ``wait.fabric`` (submit
  -> send), ``fabric.rpc`` (send -> response) with the server-reported
  queue/verify times grafted as a ``fabric.remote.verify`` child span.
"""

from __future__ import annotations

import itertools
import os
import queue
import threading

from kaspa_tpu.utils.sync import ranked_lock
import time
from time import perf_counter_ns

import numpy as np

from kaspa_tpu.fabric import wire
from kaspa_tpu.fabric.client import FabricConnection
from kaspa_tpu.observability import trace
from kaspa_tpu.observability.core import REGISTRY
from kaspa_tpu.ops import dispatch as dispatch_mod
from kaspa_tpu.ops.dispatch import DispatchAbandoned, Ticket
from kaspa_tpu.resilience import supervisor
from kaspa_tpu.resilience.breaker import HUNG, CircuitBreaker

_REMOTE_JOBS = REGISTRY.counter_family("fabric_remote_jobs", "slice", help="verify jobs resolved by fabric slice")
_FAILOVERS = REGISTRY.counter("fabric_failovers", help="chunks re-routed after a slice failure/hang")
_DEGRADED = REGISTRY.counter("fabric_degraded_chunks", help="chunks resolved on the host degraded lane")

_MONITOR_TICK_S = 0.05
_RECONNECT_EVERY_S = 1.0


def _deadline_s() -> float:
    raw = os.environ.get("KASPA_TPU_FABRIC_DEADLINE_S")
    if raw:
        return float(raw)
    return supervisor.deadline_s("dispatch")


class _Slice:
    """One routable (server, remote slice) lane with its own breaker."""

    __slots__ = ("conn", "idx", "key", "breaker", "occupancy")

    def __init__(self, conn: FabricConnection, idx: int, breaker: CircuitBreaker):
        self.conn = conn
        self.idx = idx
        self.key = f"{conn.addr}#{idx}"
        self.breaker = breaker
        self.occupancy = 0  # outstanding chunks, guarded by the balancer lock


class _Job:
    __slots__ = ("ticket", "kind", "items", "ctx", "enqueued_ns", "send_ns",
                 "deadline", "tried", "slice", "req_id", "done")

    def __init__(self, ticket: Ticket, kind: str, items: list):
        self.ticket = ticket
        self.kind = kind
        self.items = items
        self.ctx = trace.context()
        self.enqueued_ns = perf_counter_ns()
        self.send_ns = 0
        self.deadline = 0.0
        self.tried: set = set()
        self.slice: _Slice | None = None
        self.req_id = 0
        self.done = False


class FabricBalancer:
    def __init__(self, addrs: list[str], deadline_s: float | None = None):
        self.addrs = list(addrs)
        self.label = "fabric:" + ",".join(self.addrs)
        self.deadline_s = deadline_s if deadline_s is not None else _deadline_s()
        self._lock = ranked_lock("fabric.balancer", reentrant=False)
        self._idle = self._lock.condition()
        self._ids = itertools.count(1)
        self._jobs: dict[int, _Job] = {}
        self._probes: dict[int, tuple[_Slice, float]] = {}
        self._slices: list[_Slice] = []
        self._conns: dict[str, FabricConnection] = {}
        self._breakers: dict[str, CircuitBreaker] = {}  # persists across reconnects
        self._last_dial: dict[str, float] = {}
        self._unresolved = 0
        self._closed = False
        self._abandoned = False
        self.counters = {
            "submitted": 0, "remote": 0, "degraded": 0, "failovers": 0,
            "late_responses": 0, "abandoned": 0,
        }
        self._degraded_q: queue.Queue = queue.Queue()  # graftlint: allow(unbounded-queue) -- degraded-mode fallback lane; entries are jobs already bounded by the dispatcher's inflight cap
        self._stopped = threading.Event()
        for addr in self.addrs:
            conn = FabricConnection(addr, on_message=self._on_message, on_disconnect=self._on_disconnect)
            self._conns[addr] = conn
            self._dial(conn)
        threading.Thread(target=self._degraded_worker, name="fabric-degraded", daemon=True).start()
        threading.Thread(target=self._monitor, name="fabric-monitor", daemon=True).start()

    # --- connection lifecycle ----------------------------------------------

    def _dial(self, conn: FabricConnection) -> bool:
        self._last_dial[conn.addr] = time.monotonic()
        try:
            hello = conn.connect(timeout=3.0)
        except Exception:  # noqa: BLE001 - dead at dial: monitor retries
            return False
        fresh = []
        for i in range(max(1, int(hello.get("slices", 1)))):
            br = self._breakers.get(f"{conn.addr}#{i}")
            if br is None:
                br = CircuitBreaker(
                    f"fabric[{conn.addr}#{i}]",
                    # graftlint: allow(env-knob) -- remote slices fail fast on purpose: a slice two strikes down should stop taking traffic before the deadline tax compounds
                    failure_threshold=int(os.environ.get("KASPA_TPU_BREAKER_THRESHOLD", "2")),
                )
                br.set_managed(True)  # only the STATUS canary probes while OPEN
                self._breakers[f"{conn.addr}#{i}"] = br
            fresh.append(_Slice(conn, i, br))
        with self._lock:
            self._slices = [s for s in self._slices if s.conn.addr != conn.addr] + fresh
        for s in fresh:
            s.breaker.record_success()  # a successful dial re-arms the lane
        return True

    def _on_disconnect(self, conn: FabricConnection, exc: Exception) -> None:
        with self._lock:
            victims = [rid for rid, job in self._jobs.items() if job.slice is not None and job.slice.conn is conn]
            dead_probes = [rid for rid, (s, _) in self._probes.items() if s.conn is conn]
            for rid in dead_probes:
                del self._probes[rid]
        for rid in victims:
            job = self._detach(rid)
            if job is not None:
                job.slice.breaker.record_failure()
                self._failover(job)

    # --- the dispatch-engine surface ---------------------------------------

    def submit(self, kind: str, items: list) -> Ticket:
        """Route one chunk of (pubkey, msg, sig) triples; same contract as
        CoalescingDispatcher.submit — the ticket resolves exactly once."""
        ticket = Ticket(self, kind, len(items))
        if not items:
            ticket._resolve(np.zeros(0, dtype=bool), None)
            return ticket
        with self._lock:
            if self._closed:
                raise RuntimeError("fabric balancer is shut down")
            self.counters["submitted"] += 1
            self._unresolved += 1
        self._route(_Job(ticket, kind, list(items)))
        return ticket

    def nudge(self) -> None:
        """No-op: chunks are sent the moment they are submitted."""

    def drain(self, timeout: float = 10.0) -> bool:
        deadline = time.monotonic() + timeout
        with self._lock:
            while self._unresolved > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._idle.wait(remaining)
        return True

    def close(self, timeout: float = 10.0, abandon: bool = True) -> bool:
        with self._lock:
            self._closed = True
        drained = self.drain(timeout)
        if not drained and abandon:
            self.abandon("close timeout: outstanding fabric chunks")
        self._stopped.set()
        self._degraded_q.put(None)
        for conn in self._conns.values():
            conn.close()
        return drained

    def abandon(self, reason: str) -> int:
        err = DispatchAbandoned(f"fabric balancer abandoned: {reason}")
        with self._lock:
            self._closed = True
            self._abandoned = True
            victims = list(self._jobs.values())
            self._jobs.clear()
            for job in victims:
                if job.slice is not None:
                    job.slice.occupancy -= 1
        stranded = []
        while True:
            try:
                job = self._degraded_q.get_nowait()
            except queue.Empty:
                break
            if job is not None:
                stranded.append(job)
        count = 0
        for job in victims + stranded:
            if self._complete(job, None, err, "abandoned"):
                count += 1
        return count

    def stats(self) -> dict:
        with self._lock:
            per_slice = [
                {"slice": s.key, "occupancy": s.occupancy, "alive": s.conn.alive,
                 "breaker": s.breaker.state, "trips": s.breaker.trips,
                 "last_trip_cause": s.breaker.last_trip_cause}
                for s in self._slices
            ]
            out = dict(self.counters)
            out.update({
                "deadline_s": self.deadline_s,
                "unresolved_chunks": self._unresolved,
                "abandoned_engine": self._abandoned,
                "slices": per_slice,
                # the zero-lost-tickets invariant, checkable from evidence:
                # every submitted chunk is resolved somewhere or still open
                "lost": self.counters["submitted"] - self.counters["remote"]
                - self.counters["degraded"] - self.counters["abandoned"] - self._unresolved,
            })
            return out

    # --- routing ------------------------------------------------------------

    def _route(self, job: _Job) -> None:
        while True:
            with self._lock:
                if job.done:
                    return
                if self._abandoned:
                    break
                ranked = sorted(
                    (s for s in self._slices if s.key not in job.tried and s.conn.alive),
                    key=lambda s: s.occupancy,
                )
            chosen = None
            for s in ranked:
                if s.breaker.allow():
                    chosen = s
                    break
                job.tried.add(s.key)  # OPEN now = not a candidate for *this* chunk
            if chosen is None:
                break
            with self._lock:
                if job.done:
                    return
                job.req_id = next(self._ids)
                job.slice = chosen
                job.tried.add(chosen.key)
                chosen.occupancy += 1
                job.send_ns = perf_counter_ns()
                job.deadline = time.monotonic() + self.deadline_s
                self._jobs[job.req_id] = job
            try:
                chosen.conn.send(wire.encode_verify_req(
                    job.req_id, job.kind, chosen.idx,
                    job.ctx.trace_id if job.ctx is not None else None, job.items,
                ))
                return
            except Exception:  # noqa: BLE001 - send failed: this slice is toast
                detached = self._detach(job.req_id)
                chosen.breaker.record_failure()
                if detached is None:
                    return  # raced a concurrent resolution
                with self._lock:
                    self.counters["failovers"] += 1
                _FAILOVERS.inc()
        # no routable slice left: the bit-identical host lane takes it
        self._degraded_q.put(job)

    def _detach(self, req_id: int) -> _Job | None:
        """Pull an outstanding request back (failure path); None when the
        job already resolved.  Occupancy is released here, exactly once."""
        with self._lock:
            job = self._jobs.pop(req_id, None)
            if job is None or job.done:
                return None
            if job.slice is not None:
                job.slice.occupancy -= 1
            return job

    def _failover(self, job: _Job) -> None:
        with self._lock:
            self.counters["failovers"] += 1
        _FAILOVERS.inc()
        self._route(job)

    def _complete(self, job: _Job, mask, error, route: str) -> bool:
        with self._lock:
            if job.done:
                return False
            job.done = True
            self._unresolved -= 1
            self.counters[route] = self.counters.get(route, 0) + 1
            if self._unresolved == 0:
                self._idle.notify_all()
        job.ticket._resolve(mask, error)
        return True

    # --- completion paths ---------------------------------------------------

    def _on_message(self, conn: FabricConnection, mtype: int, msg: dict) -> None:
        if mtype == wire.STATUS_RESP:
            with self._lock:
                probe = self._probes.pop(msg["req_id"], None)
            if probe is not None:
                probe[0].breaker.record_success()  # canary answered: re-arm
            return
        if mtype != wire.VERIFY_RESP:
            return
        t_recv = perf_counter_ns()
        req_id = msg["req_id"]
        if not msg["ok"]:
            job = self._detach(req_id)
            if job is None:
                with self._lock:
                    self.counters["late_responses"] += 1
                return
            job.slice.breaker.record_failure()
            self._failover(job)
            return
        with self._lock:
            job = self._jobs.pop(req_id, None)
            if job is None or job.done:
                self.counters["late_responses"] += 1
                return
            sl = job.slice
            sl.occupancy -= 1
        mask = msg["mask"]
        if mask.shape[0] != len(job.items):
            # a corrupted-but-decodable response must not resolve the
            # ticket with the wrong lane count — treat as a slice failure
            sl.breaker.record_failure()
            self._failover(job)
            return
        sl.breaker.record_success()
        _REMOTE_JOBS.inc(sl.key, len(job.items))
        if job.ctx is not None:
            trace.record_span("wait.fabric", job.ctx, job.enqueued_ns, job.send_ns)
            rpc = trace.record_span(
                "fabric.rpc", job.ctx, job.send_ns, t_recv,
                slice=sl.key, jobs=len(job.items), kind=job.kind,
                queue_ms=round(msg["queue_ns"] / 1e6, 3),
                verify_ms=round(msg["verify_ns"] / 1e6, 3),
                remote_inflight=msg["inflight"],
            )
            if rpc is not None and msg["verify_ns"]:
                trace.record_span(
                    "fabric.remote.verify", rpc, t_recv - msg["verify_ns"], t_recv, slice=sl.key
                )
        self._complete(job, mask, None, "remote")

    def _degraded_worker(self) -> None:
        from kaspa_tpu.crypto import secp  # deferred: jax import

        while True:
            job = self._degraded_q.get()
            if job is None:
                return
            if self._abandoned:
                self._complete(job, None, DispatchAbandoned("fabric balancer abandoned"), "abandoned")
                continue
            _DEGRADED.inc()
            try:
                with trace.span("fabric.degraded", parent=job.ctx, kind=job.kind, jobs=len(job.items)):
                    mask = secp.host_verify_batch(job.kind, job.items)
            except Exception as e:  # noqa: BLE001 - surfaced on the ticket
                self._complete(job, None, e, "degraded")
                continue
            if job.ctx is not None:
                trace.record_span("wait.fabric", job.ctx, job.enqueued_ns, perf_counter_ns())
            self._complete(job, mask, None, "degraded")

    # --- supervision --------------------------------------------------------

    def _monitor(self) -> None:
        while not self._stopped.wait(_MONITOR_TICK_S):
            now = time.monotonic()
            with self._lock:
                hung = [rid for rid, job in self._jobs.items() if now > job.deadline]
                dead_probes = [rid for rid, (_, dl) in self._probes.items() if now > dl]
                probe_due = [
                    s for s in self._slices
                    if s.conn.alive and s.breaker.reopen_due()
                    and all(p is not s for p, _ in self._probes.values())
                ]
                redial = [
                    c for c in self._conns.values()
                    if not c.alive and now - self._last_dial.get(c.addr, 0.0) >= _RECONNECT_EVERY_S
                ]
            for rid in hung:
                job = self._detach(rid)
                if job is not None:
                    # the per-slice watchdog verdict: a deadline is a hang,
                    # and one proven hang trips the slice immediately
                    job.slice.breaker.record_failure(cause=HUNG)
                    self._failover(job)
            for rid in dead_probes:
                with self._lock:
                    probe = self._probes.pop(rid, None)
                if probe is not None:
                    probe[0].breaker.record_failure(cause=HUNG)
            for s in probe_due:
                if not s.breaker.allow(probe=True):
                    continue
                rid = next(self._ids)
                with self._lock:
                    self._probes[rid] = (s, now + min(5.0, self.deadline_s))
                try:
                    s.conn.send(wire.encode_status_req(rid))
                except Exception:  # noqa: BLE001 - probe send failed
                    with self._lock:
                        self._probes.pop(rid, None)
                    s.breaker.record_failure()
            for conn in redial:
                self._dial(conn)


# --- process-wide configuration (mirrors ops/dispatch.py) -------------------

_lock = ranked_lock("fabric.config")
_balancer: FabricBalancer | None = None


def configure(addrs: str | list[str] | None, deadline_s: float | None = None) -> FabricBalancer | None:
    """Build a balancer for ``addrs`` ("HOST:PORT,..." or a list) and
    install it as the process-wide verify engine; None/empty uninstalls
    (reverting to whatever `ops/dispatch.configure` set up)."""
    global _balancer
    if isinstance(addrs, str):
        addrs = [a.strip() for a in addrs.split(",") if a.strip()]
    with _lock:
        old, _balancer = _balancer, None
    if old is not None:
        old.close(timeout=5.0)
    if not addrs:
        return None
    bal = FabricBalancer(addrs, deadline_s=deadline_s)
    with _lock:
        _balancer = bal
    dispatch_mod.install(bal)
    return bal


def active() -> FabricBalancer | None:
    return _balancer


def shutdown(timeout: float = 10.0) -> bool:
    global _balancer
    with _lock:
        bal, _balancer = _balancer, None
    return bal.close(timeout, abandon=True) if bal is not None else True


def _fabric_state() -> dict:
    bal = _balancer
    if bal is None:
        return {"enabled": False}
    out: dict = {"enabled": True}
    out.update(bal.stats())
    return out


REGISTRY.register_collector("fabric", _fabric_state)
