"""Verify-fabric wire format.

Every message rides the PR 2 gRPC Length-Prefixed-Message framing
(`p2p/proto/framing.py`: flag byte + 4-byte big-endian length); the
payload is a 1-byte message type followed by varint/length-delimited
fields (`p2p/proto/wire_format.py` primitives) — no schema compiler, no
new dependency, same bounds discipline as the P2P wire.

    HELLO        server -> client on accept: proto version, slice count,
                 capability mode flags (proto >= 2, e.g. MODE_AGGREGATE)
    VERIFY_REQ   req_id, kind, target slice, trace id, [(pub,msg,sig)...]
    VERIFY_RESP  req_id, status; ok: packed mask + server-side timings +
                 the slice's post-completion inflight count (the load
                 signal the balancer routes on); err: utf-8 message
    STATUS_REQ   req_id — the balancer's liveness/occupancy probe
    STATUS_RESP  req_id, per-slice (inflight, queue depth)

Verify masks are bit-packed (numpy packbits order) with an explicit lane
count, so a 1024-job super-batch answers in ~128 bytes + framing.
"""

from __future__ import annotations

import numpy as np

from kaspa_tpu.p2p.proto.framing import encode_grpc_frame, read_grpc_frame
from kaspa_tpu.p2p.proto.wire_format import ProtoWireError, decode_varint, encode_varint

PROTO_VERSION = 2

# HELLO capability bitflags (proto >= 2; proto-1 peers simply omit them)
MODE_AGGREGATE = 0x01  # server can run schnorr RLC aggregate verification

HELLO = 0x01
VERIFY_REQ = 0x02
VERIFY_RESP = 0x03
STATUS_REQ = 0x04
STATUS_RESP = 0x05

STATUS_OK = 0
STATUS_ERR = 1

KINDS = ("schnorr", "ecdsa")

MAX_ITEMS = 1 << 20  # one super-batch; far above any sane coalesce target


def _pb(data: bytes) -> bytes:
    return encode_varint(len(data)) + data


def _read_bytes(buf: bytes, pos: int) -> tuple[bytes, int]:
    n, pos = decode_varint(buf, pos)
    if pos + n > len(buf):
        raise ProtoWireError(f"truncated length-delimited field ({n} bytes past end)")
    return buf[pos : pos + n], pos + n


def encode_hello(slices: int, proto: int = PROTO_VERSION, modes: int = 0) -> bytes:
    # the modes capability varint is appended after the proto-1 fields:
    # old decoders read exactly two varints and ignore trailing bytes, so
    # a v2 HELLO stays backward compatible on the wire
    return bytes([HELLO]) + encode_varint(proto) + encode_varint(slices) + encode_varint(modes)


def encode_verify_req(req_id: int, kind: str, slice_idx: int, trace_id: str | None, items) -> bytes:
    out = [bytes([VERIFY_REQ]), encode_varint(req_id), encode_varint(KINDS.index(kind)),
           encode_varint(slice_idx), _pb((trace_id or "").encode()), encode_varint(len(items))]
    for pub, msg, sig in items:
        out.append(_pb(pub))
        out.append(_pb(msg))
        out.append(_pb(sig))
    return b"".join(out)


def encode_verify_resp(req_id: int, mask, queue_ns: int, verify_ns: int, inflight: int) -> bytes:
    mask = np.asarray(mask, dtype=bool)
    return (
        bytes([VERIFY_RESP]) + encode_varint(req_id) + encode_varint(STATUS_OK)
        + encode_varint(int(mask.shape[0])) + _pb(np.packbits(mask).tobytes())
        + encode_varint(max(0, int(queue_ns))) + encode_varint(max(0, int(verify_ns)))
        + encode_varint(max(0, int(inflight)))
    )


def encode_error_resp(req_id: int, message: str) -> bytes:
    return (
        bytes([VERIFY_RESP]) + encode_varint(req_id) + encode_varint(STATUS_ERR)
        + _pb(message.encode("utf-8", "replace")[:1024])
    )


def encode_status_req(req_id: int) -> bytes:
    return bytes([STATUS_REQ]) + encode_varint(req_id)


def encode_status_resp(req_id: int, slices) -> bytes:
    out = [bytes([STATUS_RESP]), encode_varint(req_id), encode_varint(len(slices))]
    for inflight, depth in slices:
        out.append(encode_varint(max(0, int(inflight))))
        out.append(encode_varint(max(0, int(depth))))
    return b"".join(out)


def decode(message: bytes) -> tuple[int, dict]:
    """One framed payload -> (msg type, fields dict); raises ProtoWireError
    on any truncation/overrun (the transport treats that as a dead peer)."""
    if not message:
        raise ProtoWireError("empty fabric message")
    mtype, pos = message[0], 1
    if mtype == HELLO:
        proto, pos = decode_varint(message, pos)
        slices, pos = decode_varint(message, pos)
        modes = 0
        if pos < len(message):  # proto-1 peers send no capability flags
            modes, pos = decode_varint(message, pos)
        return mtype, {"proto": proto, "slices": slices, "modes": modes}
    if mtype == VERIFY_REQ:
        req_id, pos = decode_varint(message, pos)
        kind_idx, pos = decode_varint(message, pos)
        if kind_idx >= len(KINDS):
            raise ProtoWireError(f"unknown verify kind {kind_idx}")
        slice_idx, pos = decode_varint(message, pos)
        tid, pos = _read_bytes(message, pos)
        count, pos = decode_varint(message, pos)
        if count > MAX_ITEMS:
            raise ProtoWireError(f"oversized verify batch ({count} items)")
        items = []
        for _ in range(count):
            pub, pos = _read_bytes(message, pos)
            msg, pos = _read_bytes(message, pos)
            sig, pos = _read_bytes(message, pos)
            items.append((pub, msg, sig))
        return mtype, {
            "req_id": req_id, "kind": KINDS[kind_idx], "slice": slice_idx,
            "trace_id": tid.decode("utf-8", "replace") or None, "items": items,
        }
    if mtype == VERIFY_RESP:
        req_id, pos = decode_varint(message, pos)
        status, pos = decode_varint(message, pos)
        if status != STATUS_OK:
            emsg, pos = _read_bytes(message, pos)
            return mtype, {"req_id": req_id, "ok": False, "error": emsg.decode("utf-8", "replace")}
        count, pos = decode_varint(message, pos)
        if count > MAX_ITEMS:
            raise ProtoWireError(f"oversized verify mask ({count} lanes)")
        packed, pos = _read_bytes(message, pos)
        if len(packed) != (count + 7) // 8:
            raise ProtoWireError(f"mask length mismatch ({len(packed)} bytes for {count} lanes)")
        mask = np.unpackbits(np.frombuffer(packed, dtype=np.uint8), count=count).astype(bool)
        queue_ns, pos = decode_varint(message, pos)
        verify_ns, pos = decode_varint(message, pos)
        inflight, pos = decode_varint(message, pos)
        return mtype, {
            "req_id": req_id, "ok": True, "mask": mask,
            "queue_ns": queue_ns, "verify_ns": verify_ns, "inflight": inflight,
        }
    if mtype == STATUS_REQ:
        req_id, pos = decode_varint(message, pos)
        return mtype, {"req_id": req_id}
    if mtype == STATUS_RESP:
        req_id, pos = decode_varint(message, pos)
        n, pos = decode_varint(message, pos)
        if n > 4096:
            raise ProtoWireError(f"implausible slice count {n}")
        slices = []
        for _ in range(n):
            inflight, pos = decode_varint(message, pos)
            depth, pos = decode_varint(message, pos)
            slices.append((inflight, depth))
        return mtype, {"req_id": req_id, "slices": slices}
    raise ProtoWireError(f"unknown fabric message type {mtype:#x}")


def frame(message: bytes) -> bytes:
    """Payload -> on-the-wire bytes (the shared gRPC length prefix)."""
    return encode_grpc_frame(message)


def read_message(read_exactly) -> tuple[int, dict]:
    """Read + decode one framed message via ``read_exactly(n) -> bytes``."""
    return decode(read_grpc_frame(read_exactly))
