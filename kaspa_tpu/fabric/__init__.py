"""Verify fabric: the process-local verify plane as a distributed service.

- `wire`     — length-prefixed message codec (gRPC framing + varints)
- `service`  — verifyd: accepts verify super-batches, feeds slice workers
- `client`   — one socket to one verifyd, request/response correlation
- `balancer` — cross-host dispatch engine: least-loaded slice routing,
  per-slice breakers, failover to the bit-identical host degraded lane

`balancer.configure("HOST:PORT,...")` installs the balancer as the
process-wide verify engine (`ops/dispatch.install`), so every existing
caller of the coalescing dispatcher — BatchScriptChecker, the pipeline,
daemon shutdown — routes over the fabric unchanged.
"""
