"""Verify-fabric client transport: one socket to one verifyd.

Dumb by design — request/response correlation, occupancy, deadlines and
failover live in `fabric/balancer.py`; this layer owns the socket, the
reader thread, and the `fabric.send` / `fabric.recv` fault points
(cooperative modes mangle/drop frames or sever the connection, exactly
like the P2P wire's `p2p.send`/`p2p.recv`)."""

from __future__ import annotations

import socket
import threading

from kaspa_tpu.utils.sync import ranked_lock

from kaspa_tpu.fabric import wire
from kaspa_tpu.resilience.faults import FAULTS, mangle_frame


class FabricConnection:
    """Socket + reader thread; delivers decoded messages to ``on_message``
    and a single terminal ``on_disconnect(exc)`` when the stream dies."""

    def __init__(self, addr: str, on_message=None, on_disconnect=None):
        host, _, port = addr.rpartition(":")
        self.addr = addr
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self.on_message = on_message
        self.on_disconnect = on_disconnect
        self.hello: dict | None = None
        self.sock: socket.socket | None = None
        self._wlock = ranked_lock("fabric.wire", reentrant=False)
        self._dead = threading.Event()
        self._down_fired = False

    @property
    def alive(self) -> bool:
        return self.sock is not None and not self._dead.is_set()

    def connect(self, timeout: float = 5.0) -> dict:
        """Dial and read the server HELLO; starts the reader thread.
        Returns the HELLO fields (proto version, slice count)."""
        sock = socket.create_connection((self.host, self.port), timeout=timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            mtype, hello = wire.read_message(lambda n: self._read_exactly(sock, n))
            if mtype != wire.HELLO:
                raise wire.ProtoWireError(f"expected HELLO, got {mtype:#x}")
        except Exception:
            sock.close()
            raise
        sock.settimeout(None)
        self.sock = sock
        self.hello = hello
        self._dead.clear()
        self._down_fired = False
        threading.Thread(target=self._reader, name=f"fabric-client-{self.addr}", daemon=True).start()
        return hello

    @staticmethod
    def _read_exactly(sock: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("fabric server closed mid-frame")
            buf += chunk
        return buf

    def send(self, payload: bytes) -> None:
        """Frame + send one message; raises ConnectionError when the link
        is down or an injected fault severs it.  A ``drop`` fault returns
        silently — the request will deadline out upstream, which is the
        exact failure shape of a frame lost in flight."""
        frame = wire.frame(payload)
        act = FAULTS.fire("fabric.send")
        if act is not None:
            if act.mode == "disconnect":
                self._teardown(ConnectionError("fault: fabric.send disconnect"))
                raise ConnectionError("fabric.send: injected disconnect")
            frame = mangle_frame(frame, act)
            if frame is None:
                return  # dropped in flight
        sock = self.sock
        if sock is None or self._dead.is_set():
            raise ConnectionError(f"fabric connection {self.addr} is down")
        try:
            with self._wlock:
                sock.sendall(frame)
        except OSError as e:
            self._teardown(e)
            raise ConnectionError(f"fabric send to {self.addr} failed: {e}") from e

    def _reader(self) -> None:
        sock = self.sock
        try:
            while not self._dead.is_set():
                mtype, msg = wire.read_message(lambda n: self._read_exactly(sock, n))
                act = FAULTS.fire("fabric.recv")
                if act is not None:
                    if act.mode == "disconnect":
                        raise ConnectionError("fault: fabric.recv disconnect")
                    if act.mode == "drop":
                        continue  # response lost in flight -> deadline path
                if self.on_message is not None:
                    self.on_message(self, mtype, msg)
        except Exception as e:  # noqa: BLE001 - any stream error is terminal
            self._teardown(e)

    def _teardown(self, exc: Exception) -> None:
        fire = False
        with self._wlock:
            if not self._dead.is_set():
                self._dead.set()
                fire = not self._down_fired
                self._down_fired = True
        sock, self.sock = self.sock, None
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            sock.close()
        if fire and self.on_disconnect is not None:
            self.on_disconnect(self, exc)

    def close(self) -> None:
        self._teardown(ConnectionError("closed by client"))
