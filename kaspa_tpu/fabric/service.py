"""verifyd: the verify-fabric server.

Accepts verify super-batch jobs over the length-prefixed wire
(`fabric/wire.py`) and runs them on per-slice worker lanes:

- each connection gets a reader thread (same discipline as
  `p2p/transport.py`): one `VERIFY_REQ` frame -> one job queued onto the
  slice the client addressed;
- each slice worker pins its device dispatches with `mesh.slice_lane(i)`
  (disjoint devices when a 2-D grid is configured, no-op otherwise) and
  feeds the local CoalescingDispatcher when one is active — remote chunks
  coalesce with local traffic into the same super-batches — else calls
  the batched verify front-end directly;
- responses carry the server-side queue/verify nanoseconds and the
  slice's post-completion inflight count, so the client can graft remote
  spans into the block's flight trace and route by real occupancy.

Runnable standalone (the two-process quickstart / roundcheck fabric
drill):

    python -m kaspa_tpu.fabric.service --listen 127.0.0.1:0 --slices 2

prints one JSON line ``{"fabric_listen": "host:port", ...}`` once bound.
"""

from __future__ import annotations

import json
import os
import queue
import socket
import threading

from kaspa_tpu.utils.sync import ranked_lock
from time import perf_counter_ns

from kaspa_tpu.fabric import wire
from kaspa_tpu.observability import trace
from kaspa_tpu.observability.core import REGISTRY
from kaspa_tpu.resilience.faults import FAULTS

_REQS = REGISTRY.counter_family("fabric_service_requests", "slice", help="verify requests served per fabric slice")
_JOBS = REGISTRY.counter_family("fabric_service_jobs", "slice", help="verify jobs served per fabric slice")
_ERRORS = REGISTRY.counter("fabric_service_errors", help="verify requests answered with an error status")


class _Conn:
    """One accepted client: socket + write lock (slice workers interleave
    responses on the same stream)."""

    def __init__(self, sock: socket.socket, peer: str):
        self.sock = sock
        self.peer = peer
        self._wlock = ranked_lock("fabric.wire", reentrant=False)
        self.alive = True

    def read_exactly(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError(f"fabric peer {self.peer} closed mid-frame")
            buf += chunk
        return buf

    def send(self, payload: bytes) -> None:
        with self._wlock:
            self.sock.sendall(wire.frame(payload))

    def close(self) -> None:
        self.alive = False
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


class VerifyService:
    """The verifyd server; `start()` binds and returns (host, port)."""

    def __init__(self, listen: str = "127.0.0.1:0", slices: int | None = None):
        host, _, port = listen.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port or 0)
        if slices is None:
            from kaspa_tpu.ops import mesh

            slices = mesh.slice_count()
        self.slices = max(1, int(slices))
        self._queues: list[queue.Queue] = [queue.Queue() for _ in range(self.slices)]  # graftlint: allow(unbounded-queue) -- per-slice dispatch handoff; producers are the bounded wire readers, a maxsize here would deadlock the service loop
        self._inflight = [0] * self.slices
        self._served = [0] * self.slices
        self._lock = ranked_lock("fabric.service", reentrant=False)
        self._listener: socket.socket | None = None
        self._conns: list[_Conn] = []
        self._threads: list[threading.Thread] = []
        self._stopped = threading.Event()
        REGISTRY.register_collector("fabric_service", self._state)

    def _state(self) -> dict:
        with self._lock:
            return {
                "listen": f"{self.host}:{self.port}",
                "slices": [
                    {"inflight": self._inflight[i], "queue_depth": self._queues[i].qsize(),
                     "served": self._served[i]}
                    for i in range(self.slices)
                ],
                "connections": sum(1 for c in self._conns if c.alive),
            }

    def start(self) -> tuple[str, int]:
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((self.host, self.port))
        ls.listen(64)
        self.port = ls.getsockname()[1]
        self._listener = ls
        for i in range(self.slices):
            t = threading.Thread(target=self._slice_worker, args=(i,), name=f"fabric-slice-{i}", daemon=True)
            t.start()
            self._threads.append(t)
        t = threading.Thread(target=self._accept_loop, name="fabric-accept", daemon=True)
        t.start()
        self._threads.append(t)
        return self.host, self.port

    def stop(self) -> None:
        self._stopped.set()
        if self._listener is not None:
            self._listener.close()
        with self._lock:
            conns, self._conns = self._conns, []
        for c in conns:
            c.close()
        for q in self._queues:
            q.put(None)  # slice-worker sentinel

    # --- connection handling ------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                sock, addr = self._listener.accept()
            except OSError:
                return
            if self._stopped.is_set():
                # stop() closed the listener while we were blocked in
                # accept(); the in-flight syscall keeps the kernel socket
                # alive, so a reconnect racing the shutdown can still land
                # here — drop it before HELLO so the dialer fails over
                sock.close()
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = _Conn(sock, f"{addr[0]}:{addr[1]}")
            with self._lock:
                self._conns.append(conn)
            try:
                conn.send(wire.encode_hello(self.slices, modes=wire.MODE_AGGREGATE))
            except OSError:
                conn.close()
                continue
            threading.Thread(
                target=self._reader, args=(conn,), name=f"fabric-read-{conn.peer}", daemon=True
            ).start()

    def _reader(self, conn: _Conn) -> None:
        try:
            while conn.alive:
                mtype, msg = wire.read_message(conn.read_exactly)
                if mtype == wire.VERIFY_REQ:
                    self._queues[msg["slice"] % self.slices].put((conn, msg, perf_counter_ns()))  # graftlint: allow(trace-ctx-handoff) -- remote span grafting rides msg['trace_id']; the server has no local parent ctx to attach
                elif mtype == wire.STATUS_REQ:
                    with self._lock:
                        per_slice = [
                            (self._inflight[i], self._queues[i].qsize()) for i in range(self.slices)
                        ]
                    conn.send(wire.encode_status_resp(msg["req_id"], per_slice))
                # anything else from a client is ignored (forward compat)
        except (OSError, ConnectionError, wire.ProtoWireError):
            pass
        finally:
            conn.close()
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)

    # --- slice workers ------------------------------------------------------

    def _slice_worker(self, idx: int) -> None:
        from kaspa_tpu.ops import dispatch as coalesce
        from kaspa_tpu.ops import mesh

        while True:
            job = self._queues[idx].get()
            if job is None:
                return
            conn, msg, t_recv = job
            with self._lock:
                self._inflight[idx] += 1
            try:
                # the slice-hang drill point: "slow"/"hang" stalls this lane
                # past the client deadline; its breaker must trip as `hung`
                # while the other slices keep serving
                FAULTS.fire("fabric.slice_hang")
                t0 = perf_counter_ns()
                mask = self._verify(idx, msg["kind"], msg["items"], msg["trace_id"], coalesce, mesh)
                t1 = perf_counter_ns()
                with self._lock:
                    self._inflight[idx] -= 1
                    self._served[idx] += 1
                    inflight = self._inflight[idx]
                resp = wire.encode_verify_resp(msg["req_id"], mask, t0 - t_recv, t1 - t0, inflight)
            except Exception as e:  # noqa: BLE001 - answered, never crashes the lane
                with self._lock:
                    self._inflight[idx] -= 1
                _ERRORS.inc()
                resp = wire.encode_error_resp(msg["req_id"], f"{type(e).__name__}: {e}")
            _REQS.inc(str(idx))
            _JOBS.inc(str(idx), len(msg["items"]))
            try:
                conn.send(resp)
            except OSError:
                conn.close()

    def _verify(self, idx: int, kind: str, items: list, trace_id, coalesce, mesh):
        with trace.span("fabric.slice_verify", slice=idx, kind=kind, jobs=len(items),
                        remote_trace=trace_id or ""):
            with mesh.slice_lane(idx):
                eng = coalesce.active()
                # feed the *local* coalescing dispatcher only: when this
                # process also runs a fabric balancer (colocated client +
                # server), dispatching back into it would loop the job
                # straight out over the wire again
                if isinstance(eng, coalesce.CoalescingDispatcher):
                    return eng.submit(kind, items).wait()
                from kaspa_tpu.crypto import secp  # deferred: jax import

                return secp.verify_batch(kind, items)


def main(argv=None) -> int:
    import argparse
    import signal

    ap = argparse.ArgumentParser(description="kaspa-tpu verify-fabric server (verifyd)")
    ap.add_argument("--listen", default="127.0.0.1:0", help="HOST:PORT to bind (port 0 = ephemeral)")
    ap.add_argument("--slices", type=int, default=None,
                    help="slice worker lanes (default: mesh slice count)")
    ap.add_argument("--mesh", default=None, help="device mesh spec (N | auto | RxC)")
    # graftlint: allow(env-knob) -- verifyd exists to batch: its CLI default is sweep-seeded auto, deliberately diverging from the in-node default of off
    ap.add_argument("--coalesce", default=os.environ.get("KASPA_TPU_COALESCE", "auto"),
                    help="local coalescing target feeding the slices (N | auto | off)")
    ap.add_argument("--verify-mode", default=None, choices=("ladder", "aggregate", "auto"),
                    help="schnorr verify lane: per-sig ladder, RLC aggregate, or auto by batch size")
    args = ap.parse_args(argv)

    from kaspa_tpu.utils import jax_setup

    jax_setup.setup()
    from kaspa_tpu.ops import dispatch as coalesce
    from kaspa_tpu.ops import mesh

    if args.mesh is not None:
        mesh.configure(args.mesh)
    coalesce.configure(args.coalesce)
    if args.verify_mode is not None:
        coalesce.set_verify_mode(args.verify_mode)

    svc = VerifyService(args.listen, slices=args.slices)
    host, port = svc.start()
    print(json.dumps({
        "fabric_listen": f"{host}:{port}", "slices": svc.slices,
        "mesh": mesh.active_size(), "pid": os.getpid(),
        "verify_mode": coalesce.verify_mode(),
    }), flush=True)

    done = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: done.set())
    done.wait()
    svc.stop()
    coalesce.shutdown(timeout=5.0)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
