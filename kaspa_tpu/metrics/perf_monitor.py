"""Process performance monitor: CPU / memory / IO sampling.

Reference: metrics/perf_monitor/src/ — a service sampling process counters
on a tick for operator dashboards, surfaced through get_metrics.  Reads
/proc directly (no psutil dependency in the image).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass


@dataclass
class ProcessMetrics:
    resident_set_size: int  # bytes
    virtual_memory_size: int  # bytes
    core_num: int
    cpu_usage: float  # fraction of one core since the previous sample
    fd_num: int
    disk_io_read_bytes: int
    disk_io_write_bytes: int


def _read_proc_stat():
    with open("/proc/self/stat") as f:
        raw = f.read()
    # comm may contain spaces/parens: index from the last ')'
    parts = raw[raw.rindex(")") + 2 :].split()
    # parts[0] == state (field 3); utime/stime are fields 14/15
    utime, stime = int(parts[11]), int(parts[12])
    vsize, rss_pages = int(parts[20]), int(parts[21])
    return utime + stime, vsize, rss_pages * os.sysconf("SC_PAGE_SIZE")


def _read_proc_io():
    try:
        with open("/proc/self/io") as f:
            d = dict(line.strip().split(": ") for line in f if ": " in line)
        return int(d.get("read_bytes", 0)), int(d.get("write_bytes", 0))
    except OSError:
        return 0, 0


class PerfMonitor:
    def __init__(self):
        self._hz = os.sysconf("SC_CLK_TCK")
        self._last_cpu_ticks, _, _ = _read_proc_stat()
        self._last_time = time.monotonic()

    def sample(self) -> ProcessMetrics:
        now = time.monotonic()
        cpu_ticks, vsize, rss = _read_proc_stat()
        elapsed = max(now - self._last_time, 1e-9)
        cpu_usage = (cpu_ticks - self._last_cpu_ticks) / self._hz / elapsed
        self._last_cpu_ticks, self._last_time = cpu_ticks, now
        reads, writes = _read_proc_io()
        try:
            fd_num = len(os.listdir("/proc/self/fd"))
        except OSError:
            fd_num = 0
        return ProcessMetrics(
            resident_set_size=rss,
            virtual_memory_size=vsize,
            core_num=os.cpu_count() or 0,
            cpu_usage=cpu_usage,
            fd_num=fd_num,
            disk_io_read_bytes=reads,
            disk_io_write_bytes=writes,
        )
