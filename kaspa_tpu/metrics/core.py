"""Metrics core: snapshots + per-second rates (metrics/core/src/data.rs).

The reference's MetricsSnapshot holds grouped gauges (System / Storage /
Bandwidth / Connections / Network); a Metrics poller samples the node on
a tick and derives `*PerSecond` rates from consecutive snapshot deltas.
Here the snapshot is a flat dict keyed by the same metric names, the
groups index into it, and `MetricsData.rates()` computes the deltas."""

from __future__ import annotations

import time
from dataclasses import dataclass, field

METRIC_GROUPS: dict[str, list[str]] = {
    "system": [
        "node_cpu_usage",
        "node_resident_set_size_bytes",
        "node_virtual_memory_size_bytes",
        "node_file_handles_count",
    ],
    "storage": [
        "node_disk_io_read_bytes",
        "node_disk_io_read_per_sec",
        "node_disk_io_write_bytes",
        "node_disk_io_write_per_sec",
        "node_storage_size_bytes",
    ],
    "bandwidth": [
        "node_total_bytes_tx",
        "node_total_bytes_tx_per_second",
        "node_total_bytes_rx",
        "node_total_bytes_rx_per_second",
    ],
    "connections": [
        "node_active_peers",
        "node_borsh_live_connections",
        "node_json_live_connections",
    ],
    "network": [
        "node_blocks_submitted_count",
        "node_headers_processed_count",
        "node_dependencies_processed_count",
        "node_bodies_processed_count",
        "node_txs_processed_count",
        "node_chain_blocks_processed_count",
        "node_mass_processed_count",
        "node_database_blocks_count",
        "node_database_headers_count",
        "network_mempool_size",
        "network_tip_hashes_count",
        "network_difficulty",
        "network_past_median_time",
        "network_virtual_parent_hashes_count",
        "network_virtual_daa_score",
    ],
}

_RATE_SOURCES = {
    "node_disk_io_read_per_sec": "node_disk_io_read_bytes",
    "node_disk_io_write_per_sec": "node_disk_io_write_bytes",
    "node_total_bytes_tx_per_second": "node_total_bytes_tx",
    "node_total_bytes_rx_per_second": "node_total_bytes_rx",
}


@dataclass
class MetricsSnapshot:
    unixtime_millis: float
    values: dict = field(default_factory=dict)

    def get(self, name: str, default=0):
        return self.values.get(name, default)

    def group(self, name: str) -> dict:
        return {m: self.values.get(m) for m in METRIC_GROUPS.get(name, [])}


class MetricsData:
    """Rolling pair of snapshots; rates derive from the last delta
    (data.rs MetricsData duration-normalized counters)."""

    def __init__(self):
        self._prev: MetricsSnapshot | None = None
        self.last: MetricsSnapshot | None = None

    def push(self, snapshot: MetricsSnapshot) -> MetricsSnapshot:
        self._prev, self.last = self.last, snapshot
        for rate_name, source in _RATE_SOURCES.items():
            snapshot.values[rate_name] = self._rate(source)
        return snapshot

    def _rate(self, name: str) -> float:
        if self._prev is None or self.last is None:
            return 0.0
        dt = (self.last.unixtime_millis - self._prev.unixtime_millis) / 1000.0
        if dt <= 0:
            return 0.0
        return max(0.0, (self.last.get(name) - self._prev.get(name)) / dt)


def collect_snapshot(consensus, mining, perf_monitor, p2p_node=None, wire_stats=None) -> MetricsSnapshot:
    """Sample every subsystem into one snapshot (the Metrics service's
    task body in metrics/core/src/lib.rs:25-60)."""
    pm = perf_monitor.sample()
    counters = consensus.counters.snapshot()
    snap = MetricsSnapshot(unixtime_millis=time.time() * 1000)
    v = snap.values
    v["node_cpu_usage"] = pm.cpu_usage
    v["node_resident_set_size_bytes"] = pm.resident_set_size
    v["node_virtual_memory_size_bytes"] = pm.virtual_memory_size
    v["node_file_handles_count"] = pm.fd_num
    v["node_disk_io_read_bytes"] = pm.disk_io_read_bytes
    v["node_disk_io_write_bytes"] = pm.disk_io_write_bytes
    db = consensus.storage.db
    v["node_storage_size_bytes"] = db.size_on_disk() if db is not None and hasattr(db, "size_on_disk") else 0
    if wire_stats is not None:
        v["node_total_bytes_tx"] = wire_stats.bytes_tx
        v["node_total_bytes_rx"] = wire_stats.bytes_rx
    v["node_active_peers"] = len(p2p_node.peers) if p2p_node is not None else 0
    v["node_blocks_submitted_count"] = counters.blocks_submitted
    v["node_headers_processed_count"] = counters.header_counts
    v["node_dependencies_processed_count"] = counters.dep_counts
    v["node_bodies_processed_count"] = counters.body_counts
    v["node_txs_processed_count"] = counters.txs_counts
    v["node_chain_blocks_processed_count"] = counters.chain_block_counts
    v["node_mass_processed_count"] = counters.mass_counts
    v["node_database_blocks_count"] = len(consensus.storage.block_transactions)
    if consensus.storage.db is not None and hasattr(consensus.storage.db, "mem_stats"):
        for k2, v2 in consensus.storage.db.mem_stats().items():
            v[f"node_database_{k2}"] = v2
    v["node_database_headers_count"] = len(consensus.storage.headers)
    v["network_mempool_size"] = len(mining.mempool)
    v["network_tip_hashes_count"] = len(consensus.tips)
    v["network_virtual_daa_score"] = consensus.get_virtual_daa_score()
    vs = consensus.virtual_state
    v["network_virtual_parent_hashes_count"] = len(vs.parents) if vs else 0
    v["network_difficulty"] = float(vs.bits) if vs else 0.0
    v["network_past_median_time"] = vs.past_median_time if vs else 0
    return snap
