from kaspa_tpu.metrics.perf_monitor import PerfMonitor, ProcessMetrics  # noqa: F401
