"""Golden-DAG replay: validate reference-produced DAG files end to end.

Reference strategy: testing/integration/src/consensus_integration_tests.rs
json_test — JSON DAG files produced by the golang kaspad
(testdata/dags_for_json_tests/) are replayed through the full pipeline as
cross-implementation consensus equivalence testing.  Every header field our
pipeline recomputes (difficulty bits, DAA score, blue score/work, median
time, merkle roots, utxo commitments, coinbase payouts) is checked against
the golden data, so a single divergence anywhere in the stack fails the
replay.
"""

from __future__ import annotations

import gzip
import json

from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.consensus.model import (
    ComputeCommit,
    Header,
    ScriptPublicKey,
    Transaction,
    TransactionInput,
    TransactionOutpoint,
    TransactionOutput,
)
from kaspa_tpu.consensus.model.block import Block
from kaspa_tpu.consensus.params import GenesisBlock, Params


def _h(s: str) -> bytes:
    return bytes.fromhex(s)


def _parse_tx(j: dict) -> Transaction:
    inputs = []
    for i in j["inputs"]:
        op = TransactionOutpoint(_h(i["previousOutpoint"]["transactionId"]), i["previousOutpoint"]["index"])
        inputs.append(
            TransactionInput(op, _h(i["signatureScript"]), i["sequence"], ComputeCommit.sigops(i.get("sigOpCount", 0)))
        )
    outputs = []
    for o in j["outputs"]:
        spk_raw = _h(o["scriptPublicKey"])
        version = int.from_bytes(spk_raw[:2], "little")
        outputs.append(TransactionOutput(o["value"], ScriptPublicKey(version, spk_raw[2:])))
    return Transaction(
        j["version"],
        inputs,
        outputs,
        j["lockTime"],
        _h(j["subnetworkId"]),
        j["gas"],
        _h(j["payload"]),
        storage_mass=j.get("mass", 0),
    )


def _parse_block(j: dict) -> Block:
    h = j["header"]
    header = Header(
        version=h["version"],
        parents_by_level=[[_h(p) for p in level] for level in h["parentsByLevel"]],
        hash_merkle_root=_h(h["hashMerkleRoot"]),
        accepted_id_merkle_root=_h(h["acceptedIdMerkleRoot"]),
        utxo_commitment=_h(h["utxoCommitment"]),
        timestamp=h["timestamp"],
        bits=h["bits"],
        nonce=h["nonce"],
        daa_score=h["daaScore"],
        blue_work=int(h["blueWork"], 16),
        blue_score=h["blueScore"],
        pruning_point=_h(h["pruningPoint"]),
    )
    expected_hash = _h(h["hash"])
    assert header.hash == expected_hash, (
        f"header hashing divergence: computed {header.hash.hex()}, file says {expected_hash.hex()}"
    )
    return Block(header, [_parse_tx(t) for t in j["transactions"]])


def load_goref(path: str):
    """Returns (params, blocks) from a goref blocks.json(.gz) file."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        meta = json.loads(f.readline())
        blocks = [_parse_block(json.loads(line)) for line in f if line.strip()]

    br = meta["blockrate"]
    genesis_block = blocks[0]
    assert not genesis_block.header.direct_parents(), "first block must be genesis"
    bps = 1000 // br["target_time_per_block"]
    params = Params(
        name="goref",
        bps=bps,
        genesis=GenesisBlock(
            hash=genesis_block.hash,
            bits=genesis_block.header.bits,
            timestamp=genesis_block.header.timestamp,
            version=genesis_block.header.version,
            daa_score=genesis_block.header.daa_score,
        ),
        ghostdag_k=br["ghostdag_k"],
        target_time_per_block=br["target_time_per_block"],
        max_block_parents=br["max_block_parents"],
        mergeset_size_limit=br["mergeset_size_limit"],
        merge_depth=br["merge_depth"],
        finality_depth=br["finality_depth"],
        pruning_depth=br["pruning_depth"],
        coinbase_maturity=br["coinbase_maturity"],
        difficulty_window_size=meta["difficulty_window_size"],
        min_difficulty_window_size=meta["min_difficulty_window_size"],
        difficulty_sample_rate=br["difficulty_sample_rate"],
        past_median_time_window_size=meta["past_median_time_window_size"],
        past_median_time_sample_rate=br["past_median_time_sample_rate"],
        timestamp_deviation_tolerance=meta["timestamp_deviation_tolerance"],
        max_block_mass=meta["prior_block_mass_limits"]["compute"],
        mass_per_tx_byte=meta["mass_per_tx_byte"],
        mass_per_script_pub_key_byte=meta["mass_per_script_pub_key_byte"],
        mass_per_sig_op=meta["mass_per_sig_op"],
        storage_mass_parameter=meta["storage_mass_parameter"],
        max_tx_inputs=meta["max_tx_inputs"],
        max_tx_outputs=meta["max_tx_outputs"],
        max_signature_script_len=meta.get("prior_max_signature_script_len", 1000),
        max_script_public_key_len=meta["max_script_public_key_len"],
        max_coinbase_payload_len=meta["max_coinbase_payload_len"],
        deflationary_phase_daa_score=meta["deflationary_phase_daa_score"],
        pre_deflationary_phase_base_subsidy=meta["pre_deflationary_phase_base_subsidy"],
        skip_proof_of_work=meta["skip_proof_of_work"],
        max_block_level=meta["max_block_level"],
        pruning_proof_m=meta["pruning_proof_m"],
        genesis_override=genesis_block,
    )
    return params, blocks


def replay_goref(path: str, limit: int | None = None, db=None, cache_policy=None) -> Consensus:
    """Replay blocks[1:] (genesis inserted by construction); raises on any
    consensus divergence from the golden data.  ``db``/``cache_policy``
    attach persistence with bounded store caches (memory-bounded replay)."""
    params, blocks = load_goref(path)
    consensus = Consensus(params, db=db, cache_policy=cache_policy)
    for i, block in enumerate(blocks[1:], start=1):
        if limit is not None and i > limit:
            break
        status = consensus.validate_and_insert_block(block)
        if status not in ("utxo_valid", "utxo_pending"):
            raise AssertionError(f"block {i} ({block.hash.hex()}) got status {status}")
    return consensus
