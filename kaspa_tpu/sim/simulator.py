"""simpa-equivalent DAG simulator + validation replay benchmark.

Mirrors the reference's simpa tool (simpa/src/): a discrete-event
virtual-time network of miners produces a DAG at a target BPS with a
simulated propagation delay (concurrent miners see each other's blocks
late — this is what creates the blue/red merge structure), with real
schnorr-signed P2PK transactions; then the produced DAG is replayed into a
*fresh* consensus, measuring validation wall-clock — the canonical
validation-throughput harness (simpa/src/main.rs:327-345).
"""

from __future__ import annotations

import heapq
import random
import time
from dataclasses import dataclass

from kaspa_tpu.consensus import hashing as chash
from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.consensus.model import Transaction, TransactionInput, TransactionOutput
from kaspa_tpu.consensus.model.block import Block
from kaspa_tpu.consensus.mass import BlockMassLimits, NonContextualMasses
from kaspa_tpu.consensus.model.tx import ComputeCommit, SUBNETWORK_ID_NATIVE
from kaspa_tpu.consensus.params import Params, simnet_params
from kaspa_tpu.consensus.processes.coinbase import MinerData
from kaspa_tpu.crypto import eclib
from kaspa_tpu.txscript import standard


@dataclass
class SimConfig:
    bps: int = 2
    delay: float = 2.0  # seconds propagation delay
    num_miners: int = 4
    num_blocks: int = 64
    txs_per_block: int = 8
    seed: int = 42


@dataclass
class SimResult:
    blocks: list
    params: Params
    build_seconds: float
    total_txs: int
    sink: bytes
    virtual_daa_score: int


class Miner:
    def __init__(self, idx: int, rng: random.Random):
        self.idx = idx
        self.seckey = rng.randrange(1, eclib.N)
        self.pubkey = eclib.schnorr_pubkey(self.seckey)
        self.spk = standard.pay_to_pub_key(self.pubkey)
        self.miner_data = MinerData(self.spk, extra_data=f"miner-{idx}".encode())


def _make_tx(miner: Miner, outpoint, entry, rng: random.Random, mass_calculator=None) -> Transaction:
    """Spend one UTXO back to the miner (split in two) with a real signature."""
    half = entry.amount // 2
    if half == 0:
        return None
    outputs = [TransactionOutput(half, miner.spk), TransactionOutput(entry.amount - half, miner.spk)]
    inp = TransactionInput(outpoint, b"", 0, ComputeCommit.sigops(1))
    tx = Transaction(0, [inp], outputs, 0, SUBNETWORK_ID_NATIVE, 0, b"")
    if mass_calculator is None:
        from kaspa_tpu.consensus.mass import MassCalculator

        mass_calculator = MassCalculator()
    tx.storage_mass = mass_calculator.calc_contextual_masses(tx, [entry])
    reused = chash.SigHashReusedValues()
    msg = chash.calc_schnorr_signature_hash(tx, [entry], 0, chash.SIG_HASH_ALL, reused)
    sig = eclib.schnorr_sign(msg, miner.seckey, rng.randbytes(32))
    tx.inputs[0].signature_script = standard.schnorr_signature_script(sig, chash.SIG_HASH_ALL)
    tx._id_cache = None
    return tx


def simulate(cfg: SimConfig) -> SimResult:
    """Build a DAG with one authoritative consensus + per-miner delayed views."""
    rng = random.Random(cfg.seed)
    params = simnet_params(bps=cfg.bps)
    consensus = Consensus(params)
    miners = [Miner(i, rng) for i in range(cfg.num_miners)]

    t0 = time.perf_counter()
    events = []
    seq = 0
    lam = cfg.bps / cfg.num_miners
    for m in miners:
        events.append((rng.expovariate(lam), seq, m.idx))
        seq += 1
    heapq.heapify(events)

    mined: dict[bytes, tuple[float, int]] = {params.genesis.hash: (-cfg.delay, -1)}  # block -> (mine time, miner)
    total_txs = 0
    blocks: list[Block] = []

    while len(blocks) < cfg.num_blocks:
        vtime, _, midx = heapq.heappop(events)
        miner = miners[midx]
        # a block is visible to this miner if it mined it, or it propagated
        visible = {h for h, (at, owner) in mined.items() if owner == midx or at + cfg.delay <= vtime}
        tips = [h for h in visible if not any(c in visible for c in consensus.storage.relations.get_children(h))]
        tips.sort(key=lambda h: (consensus.storage.ghostdag.get_blue_work(h), h), reverse=True)
        parents = tips[: params.max_block_parents]

        def tx_selector(view, pov_daa_score, miner=miner):
            mass_calc = consensus.transaction_validator.mass_calculator
            limits = BlockMassLimits.with_shared_limit(params.max_block_mass)
            used_compute = used_transient = used_storage = 0
            txs = []
            spent = set()
            base_items = list(view.diff.add.items())
            # walk the layered view: diff adds first, then underlying set
            under = view.base
            while hasattr(under, "base"):
                base_items += list(under.diff.add.items())
                under = under.base
            base_items += list(under.items())
            removed = set(view.diff.remove.keys())
            for outpoint, entry in base_items:
                if len(txs) >= cfg.txs_per_block:
                    break
                if outpoint in spent or outpoint in removed:
                    continue
                if view.get(outpoint) is None:
                    continue
                if entry.script_public_key != miner.spk:
                    continue
                if entry.is_coinbase and entry.block_daa_score + params.coinbase_maturity > pov_daa_score:
                    continue
                tx = _make_tx(miner, outpoint, entry, rng, mass_calc)
                if tx is None:
                    continue
                # template-builder discipline: stop at the per-dimension
                # block mass limits (the validator enforces the same caps)
                nc = mass_calc.calc_non_contextual_masses(tx)
                totals = NonContextualMasses(
                    used_compute + nc.compute_mass, used_transient + nc.transient_mass
                )
                if not limits.would_fit(totals, used_storage + tx.storage_mass):
                    break
                used_compute, used_transient = totals.compute_mass, totals.transient_mass
                used_storage += tx.storage_mass
                txs.append(tx)
                spent.add(outpoint)
            return txs

        block = consensus.build_block_with_parents(
            parents, miner.miner_data, timestamp=int(vtime * 1000) + 1, tx_selector=tx_selector
        )
        status = consensus.validate_and_insert_block(block)
        assert status in ("utxo_valid", "utxo_pending"), f"built block rejected: {status}"
        blocks.append(block)
        total_txs += len(block.transactions) - 1
        mined[block.hash] = (vtime, midx)

        heapq.heappush(events, (vtime + rng.expovariate(lam), seq, midx))
        seq += 1

    build_seconds = time.perf_counter() - t0
    return SimResult(
        blocks, params, build_seconds, total_txs, consensus.sink(), consensus.get_virtual_daa_score()
    )


def replay(result: SimResult) -> tuple[float, Consensus]:
    """Replay the DAG into a fresh consensus; returns (wall seconds, consensus)
    — the simpa validation benchmark, with end-state equivalence checks."""
    fresh = Consensus(result.params)
    t0 = time.perf_counter()
    for block in result.blocks:
        status = fresh.validate_and_insert_block(block)
        assert status in ("utxo_valid", "utxo_pending"), f"replay rejected block: {status}"
    elapsed = time.perf_counter() - t0
    assert fresh.sink() == result.sink, "replay reached a different sink"
    assert fresh.get_virtual_daa_score() == result.virtual_daa_score
    return elapsed, fresh
