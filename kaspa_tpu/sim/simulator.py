"""simpa-equivalent DAG simulator + validation replay benchmark.

Mirrors the reference's simpa tool (simpa/src/): a discrete-event
virtual-time network of miners produces a DAG at a target BPS with a
simulated propagation delay (concurrent miners see each other's blocks
late — this is what creates the blue/red merge structure), with real
schnorr-signed P2PK transactions; then the produced DAG is replayed into a
*fresh* consensus, measuring validation wall-clock — the canonical
validation-throughput harness (simpa/src/main.rs:327-345).
"""

from __future__ import annotations

import heapq
import random
import time
from dataclasses import dataclass

from kaspa_tpu.consensus import hashing as chash
from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.consensus.model import ScriptPublicKey, Transaction, TransactionInput, TransactionOutput
from kaspa_tpu.consensus.model.block import Block
from kaspa_tpu.consensus.mass import BlockMassLimits, NonContextualMasses
from kaspa_tpu.consensus.model.tx import ComputeCommit, SUBNETWORK_ID_NATIVE
from kaspa_tpu.consensus.params import Params, simnet_params
from kaspa_tpu.consensus.processes.coinbase import MinerData
from kaspa_tpu.crypto import eclib
from kaspa_tpu.txscript import standard


@dataclass
class SimConfig:
    bps: int = 2
    delay: float = 2.0  # seconds propagation delay
    num_miners: int = 4
    num_blocks: int = 64
    txs_per_block: int = 8
    seed: int = 42
    # hostile workload: a deterministic fraction of P2PK spends split into
    # bare-multisig + P2SH outputs, whose later spends bypass the device
    # fast path entirely (they ride the host-VM fallback lane) — the
    # script mix the hostile-load sustain run stresses
    hostile: bool = False
    hostile_fraction: float = 0.4


@dataclass
class SimResult:
    blocks: list
    params: Params
    build_seconds: float
    total_txs: int
    sink: bytes
    virtual_daa_score: int


class Miner:
    def __init__(self, idx: int, rng: random.Random, hostile: bool = False):
        self.idx = idx
        self.seckey = rng.randrange(1, eclib.N)
        self.pubkey = eclib.schnorr_pubkey(self.seckey)
        self.spk = standard.pay_to_pub_key(self.pubkey)
        self.miner_data = MinerData(self.spk, extra_data=f"miner-{idx}".encode())
        self.hostile = hostile
        if hostile:
            # hostile-mode script destinations (extra rng draws happen only
            # here, so non-hostile DAGs stay byte-identical per seed):
            # a 2-of-3 bare schnorr multisig and a trivially-redeemable P2SH
            self.ms_keys = [rng.randrange(1, eclib.N) for _ in range(3)]
            self.ms_pubs = [eclib.schnorr_pubkey(k) for k in self.ms_keys]
            self.ms_spk = ScriptPublicKey(0, standard.multisig_redeem_script(self.ms_pubs, 2))
            self.p2sh_redeem = bytes([0x51, 0x87])  # OP_1 OP_EQUAL
            self.p2sh_spk = standard.pay_to_script_hash_script(self.p2sh_redeem)


def _make_tx(miner: Miner, outpoint, entry, rng: random.Random, mass_calculator=None) -> Transaction:
    """Spend one UTXO back to the miner (split in two) with a real signature."""
    half = entry.amount // 2
    if half == 0:
        return None
    outputs = [TransactionOutput(half, miner.spk), TransactionOutput(entry.amount - half, miner.spk)]
    inp = TransactionInput(outpoint, b"", 0, ComputeCommit.sigops(1))
    tx = Transaction(0, [inp], outputs, 0, SUBNETWORK_ID_NATIVE, 0, b"")
    if mass_calculator is None:
        from kaspa_tpu.consensus.mass import MassCalculator

        mass_calculator = MassCalculator()
    tx.storage_mass = mass_calculator.calc_contextual_masses(tx, [entry])
    reused = chash.SigHashReusedValues()
    msg = chash.calc_schnorr_signature_hash(tx, [entry], 0, chash.SIG_HASH_ALL, reused)
    sig = eclib.schnorr_sign(msg, miner.seckey, rng.randbytes(32))
    tx.inputs[0].signature_script = standard.schnorr_signature_script(sig, chash.SIG_HASH_ALL)
    tx._id_cache = None
    return tx


def _sign_and_finish(tx: Transaction, entry, miner: Miner, rng: random.Random, mass_calculator) -> Transaction:
    """Storage mass + single-input P2PK schnorr signature (shared tail)."""
    tx.storage_mass = mass_calculator.calc_contextual_masses(tx, [entry])
    reused = chash.SigHashReusedValues()
    msg = chash.calc_schnorr_signature_hash(tx, [entry], 0, chash.SIG_HASH_ALL, reused)
    sig = eclib.schnorr_sign(msg, miner.seckey, rng.randbytes(32))
    tx.inputs[0].signature_script = standard.schnorr_signature_script(sig, chash.SIG_HASH_ALL)
    tx._id_cache = None
    return tx


def _make_hostile_split_tx(miner: Miner, outpoint, entry, rng: random.Random, mass_calculator) -> Transaction:
    """Spend a P2PK UTXO into one multisig + one P2SH output: the next
    spends of those outputs are host-VM-lane work (fast-path bypass)."""
    half = entry.amount // 2
    if half == 0:
        return None
    outputs = [TransactionOutput(half, miner.ms_spk), TransactionOutput(entry.amount - half, miner.p2sh_spk)]
    inp = TransactionInput(outpoint, b"", 0, ComputeCommit.sigops(1))
    tx = Transaction(0, [inp], outputs, 0, SUBNETWORK_ID_NATIVE, 0, b"")
    return _sign_and_finish(tx, entry, miner, rng, mass_calculator)


def _push(data: bytes) -> bytes:
    assert len(data) <= 75
    return bytes([len(data)]) + data


def _spend_multisig_tx(miner: Miner, outpoint, entry, rng: random.Random, mass_calculator) -> Transaction:
    """2-of-3 bare multisig spend back to the miner's P2PK.

    Signatures are pushed in key order (the VM scans keys forward); the
    worst-case sig-op charge is 3 (sig #2 burning a miss on key #1), hence
    the committed budget.
    """
    half = entry.amount // 2
    if half == 0:
        return None
    outputs = [TransactionOutput(half, miner.spk), TransactionOutput(entry.amount - half, miner.spk)]
    inp = TransactionInput(outpoint, b"", 0, ComputeCommit.sigops(3))
    tx = Transaction(0, [inp], outputs, 0, SUBNETWORK_ID_NATIVE, 0, b"")
    tx.storage_mass = mass_calculator.calc_contextual_masses(tx, [entry])
    reused = chash.SigHashReusedValues()
    msg = chash.calc_schnorr_signature_hash(tx, [entry], 0, chash.SIG_HASH_ALL, reused)
    sig_script = b""
    for key in (miner.ms_keys[0], miner.ms_keys[2]):
        sig = eclib.schnorr_sign(msg, key, rng.randbytes(32))
        sig_script += _push(sig + bytes([chash.SIG_HASH_ALL]))
    tx.inputs[0].signature_script = sig_script
    tx._id_cache = None
    return tx


def _spend_p2sh_tx(miner: Miner, outpoint, entry, mass_calculator) -> Transaction:
    """P2SH spend (trivial OP_1 OP_EQUAL redeem): no signatures at all,
    pure VM-lane script execution."""
    half = entry.amount // 2
    if half == 0:
        return None
    outputs = [TransactionOutput(half, miner.spk), TransactionOutput(entry.amount - half, miner.spk)]
    inp = TransactionInput(outpoint, b"", 0, ComputeCommit.sigops(0))
    tx = Transaction(0, [inp], outputs, 0, SUBNETWORK_ID_NATIVE, 0, b"")
    tx.storage_mass = mass_calculator.calc_contextual_masses(tx, [entry])
    # sig script: OP_1 (redeem's EQUAL operand) then the redeem push
    tx.inputs[0].signature_script = bytes([0x51]) + _push(miner.p2sh_redeem)
    tx._id_cache = None
    return tx


def simulate(cfg: SimConfig) -> SimResult:
    """Build a DAG with one authoritative consensus + per-miner delayed views."""
    rng = random.Random(cfg.seed)
    params = simnet_params(bps=cfg.bps)
    consensus = Consensus(params)
    miners = [Miner(i, rng, hostile=cfg.hostile) for i in range(cfg.num_miners)]

    t0 = time.perf_counter()
    events = []
    seq = 0
    lam = cfg.bps / cfg.num_miners
    for m in miners:
        events.append((rng.expovariate(lam), seq, m.idx))
        seq += 1
    heapq.heapify(events)

    mined: dict[bytes, tuple[float, int]] = {params.genesis.hash: (-cfg.delay, -1)}  # block -> (mine time, miner)
    total_txs = 0
    blocks: list[Block] = []

    while len(blocks) < cfg.num_blocks:
        vtime, _, midx = heapq.heappop(events)
        miner = miners[midx]
        # a block is visible to this miner if it mined it, or it propagated
        visible = {h for h, (at, owner) in mined.items() if owner == midx or at + cfg.delay <= vtime}
        tips = [h for h in visible if not any(c in visible for c in consensus.storage.relations.get_children(h))]
        tips.sort(key=lambda h: (consensus.storage.ghostdag.get_blue_work(h), h), reverse=True)
        parents = tips[: params.max_block_parents]

        def tx_selector(view, pov_daa_score, miner=miner):
            mass_calc = consensus.transaction_validator.mass_calculator
            limits = BlockMassLimits.with_shared_limit(params.max_block_mass)
            used_compute = used_transient = used_storage = 0
            txs = []
            spent = set()
            base_items = list(view.diff.add.items())
            # walk the layered view: diff adds first, then underlying set
            under = view.base
            while hasattr(under, "base"):
                base_items += list(under.diff.add.items())
                under = under.base
            base_items += list(under.items())
            removed = set(view.diff.remove.keys())
            for outpoint, entry in base_items:
                if len(txs) >= cfg.txs_per_block:
                    break
                if outpoint in spent or outpoint in removed:
                    continue
                if view.get(outpoint) is None:
                    continue
                if entry.is_coinbase and entry.block_daa_score + params.coinbase_maturity > pov_daa_score:
                    continue
                spk = entry.script_public_key
                if spk == miner.spk:
                    if cfg.hostile and rng.random() < cfg.hostile_fraction:
                        tx = _make_hostile_split_tx(miner, outpoint, entry, rng, mass_calc)
                    else:
                        tx = _make_tx(miner, outpoint, entry, rng, mass_calc)
                elif cfg.hostile and spk == miner.ms_spk:
                    tx = _spend_multisig_tx(miner, outpoint, entry, rng, mass_calc)
                elif cfg.hostile and spk == miner.p2sh_spk:
                    tx = _spend_p2sh_tx(miner, outpoint, entry, mass_calc)
                else:
                    continue
                if tx is None:
                    continue
                # template-builder discipline: stop at the per-dimension
                # block mass limits (the validator enforces the same caps)
                nc = mass_calc.calc_non_contextual_masses(tx)
                totals = NonContextualMasses(
                    used_compute + nc.compute_mass, used_transient + nc.transient_mass
                )
                if not limits.would_fit(totals, used_storage + tx.storage_mass):
                    break
                used_compute, used_transient = totals.compute_mass, totals.transient_mass
                used_storage += tx.storage_mass
                txs.append(tx)
                spent.add(outpoint)
            return txs

        block = consensus.build_block_with_parents(
            parents, miner.miner_data, timestamp=int(vtime * 1000) + 1, tx_selector=tx_selector
        )
        status = consensus.validate_and_insert_block(block)
        assert status in ("utxo_valid", "utxo_pending"), f"built block rejected: {status}"
        blocks.append(block)
        total_txs += len(block.transactions) - 1
        mined[block.hash] = (vtime, midx)

        heapq.heappush(events, (vtime + rng.expovariate(lam), seq, midx))
        seq += 1

    build_seconds = time.perf_counter() - t0
    return SimResult(
        blocks, params, build_seconds, total_txs, consensus.sink(), consensus.get_virtual_daa_score()
    )


def replay(result: SimResult) -> tuple[float, Consensus]:
    """Replay the DAG into a fresh consensus; returns (wall seconds, consensus)
    — the simpa validation benchmark, with end-state equivalence checks."""
    fresh = Consensus(result.params)
    t0 = time.perf_counter()
    for block in result.blocks:
        status = fresh.validate_and_insert_block(block)
        assert status in ("utxo_valid", "utxo_pending"), f"replay rejected block: {status}"
    elapsed = time.perf_counter() - t0
    assert fresh.sink() == result.sink, "replay reached a different sink"
    assert fresh.get_virtual_daa_score() == result.virtual_daa_score
    return elapsed, fresh


class _NullSink:
    """Discarding wire sink for the traced-replay fanout subscriber."""

    def put(self, item, timeout=None):
        return None


def replay_pipelined(
    result: SimResult, workers: int = 2, fanout: bool = False, speculative: bool | None = None
) -> tuple[float, "Consensus"]:
    """Replay through the concurrent ConsensusPipeline — stage workers,
    virtual worker and (when configured) the coalescing dispatcher all on
    their own threads, which is the multi-thread path the flight recorder
    is built to trace.  Same end-state equivalence checks as ``replay``.

    ``fanout=True`` attaches the serving Broadcaster with one null-sink
    subscriber, reproducing the production p2p->pipeline->serving thread
    topology so every block trace crosses the serving threads too."""
    from kaspa_tpu.pipeline.pipeline import ConsensusPipeline

    fresh = Consensus(result.params)
    broadcaster = None
    if fanout:
        from kaspa_tpu.serving.broadcaster import Broadcaster, Subscriber

        broadcaster = Broadcaster(fresh.notification_root)
        sub = broadcaster.register(Subscriber("sim", lambda n: b"\x00", _NullSink()))
        broadcaster.subscribe(sub, "block-added")
        broadcaster.subscribe(sub, "utxos-changed")
    pipe = ConsensusPipeline(fresh, workers=workers, speculative=speculative)
    t0 = time.perf_counter()
    try:
        futures = [pipe.submit(b) for b in result.blocks]
        for f in futures:
            status = f.result(timeout=600)
            assert status in ("utxo_valid", "utxo_pending"), f"replay rejected block: {status}"
        elapsed = time.perf_counter() - t0
    finally:
        pipe.shutdown()
        if broadcaster is not None:
            # drains the ingest queue + subscriber deques before returning,
            # so late serving spans are recorded before any flight.dump
            broadcaster.close()
    assert fresh.sink() == result.sink, "replay reached a different sink"
    assert fresh.get_virtual_daa_score() == result.virtual_daa_score
    return elapsed, fresh
