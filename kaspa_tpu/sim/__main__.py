"""simpa-equivalent CLI (reference: simpa/src/main.rs).

Builds a virtual-time multi-miner DAG with signed transactions, then
replays it into a fresh consensus and reports validation throughput:

    python -m kaspa_tpu.sim --bps 2 --blocks 100 --miners 4 --tpb 4

Mesh replay (sharded batch verify + muhash over N devices; CPU recipe):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python -m kaspa_tpu.sim --blocks 32 --mesh 8 --json
"""

import argparse
import json

from kaspa_tpu.utils import jax_setup

jax_setup.setup()

from kaspa_tpu.ops import mesh
from kaspa_tpu.sim.simulator import SimConfig, replay, simulate


def main() -> None:
    p = argparse.ArgumentParser(prog="kaspa-tpu-sim", description="DAG simulation + validation replay benchmark")
    p.add_argument("--bps", type=int, default=2, help="target blocks per second")
    p.add_argument("--delay", type=float, default=2.0, help="simulated propagation delay (seconds)")
    p.add_argument("--miners", type=int, default=4, help="number of miners")
    p.add_argument("--blocks", type=int, default=64, help="blocks to produce")
    p.add_argument("--tpb", type=int, default=8, help="transactions per block")
    p.add_argument("--seed", type=int, default=42)
    p.add_argument(
        "--mesh", default=None, metavar="N",
        help="shard the replay's batch verify + muhash over N devices ('auto' = all visible)",
    )
    p.add_argument("--json", action="store_true", help="emit one JSON line")
    args = p.parse_args()

    mesh_size = mesh.configure(args.mesh)
    cfg = SimConfig(
        bps=args.bps, delay=args.delay, num_miners=args.miners,
        num_blocks=args.blocks, txs_per_block=args.tpb, seed=args.seed,
    )
    res = simulate(cfg)
    elapsed, fresh = replay(res)
    sink = fresh.sink()
    out = {
        "blocks": len(res.blocks),
        "txs": res.total_txs,
        "build_seconds": round(res.build_seconds, 2),
        "replay_seconds": round(elapsed, 2),
        "replay_blocks_per_sec": round(len(res.blocks) / elapsed, 2),
        "bps_target": args.bps,
        "realtime_factor": round(len(res.blocks) / args.bps / elapsed, 2),
        "mesh": mesh_size,
        # end-state fingerprints: identical across --mesh values is the
        # bit-identity acceptance check for the sharded dispatch
        "sink": sink.hex(),
        "utxo_commitment": fresh.multisets[sink].finalize().hex(),
    }
    if args.json:
        print(json.dumps(out))
    else:
        print(f"built {out['blocks']} blocks / {out['txs']} txs in {out['build_seconds']}s")
        print(
            f"replayed in {out['replay_seconds']}s = {out['replay_blocks_per_sec']} blocks/s "
            f"({out['realtime_factor']}x the {args.bps}-BPS real-time rate, mesh {mesh_size})"
        )


if __name__ == "__main__":
    main()
