"""simpa-equivalent CLI (reference: simpa/src/main.rs).

Builds a virtual-time multi-miner DAG with signed transactions, then
replays it into a fresh consensus and reports validation throughput:

    python -m kaspa_tpu.sim --bps 2 --blocks 100 --miners 4 --tpb 4

Mesh replay (sharded batch verify + muhash over N devices; CPU recipe):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python -m kaspa_tpu.sim --blocks 32 --mesh 8 --json
"""

import argparse
import json

from kaspa_tpu.utils import jax_setup

jax_setup.setup()

from kaspa_tpu.observability import flight, trace
from kaspa_tpu.ops import dispatch as coalesce
from kaspa_tpu.ops import mesh
from kaspa_tpu.sim.simulator import SimConfig, replay, replay_pipelined, simulate


def main() -> None:
    p = argparse.ArgumentParser(prog="kaspa-tpu-sim", description="DAG simulation + validation replay benchmark")
    p.add_argument("--bps", type=int, default=2, help="target blocks per second")
    p.add_argument("--delay", type=float, default=2.0, help="simulated propagation delay (seconds)")
    p.add_argument("--miners", type=int, default=4, help="number of miners")
    p.add_argument("--blocks", type=int, default=64, help="blocks to produce")
    p.add_argument("--tpb", type=int, default=8, help="transactions per block")
    p.add_argument("--seed", type=int, default=42)
    p.add_argument(
        "--mesh", default=None, metavar="N",
        help="shard the replay's batch verify + muhash over N devices ('auto' = all visible)",
    )
    p.add_argument(
        "--coalesce", default=None, metavar="N",
        help="route the replay's verify batches through the cross-block coalescing "
        "queue with super-batch target N ('auto' = best batch from BENCH_SWEEP.json; "
        "default off — results are bit-identical either way)",
    )
    p.add_argument(
        "--verify-mode", default=None, choices=("ladder", "aggregate", "auto"),
        help="schnorr verify lane: per-sig ladder (default), one RLC aggregate "
        "multi-scalar pass per batch, or auto (aggregate above the measured "
        "crossover batch size); results are bit-identical either way",
    )
    p.add_argument(
        "--fabric", default=None, metavar="ADDR[,ADDR...]",
        help="route the replay's verify batches to remote verifyd slices "
        "(`python -m kaspa_tpu.fabric.service`) through the cross-host "
        "balancer; results stay bit-identical (host degraded lane on slice "
        "loss) and the JSON report gains a 'fabric' stats block",
    )
    p.add_argument("--json", action="store_true", help="emit one JSON line")
    p.add_argument(
        "--pipeline", action="store_true",
        help="replay through the concurrent ConsensusPipeline (stage workers + "
        "virtual worker) instead of the serial loop",
    )
    p.add_argument(
        "--no-spec", action="store_true",
        help="disable the speculative chain-state precompute in --pipeline replays "
        "(bit-identity baseline; results must match speculation-on exactly)",
    )
    p.add_argument(
        "--trace", default=None, metavar="PATH",
        help="enable the per-block flight recorder during the replay and dump "
        "the completed-trace ring to PATH (tools/trace_report.py input)",
    )
    p.add_argument(
        "--notrace", action="store_true",
        help="disable span tracing entirely for the replay (overhead baseline)",
    )
    p.add_argument(
        "--hostile", action="store_true",
        help="hostile-load sustain run: multisig/P2SH fast-path-bypass script mix, "
        "attacker-fork deep reorg, out-of-order delivery; writes SUSTAIN.json",
    )
    p.add_argument(
        "--txflood", action="store_true",
        help="tx-flood sustain run: flood the batched ingest tier with clean spends, "
        "double-spend chains, RBF churn and orphan storms between paced block "
        "deliveries under the chaos schedule; adds the 'ingest' block to SUSTAIN.json "
        "(combine with --hostile for the fast-path-bypass script mix)",
    )
    p.add_argument(
        "--txflood-rates", default=None, metavar="JSON",
        help="override TxFloodConfig fields for --txflood, "
        "e.g. '{\"clean_per_block\": 12, \"rbf_chain\": 5}'",
    )
    p.add_argument(
        "--overload", action="store_true",
        help="with --txflood: ramp the flood rate (warm -> linear ramp -> hold at "
        "peak -> cooldown) against a live overload controller wired to the run's "
        "mining/ingest tier; gates on shed>0, SATURATED reached, cadence within "
        "1.5x of nominal, and recovery to NOMINAL; adds the 'overload' block to "
        "the sustain report",
    )
    p.add_argument(
        "--overload-config", default=None, metavar="JSON",
        help="override OverloadRampConfig fields for --overload, e.g. "
        "'{\"peak_scale\": 6, \"thresholds\": {\"mempool\": [15, 40, 120]}, "
        "\"expire_daa\": 6}'",
    )
    p.add_argument(
        "--no-pace", action="store_true",
        help="with --txflood: deliver blocks as fast as possible instead of the "
        "true --bps wall-clock cadence",
    )
    p.add_argument(
        "--faults", default="default", metavar="SPEC",
        help="fault schedule for --hostile: 'default', 'none', inline JSON, or @/path/to/schedule.json",
    )
    p.add_argument(
        "--sustain-out", default="SUSTAIN.json", metavar="PATH",
        help="where --hostile writes its report (default SUSTAIN.json)",
    )
    p.add_argument(
        "--wedge-drill", action="store_true",
        help="with --hostile: run the device-supervision wedge drill instead of the "
        "stock sustain schedule — inject dispatch hangs + a compile stall mid-replay "
        "and gate on bit-identity, requeue accounting, and canary recovery",
    )
    p.add_argument(
        "--swarm", type=int, default=None, metavar="N",
        help="swarm drill: N in-process nodes over the real P2P wire driven by a "
        "seeded scenario (partition/heal, deep attacker reorg, late-join IBD, "
        "relay-storm budget); writes SWARM.json and exits non-zero unless all "
        "nodes converge bit-identically to the fault-free replay (--blocks sets "
        "the base-chain length, --seed the schedule seed)",
    )
    p.add_argument(
        "--swarm-scenario", default=None, metavar="JSON|@PATH",
        help="override the stock swarm schedule: inline JSON or @/path/to/scenario.json "
        "(a list of {'op': mine|txs|partition|heal|converge|join, ...} steps)",
    )
    p.add_argument(
        "--swarm-out", default="SWARM.json", metavar="PATH",
        help="where --swarm writes its report (default SWARM.json)",
    )
    args = p.parse_args()

    if args.swarm is not None:
        _run_swarm(args)
        return

    mesh_size = mesh.configure(args.mesh)
    if args.overload and args.coalesce is None:
        # the dispatch_yield brownout action needs a live coalescing engine
        # to act on — overload runs default it on rather than silently
        # exercising a no-op action
        args.coalesce = "auto"
    coalesce_target = coalesce.configure(args.coalesce)
    if args.verify_mode is not None:
        coalesce.set_verify_mode(args.verify_mode)
    fabric_bal = None
    if args.fabric:
        from kaspa_tpu.fabric import balancer as fabric_balancer

        fabric_bal = fabric_balancer.configure(args.fabric)
    cfg = SimConfig(
        bps=args.bps, delay=args.delay, num_miners=args.miners,
        num_blocks=args.blocks, txs_per_block=args.tpb, seed=args.seed,
        hostile=args.hostile,
    )
    if args.txflood:
        _run_txflood(cfg, args)
        return
    if args.hostile:
        if args.wedge_drill:
            _run_wedge(cfg, args)
        else:
            _run_hostile(cfg, args)
        return
    res = simulate(cfg)
    if args.notrace:
        trace.disable()
    if args.trace:
        flight.enable(ring=max(2 * args.blocks, 64))
        flight.reset()
    if args.pipeline:
        # traced replays attach the serving fanout so block traces cover
        # the full production thread topology (stage/virtual/dispatch/serving)
        elapsed, fresh = replay_pipelined(
            res, fanout=bool(args.trace), speculative=False if args.no_spec else None
        )
    else:
        elapsed, fresh = replay(res)
    sink = fresh.sink()
    out = {
        "blocks": len(res.blocks),
        "txs": res.total_txs,
        "build_seconds": round(res.build_seconds, 2),
        "replay_seconds": round(elapsed, 2),
        "replay_blocks_per_sec": round(len(res.blocks) / elapsed, 2),
        "bps_target": args.bps,
        "realtime_factor": round(len(res.blocks) / args.bps / elapsed, 2),
        "mesh": mesh_size,
        "coalesce": coalesce_target,
        "verify_mode": coalesce.verify_mode(),
        # end-state fingerprints: identical across --mesh/--coalesce/
        # --verify-mode values is the bit-identity acceptance check for the
        # sharded/aggregated dispatch
        "sink": sink.hex(),
        "utxo_commitment": fresh.multisets[sink].finalize().hex(),
        "pipeline": bool(args.pipeline),
        "tracing": not args.notrace,
    }
    if fabric_bal is not None:
        from kaspa_tpu.fabric import balancer as fabric_balancer

        fabric_bal.drain(timeout=30.0)
        out["fabric"] = fabric_bal.stats()
        fabric_balancer.shutdown(timeout=10.0)
    if args.pipeline:
        from kaspa_tpu.pipeline.speculative import SpeculativeVerifier

        out["speculative"] = SpeculativeVerifier.snapshot()
        out["speculative"]["enabled"] = not args.no_spec
    if args.trace:
        path = flight.dump(args.trace, reason="sim-replay")
        out["trace_path"] = path
        out["traces"] = len(flight.traces())
        flight.disable()
    if args.json:
        print(json.dumps(out))
    else:
        print(f"built {out['blocks']} blocks / {out['txs']} txs in {out['build_seconds']}s")
        print(
            f"replayed in {out['replay_seconds']}s = {out['replay_blocks_per_sec']} blocks/s "
            f"({out['realtime_factor']}x the {args.bps}-BPS real-time rate, mesh {mesh_size})"
        )


def _parse_schedule(spec: str):
    from kaspa_tpu.resilience.sustain import default_schedule

    if spec == "default":
        return default_schedule()
    if spec == "none":
        return {}
    if spec.startswith("@"):
        with open(spec[1:]) as f:
            return json.load(f)
    return json.loads(spec)


def _run_hostile(cfg, args) -> None:
    from kaspa_tpu.resilience.sustain import run_sustain

    report = run_sustain(cfg, schedule=_parse_schedule(args.faults), seed=args.seed, out=args.sustain_out)
    det, brk = report["deterministic"], report["breaker"]
    summary = {
        "blocks": det["blocks"],
        "matches_fault_free": det["matches_fault_free"],
        "fault_events": len(det["events"]),
        "breaker_trips": brk["trips"],
        "breaker_recoveries": brk["recoveries"],
        "degraded_dispatches": report["metrics"]["secp_degraded_dispatches"],
        "replay_seconds": report["metrics"]["replay_seconds"],
        "sink": det["fingerprints"]["sink"],
        "utxo_commitment": det["fingerprints"]["utxo_commitment"],
        "sustain_out": args.sustain_out,
    }
    if args.json:
        print(json.dumps(summary))
    else:
        print(
            f"sustain: {det['blocks']} blocks, {len(det['events'])} faults injected, "
            f"breaker trips={brk['trips']} recoveries={brk['recoveries']}, "
            f"matches_fault_free={det['matches_fault_free']} -> {args.sustain_out}"
        )
    if not det["matches_fault_free"]:
        raise SystemExit(2)


def _run_txflood(cfg, args) -> None:
    from kaspa_tpu.resilience.txflood import (
        OverloadRampConfig,
        TxFloodConfig,
        run_txflood_sustain,
    )

    flood = TxFloodConfig()
    if args.txflood_rates:
        for k, v in json.loads(args.txflood_rates).items():
            if not hasattr(flood, k):
                raise SystemExit(f"unknown txflood rate field: {k}")
            setattr(flood, k, v)
    ramp = None
    if args.overload:
        ramp = OverloadRampConfig()
        if args.overload_config:
            for k, v in json.loads(args.overload_config).items():
                if not hasattr(ramp, k):
                    raise SystemExit(f"unknown overload config field: {k}")
                setattr(ramp, k, v)
    report = run_txflood_sustain(
        cfg,
        flood_cfg=flood,
        schedule=_parse_schedule(args.faults),
        seed=args.seed,
        out=args.sustain_out,
        pace=not args.no_pace,
        overload=ramp,
    )
    det, ing = report["deterministic"], report["ingest"]
    summary = {
        "blocks": det["blocks"],
        "matches_fault_free": det["matches_fault_free"],
        "fault_events": len(det["events"]),
        "txs_submitted": ing["flood"]["submitted"],
        "tx_acceptance_rate": ing["tx_acceptance_rate"],
        "template_rebuilds": ing["template_rebuilds"],
        "template_rebuild_p50_ms": ing["template_rebuild_p50_ms"],
        "template_rebuild_p99_ms": ing["template_rebuild_p99_ms"],
        "peak_mempool_occupancy": ing["peak_mempool_occupancy"],
        "lost_tickets": ing["lost_tickets"],
        "waves": ing["waves"],
        "actual_bps": ing["actual_bps"],
        "sink": det["fingerprints"]["sink"],
        "sustain_out": args.sustain_out,
    }
    ov_ok = True
    if ramp is not None:
        ov = report["overload"]
        ratio = ov["cadence"]["saturated_over_nominal"]
        ov_ok = (
            ov["levels"]["max"] in ("SATURATED", "CRITICAL")
            and sum(ov["shed"].values()) > 0
            and ov["recovered"]
            and ratio is not None
            and ratio <= 1.5
        )
        summary.update(
            {
                "overload_max_level": ov["levels"]["max"],
                "overload_recovered": ov["recovered"],
                "overload_shed": sum(ov["shed"].values()),
                "overload_rejected": ov["overload_rejected"],
                "cadence_saturated_over_nominal": ratio,
                "overload_ok": ov_ok,
            }
        )
    if args.json:
        print(json.dumps(summary))
    else:
        print(
            f"txflood: {det['blocks']} blocks at {ing['actual_bps']} BPS "
            f"(target {ing['bps_target']}), {ing['flood']['submitted']} txs flooded, "
            f"clean acceptance {ing['tx_acceptance_rate']}, "
            f"rebuilds={ing['template_rebuilds']} p50={ing['template_rebuild_p50_ms']}ms "
            f"p99={ing['template_rebuild_p99_ms']}ms, "
            f"peak pool={ing['peak_mempool_occupancy']}, lost={ing['lost_tickets']}, "
            f"matches_fault_free={det['matches_fault_free']} -> {args.sustain_out}"
        )
        if ramp is not None:
            ov = report["overload"]
            print(
                f"overload: max={ov['levels']['max']} final={ov['levels']['final']} "
                f"shed={ov['shed']} "
                f"cadence sat/nom={ov['cadence']['saturated_over_nominal']} "
                f"recovered={ov['recovered']} ok={ov_ok}"
            )
    if not det["matches_fault_free"] or ing["lost_tickets"] != 0 or not ov_ok:
        raise SystemExit(2)


def _run_swarm(args) -> None:
    from kaspa_tpu.resilience.swarm import gates, run_swarm

    report = run_swarm(
        args.swarm,
        seed=args.seed,
        scenario=args.swarm_scenario,
        blocks=args.blocks,
        bps=args.bps,
        out=args.swarm_out,
    )
    det, fleet = report["deterministic"], report["fleet"]
    g = gates(report)
    summary = {
        "nodes": args.swarm,
        "blocks": det["blocks"],
        "converged": g["converged"],
        "matches_fault_free": g["matches_fault_free"],
        "lost_tickets": fleet["lost_tickets"],
        "amplification": fleet["relay"]["amplification"],
        "amp_ok": g["amp_ok"],
        "wall_seconds": report["metrics"]["wall_seconds"],
        "sink": det["fingerprints"]["node0"]["sink"],
        "utxo_commitment": det["fingerprints"]["node0"]["utxo_commitment"],
        "swarm_out": args.swarm_out,
    }
    if args.json:
        print(json.dumps(summary))
    else:
        print(
            f"swarm: {args.swarm} nodes, {det['blocks']} blocks mined, "
            f"converged={g['converged']} matches_fault_free={g['matches_fault_free']} "
            f"lost={fleet['lost_tickets']} amplification={fleet['relay']['amplification']} "
            f"in {summary['wall_seconds']}s -> {args.swarm_out}"
        )
    if not all(g.values()):
        raise SystemExit(2)


def _run_wedge(cfg, args) -> None:
    from kaspa_tpu.resilience.sustain import run_wedge_drill

    report = run_wedge_drill(cfg, seed=args.seed, out=args.sustain_out)
    det, sup, brk = report["deterministic"], report["supervisor"], report["breaker"]
    summary = {
        "blocks": det["blocks"],
        "matches_fault_free": det["matches_fault_free"],
        "injected_hangs": sup["injected_hangs"],
        "requeued_total": sup["requeued_total"],
        "requeue_matches_injected": sup["requeue_matches_injected"],
        "late_results_discarded": sup["late_results"],
        "compile_stall_ok": report["compile_stall"]["all_valid"] and report["compile_stall"]["shape_left_cold"],
        "tickets_ok": report["tickets"]["ok"],
        "breaker_trips": brk["trips"],
        "breaker_recoveries": brk["recoveries"],
        "recovered": sup["recovered"],
        "replay_seconds": report["metrics"]["replay_seconds"],
        "sink": det["fingerprints"]["sink"],
        "utxo_commitment": det["fingerprints"]["utxo_commitment"],
        "sustain_out": args.sustain_out,
    }
    if args.json:
        print(json.dumps(summary))
    else:
        print(
            f"wedge drill: {det['blocks']} blocks, {sup['injected_hangs']} hangs injected, "
            f"requeued={sup['requeued_total']} (match={sup['requeue_matches_injected']}), "
            f"trips={brk['trips']} recovered={sup['recovered']}, "
            f"matches_fault_free={det['matches_fault_free']} -> {args.sustain_out}"
        )
    ok = (
        det["matches_fault_free"]
        and sup["requeue_matches_injected"]
        and sup["injected_hangs"] > 0
        and summary["compile_stall_ok"]
        and summary["tickets_ok"]
        and sup["recovered"]
    )
    if not ok:
        raise SystemExit(2)


if __name__ == "__main__":
    main()
