"""Transaction script layer.

The reference implements a full stack VM (crypto/txscript, TxScriptEngine,
lib.rs:156) executed per input under rayon.  The TPU-native design splits
script checking into:

- classification of standard script classes (script_class.rs equivalents) —
  P2PK-Schnorr / P2PK-ECDSA / P2SH — whose signature checks are *collected*
  into device batches (ops/secp256k1) spanning whole blocks / mergesets;
- a host VM for general scripts (module vm.py) for everything nonstandard.

This mirrors SURVEY.md §7 step 5: the fast path must be consensus-equivalent
to the full engine for the script forms it accepts, and falls back to the
VM otherwise.
"""

from kaspa_tpu.txscript.standard import (  # noqa: F401
    ScriptClass,
    classify_script,
    pay_to_pub_key,
    pay_to_pub_key_ecdsa,
    pay_to_script_hash_script,
)
from kaspa_tpu.txscript.batch import BatchScriptChecker, ScriptCheckError  # noqa: F401
