"""TxScript VM: the general-script execution engine (host side).

Faithful re-implementation of the reference's TxScriptEngine
(crypto/txscript/src/lib.rs:156-, opcodes/mod.rs) for the pre-Toccata
opcode set: data pushes, flow control, stack/splice ops, comparison and
arithmetic (8-byte minimally-encoded numbers), crypto opcodes
(Blake2b/SHA256/CheckSig/CheckMultiSig families) and lock-time/sequence
verification, plus P2SH evaluation.  The post-Toccata surface — covenant
introspection (0xb2-0xd8), ZK precompiles (OpZkPrecompile 0xa6), blake3
ops, CheckSigFromStack, splice/bitwise/arithmetic re-enables, runtime
script-unit metering and the relaxed limits — is implemented behind
EngineFlags(covenants_enabled), gated exactly like the reference; the
KIP-10 introspection subset (0xb3/b4/b9/be/bf/c2/c3) is ungated.

This is the fall-back path behind the TPU batch fast-path
(txscript/batch.py): nonstandard scripts route here; standard P2PK spends
never do.  Signature checks inside the VM go through the shared sig cache
and the same device batch API (single-item batches) so acceptance
decisions are identical either way.

Limits (lib.rs:76-87): stack 244 combined, element 520 bytes, script
10_000 bytes, 201 non-push ops, 20 multisig keys.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass as _dataclass

from kaspa_tpu.consensus import hashing as chash
from kaspa_tpu.observability.core import DEFAULT_LATENCY_BUCKETS, REGISTRY
from kaspa_tpu.txscript.caches import SigCache

# host-VM pressure: how often validation leaves the device fast path and
# how long each general-script execution costs
_VM_EXECUTIONS = REGISTRY.counter("txscript_vm_executions", help="full input executions on the host VM")
_VM_ERRORS = REGISTRY.counter("txscript_vm_errors", help="host VM executions rejecting the input")
_VM_EXEC_TIME = REGISTRY.histogram(
    "txscript_vm_execute_seconds", DEFAULT_LATENCY_BUCKETS, help="wall time per host-VM input execution"
)

MAX_STACK_SIZE = 244
MAX_SCRIPTS_SIZE = 10_000
MAX_SCRIPT_ELEMENT_SIZE = 520
MAX_OPS_PER_SCRIPT = 201
MAX_PUB_KEYS_PER_MULTISIG = 20
NO_COST_OPCODE = 0x60  # opcodes <= Op16 don't count toward the ops limit
LOCK_TIME_THRESHOLD = 500_000_000_000
MAX_TX_IN_SEQUENCE_NUM = (1 << 64) - 1
SEQUENCE_LOCK_TIME_DISABLED = 1 << 63
SEQUENCE_LOCK_TIME_MASK = 0x00000000FFFFFFFF

OP_0 = 0x00
OP_PUSHDATA1, OP_PUSHDATA2, OP_PUSHDATA4 = 0x4C, 0x4D, 0x4E
OP_1NEGATE = 0x4F
OP_RESERVED = 0x50
OP_1, OP_16 = 0x51, 0x60

_DISABLED = {0x80, 0x81, 0x8D, 0x8E, 0x98, 0x99}  # Left,Right,2Mul,2Div,LShift,RShift
# covenant-gated (Toccata) ops are disabled pre-fork exactly like the
# reference (opcodes/mod.rs bodies error OpcodeDisabled when the flag is off):
# Invert,And,Or,Xor, Cat,Substr, Mul,Div,Mod
_PRE_TOCCATA_DISABLED = {0x83, 0x84, 0x85, 0x86, 0x7E, 0x7F, 0x95, 0x96, 0x97}
_ALWAYS_ILLEGAL = {0x65, 0x66}  # VerIf, VerNotIf
_RESERVED = {0x50, 0x62, 0x89, 0x8A}  # Reserved, Ver, Reserved1, Reserved2

I64_MIN, I64_MAX = -(1 << 63), (1 << 63) - 1


class TxScriptError(Exception):
    pass


# ---------------------------------------------------------------------------
# number / bool codec (data_stack.rs)
# ---------------------------------------------------------------------------

def check_minimal_data_encoding(v: bytes) -> None:
    if not v:
        return
    if v[-1] & 0x7F == 0:
        if len(v) == 1 or v[-2] & 0x80 == 0:
            raise TxScriptError(f"numeric value {v.hex()} is not minimally encoded")


def deserialize_i64(v: bytes, enforce_minimal: bool, max_len: int = 8) -> int:
    if len(v) > max_len:
        raise TxScriptError(f"numeric value {v.hex()} exceeds max length {max_len}")
    if len(v) == 0:
        return 0
    if enforce_minimal:
        check_minimal_data_encoding(v)
    msb = v[-1]
    sign = 1 - 2 * (msb >> 7)
    acc = msb & 0x7F
    for byte in reversed(v[:-1]):
        acc = (acc << 8) + byte
    return acc * sign


def serialize_i64(value: int) -> bytes:
    """Sign-magnitude little-endian (data_stack.rs serialize_i64)."""
    if value == 0:
        return b""
    negative = value < 0
    positive = abs(value)
    out = bytearray()
    while positive:
        out.append(positive & 0xFF)
        positive >>= 8
    if out[-1] & 0x80:
        out.append(0x80 if negative else 0x00)
    elif negative:
        out[-1] |= 0x80
    return bytes(out)


def as_bool(v: bytes) -> bool:
    """Nonzero excluding negative zero (data_stack.rs bool deserialize)."""
    if not v:
        return False
    return (v[-1] & 0x7F) != 0 or any(b != 0 for b in v[:-1])


# ---------------------------------------------------------------------------
# script parsing (opcode stream)
# ---------------------------------------------------------------------------

def parse_script(script: bytes):
    """Yields (opcode, data, opcode_len) — errors on truncated pushes."""
    i = 0
    n = len(script)
    while i < n:
        op = script[i]
        if 1 <= op <= 75:
            end = i + 1 + op
            if end > n:
                raise TxScriptError(f"truncated push of {op} bytes")
            yield op, script[i + 1 : end]
            i = end
        elif op == OP_PUSHDATA1:
            if i + 2 > n:
                raise TxScriptError("truncated pushdata1 length")
            ln = script[i + 1]
            end = i + 2 + ln
            if end > n:
                raise TxScriptError("truncated pushdata1")
            yield op, script[i + 2 : end]
            i = end
        elif op == OP_PUSHDATA2:
            if i + 3 > n:
                raise TxScriptError("truncated pushdata2 length")
            ln = int.from_bytes(script[i + 1 : i + 3], "little")
            end = i + 3 + ln
            if end > n:
                raise TxScriptError("truncated pushdata2")
            yield op, script[i + 3 : end]
            i = end
        elif op == OP_PUSHDATA4:
            if i + 5 > n:
                raise TxScriptError("truncated pushdata4 length")
            ln = int.from_bytes(script[i + 1 : i + 5], "little")
            end = i + 5 + ln
            if end > n:
                raise TxScriptError("truncated pushdata4")
            yield op, script[i + 5 : end]
            i = end
        else:
            yield op, None
            i += 1


def is_push_opcode(op: int) -> bool:
    """Opcodes through Op16 (incl. reserved 0x50) count as pushes (lib.rs:616)."""
    return op <= NO_COST_OPCODE


def check_minimal_data_push(op: int, data: bytes) -> None:
    """opcodes/macros.rs check_minimal_data_push (bitcoin minimal-push rules)."""
    ln = len(data)
    if ln == 0:
        if op != OP_0:
            raise TxScriptError("empty data push must use OP_0")
    elif ln == 1 and 1 <= data[0] <= 16:
        if op != OP_1 + data[0] - 1:
            raise TxScriptError(f"push of {data[0]} must use OP_{data[0]}")
    elif ln == 1 and data[0] == 0x81:
        if op != OP_1NEGATE:
            raise TxScriptError("push of -1 must use OP_1NEGATE")
    elif ln <= 75:
        if op != ln:
            raise TxScriptError(f"push of {ln} bytes must use direct push")
    elif ln <= 255:
        if op != OP_PUSHDATA1:
            raise TxScriptError("push must use OP_PUSHDATA1")
    elif ln <= 65535:
        if op != OP_PUSHDATA2:
            raise TxScriptError("push must use OP_PUSHDATA2")


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------

_COND_TRUE, _COND_FALSE, _COND_SKIP = 1, 0, -1


@_dataclass
class EngineFlags:
    """Fork-dependent engine behavior (lib.rs EngineFlags).  The Toccata
    master switch enables covenants, introspection breadth, ZK precompiles,
    splice/bitwise/arithmetic re-enables and the post-Toccata limits."""

    covenants_enabled: bool = False


# post-Toccata limits (lib.rs:78-82)
MAX_SCRIPTS_SIZE_POST_TOCCATA = 1_000_000
MAX_SCRIPT_ELEMENT_SIZE_POST_TOCCATA = 1_000_000
MAX_OPS_PER_SCRIPT_POST_TOCCATA = 1_000_000


class TxScriptEngine:
    """Executes (signature_script, script_public_key[, p2sh]) for one input."""

    def __init__(
        self,
        tx=None,
        utxo_entries=None,
        input_index: int = 0,
        reused=None,
        sig_cache: SigCache | None = None,
        flags: EngineFlags | None = None,
        covenants_ctx=None,
        meter=None,
        seq_commit_accessor=None,
    ):
        self.tx = tx
        self.utxo_entries = utxo_entries
        self.input_index = input_index
        self.reused = reused if reused is not None else chash.SigHashReusedValues()
        self.sig_cache = sig_cache if sig_cache is not None else SigCache()
        self.flags = flags if flags is not None else EngineFlags()
        self.covenants_ctx = covenants_ctx  # built lazily when needed
        self.meter = meter  # RuntimeResourceMeter; None = uncharged regime
        self.seq_commit_accessor = seq_commit_accessor  # KIP-21 lanes
        self.dstack: list[bytes] = []
        self.astack: list[bytes] = []
        self.cond_stack: list[int] = []
        self.num_ops = 0
        self._pushed_bytes = 0  # per-opcode data-stack push accounting

    # --- flag-dependent limits (lib.rs:136-147) ---

    @property
    def max_scripts_size(self) -> int:
        return MAX_SCRIPTS_SIZE_POST_TOCCATA if self.flags.covenants_enabled else MAX_SCRIPTS_SIZE

    @property
    def max_element_size(self) -> int:
        return MAX_SCRIPT_ELEMENT_SIZE_POST_TOCCATA if self.flags.covenants_enabled else MAX_SCRIPT_ELEMENT_SIZE

    @property
    def max_ops(self) -> int:
        return MAX_OPS_PER_SCRIPT_POST_TOCCATA if self.flags.covenants_enabled else MAX_OPS_PER_SCRIPT

    def consume_script_units(self, units: int) -> None:
        if self.meter is not None:
            from kaspa_tpu.txscript.resource_meter import MeterError

            try:
                self.meter.consume_script_units(units)
            except MeterError as e:
                raise TxScriptError(str(e)) from e

    def consume_sig_op_cost(self, count: int = 1) -> None:
        if self.meter is not None:
            from kaspa_tpu.txscript.resource_meter import MeterError

            try:
                self.meter.consume_sig_ops(count)
            except MeterError as e:
                raise TxScriptError(str(e)) from e

    # --- stack helpers ---

    def _push(self, item: bytes):
        self._pushed_bytes += len(item)
        self.dstack.append(item)

    def _pop(self) -> bytes:
        if not self.dstack:
            raise TxScriptError("attempt to pop from empty stack")
        return self.dstack.pop()

    def _pop_num(self, max_len: int = 8) -> int:
        return deserialize_i64(self._pop(), enforce_minimal=True, max_len=max_len)

    def _pop_i32(self) -> int:
        v = deserialize_i64(self._pop(), enforce_minimal=True, max_len=4)
        return v

    def _pop_bool(self) -> bool:
        return as_bool(self._pop())

    def _push_num(self, v: int):
        if not (I64_MIN <= v <= I64_MAX):
            raise TxScriptError("number exceeds 64-bit signed integer range")
        self._push(serialize_i64(v))

    def _push_bool(self, b: bool):
        self._push(b"\x01" if b else b"")

    def _peek(self, depth: int = 0) -> bytes:
        if len(self.dstack) <= depth:
            raise TxScriptError("invalid stack operation")
        return self.dstack[-1 - depth]

    def is_executing(self) -> bool:
        return all(c == _COND_TRUE for c in self.cond_stack)

    # --- public entry points ---

    def execute(self) -> None:
        """Full input execution: sig script, spk, optional p2sh redeem."""
        from time import perf_counter_ns

        _VM_EXECUTIONS.inc()
        t0 = perf_counter_ns()
        try:
            self._execute_inner()
        except Exception:
            _VM_ERRORS.inc()
            raise
        finally:
            _VM_EXEC_TIME.observe((perf_counter_ns() - t0) * 1e-9)

    def _execute_inner(self) -> None:
        from kaspa_tpu.txscript import standard

        entry = self.utxo_entries[self.input_index]
        spk = entry.script_public_key
        if spk.version > standard.MAX_SCRIPT_PUBLIC_KEY_VERSION:
            return  # unknown versions are accepted without execution
        sig_script = self.tx.inputs[self.input_index].signature_script
        is_p2sh = standard.is_pay_to_script_hash(spk.script)
        scripts = [sig_script, spk.script]
        if not any(scripts):
            raise TxScriptError("false stack entry at end of script execution")
        for s in scripts:
            if len(s) > self.max_scripts_size:
                raise TxScriptError(f"script size {len(s)} above limit")

        saved_stack = None
        for idx, s in enumerate(scripts):
            if not s:
                continue
            if is_p2sh and idx == 1:
                saved_stack = list(self.dstack)
            self.execute_script(s, verify_only_push=(idx == 0))
        if is_p2sh:
            self._check_error_condition(final_script=False)
            if saved_stack is None:
                raise TxScriptError("empty stack for p2sh redeem")
            self.dstack = saved_stack
            redeem = self._pop()
            self.execute_script(redeem, verify_only_push=False)
        self._check_error_condition(final_script=True)

    def execute_standalone(self, script: bytes) -> None:
        """StandAloneScripts source (tests / script-builder checks)."""
        if len(script) > self.max_scripts_size:
            raise TxScriptError("script too large")
        if not script:
            raise TxScriptError("no scripts to execute")
        self.execute_script(script, verify_only_push=False)
        self._check_error_condition(final_script=True)

    def _check_error_condition(self, final_script: bool) -> None:
        if final_script:
            if len(self.dstack) > 1:
                raise TxScriptError(f"stack contains {len(self.dstack) - 1} unexpected items")
            if len(self.dstack) < 1:
                raise TxScriptError("stack empty at end of script execution")
        if not self._pop_bool():
            raise TxScriptError("false stack entry at end of script execution")

    # --- script execution ---

    def execute_script(self, script: bytes, verify_only_push: bool) -> None:
        for op, data in parse_script(script):
            if op in _DISABLED or (op in _PRE_TOCCATA_DISABLED and not self.flags.covenants_enabled):
                raise TxScriptError(f"attempt to execute disabled opcode {op:#x}")
            if op in _ALWAYS_ILLEGAL:
                raise TxScriptError(f"attempt to execute reserved opcode {op:#x}")
            if verify_only_push and not is_push_opcode(op):
                raise TxScriptError("signature script is not push only")
            self._execute_opcode(op, data)
            if len(self.dstack) + len(self.astack) > MAX_STACK_SIZE:
                raise TxScriptError(f"combined stack size > {MAX_STACK_SIZE}")
        if self.cond_stack:
            raise TxScriptError("end of script reached in conditional execution")
        self.astack.clear()
        self.num_ops = 0

    def _execute_opcode(self, op: int, data: bytes | None) -> None:
        if not is_push_opcode(op):
            self.num_ops += 1
            if self.num_ops > self.max_ops:
                raise TxScriptError(f"exceeded max operation limit of {self.max_ops}")
        elif data is not None and len(data) > self.max_element_size:
            raise TxScriptError(f"element size {len(data)} above limit")

        executing = self.is_executing()
        if not executing and not (0x63 <= op <= 0x68):  # conditionals always run
            return

        if data is not None:  # push opcodes with payload
            if executing:
                # post-Toccata drops minimal-push enforcement (lib.rs:623)
                if not self.flags.covenants_enabled:
                    check_minimal_data_push(op, data)
                self._push(data)
                self._charge_pushed_bytes()
            return

        self._OPS[op](self)
        self._charge_pushed_bytes()

    def _charge_pushed_bytes(self) -> None:
        """Script-unit charge for bytes this opcode pushed (lib.rs:632);
        a no-op under the sig-op metering regime."""
        pushed, self._pushed_bytes = self._pushed_bytes, 0
        if pushed and self.meter is not None:
            from kaspa_tpu.txscript.resource_meter import MeterError

            try:
                self.meter.charge_newly_pushed_bytes(pushed)
            except MeterError as e:
                raise TxScriptError(str(e)) from e

    # --- opcode implementations ---

    def _op_false(self):
        self._push(b"")

    def _op_1negate(self):
        self._push_num(-1)

    def _op_reserved(self):
        raise TxScriptError(f"attempt to execute reserved opcode")

    def _op_n(self, n: int):
        self._push_num(n)

    def _op_nop(self):
        pass

    def _op_if(self):
        if self.is_executing():
            cond_buf = self._pop()
            if len(cond_buf) > 1:
                raise TxScriptError("expected boolean")
            cond = _COND_TRUE if cond_buf == b"\x01" else (_COND_FALSE if cond_buf == b"" else None)
            if cond is None:
                raise TxScriptError("expected boolean")
        else:
            cond = _COND_SKIP
        self.cond_stack.append(cond)

    def _op_notif(self):
        if self.is_executing():
            cond_buf = self._pop()
            if len(cond_buf) > 1:
                raise TxScriptError("expected boolean")
            cond = _COND_FALSE if cond_buf == b"\x01" else (_COND_TRUE if cond_buf == b"" else None)
            if cond is None:
                raise TxScriptError("expected boolean")
        else:
            cond = _COND_SKIP
        self.cond_stack.append(cond)

    def _op_else(self):
        if not self.cond_stack:
            raise TxScriptError("condition stack empty")
        top = self.cond_stack[-1]
        if top == _COND_TRUE:
            self.cond_stack[-1] = _COND_FALSE
        elif top == _COND_FALSE:
            self.cond_stack[-1] = _COND_TRUE
        # skip stays skip

    def _op_endif(self):
        if not self.cond_stack:
            raise TxScriptError("condition stack empty")
        self.cond_stack.pop()

    def _op_verify(self):
        if not self._pop_bool():
            raise TxScriptError("verify failed")

    def _op_return(self):
        raise TxScriptError("early return")

    def _op_toaltstack(self):
        self.astack.append(self._pop())

    def _op_fromaltstack(self):
        if not self.astack:
            raise TxScriptError("alt stack empty")
        self._push(self.astack.pop())

    def _op_2drop(self):
        self._pop(), self._pop()

    def _op_2dup(self):
        a, b = self._peek(1), self._peek(0)
        self._push(a), self._push(b)

    def _op_3dup(self):
        a, b, c = self._peek(2), self._peek(1), self._peek(0)
        self._push(a), self._push(b), self._push(c)

    def _op_2over(self):
        a, b = self._peek(3), self._peek(2)
        self._push(a), self._push(b)

    def _op_2rot(self):
        if len(self.dstack) < 6:
            raise TxScriptError("invalid stack operation")
        chunk = self.dstack[-6:-4]
        del self.dstack[-6:-4]
        self.dstack.extend(chunk)

    def _op_2swap(self):
        if len(self.dstack) < 4:
            raise TxScriptError("invalid stack operation")
        chunk = self.dstack[-4:-2]
        del self.dstack[-4:-2]
        self.dstack.extend(chunk)

    def _op_ifdup(self):
        top = self._peek()
        if as_bool(top):
            self._push(top)

    def _op_depth(self):
        self._push_num(len(self.dstack))

    def _op_drop(self):
        self._pop()

    def _op_dup(self):
        self._push(self._peek())

    def _op_nip(self):
        if len(self.dstack) < 2:
            raise TxScriptError("invalid stack operation")
        del self.dstack[-2]

    def _op_over(self):
        self._push(self._peek(1))

    def _op_pick(self):
        n = self._pop_i32()
        if n < 0 or n >= len(self.dstack):
            raise TxScriptError("pick at an invalid location")
        self._push(self.dstack[-1 - n])

    def _op_roll(self):
        n = self._pop_i32()
        if n < 0 or n >= len(self.dstack):
            raise TxScriptError("roll at an invalid location")
        item = self.dstack.pop(-1 - n)
        self._push(item)

    def _op_rot(self):
        if len(self.dstack) < 3:
            raise TxScriptError("invalid stack operation")
        item = self.dstack.pop(-3)
        self._push(item)

    def _op_swap(self):
        if len(self.dstack) < 2:
            raise TxScriptError("invalid stack operation")
        self.dstack[-1], self.dstack[-2] = self.dstack[-2], self.dstack[-1]

    def _op_tuck(self):
        if len(self.dstack) < 2:
            raise TxScriptError("invalid stack operation")
        self.dstack.insert(-2, self.dstack[-1])

    # OpCat (0x7E) / OpSubstr (0x7F) are covenant-gated: they arrive with the
    # Toccata milestone (reference pops (start, end) for Substr — note the
    # operand convention when implementing).

    def _op_size(self):
        self._push_num(len(self._peek()))

    def _op_equal(self):
        b = self._pop()
        a = self._pop()
        self._push_bool(a == b)

    def _op_equalverify(self):
        self._op_equal()
        if not self._pop_bool():
            raise TxScriptError("equal verify failed")

    def _op_1add(self):
        self._push_num(self._checked(self._pop_num() + 1))

    def _op_1sub(self):
        self._push_num(self._checked(self._pop_num() - 1))

    def _op_negate(self):
        self._push_num(self._checked(-self._pop_num()))

    def _op_abs(self):
        self._push_num(self._checked(abs(self._pop_num())))

    def _op_not(self):
        self._push_num(1 if self._pop_num() == 0 else 0)

    def _op_0notequal(self):
        self._push_num(0 if self._pop_num() == 0 else 1)

    def _op_add(self):
        b, a = self._pop_num(), self._pop_num()
        self._push_num(self._checked(a + b))

    def _op_sub(self):
        b, a = self._pop_num(), self._pop_num()
        self._push_num(self._checked(a - b))

    @staticmethod
    def _checked(v: int) -> int:
        if not (I64_MIN <= v <= I64_MAX):
            raise TxScriptError("result exceeds 64-bit signed integer range")
        return v

    def _op_booland(self):
        b, a = self._pop_num(), self._pop_num()
        self._push_num(1 if (a != 0 and b != 0) else 0)

    def _op_boolor(self):
        b, a = self._pop_num(), self._pop_num()
        self._push_num(1 if (a != 0 or b != 0) else 0)

    def _op_numequal(self):
        b, a = self._pop_num(), self._pop_num()
        self._push_num(1 if a == b else 0)

    def _op_numequalverify(self):
        self._op_numequal()
        if not self._pop_bool():
            raise TxScriptError("num equal verify failed")

    def _op_numnotequal(self):
        b, a = self._pop_num(), self._pop_num()
        self._push_num(1 if a != b else 0)

    def _op_lessthan(self):
        b, a = self._pop_num(), self._pop_num()
        self._push_num(1 if a < b else 0)

    def _op_greaterthan(self):
        b, a = self._pop_num(), self._pop_num()
        self._push_num(1 if a > b else 0)

    def _op_lessthanorequal(self):
        b, a = self._pop_num(), self._pop_num()
        self._push_num(1 if a <= b else 0)

    def _op_greaterthanorequal(self):
        b, a = self._pop_num(), self._pop_num()
        self._push_num(1 if a >= b else 0)

    def _op_min(self):
        b, a = self._pop_num(), self._pop_num()
        self._push_num(min(a, b))

    def _op_max(self):
        b, a = self._pop_num(), self._pop_num()
        self._push_num(max(a, b))

    def _op_within(self):
        mx, mn, x = self._pop_num(), self._pop_num(), self._pop_num()
        self._push_num(1 if mn <= x < mx else 0)

    def _op_sha256(self):
        data = self._pop()
        self.consume_script_units(len(data))  # HashOpcodePricing::Sha256
        self._push(hashlib.sha256(data).digest())

    def _op_blake2b(self):
        data = self._pop()
        self.consume_script_units(2 * len(data))  # HashOpcodePricing::Blake2b
        self._push(hashlib.blake2b(data, digest_size=32).digest())

    # --- signature checks (lib.rs:885-942 semantics via the batch backend) ---

    def _require_tx(self):
        if self.tx is None:
            raise TxScriptError("not a transaction input")

    def _verify_schnorr(self, key: bytes, sig: bytes, hash_type: int) -> bool:
        from kaspa_tpu.crypto import eclib

        self._require_tx()
        self.consume_sig_op_cost(1)  # lib.rs:898: charged before the check
        if len(key) != 32:
            raise TxScriptError("invalid public key encoding")
        if eclib.lift_x(int.from_bytes(key, "big")) is None:
            raise TxScriptError("invalid public key")
        if len(sig) != 64:
            raise TxScriptError("invalid signature length")
        msg = chash.calc_schnorr_signature_hash(self.tx, self.utxo_entries, self.input_index, hash_type, self.reused)
        cache_key = ("schnorr", sig, msg, key)
        cached = self.sig_cache.get(cache_key)
        if cached is None:
            cached = eclib.schnorr_verify(key, msg, sig)
            self.sig_cache.insert(cache_key, cached)
        return cached

    def _verify_ecdsa(self, key: bytes, sig: bytes, hash_type: int) -> bool:
        from kaspa_tpu.crypto import eclib

        self._require_tx()
        self.consume_sig_op_cost(1)  # lib.rs:927
        if len(key) != 33 or key[0] not in (2, 3):
            raise TxScriptError("invalid public key encoding")
        if eclib.parse_compressed(key) is None:
            raise TxScriptError("invalid public key")
        if len(sig) != 64:
            raise TxScriptError("invalid signature length")
        msg = chash.calc_ecdsa_signature_hash(self.tx, self.utxo_entries, self.input_index, hash_type, self.reused)
        cache_key = ("ecdsa", sig, msg, key)
        cached = self.sig_cache.get(cache_key)
        if cached is None:
            cached = eclib.ecdsa_verify(key, msg, sig)
            self.sig_cache.insert(cache_key, cached)
        return cached

    def _op_checksig_impl(self, ecdsa: bool):
        sig_raw, key = self.dstack[-2:] if len(self.dstack) >= 2 else (None, None)
        if key is None:
            raise TxScriptError("invalid stack operation")
        del self.dstack[-2:]
        if not sig_raw:
            self._push_bool(False)
            return
        typ = sig_raw[-1]
        if typ not in chash.ALLOWED_SIG_HASH_TYPES:
            raise TxScriptError(f"invalid hash type {typ:#x}")
        sig = sig_raw[:-1]
        valid = self._verify_ecdsa(key, sig, typ) if ecdsa else self._verify_schnorr(key, sig, typ)
        self._push_bool(valid)

    def _op_checksig_schnorr(self):
        self._op_checksig_impl(ecdsa=False)

    def _op_checksig_ecdsa(self):
        self._op_checksig_impl(ecdsa=True)

    def _op_checksigverify(self):
        self._op_checksig_schnorr()
        if not self._pop_bool():
            raise TxScriptError("checksig verify failed")

    def _op_checkmultisig_impl(self, ecdsa: bool):
        num_keys = self._pop_i32()
        if num_keys < 0:
            raise TxScriptError("number of pubkeys is negative")
        if num_keys > MAX_PUB_KEYS_PER_MULTISIG:
            raise TxScriptError(f"too many pubkeys {num_keys} > {MAX_PUB_KEYS_PER_MULTISIG}")
        self.num_ops += num_keys
        if self.num_ops > self.max_ops:
            raise TxScriptError("exceeded max operation limit")
        if len(self.dstack) < num_keys:
            raise TxScriptError("invalid stack operation")
        pub_keys = self.dstack[len(self.dstack) - num_keys :] if num_keys else []
        del self.dstack[len(self.dstack) - num_keys :]
        num_sigs = self._pop_i32()
        if num_sigs < 0:
            raise TxScriptError("number of signatures is negative")
        if num_sigs > num_keys:
            raise TxScriptError("more signatures than pubkeys")
        if len(self.dstack) < num_sigs:
            raise TxScriptError("invalid stack operation")
        signatures = self.dstack[len(self.dstack) - num_sigs :] if num_sigs else []
        del self.dstack[len(self.dstack) - num_sigs :]

        failed = False
        key_pos = 0
        for sig_idx, signature in enumerate(signatures):
            if not signature:
                failed = True
                break
            typ = signature[-1]
            if typ not in chash.ALLOWED_SIG_HASH_TYPES:
                raise TxScriptError(f"invalid hash type {typ:#x}")
            sig = signature[:-1]
            while True:
                if len(pub_keys) - key_pos < num_sigs - sig_idx:
                    failed = True
                    break
                key = pub_keys[key_pos]
                key_pos += 1
                valid = self._verify_ecdsa(key, sig, typ) if ecdsa else self._verify_schnorr(key, sig, typ)
                if valid:
                    break
            if failed:
                break
        if failed and any(s for s in signatures):
            raise TxScriptError("not all signatures empty on failed checkmultisig")
        self._push_bool(not failed)

    def _op_checkmultisig(self):
        self._op_checkmultisig_impl(ecdsa=False)

    def _op_checkmultisig_ecdsa(self):
        self._op_checkmultisig_impl(ecdsa=True)

    def _op_checkmultisigverify(self):
        self._op_checkmultisig()
        if not self._pop_bool():
            raise TxScriptError("checkmultisig verify failed")

    def _op_checklocktimeverify(self):
        self._require_tx()
        raw = self._pop()
        if len(raw) > 8:
            raise TxScriptError("lockTime value longer than 8 bytes")
        stack_lock_time = int.from_bytes(raw.ljust(8, b"\x00"), "little")
        tx_lock = self.tx.lock_time
        same_kind = (tx_lock < LOCK_TIME_THRESHOLD) == (stack_lock_time < LOCK_TIME_THRESHOLD)
        if not same_kind:
            raise TxScriptError("mismatched locktime types")
        if stack_lock_time > tx_lock:
            raise TxScriptError("locktime requirement not satisfied")
        if self.tx.inputs[self.input_index].sequence == MAX_TX_IN_SEQUENCE_NUM:
            raise TxScriptError("transaction input is finalized")

    def _op_checksequenceverify(self):
        self._require_tx()
        raw = self._pop()
        if len(raw) > 8:
            raise TxScriptError("sequence value longer than 8 bytes")
        stack_sequence = int.from_bytes(raw.ljust(8, b"\x00"), "little")
        if stack_sequence & SEQUENCE_LOCK_TIME_DISABLED:
            return
        input_seq = self.tx.inputs[self.input_index].sequence
        if input_seq & SEQUENCE_LOCK_TIME_DISABLED:
            raise TxScriptError("transaction sequence has locktime-disabled bit set")
        if (stack_sequence & SEQUENCE_LOCK_TIME_MASK) > (input_seq & SEQUENCE_LOCK_TIME_MASK):
            raise TxScriptError("sequence requirement not satisfied")

    def _op_invalid(self):
        raise TxScriptError("attempt to execute invalid opcode")

    # ------------------------------------------------------------------
    # Toccata surface: splice/bitwise/arithmetic re-enables, introspection,
    # covenants, ZK precompiles, blake3, CheckSigFromStack
    # (opcodes/mod.rs 0x7e-0x97 gated bodies and 0xa6-0xda)
    # ------------------------------------------------------------------

    def _require_covenants(self):
        if not self.flags.covenants_enabled:
            raise TxScriptError("attempt to execute reserved opcode (covenants disabled)")

    def _pop_usize(self) -> int:
        v = self._pop_i32()
        if v < 0:
            raise TxScriptError(f"negative index {v}")
        return v

    def _pop_hash(self) -> bytes:
        v = self._pop()
        if len(v) != 32:
            raise TxScriptError(f"invalid hash length {len(v)}")
        return v

    def _substring(self, data: bytes, start: int, end: int) -> bytes:
        if end < start:
            raise TxScriptError(f"invalid range {start}..{end}")
        if end - start > MAX_SCRIPT_ELEMENT_SIZE_POST_TOCCATA:
            raise TxScriptError("substring too big")
        if end > len(data):
            raise TxScriptError(f"out of bounds substring {start}..{end} of {len(data)}")
        return data[start:end]

    def _op_cat(self):
        self._require_covenants()
        b = self._pop()
        a = self._pop()
        self._push(a + b)

    def _op_substr(self):
        self._require_covenants()
        end = self._pop_usize()
        start = self._pop_usize()
        data = self._pop()
        self._push(self._substring(data, start, end))

    def _op_invert(self):
        self._require_covenants()
        self._push(bytes(~b & 0xFF for b in self._pop()))

    def _bitwise(self, fn):
        self._require_covenants()
        b = self._pop()
        a = self._pop()
        if len(a) != len(b):
            raise TxScriptError("bitwise operands must be of equal length")
        self._push(bytes(fn(x, y) for x, y in zip(a, b)))

    def _op_and(self):
        self._bitwise(lambda x, y: x & y)

    def _op_or(self):
        self._bitwise(lambda x, y: x | y)

    def _op_xor(self):
        self._bitwise(lambda x, y: x ^ y)

    def _op_mul(self):
        self._require_covenants()
        b, a = self._pop_num(), self._pop_num()
        self._push_num(self._checked(a * b))

    def _op_div(self):
        self._require_covenants()
        b, a = self._pop_num(), self._pop_num()
        if b == 0 or (a == I64_MIN and b == -1):
            raise TxScriptError("quotient overflow or division by zero")
        q = abs(a) // abs(b)
        self._push_num(q if (a < 0) == (b < 0) else -q)  # trunc toward zero

    def _op_mod(self):
        self._require_covenants()
        b, a = self._pop_num(), self._pop_num()
        if b == 0:
            raise TxScriptError("illegal modulo by zero")
        q = abs(a) // abs(b)
        if (a < 0) != (b < 0):
            q = -q
        self._push_num(self._checked(a - q * b))  # sign follows dividend

    def _op_zk_precompile(self):
        self._require_covenants()
        from kaspa_tpu.txscript import zk_precompiles as zkp
        from kaspa_tpu.txscript.resource_meter import MeterError, RuntimeScriptUnitMeter

        try:
            tag = zkp.parse_tag(self._pop())
        except zkp.ZkError as e:
            raise TxScriptError(f"zk integrity: {e}") from e
        self.consume_script_units(zkp.TAG_COSTS[tag])
        meter = self.meter if self.meter is not None else RuntimeScriptUnitMeter(0, (1 << 64) - 1)
        try:
            zkp.verify_zk(tag, self.dstack, meter)
        except (zkp.ZkError, zkp.R0Error) as e:
            raise TxScriptError(f"zk integrity: {e}") from e
        except MeterError as e:
            raise TxScriptError(str(e)) from e
        self._push_bool(True)

    def _op_blake2b_keyed(self):
        self._require_covenants()
        key = self._pop()
        data = self._pop()
        if len(key) > 64:
            raise TxScriptError(f"blake2b key too big ({len(key)} > 64)")
        self.consume_script_units(2 * len(data))
        self._push(hashlib.blake2b(data, digest_size=32, key=key).digest())

    def _op_blake3(self):
        self._require_covenants()
        from kaspa_tpu.crypto.blake3 import blake3

        data = self._pop()
        self.consume_script_units(len(data))
        self._push(blake3(data))

    def _op_blake3_keyed(self):
        self._require_covenants()
        from kaspa_tpu.crypto.blake3 import blake3_keyed

        key = self._pop()
        data = self._pop()
        if len(key) != 32:
            raise TxScriptError(f"blake3 key must be 32 bytes, got {len(key)}")
        self.consume_script_units(len(data))
        self._push(blake3_keyed(key, data))

    # --- transaction introspection (KIP-10 ops are ungated; the rest are
    # covenant-gated exactly per opcodes/mod.rs) ---

    def _op_tx_version(self):
        self._require_covenants()
        self._require_tx()
        self._push_num(self.tx.version)

    def _op_tx_input_count(self):
        self._require_tx()
        self._push_num(len(self.tx.inputs))

    def _op_tx_output_count(self):
        self._require_tx()
        self._push_num(len(self.tx.outputs))

    def _op_tx_lock_time(self):
        self._require_covenants()
        self._require_tx()
        self._push_num(self._checked(self.tx.lock_time))

    def _op_tx_subnet_id(self):
        self._require_covenants()
        self._require_tx()
        self._push(self.tx.subnetwork_id)

    def _op_tx_gas(self):
        self._require_covenants()
        self._require_tx()
        self._push_num(self._checked(self.tx.gas))

    def _op_tx_payload_substr(self):
        self._require_covenants()
        self._require_tx()
        end = self._pop_usize()
        start = self._pop_usize()
        self._push(self._substring(self.tx.payload, start, end))

    def _op_tx_input_index(self):
        self._require_tx()
        self._push_num(self.input_index)

    def _get_input(self, idx: int):
        if idx >= len(self.tx.inputs):
            raise TxScriptError(f"invalid input index {idx} (tx has {len(self.tx.inputs)})")
        return self.tx.inputs[idx]

    def _get_utxo(self, idx: int):
        if idx >= len(self.utxo_entries):
            raise TxScriptError(f"invalid input index {idx} (tx has {len(self.tx.inputs)})")
        return self.utxo_entries[idx]

    def _get_output(self, idx: int):
        if idx >= len(self.tx.outputs):
            raise TxScriptError(f"invalid output index {idx}")
        return self.tx.outputs[idx]

    def _op_outpoint_tx_id(self):
        self._require_covenants()
        self._require_tx()
        self._push(self._get_input(self._pop_usize()).previous_outpoint.transaction_id)

    def _op_outpoint_index(self):
        self._require_covenants()
        self._require_tx()
        self._push_num(self._get_input(self._pop_usize()).previous_outpoint.index)

    def _op_tx_input_script_sig_substr(self):
        self._require_covenants()
        self._require_tx()
        end = self._pop_usize()
        start = self._pop_usize()
        inp = self._get_input(self._pop_usize())
        self._push(self._substring(inp.signature_script, start, end))

    def _op_tx_input_seq(self):
        self._require_covenants()
        self._require_tx()
        # sequence is a bitflag field: raw 8-byte LE push, not a number
        self._push(self._get_input(self._pop_usize()).sequence.to_bytes(8, "little"))

    def _op_tx_input_amount(self):
        self._require_tx()
        self._push_num(self._checked(self._get_utxo(self._pop_usize()).amount))

    @staticmethod
    def _spk_bytes(spk) -> bytes:
        # SpkEncoding (lib.rs:950): big-endian version + script
        return spk.version.to_bytes(2, "big") + spk.script

    def _op_tx_input_spk(self):
        self._require_tx()
        self._push(self._spk_bytes(self._get_utxo(self._pop_usize()).script_public_key))

    def _op_tx_input_daa_score(self):
        self._require_covenants()
        self._require_tx()
        self._push_num(self._checked(self._get_utxo(self._pop_usize()).block_daa_score))

    def _op_tx_input_is_coinbase(self):
        self._require_covenants()
        self._require_tx()
        self._push_num(1 if self._get_utxo(self._pop_usize()).is_coinbase else 0)

    def _op_tx_output_amount(self):
        self._require_tx()
        self._push_num(self._checked(self._get_output(self._pop_usize()).value))

    def _op_tx_output_spk(self):
        self._require_tx()
        self._push(self._spk_bytes(self._get_output(self._pop_usize()).script_public_key))

    def _op_tx_payload_len(self):
        self._require_covenants()
        self._require_tx()
        self._push_num(len(self.tx.payload))

    def _op_tx_input_spk_len(self):
        self._require_covenants()
        self._require_tx()
        self._push_num(len(self._spk_bytes(self._get_utxo(self._pop_usize()).script_public_key)))

    def _op_tx_input_spk_substr(self):
        self._require_covenants()
        self._require_tx()
        end = self._pop_usize()
        start = self._pop_usize()
        spk = self._spk_bytes(self._get_utxo(self._pop_usize()).script_public_key)
        self._push(self._substring(spk, start, end))

    def _op_tx_output_spk_len(self):
        self._require_covenants()
        self._require_tx()
        self._push_num(len(self._spk_bytes(self._get_output(self._pop_usize()).script_public_key)))

    def _op_tx_output_spk_substr(self):
        self._require_covenants()
        self._require_tx()
        end = self._pop_usize()
        start = self._pop_usize()
        spk = self._spk_bytes(self._get_output(self._pop_usize()).script_public_key)
        self._push(self._substring(spk, start, end))

    def _op_tx_input_script_sig_len(self):
        self._require_covenants()
        self._require_tx()
        self._push_num(len(self._get_input(self._pop_usize()).signature_script))

    # --- covenants (contexts pre-built by covenants.CovenantsContext) ---

    def _cov_ctx(self):
        if self.covenants_ctx is None:
            from kaspa_tpu.txscript.covenants import CovenantsContext

            self.covenants_ctx = CovenantsContext.from_tx(self.tx, self.utxo_entries)
        return self.covenants_ctx

    def _op_auth_output_count(self):
        self._require_covenants()
        self._require_tx()
        idx = self._pop_usize()
        if idx >= len(self.tx.inputs):
            raise TxScriptError(f"invalid input index {idx}")
        self._push_num(self._cov_ctx().num_auth_outputs(idx))

    def _op_auth_output_idx(self):
        from kaspa_tpu.txscript.covenants import CovenantsError

        self._require_covenants()
        self._require_tx()
        k = self._pop_usize()
        idx = self._pop_usize()
        if idx >= len(self.tx.inputs):
            raise TxScriptError(f"invalid input index {idx}")
        try:
            self._push_num(self._cov_ctx().auth_output_index(idx, k))
        except CovenantsError as e:
            raise TxScriptError(str(e)) from e

    def _op_num2bin(self):
        self._require_covenants()
        size = self._pop_usize()
        if size > 8:
            raise TxScriptError(f"NUM2BIN target size {size} exceeds 8 bytes")
        num = self._pop_num()
        # data_stack.rs serialize_i64(num, Some(size)): LE magnitude bytes
        # (plus a spill byte when the top magnitude bit is set), zero-pad to
        # size, then set the sign bit on the final byte
        out = bytearray()
        positive = abs(num)
        while positive:
            out.append(positive & 0xFF)
            positive >>= 8
        if out and out[-1] & 0x80:
            out.append(0)
        if len(out) > size:
            raise TxScriptError(f"cannot encode {num} in {size} bytes")
        out.extend(b"\x00" * (size - len(out)))
        if num < 0:
            out[-1] |= 0x80
        self._push(bytes(out))

    def _op_bin2num(self):
        self._require_covenants()
        # deserialize unrestricted (non-minimal allowed), re-push minimal
        self._push_num(deserialize_i64(self._pop(), enforce_minimal=False))

    def _op_input_covenant_id(self):
        self._require_covenants()
        self._require_tx()
        entry = self._get_utxo(self._pop_usize())
        self._push(entry.covenant_id if entry.covenant_id is not None else b"\x00" * 32)

    def _op_cov_input_count(self):
        self._require_covenants()
        self._require_tx()
        cov_id = self._pop_hash()
        self._push_num(self._cov_ctx().num_covenant_inputs(cov_id))

    def _op_cov_input_idx(self):
        from kaspa_tpu.txscript.covenants import CovenantsError

        self._require_covenants()
        self._require_tx()
        k = self._pop_usize()
        cov_id = self._pop_hash()
        try:
            self._push_num(self._cov_ctx().covenant_input_index(cov_id, k))
        except CovenantsError as e:
            raise TxScriptError(str(e)) from e

    def _op_cov_output_count(self):
        self._require_covenants()
        self._require_tx()
        cov_id = self._pop_hash()
        self._push_num(self._cov_ctx().num_covenant_outputs(cov_id))

    def _op_cov_output_idx(self):
        from kaspa_tpu.txscript.covenants import CovenantsError

        self._require_covenants()
        self._require_tx()
        k = self._pop_usize()
        cov_id = self._pop_hash()
        try:
            self._push_num(self._cov_ctx().covenant_output_index(cov_id, k))
        except CovenantsError as e:
            raise TxScriptError(str(e)) from e

    def _op_chainblock_seq_commit(self):
        # gated by accessor presence, NOT by covenants_enabled — matching
        # opcodes/mod.rs:1581 ("seq_commit_access is none only if the opcode
        # is not enabled"): the KIP-21 wiring only injects an accessor when
        # the seq-commit feature is consensus-active
        if self.seq_commit_accessor is None:
            raise TxScriptError("attempt to execute invalid opcode (seq commit unavailable)")
        block = self._pop_hash()
        anc = self.seq_commit_accessor.is_chain_ancestor_from_pov(block)
        if anc is None:
            raise TxScriptError(f"block {block.hex()} already pruned")
        if not anc:
            raise TxScriptError(f"block {block.hex()} not on the selected chain")
        commitment = self.seq_commit_accessor.seq_commitment_within_depth(block)
        if commitment is None:
            raise TxScriptError(f"block {block.hex()} is too deep")
        self._push(commitment)

    def _op_output_covenant_id(self):
        self._require_covenants()
        self._require_tx()
        out = self._get_output(self._pop_usize())
        self._push(out.covenant.covenant_id if out.covenant is not None else b"\x00" * 32)

    def _op_output_authorizing_input(self):
        self._require_covenants()
        self._require_tx()
        out = self._get_output(self._pop_usize())
        self._push_num(out.covenant.authorizing_input if out.covenant is not None else -1)

    def _op_checksig_from_stack(self, ecdsa: bool = False):
        from kaspa_tpu.crypto import eclib

        self._require_covenants()
        pubkey = self._pop()
        msg_hash = self._pop()
        signature = self._pop()
        if len(msg_hash) != 32:
            raise TxScriptError("message hash must be 32 bytes")
        self.consume_sig_op_cost(1)
        if ecdsa:
            if len(pubkey) != 33 or eclib.parse_compressed(pubkey) is None:
                raise TxScriptError("invalid public key")
            if len(signature) != 64:
                raise TxScriptError("invalid signature length")
            cache_key = ("ecdsa", bytes(signature), msg_hash, bytes(pubkey))
            valid = self.sig_cache.get(cache_key)
            if valid is None:
                valid = eclib.ecdsa_verify(pubkey, msg_hash, signature)
                self.sig_cache.insert(cache_key, valid)
        else:
            if len(pubkey) != 32 or eclib.lift_x(int.from_bytes(pubkey, "big")) is None:
                raise TxScriptError("invalid public key")
            if len(signature) != 64:
                raise TxScriptError("invalid signature length")
            cache_key = ("schnorr", bytes(signature), msg_hash, bytes(pubkey))
            valid = self.sig_cache.get(cache_key)
            if valid is None:
                valid = eclib.schnorr_verify(pubkey, msg_hash, signature)
                self.sig_cache.insert(cache_key, valid)
        self._push_bool(bool(valid))

    def _op_checksig_from_stack_ecdsa(self):
        self._op_checksig_from_stack(ecdsa=True)

    # opcode dispatch table
    _OPS = {}


def _build_ops():
    e = TxScriptEngine
    ops = {
        0x00: e._op_false,
        0x4F: e._op_1negate,
        0x61: e._op_nop,
        0x63: e._op_if,
        0x64: e._op_notif,
        0x67: e._op_else,
        0x68: e._op_endif,
        0x69: e._op_verify,
        0x6A: e._op_return,
        0x6B: e._op_toaltstack,
        0x6C: e._op_fromaltstack,
        0x6D: e._op_2drop,
        0x6E: e._op_2dup,
        0x6F: e._op_3dup,
        0x70: e._op_2over,
        0x71: e._op_2rot,
        0x72: e._op_2swap,
        0x73: e._op_ifdup,
        0x74: e._op_depth,
        0x75: e._op_drop,
        0x76: e._op_dup,
        0x77: e._op_nip,
        0x78: e._op_over,
        0x79: e._op_pick,
        0x7A: e._op_roll,
        0x7B: e._op_rot,
        0x7C: e._op_swap,
        0x7D: e._op_tuck,
        0x82: e._op_size,
        0x87: e._op_equal,
        0x88: e._op_equalverify,
        0x8B: e._op_1add,
        0x8C: e._op_1sub,
        0x8F: e._op_negate,
        0x90: e._op_abs,
        0x91: e._op_not,
        0x92: e._op_0notequal,
        0x93: e._op_add,
        0x94: e._op_sub,
        0x9A: e._op_booland,
        0x9B: e._op_boolor,
        0x9C: e._op_numequal,
        0x9D: e._op_numequalverify,
        0x9E: e._op_numnotequal,
        0x9F: e._op_lessthan,
        0xA0: e._op_greaterthan,
        0xA1: e._op_lessthanorequal,
        0xA2: e._op_greaterthanorequal,
        0xA3: e._op_min,
        0xA4: e._op_max,
        0xA5: e._op_within,
        0xA8: e._op_sha256,
        0xA9: e._op_checkmultisig_ecdsa,
        0xAA: e._op_blake2b,
        0xAB: e._op_checksig_ecdsa,
        0xAC: e._op_checksig_schnorr,
        0xAD: e._op_checksigverify,
        0xAE: e._op_checkmultisig,
        0xAF: e._op_checkmultisigverify,
        0xB0: e._op_checklocktimeverify,
        0xB1: e._op_checksequenceverify,
        # Toccata: splice/bitwise/arithmetic re-enables (flag-checked in the
        # bodies; execute_script rejects them pre-fork before dispatch)
        0x7E: e._op_cat,
        0x7F: e._op_substr,
        0x83: e._op_invert,
        0x84: e._op_and,
        0x85: e._op_or,
        0x86: e._op_xor,
        0x95: e._op_mul,
        0x96: e._op_div,
        0x97: e._op_mod,
        0xA6: e._op_zk_precompile,
        0xA7: e._op_blake2b_keyed,
        # introspection (0xb3/b4/b9/be/bf/c2/c3 are ungated KIP-10 ops)
        0xB2: e._op_tx_version,
        0xB3: e._op_tx_input_count,
        0xB4: e._op_tx_output_count,
        0xB5: e._op_tx_lock_time,
        0xB6: e._op_tx_subnet_id,
        0xB7: e._op_tx_gas,
        0xB8: e._op_tx_payload_substr,
        0xB9: e._op_tx_input_index,
        0xBA: e._op_outpoint_tx_id,
        0xBB: e._op_outpoint_index,
        0xBC: e._op_tx_input_script_sig_substr,
        0xBD: e._op_tx_input_seq,
        0xBE: e._op_tx_input_amount,
        0xBF: e._op_tx_input_spk,
        0xC0: e._op_tx_input_daa_score,
        0xC1: e._op_tx_input_is_coinbase,
        0xC2: e._op_tx_output_amount,
        0xC3: e._op_tx_output_spk,
        0xC4: e._op_tx_payload_len,
        0xC5: e._op_tx_input_spk_len,
        0xC6: e._op_tx_input_spk_substr,
        0xC7: e._op_tx_output_spk_len,
        0xC8: e._op_tx_output_spk_substr,
        0xC9: e._op_tx_input_script_sig_len,
        0xCB: e._op_auth_output_count,
        0xCC: e._op_auth_output_idx,
        0xCD: e._op_num2bin,
        0xCE: e._op_bin2num,
        0xCF: e._op_input_covenant_id,
        0xD0: e._op_cov_input_count,
        0xD1: e._op_cov_input_idx,
        0xD2: e._op_cov_output_count,
        0xD3: e._op_cov_output_idx,
        0xD4: e._op_chainblock_seq_commit,
        0xD5: e._op_output_covenant_id,
        0xD6: e._op_output_authorizing_input,
        0xD7: e._op_checksig_from_stack,
        0xD8: e._op_checksig_from_stack_ecdsa,
        0xD9: e._op_blake3,
        0xDA: e._op_blake3_keyed,
    }
    for n in range(1, 17):  # Op1..Op16
        ops[0x50 + n] = (lambda n: lambda self: self._op_n(n))(n)
    for code in _RESERVED:
        ops[code] = e._op_reserved
    # everything else (incl. post-Toccata introspection while gated off) is invalid
    for code in range(256):
        ops.setdefault(code, e._op_invalid)
    return ops


TxScriptEngine._OPS = _build_ops()


def vm_fallback(tx, utxo_entries, input_index, reused, sig_cache: SigCache | None = None, flags: EngineFlags | None = None, meter=None):
    """Adapter used by txscript.batch.BatchScriptChecker for nonstandard scripts."""
    engine = TxScriptEngine(tx, utxo_entries, input_index, reused, sig_cache, flags=flags, meter=meter)
    engine.execute()
