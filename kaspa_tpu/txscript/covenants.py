"""Covenant execution contexts + covenant-id derivation (Toccata).

Reference: crypto/txscript/src/covenants.rs and
consensus/core/src/hashing/covenant_id.rs.  A covenant id is born in a
"genesis" transaction (derived from the authorizing input's outpoint and
the authorized outputs) and then *continues* through outputs whose
authorizing input already carries the same id.  The script engine's
introspection opcodes (OpAuthOutputCount/Idx, OpCovInput*/OpCovOutput*)
read the pre-computed contexts built here.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kaspa_tpu.crypto.hashing import CovenantID as _covenant_hasher


class CovenantsError(Exception):
    pass


def covenant_id(outpoint, auth_outputs) -> bytes:
    """hashing/covenant_id.rs: id = H(outpoint || len || (index, value,
    spk)...) — the binding excludes the outputs' own covenant fields to
    avoid self-reference."""
    auth_outputs = list(auth_outputs)
    h = _covenant_hasher()
    h.update(outpoint.transaction_id)
    h.update(outpoint.index.to_bytes(4, "little"))
    h.update(len(auth_outputs).to_bytes(8, "little"))
    for index, output in auth_outputs:
        h.update(int(index).to_bytes(4, "little"))
        h.update(output.value.to_bytes(8, "little"))
        h.update(output.script_public_key.version.to_bytes(2, "little"))
        h.update(len(output.script_public_key.script).to_bytes(8, "little"))
        h.update(output.script_public_key.script)
    return h.digest()


@dataclass
class CovenantInputContext:
    auth_outputs: list[int] = field(default_factory=list)


@dataclass
class CovenantSharedContext:
    input_indices: list[int] = field(default_factory=list)
    output_indices: list[int] = field(default_factory=list)


@dataclass
class CovenantsContext:
    input_ctxs: dict = field(default_factory=dict)  # input idx -> CovenantInputContext
    shared_ctxs: dict = field(default_factory=dict)  # covenant id -> CovenantSharedContext

    # --- opcode accessors (covenants.rs:66-94) ---

    def auth_output_index(self, input_idx: int, k: int) -> int:
        ctx = self.input_ctxs.get(input_idx)
        auth = ctx.auth_outputs if ctx else []
        if k >= len(auth):
            raise CovenantsError(
                f"auth output index {k} for input {input_idx} out of bounds ({len(auth)})"
            )
        return auth[k]

    def num_auth_outputs(self, input_idx: int) -> int:
        ctx = self.input_ctxs.get(input_idx)
        return len(ctx.auth_outputs) if ctx else 0

    def num_covenant_inputs(self, cov_id: bytes) -> int:
        ctx = self.shared_ctxs.get(cov_id)
        return len(ctx.input_indices) if ctx else 0

    def covenant_input_index(self, cov_id: bytes, k: int) -> int:
        ctx = self.shared_ctxs.get(cov_id)
        indices = ctx.input_indices if ctx else []
        if k >= len(indices):
            raise CovenantsError(f"covenant input index {k} out of bounds for {cov_id.hex()}")
        return indices[k]

    def num_covenant_outputs(self, cov_id: bytes) -> int:
        ctx = self.shared_ctxs.get(cov_id)
        return len(ctx.output_indices) if ctx else 0

    def covenant_output_index(self, cov_id: bytes, k: int) -> int:
        ctx = self.shared_ctxs.get(cov_id)
        indices = ctx.output_indices if ctx else []
        if k >= len(indices):
            raise CovenantsError(f"covenant output index {k} out of bounds for {cov_id.hex()}")
        return indices[k]

    @classmethod
    def from_tx(cls, tx, utxo_entries) -> "CovenantsContext":
        """covenants.rs from_tx: collect continuation relations into the
        engine contexts and validate genesis groups by recomputing their
        covenant ids; genesis outputs do NOT populate contexts."""
        ctx = cls()
        genesis_groups: dict = {}  # (auth input idx, covenant id) -> [output idx]

        for i, entry in enumerate(utxo_entries):
            if entry.covenant_id is not None:
                ctx.shared_ctxs.setdefault(entry.covenant_id, CovenantSharedContext()).input_indices.append(i)

        for i, output in enumerate(tx.outputs):
            binding = output.covenant
            if binding is None:
                continue
            auth_idx = binding.authorizing_input
            if auth_idx >= len(utxo_entries):
                raise CovenantsError(f"output {i} authorizing input {auth_idx} out of bounds")
            entry = utxo_entries[auth_idx]
            if entry.covenant_id is not None and entry.covenant_id == binding.covenant_id:
                # continuation
                ctx.input_ctxs.setdefault(auth_idx, CovenantInputContext()).auth_outputs.append(i)
                ctx.shared_ctxs[binding.covenant_id].output_indices.append(i)
            else:
                # genesis (absent or different id on the authorizing input)
                genesis_groups.setdefault((auth_idx, binding.covenant_id), []).append(i)

        for (auth_idx, cov_id), output_indices in genesis_groups.items():
            outpoint = tx.inputs[auth_idx].previous_outpoint
            expected = covenant_id(outpoint, ((j, tx.outputs[j]) for j in output_indices))
            if expected != cov_id:
                raise CovenantsError(f"wrong genesis covenant id on input {auth_idx}")
        return ctx
