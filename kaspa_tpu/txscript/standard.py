"""Standard script classes and builders.

Reference: crypto/txscript/src/{script_class.rs,standard.rs}.
"""

from __future__ import annotations

import hashlib
from enum import Enum

from kaspa_tpu.consensus.model import ScriptPublicKey

# opcode bytes (crypto/txscript/src/opcodes/mod.rs codes)
OP_DATA_32 = 0x20
OP_DATA_33 = 0x21
OP_DATA_65 = 0x41
OP_EQUAL = 0x87
OP_BLAKE2B = 0xAA
OP_CHECKSIG_ECDSA = 0xAB
OP_CHECKSIG = 0xAC

MAX_SCRIPT_PUBLIC_KEY_VERSION = 0


class ScriptClass(Enum):
    NON_STANDARD = "nonstandard"
    PUB_KEY = "pubkey"
    PUB_KEY_ECDSA = "pubkeyecdsa"
    SCRIPT_HASH = "scripthash"


def is_pay_to_pubkey(script: bytes) -> bool:
    return len(script) == 34 and script[0] == OP_DATA_32 and script[33] == OP_CHECKSIG


def is_pay_to_pubkey_ecdsa(script: bytes) -> bool:
    return len(script) == 35 and script[0] == OP_DATA_33 and script[34] == OP_CHECKSIG_ECDSA


def is_pay_to_script_hash(script: bytes) -> bool:
    return len(script) == 35 and script[0] == OP_BLAKE2B and script[1] == OP_DATA_32 and script[34] == OP_EQUAL


def classify_script(spk: ScriptPublicKey) -> ScriptClass:
    if spk.version != MAX_SCRIPT_PUBLIC_KEY_VERSION:
        return ScriptClass.NON_STANDARD
    if is_pay_to_pubkey(spk.script):
        return ScriptClass.PUB_KEY
    if is_pay_to_pubkey_ecdsa(spk.script):
        return ScriptClass.PUB_KEY_ECDSA
    if is_pay_to_script_hash(spk.script):
        return ScriptClass.SCRIPT_HASH
    return ScriptClass.NON_STANDARD


def pay_to_pub_key(pubkey32: bytes) -> ScriptPublicKey:
    assert len(pubkey32) == 32
    return ScriptPublicKey(0, bytes([OP_DATA_32]) + pubkey32 + bytes([OP_CHECKSIG]))


def pay_to_pub_key_ecdsa(pubkey33: bytes) -> ScriptPublicKey:
    assert len(pubkey33) == 33
    return ScriptPublicKey(0, bytes([OP_DATA_33]) + pubkey33 + bytes([OP_CHECKSIG_ECDSA]))


def pay_to_script_hash_script(redeem_script: bytes) -> ScriptPublicKey:
    h = hashlib.blake2b(redeem_script, digest_size=32).digest()
    return ScriptPublicKey(0, bytes([OP_BLAKE2B, OP_DATA_32]) + h + bytes([OP_EQUAL]))


def schnorr_signature_script(sig64: bytes, hash_type: int) -> bytes:
    """Signature script for P2PK: a single push of sig||hash_type."""
    assert len(sig64) == 64
    return bytes([OP_DATA_65]) + sig64 + bytes([hash_type])


def ecdsa_signature_script(sig64: bytes, hash_type: int) -> bytes:
    assert len(sig64) == 64
    return bytes([OP_DATA_65]) + sig64 + bytes([hash_type])


def parse_single_push(script: bytes) -> bytes | None:
    """Parse a signature script that is exactly one canonical data push.

    Standard P2PK spends push one 65-byte blob (sig64 + hashtype).  Returns
    the pushed data or None if the script isn't a single plain push
    (1 <= opcode <= 75 direct-data form).
    """
    if not script:
        return None
    op = script[0]
    if 1 <= op <= 75 and len(script) == 1 + op:
        return script[1:]
    return None


def _multisig_script(pub_keys: list[bytes], required: int, check_op: int) -> bytes:
    from kaspa_tpu.txscript.script_builder import ScriptBuilder

    if not pub_keys:
        raise ValueError("provided public keys should not be empty")
    if not (1 <= required <= len(pub_keys)):
        raise ValueError(f"invalid required signatures {required} for {len(pub_keys)} keys")
    b = ScriptBuilder().add_i64(required)
    for k in pub_keys:
        b.add_data(k)
    b.add_i64(len(pub_keys))
    b.add_op(check_op)
    return b.drain()


def multisig_redeem_script(pub_keys32: list[bytes], required: int) -> bytes:
    """m-of-n schnorr multisig redeem script (standard/multisig.rs:18):
    <m> <key1> ... <keyn> <n> OpCheckMultiSig."""
    return _multisig_script(pub_keys32, required, 0xAE)  # OpCheckMultiSig


def multisig_redeem_script_ecdsa(pub_keys33: list[bytes], required: int) -> bytes:
    """ECDSA variant (standard/multisig.rs:44)."""
    return _multisig_script(pub_keys33, required, 0xA9)  # OpCheckMultiSigECDSA
