"""ScriptBuilder: canonical (minimal-push) script construction.

Reference: crypto/txscript/src/script_builder.rs — emits the minimal
encoding for every push (OP_0/OP_1..16/OP_1NEGATE/direct/pushdata) so
built scripts always satisfy the engine's minimal-push rule, with the
same size guards.
"""

from __future__ import annotations

from kaspa_tpu.txscript.vm import MAX_SCRIPT_ELEMENT_SIZE, MAX_SCRIPTS_SIZE, serialize_i64

OP_0 = 0x00
OP_1NEGATE = 0x4F
OP_1 = 0x51
OP_PUSHDATA1, OP_PUSHDATA2, OP_PUSHDATA4 = 0x4C, 0x4D, 0x4E


class ScriptBuilderError(Exception):
    pass


class ScriptBuilder:
    def __init__(self):
        self._script = bytearray()

    def add_op(self, opcode: int) -> "ScriptBuilder":
        if len(self._script) + 1 > MAX_SCRIPTS_SIZE:
            raise ScriptBuilderError("script exceeds maximum size")
        self._script.append(opcode)
        return self

    def add_ops(self, opcodes) -> "ScriptBuilder":
        for op in opcodes:
            self.add_op(op)
        return self

    def add_data(self, data: bytes) -> "ScriptBuilder":
        """Minimal push of arbitrary data (script_builder.rs add_data).

        Validates sizes *before* mutating: on error the builder is unchanged
        (the reference's validate_data_push contract)."""
        n = len(data)
        if n > MAX_SCRIPT_ELEMENT_SIZE:
            raise ScriptBuilderError(f"element size {n} above limit")
        if n == 0:
            return self.add_op(OP_0)
        if n == 1 and 1 <= data[0] <= 16:
            return self.add_op(OP_1 + data[0] - 1)
        if n == 1 and data[0] == 0x81:
            return self.add_op(OP_1NEGATE)
        if n <= 75:
            prefix = bytes([n])
        elif n <= 255:
            prefix = bytes([OP_PUSHDATA1, n])
        elif n <= 65535:
            prefix = bytes([OP_PUSHDATA2]) + n.to_bytes(2, "little")
        else:
            prefix = bytes([OP_PUSHDATA4]) + n.to_bytes(4, "little")
        if len(self._script) + len(prefix) + n > MAX_SCRIPTS_SIZE:
            raise ScriptBuilderError("script exceeds maximum size")
        self._script += prefix + data
        return self

    def add_i64(self, value: int) -> "ScriptBuilder":
        """Minimal numeric push (script_builder.rs add_i64)."""
        if value == 0:
            return self.add_op(OP_0)
        if 1 <= value <= 16:
            return self.add_op(OP_1 + value - 1)
        if value == -1:
            return self.add_op(OP_1NEGATE)
        return self.add_data(serialize_i64(value))

    def add_lock_time(self, lock_time: int) -> "ScriptBuilder":
        return self._add_u64_fixed(lock_time)

    def add_sequence(self, sequence: int) -> "ScriptBuilder":
        return self._add_u64_fixed(sequence)

    def _add_u64_fixed(self, v: int) -> "ScriptBuilder":
        """8-byte LE push (CLTV/CSV operands; minimal rules don't apply)."""
        return self.add_data(v.to_bytes(8, "little"))

    def drain(self) -> bytes:
        out = bytes(self._script)
        self._script.clear()
        return out

    def script(self) -> bytes:
        return bytes(self._script)
