"""Runtime resource metering for script execution.

Reference: crypto/txscript/src/runtime_resource_meter.rs — two regimes:
the legacy sig-op counter (pre-Toccata: each input commits to a sig-op
count, executed sig ops may not exceed it) and the Toccata script-units
meter (sig ops cost `sigop_script_units` each, newly pushed bytes cost
1:1, ZK precompiles charge their tag cost; the total is bounded by the
input's committed budget).
"""

from __future__ import annotations


class MeterError(Exception):
    """ExceededSigOpLimit / ExceededCommittedScriptUnits."""


class RuntimeSigOpCounter:
    """Pre-Toccata regime: count executed sig ops against the input limit
    (runtime_resource_meter.rs:9-71)."""

    def __init__(self, sig_op_limit: int):
        self.sig_op_limit = sig_op_limit
        self.sig_op_remaining = sig_op_limit

    def consume_sig_ops(self, count: int = 1) -> None:
        if self.sig_op_remaining < count:
            raise MeterError(f"exceeded sig op limit of {self.sig_op_limit}")
        self.sig_op_remaining -= count

    @property
    def used_sig_ops(self) -> int:
        return self.sig_op_limit - self.sig_op_remaining

    # script-unit charges are a no-op in this regime
    def consume_script_units(self, units: int) -> None:
        pass

    def charge_newly_pushed_bytes(self, n: int) -> None:
        pass

    @property
    def used_script_units(self) -> int:
        return 0


class RuntimeScriptUnitMeter:
    """Toccata regime: everything priced in script units against the
    committed budget (runtime_resource_meter.rs:74-121).  `used` reported
    in the over-budget error saturates, mirroring the reference's
    saturating_add diagnostics."""

    def __init__(self, sigop_script_units: int, script_units_limit: int):
        self.used_sig_ops = 0
        self.sigop_script_units = sigop_script_units
        self.script_units_limit = script_units_limit
        self.remaining_script_units = script_units_limit

    @property
    def used_script_units(self) -> int:
        return self.script_units_limit - self.remaining_script_units

    def consume_script_units(self, units: int) -> None:
        if units > self.remaining_script_units:
            overflow = units - self.remaining_script_units
            used = min(self.script_units_limit + overflow, (1 << 64) - 1)
            raise MeterError(
                f"exceeded committed script units: used {used}, limit {self.script_units_limit}"
            )
        self.remaining_script_units -= units

    def consume_sig_ops(self, count: int = 1) -> None:
        self.consume_script_units(count * self.sigop_script_units)
        self.used_sig_ops = min(self.used_sig_ops + count, (1 << 16) - 1)

    def charge_newly_pushed_bytes(self, n: int) -> None:
        self.consume_script_units(n)  # pushed bytes are charged 1:1
